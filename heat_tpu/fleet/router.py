"""The fleet router: one stdlib-HTTP process in front of N engine gateways.

``heat-tpu fleet --backends host:port,... --listen HOST:PORT`` runs this
in front of independent ``heat-tpu serve --listen`` processes. The
router is the pod-scale half of the ROADMAP's north star: admission
moves to the edge, placement becomes a policy over live backend status,
and the PR-17 drain-to-checkpoint machinery becomes a **work-stealing
migration primitive** between backends.

- ``POST /v1/solve`` — the same NDJSON front door every gateway has.
  The router validates each line with ``parse_request_obj`` (edge
  admission: malformed lines are rejected here and never travel),
  mints/echoes ``X-Trace-Id``, picks a backend per request via the
  placement policy (fleet/placement.py) fed from each gateway's
  ``GET /v1/status`` control payload, forwards per-backend batches, and
  streams every backend's chunked ndjson records back to the caller as
  they land — one merged stream, exactly-once per request id.
- **Retry-on-alternate**: a forward that provably never reached
  admission (connect refused/reset, 503 draining, 429 all-shed) is
  re-placed on the next-best backend; only when every backend refuses
  does the client see a terminal rejection record (error
  ``unroutable:``/``overloaded:`` — the router-502-vs-backend-429
  distinction TROUBLESHOOTING.md documents).
- **Checkpoint-handoff work stealing**: when the imbalance estimator
  sees one backend's predicted backlog exceed ``--steal-threshold``
  seconds while another idles, the router POSTs ``/drainz?handoff=1``
  to the victim, waits for the engine manifest generation to land in
  the victim's checkpoint dir, and re-drives the orphaned queued +
  in-flight work through ``resume_engine``'s skip-set front door on the
  idle backend (``POST /v1/resume``) — mid-flight lanes continue at
  their last checkpointed boundary, bit-identical bytes across the
  migration (tests/test_fleet.py proves it). The same path recovers a
  backend that dies outright: manifest-covered work resumes, the rest
  re-drives fresh (deterministic solver — same bytes either way), and
  the delivered-set dedup guarantees no double-served ids.
- Fleet-wide ``/metrics`` + ``/statusz`` + ``/v1/usage`` aggregation
  with per-backend labels; ``/v1/usage`` merges the per-engine ledgers
  so fleet totals reconcile exactly with per-backend billing.
- ``/tracez`` — the router's OWN Tracer: forward spans per backend
  track, synthesized backend-side solve spans from each record's
  ``solve_s`` + ``trace_id``, so ``heat-tpu trace`` renders one fleet
  timeline; the ring is flight-dumped when a backend is lost.

Threading model mirrors the gateway: handler threads (admission +
client streaming), one relay thread per forwarded batch, one health/
imbalance thread, recovery/steal threads spawned on demand, pollers
for resumed orphans. All router tables live under one fleet-rank lock
(``runtime/debug.LOCK_RANKS``: fleet < gateway < engine — the router is
outermost in every request path); backend state lives under the
registry's own fleet-rank lock, and the two NEVER nest.
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import queue as queue_lib
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple
from urllib.parse import parse_qs, urlsplit

from ..runtime import checkpoint as ckpt_mod
from ..runtime import debug
from ..runtime import faults
from ..runtime import prof as prof_mod
from ..runtime import trace as trace_mod
from ..runtime.logging import json_record, master_print
from ..serve.api import parse_request_obj
from ..serve.gateway import MAX_BODY_BYTES, _TRACE_ID_RE
from ..serve.scheduler import TERMINAL_STATUSES
from . import placement, resilience
from .registry import BackendRegistry


@dataclasses.dataclass
class FleetConfig:
    """Router-level knobs (per-backend engine knobs live with each
    ``heat-tpu serve`` process)."""

    policy: str = "least-loaded"   # placement policy (fleet/placement.py)
    health_interval_s: float = 2.0  # /healthz + /v1/status probe cadence
    steal_threshold_s: float = 0.0  # imbalance estimator: steal when
                                    # max-min predicted backlog exceeds
                                    # this many seconds (0 = stealing
                                    # off; forced steals via Router.steal
                                    # still work)
    steal_cooldown_s: float = 10.0  # min wall between automatic steals
                                    # (thrash guard — TROUBLESHOOTING.md)
    steal_timeout_s: float = 60.0   # drain-to-manifest wait bound
    ckpt_root: Optional[str] = None  # fallback checkpoint root: backend
                                    # K's manifests under <root>/<K> when
                                    # its status payload names no dir
    cache_dir: Optional[str] = None  # shared solve-cache dir (the same
                                    # --cache-dir the backends serve
                                    # from): the router consults it
                                    # read-only BEFORE placement — a
                                    # fleet-wide full hit is served at
                                    # the edge and never touches a
                                    # backend; a prefix hit prefers
                                    # cache-enabled backends so the
                                    # frontier is actually consumed
    inject: str = ""                # fleet fault spec (backend-down /
                                    # backend-slow; runtime/faults.py)
    retry_after_s: float = 1.0
    connect_timeout_s: float = 5.0
    stream_timeout_s: float = 600.0
    flightrec_dir: str = "."        # backend-loss flight dumps land here
    trace_buffer: int = trace_mod.DEFAULT_BUFFER
    quiet: bool = True
    # --- resilience layer (fleet/resilience.py) ---------------------------
    breaker_trip: int = 3           # consecutive errors that open the
                                    # per-backend circuit breaker
    breaker_cooldown_s: float = 5.0  # open -> half-open wait (doubles on
                                    # every failed canary, capped)
    breaker_burn_ticks: int = 8     # consecutive burn-demoted health
                                    # ticks that open the breaker
    retry_budget_cap: float = 20.0  # fleet retry-token bucket size
    retry_budget_ratio: float = 0.2  # tokens refilled per delivered
                                    # success (SRE retry budget: retries
                                    # capped as a fraction of successes)
    retry_backoff_s: float = 0.05   # base of the jittered exponential
                                    # backoff between re-placements
    hedge_factor: float = 0.0       # hedge an interactive row once it
                                    # waited factor x predicted service
                                    # time (0 = hedging off)
    hedge_floor_s: float = 0.75     # minimum wait before any hedge (a
                                    # cold predictor must not duplicate
                                    # every row)
    cut_redrive_wait_s: float = 3.0  # after a mid-stream cut against a
                                    # LIVE backend: how long to poll it
                                    # for terminal records before
                                    # re-dispatching elsewhere


class Router:
    """The long-running fleet front-end over a :class:`BackendRegistry`.

    >>> reg = BackendRegistry(parse_backends("127.0.0.1:8001,127.0.0.1:8002"))
    >>> rt = Router(reg, "127.0.0.1", 0).start()
    >>> rt.address
    >>> rt.close()
    """

    def __init__(self, registry: BackendRegistry, host: str = "127.0.0.1",
                 port: int = 0, fcfg: Optional[FleetConfig] = None):
        self.registry = registry
        self.fcfg = fcfg or FleetConfig()
        if self.fcfg.policy not in placement.POLICIES:
            raise ValueError(f"unknown placement policy "
                             f"{self.fcfg.policy!r}; known: "
                             f"{placement.POLICIES}")
        self.tracer = trace_mod.Tracer(capacity=self.fcfg.trace_buffer)
        self._plan = faults.plan_for_spec(self.fcfg.inject)
        # fleet-tier solve cache: READ-ONLY over the shared --cache-dir
        # the backends publish into (the router never writes entries;
        # ownership of publish/evict/quarantine stays with the engines)
        self.solvecache = None
        self._edge_ledger = prof_mod.UsageLedger()
        if self.fcfg.cache_dir:
            from ..serve.solvecache import SolveCache

            self.solvecache = SolveCache(self.fcfg.cache_dir,
                                         readonly=True)
        self._lock = debug.make_lock("fleet:router")
        # retry budget + per-backend breakers are self-locked at the
        # same fleet rank: their METHODS are only ever called while
        # holding no other fleet lock (the dict get-or-create below is
        # the one thing the router lock guards)
        self._budget = resilience.RetryBudget(self.fcfg.retry_budget_cap,
                                              self.fcfg.retry_budget_ratio)
        # --- under self._lock -------------------------------------------
        self._requests: Dict[str, dict] = {}   # rid -> routing state
        self._live_relays: Dict[str, set] = {}  # backend -> open responses
        self._recovering: Set[str] = set()     # backends mid-recovery/steal
        self._steals: List[dict] = []          # steal event log (statusz)
        self._breakers: Dict[str, resilience.Breaker] = {}
        self._forwards = 0                     # chaos counter (backend-down@N)
        self._rr = 0                           # round-robin tiebreak clock
        self._duplicates = 0
        self._edge_rejected = 0
        self._cache_edge_hits = 0
        self._cache_prefix_hints = 0
        self._retries = 0
        self._lost = 0
        self._deadline_shed = 0
        self._brownout_shed = 0
        self._stream_cuts = 0
        self._hedges = {"fired": 0, "won": 0, "lost": 0, "cancelled": 0}
        self._canary_seq = 0
        self._draining = False
        self._last_steal_t = 0.0
        self._last_breaker_transition_t = 0.0
        # -----------------------------------------------------------------
        self.httpd = ThreadingHTTPServer((host, port), _FleetHandler)
        self.httpd.daemon_threads = True
        self.httpd.router = self
        self.host, self.port = self.httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None
        self._health: Optional[threading.Thread] = None
        self._stop = threading.Event()
        debug.instrument_races(
            self, label="Router",
            exempt=frozenset({"registry", "httpd", "tracer", "fcfg",
                              "solvecache", "_edge_ledger", "_budget"}))

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> "Router":
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True,
                                        name="heat-tpu-fleet-http")
        self._thread.start()
        self._health = threading.Thread(target=self._health_loop,
                                        daemon=True,
                                        name="heat-tpu-fleet-health")
        self._health.start()
        return self

    def request_drain(self) -> None:
        """Stop admission (healthz flips 503; new solves get 503). The
        backends are independent processes and are NOT drained — drain
        them individually, or steal their work first."""
        with self._lock:
            self._draining = True

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def pending_count(self) -> int:
        with self._lock:
            return sum(1 for st in self._requests.values()
                       if not st["delivered"])

    def close(self) -> None:
        self._stop.set()
        self.httpd.shutdown()
        self.httpd.server_close()

    # --- HTTP client helpers ----------------------------------------------
    def _conn(self, backend, timeout: float) -> http.client.HTTPConnection:
        if backend.fault_down:
            raise ConnectionRefusedError(
                f"injected backend-down: {backend.name}")
        if self._plan is not None:
            ms = self._plan.backend_partition_ms(backend.name)
            if ms is not None:
                # backend-partition chaos: the host is alive but the
                # network to it black-holes — every connect hangs for
                # the partition latency, then times out
                time.sleep(ms / 1e3)
                raise TimeoutError(
                    f"injected backend-partition: {backend.name}")
        host, _, port = backend.address.rpartition(":")
        return http.client.HTTPConnection(host, int(port), timeout=timeout)

    def _http(self, backend, method: str, path: str, body=None,
              headers=(), timeout: Optional[float] = None
              ) -> Tuple[int, bytes]:
        conn = self._conn(backend,
                          timeout or self.fcfg.connect_timeout_s)
        try:
            conn.request(method, path, body=body, headers=dict(headers))
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()

    # --- edge admission + placement ---------------------------------------
    def admit_lines(self, body: bytes, client_q: Optional[queue_lib.Queue],
                    trace_id: str) -> Tuple[List[dict], List[dict]]:
        """Parse NDJSON lines at the edge. Returns ``(immediate,
        accepted_states)``: per-line rejection records that never travel,
        and the routing-state dicts registered for the valid rows (not
        yet dispatched — the handler calls :meth:`dispatch` next, after
        it has sent response headers for the 202 path)."""
        immediate, states = [], []
        now = time.monotonic()
        for line in body.decode("utf-8", "replace").splitlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                obj = json.loads(line)
                row = parse_request_obj(obj)
            except Exception as e:  # noqa: BLE001 — per-line record
                immediate.append({"id": None, "status": "rejected",
                                  "error": f"{type(e).__name__}: {e}"})
                continue
            if row.error is not None:
                immediate.append({"id": row.id, "status": "rejected",
                                  "error": row.error})
                continue
            st = {"id": row.id, "line": obj, "n": int(row.cfg.n),
                  "steps": int(row.cfg.ntime), "backend": None,
                  "tried": [], "delivered": False, "rec": None,
                  "q": client_q, "t0": now, "trace_id": trace_id,
                  "cfg": row.cfg, "until": row.until,
                  "tenant": row.tenant or "default",
                  "class": row.slo_class or "standard",
                  # edge-minted deadline: the monotonic instant this
                  # row's budget expires; decremented per hop/retry via
                  # X-Deadline-Ms so no backend starts expired work
                  "deadline_t": (now + row.deadline_ms / 1e3
                                 if row.deadline_ms else None),
                  "hedged": False, "hedge_backend": None,
                  "dispatch_t": None, "expect_s": None}
            with self._lock:
                if row.id in self._requests:
                    self._edge_rejected += 1
                    immediate.append(
                        {"id": row.id, "status": "rejected",
                         "error": f"duplicate request id {row.id!r} "
                                  f"(already routed by this fleet)"})
                    continue
                self._requests[row.id] = st
            states.append(st)
        with self._lock:
            self._edge_rejected += len(
                [r for r in immediate if r["status"] == "rejected"])
        return immediate, states

    def _choose(self, n: Optional[int], exclude: Set[str], prefer=None):
        # an OPEN breaker excludes its backend from placement outright;
        # half-open admits exactly the canary, which bypasses _choose
        blocked = self._breaker_blocked()
        backends = [b for b in self.registry.snapshot()
                    if b.name not in exclude and b.name not in blocked]
        with self._lock:
            self._rr += 1
            rr = self._rr
        return placement.choose(self.fcfg.policy, backends, n, rr,
                                prefer=prefer)

    # --- fleet-tier solve cache -------------------------------------------
    def _cache_backends(self) -> Set[str]:
        """Backends whose status payload says the solve cache is on —
        the only ones that can consume a cached frontier."""
        return {b.name for b in self.registry.snapshot()
                if (b.status or {}).get("cache") is not None}

    def _consult_cache(self, states: List[dict]) -> List[dict]:
        """Consult the shared solve cache BEFORE placement. A fleet-wide
        full hit is served right here at the edge (zero backends
        touched, billed cached in the router's edge ledger); a prefix
        hit tags the state so placement prefers a cache-enabled backend
        (the one holding the snapshot). Returns the states that still
        need a backend."""
        if self.solvecache is None:
            return states
        remaining = []
        for st in states:
            cfg = st.get("cfg")
            if cfg is None or st.get("until", "steps") != "steps":
                remaining.append(st)
                continue
            try:
                hit = self.solvecache.lookup(cfg)
            except OSError:
                hit = None   # a flaky shared mount must not stop routing
            if hit is not None and hit["kind"] == "full":
                if self._serve_edge_hit(st, cfg, hit):
                    continue
                remaining.append(st)
            else:
                if hit is not None:
                    with self._lock:
                        st["prefer_cached"] = True
                        self._cache_prefix_hints += 1
                remaining.append(st)
        return remaining

    def _serve_edge_hit(self, st: dict, cfg, hit: dict) -> bool:
        """Deliver a fleet-wide full hit at the edge: a synthesized
        terminal record pointing at the validated cache entry, billed
        cached (zero lane-seconds/steps) in the router's edge ledger so
        ``/v1/usage`` reconciles fleet-wide."""
        rec = {"event": "serve_request", "id": st["id"], "status": "ok",
               "exit": "cached", "cached": True,
               "tenant": st["tenant"], "class": st["class"],
               "n": int(cfg.n), "ndim": int(cfg.ndim),
               "ntime": int(cfg.ntime), "until": "steps", "error": None,
               "solve_s": 0.0, "steps_done": int(cfg.ntime),
               "steps_per_s": None, "path": hit["path"],
               "placement": "fleet-cache", "trace_id": st["trace_id"],
               "usage": {"lane_s": 0.0, "steps": 0, "chunks": 0,
                         "bytes_written": int(hit["nbytes"]),
                         "steps_saved": int(cfg.ntime), "cached": True}}
        if not self._deliver(st["id"], rec, backend=None):
            return False
        self._edge_ledger.add(st["tenant"], st["class"], "ok",
                              rec["usage"], placement="fleet-cache")
        with self._lock:
            self._cache_edge_hits += 1
        json_record("fleet_cache_hit", id=st["id"], step=hit["step"],
                    path=hit["path"])
        if self.tracer.enabled:
            self.tracer.instant(
                "cache-hit", self.tracer.track("fleet router",
                                               "placement"),
                cat="fleet", args={"id": st["id"], "step": hit["step"]})
        return True

    def _chaos_forward(self, chosen_name: str) -> None:
        """backend-down@N / backend-slow chaos, one call per forwarded
        request (strictly opt-in: None plan = one falsy test)."""
        if self._plan is None:
            return
        self._plan.backend_slow()
        with self._lock:
            self._forwards += 1
            nth = self._forwards
        target = self._plan.backend_down_target(nth)
        if target is not None:
            victim = target or chosen_name
            self.registry.set_fault_down(victim)
            json_record("fleet_backend_down_injected", backend=victim,
                        at_forward=nth)
            self._close_relays(victim)

    def dispatch(self, states: List[dict]) -> None:
        """Place every state on a backend and spawn one relay per
        (backend, batch). States that cannot be placed anywhere get a
        terminal rejection record delivered locally."""
        batches: Dict[str, List[dict]] = {}
        addr: Dict[str, str] = {}
        states = self._consult_cache(states)
        now = time.monotonic()
        level = placement.brownout_level(self.registry.snapshot())
        for st in states:
            with self._lock:
                tried = set(st["tried"])
                prefer_cached = st.get("prefer_cached", False)
                dt = st["deadline_t"]
            if dt is not None and now > dt:
                self._shed_deadline(st, "placement")
                continue
            if level and self._shed_brownout(st, level):
                continue
            b, decision = self._choose(
                st["n"], tried,
                prefer=self._cache_backends() if prefer_cached else None)
            if b is None:
                self._reject_unroutable(st, decision.get("reason",
                                                         "no-backend"))
                continue
            self._chaos_forward(b.name)
            if b.fault_down:   # the chaos drill just dropped OUR target
                b2, _ = self._choose(st["n"], tried | {b.name})
                if b2 is None:
                    self._reject_unroutable(st, "no-backend-after-fault")
                    continue
                b = b2
            # the hedge trigger's expectation: predicted queue wait plus
            # this row's own service time on the chosen backend — an
            # advisory read of registry-guarded fields, so it stays a
            # bare read OUTSIDE the router lock (registry.snapshot doc)
            expect = (placement.predicted_backlog_s(b)
                      + st["steps"] * placement.s_per_lane_step(b.status))
            with self._lock:
                st["backend"] = b.name
                st["dispatch_t"] = time.monotonic()
                st["expect_s"] = expect
            if self.tracer.enabled:
                self.tracer.instant(
                    "placed", self.tracer.track("fleet router", "placement"),
                    cat="fleet", args={"id": st["id"], **decision})
            batches.setdefault(b.name, []).append(st)
            addr[b.name] = b.address
        for name, sts in batches.items():
            self.registry.note_routed(name, len(sts),
                                      sum(s["steps"] for s in sts))
            threading.Thread(
                target=self._relay, args=(name, addr[name], sts),
                daemon=True, name=f"heat-tpu-fleet-relay-{name}").start()

    def _reject_unroutable(self, st: dict, why: str) -> None:
        rec = {"id": st["id"], "status": "rejected",
               "error": f"unroutable: no eligible backend ({why}); "
                        f"the fleet is down or nothing can serve "
                        f"n={st['n']}"}
        self._deliver(st["id"], rec, backend=None)

    # --- relays -----------------------------------------------------------
    def _relay(self, name: str, address: str, sts: List[dict]) -> None:
        """Forward one batch as a streaming POST /v1/solve and pump the
        backend's chunked record lines into delivery. A failure BEFORE
        admission (connect error, 503, 429, non-200) retries the batch
        on an alternate backend; a break MID-stream hands the
        undelivered rows to checkpoint recovery."""
        b = self.registry.get(name)
        if b is None:
            for st in sts:
                self._reject_unroutable(st, f"backend {name} vanished")
            return
        # deadline propagation: rewrite each row's budget to what is
        # LEFT of the edge-minted one (hops and retries ate the rest),
        # shedding rows that arrive at this hop already spent
        now = time.monotonic()
        live, expired = [], []
        min_remaining_ms: Optional[float] = None
        with self._lock:
            for st in sts:
                dt = st["deadline_t"]
                if dt is None:
                    live.append(st)
                    continue
                remaining_ms = (dt - now) * 1e3
                if remaining_ms < 1.0:
                    expired.append(st)
                    continue
                st["line"] = dict(st["line"],
                                  deadline_ms=round(remaining_ms, 3))
                live.append(st)
                min_remaining_ms = (remaining_ms
                                    if min_remaining_ms is None
                                    else min(min_remaining_ms,
                                             remaining_ms))
        if expired:
            self.registry.note_unrouted(name, len(expired),
                                        sum(s["steps"] for s in expired))
            for st in expired:
                self._shed_deadline(st, f"relay to {name}")
        sts = live
        if not sts:
            return
        body = ("\n".join(json.dumps(st["line"], sort_keys=True)
                          for st in sts) + "\n").encode()
        headers = {"Content-Type": "application/x-ndjson",
                   "X-Trace-Id": sts[0]["trace_id"]}
        if min_remaining_ms is not None:
            headers["X-Deadline-Ms"] = f"{min_remaining_ms:.3f}"
        tr = self.tracer
        fwd_track = (tr.track(f"backend {name}", "forward")
                     if tr.enabled else None)
        t0 = time.perf_counter()
        try:
            conn = self._conn(b, self.fcfg.stream_timeout_s)
            conn.request("POST", "/v1/solve", body=body, headers=headers)
            resp = conn.getresponse()
        except (OSError, http.client.HTTPException) as e:
            self._retry_batch(name, sts, f"connect: {type(e).__name__}: {e}")
            return
        if resp.status != 200:
            reason = f"http {resp.status}"
            try:
                resp.read()
            except (OSError, http.client.HTTPException):
                pass
            conn.close()
            if resp.status == 504:
                # the backend judged the propagated deadline spent
                # before admission: terminal, not retryable — more hops
                # only burn more of a budget that is already gone
                self.registry.note_unrouted(name, len(sts),
                                            sum(s["steps"]
                                                for s in sts))
                for st in sts:
                    self._shed_deadline(st, f"backend {name} admission")
                return
            # 503 = draining, 429 = every line shed, anything else =
            # it never streamed: none of these admitted the work
            self._retry_batch(name, sts, reason,
                              overloaded=(resp.status == 429))
            return
        if tr.enabled:
            tr.complete(f"forward x{len(sts)}", fwd_track, t0, cat="rpc",
                        args={"backend": name, "requests": len(sts)})
        with self._lock:
            self._live_relays.setdefault(name, set()).add(resp)
        broke = False
        nrecords = 0
        try:
            while True:
                line = resp.readline()
                if not line:
                    break
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                rid = rec.get("id")
                if rid is not None:
                    self._deliver(rid, rec, backend=name)
                    nrecords += 1
                if (self._plan is not None
                        and self._plan.stream_cut_fire(name, nrecords)):
                    # stream-cut chaos: the relay connection dies after
                    # N records while the backend stays healthy — the
                    # hardened exactly-once re-drive path below
                    json_record("fleet_stream_cut", backend=name,
                                after=nrecords)
                    broke = True
                    break
        except (OSError, ValueError, http.client.HTTPException,
                AttributeError):
            # AttributeError: http.client's buffered reader races
            # resp.close() from _close_relays (fp goes None mid-peek) —
            # that IS the mid-stream break the steal path engineers
            broke = True
        finally:
            with self._lock:
                live = self._live_relays.get(name)
                if live is not None:
                    live.discard(resp)
            try:
                conn.close()
            except OSError:
                pass
        with self._lock:
            missing = [st for st in sts
                       if not st["delivered"] and st["backend"] == name]
            recovering = name in self._recovering
        if missing and not recovering:
            # stream ended without every record. If the backend still
            # answers /healthz the CONNECTION died, not the backend
            # (stream-cut chaos, a proxy hiccup): its admitted rows are
            # still computing there, so take the bounded re-drive path.
            # Only a genuinely dead backend pays for checkpoint
            # recovery.
            why = "relay-" + ("broke" if broke else "eof")
            if self._backend_alive(name):
                with self._lock:
                    self._stream_cuts += 1
                self._redrive_after_cut(name, missing, why)
            else:
                self._recover_backend(name, why)

    def _retry_batch(self, name: str, sts: List[dict], why: str,
                     overloaded: bool = False) -> None:
        """Never-admitted rows: re-place on alternates (the retry
        counter is per batch hop, so statusz shows the churn)."""
        self.registry.note_retry(name)
        self.registry.note_unrouted(name, len(sts),
                                    sum(s["steps"] for s in sts))
        if not overloaded:
            # a 429 is a LOAD signal, not a backend fault: the retry
            # budget handles it; breakers only trip on real errors
            self._breaker_event(
                name, self._breaker(name).note_error(why,
                                                     time.monotonic()),
                why)
        with self._lock:
            self._retries += 1
            for st in sts:
                st["tried"].append(name)
                st["backend"] = None
            hops = max(len(st["tried"]) for st in sts)
        json_record("fleet_retry", backend=name, requests=len(sts),
                    why=why)
        if not self._budget.take():
            # SRE retry budget: retries are capped as a fraction of
            # successes — a dry bucket means the fleet is amplifying
            # its own overload, so shed instead of re-dispatching
            json_record("fleet_retry_budget_exhausted", backend=name,
                        requests=len(sts))
            for st in sts:
                self._deliver(st["id"],
                              {"id": st["id"], "status": "rejected",
                               "error": "overloaded: fleet retry "
                                        "budget exhausted; retry "
                                        "later"}, backend=None)
            return
        # jittered exponential backoff before re-placement (full
        # jitter decorrelates a retry herd without coordination)
        time.sleep(resilience.backoff_s(hops - 1,
                                        self.fcfg.retry_backoff_s))
        # registry snapshot BEFORE taking the router lock: both locks
        # rank "fleet" and same-rank locks must never nest
        alive = {b.name for b in self.registry.snapshot()
                 if not b.lost and not b.fault_down}
        remaining = []
        for st in sts:
            with self._lock:
                exhausted = alive <= set(st["tried"])
            if exhausted:
                err = ("overloaded: every backend shed this request; "
                       "retry later" if overloaded else
                       f"unroutable: every backend refused ({why})")
                self._deliver(st["id"],
                              {"id": st["id"], "status": "rejected",
                               "error": err}, backend=None)
            else:
                remaining.append(st)
        if remaining:
            self.dispatch(remaining)

    def _close_relays(self, name: str) -> None:
        """Break every live relay stream to ``name`` (steal or injected
        drop): closing the response unblocks the relay thread's read,
        which then routes its undelivered rows into recovery."""
        with self._lock:
            live = list(self._live_relays.get(name, ()))
        for resp in live:
            try:
                resp.close()
            except OSError:
                pass

    # --- resilience: breakers, canaries, shedding, hedging ----------------
    def _breaker(self, name: str) -> resilience.Breaker:
        """Get-or-create the per-backend breaker. Only the dict op is
        under the router lock — Breaker methods self-lock at the same
        fleet rank, so callers invoke them after release."""
        with self._lock:
            br = self._breakers.get(name)
            if br is None:
                br = resilience.Breaker(
                    name, trip_threshold=self.fcfg.breaker_trip,
                    cooldown_s=self.fcfg.breaker_cooldown_s,
                    burn_trip_ticks=self.fcfg.breaker_burn_ticks)
                self._breakers[name] = br
        return br

    def _breaker_blocked(self) -> Set[str]:
        """Backends whose breaker refuses new placements right now."""
        with self._lock:
            brs = list(self._breakers.values())
        return {br.backend for br in brs if not br.allows()}

    def _breaker_event(self, name: str, new_state: Optional[str],
                       reason: str) -> None:
        """Record a breaker transition (None = the feed didn't trip
        anything): structured record, trace instant, and the timestamp
        the steal loop's thrash guard keys on."""
        if new_state is None:
            return
        with self._lock:
            self._last_breaker_transition_t = time.monotonic()
        json_record("fleet_breaker_transition", backend=name,
                    state=new_state, reason=reason)
        if self.tracer.enabled:
            self.tracer.instant(
                f"breaker {new_state}",
                self.tracer.track("fleet router", "resilience"),
                cat="fleet", args={"backend": name, "reason": reason})
        master_print(f"fleet: breaker[{name}] -> {new_state} ({reason})")

    def _canary_sweep(self, now: float) -> None:
        """Move cooled-down open breakers to half-open and launch one
        router-path canary each (the breaker holds the single slot)."""
        with self._lock:
            brs = list(self._breakers.values())
        for br in brs:
            if br.try_half_open(now):
                self._breaker_event(br.backend, resilience.HALF_OPEN,
                                    "cooldown-elapsed")
                threading.Thread(
                    target=self._run_canary, args=(br.backend,),
                    daemon=True,
                    name=f"heat-tpu-fleet-canary-{br.backend}").start()

    def _run_canary(self, name: str) -> None:
        """Half-open re-admission: run the sine canary THROUGH the
        router's forward path against the suspect backend and verify
        the returned field against the closed-form answer. /healthz
        alone is not enough — a backend that answers health checks but
        serves wrong bytes stays out. A pass closes the breaker AND
        clears ``lost`` (mark_found); a failure doubles the cooldown."""
        b = self.registry.get(name)
        ok = (b is not None and not b.fault_down and not b.draining
              and self._canary_solve(b))
        state = self._breaker(name).canary_result(ok, time.monotonic())
        self._breaker_event(name, state,
                            "canary-pass" if ok else "canary-fail")
        if ok:
            self.registry.mark_found(name)
            json_record("fleet_breaker_readmit", backend=name)

    def _canary_solve(self, b) -> bool:
        """One end-to-end known-answer solve against backend ``b``
        (serve/probe.py's contract: ``_probe`` tenant, batch class,
        field fetched back and compared in f64 max-norm)."""
        import numpy as np

        from ..serve import probe as probe_mod

        with self._lock:
            self._canary_seq += 1
            rid = f"_breaker-canary-{b.name}-{self._canary_seq:04d}"
        req = dict(probe_mod.DEFAULT_PROBE_REQUEST, id=rid,
                   tenant=probe_mod.PROBE_TENANT, **{"class": "batch"})
        try:
            code, data = self._http(
                b, "POST", "/v1/solve",
                body=(json.dumps(req) + "\n").encode(),
                headers={"Content-Type": "application/x-ndjson"},
                timeout=self.fcfg.stream_timeout_s)
            if code != 200:
                return False
            rec = None
            for line in data.decode("utf-8", "replace").splitlines():
                if line.strip():
                    cand = json.loads(line)
                    if cand.get("id") == rid:
                        rec = cand
            if rec is None or rec.get("status") != "ok":
                return False
            code, data = self._http(b, "GET",
                                    f"/v1/requests/{rid}?field=1")
            if code != 200:
                return False
            T = json.loads(data).get("T")
            if T is None:
                return False
            err = float(np.max(np.abs(
                np.asarray(T, dtype=np.float64)
                - probe_mod.expected_probe_field(req))))
            return err <= probe_mod.PROBE_TOL[req["dtype"]]
        except (OSError, ValueError, KeyError,
                http.client.HTTPException):
            return False

    def _shed_deadline(self, st: dict, where: str) -> None:
        """Terminal ``deadline`` record minted at the edge: the row's
        propagated budget is spent, so it never starts (zero device
        steps billed to the tenant)."""
        rec = {"id": st["id"], "status": "deadline",
               "tenant": st["tenant"], "class": st["class"],
               "error": f"deadline: edge-minted budget exhausted at "
                        f"{where}; the request never started there "
                        f"(zero device steps billed)"}
        with self._lock:
            self._deadline_shed += 1
        json_record("fleet_deadline_shed", id=st["id"],
                    slo_class=st["class"], where=where)
        self._deliver(st["id"], rec, backend=None)

    def _shed_brownout(self, st: dict, level: int) -> bool:
        """Brownout degradation: when EVERY eligible backend's fast AND
        slow burn windows fire, shed by class at the edge — batch first
        (level 1), then standard too (level 2); interactive is never
        shed. Replaces the old all-burn behaviour for these classes
        (demotion disabled, work placed anyway): shedding the deferrable
        classes gives every replica headroom to recover."""
        cls = st["class"]
        if cls == "interactive" or (level < 2 and cls != "batch"):
            return False
        rec = {"id": st["id"], "status": "rejected",
               "tenant": st["tenant"], "class": cls,
               "error": f"brownout: every backend is burning SLO "
                        f"budget in both windows; {cls} admission "
                        f"shed at the edge (level {level})",
               "retry_after_s": self.fcfg.retry_after_s}
        with self._lock:
            self._brownout_shed += 1
        json_record("fleet_brownout_shed", id=st["id"], slo_class=cls,
                    level=level)
        self._deliver(st["id"], rec, backend=None)
        return True

    def _backend_alive(self, name: str) -> bool:
        """Quick liveness check for the stream-cut path: is the backend
        still answering /healthz after its relay stream broke?"""
        b = self.registry.get(name)
        if b is None or b.lost or b.fault_down:
            return False
        try:
            code, _ = self._http(b, "GET", "/healthz")
        except (OSError, http.client.HTTPException):
            return False
        return code == 200

    def _redrive_after_cut(self, name: str, missing: List[dict],
                           why: str) -> None:
        """Mid-stream break against a LIVE backend (stream-cut chaos, a
        proxy hiccup): the rows were already admitted there, so poll
        that same backend for their terminal records first — recomputing
        elsewhere would waste device steps. Rows still unfinished after
        the bounded wait re-dispatch on a survivor; the exactly-once
        chokepoint keeps the client stream duplicate-free either way,
        reconciled against any manifest adoption racing this."""
        json_record("fleet_stream_redrive", backend=name,
                    rows=len(missing), why=why)
        pending = {st["id"]: st for st in missing}
        deadline = time.monotonic() + self.fcfg.cut_redrive_wait_s
        while pending and time.monotonic() < deadline:
            b = self.registry.get(name)
            if b is None or b.lost or b.fault_down:
                break
            for rid in sorted(pending):
                try:
                    code, data = self._http(b, "GET",
                                            f"/v1/requests/{rid}")
                except (OSError, http.client.HTTPException):
                    break
                if code != 200:
                    continue
                try:
                    rec = json.loads(data)
                except ValueError:
                    continue
                if rec.get("status") in TERMINAL_STATUSES:
                    pending.pop(rid)
                    self._deliver(rid, rec, backend=name)
            if self._stop.wait(0.1):
                break
        leftovers = [st for st in pending.values()]
        if not leftovers:
            return
        self.registry.note_unrouted(name, len(leftovers),
                                    sum(s["steps"] for s in leftovers))
        with self._lock:
            for st in leftovers:
                st["tried"].append(name)
                st["backend"] = None
        self.dispatch(leftovers)

    def _maybe_hedge(self, now: float) -> None:
        """Tail-latency hedging (Dean & Barroso, "The Tail at Scale"):
        an interactive row that has waited past ``hedge_factor`` x its
        predicted service time (+ floor) is duplicated onto a second
        breaker-closed backend. The first terminal record wins at the
        exactly-once chokepoint; the loser is deadline-preempted at its
        next chunk boundary via POST /v1/cancel."""
        with self._lock:
            cands = [st for st in self._requests.values()
                     if (not st["delivered"] and not st["hedged"]
                         and st["class"] == "interactive"
                         and st["backend"] is not None
                         and st["dispatch_t"] is not None
                         and now - st["dispatch_t"]
                         > self.fcfg.hedge_factor * (st["expect_s"] or 0)
                         + self.fcfg.hedge_floor_s)]
        for st in cands:
            with self._lock:
                if st["hedged"] or st["delivered"]:
                    continue
                primary = st["backend"]
                tried = set(st["tried"])
            if primary is None:
                continue
            b, _ = self._choose(st["n"], tried | {primary})
            if b is None:
                continue   # nowhere to hedge to — the primary stands
            with self._lock:
                if st["hedged"] or st["delivered"]:
                    continue
                st["hedged"] = True
                st["hedge_backend"] = b.name
                self._hedges["fired"] += 1
            json_record("fleet_hedge", id=st["id"], primary=primary,
                        hedge=b.name)
            if self.tracer.enabled:
                self.tracer.instant(
                    "hedge-fired",
                    self.tracer.track("fleet router", "resilience"),
                    cat="fleet", args={"id": st["id"],
                                       "primary": primary,
                                       "hedge": b.name})
            self.registry.note_routed(b.name, 1, st["steps"])
            threading.Thread(
                target=self._hedge_relay, args=(st, b.name), daemon=True,
                name=f"heat-tpu-fleet-hedge-{b.name}").start()

    def _hedge_relay(self, st: dict, name: str) -> None:
        """Forward the hedge twin (id suffixed ``~hedge``, reserved
        tenant ``_hedge`` so per-backend ledgers attribute the duplicate
        cost — the real tenant is billed once, on the primary) and
        promote its record to the primary id iff it finishes ok; the
        exactly-once chokepoint settles the race with the primary."""
        rid = st["id"]
        hid = f"{rid}~hedge"
        with self._lock:
            line = dict(st["line"])
            dt = st["deadline_t"]
            steps = st["steps"]
        line["id"] = hid
        line["tenant"] = "_hedge"
        if dt is not None:
            line["deadline_ms"] = max(1.0,
                                      (dt - time.monotonic()) * 1e3)
        won = False
        b = self.registry.get(name)
        try:
            conn = self._conn(b, self.fcfg.stream_timeout_s)
            conn.request(
                "POST", "/v1/solve",
                body=(json.dumps(line, sort_keys=True) + "\n").encode(),
                headers={"Content-Type": "application/x-ndjson",
                         "X-Trace-Id": st["trace_id"]})
            resp = conn.getresponse()
            if resp.status == 200:
                while True:
                    raw = resp.readline()
                    if not raw:
                        break
                    raw = raw.strip()
                    if not raw:
                        continue
                    try:
                        rec = json.loads(raw)
                    except ValueError:
                        continue
                    if (rec.get("id") == hid
                            and rec.get("status") in TERMINAL_STATUSES):
                        # only an OK twin may speak for the primary id:
                        # a cancelled/failed hedge must never mask a
                        # primary that is still computing
                        if rec.get("status") == "ok":
                            rec2 = dict(rec, id=rid,
                                        tenant=st["tenant"], hedged=True)
                            won = self._deliver(rid, rec2, backend=name)
                        break
            else:
                resp.read()
            conn.close()
        except (OSError, ValueError, http.client.HTTPException):
            pass
        if not won:
            # the twin lost (or never finished): reverse its pending
            # accounting — note_done ran for the winner only
            self.registry.note_unrouted(name, 1, steps)
            with self._lock:
                self._hedges["lost"] += 1

    def _cancel_loser(self, rid: str, winner: str, primary: str,
                      hedge_backend: str, steps: int) -> None:
        """Deadline-preempt the losing side of a hedged pair at its
        next chunk boundary (POST /v1/cancel) so it stops burning
        device time, and settle the accounting for a hedge win."""
        if winner == hedge_backend:
            loser, lrid = primary, rid
            self.registry.note_unrouted(primary, 1, steps)
            with self._lock:
                self._hedges["won"] += 1
        else:
            loser, lrid = hedge_backend, f"{rid}~hedge"
        lb = self.registry.get(loser)
        if lb is None:
            return
        try:
            code, data = self._http(
                lb, "POST", "/v1/cancel",
                body=json.dumps({"id": lrid}).encode(),
                headers={"Content-Type": "application/json"})
            if code == 200 and json.loads(data).get("cancelled"):
                with self._lock:
                    self._hedges["cancelled"] += 1
        except (OSError, ValueError, http.client.HTTPException):
            pass

    # --- delivery (exactly-once) ------------------------------------------
    def _deliver(self, rid: str, rec: dict,
                 backend: Optional[str]) -> bool:
        """The single exactly-once chokepoint: the first terminal record
        for a request id wins; every later one (re-driven work finishing
        twice, a poller racing a relay) is dropped and counted."""
        with self._lock:
            st = self._requests.get(rid)
            if st is None:
                return False   # not router-tracked (direct-to-backend)
            if st["delivered"]:
                self._duplicates += 1
                return False
            st["delivered"] = True
            st["rec"] = rec
            q = st["q"]
            steps = st["steps"]
            hedged = st["hedged"]
            hedge_backend = st["hedge_backend"]
            primary = st["backend"]
        if backend is not None:
            self.registry.note_done(backend, steps)
            self._breaker(backend).note_success()
            if rec.get("status") == "ok":
                self._budget.credit()
            if hedged and hedge_backend is not None:
                # the other side of the hedged pair is still computing:
                # deadline-preempt it and settle the accounting (the
                # loser's eventual record lands here as a duplicate)
                threading.Thread(
                    target=self._cancel_loser,
                    args=(rid, backend, primary, hedge_backend, steps),
                    daemon=True,
                    name=f"heat-tpu-fleet-unhedge-{rid}").start()
        tr = self.tracer
        if tr.enabled and backend is not None:
            t1 = tr.now()
            solve_s = rec.get("solve_s") or 0.0
            tid = rec.get("trace_id")
            track = tr.track(f"backend {backend}", "solve")
            tr.complete(str(rid), track, t1 - float(solve_s), t1,
                        cat="serve", trace_id=tid,
                        args={"status": rec.get("status")})
            if tid:
                tr.flow("f", track, tid)
        if q is not None:
            q.put(rec)
        return True

    # --- health + imbalance ----------------------------------------------
    def _health_loop(self) -> None:
        while not self._stop.wait(self.fcfg.health_interval_s):
            self._health_tick()

    def _health_tick(self) -> None:
        self.registry.refresh_file()
        now = time.monotonic()
        if self._plan is not None:
            # backend-flap chaos: square-wave the fault_down bit so the
            # router DISCOVERS each edge through its own probes
            for bname, down in self._plan.backend_flap_states(
                    now).items():
                fb = self.registry.get(bname)
                if fb is None or fb.fault_down == down:
                    continue
                self.registry.set_fault_down(bname, down)
                json_record("fleet_backend_flap", backend=bname,
                            down=down)
                if down:
                    self._close_relays(bname)
        for b in self.registry.snapshot():
            if b.lost:
                # re-admission goes exclusively through the breaker's
                # half-open canary (the sweep below), never a bare probe
                continue
            ok, draining, status = False, False, None
            if not b.fault_down:
                try:
                    code, _ = self._http(b, "GET", "/healthz")
                    draining = code == 503
                    ok = code == 200
                    if ok:
                        scode, sbody = self._http(b, "GET", "/v1/status")
                        if scode == 200:
                            status = json.loads(sbody)
                except (OSError, ValueError,
                        http.client.HTTPException):
                    ok = False
            was, is_now = self.registry.note_probe(
                b.name, ok, draining=draining, status=status, now=now)
            br = self._breaker(b.name)
            if ok:
                br.note_success()
            else:
                self._breaker_event(b.name,
                                    br.note_error("probe", now), "probe")
            self._breaker_event(
                b.name,
                br.note_burn(placement.burn_demoted(status), now),
                "slo-burn")
            if was and not is_now and not draining:
                # hard down transition (connect failure / 500 / chaos):
                # recover its orphans; a 503-draining backend still
                # finishes its in-flight work, so only placement stops
                threading.Thread(
                    target=self._recover_backend,
                    args=(b.name, "health-probe"), daemon=True,
                    name=f"heat-tpu-fleet-recover-{b.name}").start()
        self._canary_sweep(now)
        if self.fcfg.hedge_factor > 0:
            self._maybe_hedge(now)
        if self.fcfg.steal_threshold_s > 0:
            self._maybe_steal(now)

    def _maybe_steal(self, now: float) -> None:
        with self._lock:
            if (self._recovering
                    or now - self._last_steal_t
                    < self.fcfg.steal_cooldown_s
                    # breaker-aware cooldown: a breaker that just moved
                    # means the fleet is mid-incident — a steal now
                    # would thrash against a flapping backend
                    or (self._last_breaker_transition_t > 0
                        and now - self._last_breaker_transition_t
                        < self.fcfg.steal_cooldown_s)):
                return
        blocked = self._breaker_blocked()
        cands = [b for b in self.registry.snapshot()
                 if b.healthy and not b.lost and not b.fault_down
                 and b.name not in blocked]
        if len(cands) < 2:
            return
        scores = {b.name: placement.predicted_backlog_s(b) for b in cands}
        victim = max(cands, key=lambda b: scores[b.name])
        thief = min(cands, key=lambda b: scores[b.name])
        if (victim.name == thief.name
                or scores[victim.name] - scores[thief.name]
                < self.fcfg.steal_threshold_s
                or placement.backlog_steps(victim) <= 0):
            return
        with self._lock:
            self._last_steal_t = now
        threading.Thread(
            target=self.steal, args=(victim.name, thief.name),
            kwargs={"reason": "imbalance"}, daemon=True,
            name="heat-tpu-fleet-steal").start()

    # --- checkpoint recovery + work stealing ------------------------------
    def _ckpt_dir(self, b) -> Optional[Path]:
        st = b.status or {}
        d = ((st.get("engine_ckpt") or {}).get("dir")
             or (Path(self.fcfg.ckpt_root) / b.name
                 if self.fcfg.ckpt_root else None))
        if d is None:
            return None
        d = Path(d)
        return d if d.is_dir() else None

    def _orphans_of(self, name: str) -> List[dict]:
        with self._lock:
            return [st for st in self._requests.values()
                    if st["backend"] == name and not st["delivered"]]

    def _adopt(self, victim: str, thief_b, detail: dict,
               orphans: List[dict]) -> Tuple[List[dict], List[dict]]:
        """Split a victim's orphans after a resume on ``thief_b``:
        manifest-covered ids are reassigned and polled there;
        everything else (including manifest-``done`` ids whose records
        died with the victim) re-drives fresh — the solver is
        deterministic, so either path produces identical bytes."""
        recovered = set(detail.get("recovered") or ())
        polled, redrive = [], []
        for st in orphans:
            if st["id"] in recovered:
                polled.append(st)
            else:
                redrive.append(st)
        moved_steps = sum(s["steps"] for s in polled + redrive)
        self.registry.note_unrouted(victim, len(polled) + len(redrive),
                                    moved_steps)
        with self._lock:
            for st in polled:
                st["tried"].append(victim)
                st["backend"] = thief_b.name
            for st in redrive:
                st["tried"].append(victim)
                st["backend"] = None
        if polled:
            self.registry.note_routed(thief_b.name, len(polled),
                                      sum(s["steps"] for s in polled))
            threading.Thread(
                target=self._poll_recovered,
                args=(thief_b.name, [st["id"] for st in polled]),
                daemon=True,
                name=f"heat-tpu-fleet-poll-{thief_b.name}").start()
        if redrive:
            self.dispatch(redrive)
        return polled, redrive

    def _recover_backend(self, name: str, reason: str) -> None:
        """A backend is gone (probe failure, relay break, chaos drop):
        flight-dump the fleet timeline, resume its newest checkpoint
        manifest onto the least-loaded survivor, poll the resumed ids
        there, and re-drive whatever the manifest does not cover."""
        with self._lock:
            if name in self._recovering:
                return
            self._recovering.add(name)
            self._lost += 1
        try:
            self.registry.mark_lost(name)
            self._breaker_event(
                name, self._breaker(name).trip("lost", time.monotonic()),
                "lost")
            b = self.registry.get(name)
            master_print(f"fleet: backend {name} lost ({reason}) — "
                         f"recovering")
            json_record("fleet_backend_lost", backend=name, reason=reason)
            self.tracer.flight_dump(self.fcfg.flightrec_dir,
                                    f"backend {name} lost ({reason})")
            self._close_relays(name)
            orphans = self._orphans_of(name)
            detail: dict = {}
            d = self._ckpt_dir(b) if b is not None else None
            thief, _ = self._choose(None, {name})
            if d is not None and thief is not None:
                try:
                    code, data = self._http(
                        thief, "POST", "/v1/resume",
                        body=json.dumps({"dir": str(d)}).encode(),
                        headers={"Content-Type": "application/json"},
                        timeout=self.fcfg.steal_timeout_s)
                    if code == 200:
                        detail = json.loads(data)
                except (OSError, ValueError,
                        http.client.HTTPException) as e:
                    master_print(f"fleet: resume of {name}'s checkpoint "
                                 f"on {thief.name} failed ({e}) — "
                                 f"re-driving fresh")
            polled, redrive = self._adopt(
                name, thief, detail, orphans) if thief is not None \
                else ([], orphans)
            if thief is None:
                for st in redrive:
                    self._reject_unroutable(st, "fleet-exhausted")
            json_record("fleet_recovery", backend=name, reason=reason,
                        generation=detail.get("generation", 0),
                        recovered=len(polled), redriven=len(redrive))
        finally:
            with self._lock:
                self._recovering.discard(name)

    def steal(self, victim: str, thief: Optional[str] = None,
              reason: str = "forced") -> Optional[dict]:
        """Work stealing as checkpoint handoff: drain the victim to a
        checkpoint (``/drainz?handoff=1``), pick up the manifest
        generation from its checkpoint dir, resume it on the thief, and
        re-point the orphans. Returns the steal event dict (also on
        /statusz) or None if a recovery already owns the victim."""
        t0 = time.monotonic()
        with self._lock:
            if victim in self._recovering:
                return None
            self._recovering.add(victim)
        try:
            vb = self.registry.get(victim)
            if vb is None:
                return None
            gen_before = int(((vb.status or {}).get("engine_ckpt")
                              or {}).get("generation") or 0)
            d = self._ckpt_dir(vb)
            self.registry.mark_lost(victim)   # placement stops NOW; the
            # probe loop must not start a second, competing recovery
            try:
                self._http(vb, "POST", "/drainz?handoff=1",
                           timeout=self.fcfg.connect_timeout_s)
            except (OSError, http.client.HTTPException) as e:
                master_print(f"fleet: steal drain of {victim} failed "
                             f"({e}) — falling back to loss recovery")
            self._close_relays(victim)
            t_drain = time.monotonic()
            generation = 0
            if d is not None:
                deadline = t0 + self.fcfg.steal_timeout_s
                while time.monotonic() < deadline:
                    manifest, _ = ckpt_mod.latest_engine_manifest(d)
                    if (manifest is not None
                            and int(manifest["generation"]) > gen_before):
                        generation = int(manifest["generation"])
                        break
                    if self._stop.wait(0.1):
                        break
            tb = (self.registry.get(thief) if thief
                  else self._choose(None, {victim})[0])
            detail: dict = {}
            if generation and tb is not None:
                try:
                    code, data = self._http(
                        tb, "POST", "/v1/resume",
                        body=json.dumps({"dir": str(d)}).encode(),
                        headers={"Content-Type": "application/json"},
                        timeout=self.fcfg.steal_timeout_s)
                    if code == 200:
                        detail = json.loads(data)
                except (OSError, ValueError,
                        http.client.HTTPException) as e:
                    master_print(f"fleet: steal resume on "
                                 f"{tb.name} failed ({e})")
            t_resume = time.monotonic()
            orphans = self._orphans_of(victim)
            polled, redrive = self._adopt(
                victim, tb, detail, orphans) if tb is not None \
                else ([], orphans)
            if tb is None:
                for st in redrive:
                    self._reject_unroutable(st, "fleet-exhausted")
            self.registry.note_steal(victim, tb.name if tb else "")
            event = {"victim": victim,
                     "thief": tb.name if tb is not None else None,
                     "reason": reason, "generation": generation,
                     "recovered": len(polled), "redriven": len(redrive),
                     "drain_s": round(t_drain - t0, 3),
                     "resume_s": round(t_resume - t_drain, 3),
                     "wall_s": round(time.monotonic() - t0, 3)}
            with self._lock:
                self._steals.append(event)
            json_record("fleet_steal", **event)
            master_print(f"fleet: stole {len(polled) + len(redrive)} "
                         f"request(s) from {victim} -> "
                         f"{event['thief']} (gen {generation}, "
                         f"{event['wall_s']}s)")
            return event
        finally:
            with self._lock:
                self._recovering.discard(victim)

    def _poll_recovered(self, thief_name: str, rids: List[str]) -> None:
        """Relay terminal records for resumed orphans by polling the
        thief's ``GET /v1/requests/<id>`` (a resumed request has no
        streaming response anywhere — the victim's stream died with
        it)."""
        pending = set(rids)
        deadline = time.monotonic() + self.fcfg.stream_timeout_s
        while pending and time.monotonic() < deadline:
            tb = self.registry.get(thief_name)
            if tb is None or tb.lost:
                break    # thief died too; its own recovery re-drives
            for rid in sorted(pending):
                try:
                    code, data = self._http(tb, "GET",
                                            f"/v1/requests/{rid}")
                except (OSError, http.client.HTTPException):
                    break
                if code != 200:
                    continue
                try:
                    rec = json.loads(data)
                except ValueError:
                    continue
                if rec.get("status") in TERMINAL_STATUSES:
                    pending.discard(rid)
                    self._deliver(rid, rec, backend=thief_name)
            if self._stop.wait(0.15):
                break
        for rid in sorted(pending):
            self._deliver(rid, {"id": rid, "status": "error",
                                "error": "steal: resumed request did "
                                         "not finish within the stream "
                                         "timeout"},
                          backend=thief_name)

    # --- observability snapshots ------------------------------------------
    def snapshot(self) -> dict:
        """Router + per-backend state for /metrics, /statusz and
        /v1/status — one consistent read of the router tables, then the
        registry (the two locks never nest)."""
        with self._lock:
            router = {"pending": sum(1 for st in self._requests.values()
                                     if not st["delivered"]),
                      "requests": len(self._requests),
                      "duplicates": self._duplicates,
                      "edge_rejected": self._edge_rejected,
                      "cache_edge_hits": self._cache_edge_hits,
                      "cache_prefix_hints": self._cache_prefix_hints,
                      "retries": self._retries,
                      "lost": self._lost,
                      "forwards": self._forwards,
                      "draining": self._draining,
                      "deadline_shed": self._deadline_shed,
                      "brownout_shed": self._brownout_shed,
                      "stream_cuts": self._stream_cuts,
                      "hedges": dict(self._hedges),
                      "steals": list(self._steals)}
            brs = list(self._breakers.values())
        # breaker/budget snapshots take their own fleet-rank locks, so
        # they are read strictly after the router lock is released
        router["retry_budget"] = self._budget.snapshot()
        router["breakers"] = dict(resilience.breaker_rows(brs))
        backends = {}
        for b in self.registry.snapshot():
            backends[b.name] = {
                "address": b.address,
                "healthy": b.healthy, "draining": b.draining,
                "lost": b.lost, "fault_down": b.fault_down,
                "demoted": placement.burn_demoted(b.status),
                "backlog_s": round(placement.predicted_backlog_s(b), 6),
                "backlog_steps": placement.backlog_steps(b),
                "pending_requests": b.pending_requests,
                "routed": b.routed, "delivered": b.delivered,
                "retried": b.retried,
                "stolen_from": b.stolen_from, "stolen_to": b.stolen_to,
                "probe_passes": b.probe_passes,
                "probe_fails": b.probe_fails,
                "consecutive_failures": b.consecutive_failures,
                "mega_capable": bool(((b.status or {}).get("mega")
                                      or {}).get("capable")),
                "engine_ckpt_generation": int(
                    ((b.status or {}).get("engine_ckpt")
                     or {}).get("generation") or 0),
                "serve_resumed": (b.status or {}).get("serve_resumed", 0),
                "queued_now": (b.status or {}).get("queued_now", 0),
                "cache_enabled": (b.status or {}).get("cache")
                is not None,
            }
        return {"kind": "heat-tpu-fleet-status",
                "policy": self.fcfg.policy,
                "steal_threshold_s": self.fcfg.steal_threshold_s,
                "hedge_factor": self.fcfg.hedge_factor,
                "brownout_level": placement.brownout_level(
                    self.registry.snapshot()),
                "uptime_s": round(trace_mod.process_uptime_s(), 3),
                "cache": (self.solvecache.stats()
                          if self.solvecache is not None else None),
                "router": router, "backends": backends}

    def fleet_usage(self) -> dict:
        """Fleet-wide ``/v1/usage``: every reachable backend's ledger,
        merged (exact reconciliation — the sums are the per-engine sums)
        plus the raw per-backend payloads. Edge-served cache hits never
        touched a backend, so their ledger rides along as the pseudo-
        backend ``_edge`` — fleet totals still equal the sum of the
        parts."""
        per_backend = {}
        for b in self.registry.snapshot():
            if b.lost or b.fault_down:
                continue
            try:
                code, data = self._http(b, "GET", "/v1/usage")
                if code == 200:
                    per_backend[b.name] = json.loads(data)
            except (OSError, ValueError, http.client.HTTPException):
                continue
        edge = self._edge_ledger.snapshot()
        if edge["totals"]["requests"]:
            per_backend["_edge"] = edge
        return merge_usage(per_backend)


def merge_usage(per_backend: Dict[str, dict]) -> dict:
    """Pure merge of per-engine ``/v1/usage`` ledgers: per-(tenant,
    class) fields and engine totals are summed across backends, and the
    raw payloads ride along under ``per_backend`` so the reconciliation
    is auditable — fleet totals equal the sum of per-engine ledgers by
    construction."""
    fields = ("lane_s", "steps", "chunks", "bytes_written",
              "steps_saved", "cached", "requests")
    tenants: Dict[str, dict] = {}
    totals = {f: 0 for f in fields}
    for payload in per_backend.values():
        for tname, t in (payload.get("tenants") or {}).items():
            tdst = tenants.setdefault(tname, {"classes": {}})
            for cname, c in (t.get("classes") or {}).items():
                cdst = tdst["classes"].setdefault(
                    cname, {f: 0 for f in fields})
                for f in fields:
                    cdst[f] = round(cdst[f] + c.get(f, 0), 9)
        for f in fields:
            totals[f] = round(totals[f]
                              + (payload.get("totals") or {}).get(f, 0), 9)
    return {"kind": "heat-tpu-fleet-usage",
            "backends": sorted(per_backend),
            "tenants": tenants, "totals": totals,
            "per_backend": per_backend}


def render_fleet_metrics(router: Router) -> str:
    """The router's ``/metrics`` (Prometheus text format): router-native
    series with per-backend labels. Pure function of the router so tests
    assert without a socket."""
    from ..serve.gateway import escape_label_value

    s = router.snapshot()
    out = []

    def metric(name, mtype, help_text, samples):
        out.append(f"# HELP {name} {help_text}")
        out.append(f"# TYPE {name} {mtype}")
        for labels, value in samples:
            lbl = ("{" + ",".join(
                f'{k}="{escape_label_value(v)}"' for k, v in labels) + "}"
                   if labels else "")
            out.append(f"{name}{lbl} {value}")

    metric("heat_tpu_fleet_info", "gauge",
           "Router identity/config (value is always 1).",
           [([("policy", s["policy"]),
              ("steal_threshold_s", s["steal_threshold_s"])], 1)])
    metric("heat_tpu_fleet_uptime_seconds", "gauge",
           "Seconds since this router process started.",
           [([], s["uptime_s"])])
    metric("heat_tpu_fleet_draining", "gauge",
           "1 once the router's /drainz has been called.",
           [([], int(s["router"]["draining"]))])
    bk = sorted(s["backends"].items())
    metric("heat_tpu_fleet_backend_up", "gauge",
           "1 while the backend passes health probes and accepts "
           "placements.",
           [([("backend", n)], int(b["healthy"])) for n, b in bk]
           or [([], 0)])
    metric("heat_tpu_fleet_backend_demoted", "gauge",
           "1 while burn-aware placement demotes the backend (fast AND "
           "slow SLO burn windows over threshold for some class).",
           [([("backend", n)], int(b["demoted"])) for n, b in bk]
           or [([], 0)])
    metric("heat_tpu_fleet_backend_backlog_seconds", "gauge",
           "Predicted backlog seconds per backend (cost model x queue "
           "work + router-pending) — the least-loaded placement score.",
           [([("backend", n)], b["backlog_s"]) for n, b in bk]
           or [([], 0)])
    metric("heat_tpu_fleet_routed_total", "counter",
           "Requests forwarded, per backend.",
           [([("backend", n)], b["routed"]) for n, b in bk] or [([], 0)])
    metric("heat_tpu_fleet_delivered_total", "counter",
           "Terminal records delivered to clients, per serving backend.",
           [([("backend", n)], b["delivered"]) for n, b in bk]
           or [([], 0)])
    metric("heat_tpu_fleet_retried_total", "counter",
           "Batch forwards retried on an alternate backend (the "
           "never-reached-admission path), per refused backend.",
           [([("backend", n)], b["retried"]) for n, b in bk]
           or [([], 0)])
    metric("heat_tpu_fleet_probe_failures_total", "counter",
           "Health-probe failures, per backend.",
           [([("backend", n)], b["probe_fails"]) for n, b in bk]
           or [([], 0)])
    metric("heat_tpu_fleet_backends_lost_total", "counter",
           "Backends transitioned to lost (recovery ran).",
           [([], s["router"]["lost"])])
    metric("heat_tpu_fleet_steals_total", "counter",
           "Checkpoint-handoff work steals, per victim backend.",
           [([("backend", n)], b["stolen_from"]) for n, b in bk]
           or [([], 0)])
    metric("heat_tpu_fleet_requests_pending", "gauge",
           "Router-tracked requests awaiting a terminal record.",
           [([], s["router"]["pending"])])
    metric("heat_tpu_fleet_duplicates_dropped_total", "counter",
           "Terminal records dropped by the exactly-once delivery "
           "chokepoint (a re-driven request finishing twice).",
           [([], s["router"]["duplicates"])])
    metric("heat_tpu_fleet_edge_rejected_total", "counter",
           "Request lines rejected at the router edge (parse/validate/"
           "duplicate) without ever reaching a backend.",
           [([], s["router"]["edge_rejected"])])
    metric("heat_tpu_fleet_cache_edge_hits_total", "counter",
           "Requests served entirely at the edge from the shared solve "
           "cache (zero backends touched).",
           [([], s["router"]["cache_edge_hits"])])
    metric("heat_tpu_fleet_cache_prefix_hints_total", "counter",
           "Placements steered toward a cache-enabled backend by a "
           "prefix hit in the shared solve cache.",
           [([], s["router"]["cache_prefix_hints"])])
    cache = s.get("cache") or {}
    metric("heat_tpu_fleet_cache_entries", "gauge",
           "Entries in the shared solve-cache dir as the router sees "
           "it (read-only).", [([], cache.get("entries", 0))])
    metric("heat_tpu_fleet_cache_bytes", "gauge",
           "Bytes the shared solve-cache dir holds as the router sees "
           "it.", [([], cache.get("bytes", 0))])
    metric("heat_tpu_fleet_flightrec_dumps_total", "counter",
           "Fleet-timeline flight dumps written on backend loss.",
           [([], router.tracer.dumps)])
    breakers = sorted((s["router"].get("breakers") or {}).items())
    metric("heat_tpu_fleet_breaker_state", "gauge",
           "Per-backend circuit-breaker state (0 closed, 1 half-open, "
           "2 open).",
           [([("backend", n)], b["code"]) for n, b in breakers]
           or [([], 0)])
    metric("heat_tpu_fleet_breaker_transitions_total", "counter",
           "Circuit-breaker state transitions, per backend.",
           [([("backend", n)], b["transitions"]) for n, b in breakers]
           or [([], 0)])
    hedges = s["router"]["hedges"]
    metric("heat_tpu_fleet_hedges_total", "counter",
           "Hedged interactive dispatches by outcome (fired = twin "
           "sent, won = twin's record reached the client first, lost = "
           "twin discarded, cancelled = loser preempted mid-solve).",
           [([("outcome", k)], v) for k, v in sorted(hedges.items())])
    rb = s["router"]["retry_budget"]
    metric("heat_tpu_fleet_retry_budget_remaining", "gauge",
           "Tokens left in the fleet-wide retry budget (retries are "
           "capped as a fraction of delivered successes).",
           [([], round(rb["tokens"], 6))])
    metric("heat_tpu_fleet_retry_budget_denied_total", "counter",
           "Re-dispatches refused because the retry budget was dry "
           "(the rows were shed instead of amplifying overload).",
           [([], rb["denied"])])
    metric("heat_tpu_fleet_deadline_shed_total", "counter",
           "Rows shed because their edge-minted deadline budget was "
           "already spent (at placement, a relay hop, or backend "
           "admission) — they never started device work.",
           [([], s["router"]["deadline_shed"])])
    metric("heat_tpu_fleet_brownout_shed_total", "counter",
           "Rows shed by class at the edge during fleet-wide brownout "
           "(every backend burning SLO budget in both windows).",
           [([], s["router"]["brownout_shed"])])
    metric("heat_tpu_fleet_stream_cuts_total", "counter",
           "Mid-stream relay breaks against a still-live backend that "
           "took the bounded re-drive path instead of loss recovery.",
           [([], s["router"]["stream_cuts"])])
    return "\n".join(out) + "\n"


def render_fleet_statusz(router: Router) -> str:
    """The router's ``/statusz``: the fleet at a glance for an operator
    mid-incident — per-backend health/backlog/burn table, the steal
    log, and where the flight dumps went."""
    s = router.snapshot()
    r = s["router"]
    lines = [f"heat-tpu fleet router — statusz "
             f"(uptime {s['uptime_s']:.0f}s, policy {s['policy']}, "
             f"steal threshold "
             f"{s['steal_threshold_s'] or 'off'}"
             f"{'s' if s['steal_threshold_s'] else ''}, "
             f"{'DRAINING' if r['draining'] else 'admitting'})", ""]
    lines.append(
        f"requests: {r['requests']} routed total, {r['pending']} "
        f"pending, {r['edge_rejected']} rejected at the edge, "
        f"{r['retries']} batch retr{'y' if r['retries'] == 1 else 'ies'}, "
        f"{r['duplicates']} duplicate record(s) dropped")
    cache = s.get("cache")
    if cache is None:
        lines.append("solve cache: not shared with this router "
                     "(--cache-dir unset)")
    else:
        lines.append(
            f"solve cache (read-only over {cache['dir']}): "
            f"{r['cache_edge_hits']} edge hit(s), "
            f"{r['cache_prefix_hints']} prefix placement hint(s), "
            f"{cache['entries']} entr(ies) / "
            f"{cache['bytes'] / 2**20:.2f} MiB on disk")
    rb = r["retry_budget"]
    lines.append(
        f"retry budget: {rb['tokens']:.1f}/{rb['cap']:g} tokens "
        f"(+{rb['ratio']:g}/success; {rb['taken']} taken, "
        f"{rb['denied']} denied) — {r['deadline_shed']} deadline-shed, "
        f"{r['brownout_shed']} brownout-shed"
        f"{' [BROWNOUT L' + str(s['brownout_level']) + ']' if s.get('brownout_level') else ''}, "
        f"{r['stream_cuts']} stream cut(s) re-driven")
    h = r["hedges"]
    lines.append(
        f"hedging ({'factor ' + format(s['hedge_factor'], 'g') if s.get('hedge_factor') else 'off'}): "
        f"{h['fired']} fired, {h['won']} won, {h['lost']} lost, "
        f"{h['cancelled']} loser(s) cancelled")
    breakers = r.get("breakers") or {}
    open_brs = {n: b for n, b in breakers.items()
                if b["state"] != "closed"}
    if open_brs:
        lines.append(f"breakers ({len(open_brs)} not closed):")
        for n, bs in sorted(open_brs.items()):
            lines.append(
                f"  {n}: {bs['state'].upper()} — "
                f"{bs['consecutive_errors']} consecutive error(s), "
                f"burn {bs['burn_ticks']} tick(s), cooldown "
                f"{bs['cooldown_s']:g}s, last {bs['last_reason'] or '-'} "
                f"({bs['transitions']} transition(s))")
    else:
        lines.append(f"breakers: all {len(breakers)} closed")
    lines.append(f"backends ({len(s['backends'])}; "
                 f"{r['lost']} lost so far):")
    for name, b in sorted(s["backends"].items()):
        state = ("FAULT-DOWN" if b["fault_down"] else
                 "LOST" if b["lost"] else
                 "draining" if b["draining"] else
                 "up" if b["healthy"] else "DOWN")
        lines.append(
            f"  {name} @ {b['address']}: {state}"
            f"{' DEMOTED(burn)' if b['demoted'] else ''} — backlog "
            f"{b['backlog_s']:.3f}s ({b['backlog_steps']} steps, "
            f"{b['pending_requests']} router-pending), routed "
            f"{b['routed']}, delivered {b['delivered']}, retried "
            f"{b['retried']}, probes {b['probe_passes']}/"
            f"{b['probe_fails']} fail, ckpt gen "
            f"{b['engine_ckpt_generation']}, resumed "
            f"{b['serve_resumed']}, stolen {b['stolen_from']}x from / "
            f"{b['stolen_to']}x to"
            f"{', mega' if b['mega_capable'] else ''}")
    steals = r["steals"]
    lines.append("")
    lines.append(f"steals ({len(steals)}):")
    if not steals:
        lines.append("  (none)")
    for ev in steals[-10:]:
        lines.append(
            f"  {ev['victim']} -> {ev['thief']} [{ev['reason']}]: gen "
            f"{ev['generation']}, {ev['recovered']} resumed + "
            f"{ev['redriven']} re-driven, drain {ev['drain_s']}s + "
            f"resume {ev['resume_s']}s = {ev['wall_s']}s")
    if router.tracer.dumps:
        lines.append("")
        lines.append(f"flight-recorder dumps ({router.tracer.dumps}):")
        for p in router.tracer.dump_paths:
            lines.append(f"  {p}")
    return "\n".join(lines) + "\n"


class _FleetHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    @property
    def rt(self) -> Router:
        return self.server.router

    def log_message(self, fmt, *args):  # noqa: D102
        if not self.rt.fcfg.quiet:
            master_print(f"fleet: {self.address_string()} {fmt % args}")

    @property
    def trace_id(self) -> str:
        tid = getattr(self, "_trace_id", None)
        if tid is None:
            inbound = (self.headers.get("X-Trace-Id") or "").strip()
            tid = (inbound if _TRACE_ID_RE.match(inbound)
                   else self.rt.tracer.mint_trace_id())
            self._trace_id = tid
        return tid

    def _send_headers(self, code: int, body_len: int, ctype: str,
                      headers=()) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(body_len))
        has_tid = False
        for k, v in headers:
            self.send_header(k, str(v))
            has_tid = has_tid or k == "X-Trace-Id"
        if not has_tid:
            self.send_header("X-Trace-Id", self.trace_id)
        self.end_headers()

    def _json(self, code: int, obj, headers=()) -> None:
        body = (json.dumps(obj, sort_keys=True) + "\n").encode()
        self._send_headers(code, len(body), "application/json", headers)
        try:
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass

    def _text(self, code: int, text: str, ctype: str) -> None:
        body = text.encode()
        self._send_headers(code, len(body), ctype)
        try:
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass

    # --- routes -----------------------------------------------------------
    def do_GET(self):  # noqa: N802
        parts = urlsplit(self.path)
        path = parts.path
        rt = self.rt
        if path == "/healthz":
            ups = [b for b in rt.registry.snapshot() if b.healthy]
            if rt.draining:
                self._json(503, {"status": "draining",
                                 "backends_up": len(ups)},
                           headers=[("Retry-After",
                                     int(rt.fcfg.retry_after_s))])
            elif ups:
                self._json(200, {"status": "ok",
                                 "backends_up": len(ups)})
            else:
                self._json(503, {"status": "no-backends"},
                           headers=[("Retry-After",
                                     int(rt.fcfg.retry_after_s))])
        elif path == "/metrics":
            self._text(200, render_fleet_metrics(rt),
                       "text/plain; version=0.0.4")
        elif path == "/statusz":
            self._text(200, render_fleet_statusz(rt),
                       "text/plain; charset=utf-8")
        elif path == "/v1/status":
            payload = rt.snapshot()
            payload["address"] = rt.address
            self._json(200, payload)
        elif path == "/v1/usage":
            self._json(200, rt.fleet_usage())
        elif path == "/tracez":
            self._text(200, json.dumps(rt.tracer.to_chrome()),
                       "application/json")
        elif path == "/drainz":
            self._drainz()
        elif path.startswith("/v1/requests/"):
            self._request_status(path[len("/v1/requests/"):])
        else:
            self._json(404, {"error": f"no route for GET {path}"})

    def do_POST(self):  # noqa: N802
        parts = urlsplit(self.path)
        if parts.path == "/drainz":
            self._drainz()
        elif parts.path == "/v1/solve":
            self._solve(parts)
        else:
            self._json(404, {"error": f"no route for POST {parts.path}"})

    def _drainz(self) -> None:
        self.rt.request_drain()
        self._json(200, {"draining": True,
                         "pending": self.rt.pending_count()})

    def _request_status(self, rid: str) -> None:
        """Record lookup: answered locally once delivered, proxied to
        the owning backend while in flight."""
        rt = self.rt
        with rt._lock:
            st = rt._requests.get(rid)
            rec = st["rec"] if st else None
            owner = st["backend"] if st else None
        if rec is not None:
            self._json(200, rec)
            return
        if owner is None:
            self._json(404, {"error": f"unknown request id {rid!r}"})
            return
        b = rt.registry.get(owner)
        if b is None:
            self._json(404, {"error": f"backend {owner!r} vanished"})
            return
        try:
            code, data = rt._http(b, "GET", f"/v1/requests/{rid}")
            self._json(code, json.loads(data))
        except (OSError, ValueError, http.client.HTTPException) as e:
            self._json(502, {"error": f"backend {owner} unreachable: "
                                      f"{type(e).__name__}: {e}"})

    def _read_body(self) -> Optional[bytes]:
        n = self.headers.get("Content-Length")
        if n is None:
            self._json(411, {"error": "Content-Length required"})
            return None
        n = int(n)
        if n > MAX_BODY_BYTES:
            self._json(413, {"error": f"body exceeds {MAX_BODY_BYTES} "
                                      f"bytes"})
            return None
        return self.rfile.read(n)

    def _solve(self, parts) -> None:
        rt = self.rt
        tr = rt.tracer
        if not tr.enabled:
            return self._solve_inner(parts)
        t0 = tr.now()
        try:
            self._solve_inner(parts)
        finally:
            tr.complete("POST /v1/solve", tr.thread_track("fleet router"),
                        t0, cat="http")

    def _solve_inner(self, parts) -> None:
        rt = self.rt
        if rt.draining:
            self._json(503, {"error": "draining: fleet admission "
                                      "stopped (/drainz)"},
                       headers=[("Retry-After",
                                 int(rt.fcfg.retry_after_s))])
            return
        body = self._read_body()
        if body is None:
            return
        wait = parse_qs(parts.query).get("wait", ["1"])[0] not in ("0",
                                                                   "false")
        results: Optional[queue_lib.Queue] = (queue_lib.Queue() if wait
                                              else None)
        immediate, states = rt.admit_lines(body, results, self.trace_id)
        if not immediate and not states:
            self._json(400, {"error": "empty body: expected one JSON "
                                      "request object per line"})
            return
        if not wait:
            rt.dispatch(states)
            self._json(202, {"accepted": [st["id"] for st in states],
                             "records": immediate})
            return
        self._stream(immediate, states, results)

    def _stream(self, immediate, states, results) -> None:
        """Chunked NDJSON back to the client: rejection records first,
        then each request's terminal record as its backend (original,
        retried, or stolen-to) produces it."""
        rt = self.rt
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        self.send_header("X-Trace-Id", self.trace_id)
        self.end_headers()

        def chunk(obj) -> bool:
            data = (json.dumps(obj, sort_keys=True, default=str)
                    + "\n").encode()
            try:
                self.wfile.write(b"%x\r\n" % len(data) + data + b"\r\n")
                return True
            except (BrokenPipeError, ConnectionResetError):
                return False

        alive = True
        for rec in immediate:
            alive = alive and chunk(rec)
        rt.dispatch(states)
        pending = {st["id"] for st in states}
        deadline = time.monotonic() + rt.fcfg.stream_timeout_s
        while pending and alive:
            try:
                rec = results.get(timeout=max(0.05,
                                              deadline - time.monotonic()))
            except queue_lib.Empty:
                chunk({"error": f"stream timeout after "
                                f"{rt.fcfg.stream_timeout_s:g}s; poll "
                                f"GET /v1/requests/<id> for the rest",
                       "pending": sorted(pending)})
                break
            rid = rec.get("id")
            if rid in pending:
                pending.discard(rid)
                alive = alive and chunk(rec)
        try:
            self.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError):
            pass
