"""Fleet resilience primitives: per-backend circuit breakers and the
SRE-style retry budget (heat_tpu/fleet — ISSUE 20).

The PR-17 router's failure handling was first-generation: retry-on-
alternate covered only never-admitted batches, and a flapping backend
triggered recovery/steal thrash on every down edge. This module adds the
two stateful primitives the resilience layer hangs off:

:class:`Breaker` — one closed/open/half-open state machine per backend,
fed by probe transitions, relay/connect errors, and sustained SLO burn.
An OPEN breaker excludes its backend from placement and from steal
thief/victim selection; after a cooldown it becomes HALF-OPEN, and
re-admission is gated on the sine-canary probe (serve/probe.py) passing
*through the router path* — a backend that answers /healthz but returns
wrong bytes stays out. Each failed canary doubles the cooldown (capped),
so a persistently sick backend is probed ever more rarely.

:class:`RetryBudget` — retries capped as a fraction of successes (the
SRE book's overload chapter): the bucket starts full, every delivered
success refills ``ratio`` tokens (capped), every retry hop spends one.
When the bucket is dry the router stops amplifying overload and sheds
with a structured record instead of re-dispatching.

Both are self-locked at fleet rank (``fleet:breaker`` / ``fleet:budget``)
— same rank as the router and registry locks, so by the lock discipline
(two same-rank locks never nest) every call into them is made while
holding NO other fleet lock. Pure state machines: no I/O, no threads;
the router owns the clock and the canary."""

from __future__ import annotations

import random
from typing import List, Optional, Tuple

from ..runtime import debug

# /metrics gauge encoding (heat_tpu_fleet_breaker_state{backend=...})
CLOSED, HALF_OPEN, OPEN = "closed", "half-open", "open"
STATE_CODES = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class Breaker:
    """Circuit breaker for one backend.

    closed --(trip: consecutive errors / loss / sustained burn)--> open
    open --(cooldown elapsed)--> half-open (single canary in flight)
    half-open --(router-path canary passes)--> closed
    half-open --(canary fails)--> open, cooldown doubled (capped)
    """

    TRIP_THRESHOLD = 3      # consecutive relay/connect/probe errors
    BURN_TRIP_TICKS = 8     # consecutive burn-demoted health ticks
    COOLDOWN_MAX_S = 120.0

    def __init__(self, backend: str, trip_threshold: int = TRIP_THRESHOLD,
                 cooldown_s: float = 5.0,
                 burn_trip_ticks: int = BURN_TRIP_TICKS):
        self.backend = backend
        self.trip_threshold = max(1, int(trip_threshold))
        self.base_cooldown_s = float(cooldown_s)
        self.burn_trip_ticks = max(1, int(burn_trip_ticks))
        self._lock = debug.make_lock(f"fleet:breaker-{backend}")
        self.state = CLOSED
        self.consecutive_errors = 0
        self.burn_ticks = 0
        self.cooldown_s = float(cooldown_s)
        self.opened_t = 0.0          # monotonic stamp of the last open
        self.last_transition_t = 0.0  # any state change (steal thrash guard)
        self.last_reason = ""
        self.transitions = 0
        self.canary_inflight = False
        debug.instrument_races(self, label=f"Breaker[{backend}]")

    # --- feeds (router calls these holding no other fleet lock) ----------
    def note_success(self) -> None:
        """A relay batch fully delivered / a probe passed while closed."""
        with self._lock:
            if self.state == CLOSED:
                self.consecutive_errors = 0

    def note_error(self, reason: str, now: float) -> Optional[str]:
        """A connect error, non-200, mid-stream break, or failed probe.
        Returns the new state name iff this error tripped the breaker."""
        with self._lock:
            if self.state != CLOSED:
                return None
            self.consecutive_errors += 1
            if self.consecutive_errors < self.trip_threshold:
                return None
            return self._open(reason, now)

    def trip(self, reason: str, now: float) -> Optional[str]:
        """Hard trip (backend lost / recovery started): open immediately
        regardless of the error count. Returns new state iff changed."""
        with self._lock:
            if self.state == OPEN:
                return None
            return self._open(reason, now)

    def note_burn(self, demoted: bool, now: float) -> Optional[str]:
        """One health tick's burn verdict: ``burn_trip_ticks`` consecutive
        demoted ticks trip the breaker (sustained SLO burn = sick backend,
        not a blip). Returns new state iff this tick tripped it."""
        with self._lock:
            if not demoted:
                self.burn_ticks = 0
                return None
            self.burn_ticks += 1
            if self.state != CLOSED or self.burn_ticks < self.burn_trip_ticks:
                return None
            return self._open("slo-burn", now)

    def _open(self, reason: str, now: float) -> str:
        # caller holds self._lock
        self.state = OPEN
        self.opened_t = now
        self.last_transition_t = now
        self.last_reason = reason
        self.transitions += 1
        self.canary_inflight = False
        return OPEN

    # --- half-open admission ---------------------------------------------
    def try_half_open(self, now: float) -> bool:
        """If open and the cooldown has elapsed, move to half-open and
        claim the single canary slot (True = caller must run the canary).
        At most one canary is in flight per breaker."""
        with self._lock:
            if self.state == OPEN and now - self.opened_t >= self.cooldown_s:
                self.state = HALF_OPEN
                self.last_transition_t = now
                self.transitions += 1
                self.canary_inflight = True
                return True
            return False

    def canary_result(self, ok: bool, now: float) -> str:
        """Fold the router-path canary verdict in. Pass -> closed (error
        and burn counters reset, cooldown restored to base). Fail ->
        back to open with the cooldown doubled (capped)."""
        with self._lock:
            self.canary_inflight = False
            if ok:
                self.state = CLOSED
                self.consecutive_errors = 0
                self.burn_ticks = 0
                self.cooldown_s = self.base_cooldown_s
                self.last_reason = "canary-pass"
            else:
                self.state = OPEN
                self.opened_t = now
                self.cooldown_s = min(self.COOLDOWN_MAX_S,
                                      self.cooldown_s * 2)
                self.last_reason = "canary-fail"
            self.last_transition_t = now
            self.transitions += 1
            return self.state

    # --- reads -------------------------------------------------------------
    def allows(self) -> bool:
        """May the router place NEW work here? Only when closed —
        half-open admits exactly the canary, nothing else."""
        with self._lock:
            return self.state == CLOSED

    def snapshot(self) -> dict:
        with self._lock:
            return {"backend": self.backend, "state": self.state,
                    "code": STATE_CODES[self.state],
                    "consecutive_errors": self.consecutive_errors,
                    "burn_ticks": self.burn_ticks,
                    "cooldown_s": self.cooldown_s,
                    "last_reason": self.last_reason,
                    "last_transition_t": self.last_transition_t,
                    "transitions": self.transitions}


class RetryBudget:
    """Fleet-wide retry budget: retries as a bounded fraction of
    successes. ``take()`` spends one token per retry HOP (not per row —
    a batch re-dispatch is one decision); ``credit()`` refills ``ratio``
    tokens per delivered success, capped at ``cap``. Dry bucket -> the
    router sheds instead of re-dispatching (never amplifies overload)."""

    def __init__(self, cap: float = 20.0, ratio: float = 0.2):
        self.cap = float(cap)
        self.ratio = float(ratio)
        self._lock = debug.make_lock("fleet:budget")
        self.tokens = float(cap)
        self.taken = 0
        self.denied = 0
        debug.instrument_races(self, label="RetryBudget")

    def take(self) -> bool:
        with self._lock:
            if self.tokens >= 1.0:
                self.tokens -= 1.0
                self.taken += 1
                return True
            self.denied += 1
            return False

    def credit(self, n: int = 1) -> None:
        with self._lock:
            self.tokens = min(self.cap, self.tokens + self.ratio * n)

    def snapshot(self) -> dict:
        with self._lock:
            return {"tokens": self.tokens, "cap": self.cap,
                    "ratio": self.ratio, "taken": self.taken,
                    "denied": self.denied}


def backoff_s(hop: int, base_s: float = 0.05, cap_s: float = 2.0,
              rng: Optional[random.Random] = None) -> float:
    """Jittered exponential backoff before re-placement: full jitter on
    ``min(cap, base * 2**hop)`` (AWS-style — decorrelates retry herds
    without a coordination channel)."""
    r = rng.random() if rng is not None else random.random()
    return min(cap_s, base_s * (2.0 ** max(0, hop))) * (0.5 + 0.5 * r)


def breaker_rows(breakers: List[Breaker]) -> List[Tuple[str, dict]]:
    """(name, snapshot) rows sorted by backend name — the one shape
    /metrics, /statusz, and the fleet summary all render from."""
    return sorted(((b.backend, b.snapshot()) for b in breakers),
                  key=lambda kv: kv[0])
