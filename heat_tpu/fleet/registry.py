"""Backend registry for the fleet router (heat_tpu/fleet).

One :class:`Backend` per engine gateway the router fronts: its address,
health-probe state, the last machine-readable ``GET /v1/status`` payload
(the placement policy's food), and the router-local accounting the
status payload cannot know yet (work routed there whose terminal record
has not come back). The :class:`BackendRegistry` owns them all under one
fleet-rank lock (``runtime/debug.LOCK_RANKS``): every mutation goes
through a registry method, so the race sanitizer sees one guarded
writer surface, and the placement policy reads consistent snapshots.

Backends come from the ``--backends host:port,...`` flag or a backends
file (one ``[name=]host:port`` per line, ``#`` comments) re-read when
its mtime changes — new entries join the fleet live; removing a line
does NOT evict a live backend (in-flight work may still be streaming
back from it), it only stops new placements once the probe marks it
down.
"""

from __future__ import annotations

from pathlib import Path
from typing import Dict, List, Optional, Tuple

from ..runtime import debug


def parse_backends(spec: str) -> List[Tuple[str, str]]:
    """``[name=]host:port,...`` -> ``[(name, "host:port"), ...]``.
    Unnamed entries get positional names ``b0, b1, ...`` (stable across
    restarts as long as the flag order is); duplicate names or
    addresses are a config error, not a silent merge."""
    out: List[Tuple[str, str]] = []
    for i, raw in enumerate(s.strip() for s in spec.split(",")):
        if not raw:
            continue
        name, eq, addr = raw.partition("=")
        if not eq:
            name, addr = f"b{i}", raw
        host, colon, port = addr.rpartition(":")
        if not colon or not host or not port.isdigit():
            raise ValueError(f"bad backend {raw!r}: expected "
                             f"[name=]host:port")
        out.append((name.strip(), addr.strip()))
    names = [n for n, _ in out]
    addrs = [a for _, a in out]
    for kind, vals in (("name", names), ("address", addrs)):
        dup = {v for v in vals if vals.count(v) > 1}
        if dup:
            raise ValueError(f"duplicate backend {kind}(s) in "
                             f"{spec!r}: {sorted(dup)}")
    return out


def load_backends_file(path) -> List[Tuple[str, str]]:
    """One ``[name=]host:port`` per line; ``#`` comments and blank lines
    ignored. Same grammar as the flag, one entry per line."""
    lines = []
    for line in Path(path).read_text().splitlines():
        line = line.split("#", 1)[0].strip()
        if line:
            lines.append(line)
    return parse_backends(",".join(lines))


class Backend:
    """One engine gateway as the router sees it. All mutable fields are
    guarded by the owning registry's lock (mutations go through registry
    methods); ``name``/``address`` are immutable identity."""

    def __init__(self, name: str, address: str):
        self.name = name
        self.address = address              # "host:port"
        # --- health (probe thread) ---------------------------------------
        self.healthy = True                 # optimistic until a probe says
                                            # otherwise, so a cold fleet
                                            # routes before the first tick
        self.draining = False               # backend answered 503 draining
        self.lost = False                   # transitioned down (recovery
                                            # ran or is running)
        self.fault_down = False             # backend-down chaos: the
                                            # router refuses to connect
        self.probe_passes = 0
        self.probe_fails = 0
        self.consecutive_failures = 0
        # --- placement food ----------------------------------------------
        self.status: Optional[dict] = None  # last GET /v1/status payload
        self.status_t = 0.0                 # monotonic stamp of it
        self.pending_steps = 0              # routed, no terminal record yet
        self.pending_requests = 0
        # --- counters ----------------------------------------------------
        self.routed = 0
        self.delivered = 0
        self.retried = 0
        self.stolen_from = 0
        self.stolen_to = 0
        debug.instrument_races(self, label="Backend")

    def __repr__(self) -> str:  # debugging/statusz ergonomics
        return (f"Backend({self.name}@{self.address} "
                f"{'up' if self.healthy else 'DOWN'})")


class BackendRegistry:
    """The fleet's member list + per-backend state, under one lock."""

    def __init__(self, backends: List[Tuple[str, str]] = (),
                 backends_file=None):
        self._lock = debug.make_lock("fleet:registry")
        self._backends: Dict[str, Backend] = {}
        self._file = Path(backends_file) if backends_file else None
        self._file_mtime: Optional[float] = None
        for name, addr in backends:
            self._backends[name] = Backend(name, addr)
        debug.instrument_races(self, label="BackendRegistry")
        if self._file is not None:
            self.refresh_file()

    # --- membership -------------------------------------------------------
    def snapshot(self) -> List[Backend]:
        """The live member list (registration order). Backend field
        reads after release are racy-by-design advisory reads — the
        placement policy tolerates a stale backlog number; every
        *mutation* goes back through a registry method."""
        with self._lock:
            return list(self._backends.values())

    def get(self, name: str) -> Optional[Backend]:
        with self._lock:
            return self._backends.get(name)

    def refresh_file(self) -> List[str]:
        """Re-read the backends file when its mtime moved; returns the
        names of newly joined backends. Lines that disappeared do not
        evict live members (see module doc)."""
        if self._file is None:
            return []
        try:
            mtime = self._file.stat().st_mtime
        except OSError:
            return []
        with self._lock:
            if self._file_mtime == mtime:
                return []
            self._file_mtime = mtime
        joined = []
        for name, addr in load_backends_file(self._file):
            with self._lock:
                if name not in self._backends:
                    self._backends[name] = Backend(name, addr)
                    joined.append(name)
        return joined

    # --- probe results ----------------------------------------------------
    def note_probe(self, name: str, ok: bool, draining: bool = False,
                   status: Optional[dict] = None,
                   now: float = 0.0) -> Tuple[bool, bool]:
        """Fold one health-probe round in; returns ``(was_healthy,
        is_healthy)`` so the caller sees the down transition (the
        flight-dump + recovery trigger) exactly once."""
        with self._lock:
            b = self._backends.get(name)
            if b is None:
                return (False, False)
            was = b.healthy and not b.lost
            if ok:
                b.probe_passes += 1
                b.consecutive_failures = 0
            else:
                b.probe_fails += 1
                b.consecutive_failures += 1
            b.draining = draining
            b.healthy = ok and not draining and not b.fault_down
            if status is not None:
                b.status = status
                b.status_t = now
            return (was, b.healthy)

    def set_fault_down(self, name: str,
                       down: bool = True) -> Optional[Backend]:
        """backend-down / backend-flap chaos: drop the TCP target —
        every future connect to it fails as if the host vanished
        (``down=False`` restores it: the flap's up half-period).
        ``healthy`` is left for the next probe round to flip: the
        router must DISCOVER the loss the way it would a real one
        (probe fails -> was/is transition -> flight dump + recovery),
        not be told by the drill. Placement never routes here meanwhile
        — ``eligible`` checks ``fault_down`` itself."""
        with self._lock:
            b = self._backends.get(name)
            if b is not None:
                b.fault_down = down
            return b

    def mark_lost(self, name: str) -> None:
        with self._lock:
            b = self._backends.get(name)
            if b is not None:
                b.lost = True
                b.healthy = False

    def mark_found(self, name: str) -> None:
        """Re-admit a lost backend (half-open canary passed through the
        router path): clear ``lost`` so placement and stealing see it
        again. The next probe round re-establishes ``healthy``; we set
        it optimistically here so the canary's verdict takes effect
        before the next tick."""
        with self._lock:
            b = self._backends.get(name)
            if b is not None:
                b.lost = False
                b.healthy = not b.fault_down and not b.draining
                b.consecutive_failures = 0

    # --- router-local accounting -----------------------------------------
    def note_routed(self, name: str, requests: int, steps: int) -> None:
        with self._lock:
            b = self._backends.get(name)
            if b is not None:
                b.routed += requests
                b.pending_requests += requests
                b.pending_steps += steps

    def note_done(self, name: str, steps: int) -> None:
        with self._lock:
            b = self._backends.get(name)
            if b is not None:
                b.delivered += 1
                b.pending_requests = max(0, b.pending_requests - 1)
                b.pending_steps = max(0, b.pending_steps - steps)

    def note_unrouted(self, name: str, requests: int, steps: int) -> None:
        """Work taken away from a backend (retry, steal, re-drive):
        reverse the pending accounting without counting a delivery."""
        with self._lock:
            b = self._backends.get(name)
            if b is not None:
                b.pending_requests = max(0, b.pending_requests - requests)
                b.pending_steps = max(0, b.pending_steps - steps)

    def note_retry(self, name: str) -> None:
        with self._lock:
            b = self._backends.get(name)
            if b is not None:
                b.retried += 1

    def note_steal(self, victim: str, thief: str) -> None:
        with self._lock:
            v = self._backends.get(victim)
            t = self._backends.get(thief)
            if v is not None:
                v.stolen_from += 1
            if t is not None:
                t.stolen_to += 1
