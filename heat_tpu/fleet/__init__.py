"""Pod-scale fleet serving: a router in front of many engine gateways.

``heat-tpu fleet`` runs a stdlib-HTTP router (``router.py``) over a
:class:`~.registry.BackendRegistry` of independent ``heat-tpu serve``
processes. Placement is a pure policy (``placement.py``) over each
backend's ``GET /v1/status`` control payload — least-loaded by predicted
backlog seconds, burn-aware demotion, mega-capability routing — and
rebalancing is **work stealing as checkpoint handoff**: drain a loaded
backend to its engine manifest, resume it on an idle one, bit-identical
bytes across the migration.

Import the pieces from their modules (``fleet.router``,
``fleet.registry``, ``fleet.placement``); this package init stays
import-light so ``heat_tpu.fleet.placement`` unit tests never pull the
HTTP stack.
"""
