"""XLA stencil ops: the FTCS update as pure functions.

This is the TPU-native analog of the reference's compiler-generated kernels
(the ``!$cuf kernel do(2)`` loops, ``fortran/cuda_cuf/heat.F90:31-38`` and
``fortran/mpi+cuda/heat.F90:209-215``): we express the 5-point (2D) / 7-point
(3D) update as shifted slices and let XLA fuse it into a single
bandwidth-bound elementwise kernel. The hand-written analog (the reference's
``attributes(global)`` / HIP C++ kernels) lives in ``pallas_stencil.py``.

Math (fortran/serial/heat.f90:64-68):
    T[j,k] = T_old[j,k] + r * (T_old[j+1,k] + T_old[j,k+1]
                               + T_old[j-1,k] + T_old[j,k-1] - 4*T_old[j,k])

Three boundary semantics are kept:

- ``edges``: only interior cells 2..n-1 update; the outermost cell ring is
  frozen (serial + single-GPU variants, fortran/serial/heat.f90:64).
- ``ghost``: ALL owned cells update, reading a ghost ring fixed at
  ``bc_value`` at the global domain edge (MPI variants,
  fortran/mpi+cuda/heat.F90:209-215 with IC at :243-251).
- ``periodic``: ALL cells update with wrap-around neighbors — the topology
  the reference's cartesian communicator is built to carry but never
  enables (``pbc = .false.`` fed to ``mpi_cart_create`` periods,
  fortran/mpi+cuda/heat.F90:76,97). With no boundary there is no boundary
  flux: total heat is conserved exactly (the invariant behind the
  reference's commented-out global-sum reduction, :266-273).

bfloat16 runs compute in float32 and round the result back (the "bf16
stencil + fp32 accumulate" benchmark mode; the reference's precedent is the
``SINGLE_PRECISION`` switch in fortran/hip/heat_kernel.cpp:5-9).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp


def accum_dtype_for(dtype) -> jnp.dtype:
    """Accumulation dtype: f32 for bf16, else the storage dtype itself."""
    dtype = jnp.dtype(dtype)
    if dtype == jnp.bfloat16:
        return jnp.dtype(jnp.float32)
    return dtype


def laplacian_interior(T: jax.Array) -> jax.Array:
    """Discrete 2*ndim+1-point Laplacian numerator on the interior.

    Input has shape (m0, ..., m_{d-1}); output (m0-2, ..., m_{d-1}-2) in the
    accumulation dtype: sum(neighbors) - 2*ndim*center.

    Summation order is the reference expression's left-to-right order —
    all +1 neighbors in axis order, then all -1 neighbors, then the center
    term (``T(j+1,k) + T(j,k+1) + T(j-1,k) + T(j,k-1) - 4*T(j,k)``,
    fortran/serial/heat.f90:64-68) — so f64 runs bit-match the reference on
    ANY field, not just the dyadic-valued shipped ICs where association
    can't matter.
    """
    nd = T.ndim
    acc_dt = accum_dtype_for(T.dtype)
    Tc = T.astype(acc_dt)
    ctr = tuple(slice(1, -1) for _ in range(nd))
    shifted = []
    for off in (slice(2, None), slice(0, -2)):
        for d in range(nd):
            sl = list(ctr)
            sl[d] = off
            shifted.append(Tc[tuple(sl)])
    acc = shifted[0]
    for s in shifted[1:]:
        acc = acc + s
    return acc + (-2.0 * nd) * Tc[ctr]


def ftcs_step_edges(T: jax.Array, r) -> jax.Array:
    """One FTCS step, frozen-boundary ("edges") semantics.

    Interior cells get T + r*lap; the outermost ring is returned unchanged
    (the serial loop bounds 2..n-1, fortran/serial/heat.f90:64-68).

    Two analytic properties of this update back the numerics observatory
    (ISSUE 15) and must survive any refactor here:

    - **discrete maximum principle** — with ``r <= 1/(2*ndim)`` (the CFL
      bound ``config.HeatConfig`` enforces via sigma) the update is a
      convex combination ``(1-2*ndim*r)*T + r*sum(neighbors)``, so no
      cell can ever escape ``[min(T0, bc), max(T0, bc)]``. The per-lane
      min/max witnesses the chunk programs fuse into the boundary vector
      (serve/engine rows 2-5) are checked against exactly this envelope.
    - **sine eigenmode decay** — the ``sine`` IC preset (grid.py) is an
      eigenvector of this operator: each step multiplies it by
      ``1 - 4*ndim*r*sin^2(pi/(2*(n-1)))`` (``grid.sine_decay_factor``),
      the closed form the serve canary prober verifies end to end
      (serve/probe.py).
    """
    acc_dt = accum_dtype_for(T.dtype)
    ctr = tuple(slice(1, -1) for _ in range(T.ndim))
    interior = T[ctr].astype(acc_dt) + jnp.asarray(r, acc_dt) * laplacian_interior(T)
    return T.at[ctr].set(interior.astype(T.dtype))


def pad_with_ghosts(T: jax.Array, bc_value) -> jax.Array:
    """Surround the owned field with a one-cell ghost ring at ``bc_value``
    (the ng=1 ghost allocation of fortran/mpi+cuda/heat.F90:41,107-111 with
    global-edge ghosts pinned to 1.0 at :243-251)."""
    return jnp.pad(T, 1, mode="constant", constant_values=jnp.asarray(bc_value, T.dtype))


def ftcs_step_ghost(T: jax.Array, r, bc_value) -> jax.Array:
    """One FTCS step, Dirichlet-by-ghost ("ghost") semantics, single device.

    Every owned cell updates against a conceptual ghost ring held at
    ``bc_value`` — the global, undecomposed equivalent of one MPI-variant
    timestep (fortran/mpi+cuda/heat.F90:206-219). Used as the oracle for the
    sharded backend.
    """
    padded = pad_with_ghosts(T, bc_value)
    acc_dt = accum_dtype_for(T.dtype)
    out = T.astype(acc_dt) + jnp.asarray(r, acc_dt) * laplacian_interior(padded)
    return out.astype(T.dtype)


def laplacian_periodic(T: jax.Array) -> jax.Array:
    """Discrete Laplacian numerator with wrap-around neighbors, full array.

    Same left-to-right summation order as ``laplacian_interior`` (+1
    neighbors in axis order, then -1 neighbors, then the center term) so
    periodic f64 runs bit-match the roll-free oracle transcription.
    """
    nd = T.ndim
    acc_dt = accum_dtype_for(T.dtype)
    Tc = T.astype(acc_dt)
    shifted = []
    for shift in (-1, 1):  # roll -1 brings index j+1 to j (the +1 neighbor)
        for d in range(nd):
            shifted.append(jnp.roll(Tc, shift, axis=d))
    acc = shifted[0]
    for s in shifted[1:]:
        acc = acc + s
    return acc + (-2.0 * nd) * Tc


def ftcs_step_periodic(T: jax.Array, r) -> jax.Array:
    """One FTCS step on the torus: every cell updates, neighbors wrap."""
    acc_dt = accum_dtype_for(T.dtype)
    out = T.astype(acc_dt) + jnp.asarray(r, acc_dt) * laplacian_periodic(T)
    return out.astype(T.dtype)


def run_steps(T: jax.Array, nsteps: int, step_fn: Callable[[jax.Array], jax.Array]) -> jax.Array:
    """Apply ``step_fn`` ``nsteps`` times under ``lax.fori_loop``.

    The loop-carried double buffer replaces the reference's explicit
    ``T_old = T`` device snapshot each step (fortran/cuda_kernel/heat.F90:32);
    with buffer donation XLA ping-pongs two buffers with no copy at all.
    """
    if nsteps == 0:
        return T
    return jax.lax.fori_loop(0, nsteps, lambda i, t: step_fn(t), T)
