"""Hand-written Pallas TPU stencil kernels.

The TPU equivalent of the reference's explicit device kernels: CUDA Fortran
``heat_equation`` (fortran/cuda_kernel/heat.F90:39-54), HIP C++ ``heat_eqn``
(fortran/hip/heat_kernel.cpp:31-45), and the Jinja2-JIT CUDA C kernel
(python/cuda/cuda.py:58-86). Where those tile the grid into 32x8 / 128x4
thread blocks, this kernel tiles rows into VMEM-resident blocks aligned to
the 8x128 VPU lanes and streams them HBM->VMEM->HBM through Pallas's
pipelined grid.

Design notes:
- Grid is 1-D over row tiles; each program sees its own tile plus the
  *clamped* previous/next tiles (three input BlockSpecs on the same array),
  which supplies the one-row halo that the reference fetches via its ghost
  ring. Column neighbors are in-tile shifts (full rows live in the block).
- The runtime constant ``r`` is baked into the kernel as a closure constant
  — the Pallas analog of the reference's Jinja2 constant-baking
  (python/cuda/cuda.py:85), with jit retrace standing in for re-render.
- bf16 runs upcast to f32 for the accumulate and round once at the store
  ("bf16 stencil + fp32 accumulate" mode).
- Boundary cells are masked back to their old value ("edges" BC) exactly
  like the in-kernel interior guard ``i/=1 .and. i/=ngrid`` of
  fortran/cuda_kernel/heat.F90:49.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .stencil import accum_dtype_for, ftcs_step_edges, ftcs_step_ghost

# VMEM working-set budget for tile selection (conservative: leaves room for
# Pallas's double-buffered pipeline and the output tile).
_VMEM_BUDGET_BYTES = 12 * 1024 * 1024


def _sublane(dtype) -> int:
    return 16 if jnp.dtype(dtype) == jnp.bfloat16 else 8


def _pick_row_tile(m: int, n: int, itemsize: int, sublane: int) -> Optional[int]:
    """Largest divisor of m, multiple of the sublane count, fitting 8 tiles
    of shape (tile, n) in the VMEM budget. None if no valid tile exists."""
    cap = max(sublane, _VMEM_BUDGET_BYTES // (8 * n * itemsize))
    best = None
    t = sublane
    while t <= min(m, cap):
        if m % t == 0:
            best = t
        t += sublane
    return best


def _supported(shape, dtype) -> Optional[int]:
    """Return the row tile if the Pallas path supports this problem."""
    if jnp.dtype(dtype) == jnp.float64:
        return None  # no f64 on the TPU vector unit; callers fall back to XLA
    if len(shape) not in (2, 3):
        return None
    m, n = shape[0], shape[-1]
    if n % 128 != 0:
        return None
    if len(shape) == 3 and shape[1] % _sublane(dtype) != 0:
        return None
    itemsize = jnp.dtype(dtype).itemsize
    if len(shape) == 3:
        itemsize *= shape[1]  # tiles are (t, mid, n)
    return _pick_row_tile(m, n, itemsize, _sublane(dtype) if len(shape) == 2 else 1)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _ftcs_update(c, up, dn, extra_pairs, r):
    """new = c + r * (sum(neighbors) - 2*ndim*c), f32-accumulated for bf16.

    ``extra_pairs`` are the in-tile shifted neighbor pairs beyond the
    up/down (grid-dimension) pair.
    """
    acc_dt = accum_dtype_for(c.dtype)
    ca = c.astype(acc_dt)
    nd = 1 + len(extra_pairs)
    acc = up.astype(acc_dt) + dn.astype(acc_dt) - (2.0 * nd) * ca
    for a, b in extra_pairs:
        acc = acc + a.astype(acc_dt) + b.astype(acc_dt)
    return (ca + jnp.asarray(r, acc_dt) * acc).astype(c.dtype)


def _make_kernel_2d(r: float, m: int, n: int, tile: int):
    def kernel(prev_ref, cur_ref, next_ref, out_ref):
        i = pl.program_id(0)
        g = pl.num_programs(0)
        c = cur_ref[:]
        # One-row halo from neighboring tiles (clamped index maps make the
        # edge reads safe; their values are masked out below).
        top_halo = jnp.where(i == 0, c[0:1, :], prev_ref[tile - 1 : tile, :])
        bot_halo = jnp.where(i == g - 1, c[-1:, :], next_ref[0:1, :])
        up = jnp.concatenate([top_halo, c[:-1, :]], axis=0)   # value at row j-1
        dn = jnp.concatenate([c[1:, :], bot_halo], axis=0)    # value at row j+1
        lf = jnp.concatenate([c[:, 0:1], c[:, :-1]], axis=1)  # value at col k-1
        rt = jnp.concatenate([c[:, 1:], c[:, -1:]], axis=1)   # value at col k+1
        new = _ftcs_update(c, up, dn, [(lf, rt)], r)
        # Freeze the outermost cell ring (interior guard of
        # fortran/cuda_kernel/heat.F90:49: i,j /= 1, ngrid).
        grow = i * tile + jax.lax.broadcasted_iota(jnp.int32, (tile, n), 0)
        gcol = jax.lax.broadcasted_iota(jnp.int32, (tile, n), 1)
        boundary = (grow == 0) | (grow == m - 1) | (gcol == 0) | (gcol == n - 1)
        out_ref[:] = jnp.where(boundary, c, new)

    return kernel


@functools.partial(jax.jit, static_argnames=("r",))
def _step_edges_pallas_2d(T: jax.Array, r: float) -> jax.Array:
    m, n = T.shape
    tile = _supported(T.shape, T.dtype)
    assert tile is not None
    grid = (m // tile,)
    spec = lambda imap: pl.BlockSpec((tile, n), imap, memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _make_kernel_2d(float(r), m, n, tile),
        out_shape=jax.ShapeDtypeStruct(T.shape, T.dtype),
        grid=grid,
        in_specs=[
            spec(lambda i: (jnp.maximum(i - 1, 0), 0)),
            spec(lambda i: (i, 0)),
            spec(lambda i: (jnp.minimum(i + 1, grid[0] - 1), 0)),
        ],
        out_specs=spec(lambda i: (i, 0)),
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=2 * _VMEM_BUDGET_BYTES,
        ),
        cost_estimate=pl.CostEstimate(
            flops=6 * m * n,
            bytes_accessed=2 * m * n * T.dtype.itemsize,
            transcendentals=0,
        ),
        interpret=_interpret(),
    )(T, T, T)


def _make_kernel_3d(r: float, m: int, mid: int, n: int, tile: int):
    def kernel(prev_ref, cur_ref, next_ref, out_ref):
        i = pl.program_id(0)
        g = pl.num_programs(0)
        c = cur_ref[:]
        top_halo = jnp.where(i == 0, c[0:1], prev_ref[tile - 1 : tile])
        bot_halo = jnp.where(i == g - 1, c[-1:], next_ref[0:1])
        up = jnp.concatenate([top_halo, c[:-1]], axis=0)
        dn = jnp.concatenate([c[1:], bot_halo], axis=0)
        fw = jnp.concatenate([c[:, 0:1, :], c[:, :-1, :]], axis=1)
        bk = jnp.concatenate([c[:, 1:, :], c[:, -1:, :]], axis=1)
        lf = jnp.concatenate([c[:, :, 0:1], c[:, :, :-1]], axis=2)
        rt = jnp.concatenate([c[:, :, 1:], c[:, :, -1:]], axis=2)
        new = _ftcs_update(c, up, dn, [(fw, bk), (lf, rt)], r)
        grow = i * tile + jax.lax.broadcasted_iota(jnp.int32, (tile, mid, n), 0)
        gmid = jax.lax.broadcasted_iota(jnp.int32, (tile, mid, n), 1)
        gcol = jax.lax.broadcasted_iota(jnp.int32, (tile, mid, n), 2)
        boundary = (
            (grow == 0) | (grow == m - 1)
            | (gmid == 0) | (gmid == mid - 1)
            | (gcol == 0) | (gcol == n - 1)
        )
        out_ref[:] = jnp.where(boundary, c, new)

    return kernel


@functools.partial(jax.jit, static_argnames=("r",))
def _step_edges_pallas_3d(T: jax.Array, r: float) -> jax.Array:
    m, mid, n = T.shape
    tile = _supported(T.shape, T.dtype)
    assert tile is not None
    grid = (m // tile,)
    spec = lambda imap: pl.BlockSpec((tile, mid, n), imap, memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _make_kernel_3d(float(r), m, mid, n, tile),
        out_shape=jax.ShapeDtypeStruct(T.shape, T.dtype),
        grid=grid,
        in_specs=[
            spec(lambda i: (jnp.maximum(i - 1, 0), 0, 0)),
            spec(lambda i: (i, 0, 0)),
            spec(lambda i: (jnp.minimum(i + 1, grid[0] - 1), 0, 0)),
        ],
        out_specs=spec(lambda i: (i, 0, 0)),
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=4 * _VMEM_BUDGET_BYTES,
        ),
        cost_estimate=pl.CostEstimate(
            flops=8 * m * mid * n,
            bytes_accessed=2 * m * mid * n * T.dtype.itemsize,
            transcendentals=0,
        ),
        interpret=_interpret(),
    )(T, T, T)


def pallas_available(shape, dtype) -> bool:
    return _supported(tuple(shape), dtype) is not None


def ftcs_step_edges_pallas(T: jax.Array, r: float) -> jax.Array:
    """One frozen-boundary FTCS step via the Pallas kernel, with transparent
    XLA fallback for shapes/dtypes the kernel doesn't cover."""
    if not pallas_available(T.shape, T.dtype):
        return ftcs_step_edges(T, r)
    if T.ndim == 2:
        return _step_edges_pallas_2d(T, r=float(r))
    return _step_edges_pallas_3d(T, r=float(r))


def ftcs_step_ghost_pallas(T: jax.Array, r: float, bc_value: float) -> jax.Array:
    """Ghost-BC step via Pallas: pad with the bc ring, run the edges kernel
    on the padded array (its frozen ring IS the ghost ring), crop."""
    padded = jnp.pad(T, 1, mode="constant",
                     constant_values=jnp.asarray(bc_value, T.dtype))
    if not pallas_available(padded.shape, padded.dtype):
        return ftcs_step_ghost(T, r, bc_value)
    if T.ndim == 2:
        out = _step_edges_pallas_2d(padded, r=float(r))
    else:
        out = _step_edges_pallas_3d(padded, r=float(r))
    ctr = tuple(slice(1, -1) for _ in range(T.ndim))
    return out[ctr]
