"""Hand-written Pallas TPU stencil kernels.

The TPU equivalent of the reference's explicit device kernels: CUDA Fortran
``heat_equation`` (fortran/cuda_kernel/heat.F90:39-54), HIP C++ ``heat_eqn``
(fortran/hip/heat_kernel.cpp:31-45), and the Jinja2-JIT CUDA C kernel
(python/cuda/cuda.py:58-86). Where those tile the grid into 32x8 / 128x4
thread blocks, this kernel tiles rows into VMEM-resident blocks aligned to
the 8x128 VPU lanes and streams them HBM->VMEM->HBM through Pallas's
pipelined grid.

Design notes:
- Grid is 1-D over row tiles; each program sees its own tile plus the
  *clamped* previous/next tiles (three input BlockSpecs on the same array),
  which supplies the row halo that the reference fetches via its ghost ring.
  Column neighbors are in-tile shifts (full rows live in the block).
- **Temporal blocking**: the 2D kernel runs ``ksteps`` FTCS steps per HBM
  pass. One pass costs ~16 bytes/point (3 tile reads + 1 write); fusing k
  steps amortizes that to ~16/k — the stencil analog of kernel fusion that
  the reference's one-kernel-launch-per-step model cannot express
  (fortran/cuda_kernel/heat.F90:30-34). Valid because a point's k-step
  dependency cone spans rows within distance k <= tile, all inside the
  3-tile band, and the frozen boundary ring is re-pinned after every
  mini-step (which also walls off garbage from the clamped out-of-range
  tiles at the first/last grid step).
- **Arbitrary shapes**: inputs are padded to lane/tile alignment inside the
  wrapper; padding cells are frozen (never read by logical cells beyond the
  frozen logical boundary) and cropped on return.
- The runtime constant ``r`` is baked into the kernel as a closure constant
  — the Pallas analog of the reference's Jinja2 constant-baking
  (python/cuda/cuda.py:85), with jit retrace standing in for re-render.
- bf16 runs upcast to f32 for the accumulate and round once at the store
  ("bf16 stencil + fp32 accumulate" mode).
- Boundary cells are masked back to their old value ("edges" BC) exactly
  like the in-kernel interior guard ``i/=1 .and. i/=ngrid`` of
  fortran/cuda_kernel/heat.F90:49; the Dirichlet-by-ghost ("ghost") BC is
  the same kernel on a bc-padded array whose frozen ring IS the ghost ring.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .stencil import accum_dtype_for, ftcs_step_edges, ftcs_step_ghost

# VMEM working-set budget for tile selection (conservative: leaves room for
# Pallas's double-buffered pipeline and the output tile).
_VMEM_BUDGET_BYTES = 12 * 1024 * 1024


def _sublane(dtype) -> int:
    return 16 if jnp.dtype(dtype) == jnp.bfloat16 else 8


def _round_up(x: int, mult: int) -> int:
    return ((x + mult - 1) // mult) * mult


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _ftcs_update(c, up, dn, extra_pairs, r):
    """new = c + r * (sum(neighbors) - 2*ndim*c), f32-accumulated for bf16.

    ``extra_pairs`` are the in-tile shifted neighbor pairs beyond the
    up/down (grid-dimension) pair.
    """
    acc_dt = accum_dtype_for(c.dtype)
    ca = c.astype(acc_dt)
    nd = 1 + len(extra_pairs)
    acc = up.astype(acc_dt) + dn.astype(acc_dt) - (2.0 * nd) * ca
    for a, b in extra_pairs:
        acc = acc + a.astype(acc_dt) + b.astype(acc_dt)
    return (ca + jnp.asarray(r, acc_dt) * acc).astype(c.dtype)


# --------------------------------------------------------------------------
# 2D: unified single/multi-step kernel on arbitrary shapes
# --------------------------------------------------------------------------


def _tile_2d(n_pad: int, dtype, ksteps: int) -> int:
    """Row-tile height: sublane-aligned, >= ksteps (dependency cone), sized
    so ~8 tiles of (tile, n_pad) stay inside the VMEM budget."""
    sub = _sublane(dtype)
    cap = max(sub, (_VMEM_BUDGET_BYTES // (8 * n_pad * jnp.dtype(dtype).itemsize)))
    cap = (cap // sub) * sub
    tile = min(256, max(sub, cap))
    return max(tile, _round_up(ksteps, sub))


def _make_kernel_2d(r: float, m: int, n: int, tile: int, n_pad: int, ksteps: int):
    def kernel(prev_ref, cur_ref, next_ref, out_ref):
        i = pl.program_id(0)
        band0 = jnp.concatenate([prev_ref[:], cur_ref[:], next_ref[:]], axis=0)
        grow = (i - 1) * tile + jax.lax.broadcasted_iota(
            jnp.int32, (3 * tile, n_pad), 0
        )
        gcol = jax.lax.broadcasted_iota(jnp.int32, (3 * tile, n_pad), 1)
        # freeze the logical boundary ring plus all alignment padding (and,
        # via <=0 / >=m-1, the garbage rows of clamped out-of-range tiles)
        frozen = (grow <= 0) | (grow >= m - 1) | (gcol == 0) | (gcol >= n - 1)

        def mini_step(band):
            up = jnp.concatenate([band[0:1], band[:-1]], axis=0)
            dn = jnp.concatenate([band[1:], band[-1:]], axis=0)
            lf = jnp.concatenate([band[:, 0:1], band[:, :-1]], axis=1)
            rt = jnp.concatenate([band[:, 1:], band[:, -1:]], axis=1)
            new = _ftcs_update(band, up, dn, [(lf, rt)], r)
            return jnp.where(frozen, band0, new)

        band = band0
        for _ in range(ksteps):  # static unroll
            band = mini_step(band)
        out_ref[:] = band[tile : 2 * tile]

    return kernel


@functools.partial(jax.jit, static_argnames=("r", "ksteps"))
def _pallas_2d(T: jax.Array, r: float, ksteps: int) -> jax.Array:
    """``ksteps`` frozen-boundary FTCS steps on an arbitrary 2D array."""
    m, n = T.shape
    n_pad = _round_up(max(n, 128), 128)
    tile = _tile_2d(n_pad, T.dtype, ksteps)
    m_pad = _round_up(max(m, tile), tile)
    padded = (m_pad != m) or (n_pad != n)
    Tp = jnp.pad(T, ((0, m_pad - m), (0, n_pad - n))) if padded else T
    grid = (m_pad // tile,)
    spec = lambda imap: pl.BlockSpec((tile, n_pad), imap, memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        _make_kernel_2d(float(r), m, n, tile, n_pad, ksteps),
        out_shape=jax.ShapeDtypeStruct(Tp.shape, Tp.dtype),
        grid=grid,
        in_specs=[
            spec(lambda i: (jnp.maximum(i - 1, 0), 0)),
            spec(lambda i: (i, 0)),
            spec(lambda i: (jnp.minimum(i + 1, grid[0] - 1), 0)),
        ],
        out_specs=spec(lambda i: (i, 0)),
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=8 * _VMEM_BUDGET_BYTES,
        ),
        cost_estimate=pl.CostEstimate(
            flops=6 * m_pad * n_pad * ksteps * 3,
            bytes_accessed=2 * m_pad * n_pad * Tp.dtype.itemsize,
            transcendentals=0,
        ),
        interpret=_interpret(),
    )(Tp, Tp, Tp)
    return out[:m, :n] if padded else out


# --------------------------------------------------------------------------
# 3D: plane-tiled kernel, arbitrary shapes, temporal blocking within VMEM
# --------------------------------------------------------------------------


def _tile_3d(mid_pad: int, n_pad: int, dtype) -> int:
    """Planes per tile, sized so ~8 tiles of (tile, mid_pad, n_pad) fit the
    VMEM budget, capped at 8. The fusion invariant ksteps <= tile is owned
    by _pallas_3d's assert and _multistep's chunking."""
    plane = mid_pad * n_pad * jnp.dtype(dtype).itemsize
    cap = max(1, _VMEM_BUDGET_BYTES // (8 * plane))
    return max(1, min(8, cap))


def _make_kernel_3d(r: float, shape_logical, tile: int, shape_pad, ksteps: int):
    m, mid, n = shape_logical
    _, mid_p, n_p = shape_pad

    def kernel(prev_ref, cur_ref, next_ref, out_ref):
        i = pl.program_id(0)
        band0 = jnp.concatenate([prev_ref[:], cur_ref[:], next_ref[:]], axis=0)
        bshape = (3 * tile, mid_p, n_p)
        grow = (i - 1) * tile + jax.lax.broadcasted_iota(jnp.int32, bshape, 0)
        gmid = jax.lax.broadcasted_iota(jnp.int32, bshape, 1)
        gcol = jax.lax.broadcasted_iota(jnp.int32, bshape, 2)
        frozen = (
            (grow <= 0) | (grow >= m - 1)
            | (gmid == 0) | (gmid >= mid - 1)
            | (gcol == 0) | (gcol >= n - 1)
        )

        def mini_step(band):
            up = jnp.concatenate([band[0:1], band[:-1]], axis=0)
            dn = jnp.concatenate([band[1:], band[-1:]], axis=0)
            fw = jnp.concatenate([band[:, 0:1, :], band[:, :-1, :]], axis=1)
            bk = jnp.concatenate([band[:, 1:, :], band[:, -1:, :]], axis=1)
            lf = jnp.concatenate([band[:, :, 0:1], band[:, :, :-1]], axis=2)
            rt = jnp.concatenate([band[:, :, 1:], band[:, :, -1:]], axis=2)
            new = _ftcs_update(band, up, dn, [(fw, bk), (lf, rt)], r)
            return jnp.where(frozen, band0, new)

        band = band0
        for _ in range(ksteps):  # static unroll
            band = mini_step(band)
        out_ref[:] = band[tile : 2 * tile]

    return kernel


def _aligned_shape_3d(shape, dtype):
    m, mid, n = shape
    n_pad = _round_up(max(n, 128), 128)
    mid_pad = _round_up(max(mid, _sublane(dtype)), _sublane(dtype))
    tile = _tile_3d(mid_pad, n_pad, dtype)
    m_pad = _round_up(max(m, tile), tile)
    return (m_pad, mid_pad, n_pad), tile


@functools.partial(jax.jit, static_argnames=("r", "ksteps", "logical_shape"))
def _pallas_3d_aligned(Tp: jax.Array, r: float, ksteps: int,
                       logical_shape) -> jax.Array:
    """``ksteps`` frozen-boundary FTCS steps on an already tile-aligned 3D
    array whose logical (unpadded) extents are ``logical_shape``. ksteps
    must not exceed the plane tile (callers chunk; see _multistep)."""
    (m_pad, mid_pad, n_pad), tile = _aligned_shape_3d(logical_shape, Tp.dtype)
    assert Tp.shape == (m_pad, mid_pad, n_pad) and ksteps <= tile
    m, mid, n = logical_shape
    grid = (m_pad // tile,)
    spec = lambda imap: pl.BlockSpec((tile, mid_pad, n_pad), imap,
                                     memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _make_kernel_3d(float(r), (m, mid, n), tile, Tp.shape, ksteps),
        out_shape=jax.ShapeDtypeStruct(Tp.shape, Tp.dtype),
        grid=grid,
        in_specs=[
            spec(lambda i: (jnp.maximum(i - 1, 0), 0, 0)),
            spec(lambda i: (i, 0, 0)),
            spec(lambda i: (jnp.minimum(i + 1, grid[0] - 1), 0, 0)),
        ],
        out_specs=spec(lambda i: (i, 0, 0)),
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=8 * _VMEM_BUDGET_BYTES,
        ),
        cost_estimate=pl.CostEstimate(
            flops=8 * m_pad * mid_pad * n_pad * ksteps * 3,
            bytes_accessed=2 * m_pad * mid_pad * n_pad * Tp.dtype.itemsize,
            transcendentals=0,
        ),
        interpret=_interpret(),
    )(Tp, Tp, Tp)


def max_fuse_3d(shape, dtype) -> int:
    """Largest temporal-blocking depth the 3D kernel affords for this shape."""
    _, tile = _aligned_shape_3d(shape, dtype)
    return tile


# --------------------------------------------------------------------------
# public entry points (with transparent XLA fallback)
# --------------------------------------------------------------------------


def pallas_available(shape, dtype) -> bool:
    """Arbitrary 2D/3D shapes are supported via internal alignment padding;
    only f64 (no TPU VPU support) falls back to XLA."""
    shape = tuple(shape)
    if jnp.dtype(dtype) == jnp.float64:
        return False
    return len(shape) in (2, 3)


def _multistep(T: jax.Array, r: float, ksteps: int) -> jax.Array:
    """Dispatch ksteps fused frozen-boundary steps, chunking 3D fusion down
    to what VMEM affords (pad/crop hoisted outside the chunk loop)."""
    if T.ndim == 2:
        return _pallas_2d(T, r=float(r), ksteps=ksteps)
    logical = tuple(T.shape)
    aligned, kmax = _aligned_shape_3d(logical, T.dtype)
    if aligned != logical:
        T = jnp.pad(T, [(0, p - s) for p, s in zip(aligned, logical)])
    done = 0
    while done < ksteps:
        k = min(kmax, ksteps - done)
        T = _pallas_3d_aligned(T, r=float(r), ksteps=k, logical_shape=logical)
        done += k
    if aligned != logical:
        T = T[: logical[0], : logical[1], : logical[2]]
    return T


def ftcs_step_edges_pallas(T: jax.Array, r: float) -> jax.Array:
    """One frozen-boundary FTCS step via the Pallas kernel, with transparent
    XLA fallback for dtypes the kernel doesn't cover."""
    if not pallas_available(T.shape, T.dtype):
        return ftcs_step_edges(T, r)
    return _multistep(T, r, 1)


def ftcs_step_ghost_pallas(T: jax.Array, r: float, bc_value) -> jax.Array:
    """Ghost-BC step via Pallas: pad with the bc ring, run the edges kernel
    on the padded array (its frozen ring IS the ghost ring), crop."""
    return ftcs_multistep_ghost_pallas(T, r, bc_value, 1)


def ftcs_multistep_edges_pallas(T: jax.Array, r: float, ksteps: int) -> jax.Array:
    """``ksteps`` frozen-boundary FTCS steps in fused kernel passes, with
    sequential XLA fallback where the kernel doesn't apply."""
    if pallas_available(T.shape, T.dtype):
        return _multistep(T, r, ksteps)
    out = T
    for _ in range(ksteps):
        out = ftcs_step_edges(out, r)
    return out


def ftcs_multistep_ghost_pallas(T: jax.Array, r: float, bc_value, ksteps: int) -> jax.Array:
    """``ksteps`` ghost-BC steps fused: the padded array's frozen outer ring
    IS the ghost ring, which never changes — so the edges multistep kernel on
    the padded array is exactly k ghost-BC steps."""
    if pallas_available(T.shape, T.dtype):
        padded = jnp.pad(T, 1, mode="constant",
                         constant_values=jnp.asarray(bc_value, T.dtype))
        out = _multistep(padded, r, ksteps)
        ctr = tuple(slice(1, -1) for _ in range(T.ndim))
        return out[ctr]
    out = T
    for _ in range(ksteps):
        out = ftcs_step_ghost(out, r, bc_value)
    return out
