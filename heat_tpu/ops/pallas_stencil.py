"""Hand-written Pallas TPU stencil kernels.

The TPU equivalent of the reference's explicit device kernels: CUDA Fortran
``heat_equation`` (fortran/cuda_kernel/heat.F90:39-54), HIP C++ ``heat_eqn``
(fortran/hip/heat_kernel.cpp:31-45), and the Jinja2-JIT CUDA C kernel
(python/cuda/cuda.py:58-86). Where those tile the grid into 32x8 / 128x4
thread blocks, this kernel tiles rows into VMEM-resident blocks aligned to
the 8x128 VPU lanes and streams them HBM->VMEM->HBM through Pallas's
pipelined grid.

Design notes:
- Grid is 1-D over row tiles; each program sees its own tile plus a
  ``kpad``-row halo slab above and below (three BlockSpecs on the same
  array: two thin halo blocks + the main tile), supplying the row halo the
  reference fetches via its ghost ring. Column neighbors are in-tile lane
  rotates (full rows live in the block).
- **Temporal blocking**: the kernel runs ``ksteps`` FTCS steps per HBM
  pass. One pass costs ~(1 + 2k/tile)*8 bytes/point; fusing k steps
  amortizes to ~8/k B/point/step — the stencil analog of kernel fusion that
  the reference's one-kernel-launch-per-step model cannot express
  (fortran/cuda_kernel/heat.F90:30-34). Valid because a point's k-step
  dependency cone spans <= k < kpad halo rows, and neighbor shifts are
  wrap-around rotates whose band-edge corruption also travels only one row
  per mini-step — it never reaches the center tile while k <= kpad.
- Boundary cells are frozen by a *mask-multiplied* update
  (``band + mask*r*lap`` with mask=0 on the boundary ring), the
  multiplicative form of the reference's in-kernel interior guard
  ``i/=1 .and. i/=ngrid`` (fortran/cuda_kernel/heat.F90:49). Frozen cells
  never change, so no pristine copy of the input band needs to stay live
  across the fused mini-steps (that retained copy was the old kernel's
  VMEM-pressure ceiling).
- **Arbitrary shapes**: inputs are padded to lane/tile alignment inside the
  wrapper; padding cells are frozen (never read by logical cells beyond the
  frozen logical boundary) and cropped on return.
- The runtime constant ``r`` is baked into the kernel as a closure constant
  — the Pallas analog of the reference's Jinja2 constant-baking
  (python/cuda/cuda.py:85), with jit retrace standing in for re-render.
- bf16 bands upcast to f32 once on load and round once at the store
  ("bf16 stencil + fp32 accumulate" mode).
- The Dirichlet-by-ghost ("ghost") BC is the same kernel on a bc-padded
  array whose frozen ring IS the ghost ring.

Measured on a single v5e chip (4096^2 f32): ~26 Gpts/s for the fused-XLA
step, ~128 Gpts/s for this kernel at ksteps=16 — 2.5x the 16 B/pt naive
roofline that one-step-per-pass designs (the reference's) are bound by.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .stencil import accum_dtype_for, ftcs_step_edges, ftcs_step_ghost

# VMEM ceiling passed to Mosaic; band sizing below stays well under it so
# the unrolled mini-step chain's live temporaries fit alongside the
# double-buffered pipeline.
_VMEM_LIMIT_BYTES = 100 * 1024 * 1024
# target in-kernel band footprint (accumulation dtype); measured on v5e:
# 6 MiB caps 32768^2 bf16 at 69 Gpts/s (16-row tiles, 3x halo-compute
# overhead), 12 MiB doubles it to 135 Gpts/s (64-row tiles)
_BAND_BUDGET_BYTES = 12 * 1024 * 1024
# per-pass fusion cap: halo rows (and compile-time unroll) stay bounded;
# measured throughput is flat past 16
_KMAX_2D = 32


def _sublane(dtype) -> int:
    return 16 if jnp.dtype(dtype) == jnp.bfloat16 else 8

def _round_up(x: int, mult: int) -> int:
    return ((x + mult - 1) // mult) * mult

def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# --------------------------------------------------------------------------
# 2D: halo-slab BlockSpecs, rotate shifts, masked multiplicative update
# --------------------------------------------------------------------------


def _halo_2d(ksteps: int, dtype) -> int:
    """Halo slab height: >= ksteps (dependency cone), sublane-aligned."""
    return _round_up(max(ksteps, 1), _sublane(dtype))


def _tile_2d(n_pad: int, kpad: int) -> int:
    """Row-tile height: a multiple of kpad (so halo blocks index evenly),
    sized to keep the (tile + 2*kpad)-row band near the budget (the band is
    held in the f32 accumulation dtype regardless of storage dtype)."""
    cap = _BAND_BUDGET_BYTES // (n_pad * 4) - 2 * kpad
    tile = min(256, max(cap, kpad))
    return max(kpad, (tile // kpad) * kpad)


def _make_kernel_2d(r: float, tile: int, kpad: int, n_pad: int, ksteps: int):
    """Kernel body. ``bounds_ref`` is an SMEM (1,4) i32 array
    [row_lo, row_hi, col_lo, col_hi]: cells with index <= lo or >= hi on
    either axis are frozen. For a plain solve that is the boundary ring
    (0, m-1, 0, n-1); the sharded backend passes per-shard values so only
    global-domain edges freeze (see ftcs_multistep_bounded_pallas)."""
    rows = tile + 2 * kpad

    def kernel(bounds_ref, prev_ref, cur_ref, next_ref, out_ref):
        i = pl.program_id(0)
        store_dt = out_ref.dtype
        acc_dt = accum_dtype_for(store_dt)
        band = jnp.concatenate(
            [prev_ref[:], cur_ref[:], next_ref[:]], axis=0
        ).astype(acc_dt)
        grow = i * tile - kpad + jax.lax.broadcasted_iota(
            jnp.int32, (rows, n_pad), 0
        )
        gcol = jax.lax.broadcasted_iota(jnp.int32, (rows, n_pad), 1)
        frozen = (
            (grow <= bounds_ref[0, 0]) | (grow >= bounds_ref[0, 1])
            | (gcol <= bounds_ref[0, 2]) | (gcol >= bounds_ref[0, 3])
        )
        maskr = jnp.where(frozen, 0.0, r).astype(acc_dt)

        for _ in range(ksteps):  # static unroll
            up = pltpu.roll(band, 1, 0)
            dn = pltpu.roll(band, rows - 1, 0)
            lf = pltpu.roll(band, 1, 1)
            rt = pltpu.roll(band, n_pad - 1, 1)
            band = band + maskr * (up + dn + lf + rt - 4.0 * band)
        out_ref[:] = band[kpad : kpad + tile].astype(store_dt)

    return kernel


@functools.partial(jax.jit, static_argnames=("r", "ksteps"))
def _pallas_2d(T: jax.Array, r: float, ksteps: int,
               bounds: jax.Array | None = None) -> jax.Array:
    """``ksteps`` FTCS steps on an arbitrary 2D array, freezing cells at or
    beyond ``bounds`` (default: the boundary ring — "edges" semantics).
    ksteps must not exceed _KMAX_2D (callers chunk; see _multistep)."""
    assert ksteps <= _KMAX_2D, (
        f"ksteps={ksteps} exceeds _KMAX_2D={_KMAX_2D}; chunk via _multistep "
        f"(unbounded fusion inflates compile time and VMEM)")
    m, n = T.shape
    if bounds is None:
        bounds = jnp.asarray([[0, m - 1, 0, n - 1]], jnp.int32)
        # with the boundary ring frozen, garbage in the clamped out-of-range
        # halo blocks of the first/last grid step is only read by frozen
        # rows; custom bounds callers own a discard margin >= ksteps instead
    bounds = bounds.reshape(1, 4).astype(jnp.int32)
    n_pad = _round_up(max(n, 128), 128)
    kpad = _halo_2d(ksteps, T.dtype)
    tile = _tile_2d(n_pad, kpad)
    assert ksteps <= kpad <= tile and tile % kpad == 0
    m_pad = _round_up(max(m, tile), tile)
    padded = (m_pad != m) or (n_pad != n)
    Tp = jnp.pad(T, ((0, m_pad - m), (0, n_pad - n))) if padded else T
    grid = (m_pad // tile,)
    ratio = tile // kpad
    nhblk = m_pad // kpad
    smem = pl.BlockSpec((1, 4), lambda i: (0, 0), memory_space=pltpu.SMEM)
    halo = lambda imap: pl.BlockSpec((kpad, n_pad), imap, memory_space=pltpu.VMEM)
    main = lambda imap: pl.BlockSpec((tile, n_pad), imap, memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        _make_kernel_2d(float(r), tile, kpad, n_pad, ksteps),
        out_shape=jax.ShapeDtypeStruct(Tp.shape, Tp.dtype),
        grid=grid,
        in_specs=[
            smem,
            halo(lambda i: (jnp.maximum(i * ratio - 1, 0), 0)),
            main(lambda i: (i, 0)),
            halo(lambda i: (jnp.minimum((i + 1) * ratio, nhblk - 1), 0)),
        ],
        out_specs=main(lambda i: (i, 0)),
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=_VMEM_LIMIT_BYTES,
        ),
        cost_estimate=pl.CostEstimate(
            flops=9 * (tile + 2 * kpad) * grid[0] * n_pad * ksteps,
            bytes_accessed=(2 * m_pad + 2 * kpad * grid[0]) * n_pad
            * Tp.dtype.itemsize,
            transcendentals=0,
        ),
        interpret=_interpret(),
    )(bounds, Tp, Tp, Tp)
    return out[:m, :n] if padded else out


# --------------------------------------------------------------------------
# 3D: plane-tiled kernel, arbitrary shapes, temporal blocking within VMEM
# --------------------------------------------------------------------------


# rough v5e machine balance for the 3D plan's cost model: effective VPU
# elementwise rate (backed out of the measured 2D kernel: ~10 ops/pt-step
# at 1.4e11 pts/s) and HBM bandwidth
_VPU_OPS_PER_S = 1.4e12
_HBM_BYTES_PER_S = 819e9


def _plan_3d(shape, dtype, ksteps: int):
    """Choose (padded_shape, tile, kchunk) for the plane-tiled 3D kernel.

    The halo here is whole (mid, n) planes, so — unlike 2D, where the halo
    slab is a thin strip — deeper fusion shrinks HBM traffic but inflates
    the redundantly-computed band fraction (tile+2k)/tile. Pick the
    (tile, k) minimizing max(compute, bandwidth) per point-step under the
    band budget."""
    m, mid, n = shape
    n_pad = _round_up(max(n, 128), 128)
    mid_pad = _round_up(max(mid, _sublane(dtype)), _sublane(dtype))
    plane = mid_pad * n_pad * 4  # band is held in the accumulation dtype
    budget_planes = max(3, _BAND_BUDGET_BYTES // plane)
    item = jnp.dtype(dtype).itemsize
    best = None
    for k in range(1, min(max(ksteps, 1), 8) + 1):
        cap = budget_planes - 2 * k
        if cap < k:
            continue
        # don't tile far past the array itself (padding is wasted work)
        cap = min(cap, _round_up(max(m, k), k))
        tile = (cap // k) * k
        compute = 11.0 * (tile + 2 * k) / tile / _VPU_OPS_PER_S
        bw = (2.0 * tile + 2 * k) / (tile * k) * item / _HBM_BYTES_PER_S
        key = (max(compute, bw), -k)
        if best is None or key < best[0]:
            best = (key, tile, k)
    _, tile, kchunk = best
    m_pad = _round_up(max(m, tile), tile)
    return (m_pad, mid_pad, n_pad), tile, kchunk


def _make_kernel_3d(r: float, tile: int, kpad: int, shape_pad, ksteps: int):
    """Kernel body; ``bounds_ref`` is SMEM (1,6) i32
    [row_lo, row_hi, mid_lo, mid_hi, col_lo, col_hi] (see 2D)."""
    _, mid_p, n_p = shape_pad
    rows = tile + 2 * kpad

    def kernel(bounds_ref, prev_ref, cur_ref, next_ref, out_ref):
        i = pl.program_id(0)
        store_dt = out_ref.dtype
        acc_dt = accum_dtype_for(store_dt)
        band = jnp.concatenate(
            [prev_ref[:], cur_ref[:], next_ref[:]], axis=0
        ).astype(acc_dt)
        bshape = (rows, mid_p, n_p)
        grow = i * tile - kpad + jax.lax.broadcasted_iota(jnp.int32, bshape, 0)
        gmid = jax.lax.broadcasted_iota(jnp.int32, bshape, 1)
        gcol = jax.lax.broadcasted_iota(jnp.int32, bshape, 2)
        frozen = (
            (grow <= bounds_ref[0, 0]) | (grow >= bounds_ref[0, 1])
            | (gmid <= bounds_ref[0, 2]) | (gmid >= bounds_ref[0, 3])
            | (gcol <= bounds_ref[0, 4]) | (gcol >= bounds_ref[0, 5])
        )
        maskr = jnp.where(frozen, 0.0, r).astype(acc_dt)

        for _ in range(ksteps):  # static unroll
            up = pltpu.roll(band, 1, 0)
            dn = pltpu.roll(band, rows - 1, 0)
            fw = pltpu.roll(band, 1, 1)
            bk = pltpu.roll(band, mid_p - 1, 1)
            lf = pltpu.roll(band, 1, 2)
            rt = pltpu.roll(band, n_p - 1, 2)
            band = band + maskr * (up + dn + fw + bk + lf + rt - 6.0 * band)
        out_ref[:] = band[kpad : kpad + tile].astype(store_dt)

    return kernel


@functools.partial(jax.jit,
                   static_argnames=("r", "ksteps", "kpad", "logical_shape"))
def _pallas_3d_aligned(Tp: jax.Array, r: float, ksteps: int, kpad: int,
                       logical_shape, bounds: jax.Array | None = None) -> jax.Array:
    """``ksteps`` FTCS steps on an already tile-aligned 3D array whose
    logical (unpadded) extents are ``logical_shape``. ``kpad`` is the plan's
    halo depth (fixed block geometry across chunks); a remainder pass may
    run ksteps < kpad. Callers chunk — see _multistep."""
    (m_pad, mid_pad, n_pad), tile, kplan = _plan_3d(logical_shape, Tp.dtype, kpad)
    assert Tp.shape == (m_pad, mid_pad, n_pad)
    assert kplan == kpad and ksteps <= kpad and tile % kpad == 0
    m, mid, n = logical_shape
    if bounds is None:
        bounds = jnp.asarray([[0, m - 1, 0, mid - 1, 0, n - 1]], jnp.int32)
    bounds = bounds.reshape(1, 6).astype(jnp.int32)
    grid = (m_pad // tile,)
    ratio = tile // kpad
    nhblk = m_pad // kpad
    smem = pl.BlockSpec((1, 6), lambda i: (0, 0), memory_space=pltpu.SMEM)
    halo = lambda imap: pl.BlockSpec((kpad, mid_pad, n_pad), imap,
                                     memory_space=pltpu.VMEM)
    main = lambda imap: pl.BlockSpec((tile, mid_pad, n_pad), imap,
                                     memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _make_kernel_3d(float(r), tile, kpad, Tp.shape, ksteps),
        out_shape=jax.ShapeDtypeStruct(Tp.shape, Tp.dtype),
        grid=grid,
        in_specs=[
            smem,
            halo(lambda i: (jnp.maximum(i * ratio - 1, 0), 0, 0)),
            main(lambda i: (i, 0, 0)),
            halo(lambda i: (jnp.minimum((i + 1) * ratio, nhblk - 1), 0, 0)),
        ],
        out_specs=main(lambda i: (i, 0, 0)),
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=_VMEM_LIMIT_BYTES,
        ),
        cost_estimate=pl.CostEstimate(
            flops=11 * (tile + 2 * kpad) * grid[0] * mid_pad * n_pad * ksteps,
            bytes_accessed=(2 * m_pad + 2 * kpad * grid[0]) * mid_pad * n_pad
            * Tp.dtype.itemsize,
            transcendentals=0,
        ),
        interpret=_interpret(),
    )(bounds, Tp, Tp, Tp)


# --------------------------------------------------------------------------
# public entry points (with transparent XLA fallback)
# --------------------------------------------------------------------------


def pallas_available(shape, dtype) -> bool:
    """Arbitrary 2D/3D shapes are supported via internal alignment padding;
    only f64 (no TPU VPU support) falls back to XLA."""
    shape = tuple(shape)
    if jnp.dtype(dtype) == jnp.float64:
        return False
    return len(shape) in (2, 3)


def _multistep(T: jax.Array, r: float, ksteps: int,
               bounds: jax.Array | None = None) -> jax.Array:
    """Dispatch ksteps fused frozen-boundary steps, chunking fusion down to
    what each kernel's dependency-cone bound affords."""
    if T.ndim == 2:
        done = 0
        while done < ksteps:
            k = min(_KMAX_2D, ksteps - done)
            T = _pallas_2d(T, r=float(r), ksteps=k, bounds=bounds)
            done += k
        return T
    logical = tuple(T.shape)
    aligned, _, kchunk = _plan_3d(logical, T.dtype, ksteps)
    if aligned != logical:
        T = jnp.pad(T, [(0, p - s) for p, s in zip(aligned, logical)])
    done = 0
    while done < ksteps:
        k = min(kchunk, ksteps - done)
        T = _pallas_3d_aligned(T, r=float(r), ksteps=k, kpad=kchunk,
                               logical_shape=logical, bounds=bounds)
        done += k
    if aligned != logical:
        T = T[: logical[0], : logical[1], : logical[2]]
    return T


def ftcs_multistep_bounded_pallas(T: jax.Array, r: float, ksteps: int,
                                  bounds: jax.Array) -> jax.Array:
    """``ksteps`` fused FTCS steps freezing cells at or beyond ``bounds``
    (i32 [lo, hi] pair per dimension, flattened; may be traced values —
    e.g. computed from ``lax.axis_index`` inside shard_map).

    Contract: cells NOT frozen by ``bounds`` include array-edge cells whose
    out-of-range neighbors are garbage (wrap rotates / clamped halo blocks),
    so the caller MUST own a discard margin >= ksteps on every non-frozen
    side — exactly the halo-width invariant of the sharded backend's
    communication-avoiding exchange (one width-k exchange buys k steps).
    """
    assert pallas_available(T.shape, T.dtype), (T.shape, T.dtype)
    return _multistep(T, r, ksteps, bounds=jnp.asarray(bounds, jnp.int32))


def ftcs_step_edges_pallas(T: jax.Array, r: float) -> jax.Array:
    """One frozen-boundary FTCS step via the Pallas kernel, with transparent
    XLA fallback for dtypes the kernel doesn't cover."""
    if not pallas_available(T.shape, T.dtype):
        return ftcs_step_edges(T, r)
    return _multistep(T, r, 1)


def ftcs_step_ghost_pallas(T: jax.Array, r: float, bc_value) -> jax.Array:
    """Ghost-BC step via Pallas: pad with the bc ring, run the edges kernel
    on the padded array (its frozen ring IS the ghost ring), crop."""
    return ftcs_multistep_ghost_pallas(T, r, bc_value, 1)


def ftcs_multistep_edges_pallas(T: jax.Array, r: float, ksteps: int) -> jax.Array:
    """``ksteps`` frozen-boundary FTCS steps in fused kernel passes, with
    sequential XLA fallback where the kernel doesn't apply."""
    if pallas_available(T.shape, T.dtype):
        return _multistep(T, r, ksteps)
    out = T
    for _ in range(ksteps):
        out = ftcs_step_edges(out, r)
    return out


def ftcs_multistep_ghost_pallas(T: jax.Array, r: float, bc_value, ksteps: int) -> jax.Array:
    """``ksteps`` ghost-BC steps fused: the padded array's frozen outer ring
    IS the ghost ring, which never changes — so the edges multistep kernel on
    the padded array is exactly k ghost-BC steps."""
    if pallas_available(T.shape, T.dtype):
        padded = jnp.pad(T, 1, mode="constant",
                         constant_values=jnp.asarray(bc_value, T.dtype))
        out = _multistep(padded, r, ksteps)
        ctr = tuple(slice(1, -1) for _ in range(T.ndim))
        return out[ctr]
    out = T
    for _ in range(ksteps):
        out = ftcs_step_ghost(out, r, bc_value)
    return out
