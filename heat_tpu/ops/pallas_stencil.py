"""Hand-written Pallas TPU stencil kernels.

The TPU equivalent of the reference's explicit device kernels: CUDA Fortran
``heat_equation`` (fortran/cuda_kernel/heat.F90:39-54), HIP C++ ``heat_eqn``
(fortran/hip/heat_kernel.cpp:31-45), and the Jinja2-JIT CUDA C kernel
(python/cuda/cuda.py:58-86). Where those tile the grid into 32x8 / 128x4
thread blocks, this kernel tiles rows into VMEM-resident blocks aligned to
the 8x128 VPU lanes and streams them HBM->VMEM->HBM through Pallas's
pipelined grid.

Design notes:
- Grid is 1-D over row tiles; each program sees its own tile plus a
  ``kpad``-row halo slab above and below (three BlockSpecs on the same
  array: two thin halo blocks + the main tile), supplying the row halo the
  reference fetches via its ghost ring. Column neighbors are in-tile lane
  rotates (full rows live in the block).
- **Temporal blocking**: the kernel runs ``ksteps`` FTCS steps per HBM
  pass. One pass costs ~(1 + 2k/tile)*8 bytes/point; fusing k steps
  amortizes to ~8/k B/point/step — the stencil analog of kernel fusion that
  the reference's one-kernel-launch-per-step model cannot express
  (fortran/cuda_kernel/heat.F90:30-34). Valid because a point's k-step
  dependency cone spans <= k < kpad halo rows, and neighbor shifts are
  wrap-around rotates whose band-edge corruption also travels only one row
  per mini-step — it never reaches the center tile while k <= kpad.
- Boundary cells are frozen by a *mask-multiplied* update
  (``band + mask*r*lap`` with mask=0 on the boundary ring), the
  multiplicative form of the reference's in-kernel interior guard
  ``i/=1 .and. i/=ngrid`` (fortran/cuda_kernel/heat.F90:49). Frozen cells
  never change, so no pristine copy of the input band needs to stay live
  across the fused mini-steps (that retained copy was the old kernel's
  VMEM-pressure ceiling).
- **Arbitrary shapes**: inputs are padded to lane/tile alignment inside the
  wrapper; padding cells are frozen (never read by logical cells beyond the
  frozen logical boundary) and cropped on return.
- The runtime constant ``r`` is baked into the kernel as a closure constant
  — the Pallas analog of the reference's Jinja2 constant-baking
  (python/cuda/cuda.py:85), with jit retrace standing in for re-render.
- bf16 bands upcast to f32 once on load and round once at the store
  ("bf16 stencil + fp32 accumulate" mode).
- The Dirichlet-by-ghost ("ghost") BC is the same kernel on a bc-padded
  array whose frozen ring IS the ghost ring.

Measured on a single v5e chip (4096^2 f32): ~26 Gpts/s for the fused-XLA
step, ~128 Gpts/s for this kernel at ksteps=16 — 2.5x the 16 B/pt naive
roofline that one-step-per-pass designs (the reference's) are bound by.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

if not hasattr(pltpu, "CompilerParams"):  # pre-rename JAX (<= 0.4.x) spells
    pltpu.CompilerParams = pltpu.TPUCompilerParams  # it TPUCompilerParams

from .. import machine
from .stencil import (accum_dtype_for, ftcs_step_edges, ftcs_step_ghost,
                      ftcs_step_periodic)

# Chip-dependent constants (VMEM ceilings, band budgets, fitted op/HBM
# rates for the cost models) live in heat_tpu.machine, selected by
# device_kind — v5e values are measured, other chips spec-derived. The
# derivation notes for the v5e numbers:
# - vmem_limit 110 MiB (of the chip's 128): the 3D plan's 512^3
#   (64,64,k=8) winner measures 102.05 MiB scoped demand; a 100 MiB
#   ceiling rejects it at compile time (the planner's _fits_vmem estimate
#   runs ~20 MiB below Mosaic's true stack demand).
# - band_budget 12 MiB: 6 MiB caps 32768^2 bf16 at 69 Gpts/s (16-row
#   tiles, 3x halo overhead); 12 MiB doubles it to 135 Gpts/s.
# - vpu_ops 2.2e12: backed out of overhead-corrected on-chip runs (rolled
#   col-tiled bf16 32768^2 at 512x4096 = 1.89e11 pts/s x ~12.4 ops/pt
#   ~= 2.3e12; thin-band 4096^2 f32 ~= 2.0e12; midpoint).
# - ops_rate_3d 2.86e12: fit from the 512^3 sweep with ADDITIVE
#   compute+bandwidth cost (max() mispicked k=2 at 68% roofline over k=8
#   at 112%); (R=64,M=64) k=4/k=8 rates match within 1%.
# - coltiled_band_cap 10 MiB: bands past it send Mosaic compiles from
#   ~1 min (256-row tiles) to 5 min (512) to >12 min (1024 rows).
_chip = machine.current

# per-pass fusion cap: halo rows (and compile-time unroll) stay bounded;
# measured throughput is flat past 16. Architectural (dependency-cone /
# unroll bound), not a per-chip rate — stays module-level.
_KMAX_2D = 32
# 3D per-pass fusion cap: the (row,mid)-tiled kernel's band pays a 2k
# margin on BOTH non-lane axes, so deep unrolls blow the VMEM band budget
# much earlier than in 2D — the _plan_3d search never considers k > 8
_KMAX_3D = 8


def _sublane(dtype) -> int:
    return 16 if jnp.dtype(dtype) == jnp.bfloat16 else 8

def _round_up(x: int, mult: int) -> int:
    return ((x + mult - 1) // mult) * mult

_FORCE_COMPILED = False


def _interpret() -> bool:
    if _FORCE_COMPILED:
        return False
    return jax.default_backend() != "tpu"


import contextlib  # noqa: E402


@contextlib.contextmanager
def force_compiled_kernels():
    """Trace Pallas calls as real Mosaic custom calls even off-TPU.

    For AOT *topology* compiles: ``jax.experimental.topologies`` lets a
    CPU-only host compile a genuine multi-chip TPU executable (the Mosaic
    compiler ships with libtpu and needs no attached device), which is
    how benchmarks/topology_schedule.py extracts real multi-chip TPU
    schedules — async collective-permute pairs and all — during tunnel
    outages. Clears jit caches on entry/exit: interpret-mode tracings of
    the same call signature share cache keys with compiled ones."""
    global _FORCE_COMPILED
    jax.clear_caches()
    _FORCE_COMPILED = True
    try:
        yield
    finally:
        _FORCE_COMPILED = False
        jax.clear_caches()


# --------------------------------------------------------------------------
# 2D: halo-slab BlockSpecs, rotate shifts, masked multiplicative update
# --------------------------------------------------------------------------


def _halo_2d(ksteps: int, dtype) -> int:
    """Halo slab height: >= ksteps (dependency cone), sublane-aligned."""
    return _round_up(max(ksteps, 1), _sublane(dtype))


# thin-band deep-unroll compile cap (round 4): the 32-step unrolled thin
# kernel on a ~10 MiB band (8320-wide rows, the 8192-local shard family)
# sent Mosaic/LLVM into a >36-min compile, observed live and killed,
# while narrow bands (5.4 MiB, 4224-wide — the headline 4096^2 shape)
# compile at k=32 in ~1 min on chip. Above this band size, thin passes
# chunk at 16 instead of _KMAX_2D. Per-k curves:
# benchmarks/compile_bisect_topology*.json (the bisect pins
# local_kernel="pallas" — off-TPU "auto" measures the XLA program).
_THIN_DEEP_BAND_CAP_BYTES = 6 * 1024 * 1024


def _thin_chunk_cap(n_pad: int, dtype_str) -> int:
    """Max per-pass unroll for the thin-band kernel at this row width —
    the compile-sanity analog of the chip table's coltiled band cap."""
    kpad = _halo_2d(_KMAX_2D, dtype_str)
    tile = _tile_2d(n_pad, kpad)
    band = (tile + 2 * kpad) * n_pad * 4
    return 16 if band > _THIN_DEEP_BAND_CAP_BYTES else _KMAX_2D


def effective_chunk_2d(shape, dtype_str, ksteps: int | None = None) -> int:
    """Per-pass chunk depth of the kernel ``_plan_2d`` SELECTS at a
    LOGICAL runtime shape — the shape the kernel will actually see,
    ghosts included. The one derivation callers outside the planner (the
    sharded fuse chooser, the compile guard) may use: re-deriving the
    padding recipe in another module is how the round-5 near-threshold
    bug happened (cap computed on the unpadded width while the kernel
    ran on the ghost-padded one), and hardcoding the THIN cap would pin
    the exchange depth to the wrong kernel when the planner picks the
    coltiled body (its plan carries its own kchunk)."""
    plan = _plan_2d(tuple(shape), dtype_str,
                    _KMAX_2D if ksteps is None else ksteps)
    return plan[1] if plan[0] == "thin" else plan[-1]


def _tile_2d(n_pad: int, kpad: int) -> int:
    """Row-tile height: a multiple of kpad (so halo blocks index evenly),
    sized to keep the (tile + 2*kpad)-row band near the budget (the band is
    held in the f32 accumulation dtype regardless of storage dtype)."""
    cap = _chip().band_budget_bytes // (n_pad * 4) - 2 * kpad
    tile = min(256, max(cap, kpad))
    return max(kpad, (tile // kpad) * kpad)


def cost_thin_2d(n_pad: int, kchunk: int, dtype_str, chip) -> float:
    """Modeled seconds per point-step for the thin-band kernel at chunk
    depth ``kchunk`` — additive compute + bandwidth (measured: the two
    don't overlap enough for max(); see the ops_rate_3d note). THE cost
    model ``_plan_2d`` ranks with, exposed at module level so
    ``heat_tpu.calibrate`` inverts the planner's actual model (not a
    hand-copied formula that drifts)."""
    item = jnp.dtype(dtype_str).itemsize
    kpad = _halo_2d(kchunk, dtype_str)
    tile = _tile_2d(n_pad, kpad)
    compute = 11.0 * (tile + 2 * kpad) / tile / chip.vpu_ops_per_s
    bw = (2.0 * tile + 2 * kpad) * item / (tile * kchunk) / chip.hbm_bytes_per_s
    return compute + bw


def cost_3d(R: int, M: int, k: int, dtype_str, chip) -> float:
    """Modeled seconds per COMPUTED point-step for the (row, mid)-tiled 3D
    kernel at geometry (R, M, k) — callers apply the alignment-padding
    waste factor for logical points. Shared by ``_plan_3d`` and
    ``heat_tpu.calibrate`` (same no-drift contract as cost_thin_2d)."""
    item = jnp.dtype(dtype_str).itemsize
    km = _round_up(k, _sublane(dtype_str))
    band = (R + 2 * k) * (M + 2 * km)
    tile = R * M
    compute = 13.0 * band / tile / chip.ops_rate_3d
    bw = (band + tile) * item / (tile * k) / chip.hbm_bytes_per_s
    return compute + bw


def _make_kernel_2d(r: float, tile: int, kpad: int, n_pad: int, ksteps: int):
    """Kernel body. ``bounds_ref`` is an SMEM (1,4) i32 array
    [row_lo, row_hi, col_lo, col_hi]: cells with index <= lo or >= hi on
    either axis are frozen. For a plain solve that is the boundary ring
    (0, m-1, 0, n-1); the sharded backend passes per-shard values so only
    global-domain edges freeze (see ftcs_multistep_bounded_pallas)."""
    rows = tile + 2 * kpad

    def kernel(bounds_ref, prev_ref, cur_ref, next_ref, out_ref):
        i = pl.program_id(0)
        store_dt = out_ref.dtype
        acc_dt = accum_dtype_for(store_dt)
        band = jnp.concatenate(
            [prev_ref[:], cur_ref[:], next_ref[:]], axis=0
        ).astype(acc_dt)
        grow = i * tile - kpad + jax.lax.broadcasted_iota(
            jnp.int32, (rows, n_pad), 0
        )
        gcol = jax.lax.broadcasted_iota(jnp.int32, (rows, n_pad), 1)
        frozen = (
            (grow <= bounds_ref[0, 0]) | (grow >= bounds_ref[0, 1])
            | (gcol <= bounds_ref[0, 2]) | (gcol >= bounds_ref[0, 3])
        )
        maskr = jnp.where(frozen, 0.0, r).astype(acc_dt)

        for _ in range(ksteps):  # static unroll
            up = pltpu.roll(band, 1, 0)
            dn = pltpu.roll(band, rows - 1, 0)
            lf = pltpu.roll(band, 1, 1)
            rt = pltpu.roll(band, n_pad - 1, 1)
            # solo band is NaN-free by construction (no foreign lanes);
            # the multiplicative freeze is the reference's interior guard
            # heat-tpu: allow[mosaic-kernel-safety] solo NaN-free freeze
            band = band + maskr * (up + dn + lf + rt - 4.0 * band)
        out_ref[:] = band[kpad : kpad + tile].astype(store_dt)

    return kernel


@functools.partial(jax.jit, static_argnames=("r", "ksteps"))
def _pallas_2d(T: jax.Array, r: float, ksteps: int,
               bounds: jax.Array | None = None) -> jax.Array:
    """``ksteps`` FTCS steps on an arbitrary 2D array, freezing cells at or
    beyond ``bounds`` (default: the boundary ring — "edges" semantics).
    ksteps must not exceed _KMAX_2D (callers chunk; see _multistep)."""
    assert ksteps <= _KMAX_2D, (
        f"ksteps={ksteps} exceeds _KMAX_2D={_KMAX_2D}; chunk via _multistep "
        f"(unbounded fusion inflates compile time and VMEM)")
    m, n = T.shape
    if bounds is None:
        bounds = jnp.asarray([[0, m - 1, 0, n - 1]], jnp.int32)
        # with the boundary ring frozen, garbage in the clamped out-of-range
        # halo blocks of the first/last grid step is only read by frozen
        # rows; custom bounds callers own a discard margin >= ksteps instead
    bounds = bounds.reshape(1, 4).astype(jnp.int32)
    n_pad = _round_up(max(n, 128), 128)
    kpad = _halo_2d(ksteps, T.dtype)
    tile = _tile_2d(n_pad, kpad)
    assert ksteps <= kpad <= tile and tile % kpad == 0
    m_pad = _round_up(max(m, tile), tile)
    padded = (m_pad != m) or (n_pad != n)
    Tp = jnp.pad(T, ((0, m_pad - m), (0, n_pad - n))) if padded else T
    grid = (m_pad // tile,)
    ratio = tile // kpad
    nhblk = m_pad // kpad
    smem = pl.BlockSpec((1, 4), lambda i: (0, 0), memory_space=pltpu.SMEM)
    halo = lambda imap: pl.BlockSpec((kpad, n_pad), imap, memory_space=pltpu.VMEM)
    main = lambda imap: pl.BlockSpec((tile, n_pad), imap, memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        _make_kernel_2d(float(r), tile, kpad, n_pad, ksteps),
        out_shape=jax.ShapeDtypeStruct(Tp.shape, Tp.dtype),
        grid=grid,
        in_specs=[
            smem,
            halo(lambda i: (jnp.maximum(i * ratio - 1, 0), 0)),
            main(lambda i: (i, 0)),
            halo(lambda i: (jnp.minimum((i + 1) * ratio, nhblk - 1), 0)),
        ],
        out_specs=main(lambda i: (i, 0)),
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=_chip().vmem_limit_bytes,
        ),
        cost_estimate=pl.CostEstimate(
            flops=9 * (tile + 2 * kpad) * grid[0] * n_pad * ksteps,
            bytes_accessed=(2 * m_pad + 2 * kpad * grid[0]) * n_pad
            * Tp.dtype.itemsize,
            transcendentals=0,
        ),
        interpret=_interpret(),
    )(bounds, Tp, Tp, Tp)
    return out[:m, :n] if padded else out


# --------------------------------------------------------------------------
# two-axis tiling (3x3 halo-block scheme): shared planning machinery
#
# The thin-band 2D kernel above tiles rows only; its band must span the full
# row width, so very wide arrays (bf16 32768^2: 128 KiB/row) afford few rows
# per band and the halo fraction balloons (round-1: 1.5x redundant compute).
# The 3D kernel has the same disease worse: whole (mid, n) planes as halo.
# Cure for both: tile a second axis too, fetching a 3x3 neighborhood of
# blocks (4 corners + 4 edges + center) so halo volume scales with the tile
# surface. Mini-steps use shrinking slices (the valid region loses one cell
# per side per step) instead of full-band rotates — on the non-lane axes a
# shifted slice is an addressing offset, not a data permute.
# --------------------------------------------------------------------------


# cost-model rates and caps come from the per-chip table (see the
# derivation block at the top of this module); the planner caches below
# embed them, so machine.override() must flush those caches — they
# register with machine.register_cache at the bottom of this module


def _fits_vmem(band_cells: int, tile_cells: int, item: int) -> bool:
    # VMEM feasibility for the 3x3 scheme: double-buffered in/out blocks in
    # the storage dtype + the assembled band and its mini-step temporaries
    # in the accumulation dtype must fit under the Mosaic limit w/ headroom
    pipeline = 2 * (band_cells + tile_cells) * item
    working = 3 * band_cells * 4  # band + ~2 live temps, accumulation dtype
    return pipeline + working <= _chip().vmem_fit_bytes


def _grid_specs_3x3(shape_blocks, halo_blocks, nblocks, extra_dims):
    """BlockSpecs for the 3x3 neighborhood fetch over a (gi, gj) grid.

    ``shape_blocks`` = (tile_i, tile_j), ``halo_blocks`` = (k_i, k_j) block
    sizes; ``nblocks`` = (#halo-granularity blocks per axis) for clamping;
    ``extra_dims`` = trailing full-extent dims (3D: the lane axis).
    """
    (Ti, Tj), (ki, kj) = shape_blocks, halo_blocks
    ri, rj = Ti // ki, Tj // kj
    ni, nj = nblocks
    ext = tuple(extra_dims)
    zeros = (0,) * len(ext)

    def icl(i):
        return jnp.clip(i, 0, ni - 1)

    def jcl(j):
        return jnp.clip(j, 0, nj - 1)

    def bs(shape, imap):
        return pl.BlockSpec(shape + ext, imap, memory_space=pltpu.VMEM)

    return [
        bs((ki, kj), lambda i, j: (icl(i * ri - 1), jcl(j * rj - 1)) + zeros),
        bs((ki, Tj), lambda i, j: (icl(i * ri - 1), j) + zeros),
        bs((ki, kj), lambda i, j: (icl(i * ri - 1), jcl((j + 1) * rj)) + zeros),
        bs((Ti, kj), lambda i, j: (i, jcl(j * rj - 1)) + zeros),
        bs((Ti, Tj), lambda i, j: (i, j) + zeros),
        bs((Ti, kj), lambda i, j: (i, jcl((j + 1) * rj)) + zeros),
        bs((ki, kj), lambda i, j: (icl((i + 1) * ri), jcl(j * rj - 1)) + zeros),
        bs((ki, Tj), lambda i, j: (icl((i + 1) * ri), j) + zeros),
        bs((ki, kj), lambda i, j: (icl((i + 1) * ri), jcl((j + 1) * rj)) + zeros),
    ], bs((Ti, Tj), lambda i, j: (i, j) + zeros)


def _assemble_band(refs, acc_dt):
    """Concatenate the 3x3 fetched blocks into one band, rows x mids."""
    rows = [jnp.concatenate([refs[3 * g][:], refs[3 * g + 1][:],
                             refs[3 * g + 2][:]], axis=1) for g in range(3)]
    return jnp.concatenate(rows, axis=0).astype(acc_dt)


# --------------------------------------------------------------------------
# 3D: (row, mid)-tiled kernel, lane axis full-extent
# --------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _plan_3d(shape, dtype_str, ksteps: int):
    """Choose ((m_pad, mid_pad, n_pad), R, M, kchunk) for the tiled 3D
    kernel: minimize (compute + bandwidth) per LOGICAL point-step —
    additive, not max(): measured, the two don't overlap enough (see
    machine.ops_rate_3d derivation note at the top of this
    module) — scaled by the alignment-padding waste factor.
    Ops/pt-step ~ 13 x band/tile area ratio (2 lane rotates + 2
    sublane-shifted reads + ~9 arithmetic; row-axis neighbor reads are
    addressing offsets)."""
    m, mid, n = shape
    sub = _sublane(dtype_str)
    n_pad = _round_up(max(n, 128), 128)
    item = jnp.dtype(dtype_str).itemsize
    chip = _chip()
    best = None
    for k in range(1, min(max(ksteps, 1), _KMAX_3D) + 1):
        km = _round_up(k, sub)
        for R in (8, 16, 32, 48, 64, 96, 128):
            if R % k:
                R = _round_up(R, k)
            R = min(R, _round_up(max(m, k), k))
            for M in (sub, 32, 64, 96, 128, 192):
                M = _round_up(M, km)
                M = min(M, _round_up(max(mid, km), km))
                band = (R + 2 * k) * (M + 2 * km)
                tile = R * M
                if not _fits_vmem(band * n_pad, tile * n_pad, item):
                    continue
                # cost per LOGICAL point: alignment padding is computed then
                # discarded (R=70 on a 512-row grid pads 9% dead rows)
                pad = (_round_up(max(m, R), R) * _round_up(max(mid, M), M)
                       / max(m * mid, 1))
                # ADDITIVE cost (cost_3d; measured: compute and HBM
                # streaming do not overlap enough for max() — see the
                # ops_rate_3d note); ties break toward deeper fusion
                key = (cost_3d(R, M, k, dtype_str, chip) * pad, band, -k)
                if best is None or key < best[0]:
                    best = (key, R, M, k)
    if best is None:
        # lane extent so large no (R, M, k) band fits VMEM: no kernel plan —
        # pallas_available() reports False and callers take the XLA path
        return None
    _, R, M, k = best
    m_pad = _round_up(max(m, R), R)
    mid_pad = _round_up(max(mid, M), M)
    return (m_pad, mid_pad, n_pad), R, M, k


def _make_kernel_3d(r: float, R: int, M: int, k: int, km: int, n_pad: int,
                    ksteps: int):
    """(row, mid)-tiled 3D body; ``bounds_ref`` is SMEM (1,6) i32
    [row_lo, row_hi, mid_lo, mid_hi, col_lo, col_hi] (see 2D). Mini-steps
    shrink the valid region by one cell per side (rows/mids); lane
    neighbors are wrap rotates whose band-edge garbage is confined the
    same way as the thin-band kernel's."""
    rows = R + 2 * k
    mids = M + 2 * km

    def kernel(bounds_ref, *refs):
        i = pl.program_id(0)
        j = pl.program_id(1)
        out_ref = refs[-1]
        store_dt = out_ref.dtype
        acc_dt = accum_dtype_for(store_dt)
        band = _assemble_band(refs[:9], acc_dt)

        bshape = (rows, mids, n_pad)
        grow = i * R - k + jax.lax.broadcasted_iota(jnp.int32, bshape, 0)
        gmid = j * M - km + jax.lax.broadcasted_iota(jnp.int32, bshape, 1)
        gcol = jax.lax.broadcasted_iota(jnp.int32, bshape, 2)
        frozen = (
            (grow <= bounds_ref[0, 0]) | (grow >= bounds_ref[0, 1])
            | (gmid <= bounds_ref[0, 2]) | (gmid >= bounds_ref[0, 3])
            | (gcol <= bounds_ref[0, 4]) | (gcol >= bounds_ref[0, 5])
        )
        maskr = jnp.where(frozen, 0.0, r).astype(acc_dt)

        cur = band
        for s in range(ksteps):  # static unroll, shrinking shapes
            # rolls are on the full lane axis only; the shrink is on the
            # non-lane axes with alignment held by construction, proven
            # by the chipless v5e compile labs (benchmarks/chip_check)
            # heat-tpu: allow[mosaic-kernel-safety] lane-axis-only rolls
            lf = pltpu.roll(cur, 1, 2)
            rt = pltpu.roll(cur, n_pad - 1, 2)
            ctr = cur[1:-1, 1:-1, :]
            lap = (cur[2:, 1:-1, :] + cur[:-2, 1:-1, :]
                   + cur[1:-1, 2:, :] + cur[1:-1, :-2, :]
                   + lf[1:-1, 1:-1, :] + rt[1:-1, 1:-1, :] - 6.0 * ctr)
            m_s = maskr[s + 1: rows - s - 1, s + 1: mids - s - 1, :]
            # solo band is NaN-free by construction (reference form)
            # heat-tpu: allow[mosaic-kernel-safety] solo NaN-free freeze
            cur = ctr + m_s * lap
        out_ref[:] = jax.lax.slice(
            cur, (k - ksteps, km - ksteps, 0),
            (k - ksteps + R, km - ksteps + M, n_pad)).astype(store_dt)

    return kernel


@functools.partial(jax.jit,
                   static_argnames=("r", "ksteps", "kplan", "logical_shape"))
def _pallas_3d_aligned(Tp: jax.Array, r: float, ksteps: int, kplan: int,
                       logical_shape, bounds: jax.Array | None = None) -> jax.Array:
    """``ksteps`` FTCS steps on an already tile-aligned 3D array whose
    logical (unpadded) extents are ``logical_shape``. ``kplan`` fixes the
    block geometry across chunks; a remainder pass may run ksteps < kplan.
    Callers chunk — see _multistep."""
    (m_pad, mid_pad, n_pad), R, M, kp = _plan_3d(
        logical_shape, str(Tp.dtype), kplan)
    assert Tp.shape == (m_pad, mid_pad, n_pad), (Tp.shape, m_pad, mid_pad, n_pad)
    assert kp == kplan and ksteps <= kplan
    sub = _sublane(Tp.dtype)
    km = _round_up(kplan, sub)
    m, mid, n = logical_shape
    if bounds is None:
        bounds = jnp.asarray([[0, m - 1, 0, mid - 1, 0, n - 1]], jnp.int32)
    bounds = bounds.reshape(1, 6).astype(jnp.int32)
    grid = (m_pad // R, mid_pad // M)
    smem = pl.BlockSpec((1, 6), lambda i, j: (0, 0), memory_space=pltpu.SMEM)
    in_specs, out_spec = _grid_specs_3x3(
        (R, M), (kplan, km), (m_pad // kplan, mid_pad // km), (n_pad,))
    band = (R + 2 * kplan) * (M + 2 * km)
    return pl.pallas_call(
        _make_kernel_3d(float(r), R, M, kplan, km, n_pad, ksteps),
        out_shape=jax.ShapeDtypeStruct(Tp.shape, Tp.dtype),
        grid=grid,
        in_specs=[smem] + in_specs,
        out_specs=out_spec,
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=_chip().vmem_limit_bytes,
        ),
        cost_estimate=pl.CostEstimate(
            flops=13 * band * n_pad * grid[0] * grid[1] * ksteps,
            bytes_accessed=(band + R * M) * n_pad * grid[0] * grid[1]
            * Tp.dtype.itemsize,
            transcendentals=0,
        ),
        interpret=_interpret(),
    )(bounds, *([Tp] * 9))


# --------------------------------------------------------------------------
# 2D wide arrays: (row, col)-tiled kernel
# --------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _plan_2d(shape, dtype_str, ksteps: int):
    """Choose the 2D kernel: ('thin', kchunk) — the row-banded kernel above
    (best when full rows are cheap) — or ('coltiled', R, C, kr, kc, kchunk)
    when the array is wide enough that full-width bands would starve the
    tile of rows (bf16 32768^2: 1.5x redundant compute in round 1)."""
    m, n = shape
    item = jnp.dtype(dtype_str).itemsize
    sub = _sublane(dtype_str)
    n_pad = _round_up(max(n, 128), 128)
    chip = _chip()

    def cost_thin(k):
        # additive model (cost_thin_2d): measured thin 4096^2 f32 =
        # 6.2e-12 s/pt-step; additive predicts 6.16e-12 where max() says
        # 5.63e-12
        return cost_thin_2d(n_pad, k, dtype_str, chip)

    k_thin = min(max(ksteps, 1), _thin_chunk_cap(n_pad, dtype_str))
    best_col = None
    for k in (4, 8, 16, 32):
        if k > max(ksteps, 1):
            continue
        kr = _round_up(k, sub)
        kc = 128
        for C in (2048, 4096, 8192):
            if C >= n_pad:  # col-tiling a narrow array is pure overhead
                continue
            for R in (128, 256, 512, 1024):
                R = _round_up(R, kr)
                R = min(R, _round_up(max(m, kr), kr))
                band = (R + 2 * kr) * (C + 2 * kc)
                tile = R * C
                if not _fits_vmem(band, tile, item):
                    continue
                # compile sanity: bands past the cap send Mosaic compiles
                # from ~1 min to 5 min (512 rows) to >12 min (1024 rows);
                # the modeled gain past it is <4%
                if band * 4 > chip.coltiled_band_cap_bytes:
                    continue
                compute = 11.0 * band / tile / chip.vpu_ops_per_s
                bw = (band + tile) * item / (tile * k) / chip.hbm_bytes_per_s
                key = (compute + bw, band, -k)
                if best_col is None or key < best_col[0]:
                    best_col = (key, R, C, kr, kc, k)
    # the thin-band kernel is the measured-proven default; switch only for
    # a clear (>10%) modeled win
    if best_col is not None and best_col[0][0] < 0.9 * cost_thin(k_thin):
        _, R, C, kr, kc, k = best_col
        return ("coltiled", R, C, kr, kc, k)
    return ("thin", k_thin)


def _make_kernel_2d_coltiled(r: float, R: int, C: int, kr: int, kc: int,
                             ksteps: int):
    """(row, col)-tiled 2D body: the thin kernel's full-band wrap rotates +
    masked multiplicative update, on a two-axis tile. Every op is
    lane/sublane-aligned. (A shrinking-slices body — neighbor reads as
    addressing offsets — was measured to send Mosaic into multi-minute
    compiles at deep unrolls: sublane/lane-misaligned slice offsets force
    per-step relayouts. Wrap-rotate band-edge corruption travels one cell
    per mini-step and stays inside the kr/kc margins, the same invariant as
    the thin kernel's.)"""
    rows = R + 2 * kr
    cols = C + 2 * kc

    def kernel(bounds_ref, *refs):
        i = pl.program_id(0)
        j = pl.program_id(1)
        out_ref = refs[-1]
        store_dt = out_ref.dtype
        acc_dt = accum_dtype_for(store_dt)
        band = _assemble_band(refs[:9], acc_dt)

        bshape = (rows, cols)
        grow = i * R - kr + jax.lax.broadcasted_iota(jnp.int32, bshape, 0)
        gcol = j * C - kc + jax.lax.broadcasted_iota(jnp.int32, bshape, 1)
        frozen = (
            (grow <= bounds_ref[0, 0]) | (grow >= bounds_ref[0, 1])
            | (gcol <= bounds_ref[0, 2]) | (gcol >= bounds_ref[0, 3])
        )
        maskr = jnp.where(frozen, 0.0, r).astype(acc_dt)

        for _ in range(ksteps):  # static unroll
            up = pltpu.roll(band, 1, 0)
            dn = pltpu.roll(band, rows - 1, 0)
            lf = pltpu.roll(band, 1, 1)
            rt = pltpu.roll(band, cols - 1, 1)
            # solo band is NaN-free by construction (reference form)
            # heat-tpu: allow[mosaic-kernel-safety] solo NaN-free freeze
            band = band + maskr * (up + dn + lf + rt - 4.0 * band)
        out_ref[:] = band[kr: kr + R, kc: kc + C].astype(store_dt)

    return kernel


@functools.partial(jax.jit,
                   static_argnames=("r", "ksteps", "R", "C", "kr", "kc",
                                    "logical_shape"))
def _pallas_2d_coltiled(Tp: jax.Array, r: float, ksteps: int, R: int, C: int,
                        kr: int, kc: int, logical_shape,
                        bounds: jax.Array | None = None) -> jax.Array:
    m_pad, n_pad = Tp.shape
    m, n = logical_shape
    assert m_pad % R == 0 and n_pad % C == 0
    assert R % kr == 0 and C % kc == 0 and ksteps <= min(kr, kc)
    if bounds is None:
        bounds = jnp.asarray([[0, m - 1, 0, n - 1]], jnp.int32)
    bounds = bounds.reshape(1, 4).astype(jnp.int32)
    grid = (m_pad // R, n_pad // C)
    smem = pl.BlockSpec((1, 4), lambda i, j: (0, 0), memory_space=pltpu.SMEM)
    in_specs, out_spec = _grid_specs_3x3(
        (R, C), (kr, kc), (m_pad // kr, n_pad // kc), ())
    band = (R + 2 * kr) * (C + 2 * kc)
    return pl.pallas_call(
        _make_kernel_2d_coltiled(float(r), R, C, kr, kc, ksteps),
        out_shape=jax.ShapeDtypeStruct(Tp.shape, Tp.dtype),
        grid=grid,
        in_specs=[smem] + in_specs,
        out_specs=out_spec,
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=_chip().vmem_limit_bytes,
        ),
        cost_estimate=pl.CostEstimate(
            flops=11 * band * grid[0] * grid[1] * ksteps,
            bytes_accessed=(band + R * C) * grid[0] * grid[1]
            * Tp.dtype.itemsize,
            transcendentals=0,
        ),
        interpret=_interpret(),
    )(bounds, *([Tp] * 9))


def plan_summary(shape, dtype_str: str, ksteps: int) -> str:
    """One-line human description of the kernel plan for ``shape`` — the
    geometry derived by the SAME rules the kernels use (keep this next to
    the planners; CLI/`plan` must not re-derive it)."""
    shape = tuple(shape)
    if not pallas_available(shape, jnp.dtype(dtype_str)):
        return ("XLA fused stencil (no Pallas plan for this shape/dtype — "
                "f64 or oversized lane extent)")
    if len(shape) == 2:
        p = _plan_2d(shape, dtype_str, ksteps)
        if p[0] == "thin":
            k = p[1]
            kpad = _halo_2d(k, dtype_str)
            n_pad = _round_up(max(shape[1], 128), 128)
            tile = _tile_2d(n_pad, kpad)
            return (f"thin-band 2D (rows banded, full-width); tile {tile} "
                    f"rows, halo {kpad}, per-pass chunk {k}, band "
                    f"{(tile + 2 * kpad) * n_pad * 4 / 2**20:.1f} MiB, "
                    f"halo-compute overhead {(tile + 2 * kpad) / tile:.2f}x")
        _, R, C, kr, kc, k = p
        band = (R + 2 * kr) * (C + 2 * kc)
        return (f"col-tiled 2D 3x3-halo; tile {R}x{C}, halo {kr}x{kc}, "
                f"per-pass chunk {k}, band {band * 4 / 2**20:.1f} MiB, "
                f"halo-compute "
                f"overhead {band / (R * C):.2f}x")
    (_, _, n_pad), R, M, k = _plan_3d(shape, dtype_str, min(ksteps, 8))
    km = _round_up(k, _sublane(dtype_str))
    band = (R + 2 * k) * (M + 2 * km)
    return (f"(row,mid)-tiled 3D 3x3-halo; tile {R}x{M}x{n_pad}, per-pass "
            f"chunk {k}, band {band * n_pad * 4 / 2**20:.1f} MiB, "
            f"halo-compute "
            f"overhead {band / (R * M):.2f}x")


# --------------------------------------------------------------------------
# public entry points (with transparent XLA fallback)
# --------------------------------------------------------------------------


def pallas_available(shape, dtype) -> bool:
    """Arbitrary 2D/3D shapes are supported via internal alignment padding;
    f64 (no TPU VPU support) falls back to XLA, as do 3D shapes whose lane
    extent is so large no tiled band fits VMEM (no plan exists)."""
    shape = tuple(shape)
    if jnp.dtype(dtype) == jnp.float64:
        return False
    if len(shape) == 3:
        return _plan_3d(shape, str(jnp.dtype(dtype)), 8) is not None
    return len(shape) == 2


def _multistep(T: jax.Array, r: float, ksteps: int,
               bounds: jax.Array | None = None) -> jax.Array:
    """Dispatch ksteps fused frozen-boundary steps, chunking fusion down to
    what each kernel's dependency-cone bound affords."""
    logical = tuple(T.shape)
    if T.ndim == 2:
        plan = _plan_2d(logical, str(T.dtype), ksteps)
        if plan[0] == "thin":
            n_pad = _round_up(max(logical[1], 128), 128)
            cap = _thin_chunk_cap(n_pad, str(T.dtype))
            done = 0
            while done < ksteps:
                k = min(cap, ksteps - done)
                T = _pallas_2d(T, r=float(r), ksteps=k, bounds=bounds)
                done += k
            return T
        _, R, C, kr, kc, kchunk = plan
        aligned = (_round_up(max(logical[0], R), R),
                   _round_up(max(logical[1], C), C))
        if aligned != logical:
            T = jnp.pad(T, [(0, p - s) for p, s in zip(aligned, logical)])
        done = 0
        while done < ksteps:
            k = min(kchunk, ksteps - done)
            T = _pallas_2d_coltiled(T, r=float(r), ksteps=k, R=R, C=C,
                                    kr=kr, kc=kc, logical_shape=logical,
                                    bounds=bounds)
            done += k
        if aligned != logical:
            T = T[: logical[0], : logical[1]]
        return T
    plan = _plan_3d(logical, str(T.dtype), ksteps)
    assert plan is not None, (
        f"no 3D kernel plan for {logical} {T.dtype} (gate on "
        f"pallas_available before calling)")
    aligned, _, _, kchunk = plan
    if aligned != logical:
        T = jnp.pad(T, [(0, p - s) for p, s in zip(aligned, logical)])
    done = 0
    while done < ksteps:
        k = min(kchunk, ksteps - done)
        T = _pallas_3d_aligned(T, r=float(r), ksteps=k, kplan=kchunk,
                               logical_shape=logical, bounds=bounds)
        done += k
    if aligned != logical:
        T = T[: logical[0], : logical[1], : logical[2]]
    return T


def ftcs_multistep_bounded_pallas(T: jax.Array, r: float, ksteps: int,
                                  bounds: jax.Array) -> jax.Array:
    """``ksteps`` fused FTCS steps freezing cells at or beyond ``bounds``
    (i32 [lo, hi] pair per dimension, flattened; may be traced values —
    e.g. computed from ``lax.axis_index`` inside shard_map).

    Contract: cells NOT frozen by ``bounds`` include array-edge cells whose
    out-of-range neighbors are garbage (wrap rotates / clamped halo blocks),
    so the caller MUST own a discard margin >= ksteps on every non-frozen
    side — exactly the halo-width invariant of the sharded backend's
    communication-avoiding exchange (one width-k exchange buys k steps).
    """
    assert pallas_available(T.shape, T.dtype), (T.shape, T.dtype)
    return _multistep(T, r, ksteps, bounds=jnp.asarray(bounds, jnp.int32))


def ftcs_step_edges_pallas(T: jax.Array, r: float) -> jax.Array:
    """One frozen-boundary FTCS step via the Pallas kernel, with transparent
    XLA fallback for dtypes the kernel doesn't cover."""
    if not pallas_available(T.shape, T.dtype):
        return ftcs_step_edges(T, r)
    return _multistep(T, r, 1)


def ftcs_step_ghost_pallas(T: jax.Array, r: float, bc_value) -> jax.Array:
    """Ghost-BC step via Pallas: pad with the bc ring, run the edges kernel
    on the padded array (its frozen ring IS the ghost ring), crop."""
    return ftcs_multistep_ghost_pallas(T, r, bc_value, 1)


def ftcs_multistep_edges_pallas(T: jax.Array, r: float, ksteps: int) -> jax.Array:
    """``ksteps`` frozen-boundary FTCS steps in fused kernel passes, with
    sequential XLA fallback where the kernel doesn't apply."""
    if pallas_available(T.shape, T.dtype):
        return _multistep(T, r, ksteps)
    out = T
    for _ in range(ksteps):
        out = ftcs_step_edges(out, r)
    return out


# periodic ("pbc") runs freeze nothing: bounds no cell index can satisfy
_NO_FREEZE = 2**30


def periodic_pad_width(shape, ksteps: int) -> int:
    """Wrap-ring width per chunk of the periodic multistep — the single
    derivation both the kernel dispatch and `plan` report (CLI must not
    re-derive planner geometry)."""
    cap = _KMAX_2D if len(shape) == 2 else 16  # 3D chunks further internally
    # keep the wrap ring within one period (jnp.pad wrap width <= extent)
    return max(1, min(cap, max(ksteps, 1), min(shape)))


def ftcs_multistep_periodic_pallas(T: jax.Array, r: float, ksteps: int) -> jax.Array:
    """``ksteps`` FTCS steps on the torus via the bounded kernel.

    Scheme: wrap-pad a width-k ghost ring (``jnp.pad mode="wrap"`` — the
    periodic analog of the halo exchange, one "message" from the opposite
    edge), run k fused steps with bounds that freeze nothing, crop. The
    wrap ring IS the discard margin the bounded kernel's contract demands,
    and ghost layer L is valid for the first k-L mini-steps — the same
    communication-avoiding invariant as the sharded backend's width-k
    exchange. Chunked so pad/crop overhead stays ~2 passes per _KMAX_2D
    steps.
    """
    if ksteps <= 0:
        return T
    nd = T.ndim
    cap = periodic_pad_width(T.shape, ksteps)
    # gate on EVERY wrap-padded shape the chunk loop will build — the full
    # chunks (cap) and the remainder chunk pad differently, and for 3D a
    # plan for the cap-padded shape does not guarantee one for the smaller
    # remainder shape (_multistep asserts rather than falls back)
    last = ksteps % cap or cap
    widths = {min(cap, ksteps), last}
    if not all(pallas_available(tuple(s + 2 * w for s in T.shape), T.dtype)
               for w in widths):
        out = T
        for _ in range(ksteps):
            out = ftcs_step_periodic(out, r)
        return out
    bounds = jnp.asarray([[-_NO_FREEZE, _NO_FREEZE] * nd], jnp.int32)
    done = 0
    while done < ksteps:
        k = min(cap, ksteps - done)
        padded = jnp.pad(T, k, mode="wrap")
        out = _multistep(padded, r, k, bounds=bounds)
        ctr = tuple(slice(k, -k) for _ in range(nd))
        T = out[ctr]
        done += k
    return T


def ftcs_step_periodic_pallas(T: jax.Array, r: float) -> jax.Array:
    """One periodic FTCS step via the Pallas kernel (XLA roll fallback)."""
    return ftcs_multistep_periodic_pallas(T, r, 1)


def ftcs_multistep_ghost_pallas(T: jax.Array, r: float, bc_value, ksteps: int) -> jax.Array:
    """``ksteps`` ghost-BC steps fused: the padded array's frozen outer ring
    IS the ghost ring, which never changes — so the edges multistep kernel on
    the padded array is exactly k ghost-BC steps."""
    if pallas_available(T.shape, T.dtype):
        padded = jnp.pad(T, 1, mode="constant",
                         constant_values=jnp.asarray(bc_value, T.dtype))
        out = _multistep(padded, r, ksteps)
        ctr = tuple(slice(1, -1) for _ in range(T.ndim))
        return out[ctr]
    out = T
    for _ in range(ksteps):
        out = ftcs_step_ghost(out, r, bc_value)
    return out


# --------------------------------------------------------------------------
# multi-lane serving kernels: the lane axis as a grid dimension
#
# The serving engine (serve/engine.py) steps up to L independent requests
# as one stacked (L, B+2, ...) array. Its reference chunk program is a
# masked *vmapped XLA* stencil; the kernels below are the Pallas port: the
# lane axis becomes grid dimension 0 over the existing 2D halo-slab / 3D
# 3x3-banded plans, and ONE kernel fuses (a) the per-lane interior mask
# (cells outside [lo, n-1-lo] of the per-lane request side n, SMEM-
# resident like bounds_ref), (b) the per-lane countdown gating (a lane
# whose remaining count ran out keeps its field, step-granular),
# (c) the per-lane isfinite health reduction, and (d) the per-lane
# numerics stats (ISSUE 15: final-mini-step residual, request-region
# min/max, total heat — SMEM-accumulated next to the finite bit) — so
# lane health AND solution-quality telemetry cost zero extra passes
# over the stack instead of separate post-chunk sweeps.
#
# Bit-identity with the XLA lane program is a hard contract (the XLA path
# stays the serving oracle): every mini-step replicates the exact
# arithmetic of serve/engine._lane_step — laplacian summed in
# ops.stencil.laplacian_interior's left-to-right order, update applied by
# SELECT (jnp.where), not the solo kernels' multiply-mask (0 * NaN would
# leak a blowing-up lane's NaN into its frozen ring where the oracle
# keeps old values), and the result rounded to the storage dtype EVERY
# mini-step (the fori_loop rounds per step; the solo kernels' round-once
# bf16 mode would diverge). Per-lane frozen bounds in buffer coords:
# cells <= lo or >= n+1-lo freeze, lo = 0 (ghost) or 1 (edges) — the
# margin ring, the unused bucket corner, and the kernel's alignment
# padding all land outside, so garbage there is never read by live cells
# (reads reach one cell per mini-step; live cells sit >= 1 cell inside).
# --------------------------------------------------------------------------


# fixed per-pass fusion depth of the 2D lane kernel: matches the serve
# default --chunk 16 (one pass per chunk), stays within every dtype's
# _halo_2d alignment, and — unlike the solo planner's shape-dependent
# chunk — keeps the padded STATE layout independent of the engine's
# chunk knob (tail programs reuse the steady layout with fewer steps).
_LANE_KP_2D = 16


@functools.lru_cache(maxsize=None)
def _plan_lane_2d(bucket_n: int, dtype_str: str):
    """Geometry for the multi-lane thin-band kernel over (L, m, m) lane
    slabs, m = bucket side + 2 margin: (m_pad, n_pad, tile, kpad, kp), or
    None when no row tile fits the band budget. The row tile is chosen to
    minimize the padded slab height (alignment rows are computed-then-
    frozen waste), tie-breaking toward fewer, larger tiles."""
    m = bucket_n + 2
    n_pad = _round_up(max(m, 128), 128)
    kp = _LANE_KP_2D
    kpad = _halo_2d(kp, dtype_str)
    budget = _chip().band_budget_bytes
    best = None
    t = kpad
    tmax = max(_round_up(m, kpad), kpad)
    while t <= tmax:
        if (t + 2 * kpad) * n_pad * 4 <= budget:
            m_pad = _round_up(max(m, t), t)
            cand = (m_pad, -t)
            if best is None or cand < best[0]:
                best = (cand, t, m_pad)
        t += kpad
    if best is None:
        return None
    _, tile, m_pad = best
    return m_pad, n_pad, tile, kpad, kp


@functools.lru_cache(maxsize=None)
def _plan_lane_3d(bucket_n: int, dtype_str: str):
    """3D lane geometry: the solo (row, mid)-tiled 3x3 plan for one lane
    slab — ((m_pad, mid_pad, n_pad), R, M, kchunk, km), or None when no
    band fits VMEM (the caller falls back to the XLA lane program)."""
    m = bucket_n + 2
    p = _plan_3d((m, m, m), dtype_str, _KMAX_3D)
    if p is None:
        return None
    (m_pad, mid_pad, n_pad), R, M, k = p
    return (m_pad, mid_pad, n_pad), R, M, k, _round_up(k, _sublane(dtype_str))


def lane_state_shape(ndim: int, bucket_n: int, dtype_str: str):
    """Per-lane padded slab shape the lane kernels step in place, or None
    when this (ndim, bucket, dtype) has no kernel plan (f64 — no TPU VPU
    f64 — or a 3D lane extent no band fits VMEM for). The serving engine
    keeps its stacked state in THIS layout for the whole engine lifetime
    (requests load into the [0 : B+2] corner; alignment padding is frozen
    by the per-lane bounds and never read by a live cell), so chunk
    dispatch pays zero per-call pad/crop."""
    if jnp.dtype(dtype_str) == jnp.float64:
        return None
    if ndim == 2:
        p = _plan_lane_2d(bucket_n, dtype_str)
        return None if p is None else (p[0], p[1])
    if ndim == 3:
        p = _plan_lane_3d(bucket_n, dtype_str)
        return None if p is None else p[0]
    return None


def lane_kernel_available(ndim: int, bucket_n: int, dtype_str: str) -> bool:
    """Can the Pallas lane kernels serve this bucket? (The serve knob's
    ``auto`` gate; explicit ``pallas`` on an unavailable bucket is a
    structured fallback, never an error — serve/engine.py.)"""
    return lane_state_shape(ndim, bucket_n, dtype_str) is not None


def _lane_finite_accumulate(fin_ref, lane, first_any, out_tile,
                            lanes: int):
    """Fuse the per-lane health verdict into the stencil pass: AND this
    program's output-tile isfinite verdict into its lane's slot of the
    ONE (1, L) SMEM bit vector every grid instance revisits (block index
    constant, so the block stays resident for the whole grid; Mosaic
    requires SMEM output blocks to span the full array). The very first
    grid instance initializes all L bits; each instance then ANDs via a
    dynamic per-lane SMEM store. bf16 upcasts for the reduction
    (finiteness is preserved exactly). Spelled ``|x| < inf`` rather than
    ``jnp.isfinite`` — false for NaN (any compare with NaN is false) and
    for both infinities — because Mosaic has no ``is_finite`` lowering."""
    ok = (jnp.abs(out_tile.astype(jnp.float32))
          < jnp.float32(float("inf"))).all().astype(jnp.int32)

    @pl.when(first_any)
    def _():
        for idx in range(lanes):  # static unroll: L scalar SMEM stores
            fin_ref[0, idx] = jnp.int32(1)

    fin_ref[0, lane] = jnp.minimum(fin_ref[0, lane], ok)


def _lane_stats_accumulate(stats_ref, lane, first_any, prev_tile,
                           out_tile, region, lanes: int):
    """Fuse the per-lane numerics stats (ISSUE 15) into the stencil pass,
    exactly the shape of ``_lane_finite_accumulate``: each grid instance
    reduces its output tile (float32, the bf16 accumulation discipline)
    under the REQUEST-REGION mask — buffer coords in ``[1, n_lane]``,
    the field including its Dirichlet ring, a different mask from the
    update's ``live`` — and merges four scalars into its lane's column
    of the ONE (4, L) float32 SMEM block: row 0 max|out - prev| over
    the pass's final mini-step (max-merge), row 1 region min
    (min-merge), row 2 region max (max-merge), row 3 region sum
    (add-merge). The first grid instance initializes every slot to the
    merge identities. Cells outside the region contribute the
    identities via select, so alignment padding and the margin never
    leak into a lane's stats."""
    f32 = out_tile.astype(jnp.float32)
    delta = jnp.abs(f32 - prev_tile.astype(jnp.float32))
    inf = jnp.float32(float("inf"))
    resid = jnp.where(region, delta, jnp.float32(0)).max()
    tmin = jnp.where(region, f32, inf).min()
    tmax = jnp.where(region, f32, -inf).max()
    heat = jnp.where(region, f32, jnp.float32(0)).sum()

    @pl.when(first_any)
    def _():
        for idx in range(lanes):  # static unroll: 4L scalar SMEM stores
            stats_ref[0, idx] = jnp.float32(0)
            stats_ref[1, idx] = inf
            stats_ref[2, idx] = -inf
            stats_ref[3, idx] = jnp.float32(0)

    stats_ref[0, lane] = jnp.maximum(stats_ref[0, lane], resid)
    stats_ref[1, lane] = jnp.minimum(stats_ref[1, lane], tmin)
    stats_ref[2, lane] = jnp.maximum(stats_ref[2, lane], tmax)
    stats_ref[3, lane] = stats_ref[3, lane] + heat


def _make_lane_kernel_2d(bc_lo: int, tile: int, kpad: int, n_pad: int,
                         ksteps: int, offset: int, lanes: int):
    """Multi-lane thin-band body: one (lane, row-tile) program instance.
    ``offset`` is the pass's global step index within the chunk — the
    countdown gate compares against the chunk-start ``remaining``."""
    rows = tile + 2 * kpad

    def kernel(r_ref, n_ref, rem_ref, prev_ref, cur_ref, next_ref,
               out_ref, fin_ref, stats_ref):
        lane = pl.program_id(0)
        i = pl.program_id(1)
        store_dt = out_ref.dtype
        acc_dt = accum_dtype_for(store_dt)
        # the band WORKS in the accumulation dtype but holds exactly
        # storage-rounded values: each update is rounded through the
        # storage dtype (the oracle's per-step rounding) and selected in
        # 32 bits (Mosaic has no sub-32-bit select); the final downcast
        # is then exact, so bf16 results stay byte-identical to XLA
        band = jnp.concatenate(
            [prev_ref[:], cur_ref[:], next_ref[:]], axis=1)[0].astype(acc_dt)
        n_l = n_ref[0, lane]
        rem_l = rem_ref[0, lane]
        r_l = r_ref[0, lane].astype(acc_dt)
        grow = i * tile - kpad + jax.lax.broadcasted_iota(
            jnp.int32, (rows, n_pad), 0)
        gcol = jax.lax.broadcasted_iota(jnp.int32, (rows, n_pad), 1)
        hi = n_l + 1 - bc_lo
        live = ((grow > bc_lo) & (grow < hi)
                & (gcol > bc_lo) & (gcol < hi))
        prevb = band
        for s in range(ksteps):  # static unroll
            if s == ksteps - 1:
                # pre-final-step band: the residual stat's reference.
                # Its out-tile rows are wrap-corruption-free too —
                # corruption travels one cell per mini-step and
                # ksteps - 1 < kpad (same invariant as `out`).
                prevb = band
            # XLA-lane-program order: +1 neighbors in axis order, then -1
            # neighbors, then the center term (laplacian_interior)
            p0 = pltpu.roll(band, rows - 1, 0)
            p1 = pltpu.roll(band, n_pad - 1, 1)
            m0 = pltpu.roll(band, 1, 0)
            m1 = pltpu.roll(band, 1, 1)
            lap = p0 + p1 + m0 + m1 + (-4.0) * band
            upd = (band + r_l * lap).astype(store_dt).astype(acc_dt)
            keep = jnp.logical_and(live, offset + s < rem_l)
            band = jnp.where(keep, upd, band)
        out = band[kpad: kpad + tile].astype(store_dt)
        out_ref[:] = out.reshape(1, tile, n_pad)
        first_any = jnp.logical_and(lane == 0, i == 0)
        _lane_finite_accumulate(fin_ref, lane, first_any, out, lanes)
        # request-region mask in OUT-TILE coords ([1, n_l] per axis —
        # the Dirichlet ring included; distinct from `live`)
        oshape = (tile, n_pad)
        orow = i * tile + jax.lax.broadcasted_iota(jnp.int32, oshape, 0)
        ocol = jax.lax.broadcasted_iota(jnp.int32, oshape, 1)
        region = ((orow >= 1) & (orow <= n_l)
                  & (ocol >= 1) & (ocol <= n_l))
        _lane_stats_accumulate(stats_ref, lane, first_any,
                               prevb[kpad: kpad + tile], out, region,
                               lanes)

    return kernel


def _lane_pallas_2d(fields: jax.Array, r, n, rem, bc_lo: int, ksteps: int,
                    offset: int, plan):
    """One fused pass of <= kpad mini-steps over every lane (grid =
    (L, row-tiles)). Traced inside the serving engine's jitted advance —
    no jit of its own."""
    m_pad, n_pad, tile, kpad, _ = plan
    L = fields.shape[0]
    assert fields.shape == (L, m_pad, n_pad), (fields.shape, plan)
    assert 1 <= ksteps <= kpad and tile % kpad == 0
    grid = (L, m_pad // tile)
    ratio = tile // kpad
    nhblk = m_pad // kpad
    smem = pl.BlockSpec((1, L), lambda l, i: (0, 0),
                        memory_space=pltpu.SMEM)
    halo = lambda imap: pl.BlockSpec((1, kpad, n_pad), imap,
                                     memory_space=pltpu.VMEM)
    main = lambda imap: pl.BlockSpec((1, tile, n_pad), imap,
                                     memory_space=pltpu.VMEM)
    band = tile + 2 * kpad
    out, fin, stats = pl.pallas_call(
        _make_lane_kernel_2d(bc_lo, tile, kpad, n_pad, ksteps, offset, L),
        out_shape=(jax.ShapeDtypeStruct(fields.shape, fields.dtype),
                   jax.ShapeDtypeStruct((1, L), jnp.int32),
                   jax.ShapeDtypeStruct((4, L), jnp.float32)),
        grid=grid,
        in_specs=[
            smem, smem, smem,
            halo(lambda l, i: (l, jnp.maximum(i * ratio - 1, 0), 0)),
            main(lambda l, i: (l, i, 0)),
            halo(lambda l, i: (l, jnp.minimum((i + 1) * ratio, nhblk - 1),
                               0)),
        ],
        out_specs=(main(lambda l, i: (l, i, 0)),
                   pl.BlockSpec((1, L), lambda l, i: (0, 0),
                                memory_space=pltpu.SMEM),
                   pl.BlockSpec((4, L), lambda l, i: (0, 0),
                                memory_space=pltpu.SMEM)),
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=_chip().vmem_limit_bytes,
        ),
        cost_estimate=pl.CostEstimate(
            flops=11 * band * n_pad * L * grid[1] * ksteps,
            bytes_accessed=(2 * m_pad + 2 * kpad * grid[1]) * n_pad * L
            * fields.dtype.itemsize,
            transcendentals=0,
        ),
        interpret=_interpret(),
    )(jnp.asarray(r).reshape(1, L),
      jnp.asarray(n, jnp.int32).reshape(1, L),
      jnp.asarray(rem, jnp.int32).reshape(1, L),
      fields, fields, fields)
    return out, fin[0], stats


def _lane_grid_specs_3x3(R: int, M: int, ki: int, kj: int, nblocks,
                         n_pad: int):
    """The 3x3 halo-neighborhood BlockSpecs with a leading LANE block dim:
    maps take (l, i, j) and clamp within lane l's own slab."""
    ri, rj = R // ki, M // kj
    ni, nj = nblocks

    def icl(i):
        return jnp.clip(i, 0, ni - 1)

    def jcl(j):
        return jnp.clip(j, 0, nj - 1)

    def bs(bi, bj, imap):
        return pl.BlockSpec((1, bi, bj, n_pad), imap,
                            memory_space=pltpu.VMEM)

    specs = [
        bs(ki, kj, lambda l, i, j: (l, icl(i * ri - 1), jcl(j * rj - 1), 0)),
        bs(ki, M, lambda l, i, j: (l, icl(i * ri - 1), j, 0)),
        bs(ki, kj, lambda l, i, j: (l, icl(i * ri - 1), jcl((j + 1) * rj), 0)),
        bs(R, kj, lambda l, i, j: (l, i, jcl(j * rj - 1), 0)),
        bs(R, M, lambda l, i, j: (l, i, j, 0)),
        bs(R, kj, lambda l, i, j: (l, i, jcl((j + 1) * rj), 0)),
        bs(ki, kj, lambda l, i, j: (l, icl((i + 1) * ri), jcl(j * rj - 1), 0)),
        bs(ki, M, lambda l, i, j: (l, icl((i + 1) * ri), j, 0)),
        bs(ki, kj, lambda l, i, j: (l, icl((i + 1) * ri), jcl((j + 1) * rj), 0)),
    ]
    return specs, bs(R, M, lambda l, i, j: (l, i, j, 0))


def _make_lane_kernel_3d(bc_lo: int, R: int, M: int, kp: int, km: int,
                         n_pad: int, ksteps: int, offset: int,
                         lanes: int):
    """Multi-lane (row, mid)-tiled 3D body. Unlike the solo 3D kernel's
    shrinking slices, every mini-step runs full-band wrap rotates on ALL
    three axes with a select-kept update — the col-tiled 2D kernel's
    proven-on-Mosaic shape discipline (shrinking 3D slices hand Mosaic
    sublane-misaligned rotate shapes, rejected outright by current
    compilers). Band-edge wrap corruption travels one cell per mini-step
    and ksteps <= kp <= km, so it never reaches the out tile — the same
    invariant as every other kernel in this file. Select-kept, per-lane
    bounded/gated, storage-rounded each step (the oracle contract)."""
    rows = R + 2 * kp
    mids = M + 2 * km

    def kernel(r_ref, n_ref, rem_ref, *refs):
        out_ref, fin_ref, stats_ref = refs[-3], refs[-2], refs[-1]
        lane = pl.program_id(0)
        i = pl.program_id(1)
        j = pl.program_id(2)
        store_dt = out_ref.dtype
        acc_dt = accum_dtype_for(store_dt)
        rows_g = [jnp.concatenate([refs[3 * g][:], refs[3 * g + 1][:],
                                   refs[3 * g + 2][:]], axis=2)
                  for g in range(3)]
        # band works in the accumulation dtype, holding exactly storage-
        # rounded values (see the 2D kernel: 32-bit select + exact final
        # downcast keep bf16 byte-identical to the oracle)
        band = jnp.concatenate(rows_g, axis=1)[0].astype(acc_dt)
        n_l = n_ref[0, lane]
        rem_l = rem_ref[0, lane]
        r_l = r_ref[0, lane].astype(acc_dt)
        hi = n_l + 1 - bc_lo
        bshape = (rows, mids, n_pad)
        grow = i * R - kp + jax.lax.broadcasted_iota(jnp.int32, bshape, 0)
        gmid = j * M - km + jax.lax.broadcasted_iota(jnp.int32, bshape, 1)
        gcol = jax.lax.broadcasted_iota(jnp.int32, bshape, 2)
        live = ((grow > bc_lo) & (grow < hi) & (gmid > bc_lo) & (gmid < hi)
                & (gcol > bc_lo) & (gcol < hi))
        prevb = band
        for s in range(ksteps):  # static unroll, constant shapes
            if s == ksteps - 1:
                # pre-final-step band for the residual stat (wrap-safe
                # on the out tile: ksteps - 1 < kp <= km)
                prevb = band
            # XLA-lane-program order: +axis0 +axis1 +axis2, then -axis0
            # -axis1 -axis2, then the center term (laplacian_interior)
            p0 = pltpu.roll(band, rows - 1, 0)
            p1 = pltpu.roll(band, mids - 1, 1)
            p2 = pltpu.roll(band, n_pad - 1, 2)
            m0 = pltpu.roll(band, 1, 0)
            m1 = pltpu.roll(band, 1, 1)
            m2 = pltpu.roll(band, 1, 2)
            lap = p0 + p1 + p2 + m0 + m1 + m2 + (-6.0) * band
            upd = (band + r_l * lap).astype(store_dt).astype(acc_dt)
            keep = jnp.logical_and(live, offset + s < rem_l)
            band = jnp.where(keep, upd, band)
        out = jax.lax.slice(
            band, (kp, km, 0), (kp + R, km + M, n_pad)).astype(store_dt)
        out_ref[:] = out.reshape(1, R, M, n_pad)
        first_any = jnp.logical_and(lane == 0,
                                    jnp.logical_and(i == 0, j == 0))
        _lane_finite_accumulate(fin_ref, lane, first_any, out, lanes)
        # request-region mask in OUT-TILE coords (Dirichlet ring in,
        # padding/margin out — distinct from `live`)
        oshape = (R, M, n_pad)
        orow = i * R + jax.lax.broadcasted_iota(jnp.int32, oshape, 0)
        omid = j * M + jax.lax.broadcasted_iota(jnp.int32, oshape, 1)
        ocol = jax.lax.broadcasted_iota(jnp.int32, oshape, 2)
        region = ((orow >= 1) & (orow <= n_l) & (omid >= 1) & (omid <= n_l)
                  & (ocol >= 1) & (ocol <= n_l))
        prev_out = jax.lax.slice(prevb, (kp, km, 0),
                                 (kp + R, km + M, n_pad))
        _lane_stats_accumulate(stats_ref, lane, first_any, prev_out, out,
                               region, lanes)

    return kernel


def _lane_pallas_3d(fields: jax.Array, r, n, rem, bc_lo: int, ksteps: int,
                    offset: int, plan):
    """One fused pass of <= kchunk mini-steps over every lane (grid =
    (L, row-tiles, mid-tiles))."""
    (m_pad, mid_pad, n_pad), R, M, kp, km = plan
    L = fields.shape[0]
    assert fields.shape == (L, m_pad, mid_pad, n_pad), (fields.shape, plan)
    assert 1 <= ksteps <= kp
    grid = (L, m_pad // R, mid_pad // M)
    smem = pl.BlockSpec((1, L), lambda l, i, j: (0, 0),
                        memory_space=pltpu.SMEM)
    in_specs, out_spec = _lane_grid_specs_3x3(
        R, M, kp, km, (m_pad // kp, mid_pad // km), n_pad)
    band = (R + 2 * kp) * (M + 2 * km)
    out, fin, stats = pl.pallas_call(
        _make_lane_kernel_3d(bc_lo, R, M, kp, km, n_pad, ksteps, offset,
                             L),
        out_shape=(jax.ShapeDtypeStruct(fields.shape, fields.dtype),
                   jax.ShapeDtypeStruct((1, L), jnp.int32),
                   jax.ShapeDtypeStruct((4, L), jnp.float32)),
        grid=grid,
        in_specs=[smem, smem, smem] + in_specs,
        out_specs=(out_spec,
                   pl.BlockSpec((1, L), lambda l, i, j: (0, 0),
                                memory_space=pltpu.SMEM),
                   pl.BlockSpec((4, L), lambda l, i, j: (0, 0),
                                memory_space=pltpu.SMEM)),
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=_chip().vmem_limit_bytes,
        ),
        cost_estimate=pl.CostEstimate(
            flops=13 * band * n_pad * L * grid[1] * grid[2] * ksteps,
            bytes_accessed=(band + R * M) * n_pad * L * grid[1] * grid[2]
            * fields.dtype.itemsize,
            transcendentals=0,
        ),
        interpret=_interpret(),
    )(jnp.asarray(r).reshape(1, L),
      jnp.asarray(n, jnp.int32).reshape(1, L),
      jnp.asarray(rem, jnp.int32).reshape(1, L),
      *([fields] * 9))
    return out, fin[0], stats


def lane_multistep(fields: jax.Array, r, n, rem, ksteps: int, bc_lo: int,
                   bucket_n: int):
    """``ksteps`` masked, countdown-gated FTCS steps over a stacked lane
    array via the multi-lane Pallas kernels, health reduction and
    numerics stats fused in.

    ``fields`` is (L,) + ``lane_state_shape(...)`` (the engine keeps its
    stack in the padded layout); ``r``/``n``/``rem`` are the per-lane
    scalar vectors of the serving engine's chunk program. Returns
    ``(fields, finite, stats)`` — ``finite`` a per-lane bool, False iff
    that lane's post-chunk slab holds a non-finite value; ``stats`` a
    (4, L) float32 of per-lane (resid, tmin, tmax, heat) over the
    request region (serve/engine.BOUNDARY_ROWS rows 2-5). Multi-pass
    chunks AND the finite bits across passes and keep the LAST pass's
    stats — the pass holding the chunk's final mini-step, whose
    residual/min/max/heat are the chunk-boundary values by definition.
    Stats are tolerance-compatible, not bit-equal, with the XLA lane
    program's (grid-tiled reduction order differs); the field bytes and
    rows 0-1 stay bit-exact. Gate callers on ``lane_kernel_available``;
    chunks deeper than the per-pass fusion cap run as multiple passes
    with the countdown gate offset so a lane still stops at exactly its
    own step count."""
    assert ksteps >= 1, ksteps
    nd = fields.ndim - 1
    dtype_str = str(fields.dtype)
    if nd == 2:
        plan = _plan_lane_2d(bucket_n, dtype_str)
        step, kp = _lane_pallas_2d, plan[4]
    else:
        plan = _plan_lane_3d(bucket_n, dtype_str)
        step, kp = _lane_pallas_3d, plan[3]
    assert plan is not None, (
        f"no lane kernel plan for {nd}d bucket {bucket_n} {dtype_str} "
        f"(gate on lane_kernel_available before calling)")
    fin = stats = None
    done = 0
    while done < ksteps:
        kpass = min(kp, ksteps - done)
        fields, f, stats = step(fields, r, n, rem, bc_lo=bc_lo,
                                ksteps=kpass, offset=done, plan=plan)
        fin = f if fin is None else jnp.minimum(fin, f)
        done += kpass
    return fields, fin.astype(bool), stats


# the plan caches embed the chip's rates/caps in their values; a chip-model
# override (tests, what-if planning) must flush them
machine.register_cache(_plan_2d.cache_clear)
machine.register_cache(_plan_3d.cache_clear)
machine.register_cache(_plan_lane_2d.cache_clear)
machine.register_cache(_plan_lane_3d.cache_clear)
