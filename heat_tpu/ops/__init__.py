from .stencil import (  # noqa: F401
    accum_dtype_for,
    ftcs_step_edges,
    ftcs_step_ghost,
    laplacian_interior,
    pad_with_ghosts,
    run_steps,
)
