from .timing import Timing, now, sync  # noqa: F401
from .logging import get_logger, master_print  # noqa: F401
