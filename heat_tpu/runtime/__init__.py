from .logging import get_logger, master_print  # noqa: F401
from .timing import Timing, sync  # noqa: F401
