"""Convergence prediction: eigenmode decay fused with observed residual slope.

Semantic scheduling needs to know, per lane, *when* the residual EWMA
will cross the steady tolerance — before it happens.  Two signals are
available for free:

- **Closed form.** Under FTCS with ``bc="edges"`` the slowest surviving
  eigenmode decays by ``lambda = 1 - 4*ndim*r*sin^2(pi/(2*(n-1)))`` per
  step (LeVeque; ``grid.sine_decay_factor``).  Asymptotically every
  smooth initial condition converges at this rate, so it is a usable
  prior from the moment of admission — zero observations required.
- **Observed slope.** Each chunk boundary carries the lane's interior
  residual in the (6, L) boundary vector (PR 14); consecutive residuals
  ``steps`` apart give a measured per-step log-slope.  Early on the
  observed slope is *steeper* than the closed form (higher modes are
  still dying), so it corrects the prior where the prior is pessimistic.

``RateFuser`` blends the two: the observed slope is EWMA-smoothed and
confidence-weighted by sample count, ramping from pure closed form (no
observations) to pure observation (``OBS_FULL_WEIGHT_SAMPLES`` boundary
deltas seen).  Everything here is pure host math on Python floats — no
device work, no locks (the numerics observatory serializes calls under
its own lock), no new transfers.
"""

from __future__ import annotations

import math
from typing import Optional

from ..config import HeatConfig
from ..grid import ic_envelope, sine_decay_factor

# EWMA smoothing for the observed per-step log-slope.  Matches the
# residual EWMA alpha in runtime/numerics.py so the two estimates track
# the same effective window.
OBS_RATE_ALPHA = 0.35

# Observed-slope confidence ramps linearly from 0 to 1 over this many
# boundary-to-boundary deltas; past it the closed form is fully faded.
OBS_FULL_WEIGHT_SAMPLES = 4


def closed_form_log_rate(cfg: HeatConfig) -> Optional[float]:
    """Per-step log decay rate of the slowest eigenmode, or ``None``
    when the closed form does not predict decay (unstable ``r``, or a
    regime where ``lambda`` leaves ``(0, 1)`` and the mode oscillates)."""
    lam = sine_decay_factor(cfg)
    if 0.0 < lam < 1.0:
        return math.log(lam)
    return None


class RateFuser:
    """Per-lane fused residual decay-rate estimate.

    ``observe()`` once per chunk boundary with the raw residual and the
    remaining-step count (the step delta between observations is
    ``prev_remaining - remaining``, so variable chunk sizes — tail
    chunks — are handled for free).  ``fused_log_rate()`` returns the
    current best per-step log-rate, negative when the lane is decaying.
    """

    __slots__ = ("closed", "obs", "samples", "_last_resid", "_last_remaining")

    def __init__(self, closed: Optional[float]):
        self.closed = closed
        self.obs: Optional[float] = None
        self.samples = 0
        self._last_resid: Optional[float] = None
        self._last_remaining: Optional[int] = None

    def observe(self, resid: float, remaining: int) -> None:
        if (self._last_resid is not None and self._last_remaining is not None):
            steps = self._last_remaining - int(remaining)
            if steps > 0 and resid > 0.0 and self._last_resid > 0.0:
                rate = math.log(resid / self._last_resid) / steps
                if math.isfinite(rate):
                    if self.obs is None:
                        self.obs = rate
                    else:
                        self.obs = (OBS_RATE_ALPHA * rate
                                    + (1.0 - OBS_RATE_ALPHA) * self.obs)
                    self.samples += 1
        self._last_resid = float(resid)
        self._last_remaining = int(remaining)

    def fused_log_rate(self) -> Optional[float]:
        if self.obs is None or self.samples <= 0:
            return self.closed
        if self.closed is None:
            return self.obs
        w = min(1.0, self.samples / float(OBS_FULL_WEIGHT_SAMPLES))
        return w * self.obs + (1.0 - w) * self.closed

    # --- engine-state checkpoint / resume (serve --resume) ----------------
    # ``closed`` is NOT exported: it is recomputed from the config at
    # re-admission (deterministic), so only the observed half travels.
    def export_state(self) -> dict:
        return {"obs": self.obs, "samples": self.samples,
                "last_resid": self._last_resid,
                "last_remaining": self._last_remaining}

    def reseed(self, state: dict) -> None:
        self.obs = (None if state.get("obs") is None
                    else float(state["obs"]))
        self.samples = int(state.get("samples") or 0)
        lr = state.get("last_resid")
        self._last_resid = None if lr is None else float(lr)
        lrem = state.get("last_remaining")
        self._last_remaining = None if lrem is None else int(lrem)


def predict_steps_to_tol(resid: float, tol: float,
                         log_rate: Optional[float]) -> Optional[int]:
    """Steps until a residual decaying at ``log_rate`` per step drops
    from ``resid`` below ``tol``; ``None`` when no finite prediction
    exists (non-decaying rate, non-positive inputs)."""
    if resid is None or not (resid > 0.0) or not (tol > 0.0):
        return None
    if resid <= tol:
        return 0
    if log_rate is None or log_rate >= 0.0:
        return None
    return int(math.ceil(math.log(tol / resid) / log_rate))


def predict_admission_steps(cfg: HeatConfig, tol: float) -> Optional[int]:
    """Closed-form predicted retirement step at admission time — before
    a single boundary has been observed.

    The per-step residual of a mode with amplitude ``A`` decaying at
    ``lambda`` is ``(1 - lambda) * lambda**(s-1) * A``, so the first
    residual is ``(1 - lambda) * A`` with ``A`` bounded by the analytic
    IC envelope (``grid.ic_envelope`` — no host field materialized).
    The result is clamped to ``[0, ntime]``: a prediction past the
    nominal step count means "no early exit expected".
    """
    log_rate = closed_form_log_rate(cfg)
    if log_rate is None or not (tol > 0.0):
        return None
    lam = math.exp(log_rate)
    lo, hi = ic_envelope(cfg)
    amp = max(abs(hi), abs(lo), abs(hi - lo))
    r0 = (1.0 - lam) * amp
    s = predict_steps_to_tol(r0, tol, log_rate)
    if s is None:
        return None
    return min(int(cfg.ntime), max(0, s))
