"""Asynchronous checkpoint/telemetry pipeline: D2H + disk off the hot loop.

The reference stops the device for every host-visible event: its solution
dumps sit inline in the timed region (fortran/serial/heat.f90:77-83), and
our drive loop inherited that shape — ``sync(T_dev)`` -> full D2H fetch ->
synchronous ``checkpoint.save`` at every checkpoint boundary, seconds of
idle device per snapshot for GiB-scale fields on a tunneled link.

This module is the off-critical-path half of the rework
(``backends.common.drive`` is the on-loop half): at a boundary the driver
takes ONE on-device buffer copy (donation-safe — the live field is donated
into the next chunk while the copy stays pinned for the writer) and resumes
stepping immediately; a background thread performs the device->host
transfer (``np.asarray`` on the snapshot blocks only the writer) and the
atomic-rename disk write.

Contract:

- **Bounded queue** (default depth 2): a slow sink applies BACKPRESSURE —
  ``submit`` blocks the driver when the queue is full — rather than
  accumulating unbounded device snapshots (each is a full field buffer;
  two in flight is the memory ceiling).
- **No snapshot is ever silently dropped**: ``drain`` flushes every queued
  snapshot before returning, and the driver calls it on BOTH the normal and
  the exception exit path (``drive``'s try/except).
- **Writer failures surface, promptly**: the first sink exception is
  re-raised on the next ``submit`` (the solve must not step for hours
  against a dead disk) and again at ``drain``; queued snapshots after a
  failed one are still attempted (independent files).
- **Accounting**: ``busy_s`` (writer wall time in fetch+write), ``wait_s``
  (driver wall time blocked on the pipeline: backpressure + drain), and
  ``hidden_s = max(0, busy_s - wait_s)`` — the I/O wall time genuinely
  overlapped with compute, reported as ``Timing.overlap_s``.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Optional

from .logging import master_print

# Default queue depth: each entry pins one full-field device buffer, so the
# depth is a device-memory bound, not a tuning knob — 2 keeps one snapshot
# transferring while one more waits, which is all the pipelining a single
# writer thread can use.
DEFAULT_DEPTH = 2


class SnapshotWriter:
    """Background writer for device snapshots with a bounded queue.

    ``submit(job)`` enqueues a zero-arg callable (closing over the device
    snapshot) and returns as soon as there is queue room; the worker thread
    runs jobs in FIFO order. Start is lazy (a solve with no checkpoint
    boundary never spawns a thread); the thread is a daemon so a crashed
    driver that never drains cannot hang interpreter exit.
    """

    def __init__(self, depth: int = DEFAULT_DEPTH):
        self._q: "queue.Queue[Optional[Callable[[], None]]]" = queue.Queue(
            maxsize=max(1, depth))
        self._thread: Optional[threading.Thread] = None
        self._exc: Optional[BaseException] = None
        self.busy_s = 0.0     # writer wall time spent in D2H + disk write
        self.wait_s = 0.0     # driver wall time blocked on the pipeline
        self.submitted = 0
        self.completed = 0    # jobs RUN (successfully or not) — drained

    @property
    def hidden_s(self) -> float:
        """I/O wall time hidden behind compute (``Timing.overlap_s``)."""
        return max(0.0, self.busy_s - self.wait_s)

    def _worker(self) -> None:
        while True:
            job = self._q.get()
            try:
                if job is None:  # drain sentinel
                    return
                t0 = time.perf_counter()
                try:
                    job()
                except BaseException as e:  # noqa: BLE001 — surfaced at the
                    # next submit/drain; later snapshots still attempted
                    if self._exc is None:
                        self._exc = e
                finally:
                    self.busy_s += time.perf_counter() - t0
                    self.completed += 1
            finally:
                self._q.task_done()

    def _raise_pending(self) -> None:
        if self._exc is not None:
            exc, self._exc = self._exc, None
            raise exc

    def submit(self, job: Callable[[], None]) -> None:
        """Enqueue a snapshot job; blocks when the queue is full
        (backpressure — bounded memory beats a snapshot pileup). Re-raises
        the first pending writer error instead of queueing behind it."""
        self._raise_pending()
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._worker, daemon=True, name="heat-snapshot-writer")
            self._thread.start()
        t0 = time.perf_counter()
        self._q.put(job)
        self.wait_s += time.perf_counter() - t0
        self.submitted += 1

    def drain(self, raise_errors: bool = True) -> None:
        """Flush every queued snapshot and stop the worker.

        ``raise_errors=False`` is the exception-exit form: snapshots still
        flush (nothing dropped) but a writer error is only logged — it must
        not mask the solve error already propagating."""
        t0 = time.perf_counter()
        if self._thread is not None:
            self._q.put(None)          # after all queued jobs: FIFO drain
            self._thread.join()
            self._thread = None
        self.wait_s += time.perf_counter() - t0
        if raise_errors:
            self._raise_pending()
        elif self._exc is not None:
            master_print(f"async checkpoint writer error (suppressed while "
                         f"another error propagates): "
                         f"{type(self._exc).__name__}: {self._exc}")


def device_snapshot(T):
    """One on-device buffer copy of the live field.

    This is the whole on-loop cost of an async checkpoint: the copy is a
    device-side memcpy (HBM bandwidth, microseconds-to-milliseconds) that
    detaches the snapshot from the donation chain — the live buffer is
    donated into the next ``advance`` call while the copy stays pinned
    until the writer's ``np.asarray`` fetches and releases it. Works on
    sharded global arrays too (jitted identity, SPMD-uniform: every
    process copies its own shards)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    if isinstance(T, jax.Array):
        return jnp.copy(T)
    return np.array(T)
