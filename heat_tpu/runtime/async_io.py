"""Asynchronous checkpoint/telemetry pipeline: D2H + disk off the hot loop.

The reference stops the device for every host-visible event: its solution
dumps sit inline in the timed region (fortran/serial/heat.f90:77-83), and
our drive loop inherited that shape — ``sync(T_dev)`` -> full D2H fetch ->
synchronous ``checkpoint.save`` at every checkpoint boundary, seconds of
idle device per snapshot for GiB-scale fields on a tunneled link.

This module is the off-critical-path half of the rework
(``backends.common.drive`` is the on-loop half): at a boundary the driver
takes ONE on-device buffer copy (donation-safe — the live field is donated
into the next chunk while the copy stays pinned for the writer) and resumes
stepping immediately; a background thread performs the device->host
transfer (``np.asarray`` on the snapshot blocks only the writer) and the
atomic-rename disk write.

Contract:

- **Bounded queue** (default depth 2): a slow sink applies BACKPRESSURE —
  ``submit`` blocks the driver when the queue is full — rather than
  accumulating unbounded device snapshots (each is a full field buffer;
  two in flight is the memory ceiling).
- **No snapshot is ever silently dropped**: ``drain`` flushes every queued
  snapshot before returning, and the driver calls it on BOTH the normal and
  the exception exit path (``drive``'s try/except).
- **Writer failures surface, promptly**: the first sink exception is
  re-raised on the next ``submit`` (the solve must not step for hours
  against a dead disk) and again at ``drain``; queued snapshots after a
  failed one are still attempted (independent files).
- **Transient sink errors are retried, bounded**: an ``OSError`` in the
  EIO/ENOSPC class (flaky NFS, momentary disk pressure) gets up to
  ``retries`` in-thread re-attempts under exponential backoff before it
  becomes a surfaced failure — a single I/O hiccup must not abort a
  day-long solve. Non-transient exceptions (fingerprint errors, NaN
  snapshot rejection) surface on the first attempt.
- **Drain is bounded**: ``drain(timeout_s=...)`` (default 10 min) raises
  ``TimeoutError`` instead of blocking the exit path forever on a hung
  sink; the daemon worker thread is abandoned (it cannot outlive the
  process).
- **Accounting**: ``busy_s`` (writer wall time in fetch+write), ``wait_s``
  (driver wall time blocked on the pipeline: backpressure + drain), and
  ``hidden_s = max(0, busy_s - wait_s)`` — the I/O wall time genuinely
  overlapped with compute, reported as ``Timing.overlap_s``.
"""

from __future__ import annotations

import errno
import queue
import threading
import time
from typing import Callable, Optional

from . import debug
from .logging import master_print

# Default queue depth: each entry pins one full-field device buffer, so the
# depth is a device-memory bound, not a tuning knob — 2 keeps one snapshot
# transferring while one more waits, which is all the pipelining a single
# writer thread can use.
DEFAULT_DEPTH = 2

# Transient-sink retry policy: 3 re-attempts at 50/100/200 ms covers the
# blip class (flaky NFS op, momentary ENOSPC from a log rotation) without
# stalling a genuinely dead disk for more than ~0.35 s before surfacing.
DEFAULT_RETRIES = 3
DEFAULT_RETRY_BACKOFF_S = 0.05

# drain() must never block an exit path forever (hung NFS mount): 10 min is
# far beyond any sane snapshot write yet still bounds the wait.
DEFAULT_DRAIN_TIMEOUT_S = 600.0

_TRANSIENT_ERRNOS = frozenset({
    errno.EIO, errno.ENOSPC, errno.EAGAIN, errno.EBUSY, errno.ETIMEDOUT,
    errno.EINTR,
})


class BoundedFetchTimeout(TimeoutError):
    """A watchdog-bounded device fetch did not complete in time (wedged
    device/tunnel). The abandoned daemon thread may still be blocked on
    the transfer; the caller must treat the fetched-from state as lost."""


def bounded_call(fn: Callable[[], object], timeout_s: float,
                 what: str = "device fetch"):
    """Run ``fn`` in a daemon thread and wait at most ``timeout_s``.

    The boundary-fetch watchdog of the serving engine: a D2H transfer
    against a wedged device blocks uninterruptibly, so the only way to
    bound it is to move the blocking call off the waiting thread and
    abandon it on timeout (the same abandon-don't-wedge discipline as
    ``SnapshotWriter.drain``). Exceptions raised by ``fn`` re-raise here;
    a timeout raises ``BoundedFetchTimeout``."""
    result: list = [None, None]     # [value, exception]
    done = threading.Event()

    def runner():
        try:
            result[0] = fn()
        except BaseException as e:  # noqa: BLE001 — re-raised on the caller
            result[1] = e
        finally:
            done.set()

    t = threading.Thread(target=runner, daemon=True,
                         name="heat-bounded-fetch")
    t.start()
    if not done.wait(timeout_s):
        raise BoundedFetchTimeout(
            f"{what} did not complete within {timeout_s:g}s (wedged "
            f"device fetch?) — abandoning the fetch thread")
    if result[1] is not None:
        raise result[1]
    return result[0]


def is_transient(e: BaseException) -> bool:
    """The retry-worthy class: OS-level errors that routinely clear on
    their own. Anything else (fingerprint mismatch, NaN rejection, a
    coding bug) fails fast on the first attempt."""
    return isinstance(e, OSError) and e.errno in _TRANSIENT_ERRNOS


class SnapshotWriter:
    """Background writer for device snapshots with a bounded queue.

    ``submit(job)`` enqueues a zero-arg callable (closing over the device
    snapshot) and returns as soon as there is queue room; the worker thread
    runs jobs in FIFO order. Start is lazy (a solve with no checkpoint
    boundary never spawns a thread); the thread is a daemon so a crashed
    driver that never drains cannot hang interpreter exit.
    """

    def __init__(self, depth: int = DEFAULT_DEPTH,
                 retries: int = DEFAULT_RETRIES,
                 retry_backoff_s: float = DEFAULT_RETRY_BACKOFF_S,
                 tracer=None):
        # ``tracer`` (runtime/trace.py, optional): each job becomes one
        # span on the writer thread's track — the D2H + publish half of a
        # request/checkpoint made visible on the same timeline as the
        # compute it overlaps. Callers label jobs by setting a
        # ``job._trace = (name, trace_id)`` attribute; unlabeled jobs
        # trace as "io-job". No tracer (the default) costs nothing.
        self._tracer = tracer
        self._q: "queue.Queue[Optional[Callable[[], None]]]" = queue.Queue(
            maxsize=max(1, depth))
        self._thread: Optional[threading.Thread] = None
        # the one genuinely cross-thread cell: the worker publishes the
        # first sink error, submit/drain consume it. A ranked lock (not a
        # bare flag) so the hand-off is visible to the race sanitizer.
        self._exc_lock = debug.make_lock("writer:exc")
        self._exc: Optional[BaseException] = None
        self.retries = max(0, retries)
        self.retry_backoff_s = retry_backoff_s
        self.busy_s = 0.0     # writer wall time spent in D2H + disk write
        self.wait_s = 0.0     # driver wall time blocked on the pipeline
        self.submitted = 0
        self.completed = 0    # jobs RUN (successfully or not) — drained
        self.attempts = 0     # job executions incl. transient retries
        # race sanitizer (no-op unless HEAT_TPU_RACECHECK): the exempt
        # fields carry allow[races] markers above — instance-confined
        # driver-side accounting the static client+driver union merges
        debug.instrument_races(
            self, label="SnapshotWriter",
            exempt=frozenset({"wait_s", "submitted", "_thread"}))

    @property
    def hidden_s(self) -> float:
        """I/O wall time hidden behind compute (``Timing.overlap_s``)."""
        return max(0.0, self.busy_s - self.wait_s)

    def _run_job(self, job: Callable[[], None]) -> None:
        """One job with bounded transient retry. Retry sleeps count toward
        ``busy_s`` (the caller times around this call): a retrying writer IS
        occupying the pipeline, so the accounting stays honest about what
        compute could and couldn't hide."""
        for attempt in range(self.retries + 1):
            self.attempts += 1
            try:
                job()
                return
            except BaseException as e:  # noqa: BLE001 — surfaced at the
                # next submit/drain; later snapshots still attempted
                if not (is_transient(e) and attempt < self.retries):
                    with self._exc_lock:
                        if self._exc is None:
                            self._exc = e
                    return
                delay = self.retry_backoff_s * (2 ** attempt)
                master_print(f"async checkpoint writer: transient sink error "
                             f"({e}); retry {attempt + 1}/{self.retries} "
                             f"in {delay:.2g}s")
                time.sleep(delay)

    def _worker(self) -> None:
        while True:
            job = self._q.get()
            try:
                if job is None:  # drain sentinel
                    return
                t0 = time.perf_counter()
                try:
                    self._run_job(job)
                finally:
                    self.busy_s += time.perf_counter() - t0
                    self.completed += 1
                    tr = self._tracer
                    if tr is not None and tr.enabled:
                        name, xid = getattr(job, "_trace",
                                            ("io-job", None))
                        tr.complete(name, tr.thread_track("writer"), t0,
                                    cat="io", trace_id=xid)
            finally:
                self._q.task_done()

    def _raise_pending(self) -> None:
        with self._exc_lock:
            exc, self._exc = self._exc, None
        if exc is not None:
            raise exc

    def submit(self, job: Callable[[], None]) -> None:
        """Enqueue a snapshot job; blocks when the queue is full
        (backpressure — bounded memory beats a snapshot pileup). Re-raises
        the first pending writer error instead of queueing behind it."""
        self._raise_pending()
        if self._thread is None:  # heat-tpu: allow[races] instance-confined — each writer's submit/drain side runs on the one thread that constructed it; the static client+driver union merges distinct instances
            self._thread = threading.Thread(
                target=self._worker, daemon=True, name="heat-snapshot-writer")
            self._thread.start()
        t0 = time.perf_counter()
        self._q.put(job)
        # heat-tpu: allow[races] instance-confined — same single-driver accounting as _thread above; the worker thread never touches these fields
        self.wait_s += time.perf_counter() - t0
        self.submitted += 1

    def drain(self, raise_errors: bool = True,
              timeout_s: Optional[float] = DEFAULT_DRAIN_TIMEOUT_S) -> None:
        """Flush every queued snapshot and stop the worker, within
        ``timeout_s`` (None = wait forever).

        ``raise_errors=False`` is the exception-exit form: snapshots still
        flush (nothing dropped) but a writer error is only logged — it must
        not mask the solve error already propagating. A drain that cannot
        finish inside the timeout (sink hung on a dead mount) raises
        ``TimeoutError`` (or logs, in the suppressed form) and abandons the
        daemon worker thread — bounded exit beats a wedged process."""
        t0 = time.perf_counter()
        hung = False
        if self._thread is not None:
            deadline = None if timeout_s is None else t0 + timeout_s
            try:
                # after all queued jobs: FIFO drain. The put itself can
                # block on a full queue behind a hung job — bound it too.
                self._q.put(None, timeout=None if deadline is None else
                            max(0.001, deadline - time.perf_counter()))
            except queue.Full:
                hung = True
            if not hung:
                self._thread.join(None if deadline is None else
                                  max(0.001, deadline - time.perf_counter()))
                hung = self._thread.is_alive()
            # heat-tpu: allow[races] instance-confined — drain runs on the writer's one driving thread; see submit
            self._thread = None  # abandoned if hung: daemon, dies with us
        self.wait_s += time.perf_counter() - t0
        if hung:
            msg = (f"async checkpoint writer failed to drain within "
                   f"{timeout_s:.0f}s (sink hung?) — abandoning the writer "
                   f"thread; queued snapshots may be lost")
            if raise_errors:
                raise TimeoutError(msg)
            master_print(msg)
            return
        if raise_errors:
            self._raise_pending()
        else:
            with self._exc_lock:
                exc = self._exc
            if exc is not None:
                master_print(f"async checkpoint writer error (suppressed "
                             f"while another error propagates): "
                             f"{type(exc).__name__}: {exc}")


def device_snapshot(T):
    """One on-device buffer copy of the live field.

    This is the whole on-loop cost of an async checkpoint: the copy is a
    device-side memcpy (HBM bandwidth, microseconds-to-milliseconds) that
    detaches the snapshot from the donation chain — the live buffer is
    donated into the next ``advance`` call while the copy stays pinned
    until the writer's ``np.asarray`` fetches and releases it. Works on
    sharded global arrays too (jitted identity, SPMD-uniform: every
    process copies its own shards)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    if isinstance(T, jax.Array):
        return jnp.copy(T)
    return np.array(T)


def lane_snapshot(stacked, lane: int):
    """One-LANE on-device copy out of a stacked ``(L, ...)`` lane array
    (``device_snapshot``'s shape for the serving engine's dispatch-ahead
    extraction): the gather enqueues behind the chunks already in flight
    and produces its own buffer, detached from the donation chain, so the
    scheduler resumes dispatching immediately and only the writer thread
    ever blocks on the D2H. One lane, not the stack — a finished 256-side
    lane must not drag the other L-1 lanes' bytes across the link."""
    import jax
    import numpy as np

    if isinstance(stacked, jax.Array):
        return stacked[lane]
    return np.array(stacked[lane])
