"""Periodic checkpoint / resume.

The reference has no mid-run persistence — its only dumps are the initial
``int.dat`` and final ``soln.dat`` (fortran/serial/heat.f90:50-55,77-83).
This module is the genuine extension flagged in SURVEY.md §5: periodic
``.npz`` snapshots carrying the field, the step index, and a config
fingerprint, enabling restart of long solves (the 25k-step flagship config,
``fortran/input_all.dat``).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from ..config import HeatConfig

_FMT = "heat_step{step:08d}.npz"


def config_fingerprint(cfg: HeatConfig) -> str:
    """Hash of the physics-relevant fields; a resume must match these."""
    phys = dict(n=cfg.n, sigma=cfg.sigma, nu=cfg.nu, dom_len=cfg.dom_len,
                ndim=cfg.ndim, ic=cfg.ic, bc=cfg.bc, bc_value=cfg.bc_value,
                dtype=cfg.dtype)
    return hashlib.sha256(json.dumps(phys, sort_keys=True).encode()).hexdigest()[:16]


def save(cfg: HeatConfig, T: np.ndarray, step: int) -> Path:
    d = Path(cfg.checkpoint_dir)
    d.mkdir(parents=True, exist_ok=True)
    path = d / _FMT.format(step=step)
    # Temp name must NOT match latest()'s "heat_step*.npz" glob, or a crash
    # mid-save would leave a torn file that resume then trips over.
    tmp = d / (path.name + ".tmp")
    with open(tmp, "wb") as f:  # file handle: stops numpy appending ".npz"
        np.savez_compressed(f, T=np.asarray(T), step=step,
                            fingerprint=config_fingerprint(cfg))
    tmp.rename(path)  # atomic publish: no torn checkpoint on interrupt
    return path


_SHARD_FMT = "heat_shards_step{step:08d}.proc{proc:04d}.npz"


def save_shards(cfg: HeatConfig, T_dev, step: int) -> Path:
    """Multi-host checkpoint: each process persists only its addressable
    shards (with their global offsets), one file per process — the analog of
    the reference's per-rank ``soln#####.dat`` contract
    (fortran/mpi+cuda/heat.F90:277-288) applied to snapshots. A shared
    filesystem (the usual pod setup) makes the union a full checkpoint."""
    import jax

    d = Path(cfg.checkpoint_dir)
    d.mkdir(parents=True, exist_ok=True)
    path = d / _SHARD_FMT.format(step=step, proc=jax.process_index())
    payload = {"step": np.asarray(step),
               "fingerprint": np.asarray(config_fingerprint(cfg))}
    for i, shard in enumerate(T_dev.addressable_shards):
        starts = [s.start or 0 for s in shard.index]
        payload[f"shard{i}_data"] = np.asarray(shard.data)
        payload[f"shard{i}_start"] = np.asarray(starts, np.int64)
    tmp = d / (path.name + ".tmp")
    with open(tmp, "wb") as f:
        np.savez_compressed(f, **payload)
    tmp.rename(path)
    return path


def latest_shards(cfg: HeatConfig, max_step: Optional[int] = None) -> Optional[int]:
    """Newest step for which this process has a shard checkpoint."""
    import jax

    d = Path(cfg.checkpoint_dir)
    if not d.is_dir():
        return None
    suffix = f".proc{jax.process_index():04d}.npz"
    steps = sorted(
        int(p.name[len("heat_shards_step"):len("heat_shards_step") + 8])
        for p in d.glob("heat_shards_step*.npz") if p.name.endswith(suffix)
    )
    if max_step is not None:
        steps = [s for s in steps if s <= max_step]
    return steps[-1] if steps else None


def load_shards(cfg: HeatConfig, step: int):
    """Read this process's shard file back: (blocks, step) where blocks is a
    list of (start_offsets, ndarray). Feed into
    ``jax.make_array_from_single_device_arrays`` (see
    backends.common.resolve_initial_field) to rebuild the global array."""
    import jax

    path = Path(cfg.checkpoint_dir) / _SHARD_FMT.format(
        step=step, proc=jax.process_index())
    blocks = []
    with np.load(path, allow_pickle=False) as z:
        fp = str(z["fingerprint"])
        if fp != config_fingerprint(cfg):
            raise ValueError(
                f"checkpoint {path} was written for a different physics config "
                f"(fingerprint {fp} != {config_fingerprint(cfg)})"
            )
        i = 0
        while f"shard{i}_data" in z:
            blocks.append((tuple(int(s) for s in z[f"shard{i}_start"]),
                           z[f"shard{i}_data"]))
            i += 1
        return blocks, int(z["step"])


def latest(cfg: HeatConfig, max_step: Optional[int] = None) -> Optional[Path]:
    """Newest checkpoint, optionally capped at ``max_step`` — resuming a run
    whose ntime is *smaller* than an old checkpoint must not time-travel."""
    d = Path(cfg.checkpoint_dir)
    if not d.is_dir():
        return None
    cks = sorted(d.glob("heat_step*.npz"))
    if max_step is not None:
        cks = [c for c in cks if int(c.stem.replace("heat_step", "")) <= max_step]
    return cks[-1] if cks else None


def latest_step(cfg: HeatConfig, max_step: Optional[int] = None) -> Optional[int]:
    """Step index of ``latest()``, parsed here so the filename layout stays
    this module's private business."""
    p = latest(cfg, max_step=max_step)
    return None if p is None else int(p.stem.replace("heat_step", ""))


def load(path: Path, cfg: HeatConfig) -> Tuple[np.ndarray, int]:
    with np.load(path, allow_pickle=False) as z:
        fp = str(z["fingerprint"])
        if fp != config_fingerprint(cfg):
            raise ValueError(
                f"checkpoint {path} was written for a different physics config "
                f"(fingerprint {fp} != {config_fingerprint(cfg)})"
            )
        return z["T"], int(z["step"])
