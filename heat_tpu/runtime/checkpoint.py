"""Periodic checkpoint / resume, with validation + quarantine on discovery.

The reference has no mid-run persistence — its only dumps are the initial
``int.dat`` and final ``soln.dat`` (fortran/serial/heat.f90:50-55,77-83).
This module is the genuine extension flagged in SURVEY.md §5: periodic
``.npz`` snapshots carrying the field, the step index, and a config
fingerprint, enabling restart of long solves (the 25k-step flagship config,
``fortran/input_all.dat``).

Discovery (``latest``/``latest_shards``/``scan_resume_step``) trusts
nothing: every candidate is verified loadable and finite before it is
offered for resume; a torn, truncated, or bit-rotted file is renamed to
``*.corrupt`` (quarantine — it stops matching the discovery glob and a
human can autopsy it) and discovery falls back to the next-older step. A
fingerprint mismatch is NOT corruption — the file is intact, it just
belongs to different physics — so it raises instead of quarantining:
resuming across physics must stay a loud error, never a silent IC restart.
"""

from __future__ import annotations

import hashlib
import json
import re
from pathlib import Path
from typing import Dict, Optional, Tuple

import numpy as np

from ..config import HeatConfig
from . import faults
from .logging import master_print

_FMT = "heat_step{step:08d}.npz"


def config_fingerprint(cfg: HeatConfig) -> str:
    """Hash of the physics-relevant fields; a resume must match these."""
    phys = dict(n=cfg.n, sigma=cfg.sigma, nu=cfg.nu, dom_len=cfg.dom_len,
                ndim=cfg.ndim, ic=cfg.ic, bc=cfg.bc, bc_value=cfg.bc_value,
                dtype=cfg.dtype)
    return hashlib.sha256(json.dumps(phys, sort_keys=True).encode()).hexdigest()[:16]


def save(cfg: HeatConfig, T: np.ndarray, step: int) -> Path:
    d = Path(cfg.checkpoint_dir)
    d.mkdir(parents=True, exist_ok=True)
    path = d / _FMT.format(step=step)
    plan = faults.plan_for(cfg)
    if plan is not None:
        plan.sink_fault(step)  # injected transient sink error / slow sink
    # Temp name must NOT match latest()'s "heat_step*.npz" glob, or a crash
    # mid-save would leave a torn file that resume then trips over.
    tmp = d / (path.name + ".tmp")
    with open(tmp, "wb") as f:  # file handle: stops numpy appending ".npz"
        np.savez_compressed(f, T=np.asarray(T), step=step,
                            fingerprint=config_fingerprint(cfg))
    tmp.rename(path)  # atomic publish: no torn checkpoint on interrupt
    if plan is not None:
        plan.damage_checkpoint(path, step)  # injected post-publish bitrot
    return path


_SHARD_FMT = "heat_shards_step{step:08d}.proc{proc:04d}.npz"
_SHARD_RE = re.compile(r"heat_shards_step(\d{8})\.proc(\d{4})\.npz$")


def save_shards(cfg: HeatConfig, T_dev, step: int) -> Path:
    """Multi-host checkpoint: each process persists only its addressable
    shards (with their global offsets), one file per process — the analog of
    the reference's per-rank ``soln#####.dat`` contract
    (fortran/mpi+cuda/heat.F90:277-288) applied to snapshots. A shared
    filesystem (the usual pod setup) makes the union a full checkpoint."""
    import jax

    d = Path(cfg.checkpoint_dir)
    d.mkdir(parents=True, exist_ok=True)
    path = d / _SHARD_FMT.format(step=step, proc=jax.process_index())
    plan = faults.plan_for(cfg)
    if plan is not None:
        plan.sink_fault(step)
    payload = {"step": np.asarray(step),
               "fingerprint": np.asarray(config_fingerprint(cfg))}
    for i, shard in enumerate(T_dev.addressable_shards):
        starts = [s.start or 0 for s in shard.index]
        payload[f"shard{i}_data"] = np.asarray(shard.data)
        payload[f"shard{i}_start"] = np.asarray(starts, np.int64)
    tmp = d / (path.name + ".tmp")
    with open(tmp, "wb") as f:
        np.savez_compressed(f, **payload)
    tmp.rename(path)
    if plan is not None:
        plan.damage_checkpoint(path, step)
    return path


# --- validation + quarantine ------------------------------------------------


def _finite(a: np.ndarray) -> bool:
    a = np.asarray(a)
    if a.dtype.name == "bfloat16":  # np.isfinite has no bf16 loop
        a = a.astype(np.float32)
    return bool(np.isfinite(a).all())


def validate(path: Path, cfg: Optional[HeatConfig] = None) -> Optional[str]:
    """None when the checkpoint is restorable; else a reason string
    (unreadable / non-finite — the quarantine classes). A fingerprint
    mismatch (checked only when ``cfg`` is given) raises ValueError
    instead: the file is intact, the CONFIG is wrong, and falling back to
    an older step would silently resume different physics."""
    try:
        with np.load(path, allow_pickle=False) as z:
            fp = str(z["fingerprint"])
            int(z["step"])
            if "T" in z:
                if not _finite(z["T"]):
                    return "non-finite field"
            else:
                i = 0
                while f"shard{i}_data" in z:
                    if not _finite(z[f"shard{i}_data"]):
                        return "non-finite shard"
                    tuple(z[f"shard{i}_start"])
                    i += 1
                if i == 0:
                    return "no shard blocks"
    except Exception as e:  # torn zip, bad CRC, missing keys, short read —
        # every decode failure is the same verdict: not restorable
        return f"unreadable ({type(e).__name__}: {e})"
    if cfg is not None and fp != config_fingerprint(cfg):
        raise ValueError(
            f"checkpoint {path} was written for a different physics config "
            f"(fingerprint {fp} != {config_fingerprint(cfg)})"
        )
    return None


def quarantine(path: Path, reason: str) -> Path:
    """Rename a bad checkpoint to ``*.corrupt``: it stops matching every
    discovery glob (resume falls back to the next-older step) but stays on
    disk for autopsy."""
    q = path.with_name(path.name + ".corrupt")
    path.rename(q)
    master_print(f"checkpoint: quarantined {path.name} -> {q.name} ({reason})")
    return q


def latest_shards(cfg: HeatConfig, max_step: Optional[int] = None) -> Optional[int]:
    """Newest step for which this process has a VALID shard checkpoint;
    invalid candidates are quarantined and the next-older step is tried."""
    import jax

    d = Path(cfg.checkpoint_dir)
    if not d.is_dir():
        return None
    suffix = f".proc{jax.process_index():04d}.npz"
    byname = {
        p.name: p for p in d.glob("heat_shards_step*.npz")
        if p.name.endswith(suffix)
    }
    steps = sorted(
        int(name[len("heat_shards_step"):len("heat_shards_step") + 8])
        for name in byname
    )
    if max_step is not None:
        steps = [s for s in steps if s <= max_step]
    for step in reversed(steps):
        p = byname[_SHARD_FMT.format(step=step, proc=jax.process_index())]
        reason = validate(p, cfg)
        if reason is None:
            return step
        quarantine(p, reason)
    return None


def load_shards(cfg: HeatConfig, step: int):
    """Read this process's shard file back: (blocks, step) where blocks is a
    list of (start_offsets, ndarray). Feed into
    ``jax.make_array_from_single_device_arrays`` (see
    backends.common.resolve_initial_field) to rebuild the global array."""
    import jax

    path = Path(cfg.checkpoint_dir) / _SHARD_FMT.format(
        step=step, proc=jax.process_index())
    blocks = []
    with np.load(path, allow_pickle=False) as z:
        fp = str(z["fingerprint"])
        if fp != config_fingerprint(cfg):
            raise ValueError(
                f"checkpoint {path} was written for a different physics config "
                f"(fingerprint {fp} != {config_fingerprint(cfg)})"
            )
        i = 0
        while f"shard{i}_data" in z:
            blocks.append((tuple(int(s) for s in z[f"shard{i}_start"]),
                           z[f"shard{i}_data"]))
            i += 1
        return blocks, int(z["step"])


def latest(cfg: HeatConfig, max_step: Optional[int] = None) -> Optional[Path]:
    """Newest VALID checkpoint, optionally capped at ``max_step`` — resuming
    a run whose ntime is *smaller* than an old checkpoint must not
    time-travel. A corrupt newest candidate is quarantined (``*.corrupt``)
    and the next-older step offered instead; a fingerprint mismatch raises
    (see ``validate``)."""
    d = Path(cfg.checkpoint_dir)
    if not d.is_dir():
        return None
    cks = sorted(d.glob("heat_step*.npz"))
    if max_step is not None:
        cks = [c for c in cks if int(c.stem.replace("heat_step", "")) <= max_step]
    for c in reversed(cks):
        reason = validate(c, cfg)
        if reason is None:
            return c
        quarantine(c, reason)
    return None


def latest_step(cfg: HeatConfig, max_step: Optional[int] = None) -> Optional[int]:
    """Step index of ``latest()``, parsed here so the filename layout stays
    this module's private business."""
    p = latest(cfg, max_step=max_step)
    return None if p is None else int(p.stem.replace("heat_step", ""))


def scan_resume_step(ckpt_dir, nprocs: int = 1,
                     max_step: Optional[int] = None) -> Optional[int]:
    """Supervisor-side discovery (cli.cmd_launch): the newest step a
    relaunched world could resume from, config-free (loadable + finite
    only — the workers' own ``latest*``/``load*`` still enforce the
    fingerprint). Single-file checkpoints count directly; a shard step
    counts only when ALL ``nprocs`` per-process files are present and
    valid (a partial shard set is a crash caught between two processes'
    saves — ``_agree_resume_step`` would reject it anyway). Invalid
    candidates are quarantined here so the relaunch never re-trips."""
    d = Path(ckpt_dir)
    if not d.is_dir():
        return None
    best: Optional[int] = None
    for p in sorted(d.glob("heat_step*.npz"), reverse=True):
        step = int(p.stem.replace("heat_step", ""))
        if max_step is not None and step > max_step:
            continue
        reason = validate(p)
        if reason is None:
            best = step
            break
        quarantine(p, reason)
    by_step: Dict[int, Dict[int, Path]] = {}
    for p in d.glob("heat_shards_step*.npz"):
        m = _SHARD_RE.match(p.name)
        if m:
            by_step.setdefault(int(m.group(1)), {})[int(m.group(2))] = p
    for step in sorted(by_step, reverse=True):
        if max_step is not None and step > max_step:
            continue
        files = by_step[step]
        if set(range(nprocs)) - set(files):
            continue  # partial shard set: some process never saved this step
        bad = False
        for p in files.values():
            reason = validate(p)
            if reason is not None:
                quarantine(p, reason)
                bad = True
        if not bad:
            best = step if best is None else max(best, step)
            break
    return best


def load(path: Path, cfg: HeatConfig) -> Tuple[np.ndarray, int]:
    with np.load(path, allow_pickle=False) as z:
        fp = str(z["fingerprint"])
        if fp != config_fingerprint(cfg):
            raise ValueError(
                f"checkpoint {path} was written for a different physics config "
                f"(fingerprint {fp} != {config_fingerprint(cfg)})"
            )
        return z["T"], int(z["step"])


# --- engine-state manifests (serve/scheduler.py zero-downtime serving) -------
# A generation = one consistent cut of the whole serving engine at an
# empty-pipeline chunk boundary: one field .npz per in-flight lane plus ONE
# JSON manifest naming them all. The manifest is the commit record — it is
# submitted to the (FIFO) SnapshotWriter *after* every field job, so a
# manifest that exists on disk proves its fields (and every result
# writeback submitted before the cut) were durably published first. A kill
# mid-generation leaves fields without a manifest; discovery simply falls
# back to the previous generation.

ENGINE_MANIFEST_KIND = "heat-tpu-engine-manifest"
ENGINE_MANIFEST_VERSION = 1
ENGINE_MANIFEST_FMT = "engine_gen{gen:08d}.json"
ENGINE_FIELD_FMT = "engine_gen{gen:08d}__{rid}.npz"
_ENGINE_MANIFEST_RE = re.compile(r"engine_gen(\d{8})\.json$")


def save_engine_field(d, gen: int, rid: str, T: np.ndarray,
                      fingerprint: str, remaining: int) -> Path:
    """Persist one in-flight lane's field for generation ``gen`` (called
    from the snapshot-writer thread). Same atomic-publish discipline as
    ``save``: temp name outside every discovery glob, then rename."""
    d = Path(d)
    d.mkdir(parents=True, exist_ok=True)
    path = d / ENGINE_FIELD_FMT.format(gen=gen, rid=rid)
    tmp = d / (path.name + ".tmp")
    with open(tmp, "wb") as f:
        np.savez_compressed(f, T=np.asarray(T), remaining=int(remaining),
                            fingerprint=fingerprint)
    tmp.rename(path)
    return path


def load_engine_field(d, gen: int, rid: str,
                      fingerprint: str) -> Tuple[np.ndarray, int]:
    """Read one lane field back; the fingerprint cross-check mirrors
    ``load`` — resuming a lane onto different physics must be loud."""
    path = Path(d) / ENGINE_FIELD_FMT.format(gen=gen, rid=rid)
    with np.load(path, allow_pickle=False) as z:
        fp = str(z["fingerprint"])
        if fp != fingerprint:
            raise ValueError(
                f"engine field {path} was written for a different physics "
                f"config (fingerprint {fp} != {fingerprint})")
        return z["T"], int(z["remaining"])


def save_engine_manifest(d, gen: int, manifest: dict, plan=None) -> Path:
    """Atomically publish generation ``gen``'s manifest (the commit
    record — write this LAST). ``plan`` is the active FaultPlan, so
    ``ckpt-manifest-corrupt@N`` bitrot lands post-publish exactly like
    ``damage_checkpoint`` does for solve checkpoints."""
    d = Path(d)
    d.mkdir(parents=True, exist_ok=True)
    path = d / ENGINE_MANIFEST_FMT.format(gen=gen)
    tmp = d / (path.name + ".tmp")
    tmp.write_text(json.dumps(manifest, sort_keys=True))
    tmp.rename(path)
    if plan is not None:
        plan.damage_manifest(path, gen)
    return path


def validate_engine_manifest(path: Path):
    """(manifest, None) when the generation is restorable, else
    (None, reason). Restorable means: the JSON parses, identifies itself,
    and every in-flight entry's field file exists, loads, is finite, and
    carries the fingerprint the manifest claims for it. Any failure is
    one verdict — quarantine the manifest and fall back a generation
    (unlike solve checkpoints there is no intact-file-wrong-config case
    here: the manifest itself stamped the fingerprints)."""
    try:
        man = json.loads(Path(path).read_text())
    except Exception as e:  # torn write, bitrot, not JSON
        return None, f"unreadable ({type(e).__name__}: {e})"
    if not isinstance(man, dict) or man.get("kind") != ENGINE_MANIFEST_KIND:
        return None, "not an engine manifest"
    if man.get("version") != ENGINE_MANIFEST_VERSION:
        return None, f"unsupported manifest version {man.get('version')!r}"
    try:
        gen = int(man["generation"])
        inflight = man["inflight"]
        man["queued"]
    except Exception as e:
        return None, f"missing keys ({type(e).__name__}: {e})"
    d = Path(path).parent
    for e in inflight:
        try:
            rid, fp = str(e["id"]), str(e["fingerprint"])
        except Exception as exc:
            return None, f"bad inflight entry ({type(exc).__name__}: {exc})"
        fpath = d / ENGINE_FIELD_FMT.format(gen=gen, rid=rid)
        try:
            with np.load(fpath, allow_pickle=False) as z:
                if str(z["fingerprint"]) != fp:
                    return None, (f"field {fpath.name} fingerprint "
                                  f"mismatch (manifest says {fp})")
                if not _finite(z["T"]):
                    return None, f"field {fpath.name} non-finite"
                int(z["remaining"])
        except Exception as exc:
            return None, (f"field {fpath.name} unreadable "
                          f"({type(exc).__name__}: {exc})")
    return man, None


def latest_engine_manifest(d):
    """Newest VALID engine manifest in ``d`` as ``(manifest, path)``, or
    ``(None, None)``. A bad candidate is quarantined (``*.corrupt``) with
    a loud master_print and discovery falls back one generation — the
    PR-2 solve-checkpoint contract lifted to the whole engine."""
    d = Path(d)
    if not d.is_dir():
        return None, None
    cands = sorted(p for p in d.iterdir() if _ENGINE_MANIFEST_RE.match(p.name))
    for p in reversed(cands):
        man, reason = validate_engine_manifest(p)
        if man is not None:
            return man, p
        quarantine(p, reason)
        master_print(f"engine resume: manifest {p.name} rejected "
                     f"({reason}) — falling back one generation")
    return None, None


def next_engine_generation(d) -> int:
    """First unused generation number in ``d`` (1-based). Counts
    quarantined manifests too, so a resumed engine never re-publishes a
    generation number an autopsy file already claims."""
    d = Path(d)
    if not d.is_dir():
        return 1
    best = 0
    for p in d.iterdir():
        m = re.match(r"engine_gen(\d{8})\.json", p.name)
        if m:
            best = max(best, int(m.group(1)))
    return best + 1
