"""Periodic checkpoint / resume.

The reference has no mid-run persistence — its only dumps are the initial
``int.dat`` and final ``soln.dat`` (fortran/serial/heat.f90:50-55,77-83).
This module is the genuine extension flagged in SURVEY.md §5: periodic
``.npz`` snapshots carrying the field, the step index, and a config
fingerprint, enabling restart of long solves (the 25k-step flagship config,
``fortran/input_all.dat``).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from ..config import HeatConfig

_FMT = "heat_step{step:08d}.npz"


def config_fingerprint(cfg: HeatConfig) -> str:
    """Hash of the physics-relevant fields; a resume must match these."""
    phys = dict(n=cfg.n, sigma=cfg.sigma, nu=cfg.nu, dom_len=cfg.dom_len,
                ndim=cfg.ndim, ic=cfg.ic, bc=cfg.bc, bc_value=cfg.bc_value,
                dtype=cfg.dtype)
    return hashlib.sha256(json.dumps(phys, sort_keys=True).encode()).hexdigest()[:16]


def save(cfg: HeatConfig, T: np.ndarray, step: int) -> Path:
    d = Path(cfg.checkpoint_dir)
    d.mkdir(parents=True, exist_ok=True)
    path = d / _FMT.format(step=step)
    # Temp name must NOT match latest()'s "heat_step*.npz" glob, or a crash
    # mid-save would leave a torn file that resume then trips over.
    tmp = d / (path.name + ".tmp")
    with open(tmp, "wb") as f:  # file handle: stops numpy appending ".npz"
        np.savez_compressed(f, T=np.asarray(T), step=step,
                            fingerprint=config_fingerprint(cfg))
    tmp.rename(path)  # atomic publish: no torn checkpoint on interrupt
    return path


def latest(cfg: HeatConfig, max_step: Optional[int] = None) -> Optional[Path]:
    """Newest checkpoint, optionally capped at ``max_step`` — resuming a run
    whose ntime is *smaller* than an old checkpoint must not time-travel."""
    d = Path(cfg.checkpoint_dir)
    if not d.is_dir():
        return None
    cks = sorted(d.glob("heat_step*.npz"))
    if max_step is not None:
        cks = [c for c in cks if int(c.stem.replace("heat_step", "")) <= max_step]
    return cks[-1] if cks else None


def load(path: Path, cfg: HeatConfig) -> Tuple[np.ndarray, int]:
    with np.load(path, allow_pickle=False) as z:
        fp = str(z["fingerprint"])
        if fp != config_fingerprint(cfg):
            raise ValueError(
                f"checkpoint {path} was written for a different physics config "
                f"(fingerprint {fp} != {config_fingerprint(cfg)})"
            )
        return z["T"], int(z["step"])
