"""Numerics observatory: per-lane solution-quality telemetry (ISSUE 15).

PR 8's observatory (runtime/prof.py) meters what serving *costs*; this
module watches what serving *sells* — the quality of the PDE solution —
from the four per-lane statistics the chunk programs now fuse into the
boundary vector (serve/engine.BOUNDARY_ROWS rows 2-5: final-mini-step
residual ``max|ΔT|``, request-region min/max, total heat ``ΣT``). The
scheduler feeds each fetched boundary here; this class owns the MATH
(EWMAs, detector thresholds, fire-once state) and returns event dicts;
all POLICY — structured records, flight dumps, the ``--numerics-guard``
quarantine routing, counters, trace instants — stays in the scheduler,
exactly the prof.py split.

Three detectors per lane:

- **steady state** — the residual EWMA sits below the request's steady
  tolerance (per-request ``tol`` override, else ``--steady-tol``) while
  steps remain: the lane is burning chip on an already-converged field.
  Fires ONCE per request, so long converged jobs cannot log-storm. For
  ``until=steady`` requests the scheduler ACTS on this event — the lane
  retires at its dispatch frontier (semantic scheduling, ISSUE 16);
  for fixed-step requests it stays observability-only.
- **discrete maximum principle** — under the CFL bound each FTCS update
  is a convex combination of old values, so request-region values may
  never escape ``[min(IC, bc), max(IC, bc)]`` (LeVeque's classic
  finite-difference analysis; see PAPERS.md). The region min/max are
  exact witnesses; escape beyond a dtype-aware rounding allowance means
  a mis-set ``r`` past the CFL bound, dtype drift, a soft error, or an
  injected ``perturb`` fault.
- **heat-content jump** — total heat under Dirichlet walls changes only
  by boundary flux, chunk over chunk a smooth decay; a discontinuous
  jump (vs an EWMA of recent per-chunk deltas) is the signature of a
  corrupted field that max-principle tolerance might still admit.
  Best-effort by design (heat is NOT conserved here — flux through the
  walls is physics, not a fault), armed only after two observations.

Thread-safety/lock-ordering contract (the prof.py contract verbatim):
one small private lock, and this module NEVER takes the engine lock —
the scheduler calls in (engine -> numerics order only), and gateway
scrape threads read ``snapshot()`` under the numerics lock alone.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional

from . import convergence, debug

# Dtype-aware maximum-principle allowance, RELATIVE to the envelope
# scale: per-step storage rounding can push a convex combination
# epsilon past the envelope, so the witness tolerance must cover
# accumulated rounding without masking real escapes. bfloat16 carries
# ~8 mantissa bits (eps ~ 3.9e-3) and drifts visibly over a chunk;
# float32/float64 stay near machine epsilon.
ENVELOPE_TOL = {"float64": 1e-9, "float32": 1e-4, "bfloat16": 5e-2}

# Residual-EWMA smoothing: ~5-chunk memory — fast enough that a freshly
# loaded lane's transient clears in a few boundaries, slow enough that
# one noisy chunk cannot fake convergence.
EWMA_ALPHA = 0.35

# Heat-jump detector: fires when one chunk's |Δheat| exceeds this many
# times the EWMA of recent deltas (floored at a fraction of the heat
# scale so a fully-steady lane's zero EWMA cannot turn jitter into an
# alarm). Deliberately loose — Dirichlet flux is physics.
HEAT_JUMP_FACTOR = 50.0
HEAT_JUMP_FLOOR_FRAC = 1e-3


@dataclasses.dataclass
class _LaneState:
    """Per-request detector state, admitted at lane fill and dropped at
    the request's terminal record (every path: ok, quarantine, fail)."""

    lo: float                   # envelope min(IC, bc)
    hi: float                   # envelope max(IC, bc)
    tol: float                  # dtype-aware envelope allowance
    resid_ewma: Optional[float] = None
    heat: Optional[float] = None        # last observed ΣT
    dheat_ewma: Optional[float] = None  # EWMA of |Δheat| per chunk
    steady_fired: bool = False
    violated: bool = False
    boundaries: int = 0
    last_resid: float = float("nan")
    last_min: float = float("nan")
    last_max: float = float("nan")
    # semantic scheduling (ISSUE 16): per-request steady tolerance
    # override (None -> the engine-wide --steady-tol; distinct from
    # ``tol`` above, which is the ENVELOPE allowance) and the fused
    # eigenmode/observed decay-rate estimator feeding ETA prediction.
    steady_tol: Optional[float] = None
    fuser: Optional[convergence.RateFuser] = None


class NumericsObservatory:
    """Ingests per-lane boundary stats; returns detector events.

    ``observe`` returns a list of event dicts (usually empty — one
    comparison and an EWMA update per lane per boundary): ``{"kind":
    "steady", ...}`` once per converged request, ``{"kind":
    "violation", "why": "max-principle" | "heat-jump", ...}`` on
    detector escape. The scheduler owns what happens next."""

    def __init__(self, steady_tol: float):
        self.steady_tol = float(steady_tol)
        self._lock = debug.make_lock("observatory:numerics")
        self._lanes: Dict[str, _LaneState] = {}
        self.steady_total = 0
        self.violation_total = 0

    # --- lifecycle --------------------------------------------------------
    def admit(self, req_id: str, lo: float, hi: float, dtype: str,
              steady_tol: Optional[float] = None,
              log_rate: Optional[float] = None) -> None:
        """Arm the detectors for one request: the maximum-principle
        envelope is [min(IC, bc), max(IC, bc)] — computed by the
        scheduler from the host-side T0 it already builds at lane fill,
        so admission costs zero device work. ``steady_tol`` overrides
        the engine-wide tolerance for this request (client ``tol``);
        ``log_rate`` is the closed-form eigenmode log decay rate the
        ETA fuser starts from (``convergence.closed_form_log_rate``)."""
        lo, hi = float(lo), float(hi)
        scale = max(abs(lo), abs(hi), 1.0)
        tol = ENVELOPE_TOL.get(dtype, ENVELOPE_TOL["float32"]) * scale
        with self._lock:
            self._lanes[req_id] = _LaneState(
                lo=lo, hi=hi, tol=tol,
                steady_tol=None if steady_tol is None else float(steady_tol),
                fuser=convergence.RateFuser(log_rate))

    def forget(self, req_id: str) -> None:
        """Drop a request's state (terminal record — any status)."""
        with self._lock:
            self._lanes.pop(req_id, None)

    # --- ingestion --------------------------------------------------------
    def observe(self, req_id: str, resid: float, tmin: float, tmax: float,
                heat: float, remaining: int) -> List[dict]:
        """One fetched boundary's stats for one lane -> detector events.

        Non-finite stats are ignored outright: the finite bit on the
        same boundary row already routes that lane to the nonfinite
        path, and NaN would poison the EWMAs of a lane about to be
        rolled back."""
        events: List[dict] = []
        with self._lock:
            st = self._lanes.get(req_id)
            if st is None or not all(map(math.isfinite,
                                         (resid, tmin, tmax, heat))):
                return events
            st.boundaries += 1
            st.last_resid, st.last_min, st.last_max = resid, tmin, tmax
            if st.fuser is not None:
                st.fuser.observe(resid, remaining)
            st.resid_ewma = (resid if st.resid_ewma is None else
                             EWMA_ALPHA * resid
                             + (1.0 - EWMA_ALPHA) * st.resid_ewma)
            # maximum principle: witnesses may not escape the envelope
            if not st.violated and (tmin < st.lo - st.tol
                                    or tmax > st.hi + st.tol):
                st.violated = True  # one violation verdict per request
                self.violation_total += 1
                events.append({
                    "kind": "violation", "why": "max-principle",
                    "tmin": tmin, "tmax": tmax, "lo": st.lo, "hi": st.hi,
                    "tol": st.tol})
            # heat jump: armed after two boundaries (need a delta EWMA)
            if st.heat is not None:
                dheat = abs(heat - st.heat)
                if st.dheat_ewma is not None and not st.violated:
                    floor = HEAT_JUMP_FLOOR_FRAC * max(abs(st.heat), 1.0)
                    if dheat > HEAT_JUMP_FACTOR * max(st.dheat_ewma, floor):
                        st.violated = True
                        self.violation_total += 1
                        events.append({
                            "kind": "violation", "why": "heat-jump",
                            "heat": heat, "heat_prev": st.heat,
                            "dheat": dheat, "dheat_ewma": st.dheat_ewma})
                st.dheat_ewma = (dheat if st.dheat_ewma is None else
                                 EWMA_ALPHA * dheat
                                 + (1.0 - EWMA_ALPHA) * st.dheat_ewma)
            st.heat = heat
            # steady state: converged but still burning steps (fire once)
            eff_tol = (self.steady_tol if st.steady_tol is None
                       else st.steady_tol)
            if (not st.steady_fired and remaining > 0
                    and st.resid_ewma < eff_tol):
                st.steady_fired = True
                self.steady_total += 1
                events.append({
                    "kind": "steady", "resid": resid,
                    "resid_ewma": st.resid_ewma,
                    "steady_tol": eff_tol})
        return events

    # --- engine-state checkpoint / resume (serve --resume) ----------------
    def export_state(self, req_id: str) -> Optional[dict]:
        """JSON-safe detector state for one in-flight request, captured
        at a chunk-boundary cut for the engine manifest. The envelope
        (lo/hi/tol) and the closed-form rate are NOT exported — both are
        recomputed deterministically at re-admission; only the observed
        history (EWMAs, fire-once flags, rate samples) travels."""
        with self._lock:
            st = self._lanes.get(req_id)
            if st is None:
                return None
            return {"resid_ewma": st.resid_ewma, "heat": st.heat,
                    "dheat_ewma": st.dheat_ewma,
                    "steady_fired": st.steady_fired,
                    "violated": st.violated,
                    "boundaries": st.boundaries,
                    "last_resid": st.last_resid,
                    "last_min": st.last_min, "last_max": st.last_max,
                    "fuser": (None if st.fuser is None
                              else st.fuser.export_state())}

    def reseed(self, req_id: str, state: Optional[dict]) -> None:
        """Restore exported detector state over a fresh ``admit`` (call
        admit first: it re-arms envelope/tolerance/closed-form rate).
        The EWMAs continue where the killed engine left them, so a
        resumed ``until=steady`` lane retires on accumulated evidence
        instead of re-warming from scratch — and an already-fired
        steady flag stays fired (no duplicate steady_state record)."""
        if not state:
            return
        with self._lock:
            st = self._lanes.get(req_id)
            if st is None:
                return
            if state.get("resid_ewma") is not None:
                st.resid_ewma = float(state["resid_ewma"])
            if state.get("heat") is not None:
                st.heat = float(state["heat"])
            if state.get("dheat_ewma") is not None:
                st.dheat_ewma = float(state["dheat_ewma"])
            st.steady_fired = bool(state.get("steady_fired", False))
            st.violated = bool(state.get("violated", False))
            st.boundaries = int(state.get("boundaries") or 0)
            for k in ("last_resid", "last_min", "last_max"):
                if state.get(k) is not None:
                    setattr(st, k, float(state[k]))
            if st.fuser is not None and state.get("fuser"):
                st.fuser.reseed(state["fuser"])

    # --- prediction (semantic scheduling, ISSUE 16) -----------------------
    def _eta_locked(self, st: _LaneState) -> Optional[int]:
        """Predicted steps until this lane's residual EWMA crosses its
        effective steady tolerance (fused eigenmode + observed slope);
        None before the first boundary or when no decay is predicted.
        Caller holds the numerics lock."""
        if st.fuser is None or st.resid_ewma is None:
            return None
        eff_tol = self.steady_tol if st.steady_tol is None else st.steady_tol
        return convergence.predict_steps_to_tol(
            st.resid_ewma, eff_tol, st.fuser.fused_log_rate())

    def eta_steps(self, req_id: str) -> Optional[int]:
        """Predicted remaining steps to steady for one request, for the
        scheduler's tail sizing and the gateway's ETA gauges. Takes only
        the numerics lock (engine -> numerics order preserved)."""
        with self._lock:
            st = self._lanes.get(req_id)
            return None if st is None else self._eta_locked(st)

    # --- export surfaces (gateway scrape threads) -------------------------
    def snapshot(self) -> dict:
        """Point-in-time view for /statusz: per-lane latest stats plus
        the monotone totals. Takes only the numerics lock."""
        with self._lock:
            lanes = {
                rid: {"resid": st.last_resid,
                      "resid_ewma": st.resid_ewma,
                      "heat": st.heat,
                      "tmin": st.last_min, "tmax": st.last_max,
                      "lo": st.lo, "hi": st.hi,
                      "steady": st.steady_fired,
                      "violated": st.violated,
                      "boundaries": st.boundaries,
                      "steady_tol": (self.steady_tol if st.steady_tol is None
                                     else st.steady_tol),
                      "eta_steps": self._eta_locked(st)}
                for rid, st in self._lanes.items()}
            return {"steady_tol": self.steady_tol,
                    "steady_total": self.steady_total,
                    "violation_total": self.violation_total,
                    "lanes": lanes}
