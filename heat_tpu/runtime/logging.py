"""Process-0-gated logging.

The reference gates every print on ``masterproc`` (rank 0,
fortran/mpi+cuda/heat.F90:78-79); the JAX equivalent is
``jax.process_index() == 0``. Single-process runs always log.

Master-ness is decided LAZILY at emit time, never at import/getLogger time:
``jax.process_index()`` initializes the XLA backend, and modules that must
run *before* backend initialization (``parallel.dist`` — the world join
itself) create loggers at import. Before the backend exists the process is
treated as master (there is no world yet to be a non-master of).
"""

from __future__ import annotations

import logging
import sys


def _is_master() -> bool:
    try:
        # the distributed client knows the process id without touching the
        # XLA backend (set by jax.distributed.initialize)
        from jax._src.distributed import global_state

        if global_state.client is not None:
            return global_state.process_id == 0
        from jax._src import xla_bridge

        if not xla_bridge.backends_are_initialized():
            return True  # pre-backend: single-process as far as we know
        import jax

        return jax.process_index() == 0
    except Exception:
        return True


def master_print(*args, **kw) -> None:
    if _is_master():
        print(*args, **kw)
        sys.stdout.flush()


def json_record(event: str, **fields) -> None:
    """One structured, machine-parseable JSON line (master-gated).

    The serving engine's per-request records and any future structured
    telemetry share this single emitter so consumers can grep one shape:
    ``{"event": "<event>", ...}`` with sorted keys, one record per line.
    """
    import json

    master_print(json.dumps({"event": event, **fields}, sort_keys=True,
                            default=str))


class _MasterFilter(logging.Filter):
    """Drop sub-ERROR records on non-master processes (checked per record,
    so creating the logger costs no backend initialization)."""

    def filter(self, record: logging.LogRecord) -> bool:
        return record.levelno >= logging.ERROR or _is_master()


def get_logger(name: str = "heat_tpu") -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter("[%(name)s] %(levelname)s %(message)s"))
        logger.addHandler(h)
        logger.setLevel(logging.INFO)
        logger.addFilter(_MasterFilter())
    return logger
