"""Process-0-gated logging.

The reference gates every print on ``masterproc`` (rank 0,
fortran/mpi+cuda/heat.F90:78-79); the JAX equivalent is
``jax.process_index() == 0``. Single-process runs always log.
"""

from __future__ import annotations

import logging
import sys


def _is_master() -> bool:
    try:
        import jax

        return jax.process_index() == 0
    except Exception:
        return True


def master_print(*args, **kw) -> None:
    if _is_master():
        print(*args, **kw)
        sys.stdout.flush()


def get_logger(name: str = "heat_tpu") -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter("[%(name)s] %(levelname)s %(message)s"))
        logger.addHandler(h)
        logger.setLevel(logging.INFO)
    if not _is_master():
        logger.setLevel(logging.ERROR)
    return logger
