"""Request-scoped tracing + always-on flight recorder.

The serving stack's aggregate observability (Prometheus counters,
``Timing`` totals) says *how much* boundary wait or device idle happened,
never *which request, which lane, which chunk*. This module is the
Dapper-shaped answer (Sigelman et al. 2010 — see PAPERS.md): a trace id
minted per request at admission, carried through every hop (queue ->
lane -> chunk boundaries -> writer publish -> HTTP record), and an
exporter that writes Chrome trace-event JSON loadable in Perfetto /
``chrome://tracing``.

Design constraints, in priority order:

- **Near-zero hot-path cost.** ``record`` is one monotonic clock read +
  one bounded-deque append of a tuple; no I/O, no formatting, no string
  building on the hot path (names are preformatted by the caller at
  admission/track-creation time, not per event). A disabled tracer
  (``capacity=0``) costs one attribute test per call site.
- **Bounded memory.** Events live in a ring (``collections.deque`` with
  ``maxlen``): a week-long serve run retains the newest ``capacity``
  events and silently drops the oldest — by construction, never by
  backpressure. CPython's deque append is GIL-atomic, so scheduler,
  writer, and gateway threads append without contending a lock.
- **Always-on flight recorder.** Recording runs even with ``--trace``
  off: when a watchdog fires, a lane is quarantined after its rollback
  budget, or the scheduler loop crashes, the ring is dumped atomically to
  ``<dir>/flightrec-<ts>.trace.json`` — the last N events *before* the
  fault, exactly what a postmortem needs and exactly what aggregate
  counters can never give. ``--trace-buffer 0`` / ``HEAT_TPU_TRACE=off``
  opts out of even this.

Event model (Chrome trace-event format, the subset Perfetto renders):

- ``X`` complete spans (ts + dur) on a (pid, tid) *track* — lane
  occupancy, chunk in flight, boundary fetch, writer jobs, HTTP handling;
- ``i`` instants — enqueue, rollback, quarantine, watchdog, growth,
  numerics verdicts (steady-state, numerics-violation), and steady-exit
  retirements whose args carry ``at_step`` vs ``predicted_at_step`` so
  predictor misses are triageable in Perfetto;
- ``C`` counter samples — the numerics observatory's per-lane residual
  and total-heat series, one sample per chunk boundary, rendered by
  Perfetto as stacked counter tracks;
- ``b``/``e`` async spans (id-paired, overlap-safe) — per-request queue
  wait, which can overlap arbitrarily on one tenant track;
- ``s``/``t``/``f`` flow events (id = the request's trace id) stitching
  one request's hops across threads: submit (gateway/JSONL thread) ->
  lane admission (lane track) -> retirement -> terminal record emission
  (writer thread).

Tracks are registered names: one *process* row per bucket group with one
*thread* row per lane (the lane occupancy timeline), plus process rows
for the scheduler / writer / gateway threads and the admission queues.
"""

from __future__ import annotations

import collections
import itertools
import json
import os
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from . import debug
from .logging import master_print

# Ring capacity default: tuples are ~150 B, so the always-on recorder
# holds ~5 MiB at worst — hours of serve traffic at typical boundary
# rates, and the knob (--trace-buffer / ServeConfig.trace_buffer) is
# right there when a long wave needs more.
DEFAULT_BUFFER = 32768

ENV_VAR = "HEAT_TPU_TRACE"
_ENV_OFF = ("off", "0", "none", "")

# Flight dumps are a postmortem tool, not a log stream: a storm of
# watchdog fires across many bucket groups must not write a dump per
# group for the same incident.
MAX_FLIGHT_DUMPS = 8

# Uptime zero point for /metrics' heat_tpu_process_uptime_seconds (and
# anything else that wants "since this process started").
PROCESS_START = time.monotonic()


def process_uptime_s() -> float:
    return time.monotonic() - PROCESS_START


# Event tuples: (ts, dur, ph, name, cat, pid, tid, xid, args)
#   ts/dur   seconds on the time.perf_counter clock (the scheduler's
#            wall_clock seam uses the same clock, so queue-wait spans can
#            reuse submit timestamps verbatim); dur None except for "X"
#   ph       Chrome phase: X i b e s t f C
#   xid      trace/flow/async id (string) or None
#   args     small dict or None — the caller must not mutate it afterwards


class Tracer:
    """A bounded in-memory event ring with Chrome-trace export.

    One per serving engine (``Engine.tracer``) plus a process-global one
    for the solo ``drive()`` path (``get_tracer()``)."""

    def __init__(self, capacity: int = DEFAULT_BUFFER):
        self.capacity = int(capacity)
        self.enabled = self.capacity > 0
        self._buf: collections.deque = collections.deque(
            maxlen=max(1, self.capacity))
        self._lock = debug.make_lock(
            "observatory:trace")          # track registry + export only;
                                            # never taken on the event path
        self._procs: Dict[str, int] = {}    # process name -> pid
        self._tracks: Dict[Tuple[str, str], Tuple[int, int]] = {}
        self._track_names: Dict[Tuple[int, int], Tuple[str, str]] = {}
        self._ids = itertools.count(1)
        self._id_prefix = f"{os.getpid():x}"
        self.dumps = 0                      # flight dumps written
        self.dump_paths: List[str] = []     # where they landed (the
                                            # flightrec record + /statusz
                                            # name these so operators
                                            # never grep the filesystem)
        self.dropped_hint = False           # ring wrapped at least once
        self._appended = 0
        # race sanitizer (no-op unless HEAT_TPU_RACECHECK): the exempt
        # trio is the allow-marked lock-free ring — _append stays a
        # zero-instrumentation hot path even when the sanitizer is armed
        debug.instrument_races(
            self, label="Tracer",
            exempt=frozenset({"_buf", "_appended", "dropped_hint"}))

    # --- identity ---------------------------------------------------------
    def mint_trace_id(self) -> str:
        """A process-unique request trace id (echoed in records and the
        ``X-Trace-Id`` header; doubles as the flow id that stitches the
        request's hops). Minted even when recording is disabled so the
        record schema never depends on tracing state."""
        return f"{self._id_prefix}-{next(self._ids):04x}"

    # --- tracks -----------------------------------------------------------
    def track(self, process: str, thread: str) -> Tuple[int, int]:
        """The (pid, tid) for a named track, registered on first use.
        Call at setup time (lane install, runner construction) and keep
        the tuple — the registry lookup is locked and not meant for the
        per-event path."""
        key = (process, thread)
        t = self._tracks.get(key)
        if t is not None:
            return t
        with self._lock:
            t = self._tracks.get(key)
            if t is None:
                pid = self._procs.setdefault(process, len(self._procs) + 1)
                t = (pid, sum(1 for k in self._tracks if k[0] == process) + 1)
                self._tracks[key] = t
                self._track_names[t] = key
        return t

    def thread_track(self, process: str = "threads") -> Tuple[int, int]:
        """Track for the calling thread (scheduler loop, gateway handler,
        snapshot writer): one row per live thread name."""
        return self.track(process, threading.current_thread().name)

    # --- recording (the hot path) -----------------------------------------
    def now(self) -> float:
        return time.perf_counter()

    def complete(self, name: str, track: Tuple[int, int], t0: float,
                 t1: Optional[float] = None, cat: str = "serve",
                 trace_id: Optional[str] = None, args: Optional[dict] = None
                 ) -> None:
        """One finished span [t0, t1] on ``track`` (phase "X")."""
        if not self.enabled:
            return
        if t1 is None:
            t1 = time.perf_counter()
        self._append((t0, t1 - t0, "X", name, cat, track[0], track[1],
                      trace_id, args))

    def instant(self, name: str, track: Tuple[int, int], cat: str = "serve",
                trace_id: Optional[str] = None, args: Optional[dict] = None,
                ts: Optional[float] = None) -> None:
        if not self.enabled:
            return
        self._append((time.perf_counter() if ts is None else ts, None, "i",
                      name, cat, track[0], track[1], trace_id, args))

    def counter(self, name: str, track: Tuple[int, int], values: dict,
                cat: str = "numerics", ts: Optional[float] = None) -> None:
        """One sample of a named counter track (phase "C"): ``values``
        maps series name -> number and must not be mutated by the caller
        afterwards (same no-copy contract as every ``args`` here). The
        numerics observatory emits one of these per lane per chunk
        boundary — residual + total heat riding the boundary vector."""
        if not self.enabled:
            return
        self._append((time.perf_counter() if ts is None else ts, None, "C",
                      name, cat, track[0], track[1], None, values))

    def flow(self, phase: str, track: Tuple[int, int], flow_id: str,
             name: str = "request", ts: Optional[float] = None) -> None:
        """One hop of a cross-thread flow arrow: phase "s" (start at
        submit), "t" (step: admission, retirement), "f" (end: terminal
        record emitted). All hops of one request share ``flow_id`` (its
        trace id)."""
        if not self.enabled:
            return
        self._append((time.perf_counter() if ts is None else ts, None,
                      phase, name, "request", track[0], track[1], flow_id,
                      None))

    def async_span(self, name: str, track: Tuple[int, int], t0: float,
                   t1: float, xid: str, cat: str = "queue",
                   args: Optional[dict] = None) -> None:
        """An id-paired async span ("b"/"e"): unlike "X" spans these may
        overlap freely on one track (many requests of one tenant waiting
        at once), which is exactly the queue-wait shape."""
        if not self.enabled:
            return
        self._append((t0, None, "b", name, cat, track[0], track[1], xid,
                      args))
        self._append((t1, None, "e", name, cat, track[0], track[1], xid,
                      None))

    def _append(self, ev: tuple) -> None:
        self._appended += 1
        if self._appended > self.capacity:  # heat-tpu: allow[races] lock-free ring by design — deque.append is GIL-atomic and _appended/dropped_hint are advisory drop hints where a lost update only blurs the hint, so the span hot path takes no lock
            self.dropped_hint = True
        self._buf.append(ev)

    def __len__(self) -> int:
        return len(self._buf)

    # --- export -----------------------------------------------------------
    def snapshot(self) -> List[tuple]:
        # deque -> tuple is a C-level walk with no Python re-entry, so it
        # is consistent under the GIL against concurrent appends
        return list(tuple(self._buf))

    def to_chrome(self, events: Optional[List[tuple]] = None) -> dict:
        """The ring (or ``events``) as a Chrome trace-event JSON object.
        Timestamps are exported in microseconds relative to the earliest
        event; events are sorted, so per-track ``ts`` is monotone."""
        evs = self.snapshot() if events is None else list(events)
        evs.sort(key=lambda e: e[0])
        t0 = evs[0][0] if evs else 0.0
        out = []
        with self._lock:
            names = dict(self._track_names)
        seen_pids = set()
        for (pid, tid), (pname, tname) in sorted(names.items()):
            if pid not in seen_pids:
                seen_pids.add(pid)
                out.append({"ph": "M", "ts": 0, "pid": pid, "tid": 0,
                            "name": "process_name",
                            "args": {"name": pname}})
            out.append({"ph": "M", "ts": 0, "pid": pid, "tid": tid,
                        "name": "thread_name", "args": {"name": tname}})
        for ts, dur, ph, name, cat, pid, tid, xid, args in evs:
            e = {"ph": ph, "ts": round((ts - t0) * 1e6, 3), "pid": pid,
                 "tid": tid, "name": name, "cat": cat}
            if ph == "X":
                e["dur"] = round((dur or 0.0) * 1e6, 3)
            elif ph == "i":
                e["s"] = "t"
            if ph in ("s", "t", "f"):
                e["id"] = xid
                e["bp"] = "e"
            elif ph in ("b", "e"):
                e["id"] = xid
            a = dict(args) if args else {}
            if xid is not None and ph in ("X", "i", "b"):
                a["trace_id"] = xid
            if a:
                e["args"] = a
            out.append(e)
        return {"traceEvents": out, "displayTimeUnit": "ms"}

    def export(self, path, events: Optional[List[tuple]] = None) -> Path:
        """Write the Chrome trace JSON atomically (same torn-file
        discipline as every other publish in this repo: temp name outside
        any discovery glob, then rename)."""
        path = Path(path)
        if path.parent:
            path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_name(path.name + ".tmp")
        with open(tmp, "w") as f:
            json.dump(self.to_chrome(events), f)
        tmp.rename(path)
        return path

    def flight_dump(self, out_dir, reason: str) -> Optional[Path]:
        """Dump the ring to ``<out_dir>/flightrec-<ts>.trace.json`` (the
        flight-recorder exit: watchdog fire, quarantine-after-rollbacks,
        scheduler crash). Bounded per tracer (``MAX_FLIGHT_DUMPS``) and
        never allowed to raise into the failure path it is documenting."""
        with self._lock:
            # atomic slot reserve: concurrent failure paths (watchdog on
            # the scheduler thread, a client shutdown) must not both pass
            # the bound check and overshoot MAX_FLIGHT_DUMPS
            if not self.enabled or self.dumps >= MAX_FLIGHT_DUMPS:
                return None
            self.dumps += 1
            seq = self.dumps
        stamp = time.strftime("%Y%m%dT%H%M%S")
        path = Path(out_dir) / f"flightrec-{stamp}-{seq}.trace.json"
        try:
            self.export(path)
        except OSError as e:
            master_print(f"flight recorder: dump to {path} failed ({e}) — "
                         f"continuing without it")
            return None
        with self._lock:
            self.dump_paths.append(str(path))
        master_print(f"flight recorder: {reason} — dumped {len(self._buf)} "
                     f"event(s) to {path}")
        return path


# --- CLI/env resolution -------------------------------------------------------

def resolve_trace(path_flag: Optional[str],
                  buffer_flag: Optional[int]) -> Tuple[Optional[str], int]:
    """Fold ``--trace FILE`` / ``--trace-buffer N`` / ``HEAT_TPU_TRACE``
    into (export path or None, ring capacity).

    ``HEAT_TPU_TRACE=FILE`` is the env spelling of ``--trace FILE`` (the
    flag wins); ``HEAT_TPU_TRACE=off`` (or ``0``) disables recording
    entirely — no flight recorder, no export. An explicit
    ``--trace-buffer`` always sets the capacity; asking for an export
    with a zero buffer is a contradiction and rejected loudly."""
    env = os.environ.get(ENV_VAR, "").strip()
    env_off = env.lower() in _ENV_OFF
    path = path_flag or (None if env_off else env or None)
    if buffer_flag is not None:
        if buffer_flag < 0:
            raise ValueError(f"--trace-buffer must be >= 0 (0 disables "
                             f"recording), got {buffer_flag}")
        capacity = buffer_flag
    else:
        capacity = 0 if (env_off and env) and not path_flag else DEFAULT_BUFFER
    if path and capacity == 0:
        raise ValueError("--trace needs a non-zero --trace-buffer (the "
                         "export is the ring's contents)")
    return path, capacity


# --- process-global tracer (the solo drive() path) ----------------------------

_GLOBAL: Optional[Tracer] = None
_GLOBAL_LOCK = threading.Lock()


def get_tracer() -> Tracer:
    """The process-global tracer the solo ``drive()`` path records into
    (serving engines own theirs — ``Engine.tracer``). Created lazily with
    the default flight-recorder capacity; ``configure`` replaces it."""
    global _GLOBAL
    if _GLOBAL is None:
        with _GLOBAL_LOCK:
            if _GLOBAL is None:
                _GLOBAL = Tracer()
    return _GLOBAL


def configure(capacity: int = DEFAULT_BUFFER) -> Tracer:
    global _GLOBAL
    with _GLOBAL_LOCK:
        _GLOBAL = Tracer(capacity=capacity)
    return _GLOBAL


# --- text summary (`heat-tpu trace FILE`) -------------------------------------

def summarize(chrome: dict, top: int = 5) -> List[str]:
    """Render a text timeline summary from a Chrome trace object (a
    ``--trace`` export, a flight dump, or a ``/tracez`` response): wall
    span, per-lane utilization per bucket group, top queue-wait requests,
    boundary-fetch/device-idle totals, and notable instants."""
    if isinstance(chrome, list):      # the bare-array trace form
        chrome = {"traceEvents": chrome}
    evs = chrome.get("traceEvents", [])
    procs: Dict[int, str] = {}
    threads: Dict[Tuple[int, int], str] = {}
    for e in evs:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            procs[e["pid"]] = e["args"]["name"]
        elif e.get("name") == "thread_name":
            threads[(e["pid"], e["tid"])] = e["args"]["name"]
    data = [e for e in evs if e.get("ph") != "M"]
    if not data:
        return ["trace: no events (buffer empty — see TROUBLESHOOTING: "
                "was the ring too small, or tracing disabled?)"]
    t_lo = min(e["ts"] for e in data)
    t_hi = max(e["ts"] + e.get("dur", 0.0) for e in data)
    wall = max(t_hi - t_lo, 1e-9)
    lines = [f"trace: {len(data)} event(s) over {wall / 1e6:.3f}s across "
             f"{len(threads)} track(s)"]

    # per-lane utilization: X spans on "lane N" tracks of "lanes ..." rows
    busy: Dict[Tuple[int, int], float] = collections.defaultdict(float)
    reqs: Dict[Tuple[int, int], int] = collections.defaultdict(int)
    for e in data:
        if e.get("ph") != "X":
            continue
        key = (e["pid"], e["tid"])
        if (procs.get(e["pid"], "").startswith("lanes")
                and threads.get(key, "").startswith("lane")):
            busy[key] += e.get("dur", 0.0)
            reqs[key] += 1
    if busy:
        lines.append("lane utilization (occupancy wall / trace wall):")
        for key in sorted(busy):
            lines.append(
                f"  {procs.get(key[0], key[0])} {threads.get(key, key[1])}: "
                f"{100.0 * busy[key] / wall:5.1f}% "
                f"({reqs[key]} request(s))")

    # top queue waits: b/e pairs named queue-wait, id-paired
    begins: Dict[str, dict] = {}
    waits: List[Tuple[float, str, dict]] = []
    for e in data:
        if e.get("name") != "queue-wait":
            continue
        if e.get("ph") == "b":
            begins[e.get("id")] = e
        elif e.get("ph") == "e" and e.get("id") in begins:
            b = begins.pop(e["id"])
            waits.append((e["ts"] - b["ts"], e["id"],
                          b.get("args", {})))
    if waits:
        waits.sort(reverse=True, key=lambda w: w[0])
        lines.append(f"top queue waits (of {len(waits)}):")
        for dur, xid, args in waits[:top]:
            lines.append(f"  {args.get('id', xid)}: {dur / 1e6:.3f}s "
                         f"(tenant {args.get('tenant', '?')}, "
                         f"class {args.get('class', '?')}, "
                         f"policy {args.get('policy', '?')})")

    for name, label in (("boundary-fetch", "boundary-fetch wall"),
                        ("device-idle", "device-idle wall")):
        tot = sum(e.get("dur", 0.0) for e in data
                  if e.get("ph") == "X" and e.get("name") == name)
        n = sum(1 for e in data if e.get("ph") == "X"
                and e.get("name") == name)
        if n:
            lines.append(f"{label}: {tot / 1e6:.3f}s over {n} span(s) "
                         f"({100.0 * tot / wall:.1f}% of trace wall)")

    # counter tracks ("C" samples — the numerics observatory's per-lane
    # residual/heat series): min/max/last per series, so a text triage
    # shows whether a residual was still falling when the trace ended
    counters: Dict[Tuple[str, str], List[float]] = collections.defaultdict(list)
    for e in data:
        if e.get("ph") != "C":
            continue
        for series, v in (e.get("args") or {}).items():
            if isinstance(v, (int, float)):
                counters[(e.get("name", "?"), series)].append(float(v))
    if counters:
        lines.append("counter tracks:")
        for (name, series), vals in sorted(counters.items()):
            lines.append(
                f"  {name}/{series}: {len(vals)} sample(s), "
                f"min {min(vals):.3g}, max {max(vals):.3g}, "
                f"last {vals[-1]:.3g}")

    notable = collections.Counter(
        e["name"] for e in data if e.get("ph") == "i"
        and e.get("name") in ("watchdog-fired", "rollback", "quarantine",
                              "deadline-shed", "lane-tier-grow",
                              "numerics-violation", "steady-state",
                              "steady-exit"))
    if notable:
        lines.append("events: " + ", ".join(
            f"{n} {k}" for k, n in sorted(notable.items())))
    return lines


def summarize_file(path, top: int = 5) -> List[str]:
    with open(path) as f:
        return summarize(json.load(f), top=top)
