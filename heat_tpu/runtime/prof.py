"""Performance & cost observatory: the serving stack's metering layer.

PR 7 (runtime/trace.py) answered *where time went* for one request; this
module answers *what work costs* in aggregate — the signal layer the
ROADMAP's pod-scale router and elastic-autoscaler items need to make
placement decisions (Orca-style schedulers consume cost/utilization
signals, not per-request timelines; see PAPERS.md). Everything here is
fed from timestamps the scheduler already takes (dispatch->fetch deltas,
boundary waits, terminal-record transitions) — **zero new hot-path
device syncs** — and the whole layer switches off with
``ServeConfig(prof=False)`` / ``heat-tpu serve --prof off``
(the usage stamps on records stay, so the record schema never flickers;
only the aggregation/model/sampling work stops).

Five instruments, one :class:`Observatory` per serving engine:

- :class:`CostModel` — the **online chunk-cost model**: per
  (bucket, lane-tier, dispatch-depth) EWMA + histogram of
  seconds-per-lane-step, learned from chunk-boundary service times.
  The observation is the classic queueing service-time estimator
  ``t_fetch_done - max(prev_fetch_done, t_dispatch)``: exact under a
  fenced boundary (depth 0/1), and equal to the per-chunk service time
  under a saturated dispatch-ahead pipeline (successive boundary
  completions are spaced one chunk apart). Exported through
  ``Engine.summary()["cost_model"]``, ``/metrics`` gauges, the
  ``GET /statusz`` snapshot, and cross-checked against the static
  ``benchmarks/calibration_v5e.json`` by ``heat-tpu perfcheck`` — the
  live counterpart of that file's one-off fit, and the number a future
  autoscaler grows/shrinks lanes against instead of a constant.
- :class:`CompileLog` — the **compile observatory**: a process-wide
  structured log of every chunk-program compile
  (``backends/common.aot_compile_chunks`` — the one compile path: the
  solo drive warmup and the serve engine's lazy tail/tier compiles both
  funnel through it), with key, wall seconds, and first-vs-warm (was
  this (key, k) compiled before in this process — re-compiles are the
  persistent-cache-warm case and their wall says whether that cache is
  actually working). Surfaced as trace spans (scheduler's on_compile
  hook), ``/metrics`` counters, and a ``heat-tpu info`` line.
- :class:`MemWatermark` — **memory watermarks + leak sentinel**: polls
  device memory stats (or ``jax.live_arrays()`` where the backend has
  no allocator stats — the CPU case) every N chunk boundaries, off the
  hot path, tracking peak bytes and the growth slope over a rolling
  window. Monotone growth across the whole window past a byte floor —
  the rollback-stack / lane-grow leak shape, where every sample is
  higher than the last — emits ONE structured ``mem_watermark`` warning
  record (re-armed only after the level doubles again, so a long run
  cannot log-storm).
- :class:`UsageLedger` — the **per-tenant usage ledger**: every terminal
  record is stamped with its resource usage (lane-seconds, steps,
  chunks, bytes written) by the scheduler; the ledger aggregates the
  exact same stamps per (tenant, class), so ``GET /v1/usage`` totals
  reconcile *exactly* with the sum over per-request records — the
  attribution layer "millions of users" billing/quota needs.
- :class:`BurnMonitor` — the **SLO burn-rate monitor**: per-class
  rolling deadline-hit windows (fast + slow, Google-SRE multiwindow
  shape) over requests that carried a deadline. Burn rate is
  ``miss_fraction / error_budget`` (budget = 1 - target,
  ``config.SLO_TARGETS``): 1.0 means the class burns its budget exactly
  as fast as allowed; sustained >1 exhausts it early. When BOTH windows
  burn above the threshold the monitor returns one structured
  ``slo_alert`` (cooldown-limited) — the *proactive* signal, hours
  before the aggregate deadline-hit ratio visibly degrades.

Thread-safety/lock-ordering contract: every instrument carries its own
small lock and NONE of them ever takes the engine lock — the engine
calls *into* the observatory (sometimes while holding its own lock, e.g.
``_emit``), and the gateway's ``/metrics``/``/statusz``/``/v1/usage``
scrape threads call snapshot methods that take only observatory locks.
Lock order is therefore always engine -> observatory, never the
reverse: a scrape can never deadlock against the boundary hot path
(regression-tested by the concurrent-scrape tests).
"""

from __future__ import annotations

import collections
import itertools
import math
import threading
import time
from typing import Dict, List, Optional, Tuple

from . import debug

# --- /metrics histogram primitive (moved here from serve/policy.py so the
# --- observatory owns its primitives without a runtime -> serve import;
# --- policy.py re-exports for its existing consumers) --------------------

# Latency-shaped default buckets (seconds): sub-ms admission rejections up
# through minute-scale batch solves; queue-depth histograms reuse the same
# machinery with integer buckets.
LATENCY_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)
DEPTH_BUCKETS = (0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0)
# Per-lane-step seconds span ~7 decades between a warm TPU lane and a
# cold one-core CPU host: log-spaced buckets or the histogram says nothing
LANE_STEP_BUCKETS = tuple(10.0 ** e for e in range(-8, 1))


class Histogram:
    """A Prometheus-style cumulative histogram (stdlib-only).

    ``observe`` is called from the scheduler AND writer threads, so it
    carries its own lock (deliberately not the engine lock: a /metrics
    scrape must never contend with the boundary hot path for the lock
    that guards admission)."""

    def __init__(self, buckets=LATENCY_BUCKETS):
        self.buckets = tuple(buckets)
        self._counts = [0] * (len(self.buckets) + 1)  # +Inf tail
        self._sum = 0.0
        self._n = 0
        self._lock = debug.make_lock("observatory:hist")

    def observe(self, v: float) -> None:
        with self._lock:
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self._counts[i] += 1
                    break
            else:
                self._counts[-1] += 1
            self._sum += v
            self._n += 1

    def snapshot(self) -> dict:
        """Cumulative (le -> count) pairs + sum/count, scrape-consistent."""
        with self._lock:
            counts = list(self._counts)
            total_sum, n = self._sum, self._n
        cum = list(itertools.accumulate(counts))
        les = [*(f"{b:g}" for b in self.buckets), "+Inf"]
        return {"buckets": list(zip(les, cum)), "sum": total_sum, "count": n}

    def quantile(self, q: float) -> Optional[float]:
        """Bucket-upper-bound estimate of the q-quantile (the benchmark's
        p50/p95/p99 reporting; None when empty). Conservative: returns the
        smallest bucket bound covering q of the observations."""
        snap = self.snapshot()
        if not snap["count"]:
            return None
        target = q * snap["count"]
        for le, cum in snap["buckets"]:
            if cum >= target:
                return math.inf if le == "+Inf" else float(le)
        return math.inf


# --- (a) online chunk-cost model ---------------------------------------------

# EWMA smoothing: ~the last 10 boundaries dominate — fast enough to track
# a thermal/occupancy shift inside one wave, slow enough that one noisy
# fetch doesn't whipsaw a placement decision.
COST_EWMA_ALPHA = 0.2


class _CostEntry:
    __slots__ = ("ewma", "count", "wall_s", "lane_steps", "hist", "last")

    def __init__(self):
        self.ewma: Optional[float] = None   # s per lane-step
        self.count = 0                      # boundaries observed
        self.wall_s = 0.0                   # total observed chunk service s
        self.lane_steps = 0                 # total lane-steps covered
        self.hist = Histogram(LANE_STEP_BUCKETS)
        self.last: Optional[float] = None   # newest s per lane-step


class CostModel:
    """Online per-(bucket, lane-tier, dispatch-depth, kernel) chunk-cost
    EWMA.

    ``observe(bucket, lanes, depth, k, wall_s, kernel=...)`` records one
    chunk boundary's service time (``wall_s`` seconds for ``k`` steps of
    ``lanes`` lanes); the normalized unit is seconds per *lane-step* —
    the number a placement/autoscaling decision compares across buckets
    (cells/s for a bucket of side B falls out as ``B^ndim /
    s_per_lane_step``, the cross-check ``heat-tpu perfcheck`` runs
    against calibration_v5e.json). ``kernel`` names the chunk-program
    body ("xla" — the vmapped oracle — or "pallas", the multi-lane
    kernel family): the two are different machines with different cost
    curves, so one EWMA must never average across them (the live half
    of the serve lane-kernel A/B, benchmarks/serve_lane_kernel_lab.py)."""

    def __init__(self, alpha: float = COST_EWMA_ALPHA):
        self.alpha = float(alpha)
        self._entries: Dict[Tuple[str, int, int, str, str], _CostEntry] = {}
        self._lock = debug.make_lock("observatory:cost")

    def observe(self, bucket: str, lanes: int, depth: int, k: int,
                wall_s: float, kernel: str = "xla",
                placement: str = "packed") -> None:
        if wall_s < 0 or k < 1 or lanes < 1:
            return
        per = wall_s / (k * lanes)
        key = (bucket, lanes, depth, kernel, placement)
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                e = self._entries[key] = _CostEntry()
            e.ewma = (per if e.ewma is None
                      else (1 - self.alpha) * e.ewma + self.alpha * per)
            e.count += 1
            e.wall_s += wall_s
            e.lane_steps += k * lanes
            e.last = per
        e.hist.observe(per)   # histogram carries its own lock

    def estimate_s_per_lane_step(self, bucket: str, lanes: int, depth: int,
                                 kernel: str = "xla",
                                 placement: str = "packed"
                                 ) -> Optional[float]:
        with self._lock:
            e = self._entries.get((bucket, lanes, depth, kernel, placement))
            return None if e is None else e.ewma

    def estimate_request_s(self, bucket: str, lanes: int, depth: int,
                           ntime: int, kernel: str = "xla",
                           placement: str = "packed") -> Optional[float]:
        """Predicted wall for one request of ``ntime`` steps admitted to
        this (bucket, tier): its lane advances one step whenever the
        whole group does, and a group step costs ``lanes *
        s_per_lane_step`` — queue wait excluded (that is the admission
        policy's number, not the chunk program's). Semantic scheduling
        passes the PREDICTED step count here instead of the nominal one
        for ``until=steady`` admissions (scheduler._forecast_wall), so
        the forecast reflects the steps the request is expected to run."""
        per = self.estimate_s_per_lane_step(bucket, lanes, depth, kernel,
                                            placement)
        return None if per is None else per * lanes * ntime

    def snapshot(self) -> List[dict]:
        """Scrape-consistent list of per-key stats (summary()/ /metrics/
        /statusz all render from this one shape)."""
        with self._lock:
            items = list(self._entries.items())
        out = []
        for (bucket, lanes, depth, kernel, placement), e in sorted(items):
            mean = e.wall_s / e.lane_steps if e.lane_steps else None
            out.append({
                "bucket": bucket, "lanes": lanes, "depth": depth,
                "kernel": kernel, "placement": placement,
                "chunks": e.count,
                "ewma_s_per_lane_step": e.ewma,
                "mean_s_per_lane_step": mean,
                "last_s_per_lane_step": e.last,
                "p50_s_per_lane_step": e.hist.quantile(0.5),
                "p95_s_per_lane_step": e.hist.quantile(0.95),
                "wall_s": round(e.wall_s, 6),
            })
        return out


# --- (b) compile observatory -------------------------------------------------

# The structured compile log is process-wide (module singleton), not
# per-engine: aot_compile_chunks is called by the solo drive() warmup,
# the sharded compile guard, AND every lane engine — one log answers
# "what did this process compile, when, and was the persistent cache
# warm" for all of them.
COMPILE_LOG_CAPACITY = 512


class CompileLog:
    """Bounded structured log of chunk-program compiles."""

    def __init__(self, capacity: int = COMPILE_LOG_CAPACITY):
        self._events: collections.deque = collections.deque(maxlen=capacity)
        self._seen: set = set()
        self._lock = debug.make_lock("observatory:compile")
        self.programs = 0
        self.total_s = 0.0
        self.first_s = 0.0       # wall spent on first-time keys
        self.warm_s = 0.0        # wall spent re-compiling seen keys

    def note(self, label: str, k: int, seconds: float) -> dict:
        """Record one actually-performed compile (cache hits never reach
        here). ``first`` marks a (label, k) never compiled before in this
        process — a warm re-compile's wall is the persistent compile
        cache's report card."""
        key = (label, k)
        with self._lock:
            first = key not in self._seen
            self._seen.add(key)
            ev = {"label": label, "k": int(k),
                  "seconds": round(float(seconds), 6), "first": first,
                  "ts": time.perf_counter()}
            self._events.append(ev)
            self.programs += 1
            self.total_s += seconds
            if first:
                self.first_s += seconds
            else:
                self.warm_s += seconds
        return ev

    def snapshot(self) -> List[dict]:
        with self._lock:
            return [dict(e) for e in self._events]

    def summary(self) -> dict:
        with self._lock:
            return {"programs": self.programs,
                    "distinct": len(self._seen),
                    "total_s": round(self.total_s, 3),
                    "first_s": round(self.first_s, 3),
                    "warm_s": round(self.warm_s, 3)}


_COMPILE_LOG: Optional[CompileLog] = None
_COMPILE_LOG_LOCK = threading.Lock()


def compile_log() -> CompileLog:
    global _COMPILE_LOG
    if _COMPILE_LOG is None:
        with _COMPILE_LOG_LOCK:
            if _COMPILE_LOG is None:
                _COMPILE_LOG = CompileLog()
    return _COMPILE_LOG


# --- (c) memory watermarks + leak sentinel -----------------------------------

def device_memory_bytes() -> Tuple[Optional[int], str]:
    """Current device-memory usage in bytes, best source available:
    allocator stats where the backend exposes them (TPU/GPU
    ``memory_stats()['bytes_in_use']``), else the summed ``nbytes`` of
    every live jax array (the CPU backend's honest proxy — it sees the
    rollback stacks and lane buffers a leak would grow). ``(None,
    "unavailable")`` when jax itself is absent/uninitialized."""
    try:
        import jax

        try:
            stats = jax.local_devices()[0].memory_stats()
        except Exception:  # noqa: BLE001 — stats are best-effort
            stats = None
        if stats and stats.get("bytes_in_use") is not None:
            return int(stats["bytes_in_use"]), "device"
        return (int(sum(int(getattr(a, "nbytes", 0) or 0)
                        for a in jax.live_arrays())), "live_arrays")
    except Exception:  # noqa: BLE001 — a metering layer must never raise
        return None, "unavailable"


# Leak sentinel tuning: the window must be long enough that admission
# churn (a wave draining) shows *some* decrease, and the byte floor high
# enough that per-boundary jitter (a handle, a snapshot row) never trips
# it. A real rollback-stack or lane-grow leak adds a full lane stack per
# event — megabytes — and is strictly monotone.
MEM_WINDOW = 8
MEM_MIN_GROWTH_BYTES = 16 << 20   # 16 MiB across the window


class MemWatermark:
    """Rolling device-memory samples: peak, growth slope, leak warning."""

    def __init__(self, window: int = MEM_WINDOW,
                 min_growth_bytes: int = MEM_MIN_GROWTH_BYTES):
        self.window = max(2, int(window))
        self.min_growth = int(min_growth_bytes)
        self._samples: collections.deque = collections.deque(
            maxlen=self.window)
        self._lock = debug.make_lock("observatory:mem")
        self.peak: Optional[int] = None
        self.last: Optional[int] = None
        self.source = "unavailable"
        self.samples_taken = 0
        self.warnings = 0
        self._rearm_at: Optional[int] = None   # warn again only past this

    def note(self, nbytes: Optional[int], ts: float,
             source: str = "device") -> Optional[dict]:
        """Record one sample; returns a ``mem_watermark`` warning payload
        when the leak sentinel fires (monotone growth across the full
        window past the byte floor), else None."""
        if nbytes is None:
            return None
        with self._lock:
            self.samples_taken += 1
            self.last = int(nbytes)
            self.source = source
            if self.peak is None or nbytes > self.peak:
                self.peak = int(nbytes)
            self._samples.append((float(ts), int(nbytes)))
            if len(self._samples) < self.window:
                return None
            vals = [v for _, v in self._samples]
            growth = vals[-1] - vals[0]
            monotone = all(b > a for a, b in zip(vals, vals[1:]))
            if not monotone or growth < self.min_growth:
                return None
            if self._rearm_at is not None and vals[-1] < self._rearm_at:
                return None
            # one warning per level: re-arm only once usage doubles again,
            # so a slow leak warns at 2x, 4x, ... instead of every window
            self._rearm_at = vals[-1] * 2
            self.warnings += 1
            dt = self._samples[-1][0] - self._samples[0][0]
            return {"bytes_in_use": vals[-1], "peak_bytes": self.peak,
                    "growth_bytes": growth,
                    "window_samples": len(vals),
                    "window_s": round(dt, 3),
                    "slope_bytes_per_s": (round(growth / dt, 1)
                                          if dt > 0 else None),
                    "source": source}

    def snapshot(self) -> dict:
        with self._lock:
            return {"peak_bytes": self.peak, "last_bytes": self.last,
                    "source": self.source,
                    "samples": self.samples_taken,
                    "warnings": self.warnings}


# --- (d) per-tenant usage ledger ---------------------------------------------

# "steps" bills the steps a request ACTUALLY ran (below ntime for an
# until=steady early exit); "steps_saved" credits the steps a steady
# exit did not run — saved device time billed as saved (ISSUE 16).
# "cached" marks a solve-cache full hit (ISSUE 19): billed zero
# lane_s/steps, hit counted — on records it is a bool, in ledger cells
# it sums to the cell's hit count.
USAGE_FIELDS = ("lane_s", "steps", "chunks", "bytes_written",
                "steps_saved", "cached")


def empty_usage() -> dict:
    """The usage stamp every terminal record carries (schema-stable:
    rejected requests carry zeros, not a missing key)."""
    return {"lane_s": 0.0, "steps": 0, "chunks": 0, "bytes_written": 0,
            "steps_saved": 0, "cached": False}


class _LedgerCell:
    __slots__ = ("lane_s", "steps", "chunks", "bytes_written",
                 "steps_saved", "cached", "requests", "by_status",
                 "by_placement")

    def __init__(self):
        self.lane_s = 0.0
        self.steps = 0
        self.chunks = 0
        self.bytes_written = 0
        self.steps_saved = 0
        self.cached = 0
        self.requests = 0
        self.by_status: collections.Counter = collections.Counter()
        # placement dimension (ISSUE 10): how many of this cell's
        # requests ran as packed vmapped lanes vs mesh-spanning mega
        # lanes ("none" = rejected before placement) — a mega request
        # occupies the WHOLE mesh for its lane-seconds, so billing and
        # capacity plans need the split, not just the totals
        self.by_placement: collections.Counter = collections.Counter()

    def asdict(self) -> dict:
        return {"lane_s": round(self.lane_s, 6), "steps": self.steps,
                "chunks": self.chunks, "bytes_written": self.bytes_written,
                "steps_saved": self.steps_saved, "cached": self.cached,
                "requests": self.requests, "by_status": dict(self.by_status),
                "by_placement": dict(self.by_placement)}


class UsageLedger:
    """Aggregates the exact usage stamps the scheduler writes into each
    terminal record, per (tenant, class). Adding THE SAME values that
    land on the records is what makes ``GET /v1/usage`` reconcile
    exactly against a drained run's record stream (acceptance-tested)."""

    def __init__(self):
        self._cells: Dict[Tuple[str, str], _LedgerCell] = {}
        self._lock = debug.make_lock("observatory:ledger")

    def add(self, tenant: str, slo_class: str, status: str,
            usage: dict, placement: Optional[str] = None) -> None:
        with self._lock:
            cell = self._cells.get((tenant, slo_class))
            if cell is None:
                cell = self._cells[(tenant, slo_class)] = _LedgerCell()
            cell.lane_s += float(usage.get("lane_s") or 0.0)
            cell.steps += int(usage.get("steps") or 0)
            cell.chunks += int(usage.get("chunks") or 0)
            cell.bytes_written += int(usage.get("bytes_written") or 0)
            cell.steps_saved += int(usage.get("steps_saved") or 0)
            cell.cached += int(bool(usage.get("cached")))
            cell.requests += 1
            cell.by_status[status] += 1
            cell.by_placement[placement or "none"] += 1

    def snapshot(self) -> dict:
        """``/v1/usage`` payload: per-tenant (per-class) aggregates plus
        engine-wide totals."""
        with self._lock:
            items = [((t, c), cell.asdict())
                     for (t, c), cell in self._cells.items()]
        tenants: Dict[str, dict] = {}
        totals = _LedgerCell()
        for (tenant, cls), d in sorted(items):
            tdict = tenants.setdefault(
                tenant, {"classes": {}, "lane_s": 0.0, "steps": 0,
                         "chunks": 0, "bytes_written": 0, "steps_saved": 0,
                         "cached": 0, "requests": 0})
            tdict["classes"][cls] = d
            for f in (*USAGE_FIELDS, "requests"):
                tdict[f] = (round(tdict[f] + d[f], 6)
                            if f == "lane_s" else tdict[f] + d[f])
            totals.lane_s += d["lane_s"]
            totals.steps += d["steps"]
            totals.chunks += d["chunks"]
            totals.bytes_written += d["bytes_written"]
            totals.steps_saved += d["steps_saved"]
            totals.cached += d["cached"]
            totals.requests += d["requests"]
            totals.by_status.update(d["by_status"])
            totals.by_placement.update(d.get("by_placement") or {})
        return {"tenants": tenants, "totals": totals.asdict()}


# --- (e) SLO burn-rate monitor -----------------------------------------------

# Multiwindow burn-rate defaults (the Google-SRE shape, scaled to serve
# runs that live minutes, not months): the fast window catches an acute
# burn, the slow window keeps a blip from paging. Threshold 2.0 = the
# class is burning its error budget at twice the sustainable rate in
# BOTH windows.
SLO_FAST_WINDOW_S = 300.0
SLO_SLOW_WINDOW_S = 3600.0
SLO_BURN_THRESHOLD = 2.0
SLO_ALERT_COOLDOWN_S = 300.0


class _ClassWindow:
    __slots__ = ("events", "alerts", "last_alert_t")

    def __init__(self):
        self.events: collections.deque = collections.deque()  # (ts, ok)
        self.alerts = 0
        self.last_alert_t: Optional[float] = None


class BurnMonitor:
    """Per-class rolling deadline-hit windows -> burn-rate gauges/alerts.

    Only requests that CARRIED a deadline feed the monitor (an undated
    batch request cannot miss an SLO it never had); a hit is terminal
    status ``ok``, everything else — ``deadline``, ``nonfinite``,
    ``error`` — burns budget. Timestamps come from the engine's
    ``wall_clock`` seam so tests drive the windows deterministically."""

    def __init__(self, targets: Dict[str, float],
                 fast_window_s: float = SLO_FAST_WINDOW_S,
                 slow_window_s: float = SLO_SLOW_WINDOW_S,
                 threshold: float = SLO_BURN_THRESHOLD,
                 cooldown_s: float = SLO_ALERT_COOLDOWN_S):
        self.targets = dict(targets)
        self.fast_s = float(fast_window_s)
        self.slow_s = float(slow_window_s)
        self.threshold = float(threshold)
        self.cooldown_s = float(cooldown_s)
        self._classes: Dict[str, _ClassWindow] = {}
        self._lock = debug.make_lock("observatory:burn")

    def _budget(self, cls: str) -> float:
        target = self.targets.get(cls, 0.95)
        return max(1.0 - target, 1e-9)

    @staticmethod
    def _window_stats(events, now: float, width: float) -> Tuple[int, int]:
        lo = now - width
        n = miss = 0
        for ts, ok in events:
            if ts >= lo:
                n += 1
                if not ok:
                    miss += 1
        return n, miss

    def note(self, cls: str, ok: bool, now: float) -> Optional[dict]:
        """Record one dated request's outcome; returns an ``slo_alert``
        payload when both windows burn above threshold (cooldown-
        limited), else None."""
        with self._lock:
            w = self._classes.get(cls)
            if w is None:
                w = self._classes[cls] = _ClassWindow()
            w.events.append((float(now), bool(ok)))
            lo = now - self.slow_s
            while w.events and w.events[0][0] < lo:
                w.events.popleft()
            budget = self._budget(cls)
            n_f, m_f = self._window_stats(w.events, now, self.fast_s)
            n_s, m_s = self._window_stats(w.events, now, self.slow_s)
            fast = (m_f / n_f) / budget if n_f else 0.0
            slow = (m_s / n_s) / budget if n_s else 0.0
            if fast < self.threshold or slow < self.threshold:
                return None
            if (w.last_alert_t is not None
                    and now - w.last_alert_t < self.cooldown_s):
                return None
            w.last_alert_t = now
            w.alerts += 1
            return {"class": cls,
                    "target": self.targets.get(cls, 0.95),
                    "threshold": self.threshold,
                    "fast_burn": round(fast, 3),
                    "slow_burn": round(slow, 3),
                    "fast_window_s": self.fast_s,
                    "slow_window_s": self.slow_s,
                    "fast_events": n_f, "fast_misses": m_f,
                    "slow_events": n_s, "slow_misses": m_s}

    def snapshot(self, now: float) -> Dict[str, dict]:
        with self._lock:
            items = [(cls, list(w.events), w.alerts)
                     for cls, w in self._classes.items()]
        out = {}
        for cls, events, alerts in items:
            budget = self._budget(cls)
            n_f, m_f = self._window_stats(events, now, self.fast_s)
            n_s, m_s = self._window_stats(events, now, self.slow_s)
            out[cls] = {
                "target": self.targets.get(cls, 0.95),
                "fast_burn": round((m_f / n_f) / budget if n_f else 0.0, 4),
                "slow_burn": round((m_s / n_s) / budget if n_s else 0.0, 4),
                "fast_hit_ratio": (round(1 - m_f / n_f, 4) if n_f else None),
                "slow_hit_ratio": (round(1 - m_s / n_s, 4) if n_s else None),
                "fast_events": n_f, "slow_events": n_s,
                "alerts": alerts,
            }
        return out


# --- the per-engine facade ---------------------------------------------------

MEM_POLL_EVERY_DEFAULT = 32   # chunk boundaries between memory samples


class Observatory:
    """One engine's metering facade: the scheduler feeds it timestamps it
    already has; the gateway/statusz/summary read scrape-consistent
    snapshots. ``enabled=False`` turns every feed into an early-return
    (the overhead A/B's baseline — benchmarks/prof_overhead_lab.py)."""

    def __init__(self, enabled: bool = True,
                 slo_targets: Optional[Dict[str, float]] = None,
                 mem_poll_every: int = MEM_POLL_EVERY_DEFAULT,
                 slo_fast_window_s: float = SLO_FAST_WINDOW_S,
                 slo_slow_window_s: float = SLO_SLOW_WINDOW_S,
                 slo_burn_threshold: float = SLO_BURN_THRESHOLD):
        self.enabled = bool(enabled)
        self.cost = CostModel()
        self.ledger = UsageLedger()
        self.mem = MemWatermark()
        self.burn = BurnMonitor(slo_targets or {},
                                fast_window_s=slo_fast_window_s,
                                slow_window_s=slo_slow_window_s,
                                threshold=slo_burn_threshold)
        self.mem_poll_every = int(mem_poll_every)
        self._boundaries = 0          # mem-poll cadence counter; GIL-atomic
                                      # += is fine for a sampling cadence

    # -- feeds (scheduler side) --------------------------------------------
    def observe_chunk(self, bucket: str, lanes: int, depth: int, k: int,
                      wall_s: float, kernel: str = "xla",
                      placement: str = "packed") -> None:
        if self.enabled:
            self.cost.observe(bucket, lanes, depth, k, wall_s,
                              kernel=kernel, placement=placement)

    def note_terminal(self, snap: dict, now: float) -> Optional[dict]:
        """Feed one terminal record snapshot (ledger + burn windows);
        returns an ``slo_alert`` payload or None. Called under the engine
        lock (see module doc: engine -> observatory lock order only)."""
        if not self.enabled:
            return None
        usage = snap.get("usage") or empty_usage()
        self.ledger.add(snap.get("tenant") or "default",
                        snap.get("class") or "standard",
                        snap.get("status") or "?", usage,
                        placement=snap.get("placement"))
        if (snap.get("deadline_ms") is None
                or snap.get("status") == "rejected"):
            # undated requests have no SLO to burn; rejections never ran
            # (bad request or shed — the shed counter covers overload)
            return None
        return self.burn.note(snap.get("class") or "standard",
                              snap.get("status") == "ok", now)

    def maybe_sample_memory(self, now: float,
                            force: bool = False) -> Optional[dict]:
        """Cadenced memory sample (every ``mem_poll_every`` boundaries):
        called at chunk boundaries, where the scheduler is already doing
        host bookkeeping — never inside the dispatch hot loop. Returns a
        ``mem_watermark`` warning payload when the leak sentinel fires."""
        if not self.enabled or self.mem_poll_every <= 0:
            return None
        self._boundaries += 1
        if not force and self._boundaries % self.mem_poll_every:
            return None
        nbytes, source = device_memory_bytes()
        return self.mem.note(nbytes, now, source)

    # -- snapshots (scrape side) -------------------------------------------
    def summary(self, now: float) -> dict:
        return {"cost_model": self.cost.snapshot(),
                "mem": self.mem.snapshot(),
                "slo_burn": self.burn.snapshot(now),
                "compile": compile_log().summary()}


# -- static prior (ISSUE 13) -----------------------------------------------

_STATIC_PRIOR_CACHE: Dict[Tuple[str, str], Optional[float]] = {}


def static_prior_s_per_lane_step(bucket: str,
                                 kernel: str = "xla") -> Optional[float]:
    """The program auditor's measurement-free floor on seconds per lane
    step for one cost-model bucket label (``"2d/n512/float32/edges"``):
    jaxpr-level traffic over the machine model's HBM bandwidth. Used by
    ``heat-tpu perfcheck`` to sanity-band the *learned* cost model —
    agreement within an order of magnitude catches a units bug in
    either. Returns None when the label doesn't parse or the auditor is
    unavailable (broken JAX tree); cached, since the prior is pure
    arithmetic over static config."""
    key = (bucket, kernel)
    if key not in _STATIC_PRIOR_CACHE:
        try:
            from ..analysis.programs import lane_static_prior
            _STATIC_PRIOR_CACHE[key] = lane_static_prior(bucket, kernel)
        except Exception:
            _STATIC_PRIOR_CACHE[key] = None
    return _STATIC_PRIOR_CACHE[key]
