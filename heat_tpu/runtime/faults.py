"""Deterministic fault injection — the chaos half of the robustness layer.

The reference's MPI variants simply die when a rank fails (their MPI error
codes are collected and ignored, fortran/mpi+cuda/heat.F90), and nothing in
a clean CI run exercises what happens when one does. This module makes the
failure modes *injectable and deterministic* so the crash→resume→converge
loop (cli.cmd_launch supervisor, checkpoint quarantine, async-writer retry)
is a tested subsystem instead of a hope:

- ``crash@N[:proc=P]``       — hard worker death (``os._exit``) at step >= N
- ``nan@N[:proc=P]``         — flip one cell of the field to NaN at step >= N
                               (a soft-error analog; pairs with
                               ``--on-nan rollback``)
- ``ckpt-corrupt@N``         — scribble over the checkpoint published at
                               step >= N (bitrot / torn-write analog)
- ``ckpt-truncate@N``        — cut that checkpoint file in half instead
- ``sink-error@N[:times=K]`` — the first K checkpoint writes at step >= N
                               raise a transient ``OSError(EIO)`` (the class
                               ``async_io.SnapshotWriter`` retries)
- ``sink-slow:ms=M``         — every checkpoint write sleeps M ms first
                               (backpressure / drain-timeout exercise)

Serve-scoped kinds (the serving engine's per-lane fault domains,
serve/scheduler.py — ignored by the solo drive loop):

- ``lane-nan@N[:req=ID]``    — poison one cell of a serving lane's field
                               with NaN once that lane's request has
                               completed >= N steps (fire-once per
                               request). In a request's own ``inject``
                               the fault targets that request; in the
                               engine-level spec (``heat-tpu serve
                               --inject`` / env) ``req=ID`` selects one
                               request id, no ``req=`` poisons every
                               request. Pairs with ``--serve-on-nan``.
- ``fetch-hang[@N]:ms=M``    — the first boundary remaining-vector fetch
                               (the Nth one with ``@N``) sleeps M ms
                               before transferring: a wedged-device
                               analog for the boundary fetch watchdog
                               (fire-once).
- ``engine-kill@N``          — SIGKILL the serve process once the engine
                               has processed >= N chunk boundaries
                               (engine-wide counter, every runner).
                               The hard-death analog for engine-state
                               checkpointing: no atexit, no drain, no
                               flushed buffers — exactly what ``serve
                               --resume`` must recover from.
- ``ckpt-manifest-corrupt@N`` — scribble over the engine-state manifest
                               published at generation >= N (no ``@N`` =
                               the first one). The resume loader must
                               quarantine it and fall back one
                               generation loudly.
Solve-cache kinds (the serving engine's content-addressed result cache,
serve/solvecache.py — ignored everywhere a cache is off):

- ``cache-corrupt[@N]``      — xor-scribble 64 bytes at the midpoint of
                               the consulted cache entry's npz on the
                               Nth cache consult (no ``@N`` = the
                               first). The consult's sha256 check must
                               quarantine it to ``*.corrupt`` and fall
                               back to recompute — never serve it.
- ``cache-stale``            — rewrite the consulted entry's sidecar
                               fingerprint to a different physics hash
                               (a mis-filed / stale entry analog). The
                               consult's fingerprint check must
                               quarantine and recompute (fire-once).

Fleet-scoped kinds (the router's chaos drills, heat_tpu/fleet/router.py
— ignored by the solo drive loop and the serving engine):

- ``backend-down@N[:backend=K]`` — router-side: once the router has
                               forwarded N requests, drop the TCP
                               target (the router treats the backend as
                               connection-refused from then on, the
                               shape of a host vanishing mid-fleet).
                               ``backend=K`` names the victim; without
                               it the backend the Nth forward chose is
                               dropped. Fire-once; exercises the
                               retry-on-alternate + checkpoint-recovery
                               path without killing a real process.
- ``backend-slow:ms=M``      — every router->backend forward sleeps M ms
                               first (a congested/distant backend; the
                               placement policy and imbalance estimator
                               see realistic skew).
- ``backend-flap:period=M[:backend=K][:times=T]`` — oscillate backend K
                               (default b0) between down and up every M
                               ms, for T down half-periods (default 1:
                               one down pulse, then up forever). The
                               flapping-host shape the circuit breaker
                               exists for: without a breaker each down
                               edge triggers recovery/steal thrash.
- ``stream-cut@N[:backend=K]`` — kill the router's relay socket to
                               backend K (default: whichever relay asks
                               first) after N records have streamed back
                               (fire-once). The mid-stream break the
                               hardened exactly-once re-drive path must
                               absorb with zero lost or duplicated rows.
- ``backend-partition[:backend=K][:ms=M]`` — backend K accepts the TCP
                               connect, then stalls M ms (default 1000)
                               before the router sees a timeout —
                               distinct from ``backend-down``'s
                               connection-refused (a network partition /
                               wedged host, not a dead one).

- ``perturb@N[:req=ID][:eps=E]`` — add a bounded (finite!) perturbation
                               ``eps`` (default 1e3) to one cell of a
                               serving lane's field once that lane's
                               request has completed >= N steps
                               (fire-once per request; same ``req=``
                               targeting as lane-nan). The soft-error
                               analog the numerics observatory exists
                               for: the field stays finite, so the
                               isfinite bit never drops, but the
                               maximum-principle witnesses escape their
                               envelope. Pairs with ``--numerics-guard``.

Specs come from ``--inject`` (``HeatConfig.inject``) or the
``HEAT_TPU_FAULTS`` env var (so ``heat-tpu launch`` workers inherit one
without CLI plumbing); multiple faults are comma-separated, e.g.
``"nan@6,ckpt-corrupt@8"``. Grammar per fault: ``kind[@step][:key=val]...``.

Every fault is **restart-gated**: by default it fires only in incarnation 0
(``restart=R`` selects another, ``restart=-1`` fires in every one). The
launch supervisor exports ``HEAT_TPU_RESTART=<attempt>`` to relaunched
workers, so an injected crash kills the first world and *not* the resumed
one — exactly the transient-fault shape the self-healing path must absorb.

Strictly opt-in: with no spec, ``plan_for`` returns ``None`` and every call
site skips on one ``is not None`` test — the stepping hot path and the
checkpoint write path are behavior-identical to a build without this module.
"""

from __future__ import annotations

import dataclasses
import errno
import os
import sys
import time
from pathlib import Path
from typing import Dict, List, Optional

from .logging import master_print

ENV_VAR = "HEAT_TPU_FAULTS"
RESTART_ENV_VAR = "HEAT_TPU_RESTART"

# Distinctive worker exit code for an injected crash — the supervisor (and a
# human reading its restart records) can tell "chaos did this" from a real
# rc=1 traceback death.
CRASH_RC = 43

_KINDS = ("crash", "nan", "ckpt-corrupt", "ckpt-truncate",
          "sink-error", "sink-slow", "lane-nan", "fetch-hang", "perturb",
          "engine-kill", "ckpt-manifest-corrupt",
          "backend-down", "backend-slow", "cache-corrupt", "cache-stale",
          "backend-flap", "stream-cut", "backend-partition")


@dataclasses.dataclass
class Fault:
    kind: str
    step: Optional[int] = None  # fires at the first boundary/step >= this
    proc: Optional[int] = None  # None = every process
    times: int = 1              # sink-error: how many writes fail
    ms: float = 0.0             # sink-slow / fetch-hang: delay
    restart: int = 0            # incarnation filter (-1 = every incarnation)
    req: Optional[str] = None   # lane-nan/perturb: target request id
                                # (None = all)
    eps: float = 1e3            # perturb: added to one cell (finite, big
                                # enough to escape any envelope tolerance)
    backend: Optional[str] = None  # backend-down: named victim (None =
                                # whichever backend the Nth forward chose)
    period: float = 0.0         # backend-flap: half-period in ms
    t0: Optional[float] = None  # backend-flap: epoch (first evaluation)
    fired: bool = False


def _restart_count() -> int:
    try:
        return int(os.environ.get(RESTART_ENV_VAR, "0"))
    except ValueError:
        return 0


def _process_index() -> int:
    """This process's rank, without forcing backend init when the launch
    env already says it (workers get JAX_PROCESS_ID before jax starts)."""
    v = os.environ.get("JAX_PROCESS_ID")
    if v is not None:
        try:
            return int(v)
        except ValueError:
            pass
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def parse_spec(spec: str) -> List[Fault]:
    """Parse a fault spec; raises ValueError with the grammar on any typo
    (config validation calls this so a bad spec dies at parse time, not at
    step N of a long solve)."""
    faults = []
    for raw in spec.split(","):
        entry = raw.strip()
        if not entry:
            continue
        head, _, tail = entry.partition(":")
        kind, _, step_s = head.partition("@")
        if kind not in _KINDS:
            raise ValueError(
                f"unknown fault kind {kind!r} in {entry!r}; grammar is "
                f"'kind[@step][:key=val]...' with kind one of {_KINDS}")
        f = Fault(kind=kind)
        if step_s:
            try:
                f.step = int(step_s)
            except ValueError:
                raise ValueError(f"bad step {step_s!r} in fault {entry!r}")
        for kv in filter(None, tail.split(":")):
            key, eq, val = kv.partition("=")
            if not eq or key not in ("proc", "times", "ms", "restart",
                                     "req", "eps", "backend", "period"):
                raise ValueError(
                    f"bad fault param {kv!r} in {entry!r}; keys are "
                    f"proc=, times=, ms=, restart=, req=, eps=, backend=, "
                    f"period=")
            try:
                setattr(f, key, val if key in ("req", "backend")
                        else float(val) if key in ("ms", "eps", "period")
                        else int(val))
            except ValueError:
                raise ValueError(f"bad value {val!r} for {key} in {entry!r}")
        if (f.kind in ("crash", "nan", "lane-nan", "perturb", "engine-kill",
                       "backend-down", "stream-cut")
                and f.step is None):
            raise ValueError(f"fault {entry!r} needs a step: '{f.kind}@N'")
        if f.kind == "backend-flap" and f.period <= 0:
            raise ValueError(
                f"fault {entry!r} needs a half-period: "
                f"'backend-flap:period=MS'")
        faults.append(f)
    return faults


class FaultPlan:
    """One parsed spec with its firing state (fire-once flags, sink-error
    budgets). Plans are cached per spec string so the driver, the
    checkpoint writer, and the async sink all decrement the SAME budgets
    within a process."""

    def __init__(self, spec: str):
        self.spec = spec
        self.faults = parse_spec(spec)

    def _live(self, kind: str):
        for f in self.faults:
            if f.kind != kind:
                continue
            if f.restart != -1 and f.restart != _restart_count():
                continue
            if f.proc is not None and f.proc != _process_index():
                continue
            yield f

    # --- step-loop faults (backends.common.drive / serial loop) ----------
    def maybe_crash(self, step: int) -> None:
        for f in self._live("crash"):
            if not f.fired and step >= f.step:
                f.fired = True
                print(f"fault: injected crash at step {step} "
                      f"(proc {_process_index()}, spec {self.spec!r})",
                      file=sys.stderr, flush=True)
                os._exit(CRASH_RC)

    def maybe_nan(self, step: int, T):
        """Flip the center cell to NaN once the step arrives; returns the
        (possibly replaced) field."""
        for f in self._live("nan"):
            if not f.fired and step >= f.step:
                f.fired = True
                master_print(f"fault: injected NaN at step {step} "
                             f"(spec {self.spec!r})")
                T = _inject_nan(T)
        return T

    # --- serve-scoped faults (serve/scheduler.py lane fault domains) ------
    def lane_nan_steps(self, req_id: str) -> List[int]:
        """The step thresholds at which ``req_id``'s serving lane must be
        poisoned with NaN. Firing state for lane-nan is PER REQUEST and
        lives in the scheduler (plans are cached per spec string, so two
        requests sharing one spec must not share a fired flag) — this
        only answers 'which steps apply to this request'."""
        return sorted(f.step for f in self._live("lane-nan")
                      if f.req is None or f.req == req_id)

    def perturb_events(self, req_id: str) -> List[tuple]:
        """``(step, eps)`` thresholds at which ``req_id``'s serving lane
        must be perturbed (finite bounded bump — the numerics-observatory
        test fault). Same per-request firing contract as lane_nan_steps:
        the scheduler owns the fire-once state, this only answers 'which
        events apply to this request'."""
        return sorted((f.step, f.eps) for f in self._live("perturb")
                      if f.req is None or f.req == req_id)

    def maybe_fetch_hang(self, fetch_index: int) -> None:
        """Called inside the (watchdog-bounded) boundary fetch: the first
        live fetch-hang fault whose ``@N`` threshold the fetch counter has
        reached sleeps ``ms`` and is spent (fire-once — a wedged fetch is
        a one-shot scenario, and the watchdog that catches it fails the
        whole group anyway)."""
        for f in self._live("fetch-hang"):
            if not f.fired and fetch_index >= (f.step or 0):
                f.fired = True
                master_print(f"fault: injected {f.ms:.0f} ms hang on "
                             f"boundary fetch {fetch_index} "
                             f"(spec {self.spec!r})")
                time.sleep(f.ms / 1000.0)

    def maybe_engine_kill(self, boundary: int) -> None:
        """Called once per processed chunk boundary (engine-wide counter,
        serve/scheduler.py): SIGKILL this process — not ``os._exit``, so
        even interpreter-level cleanup is denied — once the counter
        reaches ``@N``. Fire-once per plan, though a SIGKILL that lands
        never gets a second chance anyway."""
        import signal

        for f in self._live("engine-kill"):
            if not f.fired and boundary >= f.step:
                f.fired = True
                print(f"fault: injected engine SIGKILL at boundary "
                      f"{boundary} (spec {self.spec!r})",
                      file=sys.stderr, flush=True)
                os.kill(os.getpid(), signal.SIGKILL)

    # --- fleet faults (heat_tpu/fleet/router.py chaos drills) -------------
    def backend_slow(self) -> None:
        """Called before every router->backend forward: each live
        backend-slow fault sleeps its ``ms`` (a congested or distant
        backend — placement skew the imbalance estimator must see)."""
        for f in self._live("backend-slow"):
            if f.ms > 0:
                time.sleep(f.ms / 1000.0)

    def backend_down_target(self, nth: int) -> Optional[str]:
        """Called once per forwarded request with the router-wide
        forward counter: the first live backend-down fault whose ``@N``
        threshold ``nth`` reaches is spent (fire-once) and answers which
        TCP target to drop — its ``backend=`` selector, or ``""``
        meaning 'whichever backend this Nth forward chose'. ``None`` =
        no fault fires here (the overwhelmingly common answer)."""
        for f in self._live("backend-down"):
            if not f.fired and nth >= f.step:
                f.fired = True
                print(f"fault: injected backend-down at forward {nth} "
                      f"(target {f.backend or '<routed>'}, "
                      f"spec {self.spec!r})", file=sys.stderr, flush=True)
                return f.backend or ""
        return None

    def backend_flap_states(self, now: float) -> Dict[str, bool]:
        """Called from the router's health tick: for each live
        backend-flap fault, is its target (default ``b0``) DOWN at wall
        time ``now``? The epoch is stamped on the first evaluation; the
        flap runs ``times`` down half-periods (each ``period`` ms) with
        up half-periods between, then stays up forever — a bounded flap
        the breaker must ride out without steal thrash. Returns
        {backend_name: down?}; empty dict = no flap faults live."""
        states: Dict[str, bool] = {}
        for f in self._live("backend-flap"):
            if f.t0 is None:
                f.t0 = now
            half = f.period / 1000.0
            phase = int((now - f.t0) // half) if half > 0 else 0
            # phases 0,2,4,... are down pulses; up in between; after
            # `times` down pulses (phase >= 2*times - 1) up for good
            down = phase < 2 * f.times - 1 and phase % 2 == 0
            states[f.backend or "b0"] = down
        return states

    def stream_cut_fire(self, backend: str, nrecords: int) -> bool:
        """Called from the relay read loop with the count of records
        already streamed back from ``backend``: the first live
        stream-cut fault targeting it (or untargeted) whose ``@N``
        threshold is reached is spent (fire-once) and answers True —
        the relay must sever its socket mid-stream."""
        for f in self._live("stream-cut"):
            if f.fired or (f.backend is not None and f.backend != backend):
                continue
            if nrecords >= f.step:
                f.fired = True
                print(f"fault: injected stream-cut on backend {backend} "
                      f"after {nrecords} records (spec {self.spec!r})",
                      file=sys.stderr, flush=True)
                return True
        return False

    def backend_partition_ms(self, backend: str) -> Optional[float]:
        """Called before a router->backend HTTP request: if a live
        backend-partition fault targets ``backend`` (or is untargeted),
        answer the stall in ms (default 1000) — the connect is accepted
        but the response never comes, distinct from backend-down's
        refusal. Not fire-once: a partition persists until the spec is
        lifted."""
        for f in self._live("backend-partition"):
            if f.backend is None or f.backend == backend:
                return f.ms if f.ms > 0 else 1000.0
        return None

    # --- checkpoint-sink faults (runtime.checkpoint.save/save_shards) ----
    def sink_fault(self, step: int) -> None:
        """Called at the top of a checkpoint write: transient-error and
        slow-sink faults land here, BEFORE any bytes move."""
        for f in self._live("sink-slow"):
            if f.ms > 0:
                time.sleep(f.ms / 1000.0)
        for f in self._live("sink-error"):
            if f.times > 0 and (f.step is None or step >= f.step):
                f.times -= 1
                raise OSError(
                    errno.EIO,
                    f"injected transient sink error at step {step} "
                    f"({f.times} more to come; spec {self.spec!r})")

    def damage_checkpoint(self, path: Path, step: int) -> None:
        """Called after a checkpoint file is published: corrupt/truncate
        faults damage it in place (the bitrot the quarantine path must
        catch on the next resume)."""
        for f in self._live("ckpt-corrupt"):
            if not f.fired and (f.step is None or step >= f.step):
                f.fired = True
                data = bytearray(path.read_bytes())
                mid = len(data) // 2
                for i in range(mid, min(mid + 64, len(data))):
                    data[i] ^= 0xFF
                path.write_bytes(bytes(data))
                master_print(f"fault: corrupted checkpoint {path.name} "
                             f"(spec {self.spec!r})")
        for f in self._live("ckpt-truncate"):
            if not f.fired and (f.step is None or step >= f.step):
                f.fired = True
                data = path.read_bytes()
                path.write_bytes(data[:len(data) // 2])
                master_print(f"fault: truncated checkpoint {path.name} "
                             f"(spec {self.spec!r})")

    def damage_cache(self, cache_dir, fingerprint: str,
                     consult: int) -> None:
        """Called at the top of every solve-cache consult
        (serve/solvecache.py) with the consult counter: cache-corrupt
        xor-scribbles the consulted fingerprint's npz entry (sha256
        mismatch — bitrot analog), cache-stale rewrites its sidecar
        fingerprint (a mis-filed entry analog). Both fire-once; the
        consult's validation must quarantine the damage, never serve
        it."""
        d = Path(cache_dir)
        for f in self._live("cache-corrupt"):
            if f.fired or consult < (f.step or 1):
                continue
            for p in sorted(d.glob(f"{fingerprint}-*.npz")):
                f.fired = True
                data = bytearray(p.read_bytes())
                mid = len(data) // 2
                for i in range(mid, min(mid + 64, len(data))):
                    data[i] ^= 0xFF
                p.write_bytes(bytes(data))
                master_print(f"fault: corrupted cache entry {p.name} "
                             f"(spec {self.spec!r})")
                break
        for f in self._live("cache-stale"):
            if f.fired or consult < (f.step or 1):
                continue
            for p in sorted(d.glob(f"{fingerprint}-*.json")):
                f.fired = True
                try:
                    import json as _json

                    meta = _json.loads(p.read_text())
                except ValueError:
                    meta = {}
                meta["fingerprint"] = "0" * 16
                p.write_text(_json.dumps(meta, sort_keys=True) + "\n")
                master_print(f"fault: staled cache sidecar {p.name} "
                             f"(spec {self.spec!r})")
                break

    def damage_manifest(self, path: Path, generation: int) -> None:
        """Called after an engine-state manifest is published
        (runtime.checkpoint.save_engine_manifest): xor-scribble 64 bytes
        at the midpoint — JSON turns to garbage, the resume loader's
        validate step must quarantine it and fall back one generation."""
        for f in self._live("ckpt-manifest-corrupt"):
            if not f.fired and (f.step is None or generation >= f.step):
                f.fired = True
                data = bytearray(path.read_bytes())
                mid = len(data) // 2
                for i in range(mid, min(mid + 64, len(data))):
                    data[i] ^= 0xFF
                path.write_bytes(bytes(data))
                master_print(f"fault: corrupted engine manifest "
                             f"{path.name} (spec {self.spec!r})")


def _inject_nan(T):
    import numpy as np

    idx = tuple(s // 2 for s in T.shape)
    try:
        import jax

        if isinstance(T, jax.Array):
            import jax.numpy as jnp

            return T.at[idx].set(jnp.nan)
    except ImportError:
        pass
    T = np.array(T)
    T[idx] = np.nan
    return T


_PLANS: Dict[str, FaultPlan] = {}


def plan_for(cfg=None) -> Optional[FaultPlan]:
    """The active fault plan for this run, or None (the overwhelmingly
    common case — one falsy-string test). ``cfg.inject`` wins over
    ``HEAT_TPU_FAULTS``. Plans cache per spec so firing state (fire-once,
    sink-error budgets) is shared across the driver and the checkpoint
    module within a process."""
    spec = getattr(cfg, "inject", "") or os.environ.get(ENV_VAR, "")
    return plan_for_spec(spec)


def plan_for_spec(spec: str) -> Optional[FaultPlan]:
    """A plan for a raw spec string — the fleet router's ``--inject``
    flag has no HeatConfig to hang the spec on. Same cache and firing
    state as ``plan_for``; same strictly-opt-in contract (empty spec ->
    None, one falsy test on the forward path)."""
    spec = (spec or "").strip()
    if not spec:
        return None
    plan = _PLANS.get(spec)
    if plan is None:
        plan = _PLANS[spec] = FaultPlan(spec)
    return plan


def reset() -> None:
    """Drop all cached firing state (tests re-running a spec)."""
    _PLANS.clear()
