"""Numerics checking, profiler hooks, and the lock-order watchdog.

The reference has no sanitizer story beyond hard device syncs after every
kernel (fortran/hip/heat.F90:207,220,225,246) — races are impossible in
XLA's functional model, so the debug mode that actually matters on TPU is
*numerics*: catching NaN/Inf blow-ups (e.g. sigma above the FTCS stability
bound) at the step where they appear instead of in the final output.
Profiling upgrades the reference's two wall-clock timers (SURVEY.md §5) to
a real trace (``jax.profiler``) viewable in TensorBoard/Perfetto.

The **lock-order watchdog** (``HEAT_TPU_LOCKCHECK=1``) is the dynamic
half of the ``lock-discipline`` static rule (``heat_tpu/analysis``): the
serving stack's locks form a documented partial order —

    gateway  <  engine  <  observatory (prof / trace instruments)

(the engine calls *into* the observatory, sometimes while holding its own
lock, e.g. ``Engine._emit``; observatory instruments never take the
engine lock, so a /metrics scrape can never deadlock the boundary hot
path). With the env flag set, every lock the stack creates through
:func:`make_lock` becomes an :class:`_OrderedLock` that tracks the
calling thread's held-lock stack and **raises** :class:`LockOrderError`
at the exact acquisition that would invert the order — turning a
some-day deadlock into a deterministic test failure. Off (the default),
``make_lock`` returns a plain ``threading.Lock``: zero overhead, zero
behavior change. The chaos suite and ``heat-tpu perfcheck`` run with the
watchdog armed and assert zero inversions.
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import List, Optional

# --------------------------------------------------------------------------
# lock-order watchdog (opt-in: HEAT_TPU_LOCKCHECK=1)
# --------------------------------------------------------------------------

# The documented acquisition order, lowest first. A thread may only
# acquire a lock of STRICTLY greater rank than anything it already holds:
# two same-rank locks must never nest (the observatory instruments each
# carry their own lock precisely so they never have to), and the reverse
# order (observatory -> engine) is the deadlock the PR-8 contract rules
# out. Rank names are the prefix before ":" in a make_lock name, so
# "observatory:ledger" and "observatory:burn" share a rank.
LOCK_RANKS = {"gateway": 0, "engine": 10, "writer": 20, "observatory": 30}


class LockOrderError(RuntimeError):
    """An acquisition that inverts the documented lock order."""


_tls = threading.local()
_stats_lock = threading.Lock()
_edges: set = set()          # (held_name, acquired_name) pairs observed
_violations: List[str] = []  # human-readable inversion descriptions


def lockcheck_enabled() -> bool:
    """Is the dynamic lock-order watchdog armed (HEAT_TPU_LOCKCHECK=1)?
    Read at lock *creation* time: engines built after the env flips get
    ordered locks, existing plain locks are untouched."""
    return os.environ.get("HEAT_TPU_LOCKCHECK", "") == "1"


def _held() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


class _OrderedLock:
    """A ``threading.Lock`` that enforces the LOCK_RANKS partial order.

    Duck-types the subset of the Lock API the stack uses (``acquire`` /
    ``release`` / context manager), which is also exactly what
    ``threading.Condition`` needs to wrap it — ``Condition.wait`` falls
    back to plain release/acquire pairs, each of which keeps the
    held-stack bookkeeping exact. ``acquire(blocking=False)`` performs
    the order check only on a SUCCESSFUL acquisition: Condition's
    ``_is_owned`` probe try-acquires a lock the thread already holds and
    must get a quiet ``False``, not an error."""

    __slots__ = ("name", "rank", "_lock")

    def __init__(self, name: str, rank: int):
        self.name = name
        self.rank = rank
        self._lock = threading.Lock()

    def _check_order(self) -> None:
        stack = _held()
        if not stack:
            return
        worst = max(stack, key=lambda l: l.rank)
        if any(l is self for l in stack):
            msg = (f"reentrant acquire of lock {self.name!r} "
                   f"(non-reentrant by design; this would deadlock)")
        elif self.rank <= worst.rank:
            msg = (f"lock order inversion: acquiring {self.name!r} "
                   f"(rank {self.rank}) while holding {worst.name!r} "
                   f"(rank {worst.rank}) — documented order is "
                   + " < ".join(sorted(LOCK_RANKS, key=LOCK_RANKS.get)))
        else:
            return
        with _stats_lock:
            _violations.append(msg)
        raise LockOrderError(msg)

    def _note_acquired(self) -> None:
        stack = _held()
        if stack:
            with _stats_lock:
                _edges.add((stack[-1].name, self.name))
        stack.append(self)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if blocking:
            # check BEFORE blocking: an inversion must raise, not deadlock
            self._check_order()
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            if not blocking:
                try:
                    self._check_order()
                except LockOrderError:
                    self._lock.release()
                    raise
            self._note_acquired()
        return ok

    def release(self) -> None:
        stack = _held()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                break
        self._lock.release()

    __enter__ = acquire

    def __exit__(self, *exc) -> None:
        self.release()


def make_lock(name: str):
    """The one lock factory of the serving stack: a plain
    ``threading.Lock`` normally, an order-enforcing :class:`_OrderedLock`
    under ``HEAT_TPU_LOCKCHECK=1``. ``name`` is ``"<rank>[:<detail>]"``
    with ``<rank>`` a LOCK_RANKS key (unknown ranks raise at creation —
    a misnamed lock must not silently opt out of the discipline)."""
    rank_name = name.split(":", 1)[0]
    if rank_name not in LOCK_RANKS:
        raise ValueError(f"unknown lock rank {rank_name!r} in lock name "
                         f"{name!r}; known: {sorted(LOCK_RANKS)}")
    if not lockcheck_enabled():
        return threading.Lock()
    return _OrderedLock(name, LOCK_RANKS[rank_name])


def held_locks() -> List[str]:
    """Names of ordered locks the calling thread holds (tests)."""
    return [l.name for l in _held()]


def lock_order_stats() -> dict:
    """Watchdog observations so far: every (held -> acquired) edge seen
    and every inversion raised. The chaos suite asserts
    ``violations == []`` after a full fault-injected drain."""
    with _stats_lock:
        return {"edges": sorted(_edges), "violations": list(_violations)}


def reset_lock_order_stats() -> None:
    with _stats_lock:
        _edges.clear()
        _violations.clear()


@contextlib.contextmanager
def maybe_profile(trace_dir: Optional[str]):
    """Wrap a region in a jax.profiler trace when a directory is given."""
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield


def finite_flag(T):
    """All-finite reduction WITHOUT blocking the host on it.

    Device arrays reduce ON DEVICE (``jnp.isfinite(...).all()``) and the
    replicated scalar is returned still-on-device: the caller holds it and
    fetches at the NEXT chunk boundary (``raise_if_flagged``), by which
    point the device has computed it behind the following chunk's work —
    the numerics leg of the async I/O pipeline. The on-device reduction
    also keeps the multi-host contract: the global field can span other
    processes, where ``np.asarray`` on it raises RuntimeError — the
    reduction's replicated scalar is always fetchable, and a scalar fetch
    is tunnel-cheap. Host arrays reduce eagerly (nothing to overlap).
    """
    import numpy as np

    import jax
    import jax.numpy as jnp

    if isinstance(T, jax.Array) and not isinstance(T, jax.core.Tracer):
        return jnp.isfinite(T).all()
    return np.isfinite(np.asarray(T).astype(np.float32)).all()


def raise_if_flagged(flag, step: int, label: str = "field") -> None:
    """Fetch a ``finite_flag`` result (one scalar) and raise with the step
    context the flag was computed at."""
    if not bool(flag):
        raise FloatingPointError(
            f"non-finite values in {label} at step {step} — check the CFL "
            f"bound sigma <= 1/(2*ndim) and the fuse/halo configuration"
        )


def check_finite(T, step: int, label: str = "field") -> None:
    """Synchronous form: compute the flag and block on it immediately
    (the ``--async-io off`` drive path and every per-step host caller;
    ``--async-io on`` splits this into ``finite_flag`` at the boundary +
    ``raise_if_flagged`` one boundary later)."""
    raise_if_flagged(finite_flag(T), step, label)
