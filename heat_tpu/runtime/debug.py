"""Numerics checking and profiler hooks.

The reference has no sanitizer story beyond hard device syncs after every
kernel (fortran/hip/heat.F90:207,220,225,246) — races are impossible in
XLA's functional model, so the debug mode that actually matters on TPU is
*numerics*: catching NaN/Inf blow-ups (e.g. sigma above the FTCS stability
bound) at the step where they appear instead of in the final output.
Profiling upgrades the reference's two wall-clock timers (SURVEY.md §5) to
a real trace (``jax.profiler``) viewable in TensorBoard/Perfetto.
"""

from __future__ import annotations

import contextlib
from typing import Optional


@contextlib.contextmanager
def maybe_profile(trace_dir: Optional[str]):
    """Wrap a region in a jax.profiler trace when a directory is given."""
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield


def finite_flag(T):
    """All-finite reduction WITHOUT blocking the host on it.

    Device arrays reduce ON DEVICE (``jnp.isfinite(...).all()``) and the
    replicated scalar is returned still-on-device: the caller holds it and
    fetches at the NEXT chunk boundary (``raise_if_flagged``), by which
    point the device has computed it behind the following chunk's work —
    the numerics leg of the async I/O pipeline. The on-device reduction
    also keeps the multi-host contract: the global field can span other
    processes, where ``np.asarray`` on it raises RuntimeError — the
    reduction's replicated scalar is always fetchable, and a scalar fetch
    is tunnel-cheap. Host arrays reduce eagerly (nothing to overlap).
    """
    import numpy as np

    import jax
    import jax.numpy as jnp

    if isinstance(T, jax.Array) and not isinstance(T, jax.core.Tracer):
        return jnp.isfinite(T).all()
    return np.isfinite(np.asarray(T).astype(np.float32)).all()


def raise_if_flagged(flag, step: int, label: str = "field") -> None:
    """Fetch a ``finite_flag`` result (one scalar) and raise with the step
    context the flag was computed at."""
    if not bool(flag):
        raise FloatingPointError(
            f"non-finite values in {label} at step {step} — check the CFL "
            f"bound sigma <= 1/(2*ndim) and the fuse/halo configuration"
        )


def check_finite(T, step: int, label: str = "field") -> None:
    """Synchronous form: compute the flag and block on it immediately
    (the ``--async-io off`` drive path and every per-step host caller;
    ``--async-io on`` splits this into ``finite_flag`` at the boundary +
    ``raise_if_flagged`` one boundary later)."""
    raise_if_flagged(finite_flag(T), step, label)
