"""Numerics checking and profiler hooks.

The reference has no sanitizer story beyond hard device syncs after every
kernel (fortran/hip/heat.F90:207,220,225,246) — races are impossible in
XLA's functional model, so the debug mode that actually matters on TPU is
*numerics*: catching NaN/Inf blow-ups (e.g. sigma above the FTCS stability
bound) at the step where they appear instead of in the final output.
Profiling upgrades the reference's two wall-clock timers (SURVEY.md §5) to
a real trace (``jax.profiler``) viewable in TensorBoard/Perfetto.
"""

from __future__ import annotations

import contextlib
from typing import Optional


@contextlib.contextmanager
def maybe_profile(trace_dir: Optional[str]):
    """Wrap a region in a jax.profiler trace when a directory is given."""
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield


def check_finite(T, step: int, label: str = "field") -> None:
    """Raise with step context if the field has NaN/Inf (device or host array).

    Device arrays reduce ON DEVICE (``jnp.isfinite(...).all()``): in a
    multi-host job the global field spans other processes and
    ``np.asarray`` on it raises RuntimeError — the reduction's replicated
    scalar is always fetchable, and a scalar fetch is tunnel-cheap.
    """
    import numpy as np

    import jax
    import jax.numpy as jnp

    if isinstance(T, jax.Array) and not isinstance(T, jax.core.Tracer):
        ok = bool(jnp.isfinite(T).all())
    else:
        ok = bool(np.isfinite(np.asarray(T).astype(np.float32)).all())
    if not ok:
        raise FloatingPointError(
            f"non-finite values in {label} at step {step} — check the CFL "
            f"bound sigma <= 1/(2*ndim) and the fuse/halo configuration"
        )
