"""Numerics checking, profiler hooks, and the lock-order watchdog.

The reference has no sanitizer story beyond hard device syncs after every
kernel (fortran/hip/heat.F90:207,220,225,246) — races are impossible in
XLA's functional model, so the debug mode that actually matters on TPU is
*numerics*: catching NaN/Inf blow-ups (e.g. sigma above the FTCS stability
bound) at the step where they appear instead of in the final output.
Profiling upgrades the reference's two wall-clock timers (SURVEY.md §5) to
a real trace (``jax.profiler``) viewable in TensorBoard/Perfetto.

The **lock-order watchdog** (``HEAT_TPU_LOCKCHECK=1``) is the dynamic
half of the ``lock-discipline`` static rule (``heat_tpu/analysis``): the
serving stack's locks form a documented partial order —

    gateway  <  engine  <  observatory (prof / trace instruments)

(the engine calls *into* the observatory, sometimes while holding its own
lock, e.g. ``Engine._emit``; observatory instruments never take the
engine lock, so a /metrics scrape can never deadlock the boundary hot
path). With the env flag set, every lock the stack creates through
:func:`make_lock` becomes an :class:`_OrderedLock` that tracks the
calling thread's held-lock stack and **raises** :class:`LockOrderError`
at the exact acquisition that would invert the order — turning a
some-day deadlock into a deterministic test failure. Off (the default),
``make_lock`` returns a plain ``threading.Lock``: zero overhead, zero
behavior change. The chaos suite and ``heat-tpu perfcheck`` run with the
watchdog armed and assert zero inversions.

The **race sanitizer** (``HEAT_TPU_RACECHECK=1`` to raise,
``=record`` to log-and-continue) is the dynamic half of the ``races``
static rule: :func:`instrument_races` arms Eraser-style per-(object,
field) candidate-lockset tracking on the thread-shared serving objects
(Engine, SnapshotWriter, Gateway, Tracer), fed by the watchdog's
per-thread held stacks — ``make_lock`` hands out ordered locks whenever
EITHER checker is armed. A write-write race with an empty lockset
intersection raises :class:`RaceError` (tests) or emits a structured
``race_detected`` record plus a flight-recorder dump (production);
:func:`race_stats` is queryable like :func:`lock_order_stats`.
:func:`install_thread_excepthook` rounds out the thread-debug story:
an uncaught exception in a background thread becomes a structured
``thread_crash`` record + flight dump instead of a silent stderr death.
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import List, Optional

# --------------------------------------------------------------------------
# lock-order watchdog (opt-in: HEAT_TPU_LOCKCHECK=1)
# --------------------------------------------------------------------------

# The documented acquisition order, lowest first. A thread may only
# acquire a lock of STRICTLY greater rank than anything it already holds:
# two same-rank locks must never nest (the observatory instruments each
# carry their own lock precisely so they never have to), and the reverse
# order (observatory -> engine) is the deadlock the PR-8 contract rules
# out. Rank names are the prefix before ":" in a make_lock name, so
# "observatory:ledger" and "observatory:burn" share a rank. "fleet" is
# the router in front of many gateways (heat_tpu/fleet): outermost in
# every request path, so it ranks below gateway — router threads may
# call into a (same-process, in tests) gateway/engine surface while
# holding a fleet lock, never the reverse. "cache" (the solve cache,
# serve/solvecache.py) sits between writer and observatory: the writer
# thread publishes entries on its result path, and a cache consult may
# feed observatory counters — never the reverse.
LOCK_RANKS = {"fleet": -10, "gateway": 0, "engine": 10, "writer": 20,
              "cache": 25, "observatory": 30}


class LockOrderError(RuntimeError):
    """An acquisition that inverts the documented lock order."""


_tls = threading.local()
_stats_lock = threading.Lock()
_edges: set = set()          # (held_name, acquired_name) pairs observed
_violations: List[str] = []  # human-readable inversion descriptions


def lockcheck_enabled() -> bool:
    """Is the dynamic lock-order watchdog armed (HEAT_TPU_LOCKCHECK=1)?
    Read at lock *creation* time: engines built after the env flips get
    ordered locks, existing plain locks are untouched."""
    return os.environ.get("HEAT_TPU_LOCKCHECK", "") == "1"


def _held() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


class _OrderedLock:
    """A ``threading.Lock`` that enforces the LOCK_RANKS partial order.

    Duck-types the subset of the Lock API the stack uses (``acquire`` /
    ``release`` / context manager), which is also exactly what
    ``threading.Condition`` needs to wrap it — ``Condition.wait`` falls
    back to plain release/acquire pairs, each of which keeps the
    held-stack bookkeeping exact. ``acquire(blocking=False)`` performs
    the order check only on a SUCCESSFUL acquisition: Condition's
    ``_is_owned`` probe try-acquires a lock the thread already holds and
    must get a quiet ``False``, not an error."""

    __slots__ = ("name", "rank", "_lock")

    def __init__(self, name: str, rank: int):
        self.name = name
        self.rank = rank
        self._lock = threading.Lock()

    def _check_order(self) -> None:
        stack = _held()
        if not stack:
            return
        worst = max(stack, key=lambda l: l.rank)
        if any(l is self for l in stack):
            msg = (f"reentrant acquire of lock {self.name!r} "
                   f"(non-reentrant by design; this would deadlock)")
        elif self.rank <= worst.rank:
            msg = (f"lock order inversion: acquiring {self.name!r} "
                   f"(rank {self.rank}) while holding {worst.name!r} "
                   f"(rank {worst.rank}) — documented order is "
                   + " < ".join(sorted(LOCK_RANKS, key=LOCK_RANKS.get)))
        else:
            return
        with _stats_lock:
            _violations.append(msg)
        raise LockOrderError(msg)

    def _note_acquired(self) -> None:
        stack = _held()
        if stack:
            with _stats_lock:
                _edges.add((stack[-1].name, self.name))
        stack.append(self)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if blocking:
            # check BEFORE blocking: an inversion must raise, not deadlock
            self._check_order()
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            if not blocking:
                try:
                    self._check_order()
                except LockOrderError:
                    self._lock.release()
                    raise
            self._note_acquired()
        return ok

    def release(self) -> None:
        stack = _held()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                break
        self._lock.release()

    __enter__ = acquire

    def __exit__(self, *exc) -> None:
        self.release()


def make_lock(name: str):
    """The one lock factory of the serving stack: a plain
    ``threading.Lock`` normally, an order-enforcing :class:`_OrderedLock`
    under ``HEAT_TPU_LOCKCHECK=1``. ``name`` is ``"<rank>[:<detail>]"``
    with ``<rank>`` a LOCK_RANKS key (unknown ranks raise at creation —
    a misnamed lock must not silently opt out of the discipline)."""
    rank_name = name.split(":", 1)[0]
    if rank_name not in LOCK_RANKS:
        raise ValueError(f"unknown lock rank {rank_name!r} in lock name "
                         f"{name!r}; known: {sorted(LOCK_RANKS)}")
    # the race sanitizer needs the per-thread held stacks too: candidate
    # locksets are computed from exactly this bookkeeping
    if not (lockcheck_enabled() or racecheck_enabled()):
        return threading.Lock()
    return _OrderedLock(name, LOCK_RANKS[rank_name])


def held_locks() -> List[str]:
    """Names of ordered locks the calling thread holds (tests)."""
    return [l.name for l in _held()]


def lock_order_stats() -> dict:
    """Watchdog observations so far: every (held -> acquired) edge seen
    and every inversion raised. The chaos suite asserts
    ``violations == []`` after a full fault-injected drain."""
    with _stats_lock:
        return {"edges": sorted(_edges), "violations": list(_violations)}


def reset_lock_order_stats() -> None:
    with _stats_lock:
        _edges.clear()
        _violations.clear()


# --------------------------------------------------------------------------
# Eraser-style race sanitizer (opt-in: HEAT_TPU_RACECHECK=1 | record)
# --------------------------------------------------------------------------
#
# The dynamic half of the `races` static rule (heat_tpu/analysis/races.py):
# per-(object, field) candidate locksets, maintained from the lock-order
# watchdog's per-thread held stacks (Eraser, Savage et al. SOSP '97). A
# field starts owned by its first-touching thread; when a second thread
# touches it the candidate lockset is seeded from that thread's held
# ordered locks and intersected on every later access. A WRITE from a
# second writing thread with an empty lockset intersection is reported —
# reads shift ownership state but only write locksets are judged, matching
# the static guard map's contract (the repo's documented single-writer
# GIL-publish pattern is sanctioned, write-write races are not).
#
# HEAT_TPU_RACECHECK=1      -> raise RaceError at the racing write (tests)
# HEAT_TPU_RACECHECK=record -> emit a structured `race_detected` record and
#                              trigger the registered flight-dump hook,
#                              keep running (production triage)


class RaceError(RuntimeError):
    """A write-write race: a field written by two threads with no lock
    consistently held across the writes."""


_race_lock = threading.Lock()
_race_findings: List[dict] = []
_race_instrumented = 0
_flight_dump_hook: Optional[callable] = None
_instrumented_classes: dict = {}


def racecheck_enabled() -> bool:
    """Is the dynamic race sanitizer armed? Read at instrument/lock
    creation time, like :func:`lockcheck_enabled`."""
    return os.environ.get("HEAT_TPU_RACECHECK", "") in ("1", "record")


def _racecheck_raises() -> bool:
    return os.environ.get("HEAT_TPU_RACECHECK", "") == "1"


def set_flight_dump_hook(fn: Optional[callable]) -> None:
    """Register the flight-recorder dump callable (``Engine`` passes its
    ``_flight_dump``); called with a reason string when a race or thread
    crash is recorded in non-raising mode."""
    global _flight_dump_hook
    _flight_dump_hook = fn


def _fire_flight_dump(reason: str) -> None:
    hook = _flight_dump_hook
    if hook is None:
        return
    try:
        hook(reason)
    except Exception as e:  # noqa: BLE001 — the dump must never compound
        # the failure it is documenting
        from .logging import master_print
        master_print(f"race sanitizer: flight dump failed ({e})")


def _race_access(obj, label: str, field: str, write: bool) -> None:
    if getattr(_tls, "race_busy", False):
        return
    _tls.race_busy = True
    try:
        me = threading.get_ident()
        held = frozenset(l.name for l in _held())
        states = object.__getattribute__(obj, "_race_states")
        finding = None
        with _race_lock:
            st = states.get(field)
            if st is None:
                states[field] = {"owner": me, "writers": set(
                    [me] if write else []), "lockset": None,
                    "reported": False}
                return
            if write:
                st["writers"].add(me)
                if len(st["writers"]) >= 2:
                    st["lockset"] = (held if st["lockset"] is None
                                     else st["lockset"] & held)
            elif st["lockset"] is not None and me != st["owner"]:
                # a reader participating after sharing narrows the set
                # only if it holds SOME lock (a bare read is the
                # sanctioned GIL-publish consumer, not a vote)
                if held:
                    st["lockset"] = st["lockset"] & held
            if (write and st["lockset"] is not None
                    and not st["lockset"] and not st["reported"]):
                st["reported"] = True
                finding = {
                    "object": label, "field": field,
                    "thread": threading.current_thread().name,
                    "writers": len(st["writers"]),
                    "held": sorted(held),
                }
                _race_findings.append(finding)
        if finding is not None:
            msg = (f"race detected: {label}.{field} written from "
                   f"{finding['writers']} threads with empty lockset "
                   f"intersection (this write on "
                   f"{finding['thread']!r} holds "
                   f"{finding['held'] or 'no locks'})")
            if _racecheck_raises():
                raise RaceError(msg)
            from .logging import json_record, master_print
            master_print(f"race sanitizer: {msg}")
            json_record("race_detected", object=finding["object"],
                        field=finding["field"], thread=finding["thread"],
                        writers=finding["writers"],
                        held=finding["held"])
            _fire_flight_dump(f"race detected on {label}.{field}")
    finally:
        _tls.race_busy = False


def _instrumented_class(base: type) -> type:
    cached = _instrumented_classes.get(base)
    if cached is not None:
        return cached

    class _RaceInstrumented(base):  # type: ignore[misc, valid-type]
        __race_base__ = base

        def __getattribute__(self, name):
            val = object.__getattribute__(self, name)
            if name.startswith("_race_") or (name.startswith("__")
                                             and name.endswith("__")):
                return val
            d = object.__getattribute__(self, "__dict__")
            watch = d.get("_race_watch")
            if watch is not None and name in watch:
                _race_access(self, d.get("_race_label", base.__name__),
                             name, write=False)
            return val

        def __setattr__(self, name, value):
            object.__setattr__(self, name, value)
            d = object.__getattribute__(self, "__dict__")
            watch = d.get("_race_watch")
            if watch is not None and name in watch:
                _race_access(self, d.get("_race_label", base.__name__),
                             name, write=True)

    _RaceInstrumented.__name__ = base.__name__
    _RaceInstrumented.__qualname__ = base.__qualname__
    _instrumented_classes[base] = _RaceInstrumented
    return _RaceInstrumented


def instrument_races(obj, label: Optional[str] = None,
                     exempt: frozenset = frozenset()):
    """Arm Eraser-style per-field lockset tracking on ``obj``.

    No-op (and zero cost) unless :func:`racecheck_enabled`. The watched
    set is the instance's ``__dict__`` at instrument time — call at the
    END of ``__init__`` — minus ``exempt`` (fields the committed guard
    map sanctions via allow-markers: instance-confined accounting,
    lock-free rings), minus the synchronization objects themselves.
    Returns ``obj``."""
    global _race_instrumented
    if not racecheck_enabled():
        return obj
    if getattr(type(obj), "__race_base__", None) is not None:
        return obj  # already instrumented
    import queue as _queue
    sync_types = (threading.Event, threading.Condition,
                  threading.Semaphore, _queue.Queue, _OrderedLock,
                  type(threading.Lock()), type(threading.RLock()))
    watch = frozenset(
        k for k, v in vars(obj).items()
        if k not in exempt and not k.startswith("_race_")
        and not isinstance(v, sync_types) and not callable(v))
    object.__setattr__(obj, "_race_states", {})
    object.__setattr__(obj, "_race_watch", watch)
    object.__setattr__(obj, "_race_label", label or type(obj).__name__)
    obj.__class__ = _instrumented_class(type(obj))
    with _race_lock:
        _race_instrumented += 1
    return obj


def race_stats() -> dict:
    """Sanitizer observations so far, queryable like
    :func:`lock_order_stats`: instrumented-object count and every
    recorded finding. The chaos suite asserts ``findings == []`` after a
    full fault-injected wave under ``HEAT_TPU_RACECHECK=1``."""
    with _race_lock:
        return {"instrumented": _race_instrumented,
                "findings": [dict(f) for f in _race_findings]}


def reset_race_stats() -> None:
    global _race_instrumented
    with _race_lock:
        _race_findings.clear()
        _race_instrumented = 0


# --------------------------------------------------------------------------
# background-thread crash hook
# --------------------------------------------------------------------------

_excepthook_installed = False


def install_thread_excepthook() -> None:
    """Route uncaught background-thread exceptions (writer, scheduler,
    gateway handler) into a structured ``thread_crash`` record plus a
    flight-recorder dump instead of an easy-to-miss stderr traceback.
    Idempotent; chains to the previously installed hook so default
    stderr reporting (and pytest's capture) still sees the crash."""
    global _excepthook_installed
    if _excepthook_installed:
        return
    _excepthook_installed = True
    prev = threading.excepthook

    def hook(args):
        try:
            from .logging import json_record
            name = args.thread.name if args.thread is not None else "?"
            daemon = bool(args.thread.daemon) if args.thread is not None \
                else False
            json_record("thread_crash", thread=name,
                        exc_type=getattr(args.exc_type, "__name__",
                                         str(args.exc_type)),
                        error=str(args.exc_value), daemon=daemon)
            _fire_flight_dump(f"uncaught exception in thread {name}: "
                              f"{getattr(args.exc_type, '__name__', '?')}")
        finally:
            prev(args)

    threading.excepthook = hook


@contextlib.contextmanager
def maybe_profile(trace_dir: Optional[str]):
    """Wrap a region in a jax.profiler trace when a directory is given."""
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield


def finite_flag(T):
    """All-finite reduction WITHOUT blocking the host on it.

    Device arrays reduce ON DEVICE (``jnp.isfinite(...).all()``) and the
    replicated scalar is returned still-on-device: the caller holds it and
    fetches at the NEXT chunk boundary (``raise_if_flagged``), by which
    point the device has computed it behind the following chunk's work —
    the numerics leg of the async I/O pipeline. The on-device reduction
    also keeps the multi-host contract: the global field can span other
    processes, where ``np.asarray`` on it raises RuntimeError — the
    reduction's replicated scalar is always fetchable, and a scalar fetch
    is tunnel-cheap. Host arrays reduce eagerly (nothing to overlap).
    """
    import numpy as np

    import jax
    import jax.numpy as jnp

    if isinstance(T, jax.Array) and not isinstance(T, jax.core.Tracer):
        return jnp.isfinite(T).all()
    return np.isfinite(np.asarray(T).astype(np.float32)).all()


def raise_if_flagged(flag, step: int, label: str = "field") -> None:
    """Fetch a ``finite_flag`` result (one scalar) and raise with the step
    context the flag was computed at."""
    if not bool(flag):
        raise FloatingPointError(
            f"non-finite values in {label} at step {step} — check the CFL "
            f"bound sigma <= 1/(2*ndim) and the fuse/halo configuration"
        )


def check_finite(T, step: int, label: str = "field") -> None:
    """Synchronous form: compute the flag and block on it immediately
    (the ``--async-io off`` drive path and every per-step host caller;
    ``--async-io on`` splits this into ``finite_flag`` at the boundary +
    ``raise_if_flagged`` one boundary later)."""
    raise_if_flagged(finite_flag(T), step, label)
