"""Wall-clock timing and throughput accounting.

The reference uses two timing styles: ``cpu_time`` around everything
including IO (fortran/serial/heat.f90:25,71) and barrier-bracketed
``MPI_Wtime`` around the solve only, reported as *average seconds per
timestep* (fortran/mpi+cuda/heat.F90:253,264,292 — which mislabels the
average as "total time"; fortran/hip/heat.F90:323 labels it correctly).
We report all three, labeled correctly (SURVEY.md quirk #5), plus the
derived grid-points/sec metric used as the benchmark north star.

``jax.block_until_ready`` stands in for the device sync + MPI barrier pair.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any


def sync(x: Any) -> Any:
    """Block until device work producing x is done (== cudaDeviceSynchronize
    + MPI_BARRIER before reading the clock, fortran/mpi+cuda/heat.F90:262-264).

    ``jax.block_until_ready`` alone is NOT sufficient on every platform: on
    the tunneled single-chip ``axon`` platform it returns while work is still
    queued, which silently inflates throughput numbers by orders of
    magnitude. A 1-element device->host fetch is the only reliable fence, so
    we slice one scalar out of the first array leaf (a few bytes over the
    wire — the full-buffer fetch can be seconds on a tunnel)."""
    import jax
    import numpy as np

    x = jax.block_until_ready(x)
    for leaf in jax.tree_util.tree_leaves(x):
        # indexing would raise on a multi-host array spanning non-addressable
        # devices; there block_until_ready is a real fence already
        if isinstance(leaf, jax.Array) and leaf.size and leaf.is_fully_addressable:
            np.asarray(leaf[(0,) * leaf.ndim])
            break
    return x


class TwoPointResult(tuple):
    """(rate_corrected, rate_raw) that also carries ``fell_back`` — True
    when the noise-floor fallback fired and corrected IS the raw rate.
    A plain attribute (not a third element) so every existing
    ``rate, raw = two_point_rate(...)`` unpack keeps working; consumers
    that must NOT trust an overhead-dominated number (calibrate's HBM
    probe) read the flag instead of re-deriving it by float equality."""

    fell_back: bool

    def __new__(cls, rate: float, raw: float, fell_back: bool):
        self = super().__new__(cls, (rate, raw))
        self.fell_back = fell_back
        return self

    def __getnewargs__(self):
        # tuple's default supplies ONE arg (the content tuple) to the
        # 3-arg __new__ above, breaking pickle/copy (review r5)
        return (self[0], self[1], self.fell_back)


def two_point_rate(call, x, work, repeats: int = 2):
    """(rate_corrected, rate_raw) for ``call`` doing ``work`` units/call.

    The tunneled platform carries a fixed dispatch+sync overhead per
    measurement (~0.15 s — a harness artifact, not chip time): time one
    call (T1) and two queued back-to-back calls (T2); the fixed cost
    cancels in T2-T1 with no extra compiles. The output buffer is recycled
    as the next input (timing doesn't care about values), so with a
    donating executable the whole measurement holds one in+out buffer pair
    — feeding a fresh input per call OOMs at 32768^2 f32 (4 GiB/buffer).

    Noise floor: when T2-T1 < 20% of T1 the measurement is
    overhead-dominated and per-rep jitter can inflate the corrected rate
    unboundedly — fall back to the raw single-call rate (conservative).
    The fallback is flagged on the returned ``TwoPointResult.fell_back``.
    """
    x = call(x)  # warm; consumes x when the executable donates its input
    sync(x)
    best1 = best2 = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        x = call(x)
        sync(x)
        best1 = min(best1, time.perf_counter() - t0)
        t0 = time.perf_counter()
        x = call(call(x))
        sync(x)
        best2 = min(best2, time.perf_counter() - t0)
    raw = work / best1
    diff = best2 - best1
    if diff <= 0.2 * best1:
        return TwoPointResult(raw, raw, fell_back=True)
    return TwoPointResult(work / diff, raw, fell_back=False)


@dataclasses.dataclass
class Timing:
    total_s: float = 0.0          # everything: setup + compile + solve + IO
    compile_s: float = 0.0        # jit compile (the reference has no analog;
                                  # nvcc JIT in python/cuda/cuda.py:86 is closest)
    solve_s: float = 0.0          # solve-only wall clock
    steps: int = 0
    points: int = 0               # grid points updated per step
    # overhead-corrected rate from the two-point protocol (``two_point_rate``,
    # the same measurement bench.py's headline uses) when the solve ran with
    # two_point_repeats > 0; None otherwise. Reported alongside the raw
    # single-call ``points_per_s`` so the official table and the headline
    # metric share one protocol (VERDICT r2 #9).
    points_per_s_two_point: float | None = None
    # True when the protocol's noise-floor fallback fired and the
    # two-point field above is really the raw single-call rate; None when
    # the protocol didn't run. Consumers fitting models from the rate
    # (calibrate) must refuse fallen-back values (review r5).
    two_point_fell_back: bool | None = None
    # Async I/O pipeline accounting (None when no async writer ran).
    # overlap_s: checkpoint D2H+disk wall time hidden behind compute (the
    # writer's busy time minus any time the stepping loop spent blocked on
    # it) — under the old inline-save shape this whole quantity sat in
    # solve_s as device idle. io_wait_s: what the driver DID pay — queue
    # backpressure inside the loop (lands in solve_s: it stalls stepping)
    # plus the post-solve drain (lands in total_s only: the device is done
    # stepping; the remaining flush overlaps nothing).
    overlap_s: float | None = None
    io_wait_s: float | None = None
    # Serving-engine dispatch accounting (None outside `heat-tpu serve`).
    # dispatch_depth: chunk programs kept in flight per bucket group (0 =
    # the synchronous fallback). boundary_wait_s: host wall actually spent
    # blocked on chunk-boundary remaining-vector fetches — under
    # dispatch-ahead the transfer overlaps the chunks queued behind it,
    # so this should be a small fraction of solve_s; under the sync
    # fallback it fences the whole chunk and approaches solve_s.
    dispatch_depth: int | None = None
    boundary_wait_s: float | None = None
    # Admission policy of the run (serve/policy.py: fifo | edf | fair) —
    # reported on the dispatch line because two serve runs are only
    # comparable when their admission ordering matched.
    serve_policy: str | None = None
    # Per-lane fault-domain accounting (None outside `heat-tpu serve`).
    # lanes_quarantined: requests failed with a structured `nonfinite`
    # record (their lane freed, every co-scheduled lane untouched).
    # rollbacks: --serve-on-nan rollback restore-and-re-step events.
    # deadline_misses: requests preempted (or shed while queued) past
    # their deadline_ms budget. shed: submits rejected by --max-queue.
    lanes_quarantined: int | None = None
    rollbacks: int | None = None
    deadline_misses: int | None = None
    shed: int | None = None
    # Performance-observatory accounting (runtime/prof.py; None when the
    # observatory is off or never sampled). mem_peak_bytes: the highest
    # device-memory watermark the boundary-cadence sampler saw — the
    # number a capacity plan (and the leak sentinel) keys on.
    mem_peak_bytes: int | None = None
    # Numerics-observatory accounting (runtime/numerics.py; None when the
    # observatory is off). steady_lanes: requests whose residual EWMA
    # converged below --steady-tol with steps still remaining (fire-once
    # per request). numerics_violations: maximum-principle escapes +
    # heat-content jumps detected (one verdict per request).
    steady_lanes: int | None = None
    numerics_violations: int | None = None

    @property
    def per_step_s(self) -> float:
        return self.solve_s / self.steps if self.steps else 0.0

    @property
    def points_per_s(self) -> float:
        return self.points * self.steps / self.solve_s if self.solve_s > 0 else 0.0

    def report_lines(self) -> list[str]:
        """Human-readable report, keeping the reference's familiar lines."""
        lines = [
            "simulation completed!!!!",                       # serial/heat.f90:73
            f"total time: {self.total_s:.6f}",                # serial/heat.f90:74
            f"solve time: {self.solve_s:.6f}",
            f"Average time per timestep: {self.per_step_s:.9f}",  # hip/heat.F90:323
            f"throughput: {self.points_per_s:.4g} points/s",
        ]
        if self.compile_s:
            lines.insert(2, f"compile time: {self.compile_s:.6f}")
        if self.overlap_s is not None:
            lines.append(f"async I/O overlap: {self.overlap_s:.6f} hidden, "
                         f"{self.io_wait_s or 0.0:.6f} blocked")
        if self.dispatch_depth is not None:
            pol = (f", policy {self.serve_policy}"
                   if self.serve_policy else "")
            lines.append(f"serve dispatch: depth {self.dispatch_depth}, "
                         f"boundary wait {self.boundary_wait_s or 0.0:.6f}"
                         f"{pol}")
        if self.lanes_quarantined is not None:
            lines.append(
                f"serve faults: {self.lanes_quarantined} quarantined, "
                f"{self.rollbacks or 0} rollback(s), "
                f"{self.deadline_misses or 0} deadline miss(es), "
                f"{self.shed or 0} shed")
        if self.mem_peak_bytes is not None:
            lines.append(f"observatory: mem peak "
                         f"{self.mem_peak_bytes / 2**20:.1f} MiB")
        if self.steady_lanes is not None:
            lines.append(
                f"numerics: {self.steady_lanes} steady lane(s), "
                f"{self.numerics_violations or 0} violation(s)")
        return lines
