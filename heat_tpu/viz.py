"""Visualization: 3-D surface plots of .dat files.

One shared renderer replacing the six near-identical ``init.py``/``out.py``
copies in the reference (byte-identical across variants, SURVEY.md §1 L5).
Same presentation so plots are visually comparable: matplotlib
``plot_surface`` with viridis, x,y in [0,2], z in [1,2.5]
(fortran/serial/out.py:37-41), saved to file (the mpi variant's ``sol.eps``
behavior, fortran/mpi+cuda/out.py:45) rather than shown — headless-friendly.

Because our .dat files keep the reference format, the reference's own
``out.py`` continues to work on our output, and this module renders
reference-produced files too.
"""

from __future__ import annotations

from pathlib import Path

import numpy as np

from .io import read_dat


def render_dat(path, save="sol.png", ndim: int = 2, zlim=(1.0, 2.5)):
    """Render a .dat dump as the reference-style 3-D surface.

    2-D files render directly (the reference's out.py presentation). For
    the 3-D extension's ``x y z T`` quadruplet files, the mid-plane
    z-slice is rendered — the reference has no 3-D analog to imitate.
    """
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    from matplotlib import cm

    if ndim == 3:
        (x, y, _z), T3 = read_dat(path, ndim=3)
        T = T3[:, :, T3.shape[2] // 2]
    elif ndim == 2:
        axes, T = read_dat(path, ndim=2)
        x, y = axes
    else:
        raise ValueError(f"render_dat supports ndim 2 or 3, got {ndim}")
    X, Y = np.meshgrid(x, y, indexing="ij")
    fig = plt.figure(figsize=(8, 6))
    ax = fig.add_subplot(projection="3d")
    ax.plot_surface(X, Y, T, rstride=1, cstride=1, cmap=cm.viridis,
                    linewidth=0, antialiased=False)
    ax.set_xlim(float(x.min()), float(x.max()))
    ax.set_ylim(float(y.min()), float(y.max()))
    ax.set_zlim(*zlim)
    ax.set_xlabel("$x$")
    ax.set_ylabel("$y$")
    out = Path(save)
    fig.savefig(out, dpi=120, bbox_inches="tight")
    plt.close(fig)
    return out
