"""Semantic-scheduling A/B: ``until=steady`` early exit vs fixed-step.

The claim (ISSUE 16): a diffusive request population — sine eigenmode
ICs whose residual decays as ``lambda**s`` — asked to run "until steady"
retires lanes at the first chunk boundary whose residual EWMA passes
tolerance, and the freed lanes backfill immediately. Billing the
*requested* work (what the tenant asked for) against the drain's wall
clock, the steady run must deliver >= 1.5x the effective aggregate
throughput of the same population run to completion.

Three correctness locks ride the perf number (a perf artifact must
never certify a wrong-answer engine):

- ``steady_bit_identical`` — a sample of steady records is re-solved
  solo with ``ntime=steps_done``; the early-exit field must be
  bit-identical to the truncated fixed-step run (the exit is a
  *scheduling* decision, never a numerical one).
- ``colane_bit_identical`` — fixed-step co-requests drained alongside
  the steady population must produce byte-identical fields to the
  all-fixed-step run: semantic scheduling cannot perturb lanes that
  never opted in.
- ``zero_added_transfers`` — ``engine.host_fetch`` is the ONE D2H seam;
  a spy counts calls in both runs. The steady decision rides the
  boundary vector the engine already fetches, so the steady run must
  perform NO MORE fetches than the fixed-step run (fewer, in fact:
  retired lanes stop producing boundaries).

    JAX_PLATFORMS=cpu python benchmarks/serve_steady_lab.py [--requests 64]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time
from pathlib import Path

from _util import write_atomic

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

# tolerance per grid side, chosen (see runtime/convergence.py closed
# form) so the residual EWMA crosses well inside ntime=512: n=24 fires
# near step ~185, n=32 near step ~105 — both leave >60% of the requested
# steps on the table, which is where the throughput multiplier comes from
STEADY_TOL = {24: 2e-3, 32: 2e-3}
NTIME = 512


def build_population(count: int):
    """``count`` diffusive requests: sine eigenmode IC (the one IC with
    a closed-form decay rate — grid.sine_decay_factor), two grid sides
    so both bucket/lane-tier combos stay exercised, all asking for
    NTIME=400 steps they will not need. Step count is a chunk multiple
    (chunk 16) so the fixed-step baseline never compiles a tail."""
    from heat_tpu.config import HeatConfig

    sides = (24, 32)
    return [HeatConfig(n=sides[i % 2], ntime=NTIME, dtype="float64",
                       bc="edges", ic="sine") for i in range(count)]


def build_colanes(count: int):
    """Fixed-step co-requests mixed into BOTH runs: hat ICs (no steady
    opt-in) at a shorter step count. Their fields must come out byte-
    identical whether or not steady neighbors retire around them."""
    from heat_tpu.config import HeatConfig

    sides = (24, 32)
    return [HeatConfig(n=sides[i % 2], ntime=96 + 16 * (i % 2),
                       dtype="float64", bc="edges",
                       ic=("hat", "hat_small")[i % 2])
            for i in range(count)]


def run_engine(population, colanes, lanes, chunk, depth, steady: bool):
    """Drain population + colanes through one engine; count every
    host_fetch. ``steady=True`` submits the population as until=steady
    (per-request tol); colanes are always fixed-step."""
    from heat_tpu.serve import Engine, ServeConfig
    from heat_tpu.serve import engine as engine_mod

    eng = Engine(ServeConfig(lanes=lanes, chunk=chunk, buckets=(32,),
                             dispatch_depth=depth, emit_records=False))
    fetches = [0]
    real_fetch = engine_mod.host_fetch

    def spy_fetch(x):
        fetches[0] += 1
        return real_fetch(x)

    t0 = time.perf_counter()
    try:
        engine_mod.host_fetch = spy_fetch
        ids = []
        for i, cfg in enumerate(population):
            if steady:
                ids.append(eng.submit(cfg, until="steady",
                                      tol=STEADY_TOL[cfg.n]))
            else:
                ids.append(eng.submit(cfg))
        co_ids = [eng.submit(cfg) for cfg in colanes]
        records = eng.results()
    finally:
        engine_mod.host_fetch = real_fetch
    wall = time.perf_counter() - t0
    by_id = {r["id"]: r for r in records}
    return (wall, eng, [by_id[i] for i in ids],
            [by_id[i] for i in co_ids], fetches[0])


def _block(work, wall, eng, fetches, records):
    s = eng.summary()
    return {
        "wall_s": round(wall, 3),
        "effective_points_per_s": round(work / wall, 1),
        "ok": sum(r["status"] == "ok" for r in records),
        "rejected": sum(r["status"] == "rejected" for r in records),
        "failed": sum(r["status"] not in ("ok", "rejected")
                      for r in records),
        "steady_exits": s["steady_exits"],
        "steps_saved": s["steps_saved"],
        "chunks_dispatched": s["chunks_dispatched"],
        "host_fetches": fetches,
        "step_compiles": eng.step_compiles,
        "tail_compiles": eng.tail_compiles,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--colanes", type=int, default=8)
    ap.add_argument("--lanes", type=int, default=8)
    ap.add_argument("--chunk", type=int, default=16)
    ap.add_argument("--depth", type=int, default=2)
    ap.add_argument("--out", default=str(Path(__file__).parent
                                         / "serve_steady_lab.json"))
    args = ap.parse_args(argv)

    import numpy as np

    from heat_tpu.backends import solve

    population = build_population(args.requests)
    colanes = build_colanes(args.colanes)
    # effective throughput bills the REQUESTED work on both sides: the
    # steady engine answers the same asks, it just stops stepping once
    # the answer provably stopped changing
    work = (sum(c.points * c.ntime for c in population)
            + sum(c.points * c.ntime for c in colanes))

    # fixed-step baseline first so the steady run cannot inherit a
    # warmer process (each engine owns its compile caches)
    fx_wall, fx_eng, fx_pop, fx_co, fx_fetches = run_engine(
        population, colanes, args.lanes, args.chunk, args.depth,
        steady=False)
    st_wall, st_eng, st_pop, st_co, st_fetches = run_engine(
        population, colanes, args.lanes, args.chunk, args.depth,
        steady=True)

    fixed = _block(work, fx_wall, fx_eng, fx_fetches, fx_pop + fx_co)
    steady = _block(work, st_wall, st_eng, st_fetches, st_pop + st_co)

    # lock 1: steady exits are scheduling decisions, not numerics —
    # sampled early-exit fields == the truncated solo run, bit for bit
    sample = sorted({0, 1, args.requests // 2, args.requests - 1})
    steady_bit = True
    for i in sample:
        rec = st_pop[i]
        if rec["status"] != "ok" or rec.get("exit") != "steady":
            steady_bit = False
            break
        trunc = dataclasses.replace(population[i],
                                    ntime=int(rec["steps_done"]))
        if not np.array_equal(rec["T"], solve(trunc).T):
            steady_bit = False
            break

    # lock 2: co-lanes that never opted in are untouched across runs
    colane_bit = all(
        a["status"] == b["status"] == "ok"
        and a.get("exit") == b.get("exit") == "steps"
        and np.array_equal(a["T"], b["T"])
        for a, b in zip(fx_co, st_co))

    # lock 3: the steady decision costs zero NEW transfers — it reads
    # the boundary vector the engine fetched anyway
    zero_added = st_fetches <= fx_fetches

    all_retired = (steady["steady_exits"] == args.requests
                   and all(r.get("exit") == "steady"
                           and r["steps_done"] < NTIME for r in st_pop))
    multiplier = (fx_wall / st_wall) if st_wall > 0 else None

    rec = {
        "bench": "serve_steady_lab",
        "config": {"requests": args.requests, "colanes": args.colanes,
                   "lanes": args.lanes, "chunk": args.chunk,
                   "dispatch_depth": args.depth, "buckets": [32],
                   "sides": [24, 32], "ntime": NTIME,
                   "steady_tol": {str(k): v for k, v
                                  in sorted(STEADY_TOL.items())},
                   "dtype": "float64"},
        "work_cell_steps": work,
        "fixed": fixed,
        "steady": steady,
        "throughput_multiplier": (round(multiplier, 2)
                                  if multiplier else None),
        "all_population_retired_steady": all_retired,
        "steady_bit_identical": steady_bit,
        "colane_bit_identical": colane_bit,
        "zero_added_transfers": zero_added,
    }
    write_atomic(Path(args.out), rec)
    print(json.dumps(rec, indent=2))
    passed = (fixed["ok"] == steady["ok"] == args.requests + args.colanes
              and fixed["failed"] == steady["failed"] == 0
              and fixed["steady_exits"] == 0
              and all_retired
              and steady_bit and colane_bit and zero_added
              and multiplier is not None and multiplier >= 1.5)
    print(f"serve_steady_lab: {'OK' if passed else 'FAILED'} — "
          f"{rec['throughput_multiplier']}x effective throughput "
          f"({steady['effective_points_per_s']:.3g} vs "
          f"{fixed['effective_points_per_s']:.3g} pts/s), "
          f"{steady['steady_exits']} steady exit(s) saved "
          f"{steady['steps_saved']} step(s), host fetches "
          f"{st_fetches} vs {fx_fetches} fixed, bit-identical "
          f"steady={steady_bit} colane={colane_bit}")
    return 0 if passed else 1


if __name__ == "__main__":
    sys.exit(main())
