"""Recovery overhead A/B: uninterrupted vs crash-at-50% (ISSUE 2).

Measures what the self-healing supervisor actually costs: two identical
``heat-tpu launch -n 2`` sharded solves, one clean, one with an injected
worker crash at the halfway step (``--inject crash@N/2:proc=1``,
``--max-restarts 2``). Reports wall time for both, the recovery overhead
(absolute + fraction), whether the healed run's final field is
bit-identical to the clean one, and the supervisor's restart records.

Works on any host (CPU virtual devices — the same world the chaos tests
use); on TPU the numbers additionally capture real checkpoint D2H cost.

    python benchmarks/recovery_lab.py [--n 64] [--steps 32] [--out ...]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time
from pathlib import Path

from _util import write_atomic

REPO = Path(__file__).resolve().parent.parent


def _launch(workdir: Path, n: int, steps: int, ckpt_every: int,
            inject: str | None, timeout_s: int) -> dict:
    (workdir / "input.dat").write_text(f"{n} 0.25 0.05 2.0 {steps} 1\n")
    cmd = [sys.executable, "-m", "heat_tpu", "launch", "-n", "2",
           "--max-restarts", "2", "run", "--backend", "sharded",
           "--dtype", "float64", "--mesh", "2x1",
           "--checkpoint-every", str(ckpt_every), "--async-io", "off"]
    if inject:
        cmd += ["--inject", inject]
    env = {**os.environ,
           "PYTHONPATH": str(REPO) + os.pathsep + os.environ.get("PYTHONPATH", ""),
           "HEAT_TPU_RESTART_BACKOFF_S": "0.1"}
    t0 = time.perf_counter()
    p = subprocess.run(cmd, cwd=workdir, env=env, capture_output=True,
                       text=True, timeout=timeout_s)
    wall = time.perf_counter() - t0
    restarts = [json.loads(l.split("launch: restart ", 1)[1])
                for l in p.stderr.splitlines()
                if l.startswith("launch: restart ")]
    return {"rc": p.returncode, "wall_s": round(wall, 3),
            "restarts": restarts,
            "stderr_tail": p.stderr[-1500:] if p.returncode else ""}


def _shard_bytes(workdir: Path) -> list:
    return [f.read_bytes() for f in sorted(workdir.glob("soln0*.dat"))]


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--n", type=int, default=64)
    ap.add_argument("--steps", type=int, default=32)
    ap.add_argument("--timeout", type=int, default=600,
                    help="per-run subprocess timeout (s)")
    ap.add_argument("--out", default=str(Path(__file__).parent
                                        / "recovery_lab.json"))
    args = ap.parse_args()
    ckpt_every = max(2, args.steps // 8)
    crash_at = max(ckpt_every, args.steps // 2)

    with tempfile.TemporaryDirectory() as td:
        d_clean, d_chaos = Path(td) / "clean", Path(td) / "chaos"
        d_clean.mkdir(), d_chaos.mkdir()
        clean = _launch(d_clean, args.n, args.steps, ckpt_every,
                        None, args.timeout)
        chaos = _launch(d_chaos, args.n, args.steps, ckpt_every,
                        f"crash@{crash_at}:proc=1", args.timeout)
        bit_identical = (clean["rc"] == 0 and chaos["rc"] == 0
                         and _shard_bytes(d_clean) == _shard_bytes(d_chaos))

    overhead = (round(chaos["wall_s"] - clean["wall_s"], 3)
                if clean["rc"] == 0 and chaos["rc"] == 0 else None)
    rec = {
        "bench": "recovery_lab",
        "config": {"n": args.n, "steps": args.steps,
                   "checkpoint_every": ckpt_every, "crash_at": crash_at,
                   "processes": 2, "mesh": "2x1", "dtype": "float64"},
        "uninterrupted": clean,
        "crash_resume": chaos,
        "recovery_overhead_s": overhead,
        "recovery_overhead_frac": (round(overhead / clean["wall_s"], 3)
                                   if overhead is not None
                                   and clean["wall_s"] > 0 else None),
        "bit_identical_final_field": bit_identical,
    }
    write_atomic(Path(args.out), rec)
    print(json.dumps(rec, indent=2))
    ok = (clean["rc"] == 0 and chaos["rc"] == 0 and bit_identical
          and len(chaos["restarts"]) >= 1)
    print(f"recovery_lab: {'OK' if ok else 'FAILED'} — "
          f"clean {clean['wall_s']}s vs crash-resume {chaos['wall_s']}s "
          f"({len(chaos['restarts'])} restart(s); "
          f"bit-identical={bit_identical})")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
