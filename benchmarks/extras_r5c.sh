#!/bin/bash
# Round-5 final-session phase 3: surplus-window work. Today's host is
# ~5x faster at Mosaic compiles than Aug 1 (chip_check 62 s vs ~20 min;
# 16384-class kernel compiles ~82 s vs 471 s), so the window funds
# exploration the round never had room for:
#   1. full official-table refresh — rows 1/2/4/5 were measured Aug 1
#      BEFORE the fuse-cap change that lifted bench +8.5% and row 3
#      +12%; a same-code same-host table beats a mixed-vintage one.
#   2. thin-band BAND-SIZE A/B at the headline shape: _tile_2d hard-caps
#      the band at 256 rows, but the VMEM budget at 4096^2 admits ~700
#      and the cost model says bigger is strictly better (lower halo
#      overhead + fewer passes). If 512/768 measures faster, the cap is
#      costing headline points and becomes a planner change.
#   3. bf16native at n2=4096 ON-CHIP: completes the size bracket of the
#      remote-compile-helper failure (16384 fails, AOT-topology 4096
#      compiles — does the helper accept 4096?).
#   4. 3D geometry A/B around the shipped (64,64,8,8) plan + fma variant
#      (the old queue's 3d_f32_ab/3d_fma_ab, dropped on Aug 1).
#   5. thin rolledfma A/B (old thin_fma_ab phase).
#   6. one more live k=32 compile sample (instability population).
# Waits for extras_r5b to exit first — ONE chip, ONE queue.
set -u
export JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-${XDG_CACHE_HOME:-$HOME/.cache}/heat_tpu/jax}"
export PYTHONPATH="$(cd "$(dirname "$0")/.." && pwd):${PYTHONPATH:-}"
cd "$(dirname "$0")/.."

HARD_END=${HARD_END:-1785722400}  # 2026-08-03 02:00 UTC

# Wait for the r5b queue, BOUNDED by HARD_END, and match the actual runner
# invocation only ("bash .*extras_r5b.sh") — a bare -f "extras_r5b.sh"
# matches any command line containing the string (an editor, `less`, a
# stale orphan) and this loop used to run before any deadline existed, so
# the queue could block forever (ADVICE r5).
while pgrep -f "bash .*extras_r5b\.sh" > /dev/null 2>&1; do
  if [ "$(date +%s)" -ge "$HARD_END" ]; then
    echo "=== extras_r5c gave up waiting for extras_r5b at $(date)"
    exit 1
  fi
  sleep 60
done

DEADLINE=$(( $(date +%s) + ${BUDGET_S:-30000} ))
[ "$DEADLINE" -gt "$HARD_END" ] && DEADLINE=$HARD_END

probe() { timeout 120 python -c "import jax; assert jax.devices()" 2>/dev/null; }

wait_up() {
  until probe; do
    if [ "$(date +%s)" -ge "$DEADLINE" ]; then
      echo "=== extras_r5c budget exhausted waiting at $(date)"; exit 1
    fi
    echo "tunnel down at $(date); waiting"
    sleep 300
  done
}

phase() {
  local name=$1 to=$2; shift 2
  if [ "$(date +%s)" -ge "$DEADLINE" ]; then
    echo "=== budget exhausted before $name"; exit 1
  fi
  wait_up
  local remaining=$(( DEADLINE - $(date +%s) ))
  if [ "$remaining" -lt 120 ]; then
    echo "=== budget exhausted before $name"; exit 1
  fi
  [ "$to" -gt "$remaining" ] && to=$remaining
  echo "=== $name start $(date) (timeout ${to}s)"
  if timeout "$to" "$@"; then
    echo "=== $name OK $(date)"
  else
    echo "=== $name FAILED rc=$? $(date)"
  fi
}

phase run_all_refresh  7200 python benchmarks/run_all.py --row-timeout 2500
# --steps 2048: benchthin's 64-step default is sized for 32768^2; at
# 4096^2 it is ~6 ms of device work against the ~150 ms tunnel dispatch
# floor and measures the floor, not the band size (the committed
# sweep_r5c.log rows read 6-8% of roofline for exactly this reason —
# see the annotation there; ADVICE r5).
phase thin_band_ab     3600 python benchmarks/kernel_lab.py benchthin 4096 float32 rolled,256,16 rolled,512,16 rolled,768,16 rolled,384,16 rolled,512,8 --steps 2048
phase bf16n_4096_probe 1200 python benchmarks/kernel_lab.py bench2d_rolled_var bf16native 256,4096,16,128 --n2 4096
phase 3d_geom_ab       3600 python benchmarks/kernel_lab.py bench3d_rolled_var f32 64,64,8,8 128,64,8,8 64,128,8,8 96,96,8,8
phase 3d_fma_ab        1800 python benchmarks/kernel_lab.py bench3d_rolled_var fma 64,64,8,8
phase thin_fma_ab      1800 python benchmarks/kernel_lab.py benchthin 4096 float32 rolled,256,16 rolledfma,256,16 --steps 2048
phase compile_bisect32 2000 python benchmarks/compile_bisect.py --ks 32 --timeout 1800
# Crash-recovery A/B (ISSUE 2): uninterrupted vs crash-at-50% launch,
# reporting supervisor restart overhead + bit-identity of the final field.
# CPU-world benchmark (spawns its own 2-process virtual world) — needs no
# chip, so it runs even when the tunnel is down; keep it last so chip
# phases get the budget first.
phase recovery_lab     1200 env JAX_PLATFORMS=cpu python benchmarks/recovery_lab.py
# Serving-engine A/B (ISSUE 3 + 4): 64 mixed-size requests, three ways —
# dispatch-ahead engine (pipelined boundaries, async extraction) vs the
# synchronous fallback (--dispatch-depth off) vs sequential solos.
# Reports aggregate throughput ratios, boundary-wait wall, estimated
# device-idle fraction, one-compile-per-(bucket,lane-tier) accounting,
# and a bit-identity spot-check on BOTH engine modes. CPU-world like
# recovery_lab: runs even with the tunnel down.
phase serve_lab        1200 env JAX_PLATFORMS=cpu python benchmarks/serve_lab.py
# Serving chaos A/B (ISSUE 5): the same 64-request wave clean vs ~10%
# lane-nan-poisoned — poisoned lanes must quarantine with structured
# nonfinite records while healthy-request aggregate throughput stays
# within 10% of the clean run and a healthy sample stays bit-identical.
# CPU-world: runs with the tunnel down.
phase serve_chaos_lab  1200 env JAX_PLATFORMS=cpu python benchmarks/serve_chaos_lab.py
# Serve lane-kernel A/B (ISSUE 9): the serve_lab shape/step population
# at float32 under --serve-lane-kernel pallas vs xla vs solo Pallas
# drives. Hard gates everywhere: pallas-vs-xla npz byte-identity, a
# solo-oracle sample, zero lane_kernel_fallback events. The perf gate
# (Pallas lane program beats the XLA lane program per chip, targeting
# ROADMAP's ~90%-of-solo-Pallas bar) is hard on TPU, informational on
# CPU (interpret-mode kernels). CPU-world: runs with the tunnel down.
phase serve_lane_kernel_lab 1200 env JAX_PLATFORMS=cpu python benchmarks/serve_lane_kernel_lab.py
# Two-tier placement A/B (ISSUE 10): the serve_lab small population plus
# oversized requests on a virtual 8-device CPU mesh — previously-rejected
# bucket-overflow requests must complete as sharded mega-lanes with zero
# overflow rejections, npz payloads byte-identical to a solo sharded
# drive(), and packed-lane aggregate throughput within 10% of a mega-free
# drain (and of serve_lab.json) while a mega-lane is resident.
# CPU-world: runs with the tunnel down.
phase serve_mega_lab   1200 env JAX_PLATFORMS=cpu python benchmarks/serve_mega_lab.py
# Mosaic compile check for the lane kernels (ISSUE 9): AOT-compile the
# exact serve chunk programs (both kernels' donation modes, 2D/3D,
# f32/bf16) against a single v5e chip via the chipless topology path —
# interpret-mode tier-1 cannot catch Mosaic-only rejections (SMEM block
# rules, missing lowerings, sub-32-bit selects); this can.
phase lane_kernel_compile_check 1200 env JAX_PLATFORMS=cpu python benchmarks/lane_kernel_compile_check.py
# Serving front-end A/B (ISSUE 6): open-loop Poisson arrivals into the
# ONLINE engine under --policy edf vs fifo (same seeded schedule, real
# backlog at 3x the measured service rate) — EDF must meet >= FIFO's
# deadline-hit rate — plus an offline policy-layer drain that must stay
# within 5% of serve_lab.json's engine throughput (the front-end adds
# no hot-loop cost). CPU-world: runs with the tunnel down.
phase serve_frontend_lab 1200 env JAX_PLATFORMS=cpu python benchmarks/serve_frontend_lab.py
# Tracing-overhead A/B (ISSUE 7): the serve_lab 64-request wave with
# tracing off vs flight-recorder-only vs full --trace export — the
# observability layer must keep full tracing within 2% of tracing-off
# throughput (best-of-N walls), with a non-empty Perfetto-loadable
# export. CPU-world: runs with the tunnel down.
phase trace_overhead_lab 1200 env JAX_PLATFORMS=cpu python benchmarks/trace_overhead_lab.py
# Observatory-overhead A/B (ISSUE 8): the serve_lab wave with the full
# performance/cost observatory (online chunk-cost model + per-tenant
# usage ledger + memory watermarks + SLO burn windows) vs observatory
# off — must stay within 2% and keep npz outputs byte-identical at
# dispatch depths 0 and 2, with the usage ledger reconciling exactly
# against the per-record stamps. CPU-world: runs with the tunnel down.
phase prof_overhead_lab 1200 env JAX_PLATFORMS=cpu python benchmarks/prof_overhead_lab.py
# Numerics-observatory A/B (ISSUE 15): the serve_lab wave with per-lane
# solution-quality stats (residual/min/max/heat riding the boundary
# vector) ingested vs --numerics off — must stay within 2%, keep npz
# outputs byte-identical at dispatch depths 0 and 2, verify one live
# canary probe against the closed-form sine-eigenmode decay, and fire
# the maximum-principle detector on a seeded perturb fault. CPU-world:
# runs with the tunnel down.
phase numerics_overhead_lab 1200 env JAX_PLATFORMS=cpu python benchmarks/numerics_overhead_lab.py
# Semantic scheduling A/B (ISSUE 16): 64-request diffusive population
# run until=steady vs fixed-step — >= 1.5x effective aggregate
# throughput, steady records bit-identical to the truncated fixed-step
# run, co-lanes byte-identical, zero added D2H (host_fetch-spy-gated).
# CPU-world: runs with the tunnel down.
phase serve_steady_lab 1200 env JAX_PLATFORMS=cpu python benchmarks/serve_steady_lab.py
# Zero-downtime serving A/B (ISSUE 17): the 64-request wave run
# uninterrupted vs killed at the generation nearest 50% of its
# boundaries and resumed from the surviving engine manifest — all 64
# npz byte-identical, zero re-stepped chunks past the last checkpointed
# boundary, recovery overhead = one manifest load + lane reseed.
# CPU-world: runs with the tunnel down.
phase serve_resume_lab 1200 env JAX_PLATFORMS=cpu python benchmarks/serve_resume_lab.py
# Pod-scale fleet lab (ISSUE 18): the 64-request wave drained through
# the fleet router over 1/2/4 real serve subprocesses (each request
# carrying a 200 ms writer-sink sleep so per-engine serialization makes
# fleet width measurable on one core) — gates >= 1.7x aggregate
# throughput at 2 backends and monotone at 4, plus a SIGKILL drill
# (zero lost / zero double-served via checkpoint adoption) and a forced
# /drainz?handoff=1 steal with its recovery wall recorded. CPU-world:
# runs with the tunnel down.
phase fleet_lab        1200 env JAX_PLATFORMS=cpu python benchmarks/fleet_lab.py
# Fleet resilience lab (ISSUE 20): chaos drills against the router's
# resilience layer — a flapping backend (circuit breaker opens, sine
# canary re-admits through the router path, availability >= 0.99, p99
# <= 1.5x healthy, zero flap-induced steal thrash), a mid-stream relay
# cut re-driven exactly-once (zero lost / zero duplicated rows), a
# hedged interactive row winning on the idle backend bit-identically,
# and expired edge-minted deadlines shed with zero billed device
# steps. CPU-world: runs with the tunnel down.
phase fleet_resilience_lab 1200 env JAX_PLATFORMS=cpu python benchmarks/fleet_resilience_lab.py
# Solve-cache A/B (ISSUE 19): a repeat-heavy 32-request wave cold vs
# warm against one shared cache dir — warm wave >= 5x cold with every
# request a full hit (zero device chunk programs, zero billed steps,
# npz byte-identical to the cold run), a 33%-deeper request stepping
# exactly the prefix delta, and --cache off byte-identical to cached.
# CPU-world: runs with the tunnel down.
phase serve_cache_lab  1200 env JAX_PLATFORMS=cpu python benchmarks/serve_cache_lab.py
# Invariant guard (ISSUE 11 + 14): lint + the project-native
# static-analysis suite (hot-path purity, lock discipline, traced-code
# determinism, Mosaic kernel safety, race lockset inference) + the
# record-schema and guard-map drift gates. Pure AST — no device,
# seconds of wall — so it runs first among the gates and with the
# tunnel down.
phase static_check 600 make check
# Race sanitizer e2e (ISSUE 14): the chaos + serving suites re-run with
# HEAT_TPU_RACECHECK=1 — the thread-shared engine/writer/tracer/gateway
# objects get instrumented and any cross-thread write whose Eraser
# candidate lockset drains to empty raises RaceError. CPU-world: runs
# with the tunnel down.
phase race_sanitizer 1800 make race
# Program auditor, full tier (ISSUE 13): every registered program family
# traced to jaxpr + AOT-lowered StableHLO on abstract inputs and gated
# on all five contract families — donation honored in the alias table
# (rollback provably not aliasing), zero host callbacks in hot programs,
# dtype discipline under x64, compile-key budget vs the enumerated
# ServeConfig key space, and digest drift vs the committed registry.
# `make check` above ran the fast tier; this is the full one. No device,
# no execution — runs with the tunnel down.
phase program_audit 900 env JAX_PLATFORMS=cpu python -m heat_tpu audit
# Perf regression gate (ISSUE 8): fresh prof_overhead_lab vs the
# committed baseline within a tolerance band, every committed lab's
# internal gates re-validated, the online cost model cross-checked
# against calibration_v5e.json (hard gate on TPU, informational on
# CPU), (ISSUE 11) the HEAT_TPU_LOCKCHECK=1 lock-order watchdog's
# serve-wave overhead verified noise-level with zero inversions, and
# (ISSUE 14) the HEAT_TPU_RACECHECK race sanitizer's overhead gated the
# same way with zero findings.
phase perfcheck 1800 env JAX_PLATFORMS=cpu python -m heat_tpu perfcheck
echo "=== extras_r5c done at $(date)"
