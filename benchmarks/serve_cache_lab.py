"""Solve-cache A/B: content-addressed result reuse under repeat load.

The ISSUE-19 claim, measured: a repeat-heavy wave against a warm cache
must cost file copies, not device steps. One wave of ``--requests``
requests spanning ``--distinct`` distinct physics configs runs twice
through the dispatch-ahead engine sharing one ``--cache-dir``:

- **cold**: empty cache — every distinct config computes (intra-wave
  repeats may hit entries published mid-drain; that is the production
  behavior and is measured as such);
- **warm**: a fresh engine over the SAME wave and the now-populated
  cache — every request must be a full hit: zero device chunk programs
  dispatched, zero billed steps, npz bytes identical to the cold run's.

Three acceptance gates ride in the artifact (perfcheck-enforced):

- ``warm_speedup`` >= 5: the warm wave's wall clock at least 5x under
  the cold wave's (replay is a byte copy; on a real accelerator the
  ratio is the solve cost itself);
- ``full_hit_bit_identical``: every warm npz byte-identical to its
  cold twin (replay is ``copyfile``, never re-serialization);
- ``prefix_delta_exact`` + ``prefix_bit_identical``: a request 33%
  deeper than a cached entry steps exactly the delta
  (``usage.steps == ntime - cached_step``, the prefix credited as
  ``steps_saved``) and finishes byte-identical to a cold solo solve
  of the same config.

``cache_off_bit_identical`` also rides along: ``--cache off`` (the
default) produces the same bytes as the cold cached run — the cache
can be disabled without perturbing results.

    JAX_PLATFORMS=cpu python benchmarks/serve_cache_lab.py [--requests 64]
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile
import time
from pathlib import Path

from _util import write_atomic

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def build_wave(count: int, distinct: int):
    from heat_tpu.config import HeatConfig

    sizes = (24, 32, 48)
    cfgs = [HeatConfig(n=sizes[k % len(sizes)], ntime=96 + 16 * (k % 2),
                       dtype="float64", ic=("hat", "sine")[k % 2],
                       bc="edges", nu=0.05 + 0.01 * k)
            for k in range(distinct)]
    return [cfgs[i % distinct] for i in range(count)]


def run_wave(reqs, out_dir: Path, cache_dir: Path, lanes: int,
             chunk: int, depth: int, cache: bool = True):
    from heat_tpu.serve import Engine, ServeConfig

    out_dir.mkdir(parents=True, exist_ok=True)
    eng = Engine(ServeConfig(lanes=lanes, chunk=chunk, buckets=(32, 48),
                             dispatch_depth=depth, emit_records=False,
                             out_dir=str(out_dir), cache=cache,
                             cache_dir=str(cache_dir)))
    t0 = time.perf_counter()
    ids = [eng.submit(cfg) for cfg in reqs]
    records = eng.results()
    wall = time.perf_counter() - t0
    by_id = {r["id"]: r for r in records}
    return wall, eng, [by_id[i] for i in ids]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--distinct", type=int, default=8)
    ap.add_argument("--lanes", type=int, default=8)
    ap.add_argument("--chunk", type=int, default=16)
    ap.add_argument("--depth", type=int, default=2)
    ap.add_argument("--out", default=str(Path(__file__).parent
                                         / "serve_cache_lab.json"))
    args = ap.parse_args(argv)

    import numpy as np

    from heat_tpu.serve import Engine, ServeConfig

    reqs = build_wave(args.requests, args.distinct)
    work = tempfile.mkdtemp(prefix="serve_cache_lab_")
    cache_dir = Path(work) / "solve-cache"

    cold_wall, cold_eng, cold_recs = run_wave(
        reqs, Path(work) / "cold", cache_dir, args.lanes, args.chunk,
        args.depth)
    warm_wall, warm_eng, warm_recs = run_wave(
        reqs, Path(work) / "warm", cache_dir, args.lanes, args.chunk,
        args.depth)

    warm_all_cached = all(r.get("cached") for r in warm_recs)
    warm_zero_steps = all(r["usage"]["steps"] == 0 for r in warm_recs)
    bit_identical = all(
        (Path(work) / "warm" / f"{w['id']}.npz").read_bytes()
        == (Path(work) / "cold" / f"{c['id']}.npz").read_bytes()
        for c, w in zip(cold_recs, warm_recs))
    speedup = cold_wall / warm_wall if warm_wall else float("inf")

    # prefix reuse: one config 33% deeper than its cached entry must
    # step exactly the delta and finish byte-identical to a cold solo
    base = reqs[0]
    deep = base.with_(ntime=base.ntime + base.ntime // 3)
    delta = deep.ntime - base.ntime
    _, _, (prefix_rec,) = run_wave(
        [deep], Path(work) / "prefix", cache_dir, args.lanes,
        args.chunk, args.depth)
    solo_eng = Engine(ServeConfig(lanes=args.lanes, chunk=args.chunk,
                                  buckets=(32, 48),
                                  dispatch_depth=args.depth,
                                  emit_records=False))
    solo_id = solo_eng.submit(deep)
    solo_rec = {r["id"]: r for r in solo_eng.results()}[solo_id]
    prefix_delta_exact = (prefix_rec["usage"]["steps"] == delta
                          and prefix_rec["usage"]["steps_saved"]
                          == base.ntime)
    with np.load(Path(work) / "prefix" / f"{prefix_rec['id']}.npz") as z:
        prefix_bit_identical = np.array_equal(z["T"], solo_rec["T"])

    # --cache off must be byte-identical to the cached cold run
    off_wall, _, off_recs = run_wave(
        reqs[:args.distinct], Path(work) / "off", cache_dir, args.lanes,
        args.chunk, args.depth, cache=False)
    off_identical = all(
        (Path(work) / "off" / f"{o['id']}.npz").read_bytes()
        == (Path(work) / "cold" / f"{c['id']}.npz").read_bytes()
        for o, c in zip(off_recs, cold_recs[:args.distinct]))

    cold_stats = cold_eng.summary()["cache"]
    warm_stats = warm_eng.summary()["cache"]
    rec = {
        "bench": "serve_cache_lab",
        "config": {"requests": args.requests, "distinct": args.distinct,
                   "lanes": args.lanes, "chunk": args.chunk,
                   "dispatch_depth": args.depth},
        "cold": {"wall_s": round(cold_wall, 3),
                 "ok": sum(r["status"] == "ok" for r in cold_recs),
                 "cache": cold_stats},
        "warm": {"wall_s": round(warm_wall, 3),
                 "ok": sum(r["status"] == "ok" for r in warm_recs),
                 "all_cached": warm_all_cached,
                 "zero_billed_steps": warm_zero_steps,
                 "cache": warm_stats},
        "prefix": {"cached_step": base.ntime, "ntime": deep.ntime,
                   "stepped": prefix_rec["usage"]["steps"],
                   "steps_saved": prefix_rec["usage"]["steps_saved"]},
        "warm_speedup": round(speedup, 2),
        "warm_speedup_ge_5": speedup >= 5.0,
        "full_hit_bit_identical": bit_identical,
        "prefix_delta_exact": prefix_delta_exact,
        "prefix_bit_identical": prefix_bit_identical,
        "cache_off_bit_identical": off_identical,
    }
    write_atomic(Path(args.out), rec)
    print(json.dumps(rec, indent=2))
    passed = (speedup >= 5.0 and bit_identical and warm_all_cached
              and warm_zero_steps and prefix_delta_exact
              and prefix_bit_identical and off_identical)
    print(f"serve_cache_lab: {'OK' if passed else 'FAILED'} — warm wave "
          f"{speedup:.1f}x cold ({warm_wall:.3f}s vs {cold_wall:.3f}s), "
          f"{warm_stats['hits_full']} full hit(s), prefix stepped "
          f"{prefix_rec['usage']['steps']}/{deep.ntime} "
          f"(saved {prefix_rec['usage']['steps_saved']}), "
          f"bit-identical={bit_identical}")
    return 0 if passed else 1


if __name__ == "__main__":
    sys.exit(main())
