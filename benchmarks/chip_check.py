"""On-chip numeric certification: the REAL Mosaic-compiled kernels vs the
numpy oracle.

CI validates the Pallas kernels in interpret mode (a simulation of the
kernel semantics); the compiled Mosaic artifact the chip actually runs is
only exercised by benchmarks, which never check values. This harness
closes that gap: on the attached TPU it runs every backend x BC x dtype x
rank combination the kernels ship, at real (but small) sizes, and diffs
the result against the serial numpy oracle with dtype-appropriate
tolerances — the reference's cross-variant `soln.dat`-vs-serial check
(SURVEY.md SS4), executed on hardware.

Run: PYTHONPATH=/root/repo:/root/.axon_site python benchmarks/chip_check.py
Writes benchmarks/chip_check.json (skipped off-TPU: certifying the CPU
path would re-test what CI already covers).
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def cases():
    from heat_tpu.config import HeatConfig

    # 2D: every bc on both device backends, both dtypes; the fusion axis
    # applies to pallas only (the xla step has no fuse knob — varying it
    # there would re-certify identical programs). Sizes cross tile
    # boundaries (n=200 is not lane-aligned).
    for backend in ("xla", "pallas"):
        for bc in ("edges", "ghost", "periodic"):
            for dtype, tol in (("float32", 5e-6), ("bfloat16", 5e-2)):
                fuses = (0, 1) if backend == "pallas" else (0,)
                for fuse in fuses:  # 0 = auto (deep fusion), 1 = unfused
                    yield (f"2d-{backend}-{bc}-{dtype}-fuse{fuse}",
                           HeatConfig(n=200, ntime=24, dtype=dtype,
                                      backend=backend, bc=bc, ic="hat",
                                      fuse_steps=fuse),
                           tol)
    # 3D: the (row,mid)-tiled kernel, both dtypes
    for dtype, tol in (("float32", 5e-6), ("bfloat16", 5e-2)):
        yield (f"3d-pallas-edges-{dtype}",
               HeatConfig(n=48, ndim=3, ntime=10, dtype=dtype, sigma=0.15,
                          backend="pallas", bc="edges", ic="hat"),
               tol)
    # sharded on the one real chip (1x1 mesh): the padded-carry path +
    # bounded kernel + halo machinery, all three BCs
    for bc in ("edges", "ghost", "periodic"):
        yield (f"2d-sharded-{bc}-float32",
               HeatConfig(n=256, ntime=20, dtype="float32",
                          backend="sharded", bc=bc, ic="hat"),
               5e-6)


def main() -> int:
    import jax
    import numpy as np

    if jax.default_backend() != "tpu":
        print("chip_check: no TPU attached; CI already covers the "
              "interpret/CPU paths — nothing to certify")
        return 0

    from heat_tpu.backends import solve

    rows = []
    failed = 0
    oracles = {}  # many cases collapse to one oracle config: solve it once
    for name, cfg, tol in cases():
        # oracle in f32 (bf16 storage still accumulates in f32; comparing
        # against an f32 oracle bounds the storage rounding via tol)
        oracle_cfg = cfg.with_(backend="serial", fuse_steps=0,
                               dtype="float32")
        try:
            if oracle_cfg not in oracles:
                oracles[oracle_cfg] = solve(oracle_cfg).T
            ref = oracles[oracle_cfg]
            got = solve(cfg, warm_exec=False).T
            err = float(np.max(np.abs(
                np.asarray(got, np.float32) - np.asarray(ref, np.float32))))
            ok = bool(err < tol)
        except Exception as e:  # noqa: BLE001 - record, keep certifying
            err, ok = None, False  # None: JSON-safe (NaN is invalid JSON)
            print(f"{name:40s} ERROR {type(e).__name__}: {str(e)[:120]}",
                  flush=True)
        else:
            print(f"{name:40s} max|err| {err:.2e}  "
                  f"{'OK' if ok else f'FAIL (tol {tol:g})'}", flush=True)
        failed += not ok
        rows.append({"name": name, "max_abs_err": err, "tol": tol,
                     "ok": ok})

    out = Path(__file__).parent / "chip_check.json"
    out.write_text(json.dumps(
        {"ts": time.time(), "platform": "tpu",
         "passed": len(rows) - failed, "failed": failed, "rows": rows},
        indent=2))
    print(f"chip_check: {len(rows) - failed}/{len(rows)} passed; wrote {out}")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
