"""Observatory-overhead A/B: the metering layer must observe, not perturb.

Two runs of serve_lab's 64-request wave through the same engine
configuration, differing ONLY in ``ServeConfig.prof`` (runtime/prof.py):

- ``off`` — observatory disabled: no cost model, no usage aggregation,
  no memory watermark sampling, no burn windows (records still carry
  their usage stamps — those are schema, not metering);
- ``on``  — the FULL observatory: online chunk-cost model, per-tenant
  usage ledger, memory watermarks sampled every 8 boundaries (denser
  than the production default of 32, so the A/B bounds a *worse* cadence
  than deployments pay), and SLO burn-rate windows fed by per-request
  deadlines. Requests carry tenants and deadlines so every instrument
  actually runs.

Acceptance gates (ISSUE 8):

- **on within 2% of off** (best-of-N walls — the per-boundary delta is
  microseconds, so best-of-N is the honest cost-floor estimator, same
  protocol as trace_overhead_lab.py);
- **bit-identity**: result npz files byte-identical with the observatory
  on vs off at dispatch depths 0 AND 2 (the observatory touches no
  device program, no dispatch order, no donation chain — identical
  bytes are the proof);
- **usage reconciliation**: the ledger's totals equal the sum of the
  per-record usage stamps exactly (ints) / to 1e-6 (lane-seconds float
  summation order).

The committed JSON also embeds the "on" engine's cost-model snapshot —
``heat-tpu perfcheck`` cross-checks it against the committed baseline
and against calibration_v5e.json.

    JAX_PLATFORMS=cpu python benchmarks/prof_overhead_lab.py [--repeats 3]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

from _util import write_atomic

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(Path(__file__).resolve().parent))

from serve_lab import build_requests  # noqa: E402  (benchmarks dir path)

TENANTS = ("acme", "zeta", "free-tier")
CLASSES = ("interactive", "standard", "batch")


def submit_all(eng, reqs):
    """The serve_lab population dressed with SLO fields so the ledger
    and burn monitor meter real multi-tenant traffic: round-robin
    tenants/classes, a generous deadline on every request (dated
    requests are what the burn windows count)."""
    return [eng.submit(cfg, tenant=TENANTS[i % len(TENANTS)],
                       slo_class=CLASSES[i % len(CLASSES)],
                       deadline_ms=120_000.0)
            for i, cfg in enumerate(reqs)]


def run_mode(reqs, lanes, chunk, depth, prof, out_dir=None):
    from heat_tpu.serve import Engine, ServeConfig

    eng = Engine(ServeConfig(lanes=lanes, chunk=chunk, buckets=(32, 48),
                             dispatch_depth=depth, emit_records=False,
                             prof=prof, mem_poll_every=8,
                             out_dir=str(out_dir) if out_dir else None))
    t0 = time.perf_counter()
    ids = submit_all(eng, reqs)
    records = eng.results()
    wall = time.perf_counter() - t0
    by_id = {r["id"]: r for r in records}
    ok = sum(by_id[i]["status"] == "ok" for i in ids)
    return wall, ok, eng, [by_id[i] for i in ids]


def reconcile(eng, records) -> bool:
    """Ledger totals vs the sum of per-record usage stamps — the
    GET /v1/usage exactness contract, checked inside the lab so the
    committed artifact certifies it on the full population."""
    totals = eng.prof.ledger.snapshot()["totals"]
    stamps = [r["usage"] for r in records]
    ints_ok = all(
        totals[f] == sum(int(u[f]) for u in stamps)
        for f in ("steps", "chunks", "bytes_written"))
    lane_ok = abs(totals["lane_s"]
                  - sum(float(u["lane_s"]) for u in stamps)) < 1e-6
    return ints_ok and lane_ok and totals["requests"] == len(stamps)


def bit_identity(reqs, lanes, chunk, depth, tmp) -> bool:
    """npz outputs byte-identical with the observatory on vs off."""
    dirs = {}
    for prof in (False, True):
        d = Path(tmp) / f"d{depth}_{'on' if prof else 'off'}"
        _, ok, _, recs = run_mode(reqs, lanes, chunk, depth, prof,
                                  out_dir=d)
        if ok != len(reqs):
            return False
        dirs[prof] = (d, recs)
    d_off, recs_off = dirs[False]
    d_on, _ = dirs[True]
    return all(
        (d_off / f"{r['id']}.npz").read_bytes()
        == (d_on / f"{r['id']}.npz").read_bytes()
        for r in recs_off)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--lanes", type=int, default=8)
    ap.add_argument("--chunk", type=int, default=16)
    ap.add_argument("--depth", type=int, default=2)
    ap.add_argument("--bit-requests", type=int, default=12,
                    help="population for the per-depth npz bit-identity "
                         "check (writes 4 result sets)")
    ap.add_argument("--repeats", type=int, default=3,
                    help="runs per mode; best wall is compared")
    ap.add_argument("--out", default=str(Path(__file__).parent
                                         / "prof_overhead_lab.json"))
    args = ap.parse_args(argv)

    import tempfile

    import jax

    reqs = build_requests(args.requests)
    work = sum(cfg.points * cfg.ntime for cfg in reqs)
    tmp = Path(tempfile.mkdtemp(prefix="prof_lab_"))

    # one throwaway warm-up primes the persistent compile cache and the
    # process; round-robin the modes inside each repeat so slow drift on
    # a shared box hits both equally (trace_overhead_lab protocol)
    run_mode(reqs, args.lanes, args.chunk, args.depth, prof=False)
    modes = {}
    keep = {}
    for rep in range(args.repeats):
        for name, prof in (("off", False), ("on", True)):
            wall, ok, eng, records = run_mode(reqs, args.lanes, args.chunk,
                                              args.depth, prof)
            m = modes.setdefault(name, {"walls": [], "ok": ok})
            m["walls"].append(round(wall, 3))
            m["ok"] = min(m["ok"], ok)
            keep[name] = (eng, records)
    for m in modes.values():
        m["wall_s"] = min(m["walls"])
        m["points_per_s"] = round(work / m["wall_s"], 1)

    on_eng, on_records = keep["on"]
    off_eng, _ = keep["off"]
    overhead = modes["on"]["wall_s"] / modes["off"]["wall_s"] - 1.0
    reconciles = reconcile(on_eng, on_records)
    bit0 = bit_identity(build_requests(args.bit_requests), args.lanes,
                        args.chunk, 0, tmp)
    bit2 = bit_identity(build_requests(args.bit_requests), args.lanes,
                        args.chunk, 2, tmp)

    cost_model = on_eng.prof.cost.snapshot()
    mem = on_eng.prof.mem.snapshot()
    burn = on_eng.prof.burn.snapshot(time.perf_counter())
    rec = {
        "bench": "prof_overhead_lab",
        "platform": jax.default_backend(),
        "config": {"requests": args.requests, "lanes": args.lanes,
                   "chunk": args.chunk, "dispatch_depth": args.depth,
                   "repeats": args.repeats, "buckets": [32, 48],
                   "dtype": "float64", "mem_poll_every": 8,
                   "bit_requests": args.bit_requests},
        "work_cell_steps": work,
        "off": modes["off"], "on": modes["on"],
        "on_overhead_frac": round(overhead, 4),
        "on_within_2pct_of_off": overhead <= 0.02,
        "bit_identical_depth0": bit0,
        "bit_identical_depth2": bit2,
        "usage_reconciles": reconciles,
        # the "on" engine's learned state, for perfcheck's cross-checks
        "cost_model": cost_model,
        "mem": mem,
        "slo_burn": burn,
        "usage_totals": on_eng.prof.ledger.snapshot()["totals"],
        "cost_model_off_empty": not off_eng.prof.cost.snapshot(),
    }
    write_atomic(Path(args.out), rec)
    print(json.dumps(rec, indent=2))
    passed = (rec["on_within_2pct_of_off"] and bit0 and bit2
              and reconciles and rec["cost_model_off_empty"]
              and all(m["ok"] == args.requests for m in modes.values())
              and len(cost_model) > 0 and mem["samples"] > 0)
    print(f"prof_overhead_lab: {'OK' if passed else 'FAILED'} — "
          f"off {modes['off']['wall_s']:.3f}s vs full observatory "
          f"{modes['on']['wall_s']:.3f}s ({100 * overhead:+.2f}%; gate "
          f"<= +2%); bit-identical npz depth0={bit0} depth2={bit2}; "
          f"usage reconciles={reconciles}; {len(cost_model)} cost-model "
          f"key(s), {mem['samples']} mem sample(s)")
    return 0 if passed else 1


if __name__ == "__main__":
    sys.exit(main())
