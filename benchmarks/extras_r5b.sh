#!/bin/bash
# Round-5 final-session queue (Aug 2). The Aug-1 extras queue hit its
# HARD_END with every phase unrun (sweep_r5.log tail: parked on a down
# tunnel from 11:00). This session landed on a FRESH host: the per-user
# persistent compile cache is empty, so every phase below pays a cold
# Mosaic compile — budgets are sized for that (flagship k=16 live
# compile measured 471 s on the warm Aug-1 host; 1 shared core here).
#
# Order is value-per-chip-minute under cold-cache economics:
#   1. bench rehearsal — validates the capture path on this host AND
#      warms the exact 4096^2 cache entry the driver's end-of-round
#      official capture will hit.
#   2. row3 re-measure — the round-5 fuse-optimum change (auto k=16,
#      the measured 12%-faster program) has never updated the official
#      row; expected ~13% lift on the flagship distributed row.
#   3. calibrate acceptance — VERDICT r4 #6's bar: fixed-probe run
#      reproducing the shipped v5e constants (the 08:52 Aug-1 run was
#      pre-fix and dispatch-floor-poisoned; artifact deleted not shipped).
#   4. var16k A/Bs — the n2=16384 bf16/fma kernel variants: flagship
#      32768-scale compiles die in the remote-compile helper, 16384
#      answers the half-byte-traffic hypothesis with a measurement.
#   5. certification refreshes (chip_check is round-2 vintage).
#   6. overlap_ab retry LAST: its overlap row cold-compiles >1833 s on
#      a better host than this one, the no-ship decision is already
#      recorded on census + per-step evidence, and its first row write
#      REPLACES the artifact — only a full completion adds value.
set -u
export JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-${XDG_CACHE_HOME:-$HOME/.cache}/heat_tpu/jax}"
export PYTHONPATH="$(cd "$(dirname "$0")/.." && pwd):${PYTHONPATH:-}"
cd "$(dirname "$0")/.."

# Driver reclaims the chip for the official round-5 bench when the
# session's ~12 h expire (~03:40 Aug 3 UTC). 02:00 leaves margin plus
# room for a final warm bench rehearsal after the queue exits.
HARD_END=${HARD_END:-1785722400}  # 2026-08-03 02:00 UTC
DEADLINE=$(( $(date +%s) + ${BUDGET_S:-36000} ))
[ "$DEADLINE" -gt "$HARD_END" ] && DEADLINE=$HARD_END

probe() { timeout 120 python -c "import jax; assert jax.devices()" 2>/dev/null; }

wait_up() {
  until probe; do
    if [ "$(date +%s)" -ge "$DEADLINE" ]; then
      echo "=== budget exhausted waiting for tunnel at $(date)"; exit 1
    fi
    echo "tunnel down at $(date); waiting"
    sleep 300
  done
}

phase() {  # phase <name> <timeout_s> <cmd...>
  local name=$1 to=$2; shift 2
  if [ "$(date +%s)" -ge "$DEADLINE" ]; then
    echo "=== budget exhausted before $name"; exit 1
  fi
  wait_up
  local remaining=$(( DEADLINE - $(date +%s) ))
  if [ "$remaining" -lt 120 ]; then
    echo "=== budget exhausted before $name"; exit 1
  fi
  [ "$to" -gt "$remaining" ] && to=$remaining
  echo "=== $name start $(date) (timeout ${to}s)"
  if timeout "$to" "$@"; then
    echo "=== $name OK $(date)"
  else
    echo "=== $name FAILED rc=$? $(date)"
  fi
}

phase bench             900 python bench.py
phase row3_fuse16      3600 python benchmarks/run_all.py --only 3_sharded_16384sq_f32_mesh --row-timeout 3400
phase calibrate_fixed  3000 python -m heat_tpu.cli calibrate --out benchmarks/calibration_v5e.json
phase var16k_f32       3000 python benchmarks/kernel_lab.py bench2d_rolled_var f32 256,4096,16,128 --n2 16384
phase var16k_bf16native 3000 python benchmarks/kernel_lab.py bench2d_rolled_var bf16native 256,4096,16,128 --n2 16384
phase var16k_bf16fma   3000 python benchmarks/kernel_lab.py bench2d_rolled_var bf16fma 256,4096,16,128 --n2 16384
phase var16k_fma       3000 python benchmarks/kernel_lab.py bench2d_rolled_var fma 256,4096,16,128 --n2 16384
phase chip_check       2400 python benchmarks/chip_check.py
phase sharded3d_check  1800 python benchmarks/sharded3d_check.py
phase check2d_rolled   1800 python benchmarks/kernel_lab.py check2d_rolled
phase checkthin        1800 python benchmarks/kernel_lab.py checkthin
phase check3d_rolled   1800 python benchmarks/kernel_lab.py check3d_rolled
# warm-cache second bench rehearsal: proves the driver's capture will be
# fast on this host after a day of other compiles filled the cache
phase bench_warm        900 python bench.py
phase overlap_ab_retry 9000 python benchmarks/overlap_ab.py
echo "=== extras_r5b done at $(date)"
