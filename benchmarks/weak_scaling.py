"""Weak-scaling harness: constant work per device, growing mesh.

The BASELINE.md north star is >=90% weak-scaling efficiency at 32768^2 on a
v5p-32 pod. This harness measures efficiency = T(1 device) / T(N devices)
at constant per-device grid volume, sweeping mesh shapes. On real pods run
it as-is (devices come from the job); without hardware, ``--virtual N``
exercises the identical sharded code path on N virtual CPU devices —
correctness-grade, not perf-grade, like the reference's single-node
``mpirun -np N`` development mode (fortran/mpi+cuda/makefile:1-2).

Writes ``benchmarks/weak_scaling.json``.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--virtual", type=int, default=0,
                    help="use N virtual CPU devices (no hardware needed)")
    ap.add_argument("--local-n", type=int, default=0,
                    help="per-device grid side (default: 1024 real, 64 virtual)")
    ap.add_argument("--steps", type=int, default=0)
    ap.add_argument("--dtype", default="float32")
    args = ap.parse_args()

    import os

    if args.virtual:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.virtual}"
        )
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    from heat_tpu.backends import solve
    from heat_tpu.config import HeatConfig
    from heat_tpu.parallel.mesh import auto_mesh_shape

    ndev_total = len(jax.devices())
    local_n = args.local_n or (64 if args.virtual else 1024)
    steps = args.steps or (10 if args.virtual else 200)

    sweep = []
    d = 1
    while d <= ndev_total:
        sweep.append(d)
        d *= 2

    import math

    rows = []
    for ndev in sweep:
        mesh_shape = auto_mesh_shape(ndev, 2)
        # constant per-device volume: n^2 = local_n^2 * ndev, rounded to a
        # multiple of lcm(mesh) so shards divide evenly (non-square device
        # counts land within ~2% of local_n^2 per device)
        mult = math.lcm(*mesh_shape)
        n = max(mult, round(local_n * math.sqrt(ndev) / mult) * mult)
        for s in mesh_shape:
            assert n % s == 0
        cfg = HeatConfig(n=n, ntime=steps, dtype=args.dtype,
                         backend="sharded", mesh_shape=mesh_shape)
        # best-of-R: one-shot timings on a shared host are noise-dominated
        # (ADVICE r1: a loaded host produced 40x-off rows)
        per_step = min(
            solve(cfg, fetch=False, warm_exec=True).timing.per_step_s
            for _ in range(3))
        # weak efficiency compares seconds per (point/device): constant under
        # perfect scaling as the global grid grows with the mesh
        pts_per_dev = n * n / ndev
        t_norm = per_step / pts_per_dev  # seconds per (point/device)
        pts_per_s = n * n / per_step
        rows.append({
            "devices": ndev, "mesh": list(mesh_shape), "n": n,
            "per_step_s": per_step,
            "points_per_s_total": pts_per_s,
            "s_per_point_per_device": t_norm,
        })
        print(f"{ndev:3d} devices mesh {mesh_shape}: n={n:6d} "
              f"per-step {per_step * 1e6:9.1f} us  "
              f"{pts_per_s:.3e} pts/s")

    base = rows[0]["s_per_point_per_device"]
    for row in rows:
        row["weak_efficiency"] = base / row["s_per_point_per_device"]
        print(f"{row['devices']:3d} devices: weak efficiency "
              f"{100 * row['weak_efficiency']:.1f}%")

    conditions = {
        "mode": "virtual-cpu" if args.virtual else "hardware",
        "repeats": 3,
        "timing": "best-of-repeats, warm-executed, no final fetch",
        "note": (
            "virtual-cpu rows share ONE host's cores across all logical "
            "devices: weak efficiency cannot hold by construction and is "
            "correctness/shape-grade only, NOT predictive of pod scaling "
            "over ICI — see BASELINE.md's v5p-32 analytic projection for "
            "the hardware model"
        ) if args.virtual else "one device per chip; efficiency is real",
    }
    out = Path(__file__).parent / "weak_scaling.json"
    out.write_text(json.dumps({"ts": time.time(),
                               "platform": jax.default_backend(),
                               "conditions": conditions,
                               "rows": rows}, indent=2))
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
