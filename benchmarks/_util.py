"""Shared helpers for the benchmark scripts."""

from __future__ import annotations

import json
import os
from pathlib import Path


def write_atomic(out: Path, obj) -> None:
    """Temp-file + rename: a SIGKILL mid-write (row/phase timeout,
    external deadline) must not leave truncated JSON that poisons later
    merges or re-reads."""
    tmp = out.with_suffix(".tmp")
    tmp.write_text(json.dumps(obj, indent=2))
    os.replace(tmp, out)


def deep_fuse_proven(k: int = 32, budget_s: float = 600) -> bool:
    """Has a bisect artifact PROVEN the depth-``k`` flagship compile
    bounded? True once either the on-chip bisect or the chipless
    AOT-topology bisect (round 4: the whole k=8..32 curve measured flat
    at 5-9 s cold — the round-3 >25-min stall was the tunnel wedge)
    recorded a sub-budget compile. The ONE gate the chip labs
    (collective_overhead, overlap_ab) consult before queueing deep-fuse
    rows."""
    here = Path(__file__).parent
    for fname in ("compile_bisect.json", "compile_bisect_topology.json"):
        try:
            rows = json.loads((here / fname).read_text())["rows"]
            row = rows.get(str(k), {})
            if "compile_s" in row and row["compile_s"] < budget_s:
                return True
        except (OSError, json.JSONDecodeError, KeyError):
            continue
    return False
