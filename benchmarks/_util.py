"""Shared helpers for the benchmark scripts."""

from __future__ import annotations

import json
import os
from pathlib import Path


def write_atomic(out: Path, obj) -> None:
    """Temp-file + rename: a SIGKILL mid-write (row/phase timeout,
    external deadline) must not leave truncated JSON that poisons later
    merges or re-reads."""
    tmp = out.with_suffix(".tmp")
    tmp.write_text(json.dumps(obj, indent=2))
    os.replace(tmp, out)


def ensure_cache_env() -> str:
    """``heat_tpu.utils.cache.ensure_cache_env`` for supervisor processes
    that must stay jax-free (compile_bisect, run_all parents: importing
    the heat_tpu package pulls jax in, adding import cost and failure
    modes to the process whose job is to outlive wedged children). The
    module file is stdlib-only, so load it by PATH — one source of truth
    for the cache-dir derivation, no package ``__init__`` executed."""
    import importlib.util

    src = Path(__file__).parent.parent / "heat_tpu" / "utils" / "cache.py"
    spec = importlib.util.spec_from_file_location("_heat_cache_standalone",
                                                  src)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.ensure_cache_env()


def deep_fuse_proven(k: int = 32, budget_s: float = 1500) -> bool:
    """Has a bisect artifact PROVEN the depth-``k`` flagship compile
    bounded? True once either the on-chip bisect or the chipless
    AOT-topology bisect recorded a sub-budget compile of the REAL
    (Pallas local kernel) program. Round-4 measured truth
    (compile_bisect_topology.json, local_kernel pinned to pallas):
    16384-local k=8/16/32 cold-compile in 393/980/665 s — minutes,
    bounded, inside the 1500 s default — while the 8192-local thin-band
    k=32 family is a genuine >36-min wedge. The ONE gate the chip labs
    (collective_overhead, overlap_ab) consult before queueing deep-fuse
    rows."""
    here = Path(__file__).parent
    for fname in ("compile_bisect.json", "compile_bisect_topology.json"):
        try:
            rows = json.loads((here / fname).read_text())["rows"]
            row = rows.get(str(k), {})
            # rows must prove the PALLAS program: the retracted first
            # curves measured the XLA path (local_kernel unpinned) and
            # rows from that era carry no local_kernel field — reject them
            if (row.get("local_kernel") == "pallas"
                    and "compile_s" in row and row["compile_s"] < budget_s):
                return True
        except (OSError, json.JSONDecodeError, KeyError):
            continue
    return False


def custom_call_census(txt: str, call_marker: str, target_re: str) -> dict:
    """Census of custom calls in a compiler-IR text dump: total calls,
    Mosaic (TPU) calls, and distinct payloads after SSA-id normalization.

    ONE implementation for both the post-compile HLO census
    (compile_bisect: ``call_marker="custom-call"``) and the lowering-IR
    census (kernel_census: ``"stablehlo.custom_call"``) — the first cut
    existed twice and one copy silently recorded zeros when the printer
    syntax didn't match its regex (the round-5 k=8/16 bisect rows).
    When the target regex matches nothing but call lines exist, falls
    back to whole-line hashing and SAYS so (``census_method``) instead of
    recording a confident zero."""
    import hashlib
    import re

    lines = [ln for ln in txt.splitlines() if call_marker in ln]
    mosaic, method, matched_any = [], "target-match", False
    for ln in lines:
        m = re.search(target_re, ln)
        if m:
            matched_any = True
            if "tpu" in m.group(1):
                mosaic.append(m.group(0))
    if lines and not matched_any:
        # printer-syntax mismatch (NO line parsed): count via line
        # hashing and say so. A parse that succeeds but finds zero TPU
        # targets is a real mosaic_calls=0 (e.g. an xla-local-kernel
        # program with only host/sharding custom calls) — not a fallback.
        mosaic, method = list(lines), "line-hash-fallback"
    norm = [re.sub(r"%[\w#.\-]+", "%", c) for c in mosaic]
    return {"custom_calls": len(lines),
            "mosaic_calls": len(mosaic),
            "distinct_kernel_bodies": len(
                {hashlib.sha1(c.encode()).hexdigest() for c in norm}),
            "census_method": method}
