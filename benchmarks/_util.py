"""Shared helpers for the benchmark scripts."""

from __future__ import annotations

import json
import os
from pathlib import Path


def write_atomic(out: Path, obj) -> None:
    """Temp-file + rename: a SIGKILL mid-write (row/phase timeout,
    external deadline) must not leave truncated JSON that poisons later
    merges or re-reads."""
    tmp = out.with_suffix(".tmp")
    tmp.write_text(json.dumps(obj, indent=2))
    os.replace(tmp, out)
