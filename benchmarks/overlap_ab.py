"""A/B: exchange="overlap" vs "indep" on the attached chip (VERDICT r3 #5).

Times the real sharded solve (padded-carry path, two-point protocol) at
16384^2 f32 on the 1x1 mesh for each exchange mode and fuse depth. On a
single chip the ppermute degenerates (no wire), so what this measures is
the RESTRUCTURING cost/benefit: the interior/rim split's extra kernel
launches + band recompute vs the shorter critical path (interior no
longer waits on the exchange's select/DUS chain). The multi-chip overlap
win (collective latency hidden behind interior compute) is validated for
correctness by dryrun sub-check #12 and awaits multi-chip hardware for
measurement — this lab decides whether overlap SHIPS as a default
(ship only if it at least ties on one chip: VERDICT r3 #5 "ship only if
it wins").

Fuse depths: 16 (the guard's safe depth) always; 32 added when
compile_bisect.json has proven the deep compile bounded (same gate as
collective_overhead).

Run on chip: ``python benchmarks/overlap_ab.py``
CPU smoke: ``python benchmarks/overlap_ab.py --smoke``
Writes benchmarks/overlap_ab.json (atomic, incremental).
"""

from __future__ import annotations

import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))

from _util import write_atomic  # noqa: E402


def _ks() -> tuple[int, ...]:
    """Depths for the A/B. Default: fuse 16 only — the A/B question (does
    the interior/rim restructuring win?) is answerable at one depth, and
    the chipless compile check measured the flagship overlap program at
    1833 s cold (overlap_compile_check.json: 5 Mosaic kernels vs indep's
    1), so two depths' worth of cold compiles would blow the chip phase.
    ``--deep`` adds 32 when a Pallas-pinned bisect proved it bounded."""
    from _util import deep_fuse_proven

    if "--deep" in sys.argv and deep_fuse_proven(32):
        return (16, 32)
    return (16,)


# Round-5 note: exchange="overlap" is now the NARROW-DEPENDENCY form
# (backends/sharded.py::padded_multi_overlap): 3^nd-1 rim regions (9
# kernel calls in 2D vs the round-4 wide form's 5), each face band
# depending only on its own axis's ppermutes. Chipless flagship census:
# every collective flight window now holds 2-4 kernels
# (topology_schedule_flagship_f32.json, per-window [2,2,4,2], compile
# 1753 s at 8192-local 2x2 — inside the 2400 s guard budget). On the 1x1
# mesh HERE the extra region launches make the single-chip bar slightly
# harder; the ship rule stands: default flips only if overlap >= indep
# on this measurement.


def main():
    smoke = "--smoke" in sys.argv
    if smoke:
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    from heat_tpu.backends.sharded import solve as sharded_solve
    from heat_tpu.config import HeatConfig

    n = 512 if smoke else 16384
    steps = 32 if smoke else 512
    out = Path(__file__).parent / (
        "overlap_ab_smoke.json" if smoke else "overlap_ab.json")
    rec = {"ts": time.time(), "platform": jax.default_backend(),
           "n": n, "steps": steps, "rows": {}}

    for k in (4,) if smoke else _ks():
        for exchange in ("indep", "overlap"):
            cfg = HeatConfig(n=n, ntime=steps, dtype="float32",
                             backend="sharded", mesh_shape=(1, 1),
                             fuse_steps=k, exchange=exchange,
                             local_kernel="pallas")
            res = sharded_solve(cfg, fetch=False, warm_exec=True,
                                two_point_repeats=2)
            tp = (res.timing.points_per_s_two_point
                  or res.timing.points_per_s)
            rec["rows"][f"{exchange}_fuse{k}"] = {
                "points_per_s_two_point": tp,
                "solve_s": res.timing.solve_s,
                "compile_s": res.timing.compile_s,
            }
            print(f"{exchange:8s} fuse={k:2d}: {tp:.3e} pts/s "
                  f"(compile {res.timing.compile_s:.0f}s)", flush=True)
            write_atomic(out, rec)
        a = rec["rows"].get(f"indep_fuse{k}", {}).get(
            "points_per_s_two_point")
        b = rec["rows"].get(f"overlap_fuse{k}", {}).get(
            "points_per_s_two_point")
        if a and b:
            print(f"fuse={k}: overlap/indep = {b / a:.3f}", flush=True)
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
