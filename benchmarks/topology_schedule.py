"""Multi-chip schedule evidence via AOT topology compile — no chip needed.

The overlap exchange (backends/sharded.py::padded_multi_overlap) claims
XLA's latency-hiding scheduler will fly the halo collectives behind the
interior kernel. A 1x1 mesh can't show that (ppermute degenerates), and
multi-chip hardware isn't attached — but ``jax.experimental.topologies``
compiles a GENUINE multi-chip TPU executable on a CPU-only host (the
Mosaic + XLA:TPU compilers ship in libtpu and need no device), so the
claim is checkable from the compiled module's schedule order:

- async ``collective-permute-start``/``-done`` pairs (TPU lowering of the
  ppermutes), and
- how many Mosaic ``custom-call`` kernels are scheduled strictly inside
  a start->done flight window (>0 = kernel work overlaps the wire time).

Compiled-module text is in schedule order for TPU, so "inside the
window" is the scheduler's actual decision, not an inference. Measured
first run (v5e:2x4, 4x2 mesh, 1024^2, fuse 4):
``indep``: 1 kernel call, 0 in-window (strictly exchange-then-kernel);
``overlap``: 5 kernel calls (interior + 4 rim bands), interior IN-window.

Run (anywhere, tunnel up or down): ``python benchmarks/topology_schedule.py``
Writes benchmarks/topology_schedule.json (atomic, incremental).
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))

from _util import write_atomic  # noqa: E402


def schedule_census(txt: str) -> dict:
    """Per-flight-window schedule analysis of a compiled TPU module.

    Windows are matched exactly: each ``collective-permute-done`` names
    its ``-start`` as an operand, so every (start, done) pair is the real
    flight window even when windows interleave (start1 start2 done1
    done2 — the shape a latency-hiding schedule produces). A kernel
    counts as in-flight iff its line sits strictly inside SOME matched
    window (kernels between disjoint windows don't count)."""
    import re

    lines = txt.splitlines()
    # op DEFINITIONS only (`%name = ... collective-permute-start(...)`):
    # fusion lines that merely take a start/done as an operand must not
    # count as windows
    start_def = re.compile(r"\s*(\S+?)\s*=.*\scollective-permute-start\(")
    done_def = re.compile(r"\s*\S+\s*=.*\scollective-permute-done\((.*)")
    start_idx = {}
    for i, ln in enumerate(lines):
        m = start_def.match(ln)
        if m:
            start_idx[m.group(1).lstrip("%")] = i
    windows = []
    unmatched = 0
    for i, ln in enumerate(lines):
        m = done_def.match(ln)
        if not m:
            continue
        # Printer-robust operand parse (the custom_call_census lesson: a
        # regex tuned to one HLO printer silently records zeros on the
        # next). Newer printers annotate the operand with its full
        # tuple type — "done((f32[4,40]{1,0:T(4,128)S(1)}, ...)
        # %collective-permute-start.1)" — so a [^)]* capture eats layout
        # tokens, never the name. SSA names are the only %-prefixed
        # tokens on the line; older printers spell operands bare, so
        # fall back to the comma-split form when no %-token appears.
        ops = [o.lstrip("%") for o in re.findall(r"%[\w.\-#]+", m.group(1))]
        if not ops:
            ops = [o.strip() for o in m.group(1).rstrip(")").split(",")]
        s = next((start_idx[o] for o in ops if o in start_idx), None)
        if s is None:
            unmatched += 1
        else:
            windows.append((s, i))
    customs = [i for i, ln in enumerate(lines) if "custom-call" in ln]
    per_window = [sum(1 for c in customs if s < c < d) for s, d in windows]
    in_flight = len({c for c in customs
                     for s, d in windows if s < c < d})
    return {
        "async_pairs": len(windows),
        "unmatched_dones": unmatched,
        "custom_calls": len(customs),
        "kernels_in_flight": in_flight,
        "kernels_in_flight_per_window": per_window,
        "copies": txt.count(" copy("),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--topology", default="v5e:2x4",
                    help="TPU topology name for the AOT compile")
    ap.add_argument("--mesh", default="4x2")
    ap.add_argument("--n", type=int, default=1024)
    ap.add_argument("--fuse", type=int, default=4)
    ap.add_argument("--steps", type=int, default=16)
    ap.add_argument("--out", default="topology_schedule.json",
                    help="output filename (under benchmarks/) — flagship-"
                         "shape runs must not clobber the toy-scale row")
    ap.add_argument("--exchanges", default="seq,indep,overlap")
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")  # works chipless by design
    import jax.numpy as jnp
    from jax.experimental import topologies
    from jax.sharding import NamedSharding, PartitionSpec as P

    from heat_tpu.backends.sharded import make_padded_carry_machinery
    from heat_tpu.config import HeatConfig
    from heat_tpu.ops.pallas_stencil import force_compiled_kernels
    from heat_tpu.parallel.mesh import build_mesh  # noqa: F401 (parity cite)

    mesh_shape = tuple(int(v) for v in args.mesh.split("x"))
    if len(mesh_shape) not in (2, 3):
        # ndim follows the mesh rank below; a 1-axis mesh would need a
        # separate field-rank flag this census has never exercised (the
        # old code also built a rank-1 padded struct for it and crashed
        # later) — fail clearly at the argument instead
        ap.error(f"--mesh must be 2-D or 3-D (AxB or AxBxC), got "
                 f"{args.mesh!r}")
    topo = topologies.get_topology_desc(args.topology, "tpu")
    mesh = topologies.make_mesh(topo, mesh_shape,
                                tuple("xyz"[: len(mesh_shape)]))

    out = Path(__file__).parent / args.out
    rec = {"ts": time.time(), "topology": args.topology,
           "mesh": list(mesh_shape), "n": args.n, "fuse": args.fuse,
           "steps": args.steps, "rows": {}}

    with force_compiled_kernels():
        for ex in args.exchanges.split(","):
            # ndim follows the mesh rank (a 2x2x2 --mesh censuses the 3D
            # 26-region narrow overlap, 6 flight windows)
            cfg = HeatConfig(n=args.n, ndim=len(mesh_shape),
                             ntime=args.steps, dtype="float32",
                             backend="sharded", mesh_shape=mesh_shape,
                             fuse_steps=args.fuse, exchange=ex,
                             local_kernel="pallas")
            _, advance, _ = make_padded_carry_machinery(cfg, mesh)
            shape = tuple(args.n + 2 * args.fuse * s for s in mesh_shape)
            struct = jax.ShapeDtypeStruct(
                shape, jnp.float32,
                sharding=NamedSharding(mesh, P(*mesh.axis_names)))
            t0 = time.perf_counter()
            try:
                txt = advance.lower(struct, args.steps).compile().as_text()
            except Exception as e:  # record, keep going
                rec["rows"][ex] = {"error": f"{type(e).__name__}: {e}"}
                print(f"{ex:8s} FAILED: {type(e).__name__}: "
                      f"{str(e)[:160]}", flush=True)
                write_atomic(out, rec)
                continue
            row = schedule_census(txt)
            row["compile_s"] = time.perf_counter() - t0
            rec["rows"][ex] = row
            print(f"{ex:8s} pairs={row['async_pairs']} "
                  f"kernels={row['custom_calls']} "
                  f"in-flight={row['kernels_in_flight']} "
                  f"(per-window {row['kernels_in_flight_per_window']}) "
                  f"copies={row['copies']} "
                  f"[compile {row['compile_s']:.0f}s]", flush=True)
            write_atomic(out, rec)
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
