"""Do the multi-lane serve kernels compile under REAL Mosaic? (chipless)

CPU tier-1 exercises the lane kernel family only in Pallas interpret
mode, which accepts several things the real compiler rejects — this
repo's round of ISSUE-9 hardening hit three: blocked sub-array SMEM
outputs (Mosaic wants full-array SMEM blocks), ``is_finite`` (no Mosaic
lowering — spelled ``|x| < inf``), and sub-32-bit selects / misaligned
shrinking-slice rotates (bf16 ``where`` and the solo 3D kernel's
shrinking shapes both die). This check AOT-compiles the EXACT serve
chunk programs (``serve.engine.make_lane_advance(kernel="pallas")`` —
grid over lanes, SMEM per-lane scalars, fused countdown gate + health
reduction, both donation modes) against a single v5e chip through
``jax.experimental.topologies`` + ``force_compiled_kernels`` (the
Mosaic compiler ships with libtpu; no attached device needed), so a
kernel regression that only a real TPU would catch fails HERE, in a
CPU-world lab.

Writes benchmarks/lane_kernel_compile_check.json; nonzero exit if any
variant fails to compile.

    python benchmarks/lane_kernel_compile_check.py
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

from _util import write_atomic

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

# the serve-relevant matrix: default-bucket 2D at both lane dtypes, the
# rollback (donate=False) variant, a tail-sized program, and 3D (which
# chunks into multiple Mosaic passes)
VARIANTS = (
    ("2d_f32_ghost_L8_k16", 2, 256, "float32", "ghost", 8, 16, True),
    ("2d_bf16_edges_L8_k16", 2, 256, "bfloat16", "edges", 8, 16, True),
    ("2d_f32_edges_L8_k4_rollback", 2, 48, "float32", "edges", 8, 4, False),
    ("3d_f32_ghost_L4_k16", 3, 64, "float32", "ghost", 4, 16, True),
)


def main(argv=None) -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")  # chipless by construction
    import jax.numpy as jnp
    from jax.experimental import topologies
    from jax.sharding import SingleDeviceSharding

    from heat_tpu.backends.guard_probe import topology_spec
    from heat_tpu.ops.pallas_stencil import (force_compiled_kernels,
                                             lane_state_shape)
    from heat_tpu.ops.stencil import accum_dtype_for
    from heat_tpu.serve.engine import BucketKey, make_lane_advance
    from heat_tpu.utils import jnp_dtype

    out = Path(argv[0]) if argv else (Path(__file__).parent
                                      / "lane_kernel_compile_check.json")
    name, kwargs = topology_spec("v5e", 1)
    topo = topologies.get_topology_desc(name, "tpu", **kwargs)
    sh = SingleDeviceSharding(topo.devices[0])
    rec = {"ts": time.time(), "topology": name, "variants": {}}
    ok = True
    with force_compiled_kernels():
        for tag, ndim, bucket, dtype, bc, lanes, chunk, donate in VARIANTS:
            key = BucketKey(ndim, bucket, dtype, bc)
            slab = lane_state_shape(ndim, bucket, dtype)
            dt = jnp_dtype(dtype)
            acc = accum_dtype_for(dt)
            structs = (
                jax.ShapeDtypeStruct((lanes,) + slab, dt, sharding=sh),
                jax.ShapeDtypeStruct((lanes,), acc, sharding=sh),
                jax.ShapeDtypeStruct((lanes,), jnp.int32, sharding=sh),
                jax.ShapeDtypeStruct((lanes,), jnp.int32, sharding=sh),
            )
            adv = make_lane_advance(key, kernel="pallas", donate=donate)
            t0 = time.perf_counter()
            try:
                txt = adv.lower(*structs, chunk).compile().as_text()
                row = {"compiles": True,
                       "compile_s": round(time.perf_counter() - t0, 3),
                       "mosaic_calls": txt.count("tpu_custom_call")}
            except Exception as e:  # noqa: BLE001 — recorded verdict
                ok = False
                row = {"compiles": False,
                       "error": f"{type(e).__name__}: {str(e)[:300]}"}
            rec["variants"][tag] = row
            print(f"{tag:32s} "
                  + (f"OK {row['compile_s']:.1f}s "
                     f"({row['mosaic_calls']} mosaic call(s))"
                     if row["compiles"] else f"FAILED {row['error']}"),
                  flush=True)
    rec["all_compile"] = ok
    write_atomic(out, rec)
    print(json.dumps({"all_compile": ok, "out": str(out)}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
