"""Serving-engine throughput A/B: continuous batching vs sequential solos.

The serving claim (ISSUE 3 acceptance): draining 64 small mixed-size
requests through the batched engine beats running the same requests
sequentially — one ``backends.solve`` per request, the solo ``heat-tpu
run`` shape, where every invocation pays its own compile — by >= 3x
aggregate throughput on CPU, while compiling at most one stepping program
per (bucket, lane-count).

Aggregate throughput is request work over wall time: sum over requests of
``n^ndim * ntime`` divided by the drain's wall clock (compiles included on
BOTH sides — serving latency is what a tenant sees, not device-seconds).
The engine wins twice: same-bucket requests amortize ONE compile across
every request that flows through the lanes, and the vmapped stack turns
L tiny grids into one larger device program instead of L dispatch-bound
small ones.

A correctness spot-check rides along: a sample of engine results must be
bit-identical to their solo runs (the full matrix lives in
tests/test_serve.py; the bench re-checks a few so a perf artifact can
never certify a wrong-answer engine).

    JAX_PLATFORMS=cpu python benchmarks/serve_lab.py [--requests 64]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

from _util import write_atomic

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def build_requests(count: int):
    """Mixed-size request population: three grid sides, two diffusivities,
    varying step counts — the mix forces two buckets and mid-flight
    admissions without leaving the 'small request' regime."""
    from heat_tpu.config import HeatConfig

    sides = (24, 32, 48)
    reqs = []
    for i in range(count):
        n = sides[i % len(sides)]
        reqs.append(HeatConfig(
            n=n, ntime=96 + 16 * (i % 3), dtype="float64", bc="edges",
            ic=("hat", "hat_small")[i % 2], nu=(0.05, 0.1)[(i // 3) % 2]))
    return reqs


def run_engine(reqs, lanes: int, chunk: int):
    from heat_tpu.serve import Engine, ServeConfig

    eng = Engine(ServeConfig(lanes=lanes, chunk=chunk, buckets=(32, 48),
                             emit_records=False))
    t0 = time.perf_counter()
    ids = [eng.submit(cfg) for cfg in reqs]
    records = eng.results()
    wall = time.perf_counter() - t0
    by_id = {r["id"]: r for r in records}
    return wall, eng, [by_id[i] for i in ids]


def run_sequential(reqs):
    """The baseline a user has today: one solo solve per request, in
    order. Each call builds (and compiles) its own advance program —
    exactly what N separate ``heat-tpu run`` invocations in one process
    would do."""
    from heat_tpu.backends import solve

    t0 = time.perf_counter()
    fields = [solve(cfg).T for cfg in reqs]
    return time.perf_counter() - t0, fields


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--lanes", type=int, default=8)
    ap.add_argument("--chunk", type=int, default=16)
    ap.add_argument("--out", default=str(Path(__file__).parent
                                         / "serve_lab.json"))
    args = ap.parse_args()

    import numpy as np

    reqs = build_requests(args.requests)
    work = sum(cfg.points * cfg.ntime for cfg in reqs)

    seq_wall, seq_fields = run_sequential(reqs)
    eng_wall, eng, records = run_engine(reqs, args.lanes, args.chunk)

    ok = sum(r["status"] == "ok" for r in records)
    # correctness spot-check: first/middle/last request bit-identical
    sample = [0, len(reqs) // 2, len(reqs) - 1]
    bit_identical = all(
        np.array_equal(records[i]["T"], seq_fields[i]) for i in sample)

    combos = {(r["bucket"], min(args.lanes, args.requests))
              for r in records if r["bucket"] is not None}
    speedup = seq_wall / eng_wall if eng_wall > 0 else None
    rec = {
        "bench": "serve_lab",
        "config": {"requests": args.requests, "lanes": args.lanes,
                   "chunk": args.chunk, "buckets": [32, 48],
                   "sides": [24, 32, 48], "dtype": "float64"},
        "work_cell_steps": work,
        "sequential": {"wall_s": round(seq_wall, 3),
                       "points_per_s": round(work / seq_wall, 1)},
        "engine": {"wall_s": round(eng_wall, 3),
                   "points_per_s": round(work / eng_wall, 1),
                   "ok": ok,
                   "step_compiles": eng.step_compiles,
                   "compile_s": round(eng.compile_s, 3)},
        "aggregate_speedup": round(speedup, 2) if speedup else None,
        "one_compile_per_bucket_lane": eng.step_compiles <= len(combos),
        "bit_identical_sample": bit_identical,
    }
    write_atomic(Path(args.out), rec)
    print(json.dumps(rec, indent=2))
    passed = (ok == args.requests and bit_identical
              and speedup is not None and speedup >= 3.0
              and rec["one_compile_per_bucket_lane"])
    print(f"serve_lab: {'OK' if passed else 'FAILED'} — engine "
          f"{rec['engine']['points_per_s']:.3g} pts/s vs sequential "
          f"{rec['sequential']['points_per_s']:.3g} "
          f"({rec['aggregate_speedup']}x, {eng.step_compiles} stepping "
          f"compile(s) for {len(combos)} bucket/lane combo(s); "
          f"bit-identical sample={bit_identical})")
    return 0 if passed else 1


if __name__ == "__main__":
    sys.exit(main())
