"""Serving-engine throughput A/B: dispatch-ahead vs sync vs sequential.

Two claims, one harness:

- The serving claim (ISSUE 3): draining 64 small mixed-size requests
  through the batched engine beats running the same requests sequentially
  — one ``backends.solve`` per request, the solo ``heat-tpu run`` shape,
  where every invocation pays its own compile — by a wide aggregate
  throughput margin on CPU, while compiling at most one stepping program
  per (bucket, lane-tier).
- The dispatch-ahead claim (ISSUE 4): the pipelined hot loop
  (``dispatch_depth=2``: boundary D2H + bookkeeping overlap the chunks
  queued behind them, lane extraction in the writer thread, cross-bucket
  round-robin) beats the synchronous fallback (``dispatch_depth=0``, the
  PR-3 fence-every-chunk shape) on the SAME workload. The A/B also
  records the boundary-wait wall and an estimated device-idle fraction —
  on CPU the win is host-bookkeeping overlap; on a real accelerator the
  same numbers bound the latency hiding, which grows with chunk cost.

Aggregate throughput is request work over wall time: sum over requests of
``n^ndim * ntime`` divided by the drain's wall clock (compiles included on
BOTH sides — serving latency is what a tenant sees, not device-seconds).

A correctness spot-check rides along: a sample of engine results from
EACH mode must be bit-identical to their solo runs (the full matrix lives
in tests/test_serve.py; the bench re-checks a few so a perf artifact can
never certify a wrong-answer engine).

    JAX_PLATFORMS=cpu python benchmarks/serve_lab.py [--requests 64]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

from _util import write_atomic

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def build_requests(count: int, dtype: str = "float64"):
    """Mixed-size request population: three grid sides, two diffusivities,
    varying step counts — the mix forces two buckets and mid-flight
    admissions without leaving the 'small request' regime. This is the
    SAME population the PR-3 baseline json was committed with, so the
    aggregate-speedup numbers compare release to release. (Step counts
    are chunk multiples, so the tail-chunk path stays cold here — on a
    one-core CPU host a tail compile costs ~100 ms to save ~ms of masked
    compute; tests/test_serve.py exercises tails directly.)

    ``dtype`` keeps the population shared across labs: this lab's
    committed artifact stays f64, while serve_lane_kernel_lab.py runs the
    SAME shape/step mix at float32 (the Pallas lane kernels have no f64
    — no f64 on the TPU VPU — and a fallback-only A/B would measure
    nothing)."""
    from heat_tpu.config import HeatConfig

    sides = (24, 32, 48)
    reqs = []
    for i in range(count):
        n = sides[i % len(sides)]
        reqs.append(HeatConfig(
            n=n, ntime=96 + 16 * (i % 3), dtype=dtype, bc="edges",
            ic=("hat", "hat_small")[i % 2], nu=(0.05, 0.1)[(i // 3) % 2]))
    return reqs


def build_oversized(dtype: str = "float64"):
    """Two requests bigger than every bucket (ISSUE 10): on a
    single-device host they must be REJECTED (bucket-overflow with the
    mega hint — counted in this lab's ``rejected`` field, permanently
    regression-locking the rejection path); on a multi-device mesh they
    are served as sharded mega-lanes instead (the two-tier placement
    path, measured in depth by benchmarks/serve_mega_lab.py). Side 96
    divides evenly over every balanced mesh of 2/4/8 devices."""
    from heat_tpu.config import HeatConfig

    return [HeatConfig(n=96, ntime=32, dtype=dtype, bc="edges", ic="hat"),
            HeatConfig(n=96, ntime=16, dtype=dtype, bc="ghost",
                       ic="uniform")]


def run_engine(reqs, lanes: int, chunk: int, depth: int, oversized=()):
    from heat_tpu.serve import Engine, ServeConfig

    eng = Engine(ServeConfig(lanes=lanes, chunk=chunk, buckets=(32, 48),
                             dispatch_depth=depth, emit_records=False))
    t0 = time.perf_counter()
    ids = [eng.submit(cfg) for cfg in reqs]
    ids += [eng.submit(cfg) for cfg in oversized]
    records = eng.results()
    wall = time.perf_counter() - t0
    by_id = {r["id"]: r for r in records}
    return wall, eng, [by_id[i] for i in ids]


def run_sequential(reqs):
    """The baseline a user has today: one solo solve per request, in
    order. Each call builds (and compiles) its own advance program —
    exactly what N separate ``heat-tpu run`` invocations in one process
    would do."""
    from heat_tpu.backends import solve

    t0 = time.perf_counter()
    fields = [solve(cfg).T for cfg in reqs]
    return time.perf_counter() - t0, fields


def _engine_block(work, wall, eng, records, sample, seq_fields):
    import numpy as np

    bit_identical = all(
        np.array_equal(records[i]["T"], seq_fields[i]) for i in sample)
    s = eng.summary()
    return {
        "wall_s": round(wall, 3),
        "points_per_s": round(work / wall, 1),
        "ok": sum(r["status"] == "ok" for r in records),
        # schema gap fix (ISSUE 5): a regression that starts rejecting or
        # failing requests must show in the committed artifact, not hide
        # behind an unchanged throughput number
        "rejected": sum(r["status"] == "rejected" for r in records),
        "failed": sum(r["status"] not in ("ok", "rejected")
                      for r in records),
        "step_compiles": eng.step_compiles,
        "tail_compiles": eng.tail_compiles,
        "compile_s": round(eng.compile_s, 3),
        "dispatch_depth": s["dispatch_depth"],
        "chunks_dispatched": s["chunks_dispatched"],
        "tail_chunks": s["tail_chunks"],
        "boundary_waits": s["boundary_waits"],
        "boundary_wait_s": s["boundary_wait_s"],
        "device_idle_s_est": s["device_idle_s"],
        "device_idle_frac_est": round(s["device_idle_s"] / wall, 4),
        "bit_identical_sample": bit_identical,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--lanes", type=int, default=8)
    ap.add_argument("--chunk", type=int, default=16)
    ap.add_argument("--depth", type=int, default=2,
                    help="dispatch depth for the pipelined side of the A/B")
    ap.add_argument("--out", default=str(Path(__file__).parent
                                         / "serve_lab.json"))
    args = ap.parse_args(argv)

    reqs = build_requests(args.requests)
    # two permanently-oversized requests (ISSUE 10): single-device hosts
    # reject them (the count lands in the blocks' "rejected" field);
    # multi-device hosts serve them as mega-lanes
    big = build_oversized()
    work = sum(cfg.points * cfg.ntime for cfg in reqs)
    sample = sorted({0, len(reqs) // 2, len(reqs) - 1})

    seq_wall, seq_fields = run_sequential(reqs)
    # sync fallback first so the pipelined run cannot inherit a warmer
    # process (each engine still owns its compiles — separate caches)
    off_wall, off_eng, off_recs = run_engine(reqs, args.lanes, args.chunk,
                                             depth=0, oversized=big)
    eng_wall, eng, records = run_engine(reqs, args.lanes, args.chunk,
                                        depth=args.depth, oversized=big)

    engine_on = _engine_block(work, eng_wall, eng, records, sample,
                              seq_fields)
    engine_off = _engine_block(work, off_wall, off_eng, off_recs, sample,
                               seq_fields)
    import jax

    ndev = len(jax.devices())
    mega_capable = ndev > 1
    big_on = records[args.requests:]
    big_off = off_recs[args.requests:]
    combos = {(r["bucket"],) for r in records if r["bucket"] is not None}
    speedup = seq_wall / eng_wall if eng_wall > 0 else None
    ab = off_wall / eng_wall if eng_wall > 0 else None
    rec = {
        "bench": "serve_lab",
        "config": {"requests": args.requests, "lanes": args.lanes,
                   "chunk": args.chunk, "dispatch_depth": args.depth,
                   "buckets": [32, 48], "sides": [24, 32, 48],
                   "ntimes": [96, 112, 128], "dtype": "float64",
                   "oversized_sides": [c.n for c in big],
                   "devices": ndev},
        # the two-tier placement lock (ISSUE 10): oversized requests are
        # rejected (with the --mega-lanes hint) on a single device and
        # served as sharded mega-lanes on a mesh — either way, visibly
        "oversized": {
            "count": len(big),
            "expected": "mega" if mega_capable else "rejected",
            "statuses": sorted(r["status"] for r in big_on + big_off),
            "hint_present": all("hint" in r for r in big_on + big_off
                                if r["status"] == "rejected"),
        },
        "work_cell_steps": work,
        "sequential": {"wall_s": round(seq_wall, 3),
                       "points_per_s": round(work / seq_wall, 1)},
        "engine": engine_on,
        "engine_sync": engine_off,
        "aggregate_speedup": round(speedup, 2) if speedup else None,
        "dispatch_ab_speedup": round(ab, 2) if ab else None,
        "one_compile_per_bucket_lane_tier":
            eng.step_compiles <= len(combos)
            and eng.tail_compiles <= len(combos),
        "bit_identical_sample": (engine_on["bit_identical_sample"]
                                 and engine_off["bit_identical_sample"]),
    }
    write_atomic(Path(args.out), rec)
    print(json.dumps(rec, indent=2))
    exp_ok = args.requests + (len(big) if mega_capable else 0)
    exp_rej = 0 if mega_capable else len(big)
    big_ok = (all(r["status"] == "ok" for r in big_on + big_off)
              if mega_capable else
              all(r["status"] == "rejected" and "hint" in r
                  for r in big_on + big_off))
    passed = (engine_on["ok"] == exp_ok
              and engine_off["ok"] == exp_ok
              and engine_on["rejected"] == engine_off["rejected"] == exp_rej
              and engine_on["failed"] == engine_off["failed"] == 0
              and big_ok
              and rec["bit_identical_sample"]
              and speedup is not None and speedup >= 3.0
              and ab is not None
              and rec["one_compile_per_bucket_lane_tier"])
    print(f"serve_lab: {'OK' if passed else 'FAILED'} — dispatch-ahead "
          f"{engine_on['points_per_s']:.3g} pts/s vs sync "
          f"{engine_off['points_per_s']:.3g} ({rec['dispatch_ab_speedup']}x "
          f"A/B) vs sequential {rec['sequential']['points_per_s']:.3g} "
          f"({rec['aggregate_speedup']}x aggregate; {eng.step_compiles} "
          f"stepping + {eng.tail_compiles} tail compile(s); boundary wait "
          f"{engine_on['boundary_wait_s']:.3f}s vs "
          f"{engine_off['boundary_wait_s']:.3f}s sync; bit-identical "
          f"sample={rec['bit_identical_sample']})")
    return 0 if passed else 1


if __name__ == "__main__":
    sys.exit(main())
