"""Why did the bf16 kernel A/B variants fail on-chip? (round 5)

The sweep's `bench2d_rolled_var bf16native|bf16fma` rows died with
`MosaicError: INTERNAL: .../remote_compile: HTTP 500: tpu_compile_helper
subprocess exit code 1` — an opaque tunnel-helper crash that cannot
distinguish "Mosaic rejects the kernel" from "the helper fell over".
This lab answers what it can chiplessly: compile the EXACT lab program
(same tile, same fori_loop wrapper) through the local AOT topology path
(`guard_probe.topology_spec` single-chip spelling +
`force_compiled_kernels`), where failures come back as real XLA errors
with numbers in them, at TWO scales:

- n2=4096: all four variants COMPILE — Mosaic accepts the bf16-native
  kernels; the on-chip failure is not a kernel rejection.
- n2=32768 (flagship): ALL variants RESOURCE_EXHAUSTED at an identical
  "program 18.00G" — including `f32`, which the same sweep compiled AND
  ran on the real chip at 1.689e11 pts/s minutes earlier. The flagship
  rows of this harness are therefore an AOT-path accounting artifact
  (unfaithful to the committed-buffer on-chip path) and say NOTHING
  about the bf16 variants specifically; they are recorded with that
  label so nobody quotes them as evidence.

Net: the bf16native/bf16fma flagship-scale failure remains attributable
to the axon remote-compile helper or flagship-scale resources, not to
Mosaic rejecting the kernel; the measurable A/B moves to n2=16384
on-chip (`kernel_lab.py bench2d_rolled_var --n2 16384 ...`).

Writes benchmarks/bf16_variant_compile_check.json.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    import jax
    import jax.numpy as jnp

    jax.config.update("jax_platforms", "cpu")

    from jax.experimental import topologies
    from jax.sharding import NamedSharding, PartitionSpec as P

    from heat_tpu.backends.guard_probe import topology_spec
    from heat_tpu.ops.pallas_stencil import force_compiled_kernels
    from kernel_lab import _round_up, pallas_2d_coltiled_rolled

    name, kw = topology_spec("v5e", 1)
    topo = topologies.get_topology_desc(name, "tpu", **kw)
    mesh = topologies.make_mesh(topo, (1,), ("d",))
    sh = NamedSharding(mesh, P())

    R, C, kr, kc = 256, 4096, 16, 128  # the sweep's A/B tile
    k = min(kr, kc)
    steps = 96

    rec: dict = {"ts": time.time(),
                 "tile": {"R": R, "C": C, "kr": kr, "kc": kc},
                 "topology": name, "scales": {}}

    for n2 in (4096, 32768):
        shape = (_round_up(n2, R), _round_up(n2, C))
        x = jax.ShapeDtypeStruct(shape, jnp.bfloat16, sharding=sh)
        rows: dict = {}
        for variant in ("f32", "fma", "bf16native", "bf16fma"):

            def run(Tp, variant=variant, n2=n2):
                def body(i, t):
                    return pallas_2d_coltiled_rolled(
                        t, r=0.25, ksteps=k, R=R, C=C, kr=kr, kc=kc,
                        logical=(n2, n2), variant=variant)

                return jax.lax.fori_loop(0, steps // k, body, Tp)

            t0 = time.perf_counter()
            try:
                with force_compiled_kernels():
                    compiled = jax.jit(run).lower(x).compile()
                mem = compiled.memory_analysis()
                row = {"compiles": True,
                       "compile_s": time.perf_counter() - t0,
                       "temp_bytes": getattr(mem, "temp_size_in_bytes",
                                             None)}
            except Exception as e:
                row = {"compiles": False,
                       "compile_s": time.perf_counter() - t0,
                       "error_type": type(e).__name__,
                       "error": str(e)[:600]}
            rows[variant] = row
            print(n2, variant, json.dumps(row)[:180], flush=True)
        scale_rec: dict = {"variants": rows}
        if n2 == 32768 and not rows["f32"]["compiles"]:
            scale_rec["unfaithful"] = (
                "f32 control OOMs here yet compiled+ran on the real chip "
                "in the same sweep — these flagship AOT rows are a "
                "harness artifact, NOT evidence about any variant")
        rec["scales"][str(n2)] = scale_rec

    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "bf16_variant_compile_check.json")
    with open(out + ".tmp", "w") as f:
        json.dump(rec, f, indent=2)
    os.replace(out + ".tmp", out)
    print("wrote", out)


if __name__ == "__main__":
    main()
