#!/bin/bash
# Outage recovery: drain the chip work queue across tunnel flaps.
#
# Round-3 lesson: the tunnel doesn't just go down and come back — it
# FLAPS (12 min up at 03:46, wedged again by 03:58). A linear sweep
# burns each phase's full timeout against a dead tunnel. So: probe
# before every phase; when the tunnel is down, park in the wait loop
# instead of consuming the queue. Phases write their artifacts
# incrementally+atomically (collective_overhead.py, run_all.py), so a
# mid-phase wedge costs only the un-flushed remainder.
#
# Queue order is value-per-minute: the bench rehearsal and the flagship
# kernel A/Bs (VERDICT #2) first, correctness certification and the
# long full-table refresh last.
set -u
# per-user persistent cache default (ADVICE r4); user env honored. Keep
# the XDG fallback in sync with heat_tpu/utils/cache.py so launcher and
# direct invocations share ONE warm cache.
export JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-${XDG_CACHE_HOME:-$HOME/.cache}/heat_tpu/jax}"
export PYTHONPATH="$(cd "$(dirname "$0")/.." && pwd):${PYTHONPATH:-}"
cd "$(dirname "$0")/.."

DEADLINE=$(( $(date +%s) + ${BUDGET_S:-36000} ))

probe() { timeout 120 python -c "import jax; assert jax.devices()" 2>/dev/null; }

wait_up() {
  until probe; do
    if [ "$(date +%s)" -ge "$DEADLINE" ]; then
      echo "=== budget exhausted waiting for tunnel at $(date)"; exit 1
    fi
    echo "tunnel down at $(date); waiting"
    sleep 300
  done
}

phase() {  # phase <name> <timeout_s> <cmd...>
  local name=$1 to=$2; shift 2
  if [ "$(date +%s)" -ge "$DEADLINE" ]; then
    echo "=== budget exhausted before $name"; exit 1
  fi
  wait_up
  # clamp to the remaining budget: a phase must never run past the
  # deadline — the driver's end-of-round bench needs the chip free
  local remaining=$(( DEADLINE - $(date +%s) ))
  if [ "$remaining" -lt 120 ]; then
    echo "=== budget exhausted before $name"; exit 1
  fi
  [ "$to" -gt "$remaining" ] && to=$remaining
  echo "=== $name start $(date) (timeout ${to}s)"
  if timeout "$to" "$@"; then
    echo "=== $name OK $(date)"
  else
    echo "=== $name FAILED rc=$? $(date)"
  fi
}

# Round-4 priority order (VERDICT r3 #1): (a) bench rc=0, (b) the full
# results.json refresh with two-point fields, (c) the config-5 kernel
# A/Bs, (d) exchange census + fuse-cost fit points + overlap A/B, then
# certification. Phase budgets account for the measured cold Mosaic
# compile times (compile_bisect_topology*.json: flagship kernels are
# 6-16 MINUTES cold; the persistent compile cache amortizes repeats) —
# the round-3 "wedge" was mostly this. The on-chip k=32 bisect row
# (tunnel-side compile overhead closure) runs late: the local AOT
# topology curve already answered the cliff question.
phase bench                 700 python bench.py
phase run_all             14000 python benchmarks/run_all.py --row-timeout 2500
# VERDICT r4 #6 acceptance: on-chip calibrate must reproduce the shipped
# v5e table within tolerance (the vs_table ratios in the artifact)
phase calibrate            2400 python -m heat_tpu.cli calibrate --out benchmarks/calibration_v5e.json
phase fma_ab               2400 python benchmarks/kernel_lab.py bench2d_rolled_var fma 256,4096,16,128
phase bf16native_ab        2400 python benchmarks/kernel_lab.py bench2d_rolled_var bf16native 256,4096,16,128
phase bf16fma_ab           2400 python benchmarks/kernel_lab.py bench2d_rolled_var bf16fma 256,4096,16,128
phase f32_rolled_base      2400 python benchmarks/kernel_lab.py bench2d_rolled_var f32 256,4096,16,128
phase collective_overhead  3600 python benchmarks/collective_overhead.py
phase exchange_lab         2400 python benchmarks/exchange_lab.py
phase overlap_ab           5400 python benchmarks/overlap_ab.py
phase sharded3d_check      1800 python benchmarks/sharded3d_check.py
phase check2d_rolled       1800 python benchmarks/kernel_lab.py check2d_rolled
phase checkthin            1800 python benchmarks/kernel_lab.py checkthin
phase check3d_rolled       1800 python benchmarks/kernel_lab.py check3d_rolled
phase thin_fma_ab          2400 python benchmarks/kernel_lab.py benchthin 4096 float32 rolled,256,16 rolledfma,256,16 --steps 2048
phase 3d_f32_ab            2400 python benchmarks/kernel_lab.py bench3d_rolled_var f32 64,64,8,8
phase 3d_fma_ab            2400 python benchmarks/kernel_lab.py bench3d_rolled_var fma 64,64,8,8
phase chip_check           2400 python benchmarks/chip_check.py
phase compile_bisect_32    2000 python benchmarks/compile_bisect.py --ks 32 --timeout 1800
echo "=== sweep done at $(date)"
