#!/bin/bash
# Outage recovery: probe the tunneled TPU every 5 min; on recovery run
# the on-chip certification + the full benchmark suite. Used during the
# round-2 6+ hour tunnel outage (see TROUBLESHOOTING.md "Outages") so
# the chip work queue drains the moment the tunnel returns, with results
# flushed to benchmarks/*.json as they land.
set -u
export JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-/tmp/jax_cache}"
export PYTHONPATH="$(cd "$(dirname "$0")/.." && pwd):${PYTHONPATH:-}"
cd "$(dirname "$0")/.."
for i in $(seq 1 "${PROBES:-48}"); do
  if timeout 120 python -c "import jax; assert jax.devices()" 2>/dev/null; then
    echo "=== TPU back at $(date); starting round-3 sweep"
    echo "=== bench (driver artifact dry run)"
    timeout 700 python bench.py
    echo "=== collective_overhead (weak-scaling anchor)"
    timeout 1800 python benchmarks/collective_overhead.py
    echo "=== kernel variant checks"
    timeout 1800 python benchmarks/kernel_lab.py check2d_rolled
    timeout 1800 python benchmarks/kernel_lab.py checkthin
    timeout 1800 python benchmarks/kernel_lab.py check3d_rolled
    echo "=== fma A/B at the shipped tile"
    timeout 2400 python benchmarks/kernel_lab.py bench2d_rolled_var fma 256,4096,16,128
    echo "=== bf16native A/B"
    timeout 2400 python benchmarks/kernel_lab.py bench2d_rolled_var bf16native 256,4096,16,128
    echo "=== bf16fma A/B"
    timeout 2400 python benchmarks/kernel_lab.py bench2d_rolled_var bf16fma 256,4096,16,128
    echo "=== thin fma A/B at the 4096^2 headline tile"
    timeout 2400 python benchmarks/kernel_lab.py benchthin 4096 float32 rolled,256,16 rolledfma,256,16
    echo "=== 3D fma A/B at the shipped 512^3 plan"
    timeout 2400 python benchmarks/kernel_lab.py bench3d_rolled_var f32 64,64,8,8
    timeout 2400 python benchmarks/kernel_lab.py bench3d_rolled_var fma 64,64,8,8
    echo "=== chip_check"; timeout 2400 python benchmarks/chip_check.py
    echo "=== run_all";   timeout 5400 python benchmarks/run_all.py
    echo "=== sweep done at $(date)"
    exit 0
  fi
  echo "probe $i: still down at $(date)"
  sleep 300
done
echo "gave up at $(date)"
exit 1
