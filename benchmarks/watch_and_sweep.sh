#!/bin/bash
# Outage recovery: probe the tunneled TPU every 5 min; on recovery run
# the on-chip certification + the full benchmark suite. Used during the
# round-2 6+ hour tunnel outage (see TROUBLESHOOTING.md "Outages") so
# the chip work queue drains the moment the tunnel returns, with results
# flushed to benchmarks/*.json as they land.
set -u
export JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-/tmp/jax_cache}"
export PYTHONPATH="$(cd "$(dirname "$0")/.." && pwd):${PYTHONPATH:-}"
cd "$(dirname "$0")/.."
for i in $(seq 1 "${PROBES:-48}"); do
  if timeout 120 python -c "import jax; assert jax.devices()" 2>/dev/null; then
    echo "=== TPU back at $(date); starting sweep"
    echo "=== chip_check"; timeout 2400 python benchmarks/chip_check.py
    echo "=== run_all";   timeout 3600 python benchmarks/run_all.py
    echo "=== sweep done at $(date)"
    exit 0
  fi
  echo "probe $i: still down at $(date)"
  sleep 300
done
echo "gave up at $(date)"
exit 1
