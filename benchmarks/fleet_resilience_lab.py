"""Fleet resilience lab: flap, stream-cut, hedge and deadline drills.

Four drills over in-process ``Engine``+``Gateway`` backends behind the
fleet router (ISSUE 20) — in-process because every drill measures the
ROUTER's resilience machinery (breakers, re-drive, hedging, deadline
shedding), not process spin-up, and in-process backends make the chaos
timing deterministic enough to gate on:

- **Flap drill**: a 4-backend fleet drains the same sink-slow wave
  twice — healthy, then with ``backend-flap`` chaos square-waving one
  backend. Gates: availability stays >= 0.99 (zero rows lost to the
  flap), tail latency degrades no worse than the capacity loss
  (p99 ratio <= 1.5 ~ the 4/3 theoretical + margin), the outputs stay
  bit-identical, and the breaker's transition cooldown keeps the steal
  loop quiet while the incident is live (no flap-induced steal thrash).
- **Stream-cut drill**: ``stream-cut@N`` kills a relay socket
  mid-stream while the backend stays healthy; the bounded re-drive
  path must deliver every row exactly once (zero lost, zero duplicate).
- **Hedge drill**: one backend is pre-loaded OUTSIDE the router so the
  placement view is stale; an interactive row stalls there and must be
  hedged onto the idle backend, win, and return bytes identical to the
  solo solve.
- **Deadline drill**: rows with spent edge-minted budgets are shed
  with structured ``deadline`` records and zero backend dispatch
  (never billed a device step); live-budget rows ride the propagated
  ``X-Deadline-Ms`` header end-to-end and complete.

    JAX_PLATFORMS=cpu python benchmarks/fleet_resilience_lab.py
"""

from __future__ import annotations

import argparse
import http.client
import json
import sys
import time
from pathlib import Path

from _util import write_atomic

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

SINK_MS = 120


def make_backend(workdir: Path, name: str, **kw):
    from heat_tpu.serve import Engine, ServeConfig
    from heat_tpu.serve.gateway import Gateway

    d = workdir / name
    d.mkdir(parents=True, exist_ok=True)
    kw.setdefault("emit_records", False)
    kw.setdefault("lanes", 2)
    kw.setdefault("chunk", 8)
    kw.setdefault("buckets", (32,))
    kw.setdefault("out_dir", str(d))
    kw.setdefault("engine_ckpt_interval", 4)
    kw.setdefault("engine_ckpt_dir", str(d / "ckpt"))
    return Gateway(Engine(ServeConfig(**kw)), "127.0.0.1", 0).start()


def make_router(gws, **fcfg_kw):
    from heat_tpu.fleet.registry import BackendRegistry, parse_backends
    from heat_tpu.fleet.router import FleetConfig, Router

    spec = ",".join(f"b{i}={gw.address}" for i, gw in enumerate(gws))
    fcfg_kw.setdefault("health_interval_s", 0.2)
    rt = Router(BackendRegistry(parse_backends(spec)), "127.0.0.1", 0,
                FleetConfig(**fcfg_kw))
    return rt.start()


def build_lines(count: int, prefix: str, sink_ms: int = SINK_MS):
    lines = []
    for i in range(count):
        lines.append({"id": f"{prefix}-r{i}", "n": 24,
                      "ntime": 48 + 16 * (i % 2), "dtype": "float64",
                      "ic": "hat", "bc": "edges", "nu": 0.05})
        if sink_ms:
            lines[-1]["inject"] = f"sink-slow:ms={sink_ms}"
    return lines


def post_stream(host, port, lines, query="", headers=(),
                timeout: float = 600.0):
    """One streaming POST; returns (records, per-record latencies_s)."""
    body = "".join(json.dumps(ln) + "\n" for ln in lines).encode()
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    t0 = time.perf_counter()
    conn.request("POST", f"/v1/solve{query}", body=body,
                 headers=dict(headers))
    resp = conn.getresponse()
    recs, lats = [], []
    while True:
        raw = resp.readline()
        if not raw:
            break
        raw = raw.strip()
        if raw:
            recs.append(json.loads(raw))
            lats.append(time.perf_counter() - t0)
    conn.close()
    return recs, lats


def p99(lats):
    if not lats:
        return None
    s = sorted(lats)
    return s[min(len(s) - 1, int(0.99 * len(s)))]


def solo_T(ln):
    from heat_tpu.backends import solve
    from heat_tpu.config import HeatConfig

    kw = {k: v for k, v in ln.items()
          if k not in ("id", "inject", "tenant", "class", "deadline_ms")}
    return solve(HeatConfig(**kw)).T


def check_bits(gws, lines, sample_idx, suffix=""):
    """npz byte-identity vs solo in-process solves for a sample."""
    import numpy as np

    for i in sample_idx:
        rid = lines[i]["id"] + suffix
        paths = [Path(gw.engine.scfg.out_dir) / f"{rid}.npz" for gw in gws
                 if (Path(gw.engine.scfg.out_dir) / f"{rid}.npz").exists()]
        if len(paths) != 1:
            return False
        with np.load(paths[0]) as z:
            if not np.array_equal(z["T"], solo_T(lines[i])):
                return False
    return True


def close_all(rt, gws):
    rt.close()
    for gw in gws:
        try:
            gw.request_drain()
            gw.wait_drained(120)
        finally:
            gw.close()


def flap_drill(workdir: Path, requests: int, sink_ms: int):
    """Healthy wave vs flapping-backend wave over the same 4 backends."""
    gws = [make_backend(workdir, f"fl{i}") for i in range(4)]
    sample = sorted({0, requests // 2, requests - 1})
    try:
        # pay every backend's bucket compile before any timed wave so
        # the p99 ratio compares serving latency, not cold compiles
        for i, gw in enumerate(gws):
            host, _, port = gw.address.rpartition(":")
            post_stream(host, int(port),
                        build_lines(2, f"warm{i}", sink_ms=0))
        # healthy baseline
        rt = make_router(gws)
        try:
            time.sleep(0.6)
            lines = build_lines(requests, "base", sink_ms)
            recs, lats = post_stream(rt.host, rt.port, lines)
            base_ok = sum(r.get("status") == "ok" for r in recs)
            base_p99 = p99(lats)
        finally:
            rt.close()
        assert base_ok == requests, f"healthy wave lost rows: {base_ok}"

        # the same wave with b1 square-waved down: the breaker opens,
        # placement routes around it, the canary re-admits it, and the
        # transition cooldown keeps the steal loop out of the incident
        rt = make_router(gws, inject="backend-flap:period=500:backend=b1",
                         breaker_cooldown_s=0.5,
                         steal_threshold_s=0.001, steal_cooldown_s=3.0,
                         flightrec_dir=str(workdir / "flightrec"))
        try:
            time.sleep(0.8)   # first tick stamps the flap t0 -> down edge
            lines = build_lines(requests, "flap", sink_ms)
            recs, lats = post_stream(rt.host, rt.port, lines)
            flap_ok = sum(r.get("status") == "ok" for r in recs)
            flap_p99 = p99(lats)
            snap = rt.snapshot()
        finally:
            rt.close()
        transitions = sum(b["transitions"]
                          for b in snap["router"]["breakers"].values())
        return {
            "requests": requests,
            "healthy_p99_s": round(base_p99, 3),
            "flap_p99_s": round(flap_p99, 3),
            "p99_ratio": round(flap_p99 / base_p99, 3),
            "availability": round(flap_ok / requests, 4),
            "breaker_transitions": transitions,
            "steals": len(snap["router"]["steals"]),
            "retries": snap["router"]["retries"],
            "bit_identical": check_bits(gws, lines, sample),
            "steals_suppressed": (len(snap["router"]["steals"]) == 0
                                  and transitions >= 1),
        }
    finally:
        for gw in gws:
            try:
                gw.request_drain()
                gw.wait_drained(120)
            finally:
                gw.close()


def cut_drill(workdir: Path, requests: int, sink_ms: int):
    """Mid-stream relay break against a live backend: bounded re-drive
    delivers every admitted row exactly once."""
    gws = [make_backend(workdir, f"ct{i}") for i in range(2)]
    rt = make_router(gws, inject="stream-cut@3:backend=b0",
                     cut_redrive_wait_s=30.0)
    try:
        time.sleep(0.6)
        lines = build_lines(requests, "cut", sink_ms)
        recs, _ = post_stream(rt.host, rt.port, lines)
        snap = rt.snapshot()
        ids = [r.get("id") for r in recs]
        return {
            "requests": requests,
            "records": len(recs),
            "ok": sum(r.get("status") == "ok" for r in recs),
            "stream_cuts": snap["router"]["stream_cuts"],
            "zero_lost": (sorted(ids) == sorted(ln["id"] for ln in lines)
                          and all(r.get("status") == "ok" for r in recs)),
            "zero_duplicates": (snap["router"]["duplicates"] == 0
                                and len(ids) == len(set(ids))),
        }
    finally:
        close_all(rt, gws)


def hedge_drill(workdir: Path, sink_ms: int):
    """Stale-predictor tail: the interactive row stalls on a pre-loaded
    backend and must win on the hedge instead."""
    gws = [make_backend(workdir, f"hg{i}") for i in range(2)]
    # round-robin's rotation starts at the second backend, so pre-load
    # it OUTSIDE the router (the stale-view setup hedging exists for)
    rt = make_router(gws, policy="round-robin",
                     health_interval_s=0.15,
                     hedge_factor=0.05, hedge_floor_s=0.4)
    try:
        time.sleep(0.5)
        host, _, port = gws[1].address.rpartition(":")
        heavy = build_lines(5, "heavy", sink_ms=5 * sink_ms)
        body = "".join(json.dumps(ln) + "\n" for ln in heavy).encode()
        conn = http.client.HTTPConnection(host, int(port), timeout=60)
        conn.request("POST", "/v1/solve?wait=0", body=body)
        assert conn.getresponse().status == 202
        conn.close()

        tail = [{"id": "hedge-r0", "n": 24, "ntime": 48,
                 "dtype": "float64", "ic": "hat", "bc": "edges",
                 "nu": 0.05, "tenant": "acme", "class": "interactive"}]
        t0 = time.perf_counter()
        recs, _ = post_stream(rt.host, rt.port, tail)
        wall = time.perf_counter() - t0
        snap = rt.snapshot()
        rec = recs[-1]
        # the duplicate's bytes are the solo solve's bytes wherever the
        # twin landed (id suffix ``~hedge`` on the hedge backend)
        bit = (check_bits(gws, tail, [0], suffix="~hedge")
               or check_bits(gws, tail, [0]))
        return {
            "stall_depth_s": round(5 * 5 * sink_ms / 1000.0, 2),
            "hedged_wall_s": round(wall, 3),
            "status": rec.get("status"),
            "hedged_record": bool(rec.get("hedged")),
            "fired": snap["router"]["hedges"]["fired"],
            "won": snap["router"]["hedges"]["won"],
            "cancelled": snap["router"]["hedges"]["cancelled"],
            "bit_identical": bool(bit and rec.get("status") == "ok"),
        }
    finally:
        close_all(rt, gws)


def deadline_drill(workdir: Path, expired: int, live: int):
    """Spent budgets shed at the edge with zero dispatch + zero billing;
    live budgets propagate and complete."""
    gws = [make_backend(workdir, f"dl{i}") for i in range(2)]
    rt = make_router(gws)
    try:
        time.sleep(0.6)
        lines = []
        for i in range(expired):
            lines.append({"id": f"dead-r{i}", "n": 24, "ntime": 48,
                          "dtype": "float64", "tenant": "doomed",
                          "deadline_ms": 0.001})
        for i in range(live):
            lines.append({"id": f"live-r{i}", "n": 24, "ntime": 48,
                          "dtype": "float64", "deadline_ms": 120000})
        recs, _ = post_stream(rt.host, rt.port, lines)
        by = {r["id"]: r for r in recs}
        shed = [r for r in by.values() if r.get("status") == "deadline"]
        served = [r for r in by.values() if r.get("status") == "ok"]
        snap = rt.snapshot()
        usage = rt.fleet_usage()
        return {
            "expired": expired, "live": live,
            "shed_records": len(shed),
            "served_records": len(served),
            "router_deadline_shed": snap["router"]["deadline_shed"],
            "doomed_tenant_billed": "doomed" in usage["tenants"],
            "shed_exact": (len(shed) == expired
                           and len(served) == live
                           and snap["router"]["deadline_shed"] == expired
                           and "doomed" not in usage["tenants"]
                           and all("zero device steps" in r["error"]
                                   for r in shed)),
        }
    finally:
        close_all(rt, gws)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=36,
                    help="wave size for the flap drill")
    ap.add_argument("--sink-ms", type=int, default=SINK_MS)
    ap.add_argument("--out", default=str(Path(__file__).parent
                                         / "fleet_resilience_lab.json"))
    ap.add_argument("--workdir", default=None)
    args = ap.parse_args(argv)

    import tempfile

    tmp = None
    if args.workdir:
        workdir = Path(args.workdir)
        workdir.mkdir(parents=True, exist_ok=True)
    else:
        tmp = tempfile.TemporaryDirectory(
            prefix="heat-tpu-fleet-resilience-")
        workdir = Path(tmp.name)

    try:
        print("fleet_resilience_lab: flap drill", flush=True)
        flap = flap_drill(workdir, args.requests, args.sink_ms)
        print(f"fleet_resilience_lab: flap {flap}", flush=True)
        print("fleet_resilience_lab: stream-cut drill", flush=True)
        cut = cut_drill(workdir, 24, args.sink_ms // 2)
        print(f"fleet_resilience_lab: cut {cut}", flush=True)
        print("fleet_resilience_lab: hedge drill", flush=True)
        hedge = hedge_drill(workdir, args.sink_ms)
        print(f"fleet_resilience_lab: hedge {hedge}", flush=True)
        print("fleet_resilience_lab: deadline drill", flush=True)
        deadline = deadline_drill(workdir, expired=8, live=8)
        print(f"fleet_resilience_lab: deadline {deadline}", flush=True)
    finally:
        if tmp is not None:
            tmp.cleanup()

    rec = {
        "bench": "fleet_resilience_lab",
        "config": {"requests": args.requests, "sink_ms": args.sink_ms,
                   "backend": "in-process Engine+Gateway, lanes 2, "
                              "chunk 8, buckets (32,)",
                   "policy": "least-loaded (flap/cut/deadline), "
                             "round-robin (hedge)"},
        "flap_drill": flap,
        "cut_drill": cut,
        "hedge_drill": hedge,
        "deadline_drill": deadline,
        # the perfcheck gate fields (heat-tpu perfcheck)
        "flap_availability": flap["availability"],
        "flap_p99_ratio": flap["p99_ratio"],
        "flap_bit_identical": bool(flap["bit_identical"]),
        "cut_zero_lost": bool(cut["zero_lost"]),
        "cut_zero_duplicates": bool(cut["zero_duplicates"]),
        "hedges_won": hedge["won"],
        "hedge_bit_identical": bool(hedge["bit_identical"]),
        "deadline_shed_exact": bool(deadline["shed_exact"]),
        "breaker_steals_suppressed": bool(flap["steals_suppressed"]),
    }
    write_atomic(Path(args.out), rec)
    print(json.dumps(rec, indent=2))
    passed = (rec["flap_availability"] >= 0.99
              and rec["flap_p99_ratio"] <= 1.5
              and rec["flap_bit_identical"]
              and rec["cut_zero_lost"]
              and rec["cut_zero_duplicates"]
              and rec["hedges_won"] >= 1
              and rec["hedge_bit_identical"]
              and rec["deadline_shed_exact"]
              and rec["breaker_steals_suppressed"])
    print(f"fleet_resilience_lab: {'OK' if passed else 'FAILED'} — flap "
          f"availability {rec['flap_availability']} p99x"
          f"{rec['flap_p99_ratio']} (gates >= 0.99, <= 1.5); cut "
          f"lost=0:{rec['cut_zero_lost']} dup=0:"
          f"{rec['cut_zero_duplicates']}; hedge won {rec['hedges_won']} "
          f"bits:{rec['hedge_bit_identical']}; deadline exact:"
          f"{rec['deadline_shed_exact']}; steal thrash suppressed:"
          f"{rec['breaker_steals_suppressed']}")
    return 0 if passed else 1


if __name__ == "__main__":
    sys.exit(main())
