"""A/B: checkpoint cost on vs off the stepping critical path (async I/O).

The round-5 drive loop stalled the device for every checkpoint —
``sync(T_dev)`` -> full D2H fetch -> synchronous ``checkpoint.save`` —
"seconds for GiB-scale fields on a tunneled link". The async pipeline
(runtime/async_io.py) replaces that with one device-side buffer copy plus
a bounded-queue background writer. This lab measures exactly that claim,
CPU-runnable for CI:

- **Fake slow sink**: for the PERF rows ``checkpoint.save`` is replaced by
  a pure ``time.sleep`` sized from a calibration run (default 60% of one
  checkpoint interval's compute time) — the tunnel's D2H+write seconds as
  wall time only. Deliberately no real disk write in those rows: on CPU
  the "device" is the same silicon, so a compressing writer thread would
  steal cores from XLA and the measurement would conflate I/O latency
  (what the pipeline hides) with compute contention (a CPU-only artifact
  a TPU run doesn't have). Patching the module attribute covers the sync
  AND async paths (both resolve ``checkpoint.save`` at call time). The
  bit-identity rows run separately with the REAL save.
- **Rows**: baseline (checkpoint_every=0), sync (``--async-io off``),
  async (``--async-io on``) — all with the same heartbeat cadence so every
  row runs the identical chunk structure and only the I/O policy differs.
- **Acceptance** (ISSUE 1): async solve_s within 10% of baseline; sync
  measurably slower (it pays n_ckpts x sink delay inline). Also
  cross-checks that async-written checkpoints are bit-identical to
  sync-written ones.

Run: ``python benchmarks/ckpt_overlap.py`` (CPU ok; writes
benchmarks/ckpt_overlap.json, atomic). ``--delay S`` pins the sink delay
instead of calibrating.
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))

from _util import write_atomic  # noqa: E402


def _solve(cfg, repeats: int):
    """Best-of-``repeats`` solve (fresh checkpoint dir per rep so every rep
    writes the same number of files). Returns (best SolveResult, dir of the
    best rep's checkpoints)."""
    from heat_tpu.backends import solve

    best = None
    best_dir = None
    for _ in range(repeats):
        d = tempfile.mkdtemp(prefix="ckpt_overlap_")
        res = solve(cfg.with_(checkpoint_dir=d) if cfg.checkpoint_every
                    else cfg, fetch=False)
        if best is None or res.timing.solve_s < best.timing.solve_s:
            best, best_dir = res, d
    return best, best_dir


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--n", type=int, default=512)
    ap.add_argument("--steps", type=int, default=256)
    ap.add_argument("--every", type=int, default=32,
                    help="checkpoint interval (steps)")
    ap.add_argument("--backend", default="xla",
                    choices=["xla", "pallas", "sharded"])
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--repeats", type=int, default=2)
    ap.add_argument("--delay", type=float, default=0.0,
                    help="fake sink delay per save, seconds "
                         "(0 = calibrate to 0.75x one interval's compute)")
    ap.add_argument("--out", default=str(Path(__file__).parent
                                         / "ckpt_overlap.json"))
    args = ap.parse_args()

    import jax
    import numpy as np

    from heat_tpu.config import HeatConfig
    from heat_tpu.runtime import checkpoint

    n_ckpts = args.steps // args.every
    if n_ckpts < 2:
        sys.exit("need steps/every >= 2 checkpoints for a meaningful A/B")

    base = HeatConfig(n=args.n, ntime=args.steps, dtype=args.dtype,
                      backend=args.backend,
                      # same heartbeat cadence everywhere: every row runs
                      # identical chunk sizes; only the I/O policy differs
                      heartbeat_every=args.every)

    rec = {"ts": time.time(), "platform": jax.default_backend(),
           "n": args.n, "steps": args.steps, "every": args.every,
           "backend": args.backend, "rows": {}}
    out = Path(args.out)

    # --- row 1: no checkpoints (the wall-time target async must hold) ----
    res0, _ = _solve(base, args.repeats)
    rec["rows"]["baseline"] = {"solve_s": res0.timing.solve_s}
    print(f"baseline (no ckpt): solve {res0.timing.solve_s:.3f}s", flush=True)

    # --- fake slow sink ---------------------------------------------------
    delay = args.delay or max(0.005, 0.6 * res0.timing.solve_s / n_ckpts)
    rec["sink_delay_s"] = delay
    print(f"fake sink delay: {delay * 1e3:.1f} ms/save "
          f"({n_ckpts} saves/run)", flush=True)
    real_save = checkpoint.save

    def fake_sink(cfg, T, step):
        time.sleep(delay)  # the tunnel's D2H+write seconds, as wall time

    checkpoint.save = fake_sink
    try:
        ck = base.with_(checkpoint_every=args.every)
        res_sync, _ = _solve(ck.with_(async_io="off"), args.repeats)
        rec["rows"]["ckpt_sync"] = {"solve_s": res_sync.timing.solve_s}
        print(f"ckpt  --async-io off: solve {res_sync.timing.solve_s:.3f}s",
              flush=True)
        res_async, _ = _solve(ck.with_(async_io="on"), args.repeats)
        rec["rows"]["ckpt_async"] = {
            "solve_s": res_async.timing.solve_s,
            "overlap_s": res_async.timing.overlap_s,
            "io_wait_s": res_async.timing.io_wait_s,
        }
        print(f"ckpt  --async-io on : solve {res_async.timing.solve_s:.3f}s "
              f"(overlap {res_async.timing.overlap_s:.3f}s hidden, "
              f"{res_async.timing.io_wait_s:.3f}s blocked)", flush=True)
    finally:
        checkpoint.save = real_save

    # --- verdicts ---------------------------------------------------------
    b = res0.timing.solve_s
    rec["async_vs_baseline"] = res_async.timing.solve_s / b
    rec["sync_vs_baseline"] = res_sync.timing.solve_s / b
    ok_async = rec["async_vs_baseline"] <= 1.10
    ok_sync = rec["sync_vs_baseline"] > rec["async_vs_baseline"]
    print(f"async/baseline = {rec['async_vs_baseline']:.3f} "
          f"({'PASS: within 10%' if ok_async else 'FAIL: > 10% over'}); "
          f"sync/baseline = {rec['sync_vs_baseline']:.3f}", flush=True)

    # --- bit-identity: async-written checkpoints == sync-written ----------
    # separate short runs with the REAL save (the perf rows wrote nothing)
    _, d_sync = _solve(ck.with_(async_io="off"), 1)
    _, d_async = _solve(ck.with_(async_io="on"), 1)
    identical = True
    for step in range(args.every, args.steps + 1, args.every):
        Ts, ss = checkpoint.load(
            checkpoint.latest(ck.with_(checkpoint_dir=d_sync,
                                       ntime=step)), ck)
        Ta, sa = checkpoint.load(
            checkpoint.latest(ck.with_(checkpoint_dir=d_async,
                                       ntime=step)), ck)
        if ss != sa or not np.array_equal(Ts, Ta):
            identical = False
    rec["bit_identical"] = identical
    print(f"async checkpoints bit-identical to sync: {identical}", flush=True)

    write_atomic(out, rec)
    print(f"wrote {out}")
    return 0 if (ok_async and ok_sync and identical) else 1


if __name__ == "__main__":
    sys.exit(main())
