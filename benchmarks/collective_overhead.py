"""Measure per-collective dispatch overhead on the attached chip.

VERDICT r2 item 3: BASELINE.md's v5p-32 weak-scaling projection rested on
an *assumed* 5 µs per-ppermute cost. One chip cannot measure ICI wire
latency, but it CAN measure the per-collective launch/dispatch overhead
the projection's latency term is built from, three ways:

1. ``ppermute_chain``: shard_map programs with m chained self-ppermutes
   (perm [(0,0)] on a 1-device axis) over a realistic halo slab;
   slope of time vs m = per-ppermute dispatch cost.
2. ``dispatch_chain``: the same chain with plain elementwise ops instead
   of collectives — separates "any op dispatch" from "collective
   dispatch".
3. ``exchange_delta``: the sharded backend's own ``padded_multi`` (one
   width-k exchange + k fused steps) vs the bare kernel on the same
   block — the per-exchange cost the single-chip fuse-depth sweep
   actually pays (exchange = fusion break + masked-neighbor select on a
   1x1 mesh; no wire).

Writes benchmarks/collective_overhead.json and prints one line per probe.
Run on the real chip: ``python benchmarks/collective_overhead.py``
Smoke (CPU): ``python benchmarks/collective_overhead.py --smoke``
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))


def _sync(x):
    from heat_tpu.runtime.timing import sync

    return sync(x)


def _best_time(call, x, repeats=5):
    """Best-of wall time of call(x) with the scalar-fetch fence; the
    fixed tunnel overhead is NOT subtracted here — probes difference
    pairs of these, which cancels it exactly like two_point_rate."""
    _sync(call(x))  # warm (no donation in these probes); scalar-fetch fence
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        y = call(x)
        _sync(y)
        best = min(best, time.perf_counter() - t0)
    return best


def probe_chains(smoke: bool):
    """Probes 1 + 2: chained self-ppermutes vs chained elementwise ops."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:  # pragma: no cover
        from jax.experimental.shard_map import shard_map

    mesh = Mesh(jax.devices()[:1], ("x",))
    # a realistic halo slab: width-8 exchange of a 16384-wide row block, f32
    slab = jnp.zeros((8, 1024 if smoke else 16384), jnp.float32)
    ms = (0, 1, 2, 4, 8, 16)

    def chain(m, collective):
        def body(s):
            for i in range(m):
                if collective:
                    s = jax.lax.ppermute(s, "x", [(0, 0)])
                # the +i dependency chain stops XLA from CSE-merging the
                # repeated identical stages (and is the non-collective
                # chain's whole payload)
                s = s + jnp.float32(1 + i)
            return s

        return jax.jit(shard_map(body, mesh=mesh, in_specs=(P("x"),),
                                 out_specs=P("x")))

    out = {}
    for collective in (True, False):
        name = "ppermute_chain" if collective else "dispatch_chain"
        times = {}
        for m in ms:
            fn = chain(m, collective)
            times[m] = _best_time(fn, slab)
        # least-squares slope of time vs m = per-stage cost
        import numpy as np

        xs = np.asarray(list(times), float)
        ys = np.asarray([times[m] for m in times], float)
        slope = float(np.polyfit(xs, ys, 1)[0])
        out[name] = {"times_s": {str(m): times[m] for m in times},
                     "per_stage_s": slope}
        print(f"{name}: per-stage {slope * 1e6:.2f} us "
              f"(t0={times[0] * 1e3:.2f} ms, t16={times[16] * 1e3:.2f} ms)")
    # the collective's own cost is the chain slope minus the elementwise
    # chain's slope (both carry one add per stage)
    per_ppermute = (out["ppermute_chain"]["per_stage_s"]
                    - out["dispatch_chain"]["per_stage_s"])
    out["per_ppermute_dispatch_s"] = per_ppermute
    print(f"per-ppermute dispatch overhead: {per_ppermute * 1e6:.2f} us")
    return out


def _auto_ks() -> tuple[int, ...]:
    """Fuse depths for the exchange-delta sweep. Round 3's fuse=32 case
    sat >25 min (resolved in round 4: the tunnel wedge, not a compile
    cliff — see _util.deep_fuse_proven); 32 joins once a bisect artifact
    has proven its compile bounded. VERDICT r3 #6 wants the {16,32}
    points for a >=3-point t(k) fit; {1,8,16} alone already give three."""
    from _util import deep_fuse_proven

    base = (1, 8, 16)
    return base + (32,) if deep_fuse_proven(32) else base


def probe_exchange_delta(smoke: bool, flush, rec: dict, ks=None):
    """Probe 3: the sharded backend's real per-exchange cost at mesh 1x1.

    Times the padded-carry advance at fuse depth k (one exchange per k
    steps) over a fixed step count; the per-exchange cost C falls out of
    t(k) = steps*(t_step + C/k). Each k's row flushes atomically the
    moment it lands (a wedged deeper-k row must not void measured ones),
    and the fit is refreshed after every row."""
    import numpy as np

    from heat_tpu.backends.sharded import solve as sharded_solve
    from heat_tpu.config import HeatConfig

    n = 512 if smoke else 16384
    steps = 32 if smoke else 512
    out = rec.setdefault("exchange_delta", {})
    rates = {}
    for k in ks or _auto_ks():
        cfg = HeatConfig(n=n, ntime=steps, dtype="float32",
                         backend="sharded", mesh_shape=(1, 1), fuse_steps=k)
        res = sharded_solve(cfg, fetch=False, warm_exec=True,
                            two_point_repeats=2)
        tp = res.timing.points_per_s_two_point or res.timing.points_per_s
        rates[k] = tp
        out[f"fuse_{k}"] = {"points_per_s_two_point": tp,
                            "solve_s": res.timing.solve_s,
                            "compile_s": res.timing.compile_s}
        print(f"exchange_delta fuse={k}: {tp:.3e} pts/s", flush=True)
        if len(rates) >= 2:
            # t_step(k) = t_compute + C/k: least-squares over all measured
            # k uses every paid-for point; refreshed per row so a later
            # wedge still leaves the best fit money bought
            inv_k = np.asarray([1 / k for k in rates], float)
            t_step = np.asarray([n * n / rates[k] for k in rates], float)
            C, t_comp = np.polyfit(inv_k, t_step, 1)
            resid = t_step - (t_comp + C * inv_k)
            out["per_exchange_s"] = float(C)
            out["t_step_compute_s"] = float(t_comp)
            out["fit_ks"] = sorted(rates)
            out["fit_residuals_s"] = [float(r) for r in resid]
        flush()
    if "per_exchange_s" in out:
        print(f"per-exchange cost (1x1 mesh, no wire): "
              f"{out['per_exchange_s'] * 1e6:.2f} us over k={sorted(rates)}")
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true", help="tiny sizes, CPU-safe")
    ap.add_argument("--ks", help="comma-separated fuse depths for the "
                                 "exchange-delta probe (default: auto — "
                                 "{1,8,16} + 32 iff compile_bisect proved "
                                 "its compile bounded)")
    args = ap.parse_args()
    if args.smoke:
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    rec = {"ts": time.time(), "platform": jax.default_backend(),
           "smoke": bool(args.smoke)}
    out = Path(__file__).parent / (
        "collective_overhead_smoke.json" if args.smoke
        else "collective_overhead.json")
    from _util import write_atomic

    def flush():
        # atomic + after each probe: the round-3 sweep lost a completed
        # chains probe when a later probe blew the phase timeout before
        # the single end-of-run write
        write_atomic(out, rec)

    rec.update(probe_chains(args.smoke))
    flush()
    ks = tuple(int(s) for s in args.ks.split(",")) if args.ks else None
    probe_exchange_delta(args.smoke, flush, rec, ks=ks)
    flush()
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
