#!/bin/bash
# Round-5 phase 2: chip work DISCOVERED during the first up-window —
# items that did not exist when watch_and_sweep.sh was parked:
#   * calibrate with the fixed probes (the 08:52 run was pre-fix and
#     dispatch-floor-poisoned; its artifact was deleted, not shipped)
#   * the n2=16384 bf16-variant A/B (flagship-scale compiles of
#     bf16native/bf16fma die in the remote-compile helper; 16384 fits
#     and answers the half-byte hypothesis with a measurement)
# Waits for the main sweep to exit first — ONE chip, ONE queue.
set -u
export JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-${XDG_CACHE_HOME:-$HOME/.cache}/heat_tpu/jax}"
export PYTHONPATH="$(cd "$(dirname "$0")/.." && pwd):${PYTHONPATH:-}"
cd "$(dirname "$0")/.."

while pgrep -f "watch_and_sweep.sh" > /dev/null 2>&1; do
  sleep 120
done

# budget must FUND the full queue: phase caps below sum to ~21,700s, so
# a 14,400s default silently clamped/skipped the tail phases in exactly
# the slow-host scenario the retry exists for (review r5). The HARD_END
# wall-clock cap exists because this queue starts whenever the main
# sweep exits — possibly very late: the round's driver reclaims the
# chip for its final bench around 20:27 UTC, and a phase still holding
# the chip then would fail the round's official capture. 19:40 leaves
# ~45 min of margin.
HARD_END=${HARD_END:-1785613200}  # 2026-08-01 19:40 UTC
DEADLINE=$(( $(date +%s) + ${BUDGET_S:-23000} ))
[ "$DEADLINE" -gt "$HARD_END" ] && DEADLINE=$HARD_END

probe() { timeout 120 python -c "import jax; assert jax.devices()" 2>/dev/null; }

wait_up() {
  until probe; do
    if [ "$(date +%s)" -ge "$DEADLINE" ]; then
      echo "=== extras budget exhausted waiting at $(date)"; exit 1
    fi
    echo "tunnel down at $(date); waiting"
    sleep 300
  done
}

phase() {
  local name=$1 to=$2; shift 2
  if [ "$(date +%s)" -ge "$DEADLINE" ]; then
    echo "=== extras budget exhausted before $name"; exit 1
  fi
  wait_up
  local remaining=$(( DEADLINE - $(date +%s) ))
  if [ "$remaining" -lt 120 ]; then
    echo "=== extras budget exhausted before $name"; exit 1
  fi
  [ "$to" -gt "$remaining" ] && to=$remaining
  echo "=== $name start $(date) (timeout ${to}s)"
  if timeout "$to" "$@"; then
    echo "=== $name OK $(date)"
  else
    echo "=== $name FAILED rc=$? $(date)"
  fi
}

# Priority order: VERDICT-facing first. calibrate is the r4 #6
# acceptance run; the overlap retry is the r4 #4 direct wall-clock
# (the main sweep's attempt hit its 5400 s cap at rc=124 while the
# 1-core host was shared with test suites — NOTE overlap_ab.py has no
# row-resume: the retry re-runs the indep row too, cheap only via the
# warm compile cache, and its FIRST row write replaces the whole
# artifact — a retry that lands one row has already dropped the prior
# run's rows, and only a full completion restores them); row3 captures
# the fuse-optimum lift; the var16k A/Bs are BASELINE evidence.
phase calibrate_fixed   2400 python -m heat_tpu.cli calibrate --out benchmarks/calibration_v5e.json
phase overlap_ab_retry  7200 python benchmarks/overlap_ab.py
# round-5 fuse-optimum change: auto depth at 16384^2 is now k=16 (the
# measured 12%-faster program, warm in the cache from the
# collective_overhead fuse_16 row) — re-measure the official row
phase row3_fuse16       2500 python benchmarks/run_all.py --only 3_sharded_16384sq_f32_mesh --row-timeout 2400
phase var16k_f32        2400 python benchmarks/kernel_lab.py bench2d_rolled_var f32 256,4096,16,128 --n2 16384
phase var16k_bf16native 2400 python benchmarks/kernel_lab.py bench2d_rolled_var bf16native 256,4096,16,128 --n2 16384
phase var16k_bf16fma    2400 python benchmarks/kernel_lab.py bench2d_rolled_var bf16fma 256,4096,16,128 --n2 16384
phase var16k_fma        2400 python benchmarks/kernel_lab.py bench2d_rolled_var fma 256,4096,16,128 --n2 16384
# Certification phases the MAIN sweep will have dropped if its budget
# expired waiting out the outage — best-effort here, clamped by
# HARD_END; chip_check refreshes the hardware numeric certification
# artifact (round-2 vintage otherwise).
phase sharded3d_check   1800 python benchmarks/sharded3d_check.py
phase check2d_rolled    1800 python benchmarks/kernel_lab.py check2d_rolled
phase checkthin         1800 python benchmarks/kernel_lab.py checkthin
phase check3d_rolled    1800 python benchmarks/kernel_lab.py check3d_rolled
phase chip_check        2400 python benchmarks/chip_check.py
echo "=== extras done at $(date)"
