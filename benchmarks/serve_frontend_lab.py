"""Serving front-end A/B: online Poisson arrivals, EDF vs FIFO, plus a
front-end-cost check against the PR-5 offline drain.

Two claims, one harness:

- **The front-end adds no hot-loop cost** (ISSUE 6 acceptance): draining
  the PR-3 64-request population through the policy layer (fifo queue,
  admission trace, per-class histograms all live) must stay within 5% of
  the committed ``serve_lab.json`` engine aggregate throughput — the
  policy extraction is bookkeeping on the admission path, never on the
  chunk boundary.
- **Deadlines shape admission, not just shedding**: the SAME seeded
  open-loop Poisson arrival schedule (a burst at ~2x the measured service
  rate, so a real backlog forms) is fed to a *running* online engine
  twice — ``--policy fifo`` vs ``--policy edf``. Requests carry SLO
  classes (1/4 interactive with a tight deadline, 1/4 standard with a
  looser one, 1/2 batch undated); under backlog FIFO serves in arrival
  order and late-arriving dated requests blow their budgets, while EDF
  admits them first. The artifact records per-class p50/p95/p99 latency
  (from the same histograms ``/metrics`` exports) and the deadline-hit
  rate per policy; EDF >= FIFO is the pass criterion.

Arrivals are open-loop (submission times fixed up front, independent of
completions — the "millions of users" shape), deterministic via a seeded
RNG. The online engine starts at tier 1 and grows lanes as the burst
builds, so the run also exercises the lane-growth path end to end.

    JAX_PLATFORMS=cpu python benchmarks/serve_frontend_lab.py [--requests 64]
"""

from __future__ import annotations

import argparse
import json
import random
import sys
import time
from pathlib import Path

from _util import write_atomic

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

BASELINE = Path(__file__).parent / "serve_lab.json"


def build_requests(count: int):
    """The PR-3/PR-5 serve_lab population (import, not copy — the labs
    must measure the same work)."""
    import serve_lab

    return serve_lab.build_requests(count)


def classify(i: int, n_requests: int, drain_s: float):
    """Deterministic SLO assignment: i%4==0 interactive (tight deadline),
    i%4==2 standard (looser), else batch (undated). Deadlines scale with
    the measured offline drain (which includes the compile cost an online
    cold start also pays) so the lab stresses the same way on any host
    speed: the 3x-rate burst makes the whole online run span roughly
    2-3 drain walls, so a ~1.2x budget is meetable only by jumping the
    queue — EDF's move — while FIFO's arrival order leaves late dated
    requests far past it."""
    if i % 4 == 0:
        return "interactive", 1.2 * drain_s * 1e3
    if i % 4 == 2:
        return "standard", 2.0 * drain_s * 1e3
    return "batch", None


def run_offline(reqs, lanes, chunk):
    from heat_tpu.serve import Engine, ServeConfig

    eng = Engine(ServeConfig(lanes=lanes, chunk=chunk, buckets=(32, 48),
                             emit_records=False))
    t0 = time.perf_counter()
    for cfg in reqs:
        eng.submit(cfg)
    records = eng.results()
    wall = time.perf_counter() - t0
    ok = sum(r["status"] == "ok" for r in records)
    return wall, ok, eng


def run_online(reqs, schedule, policy, lanes, chunk, drain_s):
    """Feed the seeded arrival schedule into a RUNNING engine under one
    policy; returns (records-by-status counts, per-class quantiles,
    deadline hit rate, engine)."""
    from heat_tpu.serve import Engine, ServeConfig

    eng = Engine(ServeConfig(lanes=lanes, chunk=chunk, buckets=(32, 48),
                             emit_records=False, policy=policy)).start()
    ids, dated = [], []
    t0 = time.perf_counter()
    for (arrival, i, cfg) in schedule:
        delay = arrival - (time.perf_counter() - t0)
        if delay > 0:
            time.sleep(delay)
        cls, deadline_ms = classify(i, len(reqs), drain_s)
        rid = eng.submit(cfg, request_id=f"{policy}-{i:03d}",
                         deadline_ms=deadline_ms, slo_class=cls,
                         tenant="lab")
        ids.append(rid)
        if deadline_ms is not None:
            dated.append(rid)
    recs = {}
    for rid in ids:
        recs[rid] = eng.wait(rid, timeout=600)
        assert recs[rid] is not None, f"timed out waiting for {rid}"
    wall = time.perf_counter() - t0
    eng.shutdown(timeout=600)
    statuses = {}
    for r in recs.values():
        statuses[r["status"]] = statuses.get(r["status"], 0) + 1
    hits = sum(recs[rid]["status"] == "ok" for rid in dated)
    quantiles = {
        cls: {q: h.quantile(p) for q, p in
              (("p50", 0.5), ("p95", 0.95), ("p99", 0.99))}
        for cls, h in sorted(eng.lat_hist.items())}
    return {
        "policy": policy,
        "wall_s": round(wall, 3),
        "statuses": statuses,
        "deadline_carrying": len(dated),
        "deadline_hits": hits,
        "deadline_hit_rate": round(hits / len(dated), 4) if dated else None,
        "deadline_misses": eng.deadline_misses,
        "lane_grows": eng.lane_grows,
        "latency_quantiles_s": quantiles,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--lanes", type=int, default=8)
    ap.add_argument("--chunk", type=int, default=16)
    ap.add_argument("--seed", type=int, default=20260804)
    ap.add_argument("--out", default=str(Path(__file__).parent
                                         / "serve_frontend_lab.json"))
    args = ap.parse_args(argv)

    reqs = build_requests(args.requests)
    work = sum(cfg.points * cfg.ntime for cfg in reqs)

    # offline drain through the policy layer: best of 3 (each engine pays
    # its own compiles, exactly like the committed serve_lab baseline run)
    offline = [run_offline(reqs, args.lanes, args.chunk) for _ in range(3)]
    off_wall = min(w for w, _, _ in offline)
    off_ok = offline[0][1]
    off_pps = work / off_wall

    baseline_pps = baseline_ratio = None
    if BASELINE.exists() and args.requests == 64:
        base = json.loads(BASELINE.read_text())
        baseline_pps = base["engine"]["points_per_s"]
        baseline_ratio = round(off_pps / baseline_pps, 4)

    # seeded open-loop Poisson burst at ~3x the measured service rate:
    # a genuine backlog, identical arrival instants for both policies
    rng = random.Random(args.seed)
    rate = 3.0 * args.requests / max(off_wall, 1e-3)
    t = 0.0
    schedule = []
    for i, cfg in enumerate(reqs):
        schedule.append((t, i, cfg))
        t += rng.expovariate(rate)
    fifo = run_online(reqs, schedule, "fifo", args.lanes, args.chunk,
                      off_wall)
    edf = run_online(reqs, schedule, "edf", args.lanes, args.chunk,
                     off_wall)

    rec = {
        "bench": "serve_frontend_lab",
        "config": {"requests": args.requests, "lanes": args.lanes,
                   "chunk": args.chunk, "buckets": [32, 48],
                   "seed": args.seed,
                   "arrival_rate_req_per_s": round(rate, 1),
                   "deadline_policy": "interactive 0.5x / standard 0.8x "
                                      "of the offline drain wall; batch "
                                      "undated"},
        "work_cell_steps": work,
        "offline_drain": {
            "wall_s": round(off_wall, 3),
            "points_per_s": round(off_pps, 1),
            "ok": off_ok,
            "baseline_points_per_s": baseline_pps,
            "vs_serve_lab_engine": baseline_ratio,
        },
        "online_fifo": fifo,
        "online_edf": edf,
        "edf_vs_fifo_hit_rate_delta": (
            round(edf["deadline_hit_rate"] - fifo["deadline_hit_rate"], 4)
            if edf["deadline_hit_rate"] is not None else None),
    }
    write_atomic(Path(args.out), rec)
    print(json.dumps(rec, indent=2))
    passed = (off_ok == args.requests
              and edf["deadline_hit_rate"] is not None
              and edf["deadline_hit_rate"] >= fifo["deadline_hit_rate"]
              and (baseline_ratio is None or baseline_ratio >= 0.95))
    print(f"serve_frontend_lab: {'OK' if passed else 'FAILED'} — offline "
          f"drain {off_pps:.3g} pts/s"
          + (f" ({100 * baseline_ratio:.1f}% of serve_lab engine)"
             if baseline_ratio is not None else "")
          + f"; deadline hit rate EDF {edf['deadline_hit_rate']} vs FIFO "
            f"{fifo['deadline_hit_rate']} "
            f"(+{rec['edf_vs_fifo_hit_rate_delta']}); lane grows "
            f"fifo={fifo['lane_grows']} edf={edf['lane_grows']}")
    return 0 if passed else 1


if __name__ == "__main__":
    sys.exit(main())
