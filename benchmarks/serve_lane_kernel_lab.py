"""Serve lane-kernel A/B: Pallas lane program vs XLA lane program vs solo.

The ISSUE-9 claim, measured: the serving engine's chunk program has two
interchangeable bodies — the vmapped masked XLA stencil (the bit-exact
oracle) and the multi-lane Pallas kernel family (the lane axis as a grid
dimension over the solo hand-tuned plans, with per-lane masking,
countdown gating, and the isfinite health reduction fused into one
kernel). Three ways over the PR-3 64-request population (serve_lab.py's
exact shape/step mix at float32 — the Pallas kernels have no f64):

1. ``--serve-lane-kernel pallas``: the Pallas lane program;
2. ``--serve-lane-kernel xla``: the oracle lane program, same engine;
3. solo Pallas drives: one ``backends.solve`` per request with
   ``backend="pallas"`` — the hand-tuned solo kernel each request would
   get alone, i.e. the per-chip ceiling ROADMAP's ~90% bar is against.

Recorded per side: per-chip pts/s, chunk/boundary counters, the online
cost-model rows (now keyed by kernel — the committed live counterpart of
this A/B), and lane_kernel_fallback counts (must be ZERO here: every
bucket in this population has a kernel plan at f32). A bit-identity
check between the pallas and xla engine results is a hard gate on every
platform — a perf artifact must never certify a wrong-answer kernel.

Platform semantics (the lab runs UNCHANGED on TPU — that is the point):
on a TPU host the Pallas side must beat the XLA side per chip
(``pallas_beats_xla`` is a hard gate there) and is measured against the
solo ceiling (``pallas_vs_solo`` vs ROADMAP's ~0.9). On CPU the Pallas
kernels run in interpret mode, so both ratios are recorded but
informational — the committed CPU artifact certifies bit-identity,
fallback honesty, and the harness itself.

    JAX_PLATFORMS=cpu python benchmarks/serve_lane_kernel_lab.py [--requests 64]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

from _util import write_atomic

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from serve_lab import build_requests  # noqa: E402  (the PR-3 population)


def run_engine(reqs, lanes: int, chunk: int, depth: int, kernel: str):
    from heat_tpu.serve import Engine, ServeConfig

    eng = Engine(ServeConfig(lanes=lanes, chunk=chunk, buckets=(32, 48),
                             dispatch_depth=depth, lane_kernel=kernel,
                             emit_records=False))
    t0 = time.perf_counter()
    ids = [eng.submit(cfg) for cfg in reqs]
    records = eng.results()
    wall = time.perf_counter() - t0
    by_id = {r["id"]: r for r in records}
    return wall, eng, [by_id[i] for i in ids]


def run_solo_pallas(reqs):
    """The per-chip ceiling: each request alone on the hand-tuned solo
    Pallas kernel (transparent XLA fallback where it doesn't apply —
    none here at f32)."""
    from heat_tpu.backends import solve

    t0 = time.perf_counter()
    fields = [solve(cfg.with_(backend="pallas")).T for cfg in reqs]
    return time.perf_counter() - t0, fields


def _engine_block(work, wall, eng, records):
    s = eng.summary()
    return {
        "wall_s": round(wall, 3),
        "points_per_s": round(work / wall, 1),
        "ok": sum(r["status"] == "ok" for r in records),
        "rejected": sum(r["status"] == "rejected" for r in records),
        "failed": sum(r["status"] not in ("ok", "rejected")
                      for r in records),
        "step_compiles": eng.step_compiles,
        "tail_compiles": eng.tail_compiles,
        "compile_s": round(eng.compile_s, 3),
        "chunks_dispatched": s["chunks_dispatched"],
        "boundary_wait_s": s["boundary_wait_s"],
        "lane_kernel": s["lane_kernel"],
        "lane_kernel_fallbacks": s["lane_kernel_fallbacks"],
        "cost_model": s["cost_model"],
    }


def main(argv=None) -> int:
    import numpy as np

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--lanes", type=int, default=8)
    ap.add_argument("--chunk", type=int, default=16)
    ap.add_argument("--depth", type=int, default=2)
    ap.add_argument("--out", default=str(Path(__file__).parent
                                         / "serve_lane_kernel_lab.json"))
    args = ap.parse_args(argv)

    import jax

    platform = jax.default_backend()
    reqs = build_requests(args.requests, dtype="float32")
    work = sum(cfg.points * cfg.ntime for cfg in reqs)

    # XLA first so the Pallas side cannot inherit a warmer process; the
    # solo drives last (their compiles are their own, like N `heat-tpu
    # run` invocations)
    xla_wall, xla_eng, xla_recs = run_engine(reqs, args.lanes, args.chunk,
                                             args.depth, kernel="xla")
    pal_wall, pal_eng, pal_recs = run_engine(reqs, args.lanes, args.chunk,
                                             args.depth, kernel="pallas")
    solo_wall, solo_fields = run_solo_pallas(reqs)

    # hard gate everywhere: the Pallas lane program is byte-identical to
    # the XLA oracle on EVERY request (fields ride the records in-memory)
    bit_identical = all(
        a["T"].dtype == b["T"].dtype
        and a["T"].tobytes() == b["T"].tobytes()
        for a, b in zip(xla_recs, pal_recs))
    # and a sample matches the solo ORACLE drive (default XLA backend —
    # the bit-identity reference of tests/test_serve.py; the solo Pallas
    # kernel above is the PERF ceiling, not the bit oracle: it fuses in a
    # different summation order, so it is compared by throughput only)
    from heat_tpu.backends import solve

    sample = sorted({0, len(reqs) // 2, len(reqs) - 1})
    solo_identical = all(
        np.array_equal(pal_recs[i]["T"], solve(reqs[i]).T) for i in sample)

    pallas_vs_xla = xla_wall / pal_wall if pal_wall > 0 else None
    pallas_vs_solo = solo_wall / pal_wall if pal_wall > 0 else None
    rec = {
        "bench": "serve_lane_kernel_lab",
        "platform": platform,
        "config": {"requests": args.requests, "lanes": args.lanes,
                   "chunk": args.chunk, "dispatch_depth": args.depth,
                   "buckets": [32, 48], "sides": [24, 32, 48],
                   "ntimes": [96, 112, 128], "dtype": "float32"},
        "work_cell_steps": work,
        "pallas": _engine_block(work, pal_wall, pal_eng, pal_recs),
        "xla": _engine_block(work, xla_wall, xla_eng, xla_recs),
        "solo_pallas": {"wall_s": round(solo_wall, 3),
                        "points_per_s": round(work / solo_wall, 1)},
        # engine-aggregate vs solo-sequential ratios: >1 means the lane
        # program outruns N sequential solo drives (batching + warm
        # compiles); the ROADMAP bar is pallas_vs_solo on TPU ~>= 0.9
        # per chip at full lanes
        "pallas_vs_xla": round(pallas_vs_xla, 3) if pallas_vs_xla else None,
        "pallas_vs_solo": (round(pallas_vs_solo, 3)
                           if pallas_vs_solo else None),
        "bit_identical": bool(bit_identical),
        "solo_sample_identical": bool(solo_identical),
        "zero_fallbacks": (pal_eng.lane_kernel_fallbacks == 0
                           and xla_eng.lane_kernel_fallbacks == 0),
        # the TPU gate travels with the artifact: informational on CPU
        # (interpret-mode Pallas), hard where the kernels are real
        "pallas_beats_xla": (pallas_vs_xla or 0) > 1.0,
    }
    write_atomic(Path(args.out), rec)
    print(json.dumps(rec, indent=2))
    passed = (rec["bit_identical"] and rec["solo_sample_identical"]
              and rec["zero_fallbacks"]
              and rec["pallas"]["ok"] == args.requests
              and rec["xla"]["ok"] == args.requests)
    if platform == "tpu":
        passed = passed and rec["pallas_beats_xla"]
    tag = "informational on cpu" if platform != "tpu" else "hard gate"
    print(f"serve_lane_kernel_lab: {'OK' if passed else 'FAILED'} — "
          f"pallas {rec['pallas']['points_per_s']:.3g} pts/s vs xla "
          f"{rec['xla']['points_per_s']:.3g} ({rec['pallas_vs_xla']}x, "
          f"{tag}) vs solo pallas "
          f"{rec['solo_pallas']['points_per_s']:.3g} "
          f"({rec['pallas_vs_solo']}x); bit-identical="
          f"{rec['bit_identical']}, fallbacks=0:"
          f"{rec['zero_fallbacks']}")
    return 0 if passed else 1


if __name__ == "__main__":
    sys.exit(main())
