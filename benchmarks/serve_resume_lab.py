"""Zero-downtime serving A/B: kill-at-50%-then-resume vs uninterrupted.

The ISSUE-17 claim, measured: engine-state checkpointing plus ``serve
--resume`` must make a mid-wave kill invisible in the *results* and
nearly free in *work*. The serve_lab 64-request population runs twice:

- **uninterrupted**: one engine drains the wave, npz per request — the
  golden bytes;
- **kill + resume**: the SAME wave runs with ``--engine-ckpt-interval``
  cadence checkpoints; the kill is simulated at the generation closest
  to 50% of the wave's boundaries by deleting every newer generation
  (exactly what a SIGKILL leaves: the FIFO writer ordering guarantees a
  surviving manifest's fields and pre-cut writebacks are durable) and
  every result file the manifest does not list as done. A second engine
  ``resume_engine``-s from the surviving generation and drains the rest.

Three acceptance gates ride in the artifact:

- ``resumed_bit_identical``: every one of the 64 npz files — done-
  before-the-cut from the killed run, the rest re-published by the
  resumed run — byte-identical to the uninterrupted golden bytes;
- ``zero_resteps``: per resumed request, chunks and steps (summed
  across both incarnations by the cumulative usage stamps) equal the
  uninterrupted run's — no chunk re-stepped past the last checkpointed
  boundary, no step double-billed;
- ``resumed_requests_recovered``: the surviving manifest accounts for
  the whole wave (in-flight + queued + done = all 64 ids) and every
  resumed request finishes ok.

Recovery overhead is reported as the wall time of the resume call
itself — one manifest load + per-lane reseed, no recompute.

    JAX_PLATFORMS=cpu python benchmarks/serve_resume_lab.py [--requests 64]
"""

from __future__ import annotations

import argparse
import json
import re
import sys
import time
from pathlib import Path

from _util import write_atomic

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

CKPT_INTERVAL = 25   # boundaries between generations (~16 gens per wave)


def run_wave(reqs, workdir: Path, tag: str, lanes: int, chunk: int,
             depth: int, interval: int = 0, engine=None):
    from heat_tpu.serve import Engine, ServeConfig

    out = workdir / tag
    eng = engine
    if eng is None:
        eng = Engine(ServeConfig(
            lanes=lanes, chunk=chunk, buckets=(32, 48),
            dispatch_depth=depth, emit_records=False, out_dir=str(out),
            engine_ckpt_interval=interval,
            engine_ckpt_dir=str(workdir / f"{tag}-ckpt")))
    for i, cfg in enumerate(reqs):
        eng.submit(cfg, request_id=f"r{i:03d}")
    t0 = time.perf_counter()
    records = eng.results()
    return time.perf_counter() - t0, eng, {r["id"]: r for r in records}


def simulate_kill_at_half(ckdir: Path, outdir: Path):
    """Delete every generation newer than the one closest to 50% of the
    wave's boundaries, plus every npz the survivor does NOT list as done
    — the on-disk state a SIGKILL at that cut would have left."""
    gens = {}
    for p in sorted(ckdir.glob("engine_gen*.json")):
        man = json.loads(p.read_text())
        gens[int(man["generation"])] = man
    final_boundaries = max(m["boundaries"] for m in gens.values())
    cut = min(gens, key=lambda g: abs(gens[g]["boundaries"]
                                      - final_boundaries / 2))
    for p in list(ckdir.glob("engine_gen*")):
        if int(re.search(r"gen(\d+)", p.name).group(1)) > cut:
            p.unlink()
    done = set(gens[cut]["done"])
    for p in list(outdir.glob("*.npz")):
        if p.stem not in done:
            p.unlink()
    return gens[cut], final_boundaries


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--lanes", type=int, default=8)
    ap.add_argument("--chunk", type=int, default=16)
    ap.add_argument("--depth", type=int, default=2)
    ap.add_argument("--workdir", default=None,
                    help="scratch dir (default: a fresh tempdir)")
    ap.add_argument("--out", default=str(Path(__file__).parent
                                         / "serve_resume_lab.json"))
    args = ap.parse_args(argv)

    import tempfile

    from serve_lab import build_requests

    from heat_tpu.serve import Engine, ServeConfig
    from heat_tpu.serve.resume import resume_engine

    workdir = Path(args.workdir or tempfile.mkdtemp(prefix="resume_lab_"))
    reqs = build_requests(args.requests)

    golden_wall, _, golden = run_wave(reqs, workdir, "golden", args.lanes,
                                      args.chunk, args.depth)
    killed_wall, _, _ = run_wave(reqs, workdir, "killed", args.lanes,
                                 args.chunk, args.depth,
                                 interval=CKPT_INTERVAL)
    ckdir = workdir / "killed-ckpt"
    survivor, final_boundaries = simulate_kill_at_half(
        ckdir, workdir / "killed")

    resumed_eng = Engine(ServeConfig(
        lanes=args.lanes, chunk=args.chunk, buckets=(32, 48),
        dispatch_depth=args.depth, emit_records=False,
        out_dir=str(workdir / "resumed"),
        engine_ckpt_interval=CKPT_INTERVAL,
        engine_ckpt_dir=str(ckdir)))
    t0 = time.perf_counter()
    skip = resume_engine(resumed_eng, ckdir)
    recovery_s = time.perf_counter() - t0
    resume_wall, _, resumed = run_wave(reqs[:0], workdir, "resumed",
                                       args.lanes, args.chunk, args.depth,
                                       engine=resumed_eng)

    all_ids = [f"r{i:03d}" for i in range(args.requests)]
    recovered_all = set(skip) == set(all_ids)
    resumed_ok = all(r["status"] == "ok" for r in resumed.values())

    # byte-identity over the MERGED result set: done-before-the-cut files
    # survive the kill in killed/, everything else re-published by the
    # resumed engine
    identical = []
    for rid in all_ids:
        a = workdir / "golden" / f"{rid}.npz"
        b = workdir / "killed" / f"{rid}.npz"
        if not b.exists():
            b = workdir / "resumed" / f"{rid}.npz"
        identical.append(b.exists()
                         and a.read_bytes() == b.read_bytes())
    bit_identical = all(identical)

    # zero re-stepped chunks / no double billing: the resumed records'
    # usage stamps are cumulative across incarnations by construction
    resteps = []
    for rid, rec in resumed.items():
        g = golden[rid]
        if (rec["usage"]["chunks"] != g["usage"]["chunks"]
                or rec["usage"]["steps"] != g["usage"]["steps"]):
            resteps.append({"id": rid,
                            "chunks": [g["usage"]["chunks"],
                                       rec["usage"]["chunks"]],
                            "steps": [g["usage"]["steps"],
                                      rec["usage"]["steps"]]})
    zero_resteps = not resteps and resumed_ok

    rec = {
        "bench": "serve_resume_lab",
        "config": {"requests": args.requests, "lanes": args.lanes,
                   "chunk": args.chunk, "dispatch_depth": args.depth,
                   "ckpt_interval": CKPT_INTERVAL},
        "golden_wall_s": round(golden_wall, 3),
        "killed_wall_s": round(killed_wall, 3),
        "resume_wall_s": round(resume_wall, 3),
        "recovery_overhead_s": round(recovery_s, 4),
        "cut": {"generation": survivor["generation"],
                "boundaries": survivor["boundaries"],
                "of_total_boundaries": final_boundaries,
                "inflight": len(survivor["inflight"]),
                "queued": len(survivor["queued"]),
                "done": len(survivor["done"])},
        "resumed_requests": len(resumed),
        "resumed_bit_identical": bit_identical,
        "zero_resteps": zero_resteps,
        "restep_witnesses": resteps[:5],
        "resumed_requests_recovered": recovered_all,
    }
    write_atomic(Path(args.out), rec)
    print(json.dumps(rec, indent=2))
    passed = bit_identical and zero_resteps and recovered_all
    print(f"serve_resume_lab: {'OK' if passed else 'FAILED'} — killed at "
          f"gen {survivor['generation']} (boundary "
          f"{survivor['boundaries']}/{final_boundaries}), "
          f"{len(survivor['inflight'])} in-flight + "
          f"{len(survivor['queued'])} queued resumed in {recovery_s:.3f}s "
          f"overhead; {sum(identical)}/{len(identical)} npz byte-identical")
    return 0 if passed else 1


if __name__ == "__main__":
    sys.exit(main())
