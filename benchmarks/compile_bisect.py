"""Bisect the fuse-depth compile cliff on the default sharded path.

Round-3 open question (VERDICT r3 weak #3, memory `fuse32-compile-cliff`):
the 16384^2 sharded fuse=32 case sat >25 min without completing — Mosaic
compile cliff, or the tunnel wedge that hit at the same time? When this
lab was written the auto depth planner picked k*=32 for exactly that
config, so if it IS a compile cliff, the DEFAULT flagship run stalled.
(Round 5 capped the auto depth at the kernel's per-pass chunk — the
flagship default is now k=16, 471 s measured live — so k=32 rows here
describe the EXPLICIT --fuse-steps 32 program; the curve remains the
guard-budget evidence for every depth a user can request.)

This lab answers it directly: for k in {8, 16, 20, 24, 28, 32} it times
`advance.lower(...).compile()` of the real padded-carry flagship program
(16384^2 f32, 1x1 mesh, 500-step chunk — byte-identical to what
`run_all.py` row 3 compiles) in a per-k SUBPROCESS under a hard timeout,
so a wedged compile costs one row, not the phase. Lowering uses a
sharded ShapeDtypeStruct — no device buffers, no H2D: the row measures
compile time alone (plus the tunnel's program-transfer cost, which the
real user pays too).

Each k runs against a FRESH compile cache dir by default (true cold
compile; `--cache shared` measures the warm-cache behavior real reruns
see). Rows land incrementally+atomically in benchmarks/compile_bisect.json.

Run on chip: ``python benchmarks/compile_bisect.py``
CPU smoke (interpret-mode, validates the harness only):
``python benchmarks/compile_bisect.py --smoke``
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))

N = 16384
STEPS = 500  # run_all row 3's chunk: drive compiles the whole solve as one
KS = (8, 16, 20, 24, 28, 32)


def child(k: int, n: int, steps: int, smoke: bool,
          topology: str | None = None, uncap: bool = False) -> None:
    """One compile measurement. ``topology`` set = AOT topology mode: no
    chip (and no tunnel) involved — the XLA:TPU + Mosaic compilers run
    locally against a virtual v5e:2x2, with n doubled so the LOCAL shard
    (and hence the Mosaic kernel program, the suspected cliff) is
    byte-identical to the flagship 16384^2 1x1 case. This isolates a
    compiler cliff from a tunnel wedge by construction."""
    import contextlib

    import jax

    from jax.sharding import NamedSharding, PartitionSpec as P

    from heat_tpu.backends.sharded import make_padded_carry_machinery
    from heat_tpu.config import HeatConfig

    if smoke or topology:
        jax.config.update("jax_platforms", "cpu")

    if topology:
        import math

        from jax.experimental import topologies

        from heat_tpu.ops.pallas_stencil import force_compiled_kernels

        topo = topologies.get_topology_desc(topology, "tpu")
        ndev = len(topo.devices)
        s = math.isqrt(ndev)
        if s * s != ndev:
            raise SystemExit(
                f"--topology {topology} has {ndev} devices; the bisect "
                f"needs a SQUARE mesh so the local shard stays n x n "
                f"(the flagship kernel program) — use e.g. v5e:2x2")
        mesh_shape = (s, s)
        n_glob = n * s  # local shard stays n x n — the flagship kernel
        mesh = topologies.make_mesh(topo, mesh_shape, ("x", "y"))
        ctx = force_compiled_kernels()
    else:
        from heat_tpu.parallel.mesh import build_mesh

        mesh_shape = (1, 1)
        n_glob = n
        mesh = build_mesh(2, mesh_shape)
        ctx = contextlib.nullcontext()

    # pin the Pallas kernel in BOTH modes: on-chip "auto" would resolve to
    # pallas anyway (f32 on TPU), but in topology mode default_backend()
    # is cpu, so "auto" silently bisects the XLA program — the round-4
    # retracted-curve bug (flat 5-14 s "curves" that were the XLA path
    # while the real Mosaic compile wedged >30 min). deep_fuse_proven
    # requires the row to carry local_kernel == "pallas".
    lk = "pallas"
    cfg = HeatConfig(n=n_glob, ntime=steps, dtype="float32",
                     backend="sharded", mesh_shape=mesh_shape, fuse_steps=k,
                     local_kernel=lk)
    if uncap:
        from heat_tpu.ops import pallas_stencil as _ps

        _ps._THIN_DEEP_BAND_CAP_BYTES = 1 << 60
        for clear in (_ps._plan_2d.cache_clear, _ps._plan_3d.cache_clear):
            clear()
    with ctx:
        _, advance, _ = make_padded_carry_machinery(cfg, mesh)
        padded = jax.ShapeDtypeStruct(
            tuple(n_glob + 2 * k * s for s in mesh_shape), "float32",
            sharding=NamedSharding(mesh, P(*mesh.axis_names)))
        t0 = time.perf_counter()
        lowered = advance.lower(padded, steps)
        t_lower = time.perf_counter() - t0
        t0 = time.perf_counter()
        compiled = lowered.compile()
        t_compile = time.perf_counter() - t0
    # Program fingerprint for the compile-cost curve (VERDICT r4 weak #3:
    # a non-monotone curve needs a CAUSE): how many Mosaic kernel calls
    # the program makes and how many DISTINCT kernel bodies Mosaic had to
    # compile — k=32 chunks into two unroll-16 passes at the thin cap, so
    # if both passes share one body its compile should NOT cost more than
    # k=16's single pass.
    census = {}
    try:
        from _util import custom_call_census

        # Mosaic call lines carry custom_call_target="tpu_custom_call"
        # (backend_config uses BRACE syntax in this XLA — a first cut
        # assumed the quoted form and recorded mosaic_calls=0 against
        # visibly custom-call-bearing programs). Shared helper with the
        # labeled line-hash fallback so a printer-syntax change can never
        # regress to confident zeros again.
        census = custom_call_census(compiled.as_text(), "custom-call",
                                    r'custom_call_target="([^"]*)".*')
    except Exception as e:  # census is best-effort; the timing is the row
        census = {"census_error": f"{type(e).__name__}: {e}"}
    print(json.dumps({"k": k, "n_local": n, "lower_s": t_lower,
                      "compile_s": t_compile, "local_kernel": lk,
                      "uncapped": uncap,
                      "platform": jax.default_backend(),
                      "topology": topology, **census}), flush=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="CPU interpret mode, tiny size (harness check)")
    ap.add_argument("--child", type=int, help="run one k inline (internal)")
    ap.add_argument("--timeout", type=int, default=900,
                    help="seconds per k before the row is declared wedged")
    ap.add_argument("--cache", choices=("fresh", "shared"), default="fresh",
                    help="fresh: cold-compile each k in its own cache dir; "
                         "shared: reuse the persistent cache (warm behavior)")
    ap.add_argument("--topology", nargs="?", const="v5e:2x2", default=None,
                    help="AOT topology mode: compile the flagship-shard "
                         "program locally against a virtual TPU topology — "
                         "no chip/tunnel involved, isolating compiler "
                         "cliffs from tunnel wedges")
    ap.add_argument("--ks", default=",".join(str(k) for k in KS))
    ap.add_argument("--uncap", action="store_true",
                    help="disable the planner's thin-band deep-unroll "
                         "compile cap for this measurement (to put the "
                         "uncapped wedge on record; expect the row to "
                         "blow its timeout)")
    ap.add_argument("--n", type=int, default=None,
                    help="LOCAL shard extent (default 16384; topology mode "
                         "scales the global so the local stays n x n). "
                         "8192 probes the thin-band deep-unroll family")
    args = ap.parse_args()

    n = args.n or (512 if args.smoke else N)
    steps = 32 if args.smoke else STEPS
    if args.child is not None:
        child(args.child, n, steps, args.smoke, topology=args.topology,
              uncap=args.uncap)
        return

    from _util import write_atomic

    suffix = f"_n{n}" if args.n and n != N else ""
    out = Path(__file__).parent / (
        "compile_bisect_smoke.json" if args.smoke
        else f"compile_bisect_topology{suffix}.json" if args.topology
        else f"compile_bisect{suffix}.json")
    rec = {"ts": time.time(), "n": n, "steps": steps, "cache": args.cache,
           "topology": args.topology,
           "timeout_s": args.timeout, "rows": {}}
    try:  # partial re-runs (e.g. one wedged k) merge into the curve
        old = json.loads(out.read_text())
        if (old.get("n"), old.get("steps"), old.get("cache"),
                old.get("topology")) == (n, steps, args.cache,
                                         args.topology):
            rec["rows"].update(old.get("rows", {}))
    except (OSError, json.JSONDecodeError):
        pass

    for k in (int(s) for s in args.ks.split(",")):
        env = dict(os.environ)
        tmp = None
        if args.cache == "fresh":
            tmp = tempfile.mkdtemp(prefix=f"jax_cache_bisect_k{k}_")
            env["JAX_COMPILATION_CACHE_DIR"] = tmp
        else:
            from _util import ensure_cache_env
            ensure_cache_env()
            env["JAX_COMPILATION_CACHE_DIR"] = \
                os.environ["JAX_COMPILATION_CACHE_DIR"]
        cmd = [sys.executable, __file__, "--child", str(k),
               "--n", str(n)]  # MUST forward: the first n8192 curve forgot
        # this and silently re-measured the 16384-local program under an
        # 8192 label (caught in review; artifact deleted)
        if args.smoke:
            cmd.append("--smoke")
        if args.topology:
            cmd.extend(["--topology", args.topology])
        if args.uncap:
            cmd.append("--uncap")
        t0 = time.time()
        try:
            p = subprocess.run(cmd, timeout=args.timeout, env=env,
                               capture_output=True, text=True)
            row = None
            for line in reversed((p.stdout or "").strip().splitlines()):
                try:
                    row = json.loads(line)
                    break
                except json.JSONDecodeError:
                    continue
            if row is None:
                tail = ((p.stderr or "") + (p.stdout or "")).splitlines()[-3:]
                row = {"k": k, "error": f"rc={p.returncode}: "
                       + " | ".join(tail)}
        except subprocess.TimeoutExpired:
            row = {"k": k, "error": f"WEDGED: no compile within "
                   f"{args.timeout}s (killed)"}
        row["wall_s"] = time.time() - t0
        # uncapped wedge-probe rows must not clobber the capped curve
        rec["rows"][f"{k}_uncapped" if args.uncap else str(k)] = row
        msg = (f"compile k={k}: " +
               (f"lower {row['lower_s']:.1f}s compile {row['compile_s']:.1f}s"
                if "compile_s" in row else row["error"]))
        print(msg, flush=True)
        write_atomic(out, rec)
        if tmp:
            subprocess.run(["rm", "-rf", tmp], check=False)
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
