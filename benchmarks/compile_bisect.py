"""Bisect the fuse-depth compile cliff on the default sharded path.

Round-3 open question (VERDICT r3 weak #3, memory `fuse32-compile-cliff`):
the 16384^2 sharded fuse=32 case sat >25 min without completing — Mosaic
compile cliff, or the tunnel wedge that hit at the same time? The auto
depth planner (`fuse_depth_sharded`) picks k*=32 for exactly that config,
so if it IS a compile cliff, the DEFAULT flagship run stalls.

This lab answers it directly: for k in {8, 16, 20, 24, 28, 32} it times
`advance.lower(...).compile()` of the real padded-carry flagship program
(16384^2 f32, 1x1 mesh, 500-step chunk — byte-identical to what
`run_all.py` row 3 compiles) in a per-k SUBPROCESS under a hard timeout,
so a wedged compile costs one row, not the phase. Lowering uses a
sharded ShapeDtypeStruct — no device buffers, no H2D: the row measures
compile time alone (plus the tunnel's program-transfer cost, which the
real user pays too).

Each k runs against a FRESH compile cache dir by default (true cold
compile; `--cache shared` measures the warm-cache behavior real reruns
see). Rows land incrementally+atomically in benchmarks/compile_bisect.json.

Run on chip: ``python benchmarks/compile_bisect.py``
CPU smoke (interpret-mode, validates the harness only):
``python benchmarks/compile_bisect.py --smoke``
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))

N = 16384
STEPS = 500  # run_all row 3's chunk: drive compiles the whole solve as one
KS = (8, 16, 20, 24, 28, 32)


def child(k: int, n: int, steps: int, smoke: bool) -> None:
    if smoke:
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    from heat_tpu.backends.sharded import make_padded_carry_machinery
    from heat_tpu.config import HeatConfig
    from heat_tpu.parallel.mesh import build_mesh
    from jax.sharding import NamedSharding, PartitionSpec as P

    cfg = HeatConfig(n=n, ntime=steps, dtype="float32", backend="sharded",
                     mesh_shape=(1, 1), fuse_steps=k)
    mesh = build_mesh(cfg.ndim, cfg.mesh_shape)
    _, advance, _ = make_padded_carry_machinery(cfg, mesh)
    padded = jax.ShapeDtypeStruct(
        (n + 2 * k, n + 2 * k), "float32",
        sharding=NamedSharding(mesh, P(*mesh.axis_names)))
    t0 = time.perf_counter()
    lowered = advance.lower(padded, steps)
    t_lower = time.perf_counter() - t0
    t0 = time.perf_counter()
    lowered.compile()
    t_compile = time.perf_counter() - t0
    print(json.dumps({"k": k, "lower_s": t_lower, "compile_s": t_compile,
                      "platform": jax.default_backend()}), flush=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="CPU interpret mode, tiny size (harness check)")
    ap.add_argument("--child", type=int, help="run one k inline (internal)")
    ap.add_argument("--timeout", type=int, default=900,
                    help="seconds per k before the row is declared wedged")
    ap.add_argument("--cache", choices=("fresh", "shared"), default="fresh",
                    help="fresh: cold-compile each k in its own cache dir; "
                         "shared: reuse /tmp/jax_cache (warm behavior)")
    ap.add_argument("--ks", default=",".join(str(k) for k in KS))
    args = ap.parse_args()

    n = 512 if args.smoke else N
    steps = 32 if args.smoke else STEPS
    if args.child is not None:
        child(args.child, n, steps, args.smoke)
        return

    from _util import write_atomic

    out = Path(__file__).parent / (
        "compile_bisect_smoke.json" if args.smoke else "compile_bisect.json")
    rec = {"ts": time.time(), "n": n, "steps": steps, "cache": args.cache,
           "timeout_s": args.timeout, "rows": {}}

    for k in (int(s) for s in args.ks.split(",")):
        env = dict(os.environ)
        tmp = None
        if args.cache == "fresh":
            tmp = tempfile.mkdtemp(prefix=f"jax_cache_bisect_k{k}_")
            env["JAX_COMPILATION_CACHE_DIR"] = tmp
        else:
            env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")
        cmd = [sys.executable, __file__, "--child", str(k)]
        if args.smoke:
            cmd.append("--smoke")
        t0 = time.time()
        try:
            p = subprocess.run(cmd, timeout=args.timeout, env=env,
                               capture_output=True, text=True)
            row = None
            for line in reversed((p.stdout or "").strip().splitlines()):
                try:
                    row = json.loads(line)
                    break
                except json.JSONDecodeError:
                    continue
            if row is None:
                tail = ((p.stderr or "") + (p.stdout or "")).splitlines()[-3:]
                row = {"k": k, "error": f"rc={p.returncode}: "
                       + " | ".join(tail)}
        except subprocess.TimeoutExpired:
            row = {"k": k, "error": f"WEDGED: no compile within "
                   f"{args.timeout}s (killed)"}
        row["wall_s"] = time.time() - t0
        rec["rows"][str(k)] = row
        msg = (f"compile k={k}: " +
               (f"lower {row['lower_s']:.1f}s compile {row['compile_s']:.1f}s"
                if "compile_s" in row else row["error"]))
        print(msg, flush=True)
        write_atomic(out, rec)
        if tmp:
            subprocess.run(["rm", "-rf", tmp], check=False)
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
