"""512^3 sharded-1x1x1 no-regression check (VERDICT r2 item 7).

The rank-aware fuse-depth cap (3D auto depth now clamps at _KMAX_3D=8
instead of borrowing the 2D _KMAX_2D=32) changes the exchange width the
sharded backend picks for 3D shards. This measures the sharded backend
at 512^3 on the degenerate 1x1x1 mesh — auto depth and the old depth-32
request side by side — so the cap change is pinned to a measured
improvement (or at least no regression) rather than a model.

Writes benchmarks/sharded3d_check.json. Run on the real chip.
"""

from __future__ import annotations

import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))


def measure(fuse_steps: int | None, n=512, steps=960):
    from heat_tpu.backends.sharded import solve as sharded_solve
    from heat_tpu.config import HeatConfig

    cfg = HeatConfig(n=n, ndim=3, ntime=steps, dtype="float32",
                     backend="sharded", mesh_shape=(1, 1, 1),
                     sigma=1 / 6, fuse_steps=fuse_steps or 0)
    res = sharded_solve(cfg, fetch=False, warm_exec=True,
                        two_point_repeats=2)
    tp = res.timing.points_per_s_two_point or res.timing.points_per_s
    return {"fuse_steps_requested": fuse_steps or "auto",
            "points_per_s": res.timing.points_per_s,
            "points_per_s_two_point": tp,
            "solve_s": res.timing.solve_s}


def main():
    import jax

    out = Path(__file__).parent / "sharded3d_check.json"
    from _util import write_atomic

    rec = {"ts": time.time(), "platform": jax.default_backend(), "rows": []}

    def flush():
        write_atomic(out, rec)

    for fuse in (None, 8, 32):  # auto (==8 after the cap), the cap, the old 2D-borrowed depth
        row = measure(fuse)
        rec["rows"].append(row)
        print(f"sharded 512^3 1x1x1 fuse={row['fuse_steps_requested']}: "
              f"{row['points_per_s_two_point']:.3e} pts/s two-point "
              f"({row['solve_s']:.2f}s solve)", flush=True)
        flush()
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
