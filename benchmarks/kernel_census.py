"""Program-structure census for the compile-cost curve — lowering level.

VERDICT r4 weak #3: the 16384-local bisect curve is non-monotone
(k=8/16/32 cold-compile 393/980/665 s round 4; 780/2038/1133 s in the
round-5 re-measure — uniformly inflated by host contention, same shape)
and a curve used to justify ``_SAFE_FUSE`` needs a cause.

This lab characterizes the PRE-BACKEND structure: ``advance.lower(...)``
emits the StableHLO module in seconds, Mosaic custom calls included.
Measured round 5: **every k in {8,16,32} lowers to the same structure —
2 Mosaic calls, 2 distinct payloads** (the fused steady body + the
500-step remainder body; ``_thin_chunk_cap`` chunking reuses one body
per pass at this level). The post-compile census of the same k=32
program records 4 calls over 3 distinct bodies
(``compile_bisect_topology.json``), so the backend DUPLICATES AND
SPECIALIZES bodies after lowering — the two censuses are complementary
views, and only the post-compile one says what Mosaic actually built.
Consequence for the inversion: pass count cannot explain k=16 costing
2.6x k=8 (identical lowered structure); the cost difference lives in
per-body geometry (wpad changes n_pad/tile) and backend specialization.

Run (chipless, seconds per k): ``python benchmarks/kernel_census.py``
Writes benchmarks/kernel_census.json.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))

from _util import custom_call_census, write_atomic  # noqa: E402

N_LOCAL = 16384
KS = (8, 16, 32)


def lowered_census(txt: str) -> dict:
    """Census of the LOWERED (StableHLO) module — pre-backend structure
    only; see the module docstring for why this differs from (and does
    not replace) the post-compile census."""
    return custom_call_census(txt, "stablehlo.custom_call",
                              r"@([\w.]+).*")


def main() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp  # noqa: F401
    from jax.experimental import topologies
    from jax.sharding import NamedSharding, PartitionSpec as P

    from heat_tpu.backends.sharded import make_padded_carry_machinery
    from heat_tpu.config import HeatConfig
    from heat_tpu.ops.pallas_stencil import force_compiled_kernels

    topo = topologies.get_topology_desc("v5e:2x2", "tpu")
    mesh = topologies.make_mesh(topo, (2, 2), ("x", "y"))
    n_glob = N_LOCAL * 2

    out = Path(__file__).parent / "kernel_census.json"
    rec = {"ts": time.time(), "n_local": N_LOCAL, "topology": "v5e:2x2",
           "local_kernel": "pallas", "steps": 500, "rows": {}}

    with force_compiled_kernels():
        for k in KS:
            cfg = HeatConfig(n=n_glob, ntime=500, dtype="float32",
                             backend="sharded", mesh_shape=(2, 2),
                             fuse_steps=k, local_kernel="pallas")
            _, advance, _ = make_padded_carry_machinery(cfg, mesh)
            struct = jax.ShapeDtypeStruct(
                tuple(n_glob + 2 * k * s for s in (2, 2)), "float32",
                sharding=NamedSharding(mesh, P("x", "y")))
            t0 = time.perf_counter()
            txt = advance.lower(struct, 500).as_text()
            row = lowered_census(txt)
            row["lower_s"] = time.perf_counter() - t0
            rec["rows"][str(k)] = row
            print(f"k={k}: {row}", flush=True)
            write_atomic(out, rec)
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
