"""Compile-validate the per-chip machine model on every chip class — chiplessly.

VERDICT r3 #4's residual risk: the v4/v5p/v6e entries in
``heat_tpu/machine.py`` are spec-derived ("uncalibrated") — if a VMEM
ceiling or band budget is wrong for a chip, the planner's geometry might
not even compile there. The AOT topology compilers for all four chip
classes ship in libtpu, so that risk is checkable without hardware:

- **Section A (chip tables)**: for each chip class, activate its machine
  model (``machine.override``), let the planners pick geometry for the
  flagship-scale shard, and compile the real sharded advance against
  that chip's topology. Records compile time, the planner's plan string,
  and the compiler's own memory analysis (per-chip argument/output/temp
  bytes — the true VMEM/HBM verdict, not the planner's estimate).
- **Section B (north star)**: BASELINE.md's weak-scaling scenario —
  config 5 (32768^2 bf16+f32acc) on a 16-chip v5p 4x4 mesh, 8192^2
  local block — compiled end to end. The projection's program is now
  compiler-verified, not just arithmetic.

Run (anywhere; no chip): ``python benchmarks/topology_validate.py``
One libtpu process at a time (/tmp/libtpu_lockfile).
Writes benchmarks/topology_validate.json (atomic, incremental).
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))

from _util import write_atomic  # noqa: E402

# (chip kind for machine.override, topology name, mesh shape)
CASES = [
    ("TPU v5 lite", "v5e:2x2", (2, 2)),
    ("TPU v5", "v5p:2x2x1", (2, 2)),
    ("TPU v4", "v4:2x2x1", (4, 2)),
    ("TPU v6 lite", "v6e:2x2", (2, 2)),
]


def _mem(compiled) -> dict:
    try:
        m = compiled.memory_analysis()
        return {k: int(getattr(m, k)) for k in (
            "argument_size_in_bytes", "output_size_in_bytes",
            "temp_size_in_bytes", "generated_code_size_in_bytes")
            if hasattr(m, k)}
    except Exception as e:  # memory analysis is best-effort
        return {"error": f"{type(e).__name__}: {e}"}


def _compile_case(topology, mesh_shape, cfg, steps):
    import jax
    import jax.numpy as jnp
    from jax.experimental import topologies
    from jax.sharding import NamedSharding, PartitionSpec as P

    from heat_tpu.backends.sharded import (fuse_depth_sharded,
                                           make_padded_carry_machinery)
    from heat_tpu.utils import jnp_dtype

    names = tuple("xyz"[: len(mesh_shape)])
    topo = topologies.get_topology_desc(topology, "tpu")
    try:
        mesh = topologies.make_mesh(topo, mesh_shape, names)
    except AssertionError:
        # v4-era topology descriptors expose per-core devices and
        # mesh_utils insists on megacore (one-device-per-chip)
        # granularity. A naive reshape placement is fine here: this lab
        # validates COMPILATION (VMEM/memory verdicts), no wire traffic
        # ever flows.
        import math as _math

        import numpy as _np
        from jax.sharding import Mesh

        need = _math.prod(mesh_shape)
        mesh = Mesh(_np.asarray(topo.devices[:need]).reshape(mesh_shape),
                    names)
    kf = fuse_depth_sharded(cfg, mesh_shape)
    _, advance, _ = make_padded_carry_machinery(cfg, mesh)
    struct = jax.ShapeDtypeStruct(
        tuple(cfg.n + 2 * kf * s for s in mesh_shape), jnp_dtype(cfg.dtype),
        sharding=NamedSharding(mesh, P(*mesh.axis_names)))
    t0 = time.perf_counter()
    compiled = advance.lower(struct, steps).compile()
    return compiled, time.perf_counter() - t0, kf


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--local", type=int, default=4096,
                    help="target local-shard extent for section A (4096 "
                         "keeps per-case Mosaic compiles ~minutes; 8192 "
                         "exercises the thin-band family's capped chunks "
                         "at ~16 min/case — see "
                         "compile_bisect_topology_n8192.json)")
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")

    from heat_tpu import machine
    from heat_tpu.backends.sharded import fuse_depth_sharded
    from heat_tpu.config import HeatConfig
    from heat_tpu.ops.pallas_stencil import (force_compiled_kernels,
                                             plan_summary)

    out = Path(__file__).parent / "topology_validate.json"
    rec = {"ts": time.time(), "local": args.local, "rows": {}}

    with force_compiled_kernels():
        for kind, topology, mesh_shape in CASES:
            machine.override(kind)
            chip = machine.current()
            n = args.local * mesh_shape[0]  # row-axis local = args.local
            cfg = HeatConfig(n=n, ntime=64, dtype="float32",
                             backend="sharded", mesh_shape=mesh_shape,
                             local_kernel="pallas")
            local_shape = tuple(n // s for s in mesh_shape)
            # summarize the LOCAL KERNEL plan at the EXCHANGE fuse depth
            # the compile below actually uses — one k, two labeled
            # concepts (the round-4 artifact described the plan at a
            # hardcoded 32 and recorded the exchange depth under the same
            # word "fuse", reading as a self-contradiction; VERDICT r4 #5)
            kf_ex = fuse_depth_sharded(cfg, mesh_shape)
            row = {"chip": chip.label, "topology": topology,
                   "mesh": list(mesh_shape), "n": n,
                   "plan": plan_summary(local_shape, "float32", kf_ex)}
            try:
                compiled, dt, kf = _compile_case(topology, mesh_shape,
                                                 cfg, 64)
                row.update(compile_s=dt, fuse_exchange=kf,
                           memory=_mem(compiled))
                print(f"{chip.label:20s} {topology:10s} n={n} fuse={kf}: "
                      f"compile {dt:.0f}s  mem={row['memory']}", flush=True)
            except Exception as e:
                row["error"] = f"{type(e).__name__}: {str(e)[:300]}"
                print(f"{chip.label:20s} {topology:10s} FAILED: "
                      f"{row['error'][:160]}", flush=True)
            rec["rows"][f"A_{topology}"] = row
            write_atomic(out, rec)
            machine.override(None)

        # Section B: the BASELINE.md north star, compiler-verified.
        machine.override("TPU v5p")
        cfg5 = HeatConfig(n=32768, ntime=64, dtype="bfloat16",
                          backend="sharded", mesh_shape=(4, 4),
                          local_kernel="pallas")
        kf_ex = fuse_depth_sharded(cfg5, (4, 4))
        row = {"chip": machine.current().label, "topology": "v5p:4x4x1",
               "mesh": [4, 4], "n": 32768, "dtype": "bfloat16",
               "plan": plan_summary((8192, 8192), "bfloat16", kf_ex)}
        try:
            compiled, dt, kf = _compile_case("v5p:4x4x1", (4, 4), cfg5, 64)
            row.update(compile_s=dt, fuse_exchange=kf, memory=_mem(compiled))
            print(f"north-star v5p-16 32768^2 bf16 fuse={kf}: compile "
                  f"{dt:.0f}s  mem={row['memory']}", flush=True)
        except Exception as e:
            row["error"] = f"{type(e).__name__}: {str(e)[:300]}"
            print(f"north-star FAILED: {row['error'][:160]}", flush=True)
        rec["rows"]["B_northstar_v5p16"] = row
        machine.override(None)
        write_atomic(out, rec)
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
