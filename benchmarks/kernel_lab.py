"""Kernel experiments: candidate Pallas stencil designs, measured on the
real chip. Not part of the framework — a lab bench for pallas_stencil.py
tuning (results feed _plan_3d / band budgets there).

Run: PYTHONPATH=/root/repo:/root/.axon_site python benchmarks/kernel_lab.py <exp>
"""

from __future__ import annotations

import functools
import sys
import time

import jax

if "--cpu" in sys.argv:  # interpret-mode checks during tunnel outages;
    sys.argv.remove("--cpu")  # the env var alone is re-pinned by the
    jax.config.update("jax_platforms", "cpu")  # site hook (TROUBLESHOOTING)
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

if not hasattr(pltpu, "CompilerParams"):  # pre-rename JAX spells it
    pltpu.CompilerParams = pltpu.TPUCompilerParams  # TPUCompilerParams

import pathlib

if str(pathlib.Path(__file__).resolve().parent.parent) not in sys.path:
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

# persistent compile cache: kernel sweeps re-run the same programs across
# lab sessions; compiles here run tens of seconds to minutes. Honor a
# user-set JAX_COMPILATION_CACHE_DIR; default per-user (ADVICE r4 —
# ensure_cache_env also pushes into the live jax config, since jax is
# already imported here)
from heat_tpu.utils import ensure_cache_env  # noqa: E402

ensure_cache_env()
jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
from heat_tpu import machine  # noqa: E402

# the framework's Mosaic VMEM ceiling for this chip — lab kernels must
# compile under the SAME limit as ops/pallas_stencil.py or lab-measured
# feasibility doesn't transfer to the planner these sweeps tune
VMEM_LIMIT = machine.current().vmem_limit_bytes


def _roof(dtype) -> float:
    """One-pass HBM roofline for the current chip class (heat_tpu.machine)."""
    return machine.current().roofline_points_per_s(dtype)


# The terminal-side libtpu's Mosaic backend does not implement
# tpu.dynamic_rotate on sub-32-bit vectors: "not implemented: Rotate with
# non-32-bit data" (first surfaced with a readable message 2026-08-02 —
# the remote-compile helper used to collapse it to an opaque "HTTP 500:
# tpu_compile_helper subprocess exit code 1", which round 5 initially
# triaged as a helper/scale failure). The venv's OWN libtpu (0.0.34, the
# chipless AOT path bf16_variant_compile_check.py drives) DOES compile
# the same kernels — a backend version skew, not a kernel bug. The
# bf16native/bf16fma variants roll in bf16 BY DESIGN (that is the
# half-byte-traffic hypothesis under test), so on backends with this
# limitation they are expected-unsupported: checks report and continue,
# and any OTHER failure still fails the run.
_BF16_ROTATE_UNSUPPORTED = "Rotate with non-32-bit data"


# the variants that roll IN bf16 by design — the only ones for which the
# 32-bit-only dynamic_rotate limitation is an EXPECTED outcome
_BF16_ROLLING_VARIANTS = {"bf16native", "bf16fma"}


def _expected_unsupported(e: BaseException, variant=None, dtype=None) -> bool:
    """Is ``e`` the known backend limitation, AND did the failing config
    actually roll sub-32-bit data? The error-string match alone let a
    32-bit variant (shrink/rolled/rolledfma on f32) silently pass a
    correctness check if it ever regressed into this message (e.g. via a
    future sub-32-bit mask); the variant/dtype gate is primary, the string
    match secondary (ADVICE r5). Callers without config context (the bench
    loops' failure LABELING, which suppresses nothing) pass neither."""
    if _BF16_ROTATE_UNSUPPORTED not in str(e):
        return False
    if variant is None and dtype is None:
        return True  # labeling-only call: no suppression rides on this
    if variant in _BF16_ROLLING_VARIANTS:
        return True
    return dtype is not None and jnp.dtype(dtype).itemsize < 4


def _failure_tag(e: BaseException) -> str:
    """One classification for every bench's except block — the honest
    label for the known backend limitation, the raw error otherwise."""
    if _expected_unsupported(e):
        return ("UNSUPPORTED (Mosaic dynamic_rotate is 32-bit-only on "
                "this backend)")
    return f"FAILED {type(e).__name__}: {str(e)[:200]}"


def _round_up(x, m):
    return ((x + m - 1) // m) * m


def measure_rate(c, dev, points_times_steps, repeats=2):
    """(pts/s corrected, pts/s raw) — the framework's shared two-point
    overhead-cancelling protocol (one measurement definition for the lab
    benches AND the headline bench.py; see runtime/timing.py)."""
    import pathlib
    import sys as _sys

    _sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    from heat_tpu.runtime.timing import two_point_rate

    return two_point_rate(c, dev, points_times_steps, repeats)


# ---------------------------------------------------------------------------
# candidate: (row, mid)-tiled 3D kernel, 3x3 halo blocks, shrinking slices
# ---------------------------------------------------------------------------


def make_3d_tiled(r, R, M, k, km, shape_pad, ksteps, n_logical):
    m_pad, mid_pad, n_pad = shape_pad
    rows = R + 2 * k
    mids = M + 2 * km

    def kernel(bounds_ref, c00, c01, c02, c10, c11, c12, c20, c21, c22,
               out_ref):
        i = pl.program_id(0)
        j = pl.program_id(1)
        store_dt = out_ref.dtype
        acc_dt = jnp.float32
        top = jnp.concatenate([c00[:], c01[:], c02[:]], axis=1)
        mid = jnp.concatenate([c10[:], c11[:], c12[:]], axis=1)
        bot = jnp.concatenate([c20[:], c21[:], c22[:]], axis=1)
        band = jnp.concatenate([top, mid, bot], axis=0).astype(acc_dt)

        bshape = (rows, mids, n_pad)
        grow = i * R - k + jax.lax.broadcasted_iota(jnp.int32, bshape, 0)
        gmid = j * M - km + jax.lax.broadcasted_iota(jnp.int32, bshape, 1)
        gcol = jax.lax.broadcasted_iota(jnp.int32, bshape, 2)
        frozen = (
            (grow <= bounds_ref[0, 0]) | (grow >= bounds_ref[0, 1])
            | (gmid <= bounds_ref[0, 2]) | (gmid >= bounds_ref[0, 3])
            | (gcol <= bounds_ref[0, 4]) | (gcol >= bounds_ref[0, 5])
        )
        maskr = jnp.where(frozen, 0.0, r).astype(acc_dt)

        cur = band
        for s in range(ksteps):
            lf = pltpu.roll(cur, 1, 2)
            rt = pltpu.roll(cur, n_pad - 1, 2)
            ctr = cur[1:-1, 1:-1, :]
            lap = (cur[2:, 1:-1, :] + cur[:-2, 1:-1, :]
                   + cur[1:-1, 2:, :] + cur[1:-1, :-2, :]
                   + lf[1:-1, 1:-1, :] + rt[1:-1, 1:-1, :]
                   - 6.0 * ctr)
            m_s = maskr[s + 1: rows - s - 1, s + 1: mids - s - 1, :]
            cur = ctr + m_s * lap
        ro = k - ksteps
        mo = km - ksteps
        out_ref[:] = jax.lax.slice(
            cur, (ro, mo, 0), (ro + R, mo + M, n_pad)).astype(store_dt)

    return kernel


@functools.partial(jax.jit,
                   static_argnames=("r", "ksteps", "R", "M", "k", "km",
                                    "logical"))
def pallas_3d_tiled(Tp, r, ksteps, R, M, k, km, logical,
                    bounds=None):
    m_pad, mid_pad, n_pad = Tp.shape
    m, mid, n = logical
    assert m_pad % R == 0 and mid_pad % M == 0
    assert R % k == 0 and M % km == 0 and ksteps <= min(k, km)
    if bounds is None:
        bounds = jnp.asarray([[0, m - 1, 0, mid - 1, 0, n - 1]], jnp.int32)
    bounds = bounds.reshape(1, 6).astype(jnp.int32)
    gr, gm = m_pad // R, mid_pad // M
    rr, rm = R // k, M // km
    nrb, nmb = m_pad // k, mid_pad // km
    smem = pl.BlockSpec((1, 6), lambda i, j: (0, 0), memory_space=pltpu.SMEM)

    def bs(shape, imap):
        return pl.BlockSpec(shape, imap, memory_space=pltpu.VMEM)

    def rclamp(i):
        return jnp.clip(i, 0, nrb - 1)

    def mclamp(j):
        return jnp.clip(j, 0, nmb - 1)

    in_specs = [
        smem,
        bs((k, km, n_pad), lambda i, j: (rclamp(i * rr - 1), mclamp(j * rm - 1), 0)),
        bs((k, M, n_pad), lambda i, j: (rclamp(i * rr - 1), j, 0)),
        bs((k, km, n_pad), lambda i, j: (rclamp(i * rr - 1), mclamp((j + 1) * rm), 0)),
        bs((R, km, n_pad), lambda i, j: (i, mclamp(j * rm - 1), 0)),
        bs((R, M, n_pad), lambda i, j: (i, j, 0)),
        bs((R, km, n_pad), lambda i, j: (i, mclamp((j + 1) * rm), 0)),
        bs((k, km, n_pad), lambda i, j: (rclamp((i + 1) * rr), mclamp(j * rm - 1), 0)),
        bs((k, M, n_pad), lambda i, j: (rclamp((i + 1) * rr), j, 0)),
        bs((k, km, n_pad), lambda i, j: (rclamp((i + 1) * rr), mclamp((j + 1) * rm), 0)),
    ]
    out = pl.pallas_call(
        make_3d_tiled(float(r), R, M, k, km, Tp.shape, ksteps, n),
        out_shape=jax.ShapeDtypeStruct(Tp.shape, Tp.dtype),
        grid=(gr, gm),
        in_specs=in_specs,
        out_specs=bs((R, M, n_pad), lambda i, j: (i, j, 0)),
        compiler_params=pltpu.CompilerParams(vmem_limit_bytes=VMEM_LIMIT),
        interpret=jax.default_backend() != "tpu",
    )(bounds, *([Tp] * 9))
    return out


# ---------------------------------------------------------------------------
# candidate: fully-ROLLED 3D body — the shipped 3D kernel shrink-slices the
# (row, mid) axes per mini-step; mid-axis slices are sublane-misaligned and
# are the remaining codegen suspect (the analogous 2D switch to rolls took
# bf16 32k from 58% to 90% of roofline). All three axes via pltpu.roll +
# masked multiplicative update; wrap corruption travels one cell per step,
# confined to the k/km margins (lane wrap lands in frozen ring / discard
# margin, same as the shipped kernel's lane rotates).
# ---------------------------------------------------------------------------


def make_3d_rolled(r, R, M, k, km, n_pad, ksteps, variant="f32"):
    rows = R + 2 * k
    mids = M + 2 * km
    assert variant in ("f32", "fma"), variant
    fma = variant == "fma"

    def kernel(bounds_ref, c00, c01, c02, c10, c11, c12, c20, c21, c22,
               out_ref):
        i = pl.program_id(0)
        j = pl.program_id(1)
        store_dt = out_ref.dtype
        acc_dt = jnp.float32
        top = jnp.concatenate([c00[:], c01[:], c02[:]], axis=1)
        mid = jnp.concatenate([c10[:], c11[:], c12[:]], axis=1)
        bot = jnp.concatenate([c20[:], c21[:], c22[:]], axis=1)
        band = jnp.concatenate([top, mid, bot], axis=0).astype(acc_dt)

        bshape = (rows, mids, n_pad)
        grow = i * R - k + jax.lax.broadcasted_iota(jnp.int32, bshape, 0)
        gmid = j * M - km + jax.lax.broadcasted_iota(jnp.int32, bshape, 1)
        gcol = jax.lax.broadcasted_iota(jnp.int32, bshape, 2)
        frozen = (
            (grow <= bounds_ref[0, 0]) | (grow >= bounds_ref[0, 1])
            | (gmid <= bounds_ref[0, 2]) | (gmid >= bounds_ref[0, 3])
            | (gcol <= bounds_ref[0, 4]) | (gcol >= bounds_ref[0, 5])
        )
        maskr = jnp.where(frozen, 0.0, r).astype(acc_dt)
        if fma:
            decay = (1.0 - 6.0 * maskr).astype(acc_dt)  # hoisted constant

        for _ in range(ksteps):
            up = pltpu.roll(band, 1, 0)
            dn = pltpu.roll(band, rows - 1, 0)
            no = pltpu.roll(band, 1, 1)
            so = pltpu.roll(band, mids - 1, 1)
            lf = pltpu.roll(band, 1, 2)
            rt = pltpu.roll(band, n_pad - 1, 2)
            if fma:
                band = decay * band + maskr * (up + dn + no + so + lf + rt)
            else:
                band = band + maskr * (up + dn + no + so + lf + rt
                                       - 6.0 * band)
        out_ref[:] = band[k: k + R, km: km + M, :].astype(store_dt)

    return kernel


@functools.partial(jax.jit,
                   static_argnames=("r", "ksteps", "R", "M", "k", "km",
                                    "logical", "variant"))
def pallas_3d_rolled(Tp, r, ksteps, R, M, k, km, logical, bounds=None,
                     variant="f32"):
    m_pad, mid_pad, n_pad = Tp.shape
    m, mid, n = logical
    assert m_pad % R == 0 and mid_pad % M == 0
    assert R % k == 0 and M % km == 0 and ksteps <= min(k, km)
    if bounds is None:
        bounds = jnp.asarray([[0, m - 1, 0, mid - 1, 0, n - 1]], jnp.int32)
    bounds = bounds.reshape(1, 6).astype(jnp.int32)
    gr, gm = m_pad // R, mid_pad // M
    rr, rm = R // k, M // km
    nrb, nmb = m_pad // k, mid_pad // km
    smem = pl.BlockSpec((1, 6), lambda i, j: (0, 0), memory_space=pltpu.SMEM)

    def bs(shape, imap):
        return pl.BlockSpec(shape, imap, memory_space=pltpu.VMEM)

    def rcl(i):
        return jnp.clip(i, 0, nrb - 1)

    def mcl(j):
        return jnp.clip(j, 0, nmb - 1)

    in_specs = [
        smem,
        bs((k, km, n_pad), lambda i, j: (rcl(i * rr - 1), mcl(j * rm - 1), 0)),
        bs((k, M, n_pad), lambda i, j: (rcl(i * rr - 1), j, 0)),
        bs((k, km, n_pad), lambda i, j: (rcl(i * rr - 1), mcl((j + 1) * rm), 0)),
        bs((R, km, n_pad), lambda i, j: (i, mcl(j * rm - 1), 0)),
        bs((R, M, n_pad), lambda i, j: (i, j, 0)),
        bs((R, km, n_pad), lambda i, j: (i, mcl((j + 1) * rm), 0)),
        bs((k, km, n_pad), lambda i, j: (rcl((i + 1) * rr), mcl(j * rm - 1), 0)),
        bs((k, M, n_pad), lambda i, j: (rcl((i + 1) * rr), j, 0)),
        bs((k, km, n_pad), lambda i, j: (rcl((i + 1) * rr), mcl((j + 1) * rm), 0)),
    ]
    return pl.pallas_call(
        make_3d_rolled(float(r), R, M, k, km, n_pad, ksteps, variant),
        out_shape=jax.ShapeDtypeStruct(Tp.shape, Tp.dtype),
        grid=(gr, gm),
        in_specs=in_specs,
        out_specs=bs((R, M, n_pad), lambda i, j: (i, j, 0)),
        compiler_params=pltpu.CompilerParams(vmem_limit_bytes=VMEM_LIMIT),
        interpret=jax.default_backend() != "tpu",
    )(bounds, *([Tp] * 9))


def check_3d_rolled():
    rng = np.random.default_rng(7)
    m, mid, n = 40, 24, 300
    T = rng.uniform(1, 2, (m, mid, n)).astype(np.float32)
    r = 0.15
    # km=8, not 4: the mid-axis halo block is the second-to-last dim of
    # its BlockSpec, and the TPU Pallas lowering requires the last two
    # block dims divisible by (8, 128) — a sub-sublane km only ever
    # worked in interpret mode (the shipped planner sublane-aligns km via
    # _round_up(k, _sublane); this toy geometry predates that rule and
    # failed its first real on-chip run, 2026-08-02). k=4 on the leading
    # axis is legal and stays, so the check still covers k != km.
    k, km = 4, 8
    R, M = 8, 8
    m_pad = _round_up(m, R)
    mid_pad = _round_up(mid, M)
    n_pad = _round_up(n, 128)
    Tp = jnp.pad(jnp.asarray(T), ((0, m_pad - m), (0, mid_pad - mid),
                                  (0, n_pad - n)))
    for variant in ("f32", "fma"):
        for ks in (1, 3, 4):
            out = pallas_3d_rolled(Tp, r=r, ksteps=ks, R=R, M=M, k=k, km=km,
                                   logical=(m, mid, n),
                                   variant=variant)[:m, :mid, :n]
            ref = ref_steps(jnp.asarray(T), r, ks)
            err = float(jnp.abs(out - ref).max())
            print(f"3d rolled {variant} ksteps={ks}: max err {err:.2e}")
            assert err < 2e-6, err


def bench_3d_rolled(configs, n3=512, steps=240, variant="f32"):
    from heat_tpu.runtime.timing import sync

    r = 0.15
    made = {}
    for R, M, k, km in configs:
        m_pad = _round_up(n3, R)
        mid_pad = _round_up(n3, M)
        shape = (m_pad, mid_pad, n3)
        if shape not in made:
            made[shape] = jax.jit(
                lambda shape=shape: jax.random.uniform(
                    jax.random.PRNGKey(0), shape, jnp.float32, 1.0, 2.0))()
            sync(made[shape])
        dev = made[shape]

        @jax.jit
        def run(Tp, R=R, M=M, k=k, km=km):
            def body(i, t):
                return pallas_3d_rolled(t, r=r, ksteps=min(k, km), R=R, M=M,
                                        k=k, km=km, logical=(n3, n3, n3),
                                        variant=variant)
            return jax.lax.fori_loop(0, steps // min(k, km), body, Tp)

        try:
            t0 = time.perf_counter()
            c = run.lower(dev).compile()
            compile_s = time.perf_counter() - t0
            nsteps = (steps // min(k, km)) * min(k, km)
            pts, pts_raw = measure_rate(c, dev, n3 ** 3 * nsteps)
            roof = _roof("float32")
            print(f"rolled {variant} R={R:4d} M={M:4d} k={k} km={km}: "
                  f"{pts:.3e} pts/s  ({pts / roof * 100:.0f}% roofline; "
                  f"raw {pts_raw / roof * 100:.0f}%)"
                  f"  [compile {compile_s:.0f}s]", flush=True)
        except Exception as e:
            print(f"rolled {variant} R={R:4d} M={M:4d} k={k} km={km}: "
                  f"{_failure_tag(e)}", flush=True)


# ---------------------------------------------------------------------------
# candidate: thin-band 2D kernel variants — A/B against the shipped one
#   shrink: row neighbors via shrinking slices (sublane-shifted reads)
#           instead of sublane rolls; lanes still rolled
#   bf16native: band stays in storage dtype; operands upcast at the adds
#               (VERDICT r1: do store-dtype rolls beat upcast-then-roll?)
#   rolled: the SHIPPED _make_kernel_2d body verbatim (the A side)
#   rolledfma: shipped body with the decay constant A = 1-4*maskr hoisted
#              out of the unroll (one fewer vector op per mini-step — the
#              round-3 op-reduction candidate for the 4096^2 headline)
# ---------------------------------------------------------------------------


def make_thin2d_variant(r, tile, kpad, n_pad, ksteps, variant):
    rows = tile + 2 * kpad

    def kernel(bounds_ref, prev_ref, cur_ref, next_ref, out_ref):
        i = pl.program_id(0)
        store_dt = out_ref.dtype
        acc_dt = jnp.float32
        band0 = jnp.concatenate(
            [prev_ref[:], cur_ref[:], next_ref[:]], axis=0)
        grow = i * tile - kpad + jax.lax.broadcasted_iota(
            jnp.int32, (rows, n_pad), 0)
        gcol = jax.lax.broadcasted_iota(jnp.int32, (rows, n_pad), 1)
        frozen = (
            (grow <= bounds_ref[0, 0]) | (grow >= bounds_ref[0, 1])
            | (gcol <= bounds_ref[0, 2]) | (gcol >= bounds_ref[0, 3])
        )

        if variant == "shrink":
            maskr = jnp.where(frozen, 0.0, r).astype(acc_dt)
            cur = band0.astype(acc_dt)
            for s in range(ksteps):
                lf = pltpu.roll(cur, 1, 1)
                rt = pltpu.roll(cur, n_pad - 1, 1)
                ctr = cur[1:-1, :]
                lap = (cur[2:, :] + cur[:-2, :]
                       + lf[1:-1, :] + rt[1:-1, :] - 4.0 * ctr)
                cur = ctr + maskr[s + 1: rows - s - 1, :] * lap
            out_ref[:] = jax.lax.slice(
                cur, (kpad - ksteps, 0),
                (kpad - ksteps + tile, n_pad)).astype(store_dt)
        elif variant == "bf16native":
            maskr = jnp.where(frozen, 0.0, r).astype(acc_dt)
            band = band0  # stays in storage dtype; adds upcast operands
            for _ in range(ksteps):
                up = pltpu.roll(band, 1, 0).astype(acc_dt)
                dn = pltpu.roll(band, rows - 1, 0).astype(acc_dt)
                lf = pltpu.roll(band, 1, 1).astype(acc_dt)
                rt = pltpu.roll(band, n_pad - 1, 1).astype(acc_dt)
                c = band.astype(acc_dt)
                band = (c + maskr * (up + dn + lf + rt - 4.0 * c)
                        ).astype(store_dt)
            out_ref[:] = band[kpad: kpad + tile]
        elif variant in ("rolled", "rolledfma"):
            maskr = jnp.where(frozen, 0.0, r).astype(acc_dt)
            band = band0.astype(acc_dt)
            if variant == "rolledfma":
                decay = (1.0 - 4.0 * maskr).astype(acc_dt)
            for _ in range(ksteps):
                up = pltpu.roll(band, 1, 0)
                dn = pltpu.roll(band, rows - 1, 0)
                lf = pltpu.roll(band, 1, 1)
                rt = pltpu.roll(band, n_pad - 1, 1)
                if variant == "rolledfma":
                    band = decay * band + maskr * (up + dn + lf + rt)
                else:
                    band = band + maskr * (up + dn + lf + rt - 4.0 * band)
            out_ref[:] = band[kpad: kpad + tile].astype(store_dt)
        else:
            raise ValueError(variant)

    return kernel


@functools.partial(jax.jit,
                   static_argnames=("r", "ksteps", "tile", "kpad", "variant",
                                    "logical"))
def pallas_thin2d_variant(Tp, r, ksteps, tile, kpad, variant, logical):
    m_pad, n_pad = Tp.shape
    m, n = logical
    assert m_pad % tile == 0 and tile % kpad == 0 and ksteps <= kpad
    bounds = jnp.asarray([[0, m - 1, 0, n - 1]], jnp.int32)
    ratio = tile // kpad
    nhblk = m_pad // kpad
    smem = pl.BlockSpec((1, 4), lambda i: (0, 0), memory_space=pltpu.SMEM)
    halo = lambda imap: pl.BlockSpec((kpad, n_pad), imap,
                                     memory_space=pltpu.VMEM)
    main = lambda imap: pl.BlockSpec((tile, n_pad), imap,
                                     memory_space=pltpu.VMEM)
    return pl.pallas_call(
        make_thin2d_variant(float(r), tile, kpad, n_pad, ksteps, variant),
        out_shape=jax.ShapeDtypeStruct(Tp.shape, Tp.dtype),
        grid=(m_pad // tile,),
        in_specs=[
            smem,
            halo(lambda i: (jnp.maximum(i * ratio - 1, 0), 0)),
            main(lambda i: (i, 0)),
            halo(lambda i: (jnp.minimum((i + 1) * ratio, nhblk - 1), 0)),
        ],
        out_specs=main(lambda i: (i, 0)),
        compiler_params=pltpu.CompilerParams(vmem_limit_bytes=VMEM_LIMIT),
        interpret=jax.default_backend() != "tpu",
    )(bounds, Tp, Tp, Tp)


def check_thin2d_variants():
    rng = np.random.default_rng(2)
    m, n = 96, 260
    for variant, dt, tol in (("shrink", np.float32, 2e-6),
                             ("bf16native", jnp.bfloat16, 5e-2),
                             ("rolled", np.float32, 2e-6),
                             ("rolledfma", np.float32, 2e-6)):
        T = rng.uniform(1, 2, (m, n)).astype(dt)
        tile, kpad = 32, 16
        m_pad = _round_up(m, tile)
        n_pad = _round_up(n, 128)
        Tp = jnp.pad(jnp.asarray(T), ((0, m_pad - m), (0, n_pad - n)))
        for ks in (1, 6):
            try:
                out = pallas_thin2d_variant(Tp, r=0.2, ksteps=ks, tile=tile,
                                            kpad=kpad, variant=variant,
                                            logical=(m, n))[:m, :n]
            except Exception as e:
                if _expected_unsupported(e, variant=variant, dtype=dt):
                    print(f"thin2d {variant}: EXPECTED-UNSUPPORTED on this "
                          f"backend (Mosaic dynamic_rotate is 32-bit-only)")
                    break
                raise
            ref = ref_steps(jnp.asarray(T), 0.2, ks)
            err = float(jnp.abs(out.astype(jnp.float32)
                                - ref.astype(jnp.float32)).max())
            print(f"thin2d {variant} ksteps={ks}: max err {err:.2e}")
            assert err < tol, err


def bench_thin2d_variants(n2, dtype, configs, steps=64):
    from heat_tpu.runtime.timing import sync

    r = 0.25
    dt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    made = {}
    for variant, tile, kpad in configs:
        k = kpad
        if steps < k:  # a zero-iteration fori_loop would "measure" an
            print(f"{variant:10s} tile={tile:4d} kpad={kpad}: SKIPPED "
                  f"(steps {steps} < kpad {kpad} -> zero passes)",
                  flush=True)  # empty program as 0 pts/s — fail loudly
            continue
        m_pad = _round_up(n2, tile)
        n_pad = _round_up(n2, 128)
        shape = (m_pad, n_pad)
        if shape not in made:
            made[shape] = jax.jit(
                lambda shape=shape: jax.random.uniform(
                    jax.random.PRNGKey(0), shape, jnp.float32, 1.0, 2.0
                ).astype(dt))()
            sync(made[shape])
        dev = made[shape]

        @jax.jit
        def run(Tp, variant=variant, tile=tile, kpad=kpad, k=k):
            def body(i, t):
                return pallas_thin2d_variant(t, r=r, ksteps=k, tile=tile,
                                             kpad=kpad, variant=variant,
                                             logical=(n2, n2))
            return jax.lax.fori_loop(0, steps // k, body, Tp)

        try:
            t0 = time.perf_counter()
            c = run.lower(dev).compile()
            compile_s = time.perf_counter() - t0
            nsteps = (steps // k) * k
            pts, pts_raw = measure_rate(c, dev, n2 * n2 * nsteps)
            roof = _roof(dtype)
            print(f"{variant:10s} tile={tile:4d} kpad={kpad}: {pts:.3e} "
                  f"pts/s ({pts / roof * 100:.0f}% {dtype} roofline; raw "
                  f"{pts_raw / roof * 100:.0f}%)"
                  f"  [compile {compile_s:.0f}s]", flush=True)
        except Exception as e:
            print(f"{variant:10s} tile={tile:4d} kpad={kpad}: "
                  f"{_failure_tag(e)}", flush=True)


# ---------------------------------------------------------------------------
# candidate: (row, col)-tiled 2D kernel for very wide arrays (bf16 32768^2):
# 3x3 halo blocks, col halo lane-aligned (128), shrinking slices, no rolls
# ---------------------------------------------------------------------------


def make_2d_coltiled(r, R, C, kr, kc, n_pad, ksteps):
    rows = R + 2 * kr
    cols = C + 2 * kc

    def kernel(bounds_ref, c00, c01, c02, c10, c11, c12, c20, c21, c22,
               out_ref):
        i = pl.program_id(0)
        j = pl.program_id(1)
        store_dt = out_ref.dtype
        acc_dt = jnp.float32
        top = jnp.concatenate([c00[:], c01[:], c02[:]], axis=1)
        mid = jnp.concatenate([c10[:], c11[:], c12[:]], axis=1)
        bot = jnp.concatenate([c20[:], c21[:], c22[:]], axis=1)
        band = jnp.concatenate([top, mid, bot], axis=0).astype(acc_dt)

        bshape = (rows, cols)
        grow = i * R - kr + jax.lax.broadcasted_iota(jnp.int32, bshape, 0)
        gcol = j * C - kc + jax.lax.broadcasted_iota(jnp.int32, bshape, 1)
        frozen = (
            (grow <= bounds_ref[0, 0]) | (grow >= bounds_ref[0, 1])
            | (gcol <= bounds_ref[0, 2]) | (gcol >= bounds_ref[0, 3])
        )
        maskr = jnp.where(frozen, 0.0, r).astype(acc_dt)

        cur = band
        for s in range(ksteps):
            ctr = cur[1:-1, 1:-1]
            lap = (cur[2:, 1:-1] + cur[:-2, 1:-1]
                   + cur[1:-1, 2:] + cur[1:-1, :-2] - 4.0 * ctr)
            m_s = maskr[s + 1: rows - s - 1, s + 1: cols - s - 1]
            cur = ctr + m_s * lap
        ro = kr - ksteps
        co = kc - ksteps
        out_ref[:] = jax.lax.slice(
            cur, (ro, co), (ro + R, co + C)).astype(store_dt)

    return kernel


@functools.partial(jax.jit,
                   static_argnames=("r", "ksteps", "R", "C", "kr", "kc",
                                    "logical"))
def pallas_2d_coltiled(Tp, r, ksteps, R, C, kr, kc, logical, bounds=None):
    m_pad, n_pad = Tp.shape
    m, n = logical
    assert m_pad % R == 0 and n_pad % C == 0
    assert R % kr == 0 and C % kc == 0 and ksteps <= min(kr, kc)
    if bounds is None:
        bounds = jnp.asarray([[0, m - 1, 0, n - 1]], jnp.int32)
    bounds = bounds.reshape(1, 4).astype(jnp.int32)
    gr, gc = m_pad // R, n_pad // C
    rr, rc = R // kr, C // kc
    nrb, ncb = m_pad // kr, n_pad // kc
    smem = pl.BlockSpec((1, 4), lambda i, j: (0, 0), memory_space=pltpu.SMEM)

    def bs(shape, imap):
        return pl.BlockSpec(shape, imap, memory_space=pltpu.VMEM)

    def rcl(i):
        return jnp.clip(i, 0, nrb - 1)

    def ccl(j):
        return jnp.clip(j, 0, ncb - 1)

    in_specs = [
        smem,
        bs((kr, kc), lambda i, j: (rcl(i * rr - 1), ccl(j * rc - 1))),
        bs((kr, C), lambda i, j: (rcl(i * rr - 1), j)),
        bs((kr, kc), lambda i, j: (rcl(i * rr - 1), ccl((j + 1) * rc))),
        bs((R, kc), lambda i, j: (i, ccl(j * rc - 1))),
        bs((R, C), lambda i, j: (i, j)),
        bs((R, kc), lambda i, j: (i, ccl((j + 1) * rc))),
        bs((kr, kc), lambda i, j: (rcl((i + 1) * rr), ccl(j * rc - 1))),
        bs((kr, C), lambda i, j: (rcl((i + 1) * rr), j)),
        bs((kr, kc), lambda i, j: (rcl((i + 1) * rr), ccl((j + 1) * rc))),
    ]
    return pl.pallas_call(
        make_2d_coltiled(float(r), R, C, kr, kc, n_pad, ksteps),
        out_shape=jax.ShapeDtypeStruct(Tp.shape, Tp.dtype),
        grid=(gr, gc),
        in_specs=in_specs,
        out_specs=bs((R, C), lambda i, j: (i, j)),
        compiler_params=pltpu.CompilerParams(vmem_limit_bytes=VMEM_LIMIT),
        interpret=jax.default_backend() != "tpu",
    )(bounds, *([Tp] * 9))


def make_2d_coltiled_rolled(r, R, C, kr, kc, ksteps, variant="f32"):
    """Col-tiled band, but mini-steps are full-band wrap rotates with a
    masked multiplicative update (the thin kernel's scheme on a 2-axis
    tile): every op is lane/sublane-aligned — no shrinking slices, which
    Mosaic compiles pathologically at deep unrolls on misaligned offsets.

    Variants (round 3: the 32768^2 bf16 config measures at the VPU op-rate
    ceiling, ~12.4 ops/pt-step x 2.2e12 ops/s — ops/pt must drop below
    ~10.7 to clear the bf16 one-pass HBM roofline):
    - "f32"        shipped form: f32 band, band + maskr*(sum - 4*band)
    - "fma"        f32 band, A*band + maskr*sum with A = 1 - 4*maskr
                   hoisted out of the unroll (one fewer vector op/step;
                   differs from "f32" only in rounding order)
    - "bf16native" band stays in storage dtype; rolls move half the bytes;
                   update upcasts to f32 and rounds back per mini-step
    - "bf16fma"    both of the above
    """
    rows = R + 2 * kr
    cols = C + 2 * kc
    assert variant in ("f32", "fma", "bf16native", "bf16fma"), variant
    native = variant in ("bf16native", "bf16fma")
    fma = variant in ("fma", "bf16fma")

    def kernel(bounds_ref, c00, c01, c02, c10, c11, c12, c20, c21, c22,
               out_ref):
        i = pl.program_id(0)
        j = pl.program_id(1)
        store_dt = out_ref.dtype
        acc_dt = jnp.float32
        top = jnp.concatenate([c00[:], c01[:], c02[:]], axis=1)
        mid = jnp.concatenate([c10[:], c11[:], c12[:]], axis=1)
        bot = jnp.concatenate([c20[:], c21[:], c22[:]], axis=1)
        band = jnp.concatenate([top, mid, bot], axis=0)
        if not native:
            band = band.astype(acc_dt)

        bshape = (rows, cols)
        grow = i * R - kr + jax.lax.broadcasted_iota(jnp.int32, bshape, 0)
        gcol = j * C - kc + jax.lax.broadcasted_iota(jnp.int32, bshape, 1)
        frozen = (
            (grow <= bounds_ref[0, 0]) | (grow >= bounds_ref[0, 1])
            | (gcol <= bounds_ref[0, 2]) | (gcol >= bounds_ref[0, 3])
        )
        maskr = jnp.where(frozen, 0.0, r).astype(acc_dt)
        if fma:
            decay = (1.0 - 4.0 * maskr).astype(acc_dt)  # hoisted constant

        for _ in range(ksteps):  # wrap corruption travels 1 cell/step,
            up = pltpu.roll(band, 1, 0)      # confined to the kr/kc margins
            dn = pltpu.roll(band, rows - 1, 0)
            lf = pltpu.roll(band, 1, 1)
            rt = pltpu.roll(band, cols - 1, 1)
            if native:
                up, dn = up.astype(acc_dt), dn.astype(acc_dt)
                lf, rt = lf.astype(acc_dt), rt.astype(acc_dt)
                c = band.astype(acc_dt)
            else:
                c = band
            if fma:
                new = decay * c + maskr * (up + dn + lf + rt)
            else:
                new = c + maskr * (up + dn + lf + rt - 4.0 * c)
            band = new.astype(store_dt) if native else new
        out_ref[:] = band[kr: kr + R, kc: kc + C].astype(store_dt)

    return kernel


@functools.partial(jax.jit,
                   static_argnames=("r", "ksteps", "R", "C", "kr", "kc",
                                    "logical", "variant"))
def pallas_2d_coltiled_rolled(Tp, r, ksteps, R, C, kr, kc, logical,
                              bounds=None, variant="f32"):
    m_pad, n_pad = Tp.shape
    m, n = logical
    assert m_pad % R == 0 and n_pad % C == 0
    assert R % kr == 0 and C % kc == 0 and ksteps <= min(kr, kc)
    if bounds is None:
        bounds = jnp.asarray([[0, m - 1, 0, n - 1]], jnp.int32)
    bounds = bounds.reshape(1, 4).astype(jnp.int32)
    gr, gc = m_pad // R, n_pad // C
    rr, rc = R // kr, C // kc
    nrb, ncb = m_pad // kr, n_pad // kc
    smem = pl.BlockSpec((1, 4), lambda i, j: (0, 0), memory_space=pltpu.SMEM)

    def bs(shape, imap):
        return pl.BlockSpec(shape, imap, memory_space=pltpu.VMEM)

    def rcl(i):
        return jnp.clip(i, 0, nrb - 1)

    def ccl(j):
        return jnp.clip(j, 0, ncb - 1)

    in_specs = [
        smem,
        bs((kr, kc), lambda i, j: (rcl(i * rr - 1), ccl(j * rc - 1))),
        bs((kr, C), lambda i, j: (rcl(i * rr - 1), j)),
        bs((kr, kc), lambda i, j: (rcl(i * rr - 1), ccl((j + 1) * rc))),
        bs((R, kc), lambda i, j: (i, ccl(j * rc - 1))),
        bs((R, C), lambda i, j: (i, j)),
        bs((R, kc), lambda i, j: (i, ccl((j + 1) * rc))),
        bs((kr, kc), lambda i, j: (rcl((i + 1) * rr), ccl(j * rc - 1))),
        bs((kr, C), lambda i, j: (rcl((i + 1) * rr), j)),
        bs((kr, kc), lambda i, j: (rcl((i + 1) * rr), ccl((j + 1) * rc))),
    ]
    return pl.pallas_call(
        make_2d_coltiled_rolled(float(r), R, C, kr, kc, ksteps, variant),
        out_shape=jax.ShapeDtypeStruct(Tp.shape, Tp.dtype),
        grid=(gr, gc),
        in_specs=in_specs,
        out_specs=bs((R, C), lambda i, j: (i, j)),
        compiler_params=pltpu.CompilerParams(vmem_limit_bytes=VMEM_LIMIT),
        interpret=jax.default_backend() != "tpu",
    )(bounds, *([Tp] * 9))


def check_2d_coltiled_rolled():
    rng = np.random.default_rng(3)
    m, n = 100, 500
    cases = ((np.float32, "f32", 2e-6), (np.float32, "fma", 2e-6),
             (jnp.bfloat16, "f32", 3e-2), (jnp.bfloat16, "fma", 3e-2),
             # per-mini-step bf16 rounding accumulates: looser tolerance
             (jnp.bfloat16, "bf16native", 6e-2),
             (jnp.bfloat16, "bf16fma", 6e-2))
    for dt, variant, tol in cases:
        T = rng.uniform(1, 2, (m, n)).astype(dt)
        r = 0.2
        R, C, kr, kc = 16, 256, 16, 128
        m_pad = _round_up(m, R)
        n_pad = _round_up(n, C)
        Tp = jnp.pad(jnp.asarray(T), ((0, m_pad - m), (0, n_pad - n)))
        for ks in (1, 5, 16):
            try:
                out = pallas_2d_coltiled_rolled(
                    Tp, r=r, ksteps=ks, R=R, C=C, kr=kr, kc=kc,
                    logical=(m, n), variant=variant)[:m, :n]
            except Exception as e:
                if _expected_unsupported(e, variant=variant, dtype=dt):
                    print(f"2d coltiled-rolled {np.dtype(dt).name} "
                          f"{variant}: EXPECTED-UNSUPPORTED on this "
                          f"backend (Mosaic dynamic_rotate is 32-bit-only)")
                    break
                raise
            ref = ref_steps(jnp.asarray(T), r, ks)
            err = float(jnp.abs(out.astype(jnp.float32)
                                - ref.astype(jnp.float32)).max())
            print(f"2d coltiled-rolled {np.dtype(dt).name} {variant} "
                  f"ksteps={ks}: max err {err:.2e}")
            assert err < tol, err


def bench_2d_rolled(configs, n2=32768, dtype="bfloat16", steps=96,
                    variant="f32"):
    from heat_tpu.runtime.timing import sync

    r = 0.25
    dt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    made = {}
    for R, C, kr, kc in configs:
        m_pad = _round_up(n2, R)
        n_pad = _round_up(n2, C)
        shape = (m_pad, n_pad)
        if shape not in made:
            made[shape] = jax.jit(
                lambda shape=shape: jax.random.uniform(
                    jax.random.PRNGKey(0), shape, jnp.float32, 1.0, 2.0
                ).astype(dt))()
            sync(made[shape])
        dev = made[shape]
        k = min(kr, kc)

        @jax.jit
        def run(Tp, R=R, C=C, kr=kr, kc=kc, k=k):
            def body(i, t):
                return pallas_2d_coltiled_rolled(
                    t, r=r, ksteps=k, R=R, C=C, kr=kr, kc=kc,
                    logical=(n2, n2), variant=variant)
            return jax.lax.fori_loop(0, steps // k, body, Tp)

        try:
            t0 = time.perf_counter()
            c = run.lower(dev).compile()
            compile_s = time.perf_counter() - t0
            nsteps = (steps // k) * k
            pts, pts_raw = measure_rate(c, dev, n2 * n2 * nsteps)
            roof = _roof(dtype)
            print(f"rolled {variant} R={R:4d} C={C:6d} kr={kr} kc={kc}: "
                  f"{pts:.3e} pts/s ({pts / roof * 100:.0f}% {dtype} "
                  f"roofline; raw {pts_raw / roof * 100:.0f}%)"
                  f"  [compile {compile_s:.0f}s]", flush=True)
        except Exception as e:
            print(f"rolled {variant} R={R:4d} C={C:6d} kr={kr} kc={kc}: "
                  f"{_failure_tag(e)}", flush=True)


def check_2d_coltiled():
    rng = np.random.default_rng(1)
    m, n = 100, 500
    for dt, tol in ((np.float32, 2e-6), (jnp.bfloat16, 3e-2)):
        T = rng.uniform(1, 2, (m, n)).astype(dt)
        r = 0.2
        R, C, kr, kc = 16, 256, 16, 128
        m_pad = _round_up(m, R)
        n_pad = _round_up(n, C)
        Tp = jnp.pad(jnp.asarray(T), ((0, m_pad - m), (0, n_pad - n)))
        for ks in (1, 5, 16):
            out = pallas_2d_coltiled(Tp, r=r, ksteps=ks, R=R, C=C, kr=kr,
                                     kc=kc, logical=(m, n))[:m, :n]
            ref = ref_steps(jnp.asarray(T), r, ks)
            err = float(jnp.abs(out.astype(jnp.float32)
                                - ref.astype(jnp.float32)).max())
            print(f"2d coltiled {np.dtype(dt).name} ksteps={ks}: "
                  f"max err {err:.2e}")
            assert err < tol, err


def bench_2d(configs, n2=32768, dtype="bfloat16", steps=96):
    from heat_tpu.runtime.timing import sync

    r = 0.25
    dt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    made = {}
    for R, C, kr, kc in configs:
        m_pad = _round_up(n2, R)
        n_pad = _round_up(n2, C)
        shape = (m_pad, n_pad)
        if shape not in made:
            made[shape] = jax.jit(
                lambda shape=shape: jax.random.uniform(
                    jax.random.PRNGKey(0), shape, jnp.float32, 1.0, 2.0
                ).astype(dt))()
            sync(made[shape])
        dev = made[shape]
        k = min(kr, kc)

        @jax.jit
        def run(Tp, R=R, C=C, kr=kr, kc=kc, k=k):
            def body(i, t):
                return pallas_2d_coltiled(t, r=r, ksteps=k, R=R, C=C,
                                          kr=kr, kc=kc, logical=(n2, n2))
            return jax.lax.fori_loop(0, steps // k, body, Tp)

        try:
            t0 = time.perf_counter()
            c = run.lower(dev).compile()
            compile_s = time.perf_counter() - t0
            nsteps = (steps // k) * k
            pts, pts_raw = measure_rate(c, dev, n2 * n2 * nsteps)
            roof = _roof(dtype)
            print(f"R={R:4d} C={C:6d} kr={kr} kc={kc}: {pts:.3e} pts/s "
                  f"({pts / roof * 100:.0f}% {dtype} roofline; raw "
                  f"{pts_raw / roof * 100:.0f}%)"
                  f"  [compile {compile_s:.0f}s]", flush=True)
        except Exception as e:
            print(f"R={R:4d} C={C:6d} kr={kr} kc={kc}: {_failure_tag(e)}",
                  flush=True)


# ---------------------------------------------------------------------------
# the SHIPPED kernels, as dispatched by the framework's plans
# ---------------------------------------------------------------------------


def bench_framework(cases):
    """Measure heat_tpu's own multistep entry points (plan-dispatched).

    cases: list of (label, shape_tuple, dtype_str, ksteps, steps).
    """
    sys.path.insert(0, str(__import__("pathlib").Path(__file__).parent.parent))
    from heat_tpu.ops.pallas_stencil import (
        _plan_2d, _plan_3d, ftcs_multistep_edges_pallas)
    from heat_tpu.runtime.timing import sync

    import gc

    r = 0.2
    for label, shape, dtype, ksteps, steps in cases:
        # the previous case's GiB-scale buffers must be gone before this
        # case allocates (a failed case's traceback pins its frame — and
        # with it `dev` — until the next exception, so collect explicitly)
        dev = None
        gc.collect()
        dt = jnp.dtype(dtype)
        dev = jax.jit(
            lambda shape=shape, dt=dt: jax.random.uniform(
                jax.random.PRNGKey(0), shape, jnp.float32, 1.0, 2.0
            ).astype(dt))()
        sync(dev)
        plan = (_plan_2d(shape, dtype, ksteps) if len(shape) == 2
                else _plan_3d(shape, dtype, ksteps))

        # donated carry: the measurement holds one in+out buffer pair —
        # without it the 32768^2 f32 case (4 GiB/buffer) exhausts HBM
        @functools.partial(jax.jit, donate_argnums=0)
        def run(T, ksteps=ksteps):
            def body(i, t):
                return ftcs_multistep_edges_pallas(t, r, ksteps)
            return jax.lax.fori_loop(0, steps // ksteps, body, T)

        try:
            t0 = time.perf_counter()
            c = run.lower(dev).compile()
            compile_s = time.perf_counter() - t0
            nsteps = (steps // ksteps) * ksteps
            pts, pts_raw = measure_rate(c, dev,
                                        float(np.prod(shape)) * nsteps)
            roof = _roof(dt)
            print(f"{label:28s} plan={plan}: {pts:.3e} pts/s "
                  f"({pts / roof * 100:.0f}% roofline; raw single-call "
                  f"{pts_raw:.3e} = {pts_raw / roof * 100:.0f}%) [compile "
                  f"{compile_s:.0f}s]", flush=True)
        except Exception as e:
            print(f"{label:28s} plan={plan}: {_failure_tag(e)}", flush=True)


FRAMEWORK_CASES = {
    "2d4096": ("2d 4096^2 f32", (4096, 4096), "float32", 16, 2048),
    "2d32k_bf16": ("2d 32768^2 bf16", (32768, 32768), "bfloat16", 16, 96),
    "2d32k_f32": ("2d 32768^2 f32", (32768, 32768), "float32", 16, 96),
    "3d512": ("3d 512^3 f32", (512, 512, 512), "float32", 8, 480),
}


# ---------------------------------------------------------------------------
# reference semantics for correctness check
# ---------------------------------------------------------------------------


def ref_steps(T, r, ksteps):
    sys.path.insert(0, ".")
    from heat_tpu.ops.stencil import ftcs_step_edges

    for _ in range(ksteps):
        T = ftcs_step_edges(T, r)
    return T


def check_3d():
    rng = np.random.default_rng(0)
    m, mid, n = 40, 24, 300
    T = rng.uniform(1, 2, (m, mid, n)).astype(np.float32)
    r = 0.15
    k = km = 4
    R, M = 8, 8
    m_pad = _round_up(m, R)
    mid_pad = _round_up(mid, M)
    n_pad = _round_up(n, 128)
    Tp = jnp.pad(jnp.asarray(T), ((0, m_pad - m), (0, mid_pad - mid),
                                  (0, n_pad - n)))
    for ks in (1, 3, 4):
        out = pallas_3d_tiled(Tp, r=r, ksteps=ks, R=R, M=M, k=k, km=km,
                              logical=(m, mid, n))[:m, :mid, :n]
        ref = ref_steps(jnp.asarray(T), r, ks)
        err = float(jnp.abs(out - ref).max())
        print(f"3d tiled ksteps={ks}: max err {err:.2e}")
        assert err < 2e-6, err


def bench_3d(configs):
    """On-device data (no 512 MiB tunnel transfers); arrays reused."""
    from heat_tpu.runtime.timing import sync

    n3 = 512
    r = 0.15
    steps = 240
    made = {}
    for R, M, k, km in configs:
        m_pad = _round_up(n3, R)
        mid_pad = _round_up(n3, M)
        shape = (m_pad, mid_pad, n3)
        if shape not in made:
            made[shape] = jax.jit(
                lambda shape=shape: jax.random.uniform(
                    jax.random.PRNGKey(0), shape, jnp.float32, 1.0, 2.0))()
            sync(made[shape])
        dev = made[shape]

        @jax.jit
        def run(Tp, R=R, M=M, k=k, km=km):
            def body(i, t):
                return pallas_3d_tiled(t, r=r, ksteps=min(k, km), R=R, M=M,
                                       k=k, km=km, logical=(n3, n3, n3))
            return jax.lax.fori_loop(0, steps // min(k, km), body, Tp)

        try:
            t0 = time.perf_counter()
            c = run.lower(dev).compile()
            compile_s = time.perf_counter() - t0
            nsteps = (steps // min(k, km)) * min(k, km)
            pts, pts_raw = measure_rate(c, dev, n3 ** 3 * nsteps)
            roof = _roof("float32")
            print(f"R={R:4d} M={M:4d} k={k} km={km}: "
                  f"{pts:.3e} pts/s  ({pts / roof * 100:.0f}% roofline; "
                  f"raw {pts_raw / roof * 100:.0f}%)"
                  f"  [compile {compile_s:.0f}s]", flush=True)
        except Exception as e:
            print(f"R={R:4d} M={M:4d} k={k} km={km}: {_failure_tag(e)}",
                  flush=True)


if __name__ == "__main__":
    exp = sys.argv[1] if len(sys.argv) > 1 else "check3d"
    if exp == "check3d":
        check_3d()
    elif exp == "bench3d":
        # configs on argv: R,M,k,km quadruples like 64,64,8,8
        cfgs = [tuple(int(t) for t in a.split(",")) for a in sys.argv[2:]]
        bench_3d(cfgs or [(64, 64, 8, 8)])
    elif exp == "check2d":
        check_2d_coltiled()
    elif exp == "bench2d":
        cfgs = [tuple(int(t) for t in a.split(",")) for a in sys.argv[2:]]
        bench_2d(cfgs or [(256, 4096, 16, 128)])
    elif exp == "bench2d_f32":
        cfgs = [tuple(int(t) for t in a.split(",")) for a in sys.argv[2:]]
        bench_2d(cfgs or [(256, 4096, 16, 128)], dtype="float32")
    elif exp == "check2d_rolled":
        check_2d_coltiled_rolled()
    elif exp == "bench2d_rolled":
        cfgs = [tuple(int(t) for t in a.split(",")) for a in sys.argv[2:]]
        bench_2d_rolled(cfgs or [(256, 4096, 16, 128)])
    elif exp == "bench2d_rolled_var":
        # args: variant then R,C,kr,kc quadruples; optional --n2 N
        # overrides the flagship 32768 extent (round 5: the bf16
        # variants' programs fail through the remote-compile helper at
        # 32768 but are valid Mosaic kernels — the measurable A/B lives
        # at 16384, see bf16_variant_compile_check.py)
        argv = sys.argv[2:]
        n2 = 32768
        usage = ("usage: kernel_lab.py bench2d_rolled_var "
                 "{f32|fma|bf16native|bf16fma} [R,C,kr,kc ...] [--n2 N]")
        if "--n2" in argv:
            i = argv.index("--n2")
            try:
                n2 = int(argv[i + 1])
            except (IndexError, ValueError):
                sys.exit(usage)
            if n2 <= 0:
                sys.exit(usage)
            argv = argv[:i] + argv[i + 2:]
        if not argv:
            sys.exit(usage)
        variant = argv[0]
        cfgs = [tuple(int(t) for t in a.split(",")) for a in argv[1:]]
        bench_2d_rolled(cfgs or [(256, 4096, 16, 128)], n2=n2,
                        variant=variant)
    elif exp == "check3d_rolled":
        check_3d_rolled()
    elif exp == "bench3d_rolled":
        cfgs = [tuple(int(t) for t in a.split(",")) for a in sys.argv[2:]]
        bench_3d_rolled(cfgs or [(64, 64, 8, 8)])
    elif exp == "bench3d_rolled_var":
        if len(sys.argv) < 3:
            sys.exit("usage: kernel_lab.py bench3d_rolled_var {f32|fma} "
                     "[R,M,k,km ...]")
        variant = sys.argv[2]
        cfgs = [tuple(int(t) for t in a.split(",")) for a in sys.argv[3:]]
        bench_3d_rolled(cfgs or [(64, 64, 8, 8)], variant=variant)
    elif exp == "bench2d_rolled_f32":
        cfgs = [tuple(int(t) for t in a.split(",")) for a in sys.argv[2:]]
        bench_2d_rolled(cfgs or [(256, 4096, 16, 128)], dtype="float32")
    elif exp == "checkthin":
        check_thin2d_variants()
    elif exp == "benchthin":
        # args: n dtype then variant,tile,kpad triples; optional --steps N.
        # The 64-step default is sized for the flagship 32768^2 extent —
        # at 4096^2 it is ~6 ms of device work against the tunnel's
        # ~150 ms dispatch floor and measures the floor, not the kernel
        # (observed 2026-08-02: the SHIPPED tile read 8% of roofline).
        # Small-extent A/Bs must raise it (e.g. --steps 2048 ~ 0.2 s).
        argv = sys.argv[2:]
        steps = 64
        usage = ("usage: kernel_lab.py benchthin N {float32|bfloat16} "
                 "[variant,tile,kpad ...] [--steps N]")
        if "--steps" in argv:
            i = argv.index("--steps")
            try:
                steps = int(argv[i + 1])
            except (IndexError, ValueError):
                sys.exit(usage)
            if steps <= 0:
                sys.exit(usage)
            argv = argv[:i] + argv[i + 2:]
        if len(argv) < 2:
            sys.exit(usage)
        n2 = int(argv[0])
        dtype = argv[1]
        cfgs = [(a.split(",")[0], int(a.split(",")[1]), int(a.split(",")[2]))
                for a in argv[2:]]
        bench_thin2d_variants(n2, dtype, cfgs, steps=steps)
    elif exp == "framework":
        keys = sys.argv[2:] or list(FRAMEWORK_CASES)
        bench_framework([FRAMEWORK_CASES[k] for k in keys])
