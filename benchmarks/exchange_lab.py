"""Where does the per-exchange cost go? (round 3)

``collective_overhead.py``'s exchange_delta probe measured C ~= 9.1 ms
per width-k exchange at 16384^2 f32 on the 1x1 mesh (fit over fuse
k={1,8}). Accounting: one kernel HBM pass ~2.6 ms + 4 ppermute
dispatches ~1.2 ms (probe 1) leaves ~5 ms unexplained — about two full
passes of the 1 GiB padded array, i.e. the ghost-write
``out.at[slab].set(...)`` updates in ``parallel/halo.py:111-112``
plausibly materialize full-array copies instead of in-place
dynamic-update-slices.

This lab times the *exchange alone* (jit'd, two-point protocol) in
three formulations and dumps the compiled HLO op census so the copies
are visible, not inferred:

- ``dus``     the shipped halo_exchange (4 sequential .at.set writes)
- ``concat``  rebuild each axis by concatenate([ghost, interior, ghost])
              (one explicit full pass per axis, no DUS aliasing question)
- ``donate``  the shipped exchange under jit with the padded buffer
              donated (gives XLA permission to update in place)

Run on chip: ``python benchmarks/exchange_lab.py [n]``; CPU smoke:
``python benchmarks/exchange_lab.py --smoke``. Writes
benchmarks/exchange_lab.json (atomic, incremental).

Findings so far (CPU census, 4x2 virtual mesh): the sequential exchange
costs the compiled advance 3 copies/iteration (2 full-local-shard);
``exchange="indep"`` removes one full-shape copy. The remaining
full-shard copy is NOT exchange-related — a control with a pure
stencil loop body (no exchange at all) shows the identical census, so
it belongs to the fori_loop carry structure itself and no exchange
reformulation can remove it. Python-unrolling the fused-block loop was
tried and REJECTED: a pure elementwise body unrolls to zero copies, but
the real exchange+kernel body keeps one copy per unrolled block
(executed-copy count unchanged from the while form), so the unroll only
buys bigger programs. CPU censuses also understate the TPU picture —
off-TPU the pallas kernel runs as inlined interpret HLO, not a Mosaic
custom call — which is why the on-chip census rows below exist.
"""

from __future__ import annotations

import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))

from _util import write_atomic  # noqa: E402


def _census(compiled) -> dict:
    """Count the ops that matter in the compiled HLO: full-array copies
    and fusions (a DUS inside a fusion is in-place; a standalone copy op
    is the smoking gun). copy_shapes says whether each copy is the full
    padded array or a cheap slab."""
    import re

    txt = compiled.as_text()
    copy_shapes = re.findall(r"=\s*(\S+?)\{[^}]*\}?\S*\s+copy\(", txt)
    return {
        "copy": txt.count(" copy("),
        "copy_shapes": copy_shapes[:8],
        "dynamic-update-slice": txt.count("dynamic-update-slice"),
        "fusion": txt.count(" fusion("),
        "collective-permute": txt.count("collective-permute"),
        "all-to-all": txt.count("all-to-all"),
    }


# NOTE: overlap *schedule* evidence (kernels inside the async
# collective-permute flight window) is NOT measurable here — this lab's
# real-advance rows run the 1x1 mesh, where ppermute degenerates and the
# compiled module has zero collective-permute pairs. The multi-chip
# schedule census lives in benchmarks/topology_schedule.py (AOT topology
# compile — works without any attached chip).


def variants(axis_names, axis_sizes, bc_value, w):
    import jax
    import jax.numpy as jnp

    from heat_tpu.parallel.halo import halo_exchange

    def dus(padded):
        return halo_exchange(padded, axis_names, axis_sizes, bc_value,
                             width=w)

    def concat(padded):
        from jax import lax

        nd = padded.ndim
        bc = jnp.asarray(bc_value, padded.dtype)
        out = padded
        for d, (name, size) in enumerate(zip(axis_names, axis_sizes)):
            idx = lax.axis_index(name)

            def slab(sl_d):
                sl = [slice(None)] * nd
                sl[d] = sl_d
                return tuple(sl)

            send_lo = out[slab(slice(w, 2 * w))]
            send_hi = out[slab(slice(-2 * w, -w))]
            pairs_fwd = [(i, i + 1) for i in range(size - 1)]
            pairs_bwd = [(i + 1, i) for i in range(size - 1)]
            from_prev = lax.ppermute(send_hi, name, pairs_fwd)
            from_next = lax.ppermute(send_lo, name, pairs_bwd)
            from_prev = jnp.where(idx == 0, bc, from_prev)
            from_next = jnp.where(idx == size - 1, bc, from_next)
            out = jnp.concatenate(
                [from_prev, out[slab(slice(w, -w))], from_next], axis=d)
        return out

    return {"dus": dus, "concat": concat}


def main():
    smoke = "--smoke" in sys.argv
    args = [a for a in sys.argv[1:] if not a.startswith("-")]
    if smoke:
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp

    from jax.sharding import Mesh, PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:  # pragma: no cover
        from jax.experimental.shard_map import shard_map

    from heat_tpu.runtime.timing import two_point_rate

    n = int(args[0]) if args else (512 if smoke else 16384)
    w = 8
    mesh = Mesh(jax.devices()[:1], ("x",))
    axis_names, axis_sizes = ("x",), (1,)
    padded = jnp.zeros((n + 2 * w, n + 2 * w), jnp.float32)

    out = Path(__file__).parent / (
        "exchange_lab_smoke.json" if smoke else "exchange_lab.json")
    rec = {"ts": time.time(), "platform": jax.default_backend(),
           "n": n, "w": w, "variants": {}}

    fns = variants(axis_names, axis_sizes, 2.0, w)
    for name, fn in fns.items():
        for donate in ((False, True) if name == "dus" else (False,)):
            label = "donate" if donate else name
            sm = shard_map(fn, mesh=mesh, in_specs=(P("x"),),
                           out_specs=P("x"))
            jf = (jax.jit(sm, donate_argnums=0) if donate
                  else jax.jit(sm))
            lowered = jf.lower(jax.ShapeDtypeStruct(padded.shape,
                                                    padded.dtype))
            compiled = lowered.compile()
            census = _census(compiled)
            # two_point_rate recycles the output as the next input, so a
            # donating executable just cycles one buffer pair
            # time the AOT executable itself — calling jf would re-trace
            # and re-compile a second copy of each large program
            rate, _ = two_point_rate(compiled, jnp.zeros_like(padded),
                                     padded.size, repeats=3)
            per_call_s = padded.size / rate if rate else None
            rec["variants"][label] = {"hlo": census,
                                      "per_exchange_s": per_call_s}
            per_call_us = (f"{per_call_s * 1e6:9.1f} us"
                           if per_call_s is not None else "      n/a")
            print(f"{label:8s} per-exchange {per_call_us}  "
                  f"hlo={census}", flush=True)
            write_atomic(out, rec)

    # the real thing: HLO census of the shipped padded-carry advance (the
    # program collective_overhead's exchange_delta times) — copies here
    # are copies the solve actually pays, donation and all
    from heat_tpu.backends.sharded import make_padded_carry_machinery
    from heat_tpu.config import HeatConfig

    from heat_tpu.parallel.mesh import build_mesh

    steps = 64
    for exchange in ("seq", "indep", "overlap"):
        for kf in (1, 8):
            if exchange == "overlap" and kf == 1:
                continue  # w=1 rim IS the shard edge; nothing to overlap
            # overlap requires the Pallas kernel; pin it for the other
            # modes too when comparing against overlap rows on TPU (on
            # CPU smoke the seq/indep rows keep the default XLA local
            # kernel — their censuses are the round-3 baseline)
            lk = "pallas" if exchange == "overlap" else "auto"
            cfg = HeatConfig(n=n, ntime=steps, dtype="float32",
                             backend="sharded", mesh_shape=(1, 1),
                             fuse_steps=kf, exchange=exchange,
                             local_kernel=lk)
            hmesh = build_mesh(cfg.ndim, cfg.mesh_shape)
            seed, advance, crop = make_padded_carry_machinery(cfg, hmesh)
            Tp = seed(jnp.zeros((n, n), jnp.float32))
            compiled = advance.lower(Tp, steps).compile()
            census = _census(compiled)
            # the advance donates its carry, so two_point recycles buffers
            # static step-count arg is baked into the executable; Tp is
            # donated into the measurement (lowering didn't consume it) —
            # a second seeded buffer would double resident padded state
            rate, _ = two_point_rate(compiled, Tp, n * n * steps,
                                     repeats=3)
            del Tp
            per_step = n * n / rate if rate else None
            key = f"real_advance_{exchange}_fuse{kf}"
            rec["variants"][key] = {"hlo": census,
                                    "per_step_s": per_step}
            per_step_us = (f"{per_step * 1e6:9.1f} us"
                           if per_step is not None else "      n/a")
            print(f"real advance {exchange} fuse={kf}: "
                  f"per-step {per_step_us}  hlo={census}",
                  flush=True)
            write_atomic(out, rec)
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
