"""Fleet-router scaling + chaos lab: 1/2/4 CPU backends behind one router.

Four claims, one harness (ISSUE 18):

- **Scaling**: the serve_lab 64-request population, each request carrying
  ``inject: sink-slow:ms=200`` (a writer-sink sleep — the CPU-world
  stand-in for the per-request device/IO time a one-core host cannot
  otherwise exhibit; results are untouched), drained through the router
  over 1 vs 2 vs 4 backend PROCESSES. Per-engine the sink serializes, so
  aggregate throughput scales with the fleet: the committed gate is
  >= 1.7x at 2 backends and monotone (no worse) at 4.
- **Bit-identity**: a sample of the fleet's npz outputs must be
  byte-identical to solo in-process solves — the router routes, it never
  does arithmetic.
- **Kill drill**: at 2 backends, one backend process is SIGKILLed
  mid-wave. The router's probe sees the loss, flight-dumps its fleet
  timeline, adopts the victim's engine-checkpoint manifest onto the
  survivor and re-drives the rest — the gate is all 64 requests reach a
  terminal ok record with zero lost and zero double-delivered.
- **Steal overhead**: a forced ``/drainz?handoff=1`` checkpoint-handoff
  steal from a loaded backend to an idle one, recording the end-to-end
  recovery wall (drain + manifest pickup + resume) and how many
  requests migrated mid-flight.

Backends are real ``heat-tpu serve`` subprocesses on localhost ports;
the router runs in-process so its counters/steal events are directly
inspectable. Walls are measured from first POST with every backend
already probed healthy (process spin-up and compile warming are paid
before the clock starts — serving latency, not cold-start latency).

    JAX_PLATFORMS=cpu python benchmarks/fleet_lab.py [--requests 64]
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import re
import signal
import subprocess
import sys
import threading
import time
from pathlib import Path

from _util import write_atomic

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

LISTEN_RE = re.compile(r"listening on http://([0-9.]+):(\d+)")
SINK_MS = 200


class BackendProc:
    """One ``heat-tpu serve`` subprocess; stdout goes to a log file we
    poll for the bound port (--listen 127.0.0.1:0)."""

    def __init__(self, name: str, workdir: Path, env: dict):
        self.name = name
        self.dir = workdir / name
        self.dir.mkdir(parents=True, exist_ok=True)
        self.log = self.dir / "serve.log"
        cmd = [sys.executable, "-m", "heat_tpu", "serve",
               "--listen", "127.0.0.1:0",
               "--lanes", "4", "--chunk", "16", "--buckets", "32,48",
               "--out-dir", str(self.dir),
               "--engine-ckpt-interval", "2",
               "--engine-ckpt-dir", str(self.dir / "ckpt")]
        self.proc = subprocess.Popen(cmd, stdout=self.log.open("wb"),
                                     stderr=subprocess.STDOUT, env=env,
                                     cwd=str(REPO))
        self.address = None

    def wait_address(self, timeout: float = 180.0) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"backend {self.name} exited rc={self.proc.returncode}:"
                    f"\n{self.log.read_text()[-2000:]}")
            m = LISTEN_RE.search(self.log.read_text(errors="replace"))
            if m:
                self.address = f"{m.group(1)}:{m.group(2)}"
                return self.address
            time.sleep(0.2)
        raise RuntimeError(f"backend {self.name} never bound a port")

    def wait_healthy(self, timeout: float = 60.0) -> None:
        host, _, port = self.address.rpartition(":")
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                conn = http.client.HTTPConnection(host, int(port),
                                                  timeout=5)
                conn.request("GET", "/healthz")
                ok = conn.getresponse().status == 200
                conn.close()
                if ok:
                    return
            except OSError:
                pass
            time.sleep(0.2)
        raise RuntimeError(f"backend {self.name} never went healthy")

    def kill(self) -> None:
        if self.proc.poll() is None:
            self.proc.send_signal(signal.SIGKILL)
            self.proc.wait(timeout=30)

    def stop(self) -> None:
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                self.kill()


def build_lines(count: int, prefix: str, sink_ms: int = SINK_MS):
    """The serve_lab population as request lines, each carrying the
    writer-sink sleep that models per-request device/IO time."""
    from serve_lab import build_requests

    lines = []
    for i, cfg in enumerate(build_requests(count)):
        lines.append({"id": f"{prefix}-r{i}", "n": cfg.n,
                      "ntime": cfg.ntime, "dtype": cfg.dtype,
                      "bc": cfg.bc, "ic": cfg.ic, "nu": cfg.nu,
                      "inject": f"sink-slow:ms={sink_ms}"})
    return lines


def post_stream(rt, lines, timeout: float = 600.0):
    """One streaming POST through the router; returns the terminal
    records (the wall the caller measures around this IS the wave)."""
    body = "".join(json.dumps(ln) + "\n" for ln in lines).encode()
    conn = http.client.HTTPConnection(rt.host, rt.port, timeout=timeout)
    conn.request("POST", "/v1/solve", body=body)
    resp = conn.getresponse()
    recs = []
    while True:
        raw = resp.readline()
        if not raw:
            break
        raw = raw.strip()
        if raw:
            recs.append(json.loads(raw))
    conn.close()
    return recs


def make_router(addresses, **fcfg_kw):
    from heat_tpu.fleet.registry import BackendRegistry, parse_backends
    from heat_tpu.fleet.router import FleetConfig, Router

    spec = ",".join(f"{n}={a}" for n, a in addresses)
    fcfg_kw.setdefault("health_interval_s", 0.5)
    rt = Router(BackendRegistry(parse_backends(spec)), "127.0.0.1", 0,
                FleetConfig(**fcfg_kw))
    return rt.start()


def warm_backend(b, lines, timeout: float = 300.0):
    """Pay a backend's bucket compiles before any timed wave: a short
    sink-free wave POSTed DIRECTLY to it (the shared JAX compilation
    cache makes every backend after the first a cache hit)."""
    host, _, port = b.address.rpartition(":")
    body = "".join(json.dumps(dict(ln, id=f"{b.name}-{ln['id']}")) + "\n"
                   for ln in lines).encode()
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
    conn.request("POST", "/v1/solve", body=body)
    resp = conn.getresponse()
    while resp.readline():
        pass
    conn.close()


def run_wave(backends, lines):
    """Drain the wave through a fresh router over already-warm backends;
    returns (wall_s, records, snapshot)."""
    rt = make_router([(b.name, b.address) for b in backends])
    try:
        time.sleep(1.2)   # a probe round: status payloads for placement
        t0 = time.perf_counter()
        recs = post_stream(rt, lines)
        wall = time.perf_counter() - t0
        snap = rt.snapshot()
    finally:
        rt.close()
    return wall, recs, snap


def check_sample(backends, lines, sample_idx):
    """npz byte-identity: fleet outputs vs solo in-process solves."""
    import numpy as np

    from heat_tpu.backends import solve
    from heat_tpu.config import HeatConfig

    for i in sample_idx:
        ln = dict(lines[i])
        rid = ln.pop("id")
        ln.pop("inject", None)
        paths = [b.dir / f"{rid}.npz" for b in backends
                 if (b.dir / f"{rid}.npz").exists()]
        if len(paths) != 1:
            return False
        with np.load(paths[0]) as z:
            got = z["T"]
        if not np.array_equal(got, solve(HeatConfig(**ln)).T):
            return False
    return True


def kill_drill(backends, lines, flight_dir):
    """SIGKILL one of two backends mid-wave; the router must recover the
    victim's checkpointed work onto the survivor and still deliver every
    request exactly once."""
    rt = make_router([(b.name, b.address) for b in backends],
                     flightrec_dir=str(flight_dir))
    try:
        time.sleep(1.2)
        recs = []
        t0 = time.perf_counter()
        waver = threading.Thread(
            target=lambda: recs.extend(post_stream(rt, lines)))
        waver.start()
        # kill the victim once it is genuinely mid-wave (several sink
        # sleeps deep, checkpoints on disk)
        time.sleep(2.5)
        backends[0].kill()
        waver.join(timeout=600)
        wall = time.perf_counter() - t0
        snap = rt.snapshot()
        assert not waver.is_alive(), "kill-drill wave never finished"
    finally:
        rt.close()
    statuses = [r.get("status") for r in recs]
    ids = [r.get("id") for r in recs]
    return {
        "wall_s": round(wall, 3),
        "records": len(recs),
        "ok": statuses.count("ok"),
        "zero_lost": (sorted(ids) == sorted(ln["id"] for ln in lines)
                      and statuses.count("ok") == len(lines)),
        "zero_duplicates": (snap["router"]["duplicates"] == 0
                            and len(ids) == len(set(ids))),
        "victim_recovered": snap["backends"][backends[0].name]["lost"],
        "flight_dumps": len(list(Path(flight_dir).glob(
            "flightrec-*.trace.json"))),
    }


def steal_drill(victim, thief, lines, workdir):
    """Forced checkpoint-handoff steal from a loaded backend to an idle
    one; records the end-to-end recovery wall."""
    bfile = workdir / "steal_backends.txt"
    bfile.write_text(f"{victim.name}={victim.address}\n")
    from heat_tpu.fleet.registry import BackendRegistry
    from heat_tpu.fleet.router import FleetConfig, Router

    rt = Router(BackendRegistry(backends_file=bfile), "127.0.0.1", 0,
                FleetConfig(health_interval_s=0.3)).start()
    try:
        time.sleep(0.8)
        body = "".join(json.dumps(ln) + "\n" for ln in lines).encode()
        conn = http.client.HTTPConnection(rt.host, rt.port, timeout=60)
        conn.request("POST", "/v1/solve?wait=0", body=body)
        assert conn.getresponse().status == 202
        conn.close()
        time.sleep(1.5)   # victim mid-wave on the sink-slow work
        bfile.write_text(f"{victim.name}={victim.address}\n"
                         f"{thief.name}={thief.address}\n")
        deadline = time.monotonic() + 30
        while (rt.registry.get(thief.name) is None
               and time.monotonic() < deadline):
            time.sleep(0.1)
        ev = rt.steal(victim.name, thief.name, reason="lab")
        assert ev is not None, "steal refused"
        deadline = time.monotonic() + 600
        while rt.pending_count() and time.monotonic() < deadline:
            time.sleep(0.25)
        ok = 0
        for ln in lines:
            conn = http.client.HTTPConnection(rt.host, rt.port,
                                              timeout=30)
            conn.request("GET", f"/v1/requests/{ln['id']}")
            resp = conn.getresponse()
            rec = json.loads(resp.read())
            conn.close()
            ok += resp.status == 200 and rec.get("status") == "ok"
    finally:
        rt.close()
    return {
        "recovered_requests": ev["recovered"],
        "redriven_requests": ev["redriven"],
        "recovery_s": ev["wall_s"],
        "drain_s": ev["drain_s"],
        "resume_s": ev["resume_s"],
        "generation": ev["generation"],
        "all_ok": ok == len(lines),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--sink-ms", type=int, default=SINK_MS)
    ap.add_argument("--out", default=str(Path(__file__).parent
                                         / "fleet_lab.json"))
    ap.add_argument("--workdir", default=None,
                    help="scratch dir (default: a fresh TemporaryDirectory)")
    args = ap.parse_args(argv)

    import tempfile

    tmp = None
    if args.workdir:
        workdir = Path(args.workdir)
        workdir.mkdir(parents=True, exist_ok=True)
    else:
        tmp = tempfile.TemporaryDirectory(prefix="heat-tpu-fleet-lab-")
        workdir = Path(tmp.name)

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   str(workdir / "jax-cache"))
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")

    from serve_lab import build_requests

    work = sum(cfg.points * cfg.ntime
               for cfg in build_requests(args.requests))
    # a short sink-free wave covering all three sides pays each
    # backend's bucket compiles before any timed wave
    warmup = [dict(ln, inject="") for ln in build_lines(6, "w", sink_ms=0)]

    print(f"fleet_lab: starting 4 scaling + 2 kill-drill + 1 steal "
          f"backend processes under {workdir}", flush=True)
    fleet = [BackendProc(f"s{i}", workdir, env) for i in range(4)]
    killers = [BackendProc(f"k{i}", workdir, env) for i in range(2)]
    stealers = [BackendProc("victim", workdir, env)]
    everyone = fleet + killers + stealers
    rec = {}
    try:
        for b in everyone:
            b.wait_address()
        for b in everyone:
            b.wait_healthy()
        for b in everyone:
            warm_backend(b, warmup)

        walls, scaling = {}, {}
        sample = sorted({0, args.requests // 2, args.requests - 1})
        bit_identical = True
        for nb in (1, 2, 4):
            lines = build_lines(args.requests, f"f{nb}",
                                sink_ms=args.sink_ms)
            wall, recs, snap = run_wave(fleet[:nb], lines)
            per_backend = {n: b["delivered"]
                           for n, b in snap["backends"].items()}
            oks = sum(r.get("status") == "ok" for r in recs)
            walls[nb] = wall
            scaling[f"fleet_{nb}"] = {
                "wall_s": round(wall, 3),
                "points_per_s": round(work / wall, 1),
                "ok": oks, "records": len(recs),
                "per_backend_delivered": per_backend,
                "retries": snap["router"]["retries"],
            }
            print(f"fleet_lab: F={nb} wall {wall:.2f}s ok {oks}/"
                  f"{len(lines)} split {per_backend}", flush=True)
            assert oks == len(lines), scaling[f"fleet_{nb}"]
            if nb == 2:
                bit_identical = check_sample(fleet[:nb], lines, sample)

        kill = kill_drill(killers,
                          build_lines(args.requests, "kd",
                                      sink_ms=args.sink_ms),
                          workdir / "flightrec")
        print(f"fleet_lab: kill drill {kill}", flush=True)
        # double the sink on a deeper wave so the victim is genuinely
        # mid-flight when the steal fires (lanes occupied + queue work
        # for the manifest to cover — the drill must migrate, not mop up)
        steal = steal_drill(stealers[0], fleet[0],
                            build_lines(16, "st",
                                        sink_ms=2 * args.sink_ms),
                            workdir)
        print(f"fleet_lab: steal drill {steal}", flush=True)

        speedup2 = walls[1] / walls[2] if walls[2] > 0 else None
        speedup4 = walls[1] / walls[4] if walls[4] > 0 else None
        rec = {
            "bench": "fleet_lab",
            "config": {"requests": args.requests,
                       "sink_ms": args.sink_ms,
                       "population": "serve_lab sides 24/32/48",
                       "backend": "heat-tpu serve subprocess, lanes 4, "
                                  "chunk 16, buckets (32,48), "
                                  "engine-ckpt-interval 2",
                       "policy": "least-loaded"},
            "work_cell_steps": work,
            "scaling": scaling,
            "speedup_2_backends": round(speedup2, 2) if speedup2 else None,
            "speedup_4_backends": round(speedup4, 2) if speedup4 else None,
            "monotone_at_4": bool(walls[4] <= walls[2]),
            "fleet_bit_identical": bool(bit_identical),
            "kill_drill": kill,
            "kill_zero_lost": bool(kill["zero_lost"]),
            "kill_zero_duplicates": bool(kill["zero_duplicates"]),
            "steal_drill": steal,
            "steal_recovered_requests": steal["recovered_requests"],
            "steal_recovery_s": steal["recovery_s"],
        }
    finally:
        for b in everyone:
            b.stop()
        if tmp is not None:
            tmp.cleanup()

    write_atomic(Path(args.out), rec)
    print(json.dumps(rec, indent=2))
    passed = (rec["speedup_2_backends"] is not None
              and rec["speedup_2_backends"] >= 1.7
              and rec["monotone_at_4"]
              and rec["fleet_bit_identical"]
              and rec["kill_zero_lost"]
              and rec["kill_zero_duplicates"]
              and rec["steal_recovered_requests"] >= 1
              and steal["all_ok"]
              and kill["victim_recovered"]
              and kill["flight_dumps"] >= 1)
    print(f"fleet_lab: {'OK' if passed else 'FAILED'} — 2-backend "
          f"speedup {rec['speedup_2_backends']}x (gate >= 1.7), 4-backend "
          f"{rec['speedup_4_backends']}x monotone={rec['monotone_at_4']}; "
          f"kill drill lost=0:{rec['kill_zero_lost']} "
          f"dup=0:{rec['kill_zero_duplicates']}; steal moved "
          f"{rec['steal_recovered_requests']} mid-flight + "
          f"{steal['redriven_requests']} re-driven in "
          f"{rec['steal_recovery_s']}s")
    return 0 if passed else 1


if __name__ == "__main__":
    sys.exit(main())
