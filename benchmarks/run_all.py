"""The five BASELINE.json benchmark configs, measured end to end.

Run on the real chip: ``python benchmarks/run_all.py``
Smoke mode (CPU, shrunken sizes): ``python benchmarks/run_all.py --smoke``

Writes ``benchmarks/results.json`` (``results_smoke.json`` in smoke mode,
so smoke never clobbers chip-measured numbers) and prints one line per
config with points/s and the fraction of the HBM roofline (BASELINE.md's
analytic bound: bytes/point/step = 2*itemsize, v5e ~819 GB/s).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

# per-row subprocess isolation (supervise_rows) re-imports jax in every
# child; a persistent compile cache keeps that to a cache hit instead of a
# full recompile — set here so direct invocations get it, not only runs
# launched via watch_and_sweep.sh. Per-user path (ADVICE r4: a fixed
# world-shared /tmp path invites collisions/tampering on multi-user hosts);
# the stdlib-only _util mirror keeps jax out of this supervisor process —
# children inherit the env var.
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))

from _util import ensure_cache_env, write_atomic  # noqa: E402

ensure_cache_env()


def bench_one(name, cfg, repeat=1):
    import jax

    from heat_tpu import machine
    from heat_tpu.backends import solve

    # fetch=False: ICs build on device and the final field never crosses the
    # wire — only timings come back (GiB-scale fetches cost minutes tunneled).
    # warm_exec: one throwaway execution so lazy first-run runtime init
    # doesn't pollute solve_s. two_point_repeats: the overhead-corrected
    # headline protocol (timing.two_point_rate) measured alongside, so the
    # official table and bench.py's metric share one protocol; the raw
    # single-call number stays as the conservative figure (device backends
    # only — the numpy oracle has no dispatch overhead to cancel and
    # reports null there).
    res = solve(cfg, fetch=False, warm_exec=True, two_point_repeats=2)
    best, best_guard = res.timing, res.guard
    for _ in range(repeat - 1):
        r = solve(cfg, fetch=False, warm_exec=True, two_point_repeats=2)
        if r.timing.solve_s < best.solve_s:
            best, best_guard = r.timing, r.guard
    chip = machine.current()
    roofline = chip.roofline_points_per_s(cfg.dtype)
    tp = best.points_per_s_two_point
    row = {
        "baseline_chip": chip.label,
        "name": name,
        "measured_ts": time.time(),  # per-row: partial --only re-measures
                                     # merge into older rows (see main)
        "n": cfg.n, "ndim": cfg.ndim, "steps": best.steps,
        "dtype": cfg.dtype, "backend": cfg.backend,
        "mesh": list(cfg.mesh_shape) if cfg.mesh_shape else None,
        "solve_s": best.solve_s,
        "per_step_s": best.per_step_s,
        "points_per_s": best.points_per_s,
        "points_per_s_two_point": tp,
        "roofline_frac": best.points_per_s / roofline,
        "roofline_frac_two_point": tp / roofline if tp else None,
        "devices": len(jax.devices()),
        "platform": jax.default_backend(),
    }
    if best_guard is not None:
        # a row measured on the guard's DEGRADED program must say so —
        # silently recording the ~5x-slower xla fallback as the flagship
        # rate would poison the official table (VERDICT r4 #8)
        import dataclasses as _dc

        row["guard"] = _dc.asdict(best_guard)
    tp_note = (f"  two-point {tp:.3e} ({100 * tp / roofline:.1f}%)"
               if tp else "")
    print(f"{name:40s} {row['points_per_s']:.3e} pts/s  "
          f"({100 * row['roofline_frac']:.1f}% of HBM roofline)  "
          f"per-step {row['per_step_s'] * 1e6:.1f} us" + tp_note)
    return row


def _read_rows(out: Path):
    if not out.exists():
        return []
    try:
        return json.loads(out.read_text()).get("rows", [])
    except json.JSONDecodeError:  # pre-atomic-write corruption: start over
        return []


def _merge_rows(out: Path, rows):
    """Merge rows into the results file by name, preserving existing order
    (partial re-measures must not clobber other configs' numbers)."""
    old = _read_rows(out)
    fresh = {r["name"]: r for r in rows}
    merged = [fresh.pop(r["name"], r) for r in old] + list(fresh.values())
    write_atomic(out, {"ts": time.time(), "rows": merged})
    return merged


def supervise_rows(names, out: Path, row_timeout: int):
    """Run each config row in its own subprocess under a per-row deadline.

    Round-3 lesson: a single pathological row (the sharded fuse=32 case
    sat >25 min — tunnel stall or Mosaic compile cliff) ate the phase's
    whole timeout and the end-of-run write never happened, voiding every
    other row's measurement. Children merge their own row into
    results.json as they finish, so the artifact grows incrementally and
    a hung row costs only itself."""
    import subprocess

    if not out.exists():
        write_atomic(out, {"ts": time.time(), "rows": []})
    for name in names:
        cmd = [sys.executable, __file__, "--only", name, "--row-timeout", "0"]
        t_start = time.time()
        try:
            rc = subprocess.run(cmd, timeout=row_timeout).returncode
            err = None if rc == 0 else f"row subprocess rc={rc}"
        except subprocess.TimeoutExpired:
            err = f"timed out after {row_timeout}s"
        if err:
            # a child can merge its measured row and THEN stall in runtime
            # teardown (the tunneled-platform hang mode) — don't clobber a
            # measurement that already landed
            landed = any(r["name"] == name
                         and r.get("measured_ts", 0) >= t_start
                         and "error" not in r for r in _read_rows(out))
            if landed:
                print(f"{name:40s} child died post-measurement ({err}); "
                      f"row kept")
                continue
            print(f"{name:40s} FAILED: {err}")
            _merge_rows(out, [{"name": name, "error": err,
                               "measured_ts": time.time()}])
    print(f"wrote {out}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true", help="tiny sizes, CPU-safe")
    ap.add_argument("--only", help="substring filter on config name")
    ap.add_argument("--row-timeout", type=int, default=1500,
                    help="seconds per config row, each in its own "
                         "subprocess (0 = run rows in-process)")
    args = ap.parse_args()

    if args.smoke:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from heat_tpu.config import HeatConfig

    s = args.smoke
    try:
        import jax

        ndev = len(jax.devices())
    except Exception:
        ndev = 1

    configs = [
        # 1. serial/numpy oracle (python/serial analog)
        ("1_serial_256sq_numpy",
         HeatConfig(n=256, ntime=8 if s else 200, dtype="float64",
                    backend="serial")),
        # 2. single-chip Pallas 4096^2 (python/cuda analog: 4096^2 x 10000).
        # Step counts are sized so solve_s >= ~1 s: the tunneled platform
        # carries ~0.15 s of fixed dispatch+sync overhead per measurement,
        # which at short runs reads as a 4x throughput loss (round-2 finding).
        ("2_pallas_4096sq_f32",
         HeatConfig(n=256 if s else 4096, ntime=20 if s else 16384,
                    dtype="float32", backend="pallas")),
        # 3. 16384^2 over a 2-D mesh (mpi+cuda analog, BASELINE 4x4 target)
        ("3_sharded_16384sq_f32_mesh",
         HeatConfig(n=256 if s else 16384, ntime=20 if s else 500,
                    dtype="float32", backend="sharded",
                    mesh_shape=(4, 2) if (s and ndev >= 8) else None)),
        # 4. 3-D 512^3 7-point stencil
        ("4_pallas_512cube_f32",
         HeatConfig(n=64 if s else 512, ndim=3, ntime=10 if s else 3200,
                    dtype="float32", backend="pallas", sigma=1 / 6)),
        # 5. bf16 storage + f32 accumulate, 32768^2 (weak-scale flagship,
        #    fortran/input_all.dat: 32768^2 x 25000)
        ("5_bf16_32768sq",
         HeatConfig(n=512 if s else 32768, ntime=10 if s else 800,
                    dtype="bfloat16", backend="pallas")),
    ]

    # smoke mode must never clobber chip-measured numbers
    out = Path(__file__).parent / (
        "results_smoke.json" if args.smoke else "results.json")

    names = [n for n, _ in configs if not args.only or args.only in n]
    if args.row_timeout > 0 and not args.smoke:
        supervise_rows(names, out, args.row_timeout)
        return

    rows = []
    for name, cfg in configs:
        if name not in names:
            continue
        try:
            rows.append(bench_one(name, cfg))
        except Exception as e:  # record failures, keep measuring
            print(f"{name:40s} FAILED: {type(e).__name__}: {e}")
            rows.append({"name": name, "error": f"{type(e).__name__}: {e}",
                         "measured_ts": time.time()})
    if args.only and out.exists():
        # partial re-measure: merge by name instead of clobbering
        _merge_rows(out, rows)
    else:
        write_atomic(out, {"ts": time.time(), "rows": rows})
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
