"""One-process chip-tuning session: run everything that needs the real TPU,
in priority order, flushing results as they land (the tunnel can die at any
moment — earlier stages must not be lost to a later hang).

Run: PYTHONPATH=/root/repo:/root/.axon_site python benchmarks/tune_on_chip.py [stages...]
Stages default to: framework lab3d lab2d thin
"""

from __future__ import annotations

import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main():
    stages = sys.argv[1:] or ["framework", "lab3d", "lab2d", "thin"]
    t_start = time.time()

    import jax

    t0 = time.time()
    print(f"devices: {jax.devices()} (init {time.time() - t0:.0f}s)",
          flush=True)

    sys.path.insert(0, str(Path(__file__).resolve().parent))
    import kernel_lab as lab

    import gc

    def run_stage(name, fn):
        print(f"=== stage {name} (t+{time.time() - t_start:.0f}s)",
              flush=True)
        try:
            fn()
        except Exception as e:
            print(f"stage {name} FAILED: {type(e).__name__}: "
                  f"{str(e)[:300]}", flush=True)
        # drop the stage's device buffers (a failed stage's traceback pins
        # frames holding GiB-scale arrays — the next stage OOMs otherwise)
        gc.collect()

    def stage(name, fn):
        if name in stages:
            run_stage(name, fn)

    # 1. the shipped kernels at the BASELINE shapes (what results.json
    # needs); "framework:2d4096,3d512" filters to named cases (multiple
    # framework:<cases> args concatenate)
    fw_filter = [c for s in stages if s.startswith("framework:")
                 for c in s.split(":", 1)[1].split(",")]
    fw_cases = fw_filter or ["2d4096", "3d512", "2d32k_bf16", "2d32k_f32"]
    if fw_filter or "framework" in stages:
        run_stage("framework", lambda: lab.bench_framework(
            [lab.FRAMEWORK_CASES[k] for k in fw_cases]))

    # 2. 3D geometry sweep around the additive-model plan's pick
    # (64x64 k=8, measured 112% of the one-pass roofline)
    stage("lab3d", lambda: lab.bench_3d([
        (64, 64, 8, 8),
        (64, 128, 8, 8),
        (32, 64, 8, 8),
        (64, 64, 4, 8),
        (48, 96, 2, 8),
    ]))

    # 3. col-tiled 2D sweep at the bf16 flagship shape
    stage("lab2d", lambda: lab.bench_2d([
        (1024, 4096, 16, 128),
        (512, 8192, 16, 128),
        (256, 4096, 16, 128),
        (1024, 2048, 16, 128),
        (512, 4096, 32, 128),
    ]))

    # 4. thin-band variant A/B (shrink rows / bf16-native rolls) at 16384^2
    stage("thin", lambda: lab.bench_thin2d_variants(16384, "bfloat16", [
        ("shrink", 64, 16),
        ("bf16native", 64, 16),
        ("shrink", 128, 16),
        ("bf16native", 128, 16),
    ]))

    print(f"tuning session done in {time.time() - t_start:.0f}s", flush=True)


if __name__ == "__main__":
    main()
