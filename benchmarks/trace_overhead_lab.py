"""Tracing-overhead A/B: the observability layer must cost ~nothing.

Three runs of serve_lab's 64-request wave through the same engine
configuration, differing ONLY in the tracing mode (runtime/trace.py):

- ``off``        — ``trace_buffer=0``: no recording at all (the only
                   thing the hot path pays is one ``enabled`` test per
                   instrumentation site);
- ``flightrec``  — the default: the always-on flight recorder records
                   every event into the bounded ring, exports nothing;
- ``full``       — flight recorder + a ``--trace`` export written at
                   drain (the export happens after the wall clock the
                   wave is judged by stops, but it shares the process).

The acceptance gate (ISSUE 7): **full tracing stays within 2% of
tracing-off throughput**. Each mode runs ``--repeats`` times and the
best (min) wall is compared — the tracing delta is microseconds per
boundary, far below one-core CI jitter, so best-of-N is the honest
estimator of the *cost floor* the instrumentation adds.

    JAX_PLATFORMS=cpu python benchmarks/trace_overhead_lab.py [--repeats 3]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

from _util import write_atomic

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(Path(__file__).resolve().parent))

from serve_lab import build_requests  # noqa: E402  (benchmarks dir path)


def run_mode(reqs, lanes, chunk, depth, trace_buffer, trace_path=None):
    from heat_tpu.serve import Engine, ServeConfig

    eng = Engine(ServeConfig(lanes=lanes, chunk=chunk, buckets=(32, 48),
                             dispatch_depth=depth, emit_records=False,
                             trace_buffer=trace_buffer,
                             trace=str(trace_path) if trace_path else None))
    t0 = time.perf_counter()
    ids = [eng.submit(cfg) for cfg in reqs]
    records = eng.results()
    wall = time.perf_counter() - t0
    by_id = {r["id"]: r for r in records}
    ok = sum(by_id[i]["status"] == "ok" for i in ids)
    return wall, ok, len(eng.tracer)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--lanes", type=int, default=8)
    ap.add_argument("--chunk", type=int, default=16)
    ap.add_argument("--depth", type=int, default=2)
    ap.add_argument("--repeats", type=int, default=3,
                    help="runs per mode; best wall is compared")
    ap.add_argument("--out", default=str(Path(__file__).parent
                                         / "trace_overhead_lab.json"))
    args = ap.parse_args(argv)

    import tempfile

    reqs = build_requests(args.requests)
    work = sum(cfg.points * cfg.ntime for cfg in reqs)
    modes = {}
    tmp = Path(tempfile.mkdtemp(prefix="trace_lab_"))
    # one throwaway warm-up run primes the persistent compile cache and
    # the process (imports, first-touch allocators) so no mode eats the
    # cold start; round-robin the modes inside each repeat so slow drift
    # on a shared box hits all three equally
    run_mode(reqs, args.lanes, args.chunk, args.depth, trace_buffer=0)
    plan = [("off", dict(trace_buffer=0)),
            ("flightrec", dict(trace_buffer=65536)),
            ("full", dict(trace_buffer=65536,
                          trace_path=tmp / "full.trace.json"))]
    for rep in range(args.repeats):
        for name, kw in plan:
            wall, ok, events = run_mode(reqs, args.lanes, args.chunk,
                                        args.depth, **kw)
            m = modes.setdefault(name, {"walls": [], "ok": ok,
                                        "events": events})
            m["walls"].append(round(wall, 3))
            m["ok"] = min(m["ok"], ok)
            m["events"] = max(m["events"], events)

    for name, m in modes.items():
        m["wall_s"] = min(m["walls"])
        m["points_per_s"] = round(work / m["wall_s"], 1)

    off, frec, full = modes["off"], modes["flightrec"], modes["full"]
    overhead_full = full["wall_s"] / off["wall_s"] - 1.0
    overhead_frec = frec["wall_s"] / off["wall_s"] - 1.0
    trace_file = tmp / "full.trace.json"
    trace_ok = trace_file.exists() and bool(
        json.loads(trace_file.read_text())["traceEvents"])
    rec = {
        "bench": "trace_overhead_lab",
        "config": {"requests": args.requests, "lanes": args.lanes,
                   "chunk": args.chunk, "dispatch_depth": args.depth,
                   "repeats": args.repeats,
                   "buckets": [32, 48], "dtype": "float64"},
        "work_cell_steps": work,
        "off": off, "flightrec": frec, "full": full,
        "flightrec_overhead_frac": round(overhead_frec, 4),
        "full_overhead_frac": round(overhead_full, 4),
        "full_within_2pct_of_off": overhead_full <= 0.02,
        "trace_export_nonempty": trace_ok,
    }
    write_atomic(Path(args.out), rec)
    print(json.dumps(rec, indent=2))
    passed = (rec["full_within_2pct_of_off"] and trace_ok
              and all(m["ok"] == args.requests for m in modes.values())
              and full["events"] > 0 and off["events"] == 0)
    print(f"trace_overhead_lab: {'OK' if passed else 'FAILED'} — "
          f"off {off['wall_s']:.3f}s vs flight-recorder "
          f"{frec['wall_s']:.3f}s ({100 * overhead_frec:+.2f}%) vs full "
          f"--trace {full['wall_s']:.3f}s ({100 * overhead_full:+.2f}%); "
          f"{full['events']} event(s) recorded per full run")
    return 0 if passed else 1


if __name__ == "__main__":
    sys.exit(main())
