"""Numerics-observatory overhead A/B: solution-quality telemetry must
ride for free.

The ISSUE-15 design claim is "always-compute, host-gate": the chunk
programs ALWAYS fuse the four per-lane stats (residual, min, max, heat)
into the boundary vector, and ``--numerics`` gates only the host-side
ingestion — so toggling it changes no device program, no transfer count,
no output byte. This lab certifies the whole claim on the serve_lab
population:

- **on within 2% of off** (best-of-N walls, modes round-robined inside
  each repeat — the trace/prof_overhead_lab protocol);
- **bit-identity**: result npz files byte-identical with the observatory
  on vs off at dispatch depths 0 AND 2;
- **probe verification**: one real canary through a live Gateway
  (serve/probe.py Prober.run_once — POST /v1/solve, GET ?field=1)
  matches the closed-form sine-eigenmode decay within tolerance;
- **detector fires**: a seeded ``perturb`` fault trips exactly one
  maximum-principle violation (the observatory is measurably awake, not
  just cheap).

``heat-tpu perfcheck`` gates on the committed artifact's booleans.

    JAX_PLATFORMS=cpu python benchmarks/numerics_overhead_lab.py [--repeats 3]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

from _util import write_atomic

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(Path(__file__).resolve().parent))

from serve_lab import build_requests  # noqa: E402  (benchmarks dir path)


def run_mode(reqs, lanes, chunk, depth, numerics, out_dir=None):
    from heat_tpu.serve import Engine, ServeConfig

    eng = Engine(ServeConfig(lanes=lanes, chunk=chunk, buckets=(32, 48),
                             dispatch_depth=depth, emit_records=False,
                             numerics=numerics,
                             out_dir=str(out_dir) if out_dir else None))
    t0 = time.perf_counter()
    ids = [eng.submit(cfg) for cfg in reqs]
    records = eng.results()
    wall = time.perf_counter() - t0
    by_id = {r["id"]: r for r in records}
    ok = sum(by_id[i]["status"] == "ok" for i in ids)
    return wall, ok, eng, [by_id[i] for i in ids]


def bit_identity(reqs, lanes, chunk, depth, tmp) -> bool:
    """npz outputs byte-identical with the observatory on vs off."""
    dirs = {}
    for numerics in (False, True):
        d = Path(tmp) / f"d{depth}_{'on' if numerics else 'off'}"
        _, ok, _, recs = run_mode(reqs, lanes, chunk, depth, numerics,
                                  out_dir=d)
        if ok != len(reqs):
            return False
        dirs[numerics] = (d, recs)
    d_off, recs_off = dirs[False]
    d_on, _ = dirs[True]
    return all(
        (d_off / f"{r['id']}.npz").read_bytes()
        == (d_on / f"{r['id']}.npz").read_bytes()
        for r in recs_off)


def probe_verification() -> dict:
    """One REAL canary: Gateway on a localhost socket, Prober.run_once
    through HTTP, verdict against the closed-form decay."""
    from heat_tpu.serve import Engine, ServeConfig
    from heat_tpu.serve.gateway import Gateway
    from heat_tpu.serve.probe import Prober

    eng = Engine(ServeConfig(lanes=2, chunk=16, buckets=(64,),
                             emit_records=False, keep_fields=True))
    gw = Gateway(eng, "127.0.0.1", 0, start_engine=True).start()
    try:
        verdict = Prober(f"http://{gw.address}",
                         interval_s=3600.0).run_once()
    finally:
        gw.request_drain()
        gw.wait_drained(120)
        gw.close()
    return verdict


def detector_fires() -> bool:
    """A seeded finite perturbation must trip exactly one
    maximum-principle violation (guard=warn: observed, not guarded)."""
    from heat_tpu.config import HeatConfig
    from heat_tpu.runtime import faults
    from heat_tpu.serve import Engine, ServeConfig

    faults.reset()
    try:
        eng = Engine(ServeConfig(lanes=1, chunk=8, buckets=(32,),
                                 emit_records=False, keep_fields=True,
                                 inject="perturb@16:eps=100"))
        eng.submit(HeatConfig(n=24, ntime=64, dtype="float32"))
        recs = eng.results()
        snap = eng.numerics.snapshot()
        return (len(recs) == 1 and recs[0]["status"] == "ok"
                and snap["violation_total"] == 1)
    finally:
        faults.reset()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--lanes", type=int, default=8)
    ap.add_argument("--chunk", type=int, default=16)
    ap.add_argument("--depth", type=int, default=2)
    ap.add_argument("--bit-requests", type=int, default=12,
                    help="population for the per-depth npz bit-identity "
                         "check (writes 4 result sets)")
    ap.add_argument("--repeats", type=int, default=3,
                    help="runs per mode; best wall is compared")
    ap.add_argument("--out", default=str(Path(__file__).parent
                                         / "numerics_overhead_lab.json"))
    args = ap.parse_args(argv)

    import tempfile

    import jax

    reqs = build_requests(args.requests)
    work = sum(cfg.points * cfg.ntime for cfg in reqs)
    tmp = Path(tempfile.mkdtemp(prefix="numerics_lab_"))

    # one throwaway warm-up primes the persistent compile cache; modes
    # round-robin inside each repeat so drift on a shared box hits both
    run_mode(reqs, args.lanes, args.chunk, args.depth, numerics=False)
    modes = {}
    keep = {}
    for rep in range(args.repeats):
        for name, numerics in (("off", False), ("on", True)):
            wall, ok, eng, _ = run_mode(reqs, args.lanes, args.chunk,
                                        args.depth, numerics)
            m = modes.setdefault(name, {"walls": [], "ok": ok})
            m["walls"].append(round(wall, 3))
            m["ok"] = min(m["ok"], ok)
            keep[name] = eng
    for m in modes.values():
        m["wall_s"] = min(m["walls"])
        m["points_per_s"] = round(work / m["wall_s"], 1)

    overhead = modes["on"]["wall_s"] / modes["off"]["wall_s"] - 1.0
    bit0 = bit_identity(build_requests(args.bit_requests), args.lanes,
                        args.chunk, 0, tmp)
    bit2 = bit_identity(build_requests(args.bit_requests), args.lanes,
                        args.chunk, 2, tmp)
    probe = probe_verification()
    fires = detector_fires()
    on_snap = keep["on"].numerics.snapshot()

    rec = {
        "bench": "numerics_overhead_lab",
        "platform": jax.default_backend(),
        "config": {"requests": args.requests, "lanes": args.lanes,
                   "chunk": args.chunk, "dispatch_depth": args.depth,
                   "repeats": args.repeats, "buckets": [32, 48],
                   "dtype": "float64",
                   "bit_requests": args.bit_requests},
        "work_cell_steps": work,
        "off": modes["off"], "on": modes["on"],
        "on_overhead_frac": round(overhead, 4),
        "on_within_2pct_of_off": overhead <= 0.02,
        "bit_identical_depth0": bit0,
        "bit_identical_depth2": bit2,
        "probe_verification_ok": bool(probe["ok"]),
        "probe_error_norm": probe["error_norm"],
        "probe_latency_s": (None if probe["latency_s"] is None
                            else round(probe["latency_s"], 3)),
        "detector_fires_on_seeded_perturb": fires,
        # the "on" engine's end-of-drain observatory state: all lanes
        # retired (forget on every terminal path), totals monotone
        "on_steady_total": on_snap["steady_total"],
        "on_violation_total": on_snap["violation_total"],
        "on_lanes_retired": not on_snap["lanes"],
        "off_observatory_absent": keep["off"].numerics is None,
    }
    write_atomic(Path(args.out), rec)
    print(json.dumps(rec, indent=2))
    passed = (rec["on_within_2pct_of_off"] and bit0 and bit2
              and rec["probe_verification_ok"] and fires
              and rec["on_violation_total"] == 0
              and rec["on_lanes_retired"]
              and rec["off_observatory_absent"]
              and all(m["ok"] == args.requests for m in modes.values()))
    print(f"numerics_overhead_lab: {'OK' if passed else 'FAILED'} — "
          f"off {modes['off']['wall_s']:.3f}s vs observatory on "
          f"{modes['on']['wall_s']:.3f}s ({100 * overhead:+.2f}%; gate "
          f"<= +2%); bit-identical npz depth0={bit0} depth2={bit2}; "
          f"probe ok={rec['probe_verification_ok']} "
          f"(err {probe['error_norm']}); perturb detector fires={fires}")
    return 0 if passed else 1


if __name__ == "__main__":
    sys.exit(main())
