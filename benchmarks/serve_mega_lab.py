"""Two-tier placement lab (ISSUE 10): sharded mega-lanes co-scheduled
with packed vmapped lanes.

The claim under test: requests that PR 5 rejected as ``bucket-overflow``
now complete as mesh-spanning sharded mega-lanes — with zero overflow
rejections, npz payloads byte-identical to a solo ``drive()`` on the
sharded backend, and WITHOUT taxing the packed tier: packed-lane
aggregate throughput while a mega-lane is resident stays within 10% of a
mega-free drain of the identical small population (and within 10% of the
committed ``serve_lab.json`` engine number for the standard population).

Shape: a virtual 8-device CPU mesh (``--xla_force_host_platform_device_
count``, the test harness's develop-without-a-cluster story), the
serve_lab 64-small population plus oversized requests bigger than every
bucket. Two engines, two waves each:

- **baseline**: smalls only — wave 1 warms every compiled program, wave
  2 is the timed mega-free packed drain;
- **mega-resident**: oversized-first + smalls — wave 1 warms (including
  the mega seed/advance/crop programs, cached per (config, mesh) so the
  timed wave re-admits them compile-free), wave 2 is the timed
  co-scheduled drain.

Timed waves are warm on BOTH sides, so the 10% band measures steady-state
co-scheduling interference (the claim), not compile noise. On this
single-core CPU box the mesh and the packed lanes share one core, so the
mega tier's whole compute budget lands inside the band — on a real pod
the packed slice and the mesh overlap instead of contending, and this
gate only gets easier.

    python benchmarks/serve_mega_lab.py [--requests 64] [--virtual 8]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(Path(__file__).resolve().parent))

from _util import write_atomic  # noqa: E402


def _ensure_virtual_devices(count: int) -> None:
    """Force a multi-device CPU world BEFORE jax initializes (no-op when
    the harness — tests/conftest.py — already did)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={count}")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _drain(eng, cfgs):
    """Submit + drain one wave; returns (wall_s, {id: record})."""
    t0 = time.perf_counter()
    ids = [eng.submit(cfg) for cfg in cfgs]
    records = eng.results()
    wall = time.perf_counter() - t0
    by_id = {r["id"]: r for r in records}
    return wall, [by_id[i] for i in ids]


def _npz_payload(path):
    """(key -> (dtype, shape, bytes)) of one npz — the byte-identity
    comparison that survives zip-member timestamps."""
    import numpy as np

    with np.load(path) as z:
        return {k: (str(z[k].dtype), z[k].shape, z[k].tobytes())
                for k in z.files}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=64,
                    help="small-request population size (serve_lab's mix)")
    ap.add_argument("--lanes", type=int, default=8)
    ap.add_argument("--chunk", type=int, default=16)
    ap.add_argument("--depth", type=int, default=2)
    ap.add_argument("--virtual", type=int, default=8,
                    help="virtual CPU device count for the mega mesh")
    ap.add_argument("--waves", type=int, default=4,
                    help="small-population repeats per TIMED drain: the "
                         "10%% interference band is a steady-state claim, "
                         "so the packed denominator must dwarf the mega "
                         "tier's fixed admission cost (seed + IC + crop "
                         "programs) the way a real drain does")
    ap.add_argument("--oversized-side", type=int, default=96,
                    help="mega request grid side (> every bucket; must "
                         "divide the mesh axes)")
    ap.add_argument("--oversized-ntimes", default="32,16",
                    help="comma-separated step counts, one mega request "
                         "each")
    ap.add_argument("--out", default=str(Path(__file__).parent
                                         / "serve_mega_lab.json"))
    args = ap.parse_args(argv)
    _ensure_virtual_devices(args.virtual)

    import numpy as np

    import serve_lab
    from heat_tpu.backends import solve
    from heat_tpu.config import HeatConfig
    from heat_tpu.serve import Engine, ServeConfig
    from heat_tpu.serve.scheduler import _write_result

    import jax

    ndev = len(jax.devices())
    smalls = serve_lab.build_requests(args.requests)
    ntimes = [int(t) for t in str(args.oversized_ntimes).split(",") if t]
    big = [HeatConfig(n=args.oversized_side, ntime=t, dtype="float64",
                      bc=("edges", "ghost")[i % 2],
                      ic=("hat", "uniform")[i % 2])
           for i, t in enumerate(ntimes)]
    timed_smalls = smalls * max(1, args.waves)
    small_work = sum(c.points * c.ntime for c in timed_smalls)
    mega_work = sum(c.points * c.ntime for c in big)

    import shutil

    out_root = Path(args.out).parent / "_serve_mega_scratch"
    shutil.rmtree(out_root, ignore_errors=True)
    base_dir = out_root / "base"
    mega_dir = out_root / "mega"
    solo_dir = out_root / "solo"

    def make_engine(out_dir):
        # BOTH engines write npz results so the timed waves pay
        # symmetric writeback I/O — the ratio isolates co-scheduling,
        # not one side's disk traffic
        return Engine(ServeConfig(
            lanes=args.lanes, chunk=args.chunk, buckets=(32, 48),
            dispatch_depth=args.depth, emit_records=False,
            out_dir=str(out_dir), keep_fields=True))

    # --- baseline: packed-only engine, warm then timed --------------------
    base_eng = make_engine(base_dir)
    _drain(base_eng, smalls)                       # warm wave
    base_wall, base_recs = _drain(base_eng, timed_smalls)
    base_ok = sum(r["status"] == "ok" for r in base_recs)
    base_tput = small_work / base_wall

    # --- mega-resident: oversized first, smalls behind --------------------
    mega_eng = make_engine(mega_dir)
    _drain(mega_eng, big + smalls)                 # warm wave (compiles
    #                                                mega machinery too)
    compiles_before = mega_eng.mega_compiles
    mega_wall, mixed_recs = _drain(mega_eng, big + timed_smalls)
    mega_recs = mixed_recs[:len(big)]
    small_recs = mixed_recs[len(big):]
    mega_tput = small_work / mega_wall
    overflow_rejections = sum(
        1 for r in mixed_recs
        if r["status"] == "rejected"
        and "bucket-overflow" in str(r.get("error")))

    # byte-identity: the timed wave's mega npz payloads vs a solo sharded
    # drive() of each config, persisted through the same writer
    solo_dir.mkdir(parents=True, exist_ok=True)
    mega_identical = True
    for i, cfg in enumerate(big):
        rid = mega_recs[i]["id"]
        res = solve(cfg.with_(backend="sharded"))
        _write_result(solo_dir, f"solo-{i}", res.T, cfg)
        a = _npz_payload(mega_dir / f"{rid}.npz")
        b = _npz_payload(solo_dir / f"solo-{i}.npz")
        mega_identical = mega_identical and a == b
    # and the co-scheduled packed lanes vs the mega-free baseline drain
    packed_identical = all(
        np.array_equal(r["T"], b["T"])
        for r, b in zip(small_recs, base_recs)
        if r["status"] == "ok" and b["status"] == "ok")

    s = mega_eng.summary()
    ratio = mega_tput / base_tput if base_tput else None
    serve_lab_path = Path(__file__).parent / "serve_lab.json"
    vs_serve_lab = None
    if serve_lab_path.exists() and args.requests == 64:
        committed = json.loads(serve_lab_path.read_text())
        committed_pts = (committed.get("engine") or {}).get("points_per_s")
        if committed_pts:
            vs_serve_lab = mega_tput / committed_pts

    rec = {
        "bench": "serve_mega_lab",
        "config": {"requests": args.requests, "lanes": args.lanes,
                   "chunk": args.chunk, "dispatch_depth": args.depth,
                   "devices": ndev, "waves": args.waves,
                   "oversized_side": args.oversized_side,
                   "oversized_ntimes": ntimes,
                   "mega_lanes": s.get("mega_lanes")},
        "small_work_cell_steps": small_work,
        "mega_work_cell_steps": mega_work,
        "baseline": {"wall_s": round(base_wall, 3),
                     "packed_points_per_s": round(base_tput, 1),
                     "ok": base_ok},
        "mega_resident": {
            "wall_s": round(mega_wall, 3),
            "packed_points_per_s": round(mega_tput, 1),
            "ok": sum(r["status"] == "ok" for r in mixed_recs),
            "mega_statuses": sorted(r["status"] for r in mega_recs),
            "mega_placements": sorted(str(r.get("placement"))
                                      for r in mega_recs),
            "warm_mega_compiles": s.get("mega_compiles", 0)
                                  - compiles_before,
            "cost_model_placements": sorted(
                {e.get("placement") for e in s.get("cost_model") or []}),
        },
        "packed_throughput_ratio": round(ratio, 4) if ratio else None,
        "vs_serve_lab_engine": (round(vs_serve_lab, 4)
                                if vs_serve_lab else None),
        "mega_bit_identical": bool(mega_identical),
        "packed_bit_identical": bool(packed_identical),
        "zero_overflow_rejections": overflow_rejections == 0,
        "packed_within_10pct": bool(ratio is not None and ratio >= 0.9),
        "packed_within_10pct_of_serve_lab": (
            bool(vs_serve_lab >= 0.9) if vs_serve_lab is not None
            else None),
    }
    write_atomic(Path(args.out), rec)
    print(json.dumps(rec, indent=2))
    passed = (rec["mega_bit_identical"]
              and rec["packed_bit_identical"]
              and rec["zero_overflow_rejections"]
              and all(st == "ok"
                      for st in rec["mega_resident"]["mega_statuses"])
              and all(p == "mega"
                      for p in rec["mega_resident"]["mega_placements"])
              and rec["mega_resident"]["warm_mega_compiles"] == 0
              and rec["packed_within_10pct"]
              and rec["packed_within_10pct_of_serve_lab"] is not False)
    print(f"serve_mega_lab: {'OK' if passed else 'FAILED'} — packed "
          f"{mega_tput:.3g} pts/s with a mega-lane resident vs "
          f"{base_tput:.3g} mega-free ({rec['packed_throughput_ratio']}x; "
          f"vs committed serve_lab {rec['vs_serve_lab_engine']}); "
          f"{len(big)} oversized served as mega-lanes "
          f"(bit-identical={rec['mega_bit_identical']}, "
          f"overflow rejections={overflow_rejections})")
    return 0 if passed else 1


if __name__ == "__main__":
    sys.exit(main())
