"""Serving-engine chaos A/B: per-lane fault domains under poisoned load.

The ISSUE-5 claim, measured: quarantining a NaN lane at a chunk boundary
must cost the HEALTHY tenants (almost) nothing. One 64-request wave runs
twice through the dispatch-ahead engine:

- **clean**: every request well-posed (the serve_lab population);
- **chaos**: the SAME wave with ~10% of the requests poisoned via the
  per-request ``lane-nan@N`` injection (runtime/faults.py) — each
  poisoned lane must fail with a structured ``nonfinite`` record at its
  next chunk boundary while its co-scheduled lanes keep stepping.

Two acceptance gates ride in the artifact:

- healthy-request aggregate throughput (healthy cell-steps over the
  drain's wall clock) in the chaos run within 10% of the clean run —
  the quarantine path may cost at most boundary bookkeeping, never a
  stall of the batch;
- a sample of healthy results BIT-IDENTICAL between the two runs (the
  masking contract confines the poison to its own lane — a perf artifact
  must never certify a chaos engine that perturbs its neighbors).

    JAX_PLATFORMS=cpu python benchmarks/serve_chaos_lab.py [--requests 64]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

from _util import write_atomic

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

# every POISON_EVERY-th request is poisoned at mid-flight step 40 (inside
# every request's 96..128-step budget, past a few chunk boundaries so the
# lane has already survived finite verdicts)
POISON_EVERY = 10
POISON_STEP = 40


def build_waves(count: int):
    from serve_lab import build_requests

    clean = build_requests(count)
    chaos = [cfg.with_(inject=f"lane-nan@{POISON_STEP}")
             if i % POISON_EVERY == POISON_EVERY - 1 else cfg
             for i, cfg in enumerate(clean)]
    poisoned = [i for i in range(count) if i % POISON_EVERY == POISON_EVERY - 1]
    return clean, chaos, poisoned


def run_wave(reqs, lanes: int, chunk: int, depth: int):
    from heat_tpu.runtime import faults
    from heat_tpu.serve import Engine, ServeConfig

    faults.reset()  # per-spec firing state must not leak between waves
    eng = Engine(ServeConfig(lanes=lanes, chunk=chunk, buckets=(32, 48),
                             dispatch_depth=depth, emit_records=False))
    t0 = time.perf_counter()
    ids = [eng.submit(cfg) for cfg in reqs]
    records = eng.results()
    wall = time.perf_counter() - t0
    by_id = {r["id"]: r for r in records}
    return wall, eng, [by_id[i] for i in ids]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--lanes", type=int, default=8)
    ap.add_argument("--chunk", type=int, default=16)
    ap.add_argument("--depth", type=int, default=2)
    ap.add_argument("--out", default=str(Path(__file__).parent
                                         / "serve_chaos_lab.json"))
    args = ap.parse_args(argv)

    import numpy as np

    clean_reqs, chaos_reqs, poisoned = build_waves(args.requests)
    healthy = [i for i in range(args.requests) if i not in set(poisoned)]
    healthy_work = sum(clean_reqs[i].points * clean_reqs[i].ntime
                       for i in healthy)
    total_work = sum(cfg.points * cfg.ntime for cfg in clean_reqs)

    clean_wall, clean_eng, clean_recs = run_wave(
        clean_reqs, args.lanes, args.chunk, args.depth)
    chaos_wall, chaos_eng, chaos_recs = run_wave(
        chaos_reqs, args.lanes, args.chunk, args.depth)

    # healthy-request aggregate throughput: the tenants that did nothing
    # wrong, against the wall clock their wave actually took
    clean_tput = total_work / clean_wall
    chaos_tput = healthy_work / chaos_wall
    ratio = chaos_tput / (clean_tput * healthy_work / total_work)

    sample = sorted({healthy[0], healthy[len(healthy) // 2], healthy[-1]})
    bit_identical = all(
        np.array_equal(chaos_recs[i]["T"], clean_recs[i]["T"])
        for i in sample)
    quarantined_ok = all(chaos_recs[i]["status"] == "nonfinite"
                         for i in poisoned)
    healthy_ok = all(chaos_recs[i]["status"] == "ok" for i in healthy)

    s = chaos_eng.summary()
    rec = {
        "bench": "serve_chaos_lab",
        "config": {"requests": args.requests, "lanes": args.lanes,
                   "chunk": args.chunk, "dispatch_depth": args.depth,
                   "poisoned": len(poisoned),
                   "poison_spec": f"lane-nan@{POISON_STEP}"},
        "clean": {
            "wall_s": round(clean_wall, 3),
            "points_per_s": round(clean_tput, 1),
            "ok": sum(r["status"] == "ok" for r in clean_recs),
            "rejected": sum(r["status"] == "rejected" for r in clean_recs),
            "failed": sum(r["status"] not in ("ok", "rejected")
                          for r in clean_recs),
        },
        "chaos": {
            "wall_s": round(chaos_wall, 3),
            "healthy_points_per_s": round(chaos_tput, 1),
            "ok": sum(r["status"] == "ok" for r in chaos_recs),
            "rejected": sum(r["status"] == "rejected" for r in chaos_recs),
            "failed": sum(r["status"] not in ("ok", "rejected")
                          for r in chaos_recs),
            "nonfinite": sum(r["status"] == "nonfinite" for r in chaos_recs),
            "lanes_quarantined": s["lanes_quarantined"],
            "rollbacks": s["rollbacks"],
            "watchdog_fired": s["watchdog_fired"],
        },
        "healthy_throughput_ratio": round(ratio, 4),
        "healthy_within_10pct": ratio >= 0.9,
        "bit_identical_healthy_sample": bit_identical,
        "all_poisoned_quarantined": quarantined_ok,
        "all_healthy_ok": healthy_ok,
    }
    write_atomic(Path(args.out), rec)
    print(json.dumps(rec, indent=2))
    passed = (rec["healthy_within_10pct"] and bit_identical
              and quarantined_ok and healthy_ok
              and s["lanes_quarantined"] == len(poisoned))
    print(f"serve_chaos_lab: {'OK' if passed else 'FAILED'} — healthy "
          f"throughput under {len(poisoned)}/{args.requests} poisoned "
          f"load at {100 * ratio:.1f}% of clean "
          f"({rec['chaos']['healthy_points_per_s']:.4g} vs "
          f"{rec['clean']['points_per_s']:.4g} pts/s scaled); "
          f"{s['lanes_quarantined']} quarantined; bit-identical healthy "
          f"sample={bit_identical}")
    return 0 if passed else 1


if __name__ == "__main__":
    sys.exit(main())
