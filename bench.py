"""Headline benchmark: grid-points/sec/chip on the 4096^2 f32 stencil.

BASELINE.md: the reference publishes no numbers, so this repo establishes
the baseline. ``vs_baseline`` is reported against the *ideal* one-pass HBM
roofline on this chip class — 819 GB/s (v5e) / 2*itemsize = 1.024e11
points/s f32, the bound no one-kernel-launch-per-step design can exceed
(the same 2*itemsize denominator benchmarks/run_all.py and BASELINE.md
use; the reference's actual structure pays 2x that via its per-step
T_old=T device snapshot, fortran/cuda_kernel/heat.F90:32). vs_baseline > 1
therefore means the temporally blocked Pallas kernel beats every possible
one-pass implementation on this chip. The measured config mirrors the
reference's single-GPU benchmark shape (python/cuda/cuda.py:31-33: 4096^2,
10k steps; we run 8192 steps, identical steady-state per-step cost).

Timing uses a scalar device->host fetch as the completion fence:
``block_until_ready`` does not block on queued work on the tunneled
single-chip platform, and a full-buffer fetch over the tunnel costs seconds
(see heat_tpu/runtime/timing.py::sync).

Prints exactly one JSON line.
"""

from __future__ import annotations

import json
import time

N = 4096
STEPS = 8192
REPEATS = 3
# ideal one-pass-per-step roofline: 819 GB/s HBM / (2 * 4 B) per point per
# step f32 (read + write once; the reference's snapshot copy doubles this)
ROOFLINE_POINTS_PER_S = 1.024e11


def main() -> None:
    import jax
    import jax.numpy as jnp

    from heat_tpu.backends.pallas import make_advance
    from heat_tpu.config import HeatConfig
    from heat_tpu.grid import initial_condition
    from heat_tpu.runtime.timing import sync

    cfg = HeatConfig(n=N, ntime=STEPS, dtype="float32", ic="hat",
                     backend="pallas")
    # keep the pristine field on host: advance donates its input, and
    # device_put of an already-on-device array would alias the donated buffer
    T0 = initial_condition(cfg).astype("float32")
    advance = make_advance(cfg)

    compiled = None
    best = float("inf")
    for rep in range(REPEATS + 1):
        T = jax.device_put(jnp.asarray(T0))  # fresh device copy each rep
        if compiled is None:
            compiled = advance.lower(T, STEPS).compile()
        sync(T)  # fence the async H2D transfer out of the timed region
        t0 = time.perf_counter()
        out = compiled(T)
        sync(out)
        dt = time.perf_counter() - t0
        if rep > 0:  # rep 0 is the warm-up
            best = min(best, dt)

    pts_per_s = N * N * STEPS / best
    print(json.dumps({
        "metric": f"grid_points_per_sec_per_chip_{N}x{N}_f32_pallas",
        "value": pts_per_s,
        "unit": "points/s",
        "vs_baseline": pts_per_s / ROOFLINE_POINTS_PER_S,
    }))


if __name__ == "__main__":
    main()
