"""Headline benchmark: grid-points/sec/chip on the 4096^2 f32 stencil.

BASELINE.md: the reference publishes no numbers, so this repo establishes
the baseline. ``vs_baseline`` is reported against the *ideal* one-pass HBM
roofline on this chip class — 819 GB/s (v5e) / 2*itemsize = 1.024e11
points/s f32, the bound no one-kernel-launch-per-step design can exceed
(the same 2*itemsize denominator benchmarks/run_all.py and BASELINE.md
use; the reference's actual structure pays 2x that via its per-step
T_old=T device snapshot, fortran/cuda_kernel/heat.F90:32). vs_baseline > 1
therefore means the temporally blocked Pallas kernel beats every possible
one-pass implementation on this chip. The measured config mirrors the
reference's single-GPU benchmark shape (python/cuda/cuda.py:31-33: 4096^2,
10k steps; we run 8192 steps, identical steady-state per-step cost).

Capture robustness (round 3): the tunneled TPU backend is transiently
unavailable — round 1's driver capture died with rc=1 on
"Unable to initialize backend 'axon'", and a bare device probe can HANG
rather than raise. So the measurement runs in a *subprocess* under a hard
timeout (a hang becomes a retryable failure), the supervisor retries with
backoff, and on final failure it still prints exactly one parseable JSON
line carrying an "error" field — the bench never again exits without a
machine-readable verdict. Run with ``--worker`` to execute the measurement
inline (no supervision).

Round 2's failure mode was the *opposite* overshoot: the retry ladder
spanned ~3.5 h (designed for tunnel outages) and the external capturer's
own deadline killed the supervisor mid-ladder (rc=124 = GNU timeout's
SIGTERM), voiding the one-line guarantee from outside. Two defenses now:

1. **Total wall budget** (``HEAT_BENCH_TOTAL_BUDGET_S``, default 540 s):
   attempts + backoff are scheduled against a single deadline; on budget
   exhaustion the supervisor prints the error-JSON line and exits while
   still alive. The budget must sit inside any plausible external watchdog
   (round 2's killed somewhere past 900 s).
2. **Signal backstop**: SIGTERM/SIGINT/SIGHUP print the error line before
   dying, so even a deadline-kill from outside leaves a parseable verdict
   (GNU timeout sends SIGTERM; only ``-k`` escalates to SIGKILL).

Timing uses a scalar device->host fetch as the completion fence:
``block_until_ready`` does not block on queued work on the tunneled
single-chip platform, and a full-buffer fetch over the tunnel costs seconds
(see heat_tpu/runtime/timing.py::sync).

Prints exactly one JSON line on stdout.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

N = 4096
STEPS = 8192
REPEATS = 3
# NOTE: the supervisor must know the metric string WITHOUT importing
# heat_tpu (a broken import must still yield one parseable error line), so
# this literal intentionally mirrors heat_tpu.benchmark.metric_name(N);
# measure() asserts they agree.
METRIC = f"grid_points_per_sec_per_chip_{N}x{N}_f32_pallas"


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


# per-attempt wall clock: H2D of the 64 MiB field over the ~8 MB/s tunnel
# (~10 s), first compile (tens of s), lazy runtime init (tens of s on a cold
# tunnel), then ~1 s/rep of actual compute — 420 s is a hang detector, not
# a tight budget
ATTEMPT_TIMEOUT_S = _env_int("HEAT_BENCH_TIMEOUT_S", 420)
ATTEMPTS = _env_int("HEAT_BENCH_ATTEMPTS", 4)
# everything — attempts AND backoff — is scheduled against this one
# deadline; it must sit inside any external capturer's kill window
# (round 2's was >900 s; round 2's 3.5 h ladder was killed from outside)
TOTAL_BUDGET_S = _env_int("HEAT_BENCH_TOTAL_BUDGET_S", 540)
# an attempt with less runway than this can't finish even cache-warm
_MIN_ATTEMPT_S = 45
BACKOFF_S = (15, 30, 60)
# failure signatures worth retrying (transient tunnel/backend states); any
# other worker crash is deterministic — fail fast with the error line.
# (Timeouts always retry; this list is only consulted for nonzero exits.)
_RETRYABLE = ("Unable to initialize backend", "UNAVAILABLE", "DEADLINE")


# every successful measurement is cached here so an outage-era error line
# can still carry the last real chip number (clearly timestamped, under
# "last_good" — never as the headline value)
_LAST_GOOD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "benchmarks", "last_bench.json")


def measure() -> None:
    """The actual benchmark (runs in the supervised subprocess); the
    measurement itself lives in heat_tpu.benchmark — ONE definition shared
    with the `heat-tpu bench` CLI subcommand."""
    # persist compiles across attempts (and across rehearsal runs of this
    # same measurement): a warm cache turns the ~1 min kernel compile into
    # a cache hit, keeping attempts comfortably inside the budget
    from heat_tpu.utils import ensure_cache_env

    ensure_cache_env()  # per-user default (ADVICE r4); user env honored
    from heat_tpu import benchmark

    # N/STEPS/REPEATS are duplicated here so the supervisor never imports
    # heat_tpu; the metric-name assert below only catches N drift, so pin
    # STEPS/REPEATS explicitly or the measurement silently changes under
    # the same metric string
    assert (STEPS, REPEATS) == (benchmark.STEPS, benchmark.REPEATS), (
        (STEPS, REPEATS), (benchmark.STEPS, benchmark.REPEATS))
    record = benchmark.headline_measure(n=N, steps=STEPS, repeats=REPEATS)
    assert record["metric"] == METRIC, (record["metric"], METRIC)
    try:  # best-effort cache; the measurement already succeeded
        cached = dict(record, measured_ts=time.time())
        tmp = _LAST_GOOD + ".tmp"
        with open(tmp, "w") as f:
            json.dump(cached, f)
        os.replace(tmp, _LAST_GOOD)
    except OSError:
        pass
    # flush: the pipe is block-buffered and JAX atexit teardown can hang
    # before interpreter stdio flush — the supervisor's salvage path needs
    # this line physically in the pipe the moment it's produced
    print(json.dumps(record), flush=True)


def _parse_result_line(stdout: str):
    """The worker's result is the last stdout line that parses as a JSON
    object with our metric (tolerates stray runtime chatter on stdout)."""
    for line in reversed(stdout.strip().splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(obj, dict) and obj.get("metric") == METRIC:
            return obj
    return None


def _error_line(err: str) -> str:
    rec = {
        "metric": METRIC,
        "value": 0.0,
        "unit": "points/s",
        "vs_baseline": 0.0,
        "error": err,
    }
    try:  # attach the last real chip measurement, clearly timestamped —
        # informative during an outage, never the headline value
        with open(_LAST_GOOD) as f:
            cached = json.load(f)
        # a stale cache from a different N/STEPS configuration must not
        # ride along under this metric's error line
        if isinstance(cached, dict) and cached.get("metric") == METRIC:
            rec["last_good"] = cached
    except (OSError, json.JSONDecodeError):
        pass
    return json.dumps(rec)


def _run_worker(holder, timeout: float) -> subprocess.CompletedProcess:
    """``subprocess.run`` equivalent that parks the live Popen in
    ``holder[0]`` so the signal backstop can reap it — an orphaned worker
    would keep the single tunneled chip busy (and block on its readerless
    stdout pipe) for up to ATTEMPT_TIMEOUT_S after the supervisor died."""
    p = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--worker"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    holder[0] = p
    try:
        out, err = p.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        p.kill()
        out, err = p.communicate()
        raise subprocess.TimeoutExpired(p.args, timeout, output=out,
                                        stderr=err)
    finally:
        holder[0] = None
    return subprocess.CompletedProcess(p.args, p.returncode, out, err)


def supervise() -> int:
    """Run ``measure`` in a subprocess under a total wall budget; always
    print one parseable JSON line — even when killed by an external
    deadline (SIGTERM backstop)."""
    t0 = time.monotonic()
    deadline = t0 + TOTAL_BUDGET_S
    last_err = "no attempt ran"
    worker = [None]  # the in-flight Popen, visible to the signal handler

    def _die(signum, frame):  # noqa: ARG001 — signal handler signature
        # an external watchdog beat our budget: reap the worker (it would
        # otherwise keep holding the chip for up to ATTEMPT_TIMEOUT_S),
        # emit the verdict line, then exit without interpreter teardown
        # (JAX atexit can hang on the tunnel — that's how round 2 died)
        if worker[0] is not None:
            try:
                worker[0].kill()
            except OSError:
                pass
        print(_error_line(
            f"killed by signal {signum} at "
            f"{time.monotonic() - t0:.0f}s; last: {last_err}"), flush=True)
        os._exit(1)

    for sig in (signal.SIGTERM, signal.SIGINT, signal.SIGHUP):
        signal.signal(sig, _die)

    for attempt in range(1, ATTEMPTS + 1):
        remaining = deadline - time.monotonic()
        eff_timeout = min(ATTEMPT_TIMEOUT_S,
                          remaining - min(5.0, 0.1 * remaining))
        if eff_timeout < _MIN_ATTEMPT_S:
            last_err += (f" | budget exhausted before attempt {attempt} "
                         f"({TOTAL_BUDGET_S}s total)")
            break
        try:
            proc = _run_worker(worker, timeout=eff_timeout)
        except subprocess.TimeoutExpired as e:
            # the worker may have finished the measurement and printed its
            # result, then hung in runtime teardown over the flaky tunnel —
            # salvage a valid result line before declaring the attempt dead
            out = e.stdout or ""  # bytes on POSIX even in text mode
            if isinstance(out, bytes):
                out = out.decode(errors="replace")
            result = _parse_result_line(out)
            if result is not None:
                print(json.dumps(result))
                return 0
            last_err = (f"attempt {attempt}: no result within "
                        f"{e.timeout:.0f}s (hung backend init?)")
        except OSError as e:  # spawn failure (ENOMEM etc.)
            last_err = f"attempt {attempt}: failed to spawn worker: {e}"
        else:
            result = _parse_result_line(proc.stdout)
            if result is not None:
                # a parsed result is a completed measurement even if runtime
                # teardown crashed afterwards (nonzero rc) — same salvage
                # rule as the timeout branch
                print(json.dumps(result))
                return 0
            full = (proc.stderr or "") + (proc.stdout or "")
            tail = full.strip().splitlines()
            last_err = (f"attempt {attempt}: rc={proc.returncode}: "
                        + " | ".join(tail[-3:]))
            if not any(sig in full for sig in _RETRYABLE):
                # deterministic crash (import error, bad config, code bug):
                # retrying reruns the identical failure — emit the verdict now
                print(f"bench attempt {attempt}/{ATTEMPTS} failed "
                      f"(non-retryable): {last_err}", file=sys.stderr)
                break
        print(f"bench attempt {attempt}/{ATTEMPTS} failed: {last_err}",
              file=sys.stderr)
        if attempt < ATTEMPTS:
            backoff = BACKOFF_S[min(attempt - 1, len(BACKOFF_S) - 1)]
            # never sleep past the point where another attempt fits
            runway = deadline - time.monotonic() - _MIN_ATTEMPT_S
            if runway <= 0:
                continue  # loop header will record budget exhaustion
            time.sleep(min(backoff, runway))
    # final failure: still emit one machine-readable line (round 1's capture
    # produced rc=1 with nothing parseable — never again)
    print(_error_line(last_err), flush=True)
    return 1


def main() -> int:
    if "--worker" in sys.argv:
        measure()
        return 0
    try:
        return supervise()
    except Exception as e:  # the one-parseable-line contract survives bugs
        print(_error_line(f"supervisor crashed: {e!r}"), flush=True)
        return 1


if __name__ == "__main__":
    sys.exit(main())
