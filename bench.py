"""Headline benchmark: grid-points/sec/chip on the 4096^2 f32 stencil.

BASELINE.md: the reference publishes no numbers, so this repo establishes
the baseline. ``vs_baseline`` is reported against the analytic HBM roofline
for this chip class (v5e: ~819 GB/s / 8 bytes-per-point-per-step f32
= ~1.0e11 points/s) — i.e. the fraction of the hardware bound achieved.
The measured config mirrors the reference's single-GPU benchmark shape
(python/cuda/cuda.py:31-33: 4096^2, 10k steps; we run 2000 steps, identical
steady-state per-step cost).

Prints exactly one JSON line.
"""

from __future__ import annotations

import json
import time

N = 4096
STEPS = 2000
ROOFLINE_POINTS_PER_S = 1.0e11  # v5e HBM-bound estimate (BASELINE.md)


def main() -> None:
    import jax
    import jax.numpy as jnp

    from heat_tpu.backends.pallas import make_advance
    from heat_tpu.config import HeatConfig
    from heat_tpu.grid import initial_condition

    cfg = HeatConfig(n=N, ntime=STEPS, dtype="float32", ic="hat",
                     backend="pallas")
    T = jax.device_put(jnp.asarray(initial_condition(cfg), jnp.float32))
    advance = make_advance(cfg)

    compiled = advance.lower(T, STEPS).compile()
    T = jax.block_until_ready(compiled(T))  # warm run (also checks execution)
    t0 = time.perf_counter()
    T = jax.block_until_ready(compiled(T))
    dt = time.perf_counter() - t0

    pts_per_s = N * N * STEPS / dt
    print(json.dumps({
        "metric": f"grid_points_per_sec_per_chip_{N}x{N}_f32_pallas",
        "value": pts_per_s,
        "unit": "points/s",
        "vs_baseline": pts_per_s / ROOFLINE_POINTS_PER_S,
    }))


if __name__ == "__main__":
    main()
