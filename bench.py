"""Headline benchmark: grid-points/sec/chip on the 4096^2 f32 stencil.

BASELINE.md: the reference publishes no numbers, so this repo establishes
the baseline. ``vs_baseline`` is reported against the analytic HBM roofline
for a one-step-per-pass stencil on this chip class (v5e: ~819 GB/s at
16 bytes/point/step f32 = ~5.1e10 points/s) — i.e. how far past the naive
design (the reference's one-kernel-launch-per-step model) the temporally
blocked Pallas kernel gets. The measured config mirrors the reference's
single-GPU benchmark shape (python/cuda/cuda.py:31-33: 4096^2, 10k steps;
we run 8192 steps, identical steady-state per-step cost).

Timing uses a scalar device->host fetch as the completion fence:
``block_until_ready`` does not block on queued work on the tunneled
single-chip platform, and a full-buffer fetch over the tunnel costs seconds
(see heat_tpu/runtime/timing.py::sync).

Prints exactly one JSON line.
"""

from __future__ import annotations

import json
import time

N = 4096
STEPS = 8192
REPEATS = 3
# naive one-pass-per-step roofline: 819 GB/s HBM / 16 B per point per step
ROOFLINE_POINTS_PER_S = 5.1e10


def main() -> None:
    import jax
    import jax.numpy as jnp

    from heat_tpu.backends.pallas import make_advance
    from heat_tpu.config import HeatConfig
    from heat_tpu.grid import initial_condition
    from heat_tpu.runtime.timing import sync

    cfg = HeatConfig(n=N, ntime=STEPS, dtype="float32", ic="hat",
                     backend="pallas")
    # keep the pristine field on host: advance donates its input, and
    # device_put of an already-on-device array would alias the donated buffer
    T0 = initial_condition(cfg).astype("float32")
    advance = make_advance(cfg)

    compiled = None
    best = float("inf")
    for rep in range(REPEATS + 1):
        T = jax.device_put(jnp.asarray(T0))  # fresh device copy each rep
        if compiled is None:
            compiled = advance.lower(T, STEPS).compile()
        sync(T)  # fence the async H2D transfer out of the timed region
        t0 = time.perf_counter()
        out = compiled(T)
        sync(out)
        dt = time.perf_counter() - t0
        if rep > 0:  # rep 0 is the warm-up
            best = min(best, dt)

    pts_per_s = N * N * STEPS / best
    print(json.dumps({
        "metric": f"grid_points_per_sec_per_chip_{N}x{N}_f32_pallas",
        "value": pts_per_s,
        "unit": "points/s",
        "vs_baseline": pts_per_s / ROOFLINE_POINTS_PER_S,
    }))


if __name__ == "__main__":
    main()
