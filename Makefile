# Convenience targets mirroring the reference's per-variant makefiles
# (fortran/*/makefile: main/init/out/clean) in one place.

PY ?= python
# tier1 needs pipefail (a dash /bin/sh has no `set -o pipefail`)
SHELL := /bin/bash

.PHONY: test tier1 chaos race lint check audit bench bench-all bench-smoke chip-check \
        weak-scaling collective-overhead exchange-lab sharded3d-check sweep \
        overlap-ab compile-bisect topology-schedule topology-validate \
        serve-lab serve-chaos-lab frontend-lab trace-lab prof-lab \
        numerics-lab steady-lab lane-lab mega-lab resume-lab fleet-lab \
        resilience-lab cache-lab perfcheck native run viz clean

test:
	$(PY) -m pytest tests/ -q

tier1:          # the ROADMAP.md tier-1 verify command, verbatim semantics
	set -o pipefail; rm -f /tmp/_t1.log; \
	timeout -k 10 870 env JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q \
	  -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
	  -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; \
	rc=$${PIPESTATUS[0]}; \
	echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); \
	exit $$rc

chaos:          # the full-fidelity chaos suite tier-1 deselects (slow
                # marker): supervisor crash-resume e2e over real 2-process
                # worlds + the serve per-lane fault-domain e2e
	env JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_chaos.py -q -m slow \
	  -p no:cacheprovider

race:           # the dynamic race sanitizer over the chaos + serving
                # e2e surface (ISSUE 14): every scheduler/writer/tracer/
                # gateway wave re-run with HEAT_TPU_RACECHECK=1 armed —
                # a cross-thread write with an empty candidate lockset
                # raises RaceError and fails the suite
	env JAX_PLATFORMS=cpu HEAT_TPU_RACECHECK=1 $(PY) -m pytest \
	  tests/test_chaos.py tests/test_serve.py tests/test_gateway.py \
	  tests/test_fleet.py tests/test_solvecache.py -q -p no:cacheprovider

lint:           # ruff when installed; syntax-level fallback otherwise
	@if $(PY) -m ruff --version >/dev/null 2>&1; then \
	  $(PY) -m ruff check heat_tpu tests benchmarks; \
	elif command -v ruff >/dev/null 2>&1; then \
	  ruff check heat_tpu tests benchmarks; \
	else \
	  echo "lint: ruff not installed — falling back to compileall syntax check"; \
	  $(PY) -m compileall -q heat_tpu tests benchmarks; \
	fi

check: lint     # the invariant gate (ISSUE 11 + 13 + 14): generic lint
                # + the project-native analyzer (hot-path purity, lock
                # discipline, traced determinism, Mosaic kernel safety,
                # race lockset/guard-map) + the record-schema and
                # guard-map drift gates — all in heat-tpu check — plus
                # the fast tier of the program auditor (digest /
                # donation / purity / budget contracts over traced
                # jaxprs; full audit = `make audit` / extras_r5c)
	$(PY) -m heat_tpu check
	env JAX_PLATFORMS=cpu $(PY) -m heat_tpu audit --fast

audit:          # the full program auditor (ISSUE 13): every registered
                # family traced to jaxpr + AOT StableHLO on abstract
                # inputs (no device) and gated on all five contract
                # families, dtype discipline and roofline extraction
                # included
	env JAX_PLATFORMS=cpu $(PY) -m heat_tpu audit

bench:
	$(PY) bench.py

bench-all:
	$(PY) benchmarks/run_all.py

chip-check:
	$(PY) benchmarks/chip_check.py

bench-smoke:
	$(PY) benchmarks/run_all.py --smoke

weak-scaling:
	$(PY) benchmarks/weak_scaling.py --virtual 8

collective-overhead:   # measured anchor for the weak-scaling projection
	$(PY) benchmarks/collective_overhead.py

exchange-lab:          # where does the per-exchange cost go (HLO census)
	$(PY) benchmarks/exchange_lab.py

sharded3d-check:       # 512^3 sharded fuse-depth no-regression
	$(PY) benchmarks/sharded3d_check.py

overlap-ab:            # exchange=overlap vs indep on chip
	$(PY) benchmarks/overlap_ab.py

compile-bisect:        # fuse-depth compile-time curve (on chip)
	$(PY) benchmarks/compile_bisect.py

# the chipless labs: AOT topology compile, no tunnel involved
topology-schedule:     # multi-chip schedule census (overlap evidence)
	$(PY) benchmarks/topology_schedule.py

topology-validate:     # cross-chip machine-model compile validation
	$(PY) benchmarks/topology_validate.py

serve-lab:             # serving A/B: dispatch-ahead vs sync fallback vs
                       # sequential solos (boundary-wait + device-idle est.)
	env JAX_PLATFORMS=cpu $(PY) benchmarks/serve_lab.py

serve-chaos-lab:       # serving chaos A/B: clean wave vs ~10% lane-nan
                       # poisoned (quarantine cost on healthy tenants)
	env JAX_PLATFORMS=cpu $(PY) benchmarks/serve_chaos_lab.py

frontend-lab:          # online front-end A/B: Poisson arrivals, EDF vs
                       # FIFO deadline-hit rate + policy-layer cost check
	env JAX_PLATFORMS=cpu $(PY) benchmarks/serve_frontend_lab.py

trace-lab:             # tracing-overhead A/B: off vs flight-recorder vs
                       # full --trace on the serve_lab wave (<= 2% gate)
	env JAX_PLATFORMS=cpu $(PY) benchmarks/trace_overhead_lab.py

prof-lab:              # observatory-overhead A/B: full cost-model/ledger/
                       # watermark/burn-rate metering vs off (<= 2% gate,
                       # npz bit-identity at depths 0 and 2)
	env JAX_PLATFORMS=cpu $(PY) benchmarks/prof_overhead_lab.py

numerics-lab:          # numerics-observatory A/B: boundary-vector stats
                       # ingestion vs off (<= 2% gate, npz bit-identity at
                       # depths 0 and 2, live-gateway probe verification)
	env JAX_PLATFORMS=cpu $(PY) benchmarks/numerics_overhead_lab.py

steady-lab:            # semantic-scheduling A/B: until=steady early exit
                       # vs fixed-step (>= 1.5x effective throughput gate;
                       # steady + co-lane bit-identity, zero added D2H)
	env JAX_PLATFORMS=cpu $(PY) benchmarks/serve_steady_lab.py

lane-lab:              # serve lane-kernel A/B: Pallas lane program vs XLA
                       # lane program vs solo Pallas drives (bit-identity
                       # hard gate; perf gate on TPU, informational on CPU)
	env JAX_PLATFORMS=cpu $(PY) benchmarks/serve_lane_kernel_lab.py
	env JAX_PLATFORMS=cpu $(PY) benchmarks/lane_kernel_compile_check.py

mega-lab:              # two-tier placement A/B (virtual 8-device mesh):
                       # oversized requests served as sharded mega-lanes,
                       # npz byte-identity vs solo sharded drive, packed
                       # throughput within 10% with a mega-lane resident
	env JAX_PLATFORMS=cpu $(PY) benchmarks/serve_mega_lab.py

resume-lab:            # zero-downtime serving A/B: uninterrupted wave vs
                       # kill-at-50%-then-resume (npz byte-identity over
                       # all 64 requests, zero re-stepped chunks, recovery
                       # overhead = one manifest load + lane reseed)
	env JAX_PLATFORMS=cpu $(PY) benchmarks/serve_resume_lab.py

fleet-lab:             # pod-scale fleet: 1/2/4 serve subprocesses behind
                       # the router (>= 1.7x at 2 backends, monotone at
                       # 4), SIGKILL drill with zero lost/duplicated
                       # requests, forced checkpoint-handoff steal with
                       # recovery overhead recorded
	env JAX_PLATFORMS=cpu $(PY) benchmarks/fleet_lab.py

resilience-lab:        # fleet resilience drills: flapping backend (breaker
                       # + canary re-admission, availability >= 0.99, p99
                       # <= 1.5x, zero steal thrash), mid-stream cut with
                       # exactly-once re-drive, hedged interactive tail,
                       # deadline shedding with zero billed device steps
	env JAX_PLATFORMS=cpu $(PY) benchmarks/fleet_resilience_lab.py

cache-lab:             # solve-cache A/B: repeat-heavy wave cold vs warm
                       # (warm >= 5x, full hits byte-identical + zero
                       # device dispatch, prefix steps exactly the delta,
                       # --cache off bit-identical)
	env JAX_PLATFORMS=cpu $(PY) benchmarks/serve_cache_lab.py

perfcheck:             # CI perf gate: fresh prof-lab vs committed baseline
                       # (tolerance band) + every committed lab's internal
                       # gates + cost-model-vs-calibration cross-check
	env JAX_PLATFORMS=cpu $(PY) -m heat_tpu perfcheck

sweep:                 # flap-tolerant full chip queue
	bash benchmarks/watch_and_sweep.sh

native:
	$(MAKE) -C heat_tpu/io/native

run:            # ≙ the reference's `make main && ./a.out`
	$(PY) -m heat_tpu run

viz:            # ≙ the reference's `make out` (plot soln.dat)
	$(PY) -m heat_tpu viz soln.dat

clean:
	rm -rf __pycache__ .pytest_cache checkpoints
	$(MAKE) -C heat_tpu/io/native clean
