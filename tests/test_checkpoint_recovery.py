"""Checkpoint edge cases on the resume path (ISSUE 2 satellites): the
time-travel cap, cross-process resume-step agreement with missing shard
files, and fingerprint rejection through the public CLI."""

import pytest

import heat_tpu.backends.common as common
from heat_tpu.backends import solve
from heat_tpu.cli import main
from heat_tpu.config import HeatConfig
from heat_tpu.runtime import checkpoint


def test_latest_max_step_time_travel_cap(tmp_path):
    """Resuming a run whose ntime is SMALLER than an old checkpoint must
    not time-travel past it: latest(max_step=...) caps discovery."""
    d = tmp_path / "ck"
    cfg = HeatConfig(n=16, ntime=8, dtype="float64", backend="xla",
                     checkpoint_every=2, checkpoint_dir=str(d))
    solve(cfg)  # checkpoints at 2, 4, 6, 8
    assert checkpoint.latest_step(cfg) == 8
    assert checkpoint.latest_step(cfg, max_step=5) == 4
    assert checkpoint.latest_step(cfg, max_step=1) is None
    # end to end: a shorter re-run resumes at its own ntime, not at 8
    res = solve(cfg.with_(ntime=6))
    assert res.start_step == 6


def test_agree_resume_step_subset_missing(monkeypatch):
    """A crash between one process's save and the others' leaves a subset
    with no shard file: everyone must agree on the MINIMUM, and 'no file
    anywhere in the subset' means all fall back together — never a silent
    IC start against peers mid-run."""
    import jax

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    # peers hold different steps: agree on the minimum
    monkeypatch.setattr(common, "_allgather_steps", lambda local: [4, 10])
    assert common._agree_resume_step(10) == 4
    # one peer has NO shard file (local=-1): everyone resumes from scratch
    monkeypatch.setattr(common, "_allgather_steps", lambda local: [-1, 10])
    assert common._agree_resume_step(10) is None
    # single process: no agreement round at all
    monkeypatch.setattr(jax, "process_count", lambda: 1)
    assert common._agree_resume_step(6) == 6
    assert common._agree_resume_step(None) is None


def test_fingerprint_mismatch_rejected_via_cli(tmp_cwd):
    """Resume rejection on fingerprint mismatch through the public CLI
    path: checkpoints written under one physics config must make a re-run
    under different physics fail loudly — not quarantine-and-fall-back,
    and never silently restart from the IC."""
    (tmp_cwd / "input.dat").write_text("16 0.25 0.05 2.0 4 0\n")
    args = ["run", "--backend", "xla", "--dtype", "float64",
            "--checkpoint-every", "2"]
    assert main(args) == 0
    assert len(list((tmp_cwd / "checkpoints").glob("*.npz"))) == 2
    # same command, different physics (nu changed in input.dat)
    (tmp_cwd / "input.dat").write_text("16 0.25 0.99 2.0 4 0\n")
    with pytest.raises(ValueError, match="different physics"):
        main(args)
    # the intact foreign checkpoint must NOT have been quarantined
    assert len(list((tmp_cwd / "checkpoints").glob("*.corrupt"))) == 0
