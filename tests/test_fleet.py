"""Live fleet-router e2e over real localhost sockets (heat_tpu/fleet).

Two in-process gateways behind one router; every socket op and wait is
bounded so the suite cannot wedge tier-1. The load-bearing contracts:

- concurrent POSTs through the router come back byte-identical to
  direct-to-engine solves of the same configs (the router adds routing,
  never arithmetic);
- edge admission: malformed/duplicate lines are rejected AT the router
  with structured records and never reach a backend;
- ``backend-down`` chaos: a dropped backend's never-admitted batch
  retries on the alternate backend, the loss flight-dumps the router's
  fleet timeline, and every request still finishes ok;
- checkpoint-handoff work stealing: ``Router.steal`` drains the victim
  to its engine manifest, resumes it on the thief (mid-flight lanes
  continue at their checkpointed boundary), and the final npz bytes are
  identical to an unmigrated run.
"""

import json
import threading
import time

import numpy as np
import pytest

from heat_tpu.backends import solve
from heat_tpu.config import HeatConfig
from heat_tpu.fleet.registry import BackendRegistry, parse_backends
from heat_tpu.fleet.router import FleetConfig, Router, render_fleet_metrics
from heat_tpu.runtime import faults
from heat_tpu.serve import Engine, ServeConfig
from heat_tpu.serve.gateway import Gateway

TIMEOUT = 60


@pytest.fixture(autouse=True)
def _fresh_faults():
    faults.reset()
    yield
    faults.reset()


def make_backend(tmp_path, name, **scfg_kw):
    d = tmp_path / name
    d.mkdir(exist_ok=True)
    scfg_kw.setdefault("emit_records", False)
    scfg_kw.setdefault("lanes", 2)
    scfg_kw.setdefault("chunk", 8)
    scfg_kw.setdefault("buckets", (32,))
    scfg_kw.setdefault("out_dir", str(d))
    scfg_kw.setdefault("engine_ckpt_interval", 2)
    scfg_kw.setdefault("engine_ckpt_dir", str(d / "ckpt"))
    eng = Engine(ServeConfig(**scfg_kw))
    return Gateway(eng, "127.0.0.1", 0).start()


def make_fleet(tmp_path, n_backends=2, fcfg=None, **scfg_kw):
    gws = [make_backend(tmp_path, f"g{i}", **scfg_kw)
           for i in range(n_backends)]
    spec = ",".join(f"b{i}={gw.address}" for i, gw in enumerate(gws))
    reg = BackendRegistry(parse_backends(spec))
    rt = Router(reg, "127.0.0.1", 0,
                fcfg or FleetConfig(health_interval_s=0.3)).start()
    return rt, gws


def close_fleet(rt, gws):
    rt.close()
    for gw in gws:
        try:
            gw.request_drain()
            gw.wait_drained(TIMEOUT)
        finally:
            gw.close()


def post_solve(rt, body, headers=(), query="", timeout=TIMEOUT):
    """Streaming POST through the router; returns (status, records,
    response-headers)."""
    import http.client

    conn = http.client.HTTPConnection(rt.host, rt.port, timeout=timeout)
    conn.request("POST", f"/v1/solve{query}", body=body.encode(),
                 headers=dict(headers))
    resp = conn.getresponse()
    recs = []
    while True:
        raw = resp.readline()
        if not raw:
            break
        raw = raw.strip()
        if raw:
            recs.append(json.loads(raw))
    status, hdrs = resp.status, resp.headers
    conn.close()
    return status, recs, hdrs


def get_json(rt, path, timeout=TIMEOUT):
    import http.client

    conn = http.client.HTTPConnection(rt.host, rt.port, timeout=timeout)
    conn.request("GET", path)
    resp = conn.getresponse()
    out = (resp.status, json.loads(resp.read()))
    conn.close()
    return out


def line(**kw):
    return json.dumps(kw) + "\n"


def gw_http(gw, method, path, body=None, headers=(), timeout=TIMEOUT):
    """Direct-to-gateway HTTP (bypassing the router) for pre-loading a
    backend and for exercising the gateway's own header contracts."""
    import http.client

    host, port = gw.address.rsplit(":", 1)
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
    conn.request(method, path, body=body, headers=dict(headers))
    resp = conn.getresponse()
    out = (resp.status, resp.read())
    conn.close()
    return out


def wait_until(pred, timeout=TIMEOUT, interval=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


# --- routing + bit-identity --------------------------------------------------


def test_fleet_routes_concurrent_posts_bit_identical(tmp_path):
    """Acceptance e2e: concurrent client POSTs through the router are
    spread across both backends and the npz outputs are bit-identical
    to direct solo solves; fleet metrics/status/usage reconcile."""
    rt, gws = make_fleet(tmp_path)
    try:
        time.sleep(0.5)   # one probe round -> status payloads exist
        cfgs = {f"r{i}": dict(n=24, ntime=48 + 16 * (i % 2),
                              dtype="float64", ic="hat", bc="edges",
                              nu=0.05 + 0.05 * (i % 2))
                for i in range(6)}
        results = {}

        def post(ids):
            body = "".join(line(id=i, **cfgs[i]) for i in ids)
            st, recs, hdrs = post_solve(rt, body)
            for r in recs:
                results[r["id"]] = (st, r, hdrs)

        threads = [threading.Thread(target=post, args=(ids,))
                   for ids in (["r0", "r1", "r2"], ["r3", "r4", "r5"])]
        for t in threads:
            t.start()
        for t in threads:
            t.join(TIMEOUT)
            assert not t.is_alive()
        assert set(results) == set(cfgs)
        for rid, (st, rec, _) in results.items():
            assert st == 200 and rec["status"] == "ok", rec
            assert "T" not in rec
        snap = rt.snapshot()
        per_backend = {n: b["delivered"]
                       for n, b in snap["backends"].items()}
        assert sum(per_backend.values()) == 6
        assert all(v > 0 for v in per_backend.values()), \
            f"least-loaded starved a backend: {per_backend}"
        # fleet usage reconciles exactly with the per-engine ledgers
        _, usage = get_json(rt, "/v1/usage")
        assert usage["totals"]["requests"] == 6
        assert usage["totals"]["steps"] == sum(
            p["totals"]["steps"] for p in usage["per_backend"].values())
        # metrics render with per-backend labels
        metrics = render_fleet_metrics(rt)
        assert 'heat_tpu_fleet_backend_up{backend="b0"} 1' in metrics
        assert 'heat_tpu_fleet_delivered_total{backend=' in metrics
        assert "heat_tpu_fleet_duplicates_dropped_total 0" in metrics
        # GET /v1/requests/<id> serves the delivered record at the edge
        st, rec = get_json(rt, "/v1/requests/r0")
        assert st == 200 and rec["status"] == "ok"
        st, _ = get_json(rt, "/v1/requests/nope")
        assert st == 404
    finally:
        close_fleet(rt, gws)
    # byte-identity: whichever backend served each request, its npz is
    # the direct solve's bytes
    for rid, kw in cfgs.items():
        paths = [p for p in (tmp_path / "g0" / f"{rid}.npz",
                             tmp_path / "g1" / f"{rid}.npz") if p.exists()]
        assert len(paths) == 1, f"{rid}: expected exactly one npz"
        with np.load(paths[0]) as z:
            np.testing.assert_array_equal(
                z["T"], solve(HeatConfig(**kw)).T)


def test_edge_admission_and_trace_propagation(tmp_path):
    """Malformed and duplicate lines die at the router edge with
    structured records; the inbound X-Trace-Id is echoed and the
    router's own tracer carries backend tracks."""
    rt, gws = make_fleet(tmp_path)
    try:
        body = ('this is not json\n'
                + line(id="ok1", n=24, ntime=16, dtype="float64")
                + line(id="dup", n=24, ntime=16, dtype="float64")
                + line(id="dup", n=24, ntime=16, dtype="float64")
                + line(id="bad", n=-5, ntime=16))
        st, recs, hdrs = post_solve(rt, body,
                                    headers=[("X-Trace-Id", "fleet.e2e")])
        assert st == 200
        assert hdrs["X-Trace-Id"] == "fleet.e2e"
        by_status = {}
        for r in recs:
            by_status.setdefault(r["status"], []).append(r)
        assert len(by_status["rejected"]) == 3   # parse, duplicate, bad
        ids_ok = [r["id"] for r in by_status["ok"]]
        assert sorted(ids_ok) == ["dup", "ok1"]  # first dup wins
        assert any("duplicate request id" in r["error"]
                   for r in by_status["rejected"])
        # backends saw exactly the two valid requests, not the garbage
        snap = rt.snapshot()
        assert sum(b["routed"] for b in snap["backends"].values()) == 2
        assert snap["router"]["edge_rejected"] >= 3
        # the fleet timeline carries per-backend tracks + solve spans
        chrome = rt.tracer.to_chrome()
        names = {e.get("name") for e in chrome["traceEvents"]}
        assert any(str(n).startswith("backend b") for n in
                   {e["args"].get("name") for e in chrome["traceEvents"]
                    if e.get("ph") == "M" and "name" in e.get("args", {})})
        assert "ok1" in names   # synthesized backend solve span
    finally:
        close_fleet(rt, gws)


# --- chaos: backend-down retry + flight dump ---------------------------------


def test_backend_down_retries_on_alternate_and_flight_dumps(tmp_path):
    """backend-down@N drops a backend's TCP target mid-dispatch: its
    never-admitted batch retries on the alternate, every request still
    comes back ok + byte-identical, the health probe notices the down
    transition, and the router flight-dumps its fleet timeline."""
    rt, gws = make_fleet(
        tmp_path,
        fcfg=FleetConfig(health_interval_s=0.3, inject="backend-down@4",
                         flightrec_dir=str(tmp_path)))
    try:
        time.sleep(0.5)
        body = "".join(line(id=f"k{i}", n=24, ntime=48, dtype="float64")
                       for i in range(6))
        st, recs, _ = post_solve(rt, body)
        assert st == 200
        statuses = {r["id"]: r["status"] for r in recs}
        assert statuses == {f"k{i}": "ok" for i in range(6)}, statuses
        snap = rt.snapshot()
        downed = [n for n, b in snap["backends"].items()
                  if b["fault_down"]]
        assert len(downed) == 1
        survivor = [n for n in snap["backends"] if n not in downed][0]
        assert snap["backends"][survivor]["delivered"] == 6
        assert snap["router"]["duplicates"] == 0
        # the health loop sees the drop and recovery flight-dumps the
        # fleet timeline exactly once for the lost backend
        assert wait_until(lambda: rt.tracer.dumps >= 1)
        assert wait_until(
            lambda: rt.snapshot()["backends"][downed[0]]["lost"])
        assert list(tmp_path.glob("flightrec-*.trace.json"))
    finally:
        close_fleet(rt, gws)
    for i in range(6):
        paths = [p for p in (tmp_path / "g0" / f"k{i}.npz",
                             tmp_path / "g1" / f"k{i}.npz") if p.exists()]
        assert len(paths) == 1
        with np.load(paths[0]) as z:
            np.testing.assert_array_equal(
                z["T"],
                solve(HeatConfig(n=24, ntime=48, dtype="float64")).T)


# --- work stealing as checkpoint handoff -------------------------------------


def test_steal_migrates_checkpointed_work_bit_identically(tmp_path):
    """The headline: load one backend through the router, join an idle
    one via the backends file (live registry refresh), then steal — the
    victim drains to its engine manifest (/drainz?handoff=1), the thief
    resumes it (mid-flight lanes continue at their last checkpointed
    boundary, serve_resumed > 0 on /v1/status), and every npz is
    byte-identical to an unmigrated solve."""
    g0 = make_backend(tmp_path, "g0")
    g1 = make_backend(tmp_path, "g1")
    bfile = tmp_path / "backends.txt"
    bfile.write_text(f"b0={g0.address}\n")
    reg = BackendRegistry(backends_file=bfile)
    rt = Router(reg, "127.0.0.1", 0,
                FleetConfig(health_interval_s=0.25)).start()
    try:
        time.sleep(0.4)
        # slow work: sink-slow serializes 400ms per record on the
        # victim's writer thread, so the queue is still deep when the
        # steal fires (per-request inject — engine-side fault kind)
        body = "".join(line(id=f"s{i}", n=24, ntime=96, dtype="float64",
                            inject="sink-slow:ms=400") for i in range(6))
        st, accept, _ = post_solve(rt, body, query="?wait=0")
        assert st == 202 and len(accept[0]["accepted"]) == 6
        time.sleep(1.2)   # b0 mid-flight on the slow work
        # the idle thief joins the fleet live via the backends file
        bfile.write_text(f"b0={g0.address}\nb1={g1.address}\n")
        assert wait_until(lambda: reg.get("b1") is not None, timeout=10)
        ev = rt.steal("b0", "b1", reason="test")
        assert ev is not None and ev["thief"] == "b1"
        assert ev["generation"] >= 1
        assert ev["recovered"] >= 1, ev   # manifest-covered work moved
        assert ev["recovered"] + ev["redriven"] >= 1
        assert ev["wall_s"] < TIMEOUT
        # every request reaches a terminal ok record through the router
        assert wait_until(lambda: rt.pending_count() == 0), \
            rt.snapshot()
        for i in range(6):
            st, rec = get_json(rt, f"/v1/requests/s{i}")
            assert st == 200 and rec["status"] == "ok", rec
        # the thief's status payload proves a real resume happened
        snap = rt.snapshot()
        assert snap["backends"]["b1"]["serve_resumed"] >= 1
        assert snap["backends"]["b0"]["lost"]
        assert snap["router"]["duplicates"] == 0
        assert rt.registry.get("b0").stolen_from == 1
        assert rt.registry.get("b1").stolen_to == 1
        from heat_tpu.fleet.router import render_fleet_statusz
        assert "b0 -> b1 [test]" in render_fleet_statusz(rt)
    finally:
        rt.close()
        g1.request_drain()
        g1.wait_drained(TIMEOUT)
        g0.close()
        g1.close()
    # byte-identity across the migration: same bytes as a solo solve,
    # whether the request finished on the victim, resumed mid-flight on
    # the thief, or was re-driven fresh
    ref = solve(HeatConfig(n=24, ntime=96, dtype="float64")).T
    for i in range(6):
        paths = [p for p in (tmp_path / "g0" / f"s{i}.npz",
                             tmp_path / "g1" / f"s{i}.npz") if p.exists()]
        assert paths, f"s{i}: npz missing"
        with np.load(paths[-1]) as z:
            np.testing.assert_array_equal(z["T"], ref)


def test_router_healthz_drain_and_empty_fleet(tmp_path):
    """Router lifecycle plumbing: healthz reflects backend health,
    /drainz stops admission with 503, an all-down fleet rejects with a
    structured unroutable record."""
    rt, gws = make_fleet(tmp_path, n_backends=1)
    try:
        st, h = get_json(rt, "/healthz")
        assert st == 200 and h["backends_up"] == 1
        # drain: admission stops, healthz flips 503
        st, d = get_json(rt, "/drainz")
        assert st == 200 and d["draining"]
        st, _ = get_json(rt, "/healthz")
        assert st == 503
        st, recs, _ = post_solve(rt, line(id="late", n=24, ntime=16,
                                          dtype="float64"))
        assert st == 503
    finally:
        close_fleet(rt, gws)


def test_unroutable_when_every_backend_is_down(tmp_path):
    """No eligible backend -> terminal rejection records at the edge
    (router-502 flavor: error says 'unroutable', never silence)."""
    rt, gws = make_fleet(tmp_path, n_backends=1)
    try:
        rt.registry.set_fault_down("b0")
        st, recs, _ = post_solve(rt, line(id="x", n=24, ntime=16,
                                          dtype="float64"))
        assert st == 200
        (rec,) = recs
        assert rec["status"] == "rejected"
        assert "unroutable" in rec["error"]
    finally:
        close_fleet(rt, gws)


def test_fleet_shared_cache_edge_hit_reconciles(tmp_path):
    """Fleet solve-cache tier (ISSUE 19): with a shared ``--cache-dir``
    the router serves a repeat request entirely at the edge — placement
    ``fleet-cache``, zero backend dispatch — billed as the pseudo-
    backend ``_edge`` so fleet ``/v1/usage`` totals remain an exact sum
    of their parts, with the hit on metrics and the snapshot."""
    cache_dir = tmp_path / "solve-cache"
    rt, gws = make_fleet(
        tmp_path, 2,
        fcfg=FleetConfig(health_interval_s=0.3,
                         cache_dir=str(cache_dir)),
        cache=True, cache_dir=str(cache_dir))
    try:
        kw = dict(n=24, ntime=48, dtype="float64", ic="hat", bc="edges")
        st, recs, _ = post_solve(rt, line(id="c0", **kw))
        assert st == 200 and recs[-1]["status"] == "ok"
        assert recs[-1]["cached"] is False
        # the serving backend's async writeback publishes the entry
        assert wait_until(lambda: list(cache_dir.glob("*.npz")))

        st, recs, _ = post_solve(rt, line(id="c1", **kw))
        (rec,) = [r for r in recs if r.get("id") == "c1"]
        assert st == 200 and rec["status"] == "ok"
        assert rec["cached"] is True
        assert rec["placement"] == "fleet-cache"
        assert rec["exit"] == "cached"
        assert rec["usage"]["steps"] == 0
        assert rec["usage"]["lane_s"] == 0.0
        assert rec["usage"]["steps_saved"] == 48

        # edge billing rides the pseudo-backend and the sums reconcile
        _, usage = get_json(rt, "/v1/usage")
        assert "_edge" in usage["per_backend"]
        assert usage["per_backend"]["_edge"]["totals"]["cached"] == 1
        assert usage["totals"]["requests"] == 2
        assert usage["totals"]["cached"] == sum(
            p["totals"].get("cached", 0)
            for p in usage["per_backend"].values())
        assert usage["totals"]["steps"] == sum(
            p["totals"]["steps"]
            for p in usage["per_backend"].values())

        snap = rt.snapshot()
        assert snap["router"]["cache_edge_hits"] == 1
        assert snap["cache"] is not None
        assert snap["cache"]["readonly"] is True
        metrics = render_fleet_metrics(rt)
        assert "heat_tpu_fleet_cache_edge_hits_total 1" in metrics
        assert "heat_tpu_fleet_cache_entries" in metrics
        # the edge hit is delivered exactly once and replayable by id
        st, rec2 = get_json(rt, "/v1/requests/c1")
        assert st == 200 and rec2["placement"] == "fleet-cache"
    finally:
        close_fleet(rt, gws)


# --- resilience layer (ISSUE 20) ---------------------------------------------


def test_flapping_backend_breaker_opens_then_canary_readmits(tmp_path):
    """backend-flap chaos square-waves b1's reachability: the breaker
    opens on the down edge (trip via the lost transition), every
    request placed during the flap still finishes ok on the survivor,
    no steal fires while breakers are moving (flap-thrash guard), and
    re-admission happens exclusively through the half-open sine canary
    run THROUGH the router path (closed breaker + mark_found)."""
    rt, gws = make_fleet(
        tmp_path, 2,
        fcfg=FleetConfig(health_interval_s=0.2,
                         inject="backend-flap:period=700:backend=b1",
                         breaker_cooldown_s=0.4,
                         steal_threshold_s=0.001, steal_cooldown_s=2.0,
                         flightrec_dir=str(tmp_path)),
        buckets=(32, 64))   # the canary's known-answer solve is n=64
    try:
        time.sleep(0.6)   # first tick stamps the flap's t0 -> b1 down
        body = "".join(line(id=f"f{i}", n=24, ntime=48, dtype="float64")
                       for i in range(4))
        st, recs, _ = post_solve(rt, body)
        assert st == 200
        assert {r["id"]: r["status"] for r in recs} == {
            f"f{i}": "ok" for i in range(4)}
        # the breaker opened on the down edge and placement excluded b1
        snap = rt.snapshot()
        assert snap["backends"]["b0"]["delivered"] == 4
        assert "b1" in snap["router"]["breakers"]
        # the flap ends (one down pulse) -> cooldown elapses -> the
        # half-open canary solves through the router path -> closed +
        # found again.  /healthz alone never re-admits a lost backend.
        assert wait_until(
            lambda: rt.snapshot()["router"]["breakers"]
            .get("b1", {}).get("state") == "closed", timeout=30)
        assert wait_until(
            lambda: (lambda b: b["healthy"] and not b["lost"])(
                rt.snapshot()["backends"]["b1"]), timeout=30)
        snap = rt.snapshot()
        br = snap["router"]["breakers"]["b1"]
        assert br["transitions"] >= 3    # open -> half-open -> closed
        # breaker-aware steal cooldown: transitions kept thrash away
        assert snap["router"]["steals"] == []
        metrics = render_fleet_metrics(rt)
        assert 'heat_tpu_fleet_breaker_state{backend="b1"} 0' in metrics
        assert 'heat_tpu_fleet_breaker_transitions_total{backend="b1"}' \
            in metrics
    finally:
        close_fleet(rt, gws)
    ref = solve(HeatConfig(n=24, ntime=48, dtype="float64")).T
    for i in range(4):
        with np.load(tmp_path / "g0" / f"f{i}.npz") as z:
            np.testing.assert_array_equal(z["T"], ref)


def test_stream_cut_redrive_is_exactly_once(tmp_path):
    """stream-cut@2 kills the relay socket to b0 after two records have
    streamed back while the backend itself stays healthy: the hardened
    re-drive path polls the SAME backend for the already-admitted rows'
    terminal records (recomputing elsewhere would waste device steps) —
    zero rows lost, zero duplicated, bytes identical."""
    rt, gws = make_fleet(
        tmp_path, 2,
        fcfg=FleetConfig(health_interval_s=0.3,
                         inject="stream-cut@2:backend=b0",
                         cut_redrive_wait_s=10.0))
    try:
        time.sleep(0.5)
        body = "".join(line(id=f"c{i}", n=24, ntime=48, dtype="float64")
                       for i in range(6))
        st, recs, _ = post_solve(rt, body)
        assert st == 200
        assert sorted(r["id"] for r in recs) == sorted(
            f"c{i}" for i in range(6))          # zero lost, zero duped
        assert all(r["status"] == "ok" for r in recs), recs
        snap = rt.snapshot()
        assert snap["router"]["stream_cuts"] >= 1
        assert snap["router"]["duplicates"] == 0
        assert "heat_tpu_fleet_stream_cuts_total" \
            in render_fleet_metrics(rt)
    finally:
        close_fleet(rt, gws)
    ref = solve(HeatConfig(n=24, ntime=48, dtype="float64")).T
    for i in range(6):
        paths = [p for p in (tmp_path / "g0" / f"c{i}.npz",
                             tmp_path / "g1" / f"c{i}.npz") if p.exists()]
        assert len(paths) == 1, f"c{i}: expected exactly one npz"
        with np.load(paths[0]) as z:
            np.testing.assert_array_equal(z["T"], ref)


def test_hedged_interactive_row_wins_on_idle_backend(tmp_path):
    """Tail-latency hedging: b1 is pre-loaded OUTSIDE the router
    (sink-slow serializes its writer), so the round-robin placement
    (whose rotation starts at b1) sends the interactive row there and
    it stalls.  After the hedge delay the row is duplicated onto the
    idle alternate as tenant
    ``_hedge``; the twin's ok record wins at the exactly-once
    chokepoint, the client sees one ok record flagged ``hedged``, the
    real tenant is billed once, and the twin's bytes are the direct
    solve's bytes."""
    rt, gws = make_fleet(
        tmp_path, 2,
        fcfg=FleetConfig(health_interval_s=0.15, policy="round-robin",
                         hedge_factor=0.01, hedge_floor_s=0.3))
    try:
        time.sleep(0.4)
        # 4 slow rows straight to g1: ~2.8s of serialized writer time
        heavy = "".join(line(id=f"h{i}", n=24, ntime=96,
                             dtype="float64", tenant="bulk",
                             inject="sink-slow:ms=700")
                        for i in range(4))
        st, _ = gw_http(gws[1], "POST", "/v1/solve?wait=0",
                        body=heavy.encode())
        assert st == 202
        # round-robin sends the first router request to b1 (stale view)
        st, recs, _ = post_solve(
            rt, line(id="i0", n=24, ntime=48, dtype="float64",
                     tenant="acme", **{"class": "interactive"}))
        assert st == 200
        (rec,) = [r for r in recs if r["id"] == "i0"]
        assert rec["status"] == "ok", rec
        assert rec.get("hedged") is True
        snap = rt.snapshot()
        assert snap["router"]["hedges"]["fired"] == 1
        assert snap["router"]["hedges"]["won"] == 1
        # the duplicate cost is attributed to the reserved ``_hedge``
        # tenant; the real tenant is never billed twice
        assert wait_until(lambda: "_hedge" in rt.fleet_usage()["tenants"])
        usage = rt.fleet_usage()
        acme = usage["tenants"].get("acme", {"classes": {}})
        assert acme["classes"].get("interactive",
                                   {}).get("requests", 0) <= 1
        assert usage["totals"]["steps"] == sum(
            p["totals"]["steps"] for p in usage["per_backend"].values())
        metrics = render_fleet_metrics(rt)
        assert 'heat_tpu_fleet_hedges_total{outcome="won"} 1' in metrics
    finally:
        close_fleet(rt, gws)
    # byte-identity of the hedged pair: whichever sides finished, the
    # bytes are the unhedged solve's bytes
    ref = solve(HeatConfig(n=24, ntime=48, dtype="float64")).T
    paths = [p for p in (tmp_path / "g1" / "i0.npz",
                         tmp_path / "g0" / "i0~hedge.npz") if p.exists()]
    assert (tmp_path / "g0" / "i0~hedge.npz") in paths   # the winner
    for p in paths:
        with np.load(p) as z:
            np.testing.assert_array_equal(z["T"], ref)


def test_deadline_propagates_from_edge_to_backend(tmp_path):
    """Cross-host deadline propagation: an expired edge-minted budget
    sheds at placement with a structured ``deadline`` record and zero
    backend dispatch (never billed); a gateway presented with a spent
    ``X-Deadline-Ms`` refuses admission with 504; a live budget rides
    the relay header end-to-end and the request completes."""
    rt, gws = make_fleet(tmp_path, 2)
    try:
        time.sleep(0.4)
        # 1 microsecond of budget is spent before dispatch runs
        st, recs, _ = post_solve(
            rt, line(id="d0", n=24, ntime=48, dtype="float64",
                     tenant="t0", deadline_ms=0.001))
        assert st == 200
        (rec,) = recs
        assert rec["status"] == "deadline"
        assert "placement" in rec["error"]
        assert "zero device steps" in rec["error"]
        snap = rt.snapshot()
        assert snap["router"]["deadline_shed"] == 1
        assert sum(b["routed"] for b in snap["backends"].values()) == 0
        # never billed: no backend ledger ever saw tenant t0
        assert "t0" not in rt.fleet_usage()["tenants"]
        assert "heat_tpu_fleet_deadline_shed_total 1" \
            in render_fleet_metrics(rt)
        # the backend's own guard: a spent propagated budget is refused
        # before admission (the router treats this 504 as terminal)
        st, data = gw_http(
            gws[0], "POST", "/v1/solve",
            body=line(id="x0", n=24, ntime=16, dtype="float64").encode(),
            headers=[("X-Deadline-Ms", "0")])
        assert st == 504
        assert "deadline" in json.loads(data)["error"]
        st, _ = gw_http(
            gws[0], "POST", "/v1/solve",
            body=line(id="x1", n=24, ntime=16, dtype="float64").encode(),
            headers=[("X-Deadline-Ms", "not-a-number")])
        assert st == 400
        # a live budget propagates through the relay and completes
        st, recs, _ = post_solve(
            rt, line(id="d1", n=24, ntime=48, dtype="float64",
                     deadline_ms=60000))
        assert st == 200 and recs[-1]["status"] == "ok"
    finally:
        close_fleet(rt, gws)


def test_brownout_sheds_batch_then_standard_never_interactive(tmp_path):
    """Brownout degradation ladder: when EVERY backend's fast AND slow
    burn windows fire, the edge sheds batch (level 1), then standard
    too when the worst fast burn doubles (level 2) — interactive is
    never shed and still places on the demoted pool."""
    rt, gws = make_fleet(tmp_path, 2,
                         fcfg=FleetConfig(health_interval_s=30.0))
    try:
        burning = {"mega": {"max_bucket": 64},
                   "slo_burn": {"interactive": {"fast_burn": 1.4,
                                                "slow_burn": 1.2}}}
        for name in ("b0", "b1"):
            rt.registry.note_probe(name, True, status=burning)
        assert rt.snapshot()["brownout_level"] == 1
        # level 1: batch shed with Retry-After, standard+interactive ok
        st, recs, _ = post_solve(rt, line(id="bt0", n=24, ntime=16,
                                          dtype="float64",
                                          **{"class": "batch"}))
        (rec,) = recs
        assert rec["status"] == "rejected"
        assert "brownout" in rec["error"] and "level 1" in rec["error"]
        assert rec["retry_after_s"] > 0
        st, recs, _ = post_solve(rt, line(id="sd0", n=24, ntime=16,
                                          dtype="float64"))
        assert recs[-1]["status"] == "ok"
        st, recs, _ = post_solve(rt, line(id="it0", n=24, ntime=16,
                                          dtype="float64",
                                          **{"class": "interactive"}))
        assert recs[-1]["status"] == "ok"
        # worst fast burn doubles -> level 2: standard sheds too
        worse = {"mega": {"max_bucket": 64},
                 "slo_burn": {"interactive": {"fast_burn": 2.5,
                                              "slow_burn": 1.2}}}
        for name in ("b0", "b1"):
            rt.registry.note_probe(name, True, status=worse)
        assert rt.snapshot()["brownout_level"] == 2
        st, recs, _ = post_solve(rt, line(id="bt1", n=24, ntime=16,
                                          dtype="float64",
                                          **{"class": "batch"}))
        assert recs[0]["status"] == "rejected"
        st, recs, _ = post_solve(rt, line(id="sd1", n=24, ntime=16,
                                          dtype="float64"))
        (rec,) = recs
        assert rec["status"] == "rejected"
        assert "level 2" in rec["error"]
        st, recs, _ = post_solve(rt, line(id="it1", n=24, ntime=16,
                                          dtype="float64",
                                          **{"class": "interactive"}))
        assert recs[-1]["status"] == "ok"   # interactive is never shed
        snap = rt.snapshot()
        assert snap["router"]["brownout_shed"] == 3
        from heat_tpu.fleet.router import render_fleet_statusz
        assert "BROWNOUT" in render_fleet_statusz(rt)
        assert "heat_tpu_fleet_brownout_shed_total 3" \
            in render_fleet_metrics(rt)
    finally:
        close_fleet(rt, gws)
