"""Two-level solve cache (ISSUE 19): content-addressed result
memoization at the admission door plus prefix reuse of deeper runs.

The load-bearing contracts:

- a **full hit** short-circuits ``Engine.submit`` — zero device chunk
  programs dispatch, the published npz is byte-identical to the cold
  solve's, and billing is ``cached`` (zero lane-seconds/steps, the full
  ``ntime`` counted as ``steps_saved``) — reconciling exactly across
  records, the per-tenant ledger, and the summary counters;
- a **prefix hit** admits through the normal lane path seeded from the
  cached frontier and steps exactly ``ntime - cached_step``, at
  dispatch depths 0 and 2, byte-identical to the cold run;
- the cache key is the canonical **physics fingerprint** only —
  tenant / SLO class / deadline / request id / key order never split
  entries (billing stays per-tenant regardless);
- ``--cache off`` (the default) consults nothing, creates nothing, and
  serves bit-identically to builds without the cache;
- a corrupt or stale entry is quarantined to ``*.corrupt`` with a
  structured record and NEVER served.
"""

import json

import numpy as np
import pytest

from heat_tpu.backends import solve
from heat_tpu.config import HeatConfig, config_from_request
from heat_tpu.runtime import faults
from heat_tpu.runtime.checkpoint import config_fingerprint
from heat_tpu.serve import Engine, ServeConfig, SolveCache
from heat_tpu.serve import engine as engine_mod
from heat_tpu.serve.engine import LaneEngine
from heat_tpu.serve.gateway import render_metrics, render_statusz, \
    status_payload
from heat_tpu.serve.solvecache import _parse_entry, entry_name


@pytest.fixture(autouse=True)
def _fresh_faults():
    faults.reset()
    yield
    faults.reset()


def quiet(**kw) -> ServeConfig:
    kw.setdefault("emit_records", False)
    return ServeConfig(**kw)


def cached_cfg(tmp_path, **kw) -> ServeConfig:
    kw.setdefault("lanes", 2)
    kw.setdefault("chunk", 8)
    kw.setdefault("buckets", (16, 32))
    kw.setdefault("cache", True)
    kw.setdefault("cache_dir", str(tmp_path / "solve-cache"))
    kw.setdefault("out_dir", str(tmp_path / "out"))
    return quiet(**kw)


CFG = HeatConfig(n=16, ntime=40, dtype="float64", bc="edges", ic="hat")
OTHER = HeatConfig(n=16, ntime=40, dtype="float64", bc="ghost", ic="hat")


def drain(eng, *submits):
    ids = [eng.submit(c) if isinstance(c, HeatConfig)
           else eng.submit(**c) for c in submits]
    recs = {r["id"]: r for r in eng.results()}
    return ids, recs


# --- SolveCache unit behavior ------------------------------------------------


def test_entry_name_parse_roundtrip():
    fp = "a" * 16
    assert entry_name(fp, 40) == f"{fp}-00000040.npz"
    from pathlib import Path
    assert _parse_entry(Path(entry_name(fp, 40))) == (fp, 40)
    assert _parse_entry(Path("garbage.npz")) is None
    assert _parse_entry(Path(f"{fp}-notanum.npz")) is None


def test_put_then_lookup_full_hit(tmp_path):
    c = SolveCache(str(tmp_path / "c"))
    T = solve(CFG).T
    p = c.put(CFG, CFG.ntime, T=T)
    assert p is not None and p.exists()
    assert p.with_suffix(".json").exists()
    hit = c.lookup(CFG)
    assert hit is not None and hit["kind"] == "full"
    assert hit["step"] == CFG.ntime
    got, step = SolveCache.load(hit["path"])
    assert step == CFG.ntime
    np.testing.assert_array_equal(got, T)
    s = c.stats()
    assert s["hits_full"] == 1 and s["misses"] == 0 and s["puts"] == 1


def test_lookup_prefers_deepest_usable_prefix(tmp_path):
    c = SolveCache(str(tmp_path / "c"))
    for step in (8, 24):
        c.put(CFG, step, T=solve(CFG.with_(ntime=step)).T)
    # an entry DEEPER than the request must never be offered as a prefix
    c.put(CFG, 48, T=solve(CFG.with_(ntime=48)).T)
    hit = c.lookup(CFG)   # ntime=40
    assert hit["kind"] == "prefix" and hit["step"] == 24


def test_lookup_miss_on_different_physics(tmp_path):
    c = SolveCache(str(tmp_path / "c"))
    c.put(CFG, CFG.ntime, T=solve(CFG).T)
    assert c.lookup(OTHER) is None
    assert c.stats()["misses"] == 1


def test_put_first_write_wins(tmp_path):
    c = SolveCache(str(tmp_path / "c"))
    T = solve(CFG).T
    p1 = c.put(CFG, CFG.ntime, T=T)
    before = p1.read_bytes()
    p2 = c.put(CFG, CFG.ntime, T=np.zeros_like(T))
    assert p1 == p2 and p1.read_bytes() == before
    assert c.stats()["puts"] == 1


def test_corrupt_entry_quarantined_not_served(tmp_path, capfd):
    c = SolveCache(str(tmp_path / "c"))
    p = c.put(CFG, CFG.ntime, T=solve(CFG).T)
    data = bytearray(p.read_bytes())
    data[len(data) // 2] ^= 0xFF
    p.write_bytes(bytes(data))
    assert c.lookup(CFG) is None
    assert not p.exists()
    corrupts = list((tmp_path / "c").glob("*.corrupt"))
    assert len(corrupts) == 2   # npz + sidecar, both renamed
    assert c.stats()["quarantined"] == 1
    out = capfd.readouterr().out
    rec = next(json.loads(ln) for ln in out.splitlines()
               if '"cache_quarantined"' in ln)
    assert "hash mismatch" in rec["reason"]


def test_stale_sidecar_fingerprint_quarantined(tmp_path, capfd):
    c = SolveCache(str(tmp_path / "c"))
    p = c.put(CFG, CFG.ntime, T=solve(CFG).T)
    meta_p = p.with_suffix(".json")
    meta = json.loads(meta_p.read_text())
    meta["fingerprint"] = "0" * 16
    meta_p.write_text(json.dumps(meta))
    assert c.lookup(CFG) is None
    assert c.stats()["quarantined"] == 1
    out = capfd.readouterr().out
    rec = next(json.loads(ln) for ln in out.splitlines()
               if '"cache_quarantined"' in ln)
    assert "fingerprint" in rec["reason"]


def test_lru_eviction_honors_max_bytes_and_hit_recency(tmp_path):
    c = SolveCache(str(tmp_path / "c"))
    import os
    import time
    cfgs = [CFG.with_(sigma=0.1 + 0.05 * i) for i in range(3)]
    paths = []
    for i, cf in enumerate(cfgs):
        paths.append(c.put(cf, cf.ntime, T=solve(cf).T))
        # distinct mtimes so LRU order is deterministic on coarse clocks
        t = time.time() - 100 + i
        os.utime(paths[-1], (t, t))
    one_entry = paths[0].stat().st_size + \
        paths[0].with_suffix(".json").stat().st_size
    # a hit on the OLDEST entry touches it; the budget then evicts the
    # two least-recently-used (cfgs[1], cfgs[2]) — not the one just hit
    assert c.lookup(cfgs[0])["kind"] == "full"
    c.max_bytes = one_entry
    c._evict()
    assert c.lookup(cfgs[0]) is not None
    assert c.lookup(cfgs[1]) is None and c.lookup(cfgs[2]) is None
    assert c.stats()["evictions"] == 2
    assert c.bytes_total() <= one_entry


def test_readonly_cache_never_writes(tmp_path):
    d = tmp_path / "never-created"
    ro = SolveCache(str(d), readonly=True)
    assert ro.put(CFG, CFG.ntime, T=solve(CFG).T) is None
    assert ro.lookup(CFG) is None
    assert not d.exists()
    # a corrupt entry in a real dir is skipped WITHOUT renaming
    rw = SolveCache(str(tmp_path / "c"))
    p = rw.put(CFG, CFG.ntime, T=solve(CFG).T)
    p.write_bytes(b"garbage")
    ro2 = SolveCache(str(tmp_path / "c"), readonly=True)
    assert ro2.lookup(CFG) is None
    assert p.exists()   # untouched: quarantine is the owners' job


def test_negative_cache_max_bytes_rejected():
    with pytest.raises(ValueError, match="cache_max_bytes"):
        ServeConfig(cache_max_bytes=-1)


# --- fingerprint canonicalization (satellite: key invariance) ---------------


def test_fingerprint_excludes_step_count():
    assert config_fingerprint(CFG) == config_fingerprint(
        CFG.with_(ntime=999))


def test_fingerprint_key_order_invariant():
    a = config_from_request({"n": 16, "ntime": 40, "sigma": 0.2,
                             "bc": "edges", "ic": "hat",
                             "dtype": "float64"})
    b = config_from_request({"dtype": "float64", "ic": "hat",
                             "bc": "edges", "sigma": 0.2, "ntime": 40,
                             "n": 16})
    assert config_fingerprint(a) == config_fingerprint(b)


def test_fingerprint_splits_on_every_physics_field():
    base = config_fingerprint(CFG)
    for variant in (CFG.with_(n=17), CFG.with_(sigma=0.19),
                    CFG.with_(nu=0.9), CFG.with_(bc="ghost"),
                    CFG.with_(ic="uniform"), CFG.with_(dtype="float32")):
        assert config_fingerprint(variant) != base


def test_scheduler_keys_never_split_the_cache(tmp_path):
    """tenant / class / deadline / request id are billing metadata, not
    physics: a request from tenant B full-hits tenant A's entry — while
    billing still lands per tenant."""
    scfg = cached_cfg(tmp_path)
    eng = Engine(scfg)
    eng.submit(CFG, tenant="alice", slo_class="standard")
    eng.results()
    eng2 = Engine(scfg)
    rid = eng2.submit(CFG, request_id="custom-id-7", tenant="bob",
                      slo_class="batch", deadline_ms=60000.0)
    rec = {r["id"]: r for r in eng2.results()}[rid]
    assert rec["cached"] is True and rec["status"] == "ok"
    snap = eng2.prof.ledger.snapshot()
    assert snap["tenants"]["bob"]["classes"]["batch"]["cached"] == 1
    assert "alice" not in snap["tenants"]


# --- full hit: byte identity + zero dispatch --------------------------------


def test_full_hit_byte_identical_zero_dispatch(tmp_path):
    """Acceptance: the warm engine dispatches ZERO chunk programs for a
    full hit and the replayed npz is byte-identical to the cold one."""
    scfg = cached_cfg(tmp_path)
    cold = Engine(scfg)
    (cold_id,), cold_recs = drain(cold, CFG)
    cold_bytes = (tmp_path / "out" / f"{cold_id}.npz").read_bytes()

    events = []
    real_fetch, real_dispatch = engine_mod.host_fetch, \
        LaneEngine.dispatch_chunk

    def spy_fetch(x):
        events.append("fetch")
        return real_fetch(x)

    def spy_dispatch(self, k=None):
        events.append("dispatch")
        return real_dispatch(self, k)

    warm = Engine(scfg)
    try:
        engine_mod.host_fetch = spy_fetch
        LaneEngine.dispatch_chunk = spy_dispatch
        (hit_id,), recs = drain(warm, CFG)
    finally:
        engine_mod.host_fetch = real_fetch
        LaneEngine.dispatch_chunk = real_dispatch
    rec = recs[hit_id]
    assert rec["status"] == "ok" and rec["cached"] is True
    assert rec["exit"] == "cached" and rec["steps_done"] == CFG.ntime
    assert events == []   # no dispatch, no fetch: the device never ran
    assert warm.chunks_dispatched == 0
    warm_bytes = (tmp_path / "out" / f"{hit_id}.npz").read_bytes()
    assert warm_bytes == cold_bytes
    u = rec["usage"]
    assert u == {"lane_s": 0.0, "steps": 0, "chunks": 0,
                 "bytes_written": len(warm_bytes),
                 "steps_saved": CFG.ntime, "cached": True}


def test_full_hit_reconciles_records_ledger_summary(tmp_path):
    scfg = cached_cfg(tmp_path)
    e1 = Engine(scfg)
    e1.submit(CFG)
    e1.results()
    e2 = Engine(scfg)
    ids, recs = drain(e2, CFG, OTHER)
    cached = [r for r in recs.values() if r["cached"]]
    assert len(cached) == 1
    snap = e2.prof.ledger.snapshot()
    t = snap["totals"]
    assert t["cached"] == 1 and t["requests"] == 2
    # ledger sums == record sums, field by field
    for f in ("lane_s", "steps", "chunks", "bytes_written",
              "steps_saved"):
        assert t[f] == round(sum(r["usage"][f] for r in recs.values()), 9)
    s = e2.summary()
    assert s["cache"]["hits_full"] == 1 and s["cache"]["misses"] == 1
    assert s["steps_saved"] >= CFG.ntime


# --- prefix hit: exact delta at depths 0 and 2 ------------------------------


@pytest.mark.parametrize("depth", [0, 2])
def test_prefix_hit_steps_exact_delta_and_bytes(tmp_path, depth):
    """A request whose fingerprint matches a cached entry at a smaller
    step count steps exactly ``ntime - cached_step`` and lands
    byte-identical to the cold run — at dispatch depths 0 and 2."""
    out = tmp_path / f"d{depth}"
    scfg = cached_cfg(out, dispatch_depth=depth)
    short = CFG.with_(ntime=24)
    e1 = Engine(scfg)
    drain(e1, short)

    cold = Engine(quiet(lanes=2, chunk=8, buckets=(16, 32),
                        dispatch_depth=depth,
                        out_dir=str(out / "cold")))
    (cold_id,), _ = drain(cold, CFG)

    e2 = Engine(scfg)
    (rid,), recs = drain(e2, CFG)
    rec = recs[rid]
    assert rec["status"] == "ok" and rec["cached"] is False
    assert rec["steps_done"] == CFG.ntime
    u = rec["usage"]
    assert u["steps"] == CFG.ntime - short.ntime
    assert u["steps_saved"] == short.ntime and u["cached"] is False
    assert e2.summary()["cache"]["hits_prefix"] == 1
    assert ((out / "out" / f"{rid}.npz").read_bytes()
            == (out / "cold" / f"{cold_id}.npz").read_bytes())


def test_prefix_zero_delta_is_a_full_hit_not_a_restore(tmp_path):
    """ntime == cached step is the degenerate prefix: it must take the
    full-hit path (no lane at all), never a zero-step restore."""
    scfg = cached_cfg(tmp_path)
    e1 = Engine(scfg)
    drain(e1, CFG)
    e2 = Engine(scfg)
    (rid,), recs = drain(e2, CFG)
    assert recs[rid]["cached"] is True
    assert e2.summary()["cache"]["hits_full"] == 1
    assert e2.summary()["cache"]["hits_prefix"] == 0


# --- placements: pallas packed + mega ---------------------------------------


def test_cache_hits_across_lane_kernels(tmp_path):
    """The cache key is physics, not placement: an entry populated by
    the xla kernel full-hits under --serve-lane-kernel pallas (and the
    replayed bytes are the xla run's — determinism makes them equal)."""
    xla = Engine(cached_cfg(tmp_path, lane_kernel="xla"))
    drain(xla, CFG)
    pallas = Engine(cached_cfg(tmp_path, lane_kernel="pallas"))
    (rid,), recs = drain(pallas, CFG)
    assert recs[rid]["cached"] is True
    assert pallas.chunks_dispatched == 0


def test_mega_placement_full_hit_short_circuits(tmp_path):
    """A bucket-overflow (mega) request is admitted at the same door:
    the second identical mega request never compiles or dispatches."""
    big = HeatConfig(n=24, ntime=16, dtype="float64", bc="edges",
                     ic="hat")
    scfg = cached_cfg(tmp_path, buckets=(16,), mega_lanes=1)
    e1 = Engine(scfg)
    (_,), recs1 = drain(e1, big)
    assert next(iter(recs1.values()))["placement"] == "mega"
    e2 = Engine(scfg)
    (rid,), recs2 = drain(e2, big)
    rec = recs2[rid]
    assert rec["cached"] is True and rec["placement"] == "mega"
    assert e2.mega_compiles == 0 and e2.chunks_dispatched == 0


def test_mega_prefix_hit_steps_delta(tmp_path):
    big_short = HeatConfig(n=24, ntime=8, dtype="float64", bc="edges",
                           ic="hat")
    scfg = cached_cfg(tmp_path, buckets=(16,), mega_lanes=1)
    drain(Engine(scfg), big_short)
    e2 = Engine(scfg)
    (rid,), recs = drain(e2, big_short.with_(ntime=24))
    rec = recs[rid]
    assert rec["status"] == "ok" and rec["placement"] == "mega"
    assert rec["usage"]["steps"] == 16
    assert rec["usage"]["steps_saved"] == 8
    np.testing.assert_array_equal(
        SolveCache.load(e2.solvecache.lookup(
            big_short.with_(ntime=24))["path"])[0],
        solve(big_short.with_(ntime=24)).T)


# --- co-lane independence ----------------------------------------------------


def test_co_lane_hit_and_miss_are_independent(tmp_path):
    """One batch, one cached physics and one cold: the hit never
    occupies a lane, the miss solves normally, both come back right."""
    scfg = cached_cfg(tmp_path, lanes=1)   # 1 lane: a hit that wrongly
    # took a lane would serialize behind the miss and still pass — but
    # chunks_dispatched pins the proof below
    drain(Engine(scfg), CFG)
    eng = Engine(scfg)
    ids, recs = drain(eng, CFG, OTHER)
    hit, miss = recs[ids[0]], recs[ids[1]]
    assert hit["cached"] is True and hit["lane"] is None
    assert miss["cached"] is False and miss["status"] == "ok"
    with np.load(miss["path"]) as z:
        np.testing.assert_array_equal(z["T"], solve(OTHER).T)
    # the hit added zero chunks: every dispatched chunk was the miss's
    assert eng.summary()["cache"]["hits_full"] == 1


# --- cache off: bit-identical to pre-cache builds ---------------------------


def test_cache_off_is_default_and_inert(tmp_path):
    scfg = quiet(lanes=2, buckets=(16,), out_dir=str(tmp_path / "o"))
    assert scfg.cache is False
    eng = Engine(scfg)
    (rid,), recs = drain(eng, CFG)
    assert eng.solvecache is None
    assert recs[rid]["cached"] is False
    assert not (tmp_path / "o" / "solve-cache").exists()
    assert eng.summary()["cache"] is None
    # same request twice: BOTH solve (no memoization without --cache)
    eng2 = Engine(scfg)
    drain(eng2, CFG)
    assert eng2.chunks_dispatched > 0


def test_cache_off_bytes_match_cache_on_bytes(tmp_path):
    """--cache on must not perturb the solve itself: cold-run bytes are
    identical with and without the cache enabled."""
    off = Engine(quiet(lanes=1, buckets=(16,),
                       out_dir=str(tmp_path / "off")))
    (a,), _ = drain(off, CFG)
    on = Engine(cached_cfg(tmp_path / "on", lanes=1, buckets=(16,)))
    (b,), _ = drain(on, CFG)
    assert ((tmp_path / "off" / f"{a}.npz").read_bytes()
            == (tmp_path / "on" / "out" / f"{b}.npz").read_bytes())


# --- until=steady interplay --------------------------------------------------


STEADY_CFG = HeatConfig(n=12, ntime=160, dtype="float64", bc="edges",
                        ic="sine")


def test_steady_exit_caches_under_actual_step(tmp_path):
    """A steady early exit publishes its entry at the EXIT step, not the
    requested ntime — so a later fixed-step request prefix-hits the real
    frontier (and an ntime == exit-step request full-hits it)."""
    scfg = cached_cfg(tmp_path, buckets=(16,))
    eng = Engine(scfg)
    sid = eng.submit(STEADY_CFG, until="steady", tol=2e-3)
    rec = {r["id"]: r for r in eng.results()}[sid]
    exit_step = rec["steps_done"]
    assert 0 < exit_step < STEADY_CFG.ntime
    hit = eng.solvecache.lookup(STEADY_CFG.with_(ntime=exit_step))
    assert hit is not None and hit["kind"] == "full"
    assert hit["step"] == exit_step
    e2 = Engine(scfg)
    rid = e2.submit(STEADY_CFG.with_(ntime=exit_step + 8))
    rec2 = {r["id"]: r for r in e2.results()}[rid]
    assert rec2["status"] == "ok" and rec2["usage"]["steps"] == 8


def test_steady_requests_never_consume_the_cache(tmp_path):
    """until=steady must re-run (its exit step depends on live
    residuals): a cached fixed-step entry is not consulted for it."""
    scfg = cached_cfg(tmp_path, buckets=(16,))
    drain(Engine(scfg), STEADY_CFG)
    e2 = Engine(scfg)
    sid = e2.submit(STEADY_CFG, until="steady", tol=2e-3)
    rec = {r["id"]: r for r in e2.results()}[sid]
    assert rec["cached"] is False and rec["exit"] == "steady"
    assert e2.summary()["cache"]["hits_full"] == 0
    assert e2.summary()["cache"]["consults"] == 0


# --- engine-checkpoint snapshots feed the prefix store ----------------------


def test_engine_ckpt_snapshot_becomes_prefix_entry(tmp_path):
    """Chunk-boundary lane snapshots written by --engine-ckpt-interval
    double as cache entries: a shorter identical-physics request
    full-hits the snapshot cut instead of recomputing."""
    long_cfg = HeatConfig(n=16, ntime=40, dtype="float64", bc="edges",
                          ic="hat", sigma=0.21)
    scfg = cached_cfg(tmp_path, lanes=1, engine_ckpt_interval=1,
                      engine_ckpt_dir=str(tmp_path / "ck"))
    eng = Engine(scfg)
    drain(eng, long_cfg)
    fp = config_fingerprint(long_cfg)
    entries = sorted(int(_parse_entry(p)[1]) for p in
                     (tmp_path / "solve-cache").glob(f"{fp}-*.npz"))
    # at least one mid-run snapshot landed below the final result
    assert entries[-1] == long_cfg.ntime and len(entries) >= 2
    snap_step = entries[0]
    assert 0 < snap_step < long_cfg.ntime
    e2 = Engine(scfg)
    (rid,), recs = drain(e2, long_cfg.with_(ntime=snap_step))
    rec = recs[rid]
    assert rec["cached"] is True
    np.testing.assert_array_equal(
        SolveCache.load(tmp_path / "solve-cache"
                        / entry_name(fp, snap_step))[0],
        solve(long_cfg.with_(ntime=snap_step)).T)


# --- observability surfaces --------------------------------------------------


def test_metrics_statusz_status_payload_surfaces(tmp_path):
    scfg = cached_cfg(tmp_path)
    drain(Engine(scfg), CFG)
    eng = Engine(scfg)
    drain(eng, CFG)
    m = render_metrics(eng)
    assert 'heat_tpu_cache_hits_total{kind="full"} 1' in m
    assert 'heat_tpu_cache_hits_total{kind="prefix"} 0' in m
    assert "heat_tpu_cache_misses_total 0" in m
    assert ('heat_tpu_usage_cached_total{tenant="default",'
            'class="standard"} 1') in m
    sz = render_statusz(eng)
    assert "solve cache: 1 full / 0 prefix hit(s)" in sz
    sp = status_payload(eng)
    assert sp["cache"]["hits_full"] == 1
    off = Engine(quiet(lanes=1))
    assert status_payload(off)["cache"] is None
    assert "heat_tpu_cache_hits_total" in render_metrics(off)


def test_chaos_kinds_registered():
    plan = faults.plan_for_spec("cache-corrupt@2")
    assert plan is not None
    plan2 = faults.plan_for_spec("cache-stale")
    assert plan2 is not None
    with pytest.raises(ValueError):
        faults.plan_for_spec("cache-bogus")


def test_injected_cache_corrupt_quarantines_and_recomputes(tmp_path,
                                                           capfd):
    """The cache-corrupt fault flips bytes in the entry at consult time:
    the engine must quarantine it, recompute, and still serve ok."""
    import dataclasses
    scfg = cached_cfg(tmp_path)
    drain(Engine(scfg), CFG)
    bad = dataclasses.replace(scfg, inject="cache-corrupt")
    eng = Engine(bad)
    (rid,), recs = drain(eng, CFG)
    rec = recs[rid]
    assert rec["status"] == "ok" and rec["cached"] is False
    np.testing.assert_array_equal(
        np.load(tmp_path / "out" / f"{rid}.npz")["T"], solve(CFG).T)
    assert eng.summary()["cache"]["quarantined"] == 1
    assert list((tmp_path / "solve-cache").glob("*.corrupt"))
    out = capfd.readouterr().out
    assert '"cache_quarantined"' in out


def test_injected_cache_stale_never_serves_wrong_entry(tmp_path):
    import dataclasses
    scfg = cached_cfg(tmp_path)
    drain(Engine(scfg), CFG)
    bad = dataclasses.replace(scfg, inject="cache-stale")
    eng = Engine(bad)
    (rid,), recs = drain(eng, CFG)
    assert recs[rid]["status"] == "ok" and recs[rid]["cached"] is False
    assert eng.summary()["cache"]["quarantined"] == 1


# --- fleet tier --------------------------------------------------------------


def test_merge_usage_carries_cached_field():
    from heat_tpu.fleet.router import merge_usage

    a = {"tenants": {"t": {"classes": {"standard": {
        "lane_s": 1.0, "steps": 10, "chunks": 2, "bytes_written": 100,
        "steps_saved": 0, "cached": 0, "requests": 1}}}},
        "totals": {"lane_s": 1.0, "steps": 10, "chunks": 2,
                   "bytes_written": 100, "steps_saved": 0, "cached": 0,
                   "requests": 1}}
    b = {"tenants": {"t": {"classes": {"standard": {
        "lane_s": 0.0, "steps": 0, "chunks": 0, "bytes_written": 100,
        "steps_saved": 10, "cached": 1, "requests": 1}}}},
        "totals": {"lane_s": 0.0, "steps": 0, "chunks": 0,
                   "bytes_written": 100, "steps_saved": 10, "cached": 1,
                   "requests": 1}}
    merged = merge_usage({"b0": a, "_edge": b})
    assert merged["totals"]["cached"] == 1
    assert merged["totals"]["requests"] == 2
    cls = merged["tenants"]["t"]["classes"]["standard"]
    assert cls["cached"] == 1 and cls["steps_saved"] == 10


def test_placement_prefer_narrows_only_when_eligible():
    from heat_tpu.fleet import placement

    class B:
        def __init__(self, name, healthy=True):
            self.name = name
            self.healthy = healthy
            self.fault_down = False
            self.lost = False
            self.status = None
            self.pending_requests = 0

    b0, b1 = B("b0"), B("b1")
    chosen, d = placement.choose("round-robin", [b0, b1], None, 0,
                                 prefer={"b1"})
    assert chosen is b1 and d.get("preferred") is True
    # an unhealthy preferred backend never wins on preference alone
    b1.healthy = False
    chosen, d = placement.choose("round-robin", [b0, b1], None, 0,
                                 prefer={"b1"})
    assert chosen is b0 and "preferred" not in d
