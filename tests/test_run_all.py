"""benchmarks/run_all.py row isolation (round 3).

A single pathological row (the fuse=32 stall) must cost only itself:
children merge rows incrementally and the supervisor records
timeout/crash rows without losing the others.
"""
import importlib.util
import json
import subprocess
import sys
from pathlib import Path

_spec = importlib.util.spec_from_file_location(
    "run_all", Path(__file__).resolve().parent.parent / "benchmarks"
    / "run_all.py")
run_all = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(run_all)


def test_merge_rows_preserves_order_and_updates(tmp_path):
    out = tmp_path / "results.json"
    run_all._merge_rows(out, [{"name": "a", "v": 1}, {"name": "b", "v": 1}])
    run_all._merge_rows(out, [{"name": "a", "v": 2}])  # update in place
    run_all._merge_rows(out, [{"name": "c", "v": 1}])  # append new
    rows = json.loads(out.read_text())["rows"]
    assert [r["name"] for r in rows] == ["a", "b", "c"]
    assert rows[0]["v"] == 2 and rows[1]["v"] == 1


def test_supervise_rows_records_failures_keeps_rest(tmp_path, monkeypatch,
                                                    capsys):
    out = tmp_path / "results.json"

    def fake_run(cmd, timeout=None):
        name = cmd[cmd.index("--only") + 1]
        if name == "hangs":
            raise subprocess.TimeoutExpired(cmd, timeout)
        if name == "crashes":
            return subprocess.CompletedProcess(cmd, 1)
        # a healthy child merges its own row, like bench_one's path does
        run_all._merge_rows(out, [{"name": name, "points_per_s": 1.0}])
        return subprocess.CompletedProcess(cmd, 0)

    # supervise_rows does `import subprocess` locally — patch the module
    monkeypatch.setattr(subprocess, "run", fake_run)
    run_all.supervise_rows(["ok1", "hangs", "crashes", "ok2"], out,
                           row_timeout=5)
    rows = {r["name"]: r for r in json.loads(out.read_text())["rows"]}
    assert rows["ok1"]["points_per_s"] == 1.0
    assert rows["ok2"]["points_per_s"] == 1.0
    assert "timed out" in rows["hangs"]["error"]
    assert "rc=1" in rows["crashes"]["error"]


def test_supervise_keeps_row_when_child_dies_post_measurement(
        tmp_path, monkeypatch):
    """A child can merge its measured row and then stall in runtime
    teardown until the row timeout fires — the measurement must survive."""
    import time as time_mod

    out = tmp_path / "results.json"

    def fake_run(cmd, timeout=None):
        name = cmd[cmd.index("--only") + 1]
        run_all._merge_rows(out, [{"name": name, "points_per_s": 7.0,
                                   "measured_ts": time_mod.time()}])
        raise subprocess.TimeoutExpired(cmd, timeout)  # teardown hang

    monkeypatch.setattr(subprocess, "run", fake_run)
    run_all.supervise_rows(["slow_teardown"], out, row_timeout=5)
    (row,) = json.loads(out.read_text())["rows"]
    assert row["points_per_s"] == 7.0 and "error" not in row


def test_merge_survives_corrupt_results_file(tmp_path):
    out = tmp_path / "results.json"
    out.write_text('{"ts": 1, "rows": [{"na')  # truncated by a SIGKILL
    run_all._merge_rows(out, [{"name": "a", "v": 1}])
    assert json.loads(out.read_text())["rows"] == [{"name": "a", "v": 1}]


def test_cache_env_util_matches_package(monkeypatch):
    """benchmarks/_util.ensure_cache_env loads heat_tpu/utils/cache.py by
    file path (no package __init__, hence no jax import of its own); this
    pins that both routes derive the SAME per-user path — a fork here
    splits the warm compile cache and re-pays minutes-long flagship
    compiles (code-review r5)."""
    bench_dir = str(Path(__file__).resolve().parent.parent / "benchmarks")
    if bench_dir not in sys.path:
        sys.path.insert(0, bench_dir)
    import _util
    from heat_tpu.utils import default_cache_dir

    monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)
    assert _util.ensure_cache_env() == default_cache_dir()
    # a user-set value is always honored, never overridden
    monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", "/custom/cache")
    assert _util.ensure_cache_env() == "/custom/cache"
