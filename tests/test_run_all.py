"""benchmarks/run_all.py row isolation (round 3).

A single pathological row (the fuse=32 stall) must cost only itself:
children merge rows incrementally and the supervisor records
timeout/crash rows without losing the others.
"""
import importlib.util
import json
import subprocess
import sys
from pathlib import Path

_spec = importlib.util.spec_from_file_location(
    "run_all", Path(__file__).resolve().parent.parent / "benchmarks"
    / "run_all.py")
run_all = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(run_all)


def test_merge_rows_preserves_order_and_updates(tmp_path):
    out = tmp_path / "results.json"
    run_all._merge_rows(out, [{"name": "a", "v": 1}, {"name": "b", "v": 1}])
    run_all._merge_rows(out, [{"name": "a", "v": 2}])  # update in place
    run_all._merge_rows(out, [{"name": "c", "v": 1}])  # append new
    rows = json.loads(out.read_text())["rows"]
    assert [r["name"] for r in rows] == ["a", "b", "c"]
    assert rows[0]["v"] == 2 and rows[1]["v"] == 1


def test_supervise_rows_records_failures_keeps_rest(tmp_path, monkeypatch,
                                                    capsys):
    out = tmp_path / "results.json"

    def fake_run(cmd, timeout=None):
        name = cmd[cmd.index("--only") + 1]
        if name == "hangs":
            raise subprocess.TimeoutExpired(cmd, timeout)
        if name == "crashes":
            return subprocess.CompletedProcess(cmd, 1)
        # a healthy child merges its own row, like bench_one's path does
        run_all._merge_rows(out, [{"name": name, "points_per_s": 1.0}])
        return subprocess.CompletedProcess(cmd, 0)

    # supervise_rows does `import subprocess` locally — patch the module
    monkeypatch.setattr(subprocess, "run", fake_run)
    run_all.supervise_rows(["ok1", "hangs", "crashes", "ok2"], out,
                           row_timeout=5)
    rows = {r["name"]: r for r in json.loads(out.read_text())["rows"]}
    assert rows["ok1"]["points_per_s"] == 1.0
    assert rows["ok2"]["points_per_s"] == 1.0
    assert "timed out" in rows["hangs"]["error"]
    assert "rc=1" in rows["crashes"]["error"]


def test_supervise_keeps_row_when_child_dies_post_measurement(
        tmp_path, monkeypatch):
    """A child can merge its measured row and then stall in runtime
    teardown until the row timeout fires — the measurement must survive."""
    import time as time_mod

    out = tmp_path / "results.json"

    def fake_run(cmd, timeout=None):
        name = cmd[cmd.index("--only") + 1]
        run_all._merge_rows(out, [{"name": name, "points_per_s": 7.0,
                                   "measured_ts": time_mod.time()}])
        raise subprocess.TimeoutExpired(cmd, timeout)  # teardown hang

    monkeypatch.setattr(subprocess, "run", fake_run)
    run_all.supervise_rows(["slow_teardown"], out, row_timeout=5)
    (row,) = json.loads(out.read_text())["rows"]
    assert row["points_per_s"] == 7.0 and "error" not in row


def test_merge_survives_corrupt_results_file(tmp_path):
    out = tmp_path / "results.json"
    out.write_text('{"ts": 1, "rows": [{"na')  # truncated by a SIGKILL
    run_all._merge_rows(out, [{"name": "a", "v": 1}])
    assert json.loads(out.read_text())["rows"] == [{"name": "a", "v": 1}]


def test_cache_env_util_matches_package(monkeypatch):
    """benchmarks/_util.ensure_cache_env loads heat_tpu/utils/cache.py by
    file path (no package __init__, hence no jax import of its own); this
    pins that both routes derive the SAME per-user path — a fork here
    splits the warm compile cache and re-pays minutes-long flagship
    compiles (code-review r5)."""
    bench_dir = str(Path(__file__).resolve().parent.parent / "benchmarks")
    if bench_dir not in sys.path:
        sys.path.insert(0, bench_dir)
    import _util
    from heat_tpu.utils import default_cache_dir

    monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)
    assert _util.ensure_cache_env() == default_cache_dir()
    # a user-set value is always honored, never overridden
    monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", "/custom/cache")
    assert _util.ensure_cache_env() == "/custom/cache"


def test_custom_call_census_fallback_is_labeled():
    """IR-census regexes silently recorded zeros once (round-5 bisect
    rows) — the shared helper must flag a printer-syntax mismatch via
    census_method instead of reporting confident zeros."""
    bench_dir = str(Path(__file__).resolve().parent.parent / "benchmarks")
    if bench_dir not in sys.path:
        sys.path.insert(0, bench_dir)
    from _util import custom_call_census

    hlo = ('%x = f32[8,8] custom-call(%y), '
           'custom_call_target="tpu_custom_call", backend_config={p1}\n'
           '%z = f32[8,8] custom-call(%x), '
           'custom_call_target="tpu_custom_call", backend_config={p2}\n'
           '%h = f32[8,8] custom-call(%z), '
           'custom_call_target="host_thing"\n')
    r = custom_call_census(hlo, "custom-call",
                           r'custom_call_target="([^"]*)".*')
    assert r == {"custom_calls": 3, "mosaic_calls": 2,
                 "distinct_kernel_bodies": 2,
                 "census_method": "target-match"}

    # same body called twice -> one distinct body after SSA normalization
    hlo2 = hlo.replace("{p2}", "{p1}")
    r2 = custom_call_census(hlo2, "custom-call",
                            r'custom_call_target="([^"]*)".*')
    assert r2["distinct_kernel_bodies"] == 1

    # unknown printer syntax (NO line parses): counts via line hashing,
    # SAYS so
    weird = "%x = custom-call(%y), tpu_thing_new_syntax\n"
    r3 = custom_call_census(weird, "custom-call",
                            r'custom_call_target="([^"]*)".*')
    assert r3["mosaic_calls"] == 1
    assert r3["census_method"] == "line-hash-fallback"

    # parses fine but genuinely Mosaic-free (xla-local-kernel program
    # with only host custom calls): a REAL zero, not a fallback
    hostonly = ('%x = custom-call(%y), '
                'custom_call_target="SPMDSharding"\n')
    r4 = custom_call_census(hostonly, "custom-call",
                            r'custom_call_target="([^"]*)".*')
    assert r4 == {"custom_calls": 1, "mosaic_calls": 0,
                  "distinct_kernel_bodies": 0,
                  "census_method": "target-match"}
