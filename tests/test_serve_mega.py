"""Two-tier placement (ISSUE 10): sharded mega-lanes for bucket-overflow
requests, co-scheduled with packed vmapped lanes.

The load-bearing contracts:

- a request whose side overflows every bucket is ADMITTED as a
  mesh-spanning sharded mega-lane on a multi-device host — and its
  result (in-memory field and npz payload) is byte-identical to a solo
  ``drive()`` on the sharded backend of the same config, at dispatch
  depths 0 and 2;
- packed-lane traffic co-scheduled with a resident mega-lane stays
  byte-identical to a mega-free run (placement never perturbs physics);
- ``--mega-lanes 0`` (and single-device hosts under auto) restore the
  PR-5 bucket-overflow rejection bit-identically, now enriched with the
  mesh capacity ceiling and a machine-readable ``hint``;
- the mega-lane is a full fault domain: deadline preemption, lane-nan
  quarantine, ``--serve-on-nan rollback`` recovery, and the
  boundary-fetch watchdog all behave like a packed group of lane-count
  one-mesh;
- every surface (records, cost model, /metrics, /v1/usage) carries the
  ``placement=packed|mega`` dimension.

The 8-virtual-CPU-device harness (tests/conftest.py) is the mesh."""

import json

import numpy as np
import pytest

from heat_tpu.backends import solve
from heat_tpu.config import HeatConfig, parse_mega_lanes
from heat_tpu.runtime import faults
from heat_tpu.serve import Engine, ServeConfig
from heat_tpu.serve import scheduler as sched_mod


@pytest.fixture(autouse=True)
def _fresh_faults():
    faults.reset()
    yield
    faults.reset()


def quiet(**kw) -> ServeConfig:
    kw.setdefault("emit_records", False)
    kw.setdefault("lanes", 2)
    kw.setdefault("chunk", 8)
    kw.setdefault("buckets", (8,))
    return ServeConfig(**kw)


# n=16 overflows the (8,) bucket table and divides the auto 4x2 mesh of
# the 8-device harness; smalls pack into the 8-bucket as usual
MEGA_CFG = HeatConfig(n=16, ntime=37, dtype="float64", bc="edges")
SMALLS = [HeatConfig(n=8, ntime=20, dtype="float64"),
          HeatConfig(n=8, ntime=11, dtype="float64", nu=0.1,
                     bc="ghost", ic="uniform")]


def solo_sharded(cfg):
    return solve(cfg.with_(backend="sharded")).T


# --- overflow -> mega admission + bit-identity -------------------------------


@pytest.mark.parametrize("depth", [0, 2])
def test_mega_lane_bit_identical_to_solo_sharded_drive(tmp_path, depth):
    """Acceptance: the previously-rejected oversized request completes
    as a mega-lane whose npz payload is byte-identical to a solo sharded
    drive(), while co-scheduled packed lanes stay byte-identical to a
    mega-free run — at dispatch depths 0 and 2."""
    # mega-free reference drain of the same smalls
    free = Engine(quiet(dispatch_depth=depth))
    free_ids = [free.submit(c) for c in SMALLS]
    free_recs = {r["id"]: r for r in free.results()}

    out = tmp_path / f"mega{depth}"
    eng = Engine(quiet(dispatch_depth=depth, out_dir=str(out),
                       keep_fields=True))
    big = eng.submit(MEGA_CFG)
    ids = [eng.submit(c) for c in SMALLS]
    recs = {r["id"]: r for r in eng.results()}

    assert recs[big]["status"] == "ok", recs[big]
    assert recs[big]["placement"] == "mega"
    assert recs[big]["bucket"] is None
    solo = solo_sharded(MEGA_CFG)
    np.testing.assert_array_equal(recs[big]["T"], solo)
    # the persisted npz payload too (same writer as packed results)
    with np.load(out / f"{big}.npz") as z:
        assert z["T"].dtype == solo.dtype
        assert z["T"].tobytes() == solo.tobytes()
        assert int(z["step"]) == MEGA_CFG.ntime
    # co-scheduled packed lanes == the mega-free run, byte for byte
    for fid, rid, cfg in zip(free_ids, ids, SMALLS):
        assert recs[rid]["status"] == "ok"
        assert recs[rid]["placement"] == "packed"
        np.testing.assert_array_equal(recs[rid]["T"], free_recs[fid]["T"])
        np.testing.assert_array_equal(recs[rid]["T"], solve(cfg).T)
    s = eng.summary()
    assert s["placement"] == {"mega": 1, "packed": len(SMALLS)}
    assert s["mega_lanes"] >= 1 and s["mega_compiles"] >= 1
    # the packed tier's compile accounting is untouched by the mega tier
    assert free.step_compiles == eng.step_compiles


def test_mega_warm_readmission_compiles_nothing():
    """Re-admitting the same oversized config reuses every cached mega
    program (machinery + chunk executables) — zero new compiles."""
    eng = Engine(quiet())
    eng.submit(MEGA_CFG)
    eng.results()
    warm = eng.mega_compiles
    assert warm >= 1
    rid = eng.submit(MEGA_CFG)
    recs = {r["id"]: r for r in eng.results()}
    assert recs[rid]["status"] == "ok"
    assert eng.mega_compiles == warm


def test_mega_ntime_zero_returns_ic():
    cfg = MEGA_CFG.with_(ntime=0)
    eng = Engine(quiet(keep_fields=True))
    rid = eng.submit(cfg)
    recs = {r["id"]: r for r in eng.results()}
    assert recs[rid]["status"] == "ok"
    np.testing.assert_array_equal(recs[rid]["T"], solo_sharded(cfg))


# --- rejection paths ---------------------------------------------------------


def test_single_device_auto_keeps_overflow_rejection(monkeypatch):
    """Auto --mega-lanes resolves 0 on a single-device host: overflow
    stays a rejection, now carrying the mesh capacity ceiling and the
    enable hint."""
    monkeypatch.setattr(sched_mod, "mega_device_count", lambda: 1)
    eng = Engine(quiet())
    big = eng.submit(MEGA_CFG)
    ok = eng.submit(SMALLS[0])
    recs = {r["id"]: r for r in eng.results()}
    assert recs[big]["status"] == "rejected"
    assert "bucket-overflow" in recs[big]["error"]
    assert "1-device" in recs[big]["error"]
    assert recs[big]["hint"] == "enable --mega-lanes"
    assert recs[big]["placement"] is None
    assert recs[ok]["status"] == "ok"


def test_mega_lanes_zero_restores_rejection_bit_identically():
    """--mega-lanes 0 is the pre-mega engine: overflow rejected (with
    the ceiling + hint), packed traffic byte-identical and admission
    trace unchanged vs an engine that never saw the overflow."""
    ref = Engine(quiet())
    ref_ids = [ref.submit(c) for c in SMALLS]
    ref_recs = {r["id"]: r for r in ref.results()}

    eng = Engine(quiet(mega_lanes=0))
    big = eng.submit(MEGA_CFG)
    ids = [eng.submit(c) for c in SMALLS]
    recs = {r["id"]: r for r in eng.results()}
    assert recs[big]["status"] == "rejected"
    assert "could serve it" in recs[big]["error"]  # the capacity ceiling
    assert recs[big]["hint"] == "enable --mega-lanes"
    for rid, fid in zip(ids, ref_ids):
        np.testing.assert_array_equal(recs[rid]["T"], ref_recs[fid]["T"])
    assert eng.admission_trace == [r for r in ids]
    assert eng.mega_compiles == 0 and eng.summary()["mega_lanes"] == 0


def test_mega_indivisible_side_rejected_with_constraint():
    """A side that does not shard evenly over the mesh is still a
    rejection — naming the mesh shape and the divisibility remedy."""
    eng = Engine(quiet())
    rid = eng.submit(HeatConfig(n=17, ntime=4, dtype="float64"))
    rec = {r["id"]: r for r in eng.results()}[rid]
    assert rec["status"] == "rejected"
    assert "does not divide evenly" in rec["error"]
    assert "hint" not in rec


def test_mega_queue_counts_against_max_queue():
    eng = Engine(quiet(mega_lanes=1, max_queue=1))
    first = eng.submit(SMALLS[0])
    shed = eng.submit(MEGA_CFG)
    recs = {r["id"]: r for r in eng.results()}
    assert recs[first]["status"] == "ok"
    assert recs[shed]["status"] == "rejected"
    assert "overloaded" in recs[shed]["error"]
    assert eng.shed == 1


# --- fault-domain parity -----------------------------------------------------


@pytest.mark.parametrize("depth", [0, 2])
def test_mega_lane_nan_quarantines_mesh_not_packed_lanes(tmp_path, depth):
    """A lane-nan-poisoned mega request fails with the structured
    nonfinite status (no npz persisted) while co-scheduled packed lanes
    drain bit-identically — the mega fault domain is one mesh."""
    out = tmp_path / f"q{depth}"
    eng = Engine(quiet(dispatch_depth=depth, out_dir=str(out),
                       keep_fields=True,
                       inject="lane-nan@10:req=boom"))
    big = eng.submit(MEGA_CFG, request_id="boom")
    small = eng.submit(SMALLS[0])
    recs = {r["id"]: r for r in eng.results()}
    assert recs[big]["status"] == "nonfinite"
    assert "mega lane" in recs[big]["error"]
    assert not (out / "boom.npz").exists()
    assert eng.lanes_quarantined == 1
    assert recs[small]["status"] == "ok"
    np.testing.assert_array_equal(recs[small]["T"], solve(SMALLS[0]).T)


@pytest.mark.parametrize("depth", [0, 2])
def test_mega_rollback_recovers_transient_poison(depth):
    """--serve-on-nan rollback restores the mega-lane's last verified
    boundary (or the IC) and re-steps the mesh; the one-shot poison
    leaves the final field bit-identical to a clean solo sharded run."""
    eng = Engine(quiet(dispatch_depth=depth, on_nan="rollback",
                       keep_fields=True, inject="lane-nan@10:req=heal"))
    big = eng.submit(MEGA_CFG, request_id="heal")
    recs = {r["id"]: r for r in eng.results()}
    assert recs[big]["status"] == "ok", recs[big]
    assert eng.rollbacks == 1 and eng.lanes_quarantined == 0
    np.testing.assert_array_equal(recs[big]["T"], solo_sharded(MEGA_CFG))


def test_mega_deadline_preempts_at_boundary(monkeypatch):
    """A mega request past its budget is preempted at its next chunk
    boundary (status deadline, partial usage billed) and the freed slot
    admits the next queued mega request (fake 1 s-per-reading clock)."""
    t = {"now": 0.0}

    def fake_clock():
        t["now"] += 1.0
        return t["now"]

    monkeypatch.setattr(sched_mod, "wall_clock", fake_clock)
    eng = Engine(quiet(mega_lanes=1))
    doomed = eng.submit(MEGA_CFG.with_(ntime=80), deadline_ms=20_000.0)
    follower = eng.submit(MEGA_CFG.with_(ntime=8))
    recs = {r["id"]: r for r in eng.results()}
    assert recs[doomed]["status"] == "deadline"
    assert "mega lane preempted" in recs[doomed]["error"]
    assert recs[doomed]["usage"]["steps"] > 0
    assert recs[follower]["status"] == "ok"
    assert eng.deadline_misses == 1


def test_mega_watchdog_fails_tier_cleanly_packed_drains(tmp_path):
    """A wedged mega boundary fetch fails the mega tier's in-flight AND
    queued requests with structured records — and the packed group keeps
    draining (no hang, a record for every request). fetch index 0 is the
    packed group's (runners round-robin groups first), index 1 the
    mega-lane's."""
    eng = Engine(quiet(inject="fetch-hang@1:ms=1500", fetch_timeout_s=0.2,
                       flight_dir=str(tmp_path)))
    packed = eng.submit(SMALLS[0])
    hung = eng.submit(MEGA_CFG, request_id="wedge")
    queued = eng.submit(MEGA_CFG.with_(ntime=5), request_id="behind")
    recs = {r["id"]: r for r in eng.results()}
    assert len(recs) == 3
    for rid in (hung, queued):
        assert recs[rid]["status"] == "error"
        assert "fetch-watchdog" in recs[rid]["error"]
    assert recs[packed]["status"] == "ok"
    assert eng.watchdog_fired == 1


def test_mega_watchdog_sync_fallback(tmp_path):
    eng = Engine(quiet(dispatch_depth=0, inject="fetch-hang:ms=1500",
                       fetch_timeout_s=0.2, flight_dir=str(tmp_path)))
    rid = eng.submit(MEGA_CFG)
    recs = {r["id"]: r for r in eng.results()}
    assert recs[rid]["status"] == "error"
    assert "fetch-watchdog" in recs[rid]["error"]


# --- observability surfaces --------------------------------------------------


def test_metrics_usage_and_cost_model_carry_placement(tmp_path):
    from heat_tpu.serve.gateway import (render_metrics, render_statusz,
                                        usage_payload)

    eng = Engine(quiet())
    eng.submit(MEGA_CFG, tenant="acme")
    eng.submit(SMALLS[0], tenant="acme")
    eng.results()
    # cost-model rows keyed by placement (and the sharded mega kernel)
    rows = eng.summary()["cost_model"]
    placements = {(e["placement"], e["kernel"]) for e in rows}
    assert ("mega", "sharded") in placements
    assert any(p == "packed" for p, _ in placements)
    text = render_metrics(eng)
    assert 'heat_tpu_serve_requests_by_placement_total{placement="mega"} 1' \
        in text
    assert ('heat_tpu_serve_requests_by_placement_total'
            '{placement="packed"} 1') in text
    assert 'placement="mega"' in text.split(
        "heat_tpu_serve_cost_s_per_lane_step", 1)[1]
    assert "heat_tpu_serve_mega_lanes 1" in text
    # every sample line still parses as name{labels} value
    for line in text.splitlines():
        if line.startswith("#") or not line:
            continue
        float(line.rsplit(" ", 1)[1])
    # usage ledger: the (tenant, class) cell splits by placement
    usage = usage_payload(eng)
    cell = usage["tenants"]["acme"]["classes"]["standard"]
    assert cell["by_placement"] == {"mega": 1, "packed": 1}
    assert usage["totals"]["by_placement"] == {"mega": 1, "packed": 1}
    assert "placement: 1 packed / 1 mega" in render_statusz(eng)


def test_gateway_serves_oversized_request_over_http(tmp_path):
    """Gateway e2e: an oversized NDJSON request POSTed to a running
    gateway streams back an ok record (placement mega) and its npz is
    byte-identical to the solo sharded drive."""
    from test_gateway import http, line, make_gateway

    gw, eng = make_gateway(tmp_path, buckets=(8,), keep_fields=True)
    try:
        st, recs, _ = http(gw, "POST", "/v1/solve",
                           line(id="giant", n=16, ntime=12,
                                dtype="float64"))
        assert st == 200
        (rec,) = recs
        assert rec["id"] == "giant" and rec["status"] == "ok", rec
        assert rec["placement"] == "mega"
    finally:
        gw.request_drain()
        assert gw.wait_drained(60)
        gw.close()
    solo = solo_sharded(HeatConfig(n=16, ntime=12, dtype="float64"))
    with np.load(tmp_path / "results" / "giant.npz") as z:
        assert z["T"].tobytes() == solo.tobytes()


# --- config / CLI surfaces ---------------------------------------------------


def test_parse_mega_lanes_grammar_and_validation():
    assert parse_mega_lanes("auto") is None
    assert parse_mega_lanes("0") == 0
    assert parse_mega_lanes(3) == 3
    with pytest.raises(ValueError, match="mega-lanes"):
        parse_mega_lanes("sideways")
    with pytest.raises(ValueError, match="mega-lanes"):
        parse_mega_lanes("-1")
    with pytest.raises(ValueError, match="mega_lanes"):
        ServeConfig(mega_lanes=-2)
    assert ServeConfig(mega_lanes=None).mega_lanes is None


def test_serve_cli_mega_flags(tmp_cwd, capsys):
    from heat_tpu.cli import main

    reqs = tmp_cwd / "reqs.jsonl"
    reqs.write_text('{"id": "big", "n": 16, "ntime": 8, '
                    '"dtype": "float64"}\n'
                    '{"id": "small", "n": 8, "ntime": 8, '
                    '"dtype": "float64"}\n')
    # mega off: the overflow is a rejection with the hint in its record
    rc = main(["serve", "--requests", "reqs.jsonl", "--buckets", "8",
               "--chunk", "8", "--mega-lanes", "0"])
    out = capsys.readouterr().out
    assert rc == 1
    records = {r["id"]: r for r in
               (json.loads(l) for l in out.splitlines()
                if l.startswith("{") and '"serve_request"' in l)}
    assert records["big"]["status"] == "rejected"
    assert records["big"]["hint"] == "enable --mega-lanes"
    assert records["small"]["status"] == "ok"
    # mega on (auto, 8-device harness): both serve; the report says so
    rc = main(["serve", "--requests", "reqs.jsonl", "--buckets", "8",
               "--chunk", "8"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "2 ok" in out
    assert "placement: 1 packed, 1 mega" in out
    # bad value is a CLI error
    rc = main(["serve", "--requests", "reqs.jsonl", "--buckets", "8",
               "--mega-lanes", "many"])
    assert rc == 2
    assert "mega-lanes" in capsys.readouterr().err


def test_info_prints_serve_placement_line(capsys):
    from heat_tpu.cli import main

    assert main(["info"]) == 0
    out = capsys.readouterr().out
    assert "serve placement: two-tier" in out
    assert "mega-lanes default 1" in out   # the 8-device harness


def test_serve_mega_lab_harness_smoke(tmp_path):
    """The mega lab harness runs end-to-end on a tiny population and
    emits every field the committed artifact relies on. The 10% perf
    ratio is deliberately NOT asserted at toy scale (the mega tier's
    fixed cost dominates a 0.1 s drain); the structural gates are."""
    import importlib.util
    import sys
    from pathlib import Path

    bench_dir = Path(__file__).resolve().parent.parent / "benchmarks"
    sys.path.insert(0, str(bench_dir))
    try:
        spec = importlib.util.spec_from_file_location(
            "serve_mega_lab_smoke", bench_dir / "serve_mega_lab.py")
        lab = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(lab)
        out = tmp_path / "serve_mega_lab.json"
        lab.main(["--requests", "4", "--lanes", "2", "--chunk", "8",
                  "--waves", "1", "--oversized-side", "64",
                  "--oversized-ntimes", "8", "--out", str(out)])
    finally:
        sys.path.remove(str(bench_dir))
    rec = json.loads(out.read_text())
    assert rec["bench"] == "serve_mega_lab"
    assert rec["mega_bit_identical"] is True
    assert rec["packed_bit_identical"] is True
    assert rec["zero_overflow_rejections"] is True
    assert rec["mega_resident"]["mega_statuses"] == ["ok"]
    assert rec["mega_resident"]["mega_placements"] == ["mega"]
    assert rec["mega_resident"]["warm_mega_compiles"] == 0
    assert rec["packed_throughput_ratio"] is not None
    assert "packed_within_10pct" in rec
