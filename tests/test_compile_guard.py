"""Compile-time guard on the default sharded path (VERDICT r3 #2).

The auto fuse depth resolves to k*=32 at flagship 2D scale, the depth
whose compile stalled >25 min in round 3. These tests pin the guard's
policy: bounded probe of EVERY chunk size drive will compile, executable
hand-off (no double compile), loud job-wide k=16 fallback on timeout,
and — just as important — all the cases where the guard must stay out of
the way (explicit fuse_steps, shallow auto depths, budget 0, CPU)."""

import time

import pytest

from heat_tpu.backends import common, sharded
from heat_tpu.config import HeatConfig
from heat_tpu.parallel.mesh import build_mesh


def _flagship_cfg(**kw):
    # n=4096: the auto depth is 32 (narrow shard, chunk cap 32), which
    # is the class the guard still covers after round 5 capped wide
    # shards at k=16 and left depths <= 16 unguarded (the 16384^2
    # flagship's k=16 live compile is a bounded 471 s; probing it via
    # the topology child costs >2000 s — see _guard_fuse_compile)
    kw.setdefault("fuse_steps", 0)
    kw.setdefault("ntime", 500)
    kw.setdefault("dtype", "float32")
    return HeatConfig(n=4096, backend="sharded", mesh_shape=(1, 1), **kw)


@pytest.fixture
def mesh():
    return build_mesh(2, (1, 1))


def test_chunk_sizes_match_drive_warmup():
    # steady chunk + remainder: both are programs drive compiles, so both
    # are programs the guard must bound
    cfg = HeatConfig(n=64, ntime=1000, heartbeat_every=300)
    assert common.chunk_sizes(cfg, 1000) == [100, 300]
    assert common.chunk_sizes(cfg, 300) == [300]
    assert common.chunk_sizes(cfg, 0) == []
    assert common.chunk_sizes(HeatConfig(n=64, ntime=500), 500) == [500]


def test_bounded_compile_success_and_timeout():
    r, err = sharded._bounded_compile(lambda: 42, budget_s=5.0)
    assert (r, err) == (42, None)
    r, err = sharded._bounded_compile(lambda: time.sleep(30), budget_s=0.05)
    assert (r, err) == (None, "timeout")


def test_bounded_compile_propagates_exceptions():
    with pytest.raises(RuntimeError, match="boom"):
        sharded._bounded_compile(
            lambda: (_ for _ in ()).throw(RuntimeError("boom")), 5.0)


def test_agree_any_timeout_single_process_is_identity():
    assert sharded._agree_any_timeout(False) is False
    assert sharded._agree_any_timeout(True) is True


def test_guard_falls_back_on_compile_timeout(mesh, monkeypatch, capsys):
    # thread mode: monkeypatching _compile_probe only works in-process
    monkeypatch.setenv("HEAT_GUARD_PROBE", "thread")
    monkeypatch.setenv("HEAT_COMPILE_BUDGET_S", "0.05")
    monkeypatch.setattr(sharded, "_guard_platform_ok", lambda: True)
    monkeypatch.setattr(sharded, "_compile_probe",
                        lambda *a, **kw: time.sleep(30))
    cfg = _flagship_cfg()
    assert sharded.fuse_depth_sharded(cfg, (1, 1)) == 32
    out, pre, rep = sharded._guard_fuse_compile(cfg, mesh, cfg.ntime)
    assert out.local_kernel == "xla" and pre is None
    # the probed depth is PINNED into the fallback: the xla kernel is
    # exempt from the chunk cap, so fuse_steps=0 could silently
    # recompute a different depth than the warning promises
    assert out.fuse_steps == 32
    assert rep.probe_s > 0  # the probe's wall cost is reported, not hidden
    assert rep.timed_out and rep.orphan == "left_running"  # thread probe
    assert rep.degraded == {"local_kernel": "xla", "fuse_steps": 32}
    msg = capsys.readouterr().out
    assert "WARNING" in msg and "local_kernel='xla'" in msg


def test_guard_falls_back_when_a_peer_timed_out(mesh, monkeypatch, capsys):
    """Job-wide agreement: even a LOCALLY successful probe must fall back
    if any peer's timed out — different fuse depths are different SPMD
    programs (mismatched collectives hang the job)."""
    monkeypatch.setenv("HEAT_GUARD_PROBE", "thread")
    monkeypatch.setenv("HEAT_COMPILE_BUDGET_S", "5")
    monkeypatch.setattr(sharded, "_guard_platform_ok", lambda: True)
    monkeypatch.setattr(sharded, "_compile_probe",
                        lambda *a, **kw: {500: object()})
    monkeypatch.setattr(sharded, "_agree_any_timeout", lambda t: True)
    out, pre, rep = sharded._guard_fuse_compile(_flagship_cfg(), mesh, 500)
    assert out.local_kernel == "xla" and pre is None
    assert rep.timed_out  # the agreed verdict, not the local outcome


def test_guard_hands_probe_executables_forward(mesh, monkeypatch):
    monkeypatch.setenv("HEAT_GUARD_PROBE", "thread")
    monkeypatch.setenv("HEAT_COMPILE_BUDGET_S", "5")
    monkeypatch.setattr(sharded, "_guard_platform_ok", lambda: True)
    fake = {500: object()}
    calls = []

    def probe(cfg, mesh, kf, remaining, padded):
        calls.append((kf, remaining, padded))
        return fake

    monkeypatch.setattr(sharded, "_compile_probe", probe)
    out, pre, rep = sharded._guard_fuse_compile(_flagship_cfg(), mesh, 500)
    assert out.fuse_steps == 0      # auto depth survives
    assert pre is fake              # drive never recompiles the probe's work
    assert calls == [(32, 500, True)]
    assert rep.probed and not rep.timed_out and rep.orphan is None


def test_guard_timeout_on_overlap_degrades_exchange_too(mesh, monkeypatch,
                                                        capsys):
    """VERDICT r4 #1 (reproduced crash): exchange='overlap' is built on the
    Pallas kernel, so a guard fallback to local_kernel='xla' that leaves
    exchange='overlap' set hands make_local_multistep a cfg it rejects.
    The fallback must degrade BOTH knobs — never raise."""
    monkeypatch.setenv("HEAT_GUARD_PROBE", "thread")
    monkeypatch.setenv("HEAT_COMPILE_BUDGET_S", "0.05")
    monkeypatch.setattr(sharded, "_guard_platform_ok", lambda: True)
    monkeypatch.setattr(sharded, "_compile_probe",
                        lambda *a, **kw: time.sleep(30))
    cfg = _flagship_cfg(exchange="overlap")
    out, pre, rep = sharded._guard_fuse_compile(cfg, mesh, cfg.ntime)
    assert out.local_kernel == "xla" and out.exchange == "indep"
    assert pre is None and rep.probe_s > 0
    assert rep.degraded == {"local_kernel": "xla", "exchange": "indep",
                            "fuse_steps": 32}
    msg = capsys.readouterr().out
    assert "overlap" in msg and "'indep'" in msg
    # the degraded cfg must be one make_local_multistep accepts (this is
    # the exact line the unfixed fallback crashed on)
    sharded.make_local_multistep(out, ("x", "y"), (1, 1))


def test_guard_probe_crash_on_overlap_degrades_exchange_too(
        mesh, monkeypatch):
    """Same cross-feature hole via the probe-crash branch (e.g.
    RESOURCE_EXHAUSTED on the deep unroll)."""
    monkeypatch.setenv("HEAT_GUARD_PROBE", "thread")
    monkeypatch.setenv("HEAT_COMPILE_BUDGET_S", "5")
    monkeypatch.setattr(sharded, "_guard_platform_ok", lambda: True)

    def boom(*a, **kw):
        raise RuntimeError("RESOURCE_EXHAUSTED: vmem")

    monkeypatch.setattr(sharded, "_compile_probe", boom)
    out, pre, _ = sharded._guard_fuse_compile(
        _flagship_cfg(exchange="overlap"), mesh, 500)
    assert out.local_kernel == "xla" and out.exchange == "indep"
    sharded.make_local_multistep(out, ("x", "y"), (1, 1))


def test_guard_timeout_keeps_non_overlap_exchange(mesh, monkeypatch):
    """The degrade is surgical: seq/indep exchanges run fine on the XLA
    kernel and must survive the fallback untouched."""
    monkeypatch.setenv("HEAT_GUARD_PROBE", "thread")
    monkeypatch.setenv("HEAT_COMPILE_BUDGET_S", "0.05")
    monkeypatch.setattr(sharded, "_guard_platform_ok", lambda: True)
    monkeypatch.setattr(sharded, "_compile_probe",
                        lambda *a, **kw: time.sleep(30))
    for exch in ("seq", "indep"):
        out, _, _ = sharded._guard_fuse_compile(
            _flagship_cfg(exchange=exch), mesh, 500)
        assert (out.local_kernel, out.exchange) == ("xla", exch)


def test_guarded_overlap_solve_end_to_end_on_timeout(mesh, monkeypatch):
    """The verdict's repro, at test scale: a guard timeout on an overlap
    cfg must SOLVE (via the degraded indep+xla program) and match the
    oracle bitwise — not raise ValueError."""
    import numpy as np

    cfg = HeatConfig(n=64, ntime=20, heartbeat_every=8, dtype="float32",
                     backend="sharded", mesh_shape=(1, 1),
                     exchange="overlap")
    ref = sharded.solve(cfg.with_(exchange="indep", local_kernel="xla"),
                        fetch=True)
    monkeypatch.setenv("HEAT_GUARD_PROBE", "thread")
    monkeypatch.setenv("HEAT_COMPILE_BUDGET_S", "0.05")
    monkeypatch.setattr(sharded, "_guard_platform_ok", lambda: True)
    monkeypatch.setattr(sharded, "_SAFE_FUSE", 1)  # open the depth gate
    monkeypatch.setattr(sharded, "_compile_probe",
                        lambda *a, **kw: time.sleep(30))
    got = sharded.solve(cfg, fetch=True)
    np.testing.assert_array_equal(np.asarray(ref.T), np.asarray(got.T))


def test_default_budget_clears_measured_flagship_compiles():
    """The budget must sit ABOVE every measured legitimate cold compile
    (slowest: 1833 s, benchmarks/overlap_compile_check.json) — otherwise
    the default-config overlap run defaults into the fallback (VERDICT r4
    weak #1: the old 1800 s default did exactly that)."""
    assert float(sharded._DEFAULT_BUDGET_S) > 1833


@pytest.mark.parametrize("why,cfg_kw,env", [
    ("explicit fuse_steps is the user's own program",
     {"fuse_steps": 32}, {}),
    ("remaining 0 compiles nothing", {"ntime": 0}, {}),
    ("xla local kernel compiles in seconds — nothing to guard",
     {"local_kernel": "xla"}, {}),
    ("f64 runs the XLA path", {"dtype": "float64"}, {}),
])
def test_guard_stays_out_of_the_way(mesh, monkeypatch, why, cfg_kw, env):
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    monkeypatch.setattr(sharded, "_guard_platform_ok", lambda: True)
    monkeypatch.setattr(
        sharded, "_compile_probe",
        lambda *a, **kw: pytest.fail(f"probe must not run: {why}"))
    cfg = _flagship_cfg(**cfg_kw)
    out, pre, rep = sharded._guard_fuse_compile(cfg, mesh, cfg.ntime)
    assert (out, pre) == (cfg, None) and not rep.probed


def test_guard_budget_zero_skips_probe_but_joins_agreement(mesh, monkeypatch):
    """HEAT_COMPILE_BUDGET_S=0 is per-host state: it must disable the
    probe but NOT the job-wide agreement — a process skipping a collective
    its peers entered hangs the job (divergence-safety contract)."""
    monkeypatch.setenv("HEAT_COMPILE_BUDGET_S", "0")
    monkeypatch.setattr(sharded, "_guard_platform_ok", lambda: True)
    monkeypatch.setattr(
        sharded, "_compile_probe",
        lambda *a, **kw: pytest.fail("budget 0 must skip the probe"))
    joined = []

    def agree(t):
        joined.append(t)
        return t

    monkeypatch.setattr(sharded, "_agree_any_timeout", agree)
    cfg = _flagship_cfg()
    out, pre, _ = sharded._guard_fuse_compile(cfg, mesh, cfg.ntime)
    assert (out, pre) == (cfg, None)
    assert joined == [False]  # participated, voted "no timeout"


def test_guard_probe_exception_falls_back_and_joins_agreement(
        mesh, monkeypatch, capsys):
    """A probe crash (e.g. RESOURCE_EXHAUSTED on the deep unroll) must
    fall back — and still reach the agreement collective."""
    monkeypatch.setenv("HEAT_GUARD_PROBE", "thread")
    monkeypatch.setenv("HEAT_COMPILE_BUDGET_S", "5")
    monkeypatch.setattr(sharded, "_guard_platform_ok", lambda: True)

    def boom(*a, **kw):
        raise RuntimeError("RESOURCE_EXHAUSTED: vmem")

    monkeypatch.setattr(sharded, "_compile_probe", boom)
    joined = []

    def agree(t):
        joined.append(t)
        return t

    monkeypatch.setattr(sharded, "_agree_any_timeout", agree)
    out, pre, _ = sharded._guard_fuse_compile(_flagship_cfg(), mesh, 500)
    assert out.local_kernel == "xla" and pre is None
    assert joined == [True]
    assert "probe failed" in capsys.readouterr().out


def test_guard_noop_on_cpu(mesh, monkeypatch):
    monkeypatch.setenv("HEAT_COMPILE_BUDGET_S", "5")
    monkeypatch.setattr(
        sharded, "_compile_probe",
        lambda *a, **kw: pytest.fail("probe must not run on cpu"))
    cfg = _flagship_cfg()
    out, pre, rep = sharded._guard_fuse_compile(cfg, mesh, cfg.ntime)
    assert (out, pre) == (cfg, None) and not rep.probed


def test_guard_noop_at_safe_depths(mesh, monkeypatch):
    # depths <= _SAFE_FUSE never probe (round 5: the chunk cap bounds
    # every such program's live compile; the probe would cost more than
    # the compile — see test_guard_skips_capped_flagship_depths)
    monkeypatch.setattr(sharded, "_guard_platform_ok", lambda: True)
    monkeypatch.setattr(
        sharded, "_compile_probe",
        lambda *a, **kw: pytest.fail("k<=16 needs no guard"))
    shallow = HeatConfig(n=128, ntime=100, dtype="float32",
                         backend="sharded", mesh_shape=(1, 1))  # k* = 8
    assert sharded.fuse_depth_sharded(shallow, (1, 1)) < sharded._SAFE_FUSE
    out, pre, rep = sharded._guard_fuse_compile(shallow, mesh, 100)
    assert (out, pre) == (shallow, None) and not rep.probed

    narrow16 = HeatConfig(n=512, ntime=100, dtype="float32",
                          backend="sharded", mesh_shape=(1, 1))
    # auto k* = sqrt(512/2) = 16 — ON the boundary: no probe
    assert sharded.fuse_depth_sharded(narrow16, (1, 1)) == sharded._SAFE_FUSE
    out, pre, rep = sharded._guard_fuse_compile(narrow16, mesh, 100)
    assert (out, pre) == (narrow16, None) and not rep.probed


def test_guard_skips_capped_flagship_depths(monkeypatch):
    """Round-5 policy: the chunk cap removes the wedge family from the
    auto path (wide shards cap at k=16, live cold compile a bounded
    471 s), and the subprocess probe's topology-path compile of that
    same program costs >2000 s (measured; live cache entries do not
    serve the topology child) — so depths <= 16 must NOT probe: the
    guard would cost 4x the compile it bounds and could time the
    default flagship into the degraded kernel. (Stub mesh: the guard
    reads only mesh.devices.shape and the probe is patched.)"""
    monkeypatch.setenv("HEAT_GUARD_PROBE", "thread")
    monkeypatch.setenv("HEAT_COMPILE_BUDGET_S", "0.05")
    monkeypatch.setattr(sharded, "_guard_platform_ok", lambda: True)
    monkeypatch.setattr(
        sharded, "_compile_probe",
        lambda *a, **kw: pytest.fail("capped depths must not probe"))

    class _Devices:
        shape = (1, 1)

    class _StubMesh:
        devices = _Devices()

    # the 16384^2 flagship: auto depth capped at 16 -> unguarded
    flagship = HeatConfig(n=16384, ntime=100, dtype="float32",
                          backend="sharded", mesh_shape=(1, 1))
    assert sharded.fuse_depth_sharded(flagship, (1, 1)) == 16
    out, pre, rep = sharded._guard_fuse_compile(flagship, _StubMesh(), 100)
    assert (out, pre) == (flagship, None) and not rep.probed

    # anisotropic wide-shallow (128-row shards of 16384^2, kf=8): also
    # unguarded — its k=8 live compile is the bounded 393 s family, not
    # the wedge
    class _Devices128:
        shape = (128, 1)

    class _StubMesh128:
        devices = _Devices128()

    aniso = HeatConfig(n=16384, ntime=100, dtype="float32",
                       backend="sharded", mesh_shape=(128, 1))
    assert sharded.fuse_depth_sharded(aniso, (128, 1)) < 16
    out, pre, rep = sharded._guard_fuse_compile(aniso, _StubMesh128(), 100)
    assert (out, pre) == (aniso, None) and not rep.probed


@pytest.mark.parametrize("padded", [True, False])
def test_compile_probe_compiles_every_chunk_size(mesh, padded):
    """The probe must cover the remainder chunk too (it unrolls the same
    deep-fused kernel and is a distinct XLA program), on the path's real
    global state shape. Runs end to end on CPU (interpret-mode pallas)."""
    cfg = HeatConfig(n=64, ntime=20, heartbeat_every=8, dtype="float32",
                     backend="sharded", mesh_shape=(1, 1), fuse_steps=4)
    pre = sharded._compile_probe(cfg, mesh, kf=4, remaining=20,
                                 padded=padded)
    assert sorted(pre) == [4, 8]  # steady 8 + remainder 20 % 8


def test_guarded_solve_uses_probe_executables(mesh, monkeypatch):
    """End-to-end on CPU: force the guard on, let the real probe compile,
    and check the solve still matches the unguarded result bitwise."""
    import numpy as np

    cfg = HeatConfig(n=64, ntime=20, heartbeat_every=8, dtype="float32",
                     backend="sharded", mesh_shape=(1, 1))
    ref = sharded.solve(cfg, fetch=True)

    monkeypatch.setenv("HEAT_COMPILE_BUDGET_S", "60")
    monkeypatch.setattr(sharded, "_guard_platform_ok", lambda: True)
    # force the depth gate open: pretend the auto depth is past safe
    monkeypatch.setattr(sharded, "_SAFE_FUSE", 1)
    got = sharded.solve(cfg, fetch=True)
    np.testing.assert_array_equal(np.asarray(ref.T), np.asarray(got.T))


def test_subprocess_probe_timeout_kills_child(mesh, monkeypatch, capsys):
    """Default (subprocess) mode, real child, sub-second budget: the
    guard must SIGKILL the probe's process group — no orphan Mosaic
    compile outlives the solve (VERDICT r4 #8) — and record the kill."""
    import subprocess

    monkeypatch.setenv("HEAT_COMPILE_BUDGET_S", "0.2")
    monkeypatch.setattr(sharded, "_guard_platform_ok", lambda: True)
    monkeypatch.setattr(sharded, "_SAFE_FUSE", 1)
    cfg = HeatConfig(n=64, ntime=20, dtype="float32", backend="sharded",
                     mesh_shape=(1, 1))
    out, pre, rep = sharded._guard_fuse_compile(cfg, mesh, cfg.ntime)
    assert rep.probe_mode == "subprocess"
    assert rep.timed_out and rep.orphan == "killed" and pre is None
    assert out.local_kernel == "xla"
    # no probe child survives the guard (retry: process-table reaping of
    # the SIGKILLed group is asynchronous, and slow under a contended
    # core — a Mosaic lab compile sharing this 1-core host stretched it
    # past a 5 s window once)
    for _ in range(40):
        left = subprocess.run(["pgrep", "-f", "heat_tpu.backends.guard_probe"],
                              capture_output=True, text=True).stdout.strip()
        if not left:
            break
        time.sleep(0.25)
    assert left == "", f"orphan probe processes: {left}"


def test_subprocess_child_error_degrades_to_thread(mesh, monkeypatch):
    """An environmental child failure (e.g. libtpu lockfile held by a
    concurrent lab) must NOT invent a timeout verdict: the guard retries
    in-thread with the remaining budget."""
    monkeypatch.setenv("HEAT_COMPILE_BUDGET_S", "30")
    monkeypatch.setattr(sharded, "_guard_platform_ok", lambda: True)
    monkeypatch.setattr(sharded, "_subprocess_probe",
                        lambda *a, **kw: (None, "child-error: lockfile"))
    fake = {500: object()}
    monkeypatch.setattr(sharded, "_compile_probe", lambda *a, **kw: fake)
    out, pre, rep = sharded._guard_fuse_compile(_flagship_cfg(), mesh, 500)
    assert rep.probe_mode == "subprocess->thread"
    assert pre is fake and not rep.timed_out
    assert out.local_kernel == "auto"  # un-degraded


def test_subprocess_deserialize_failure_keeps_pallas(mesh, monkeypatch):
    """A child that compiled IN budget but whose executables didn't
    transfer proves the program is fine: the solve proceeds un-degraded
    (drive recompiles, bounded) and the report says why compile_s will
    show a second compile."""
    monkeypatch.setenv("HEAT_COMPILE_BUDGET_S", "30")
    monkeypatch.setattr(sharded, "_guard_platform_ok", lambda: True)
    monkeypatch.setattr(sharded, "_subprocess_probe",
                        lambda *a, **kw: (None, "deserialize-failed"))
    cfg = _flagship_cfg()
    out, pre, rep = sharded._guard_fuse_compile(cfg, mesh, 500)
    assert out is cfg and pre is None
    assert rep.deserialize_failed and not rep.timed_out
    assert rep.orphan is None and rep.degraded is None


def test_solve_attaches_guard_report(mesh, monkeypatch):
    """SolveResult.guard must carry the probe's cost and verdict — a
    bench consumer has to be able to SEE that its row ran the degraded
    program (VERDICT r4 #8)."""
    monkeypatch.setenv("HEAT_GUARD_PROBE", "thread")
    monkeypatch.setenv("HEAT_COMPILE_BUDGET_S", "0.05")
    monkeypatch.setattr(sharded, "_guard_platform_ok", lambda: True)
    monkeypatch.setattr(sharded, "_SAFE_FUSE", 1)
    monkeypatch.setattr(sharded, "_compile_probe",
                        lambda *a, **kw: time.sleep(30))
    cfg = HeatConfig(n=64, ntime=20, dtype="float32", backend="sharded",
                     mesh_shape=(1, 1))
    res = sharded.solve(cfg, fetch=False)
    assert res.guard is not None and res.guard.timed_out
    assert res.guard.orphan == "left_running"
    assert res.guard.degraded == {
        "local_kernel": "xla",
        "fuse_steps": sharded.fuse_depth_sharded(cfg, (1, 1))}
    assert res.timing.compile_s >= res.guard.probe_s > 0  # cost visible

    # ... and stays None when the guard never probed
    res2 = sharded.solve(cfg.with_(local_kernel="xla"), fetch=False)
    assert res2.guard is None


def test_guard_probe_child_protocol(tmp_path):
    """The child module end-to-end on CPU: spec.json in, pickled
    serialized executables out, exit 0 — the exact protocol
    _subprocess_probe speaks (the in-process tests above monkeypatch
    around the child; this pins the child itself)."""
    import dataclasses
    import json
    import pickle
    import subprocess
    import sys

    # fuse_steps pinned so the spec's kf matches what the machinery
    # derives — a mismatched pair would pin a ghost-width the real
    # parent/child protocol never ships (code-review r5)
    cfg = HeatConfig(n=64, ntime=20, dtype="float32", backend="sharded",
                     mesh_shape=(1, 1), fuse_steps=4)
    assert sharded.fuse_depth_sharded(cfg, (1, 1)) == 4
    out_path = tmp_path / "pre.pkl"
    spec = {"cfg": {**dataclasses.asdict(cfg), "local_kernel": "xla"},
            "mesh_shape": [1, 1], "axis_names": ["x", "y"],
            "kf": 4, "remaining": 20, "padded": True,
            "platform": "cpu", "chip": "v5e", "out": str(out_path)}
    spec_path = tmp_path / "spec.json"
    spec_path.write_text(json.dumps(spec))
    p = subprocess.run(
        [sys.executable, "-m", "heat_tpu.backends.guard_probe",
         str(spec_path)], capture_output=True, text=True, timeout=300)
    assert p.returncode == 0, p.stderr[-800:]
    payloads = pickle.loads(out_path.read_bytes())
    # chunk_sizes(cfg, 20) == [20]: one steady chunk, serialized as
    # (bytes, in_tree, out_tree)
    assert sorted(payloads) == [20]
    ser, in_tree, out_tree = payloads[20]
    assert isinstance(ser, bytes) and len(ser) > 0


def test_guard_probe_topology_spec_mapping():
    from heat_tpu.backends.guard_probe import topology_spec

    # single-chip (the BENCH path) needs the sub-host bounds override:
    # the default chips_per_host_bounds 2x2x1 rejects "v5e:1x1" as not
    # divisible (observed on the attached libtpu, sweep_r5.log r5)
    assert topology_spec("v5e", 1) == (
        "v5e:1x1", {"chips_per_host_bounds": [1, 1, 1]})
    # full-host layouts use the default bounds
    assert topology_spec("v5e", 4) == ("v5e:2x2", {})
    assert topology_spec("v6e", 16) == ("v6e:4x4", {})
    # v5p/v4 are 3-D spellings ("v5p:2x4" was never valid)
    assert topology_spec("v5p", 8) == ("v5p:2x2x2", {})
    assert topology_spec("v5p", 1) == (
        "v5p:1x1x1", {"chips_per_host_bounds": [1, 1, 1]})
    # v4 exposes two devices per chip -> odd counts unspellable
    assert topology_spec("v4", 2) == (
        "v4:1x1x1", {"chips_per_host_bounds": [1, 1, 1]})
    assert topology_spec("v4", 1) is None
    assert topology_spec("v5e", 3) is None  # no spelling -> child exits 3
    assert topology_spec("unknown-chip", 4) is None


def test_guard_probe_topology_specs_construct():
    """Every spelled topology must actually CONSTRUCT against libtpu —
    the flat-table bug shipped precisely because the spellings were
    never validated (the old test pinned two invalid ones). Chipless:
    get_topology_desc needs only the libtpu compiler, no device."""
    pytest.importorskip("jax.experimental.topologies")
    from jax.experimental import topologies

    from heat_tpu.backends.guard_probe import _TOPO_BY_CHIP, topology_spec

    try:
        topologies.get_topology_desc("v5e:2x2", "tpu")
    except Exception:
        pytest.skip("no TPU-capable libtpu on this host")

    for chip, table in _TOPO_BY_CHIP.items():
        for ndev in table:
            name, kwargs = topology_spec(chip, ndev)
            topo = topologies.get_topology_desc(name, "tpu", **kwargs)
            assert len(topo.devices) == ndev, (
                f"{name} {kwargs}: {len(topo.devices)} devices, "
                f"expected {ndev}")
