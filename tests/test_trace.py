"""Request-scoped tracing + flight recorder (runtime/trace.py, ISSUE 7).

The load-bearing contracts:

- **Schema**: a ``--trace`` export is Chrome trace-event JSON Perfetto
  can load — required keys on every event, id-paired flow and async
  events, monotone timestamps per track, and the span taxonomy the
  README documents (lane occupancy, chunk-in-flight, boundary-fetch,
  queue-wait, writeback) actually present for a real drain.
- **Flight recorder**: an injected ``fetch-hang`` leaves an atomic
  ``flightrec-*.trace.json`` dump containing the wedged request's full
  span chain — without hanging the engine.
- **Bit-identity**: tracing on/off produces identical npz outputs at
  dispatch depths 0 and 2 (observability must never perturb physics).
"""

import json

import numpy as np
import pytest

from heat_tpu.config import HeatConfig
from heat_tpu.runtime import faults
from heat_tpu.runtime import trace as trace_mod
from heat_tpu.serve import Engine, ServeConfig


@pytest.fixture(autouse=True)
def _fresh_faults():
    faults.reset()
    yield
    faults.reset()


def quiet(**kw) -> ServeConfig:
    kw.setdefault("emit_records", False)
    return ServeConfig(**kw)


WAVE = [HeatConfig(n=16, ntime=24, dtype="float64"),
        HeatConfig(n=16, ntime=40, dtype="float64", nu=0.1),
        HeatConfig(n=24, ntime=32, dtype="float64", bc="ghost",
                   ic="uniform"),
        HeatConfig(n=16, ntime=16, dtype="float64", ic="hat_small")]


def drain(tmp_path, tag, **scfg_kw):
    out = tmp_path / tag
    eng = Engine(quiet(lanes=2, chunk=8, buckets=(24,), out_dir=str(out),
                       keep_fields=True, **scfg_kw))
    ids = [eng.submit(cfg, request_id=f"{tag}-{i}",
                      tenant=("acme", "free")[i % 2])
           for i, cfg in enumerate(WAVE)]
    recs = {r["id"]: r for r in eng.results()}
    return eng, recs, ids


# --- tracer unit contracts ----------------------------------------------------


def test_ring_is_bounded_and_disabled_tracer_records_nothing():
    t = trace_mod.Tracer(capacity=4)
    tr = t.track("p", "t")
    for i in range(32):
        t.instant(f"e{i}", tr)
    assert len(t) == 4 and t.dropped_hint
    # newest events survive, oldest dropped — ring, not truncation
    names = {e["name"] for e in t.to_chrome()["traceEvents"]
             if e["ph"] == "i"}
    assert names == {"e28", "e29", "e30", "e31"}

    off = trace_mod.Tracer(capacity=0)
    assert not off.enabled
    off.instant("x", off.track("p", "t"))
    off.complete("y", off.track("p", "t"), 0.0, 1.0)
    assert len(off) == 0
    # ids still mint (the record schema never depends on tracing state)
    assert off.mint_trace_id() != off.mint_trace_id()


def test_resolve_trace_env_and_flags(monkeypatch):
    monkeypatch.delenv(trace_mod.ENV_VAR, raising=False)
    assert trace_mod.resolve_trace(None, None) == (
        None, trace_mod.DEFAULT_BUFFER)
    assert trace_mod.resolve_trace("t.json", 512) == ("t.json", 512)
    monkeypatch.setenv(trace_mod.ENV_VAR, "env.json")
    assert trace_mod.resolve_trace(None, None) == (
        "env.json", trace_mod.DEFAULT_BUFFER)
    # the flag wins over the env path
    assert trace_mod.resolve_trace("flag.json", None)[0] == "flag.json"
    monkeypatch.setenv(trace_mod.ENV_VAR, "off")
    assert trace_mod.resolve_trace(None, None) == (None, 0)
    with pytest.raises(ValueError, match="trace-buffer"):
        trace_mod.resolve_trace("t.json", 0)
    with pytest.raises(ValueError, match="trace-buffer"):
        trace_mod.resolve_trace(None, -1)


def test_serve_config_validates_trace_knobs():
    with pytest.raises(ValueError, match="trace_buffer"):
        ServeConfig(trace_buffer=-1)
    with pytest.raises(ValueError, match="trace"):
        ServeConfig(trace="t.json", trace_buffer=0)


def test_counter_samples_render_in_export_and_summary():
    """The numerics observatory's 'C' counter samples (ISSUE 15): args
    flow to the Chrome export as counter tracks, and summarize() renders
    min/max/last per series. A disabled tracer records nothing."""
    t = trace_mod.Tracer(capacity=16)
    tr = t.track("lanes", "g0")
    t.counter("numerics lane 0", tr, {"resid": 1.0, "heat": 5.0})
    t.counter("numerics lane 0", tr, {"resid": 0.25, "heat": 4.0})
    chrome = t.to_chrome()
    cs = [e for e in chrome["traceEvents"] if e["ph"] == "C"]
    assert len(cs) == 2
    assert all(e["name"] == "numerics lane 0" for e in cs)
    assert cs[0]["args"] == {"resid": 1.0, "heat": 5.0}
    text = "\n".join(trace_mod.summarize(chrome))
    assert "counter tracks:" in text
    assert ("numerics lane 0/resid: 2 sample(s), min 0.25, max 1, "
            "last 0.25") in text
    assert "numerics lane 0/heat: 2 sample(s)" in text

    off = trace_mod.Tracer(capacity=0)
    off.counter("x", off.track("p", "t"), {"v": 1.0})
    assert len(off) == 0


def test_serve_trace_carries_numerics_counter_tracks(tmp_path):
    """A real drain with the observatory on exports per-lane residual/
    heat counter samples on the group's track."""
    path = tmp_path / "num.trace.json"
    drain(tmp_path, "ctr", trace=str(path))
    evs = json.loads(path.read_text())["traceEvents"]
    cs = [e for e in evs if e["ph"] == "C"]
    assert cs and any(e["name"].startswith("numerics lane") for e in cs)
    series = set()
    for e in cs:
        series |= set(e["args"])
    assert {"resid", "heat"} <= series
    text = "\n".join(trace_mod.summarize_file(path))
    assert "counter tracks:" in text and "numerics lane" in text


def test_trace_cli_triage_names_numerics_violation_dump(tmp_cwd, capsys):
    """`heat-tpu trace <flightrec-*.json>` prints a one-line triage verdict
    naming the likely trigger — a numerics violation here."""
    from heat_tpu.cli import main

    eng = Engine(quiet(lanes=1, chunk=4, buckets=(12,),
                       inject="perturb@6:eps=100",
                       flight_dir=str(tmp_cwd)))
    rid = eng.submit(HeatConfig(n=12, ntime=24, dtype="float32"))
    recs = {r["id"]: r for r in eng.results()}
    assert recs[rid]["status"] == "ok"        # guard=warn observes only
    (dump,) = sorted(tmp_cwd.glob("flightrec-*.trace.json"))
    capsys.readouterr()
    assert main(["trace", dump.name]) == 0
    out = capsys.readouterr().out
    assert "numerics-violation" in out
    assert "flight-dump triage" in out and "likely trigger" in out


# --- export schema (the Perfetto-loadability contract) ------------------------


def test_trace_export_schema_and_span_taxonomy(tmp_path):
    """Acceptance: a full drain with --trace produces a loadable Chrome
    trace: required keys everywhere, paired flow/async ids, monotone ts
    per track, and one end-to-end request visible across queue -> lane ->
    writer tracks."""
    path = tmp_path / "serve.trace.json"
    _, recs, ids = drain(tmp_path, "schema", trace=str(path))
    assert all(recs[i]["status"] == "ok" for i in ids)

    obj = json.loads(path.read_text())
    evs = obj["traceEvents"]
    assert isinstance(evs, list) and len(evs) > 20

    for e in evs:
        assert {"ph", "ts", "pid", "tid", "name"} <= set(e), e
        if e["ph"] == "X":
            assert e["dur"] >= 0

    # monotone ts per (pid, tid) track, in file order
    last = {}
    for e in evs:
        if e["ph"] == "M":
            continue
        key = (e["pid"], e["tid"])
        assert e["ts"] >= last.get(key, -1.0), (key, e)
        last[key] = e["ts"]

    # flow-event pairing: every started flow ends, steps belong to starts
    by_phase = {"s": set(), "t": set(), "f": set()}
    for e in evs:
        if e["ph"] in by_phase:
            assert e.get("id"), e
            by_phase[e["ph"]].add(e["id"])
    assert by_phase["s"] == by_phase["f"] and len(by_phase["s"]) == len(ids)
    assert by_phase["t"] <= by_phase["s"]

    # async queue-wait pairing (b/e share an id)
    b = {e["id"] for e in evs if e["ph"] == "b"}
    ee = {e["id"] for e in evs if e["ph"] == "e"}
    assert b == ee and len(b) == len(ids)

    # span taxonomy: the tracks and spans the README documents
    names = {e["name"] for e in evs if e["ph"] == "X"}
    assert {"boundary-fetch", "engine.run"} <= names
    assert any(n.startswith("chunk ") for n in names)
    assert any(n.startswith("writeback ") for n in names)
    for rid in ids:
        assert rid in names      # one occupancy span per request
    procs = {e["args"]["name"] for e in evs
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert any(p.startswith("lanes ") for p in procs)
    assert {"queue", "writer"} <= procs

    # trace ids: minted per request, echoed on the record AND in events
    rec_tids = {recs[i]["trace_id"] for i in ids}
    assert len(rec_tids) == len(ids)
    ev_tids = {e["args"]["trace_id"] for e in evs
               if e.get("args", {}).get("trace_id")}
    assert rec_tids <= ev_tids


def test_trace_summary_renders_utilization_and_queue_waits(tmp_path):
    path = tmp_path / "s.trace.json"
    drain(tmp_path, "sum", trace=str(path))
    lines = trace_mod.summarize_file(path)
    text = "\n".join(lines)
    assert "lane utilization" in text and "lane 0" in text
    assert "top queue waits" in text and "tenant acme" in text
    assert "boundary-fetch wall" in text


def test_trace_cli_subcommand_and_serve_trace_flag(tmp_cwd, capsys):
    """`heat-tpu serve --trace` writes the export; `heat-tpu trace FILE`
    summarizes it (and rejects a non-trace file loudly)."""
    from heat_tpu.cli import main

    (tmp_cwd / "reqs.jsonl").write_text(
        '{"id": "a", "n": 16, "ntime": 16, "dtype": "float64"}\n')
    assert main(["serve", "--requests", "reqs.jsonl", "--buckets", "16",
                 "--chunk", "8", "--trace", "t.trace.json"]) == 0
    out = capsys.readouterr().out
    assert "wrote trace t.trace.json" in out
    assert main(["trace", "t.trace.json"]) == 0
    out = capsys.readouterr().out
    assert "lane utilization" in out and "top queue waits" in out

    (tmp_cwd / "bogus.json").write_text("[1, 2, 3]")
    assert main(["trace", "bogus.json"]) == 2
    assert main(["trace", "missing.json"]) == 2


# --- flight recorder ----------------------------------------------------------


def test_flight_dump_on_fetch_hang_contains_span_chain(tmp_path):
    """Acceptance: an injected fetch-hang run leaves a flight-recorder
    dump containing the wedged request's full span chain (submit flow ->
    queue-wait -> occupancy -> watchdog) without hanging the engine."""
    eng = Engine(quiet(lanes=2, chunk=8, buckets=(16,),
                       inject="fetch-hang:ms=1500", fetch_timeout_s=0.2,
                       flight_dir=str(tmp_path)))
    rid = eng.submit(HeatConfig(n=16, ntime=24, dtype="float64"))
    recs = {r["id"]: r for r in eng.results()}
    assert recs[rid]["status"] == "error"
    assert eng.watchdog_fired == 1

    dumps = sorted(tmp_path.glob("flightrec-*.trace.json"))
    assert len(dumps) == 1
    evs = json.loads(dumps[0].read_text())["traceEvents"]
    tid = recs[rid]["trace_id"]
    phases = {e["ph"] for e in evs
              if e.get("id") == tid
              or e.get("args", {}).get("trace_id") == tid}
    assert "s" in phases                  # submit flow anchor
    assert {"b", "e"} <= phases           # queue-wait span
    assert "X" in phases                  # lane occupancy span
    occ = [e for e in evs if e["ph"] == "X" and e["name"] == rid]
    assert occ and occ[0]["args"]["status"] == "error"
    assert any(e["name"] == "watchdog-fired" for e in evs)
    # no torn dump left behind
    assert not list(tmp_path.glob("*.tmp"))


def test_flight_dump_on_quarantine_after_rollback_budget(tmp_path):
    """A deterministic blow-up that exhausts its rollback budget is the
    other postmortem trigger: the dump holds the rollback/quarantine
    instants for the doomed request."""
    eng = Engine(quiet(lanes=2, chunk=8, buckets=(16,), on_nan="rollback",
                       flight_dir=str(tmp_path)))
    boom = eng.submit(HeatConfig(n=16, ntime=200, dtype="float32",
                                 sigma=9.0))
    recs = {r["id"]: r for r in eng.results()}
    assert recs[boom]["status"] == "nonfinite"
    assert "deterministic blow-up" in recs[boom]["error"]
    # two dumps: the numerics observatory flags the envelope escape while
    # the field is still finite (ISSUE 15's early warning), THEN the
    # nonfinite path exhausts its rollback budget
    dumps = sorted(tmp_path.glob("flightrec-*.trace.json"))
    assert len(dumps) == 2
    first = [e["name"] for e in
             json.loads(dumps[0].read_text())["traceEvents"]
             if e["ph"] == "i"]
    assert "numerics-violation" in first
    evs = json.loads(dumps[1].read_text())["traceEvents"]
    names = [e["name"] for e in evs if e["ph"] == "i"]
    assert names.count("rollback") == 2 and "quarantine" in names


def test_no_dump_and_no_events_with_tracing_disabled(tmp_path):
    eng, recs, ids = drain(tmp_path, "off", trace_buffer=0,
                           inject="fetch-hang:ms=1500",
                           fetch_timeout_s=0.2,
                           flight_dir=str(tmp_path))
    assert not list(tmp_path.glob("flightrec-*"))
    assert len(eng.tracer) == 0
    # trace ids still minted: the record schema is tracing-independent
    assert all(recs[i]["trace_id"] for i in ids)


# --- overhead-lab harness -----------------------------------------------------


def test_trace_overhead_lab_harness_smoke(tmp_path):
    """The trace_overhead_lab harness runs end-to-end on a tiny workload
    and emits every field the committed artifact relies on. The 2% gate
    is deliberately NOT asserted here — 6 requests on a loaded CI box
    prove plumbing, not perf (the lab itself gates the real artifact)."""
    import importlib.util
    import sys
    from pathlib import Path

    bench_dir = Path(__file__).resolve().parent.parent / "benchmarks"
    sys.path.insert(0, str(bench_dir))
    try:
        spec = importlib.util.spec_from_file_location(
            "trace_overhead_lab_smoke", bench_dir / "trace_overhead_lab.py")
        lab = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(lab)
        out = tmp_path / "trace_overhead_lab.json"
        lab.main(["--requests", "6", "--lanes", "2", "--chunk", "8",
                  "--repeats", "1", "--out", str(out)])
    finally:
        sys.path.remove(str(bench_dir))
    rec = json.loads(out.read_text())
    assert rec["bench"] == "trace_overhead_lab"
    for mode in ("off", "flightrec", "full"):
        assert rec[mode]["ok"] == 6
        assert rec[mode]["wall_s"] > 0
    assert rec["off"]["events"] == 0          # tracing truly off
    assert rec["full"]["events"] > 0
    assert rec["trace_export_nonempty"] is True
    assert "full_overhead_frac" in rec and "full_within_2pct_of_off" in rec


# --- bit-identity (observability must not perturb physics) --------------------


@pytest.mark.parametrize("depth", [0, 2])
def test_trace_on_off_bit_identical_npz(tmp_path, depth):
    path = tmp_path / f"d{depth}.trace.json"
    _, off_recs, ids_off = drain(tmp_path, f"off{depth}", trace_buffer=0,
                                 dispatch_depth=depth)
    _, on_recs, ids_on = drain(tmp_path, f"on{depth}", trace=str(path),
                               dispatch_depth=depth)
    for i_off, i_on in zip(ids_off, ids_on):
        assert off_recs[i_off]["status"] == on_recs[i_on]["status"] == "ok"
        np.testing.assert_array_equal(off_recs[i_off]["T"],
                                      on_recs[i_on]["T"])
        # and through the published npz files, byte-for-byte fields
        with np.load(tmp_path / f"off{depth}" / f"{i_off}.npz") as a, \
                np.load(tmp_path / f"on{depth}" / f"{i_on}.npz") as b:
            np.testing.assert_array_equal(a["T"], b["T"])
    assert path.exists()
