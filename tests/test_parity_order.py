"""parity_order: literal update-then-swap step ordering vs a multi-rank
transcription of the reference's distributed loop.

The reference's time loop updates every owned cell against the ghosts *as
they are*, then swaps (fortran/mpi+cuda/heat.F90:206-219). With the shipped
IC the ghost ring starts filled (the IC assigns the whole padded array,
:243-251), so update-then-swap and the framework's default
exchange-then-update produce bit-identical owned cells. With an explicit T0
(a raw restart: nothing fills the ghosts) the first update reads stale
ghosts and the two orders genuinely diverge — the case round 1 argued about
in prose and this file makes executable.
"""

import numpy as np
import pytest

from heat_tpu.backends import solve
from heat_tpu.config import HeatConfig
from heat_tpu.grid import initial_condition


BASE = HeatConfig(n=24, ntime=7, dtype="float64", backend="sharded",
                  bc="ghost", ic="uniform", parity_order=True)


def literal_mpi_update_then_swap(T0, r, nsteps, bc, nranks, seed_from_ic):
    """Multi-rank transcription of fortran/mpi+cuda/heat.F90:199-223.

    1-D x decomposition over ``nranks`` (ndims=1, :28; nx=n/nblocks :92);
    each rank owns a padded ``(1-ng:nx+ng, 1-ng:ny+ng)`` block with ng=1
    (:107). Per step: snapshot (:208), update ALL owned cells reading
    ghosts (:209-215), then swap() exchanges the owned edge rows into the
    neighbors' ghost rows, proc_null edges untouched (:145-193).
    """
    n = T0.shape[0]
    nx = n // nranks
    local = []
    for rank in range(nranks):
        G = np.full((nx + 2, n + 2), bc, dtype=T0.dtype)
        G[1:-1, 1:-1] = T0[rank * nx:(rank + 1) * nx, :]
        local.append(G)
    if seed_from_ic:
        # the IC evaluates at ghost coordinates too (T = 2.0 assigns the
        # whole padded array, :243): interior-facing ghosts start holding
        # exactly the neighbor's edge values
        for rank in range(nranks):
            if rank > 0:
                local[rank][0, 1:-1] = T0[rank * nx - 1, :]
            if rank < nranks - 1:
                local[rank][-1, 1:-1] = T0[(rank + 1) * nx, :]
    for _ in range(nsteps):
        old = [G.copy() for G in local]               # Td_old = Td   :208
        for rank in range(nranks):
            G, Gold = local[rank], old[rank]
            for j in range(1, nx + 1):                # all owned cells :209-215
                for k in range(1, n + 1):
                    G[j, k] = Gold[j, k] + r * (
                        Gold[j + 1, k] + Gold[j, k + 1]
                        + Gold[j - 1, k] + Gold[j, k - 1] - 4 * Gold[j, k])
        # call swap()  :218 — collect sends first (lockstep sendrecv), owned
        # columns only (j=1..ny, :154-158); proc_null edges skipped :174-191
        sends = [(G[1, 1:-1].copy(), G[-2, 1:-1].copy()) for G in local]
        for rank in range(nranks):
            if rank > 0:
                local[rank][0, 1:-1] = sends[rank - 1][1]
            if rank < nranks - 1:
                local[rank][-1, 1:-1] = sends[rank + 1][0]
    return np.concatenate([G[1:-1, 1:-1] for G in local], axis=0)


def test_parity_order_matches_literal_transcription_ic_start():
    """IC start: parity path == the literal multi-rank loop, bitwise."""
    cfg = BASE.with_(mesh_shape=(4, 1))
    T0 = initial_condition(cfg)
    expect = literal_mpi_update_then_swap(
        T0, cfg.r, cfg.ntime, cfg.bc_value, 4, seed_from_ic=True)
    got = solve(cfg)
    np.testing.assert_array_equal(got.T, expect)


def test_parity_order_matches_literal_transcription_explicit_t0():
    """Explicit-T0 start (raw restart, ghosts unseeded): the literal
    stale-first-step behavior, bitwise."""
    cfg = BASE.with_(mesh_shape=(4, 1))
    rng = np.random.default_rng(7)
    T0 = rng.uniform(1.0, 2.0, size=(cfg.n, cfg.n))
    expect = literal_mpi_update_then_swap(
        T0, cfg.r, cfg.ntime, cfg.bc_value, 4, seed_from_ic=False)
    got = solve(cfg, T0=T0)
    np.testing.assert_array_equal(got.T, expect)


def test_parity_order_ic_start_bitmatches_default_order():
    """With shipped ICs the orders are indistinguishable (the equivalence
    the sharded docstring claims): bit-identical owned cells."""
    cfg = BASE.with_(mesh_shape=(2, 4))
    par = solve(cfg)
    default = solve(cfg.with_(parity_order=False))
    np.testing.assert_array_equal(par.T, default.T)


def test_parity_order_explicit_t0_diverges_from_default_order():
    """Explicit T0: update-then-swap reads stale ghosts on step 1 — the
    orders genuinely differ, so the flag is observable, not decorative."""
    cfg = BASE.with_(mesh_shape=(4, 1), ntime=3)
    rng = np.random.default_rng(11)
    T0 = rng.uniform(1.0, 2.0, size=(cfg.n, cfg.n))
    par = solve(cfg, T0=T0)
    default = solve(cfg.with_(parity_order=False), T0=T0)
    assert not np.array_equal(par.T, default.T)
    # ...and the divergence is exactly at shard-boundary-adjacent cells:
    # interior rows far from the rank edges agree after 1 step's reach
    diff = np.abs(par.T - default.T)
    assert diff[: cfg.n // 4 - 3].max() == 0.0


def test_parity_order_2d_mesh_matches_serial_for_ic():
    """parity_order generalizes the reference's 1-D split to the 2-D mesh;
    IC-start equivalence means it still matches the serial oracle."""
    cfg = BASE.with_(mesh_shape=(2, 2), ntime=9)
    got = solve(cfg)
    ref = solve(cfg.with_(backend="serial", mesh_shape=None,
                          parity_order=False))
    np.testing.assert_array_equal(got.T, ref.T)


def test_parity_order_rejects_checkpointing():
    cfg = BASE.with_(mesh_shape=(2, 2), checkpoint_every=2)
    with pytest.raises(ValueError, match="parity_order"):
        solve(cfg)
