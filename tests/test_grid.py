"""Coordinates, IC presets, boundary masks (fortran/serial/heat.f90:28-48)."""

import numpy as np

from heat_tpu.config import HeatConfig
from heat_tpu.grid import boundary_mask, coords, coords_1d, initial_condition


def test_coords_endpoints():
    ax = coords_1d(101, 2.0)
    assert ax[0] == 0.0 and ax[-1] == 2.0
    assert np.allclose(np.diff(ax), 2.0 / 100)


def test_hat_ic_serial():
    # fortran/serial/heat.f90:40-48: T=2 on [0.5,1.5]^2 else 1
    cfg = HeatConfig(n=41, dom_len=2.0, ic="hat", dtype="float64")
    T = initial_condition(cfg)
    ax = coords_1d(41, 2.0)
    hot = (ax >= 0.5) & (ax <= 1.5)
    expect = np.where(hot[:, None] & hot[None, :], 2.0, 1.0)
    assert np.array_equal(T, expect)


def test_hat_half_ic():
    # fortran/cuda_kernel/heat.F90:98: x in [0.5,1.5], y in [0.5,1.0]
    cfg = HeatConfig(n=41, dom_len=2.0, ic="hat_half", dtype="float64")
    T = initial_condition(cfg)
    ax = coords_1d(41, 2.0)
    hx = (ax >= 0.5) & (ax <= 1.5)
    hy = (ax >= 0.5) & (ax <= 1.0)
    expect = np.where(hx[:, None] & hy[None, :], 2.0, 1.0)
    assert np.array_equal(T, expect)


def test_hat_small_ic():
    # python/serial/heat.py:25: [0.5,1.0]^2
    cfg = HeatConfig(n=31, dom_len=2.0, ic="hat_small", dtype="float64")
    T = initial_condition(cfg)
    ax = coords_1d(31, 2.0)
    h = (ax >= 0.5) & (ax <= 1.0)
    expect = np.where(h[:, None] & h[None, :], 2.0, 1.0)
    assert np.array_equal(T, expect)


def test_uniform_ic():
    cfg = HeatConfig(n=16, ic="uniform")
    assert np.all(initial_condition(cfg) == 2.0)


def test_ic_3d():
    cfg = HeatConfig(n=17, ndim=3, ic="hat", dtype="float64")
    T = initial_condition(cfg)
    assert T.shape == (17, 17, 17)
    assert set(np.unique(T)) == {1.0, 2.0}


def test_boundary_mask():
    cfg = HeatConfig(n=10)
    m = boundary_mask(cfg)
    assert m.sum() == 10 * 10 - 8 * 8
    assert m[0].all() and m[-1].all() and m[:, 0].all() and m[:, -1].all()
    assert not m[1:-1, 1:-1].any()


def test_coords_ndim():
    cfg = HeatConfig(n=8, ndim=3)
    axes = coords(cfg)
    assert len(axes) == 3 and all(len(a) == 8 for a in axes)


def test_device_ic_bitwise_matches_host():
    # the device-side builder must agree bitwise with the host construction
    # for every preset and dtype (it derives the hat box from the identical
    # host-side coordinate comparison)
    from heat_tpu.grid import initial_condition_device

    for ic in ("hat", "hat_half", "hat_small", "uniform", "zero"):
        for dtype in ("float64", "float32", "bfloat16"):
            for ndim in (2, 3):
                cfg = HeatConfig(n=33 if ndim == 3 else 101, dom_len=2.0,
                                 ic=ic, dtype=dtype, ndim=ndim)
                host = initial_condition(cfg)
                dev = np.asarray(initial_condition_device(cfg))
                exp = host.astype(dev.dtype)
                assert (dev == exp).all(), (ic, dtype, ndim)


def test_device_ic_sharded_matches_host():
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from heat_tpu.grid import initial_condition_device
    from heat_tpu.parallel.mesh import build_mesh

    mesh = build_mesh(2, (4, 2))
    cfg = HeatConfig(n=64, ic="hat", dtype="float32")
    dev = initial_condition_device(
        cfg, sharding=NamedSharding(mesh, P(*mesh.axis_names)))
    assert len(dev.sharding.device_set) == 8
    host = initial_condition(cfg).astype(np.float32)
    assert (np.asarray(dev) == host).all()
