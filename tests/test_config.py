"""input.dat parsing and config derivations (fortran/serial/heat.f90:11-17)."""

import pytest

from heat_tpu.config import HeatConfig, parse_input, variant_config, write_input, VARIANTS


def test_parse_5_field(tmp_path):
    p = tmp_path / "input.dat"
    p.write_text("1024 0.25 0.05 2.0 30\n")  # fortran/serial/input.dat values
    cfg = parse_input(p)
    assert (cfg.n, cfg.sigma, cfg.nu, cfg.dom_len, cfg.ntime) == (1024, 0.25, 0.05, 2.0, 30)
    assert cfg.soln is False


def test_parse_6_field(tmp_path):
    p = tmp_path / "input.dat"
    p.write_text("100 0.25 0.05 2.0 10 1\n")  # fortran/mpi+cuda/input.dat values
    cfg = parse_input(p)
    assert cfg.ntime == 10 and cfg.soln is True


def test_parse_flagship(tmp_path):
    p = tmp_path / "input.dat"
    p.write_text("32768 0.25 0.05 1.0 25000 0\n")  # fortran/input_all.dat
    cfg = parse_input(p)
    assert cfg.n == 32768 and cfg.ntime == 25000 and not cfg.soln


def test_parse_multiline_and_extra_tokens(tmp_path):
    # Fortran list-directed reads span lines and ignore trailing junk.
    p = tmp_path / "input.dat"
    p.write_text("64 0.25\n0.05 2.0\n5 1 999\n")
    cfg = parse_input(p)
    assert cfg.n == 64 and cfg.soln is True


def test_parse_too_few_fields(tmp_path):
    p = tmp_path / "input.dat"
    p.write_text("64 0.25 0.05\n")
    with pytest.raises(ValueError):
        parse_input(p)


def test_write_roundtrip(tmp_path):
    cfg = HeatConfig(n=128, sigma=0.2, nu=0.1, dom_len=1.0, ntime=7, soln=True)
    p = tmp_path / "input.dat"
    write_input(cfg, p)
    back = parse_input(p)
    assert back.n == cfg.n and back.ntime == cfg.ntime and back.soln


def test_write_roundtrip_full_precision(tmp_path):
    """A write/parse cycle must not perturb the physics (dt, fingerprints)."""
    cfg = HeatConfig(n=64, sigma=0.123456789012345, nu=0.0987654321098765,
                     dom_len=1.9999999999999998, ntime=3)
    p = tmp_path / "input.dat"
    write_input(cfg, p)
    back = parse_input(p)
    assert back.sigma == cfg.sigma and back.nu == cfg.nu
    assert back.dom_len == cfg.dom_len and back.dt == cfg.dt


def test_r_equals_sigma():
    # SURVEY.md quirk #4: r = nu*dt/delta^2 with dt = sigma*delta^2/nu
    # collapses to sigma; the derivation chain is kept for parity.
    cfg = HeatConfig(n=100, sigma=0.21, nu=0.31, dom_len=1.7, ntime=1)
    assert abs(cfg.r - cfg.sigma) < 1e-15
    assert abs(cfg.delta - 1.7 / 99) < 1e-15
    assert abs(cfg.dt - 0.21 * cfg.delta**2 / 0.31) < 1e-18


def test_validation():
    with pytest.raises(ValueError):
        HeatConfig(n=2)
    with pytest.raises(ValueError):
        HeatConfig(dtype="float16")
    with pytest.raises(ValueError):
        HeatConfig(backend="mpi")
    with pytest.raises(ValueError):
        HeatConfig(bc="reflecting")
    with pytest.raises(ValueError):
        HeatConfig(ndim=4)
    # sigma sanity applies in every dimension, not just 2D
    with pytest.raises(ValueError):
        HeatConfig(ndim=3, sigma=-1.0)
    with pytest.raises(ValueError):
        HeatConfig(ndim=3, sigma=1e9)


def test_variants_cover_reference_taxonomy():
    # one preset per reference variant (SURVEY.md §0 table)
    for name in ["serial", "cuda_kernel", "cuda_managed", "cuda_cuf",
                 "mpi_cuda", "mpi_cuda_na", "hip", "python_serial", "python_cuda"]:
        assert name in VARIANTS
    cfg = variant_config("mpi_cuda")
    assert cfg.backend == "sharded" and cfg.bc == "ghost" and cfg.comm == "direct"
    assert variant_config("hip").comm == "staged"
    assert variant_config("cuda_kernel").ic == "hat_half"


def test_reference_parity_fixtures():
    """configs/ mirrors every input.dat the reference ships (SURVEY.md §2:
    fortran/*/input.dat, fortran/input_all.dat); each must parse and derive
    the same physics the reference programs would."""
    import pathlib

    fixtures = pathlib.Path(__file__).parent.parent / "configs"
    expect = {
        "serial.dat": (1024, 0.25, 0.05, 2.0, 30, False),
        "cuda_kernel.dat": (100, 0.25, 0.05, 2.0, 1000, False),
        "cuda_cuf.dat": (100, 0.25, 0.05, 2.0, 1000, False),
        "mpi_cuda.dat": (100, 0.25, 0.05, 2.0, 10, True),
        "hip.dat": (32768, 0.25, 0.05, 1.0, 25000, False),
        "input_all.dat": (32768, 0.25, 0.05, 1.0, 25000, False),
    }
    for name, (n, sigma, nu, L, ntime, soln) in expect.items():
        cfg = parse_input(fixtures / name)
        assert (cfg.n, cfg.sigma, cfg.nu, cfg.dom_len, cfg.ntime, cfg.soln) == \
            (n, sigma, nu, L, ntime, soln), name
        # r == sigma identity holds through the dt derivation chain
        assert abs(cfg.r - cfg.sigma) < 1e-12


def test_cuda_kernel_preset_kernel_contract(monkeypatch):
    """Which kernel actually runs under the cuda_kernel preset is a
    contract, not an accident: the f64 parity dtype takes the XLA fallback
    (no f64 on the TPU VPU — pallas_stencil.pallas_available), and the same
    preset at f32 (--dtype float32) runs the hand-written Pallas kernel."""
    from heat_tpu.backends import solve
    from heat_tpu.ops import pallas_stencil

    calls = []
    real = pallas_stencil._multistep

    def counting(T, r, ksteps, bounds=None):
        calls.append(ksteps)
        return real(T, r, ksteps, bounds=bounds)

    monkeypatch.setattr(pallas_stencil, "_multistep", counting)

    cfg = variant_config("cuda_kernel").with_(n=16, ntime=2)
    assert cfg.dtype == "float64" and cfg.backend == "pallas"
    solve(cfg)
    assert calls == [], "f64 parity preset must take the XLA fallback"

    solve(cfg.with_(dtype="float32"))
    assert calls, "f32 must run the hand-written Pallas kernel"


def test_parse_dispatch_depth_grammar():
    """--dispatch-depth: on -> 2, off -> 0 (sync fallback), N >= 1 -> N;
    everything else is a loud per-invocation error, never a silent
    default (a typo'd depth must not quietly change the serve pipeline)."""
    from heat_tpu.config import parse_dispatch_depth

    assert parse_dispatch_depth("on") == 2
    assert parse_dispatch_depth("OFF") == 0
    assert parse_dispatch_depth("1") == 1
    assert parse_dispatch_depth(" 4 ") == 4
    assert parse_dispatch_depth(8) == 8
    with pytest.raises(ValueError, match="dispatch-depth"):
        parse_dispatch_depth("auto")
    with pytest.raises(ValueError, match=">= 1"):
        parse_dispatch_depth("0")      # spelled 'off', not 0, on the CLI
    with pytest.raises(ValueError, match=">= 1"):
        parse_dispatch_depth("-2")
