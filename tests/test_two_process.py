"""A REAL two-process distributed run on CPU: live jax.distributed world,
cross-process ppermute halo exchange, per-process shard dumps.

The faked-seam tests (test_multihost.py) cover every multi-host branch; this
one proves the branches compose over an actual multi-process world — the
closest single-machine analog of the reference's ``mpirun -np 2`` launch
(fortran/mpi+cuda/makefile:1-2): two OS processes, a coordination service,
collectives over sockets, each process writing only its own shards.
"""

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from heat_tpu.backends import solve
from heat_tpu.config import HeatConfig
from heat_tpu.io import read_dat

_WORKER = r"""
import os, sys, json
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
from heat_tpu.cli import main
rc = main(["run", "--backend", "sharded", "--dtype", "float64",
           "--mesh", "2x2", "--report-sum", "--json"])
sys.exit(rc)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _launch_pair(tmp_cwd):
    env_base = {
        **os.environ,
        "PYTHONPATH": str(Path(__file__).resolve().parent.parent)
        + os.pathsep + os.environ.get("PYTHONPATH", ""),
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{_free_port()}",
        "JAX_NUM_PROCESSES": "2",
    }
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER],
            cwd=tmp_cwd, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env={**env_base, "JAX_PROCESS_ID": str(i)},
        )
        for i in range(2)
    ]
    outs = []
    hung = False
    for p in procs:
        try:
            out, err = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            hung = True
            p.kill()
            out, err = p.communicate()  # reap + salvage diagnostics
        outs.append((p.returncode, out, err))
    return hung, outs


def test_two_process_sharded_run(tmp_cwd):
    n, steps = 32, 6
    (tmp_cwd / "input.dat").write_text(f"{n} 0.25 0.05 2.0 {steps} 1\n")
    # _free_port is probe-then-release (racy under parallel CI): one retry
    # with a fresh port before declaring failure
    for attempt in range(2):
        hung, outs = _launch_pair(tmp_cwd)
        if not hung and all(rc == 0 for rc, _, _ in outs):
            break
        if attempt == 1:
            detail = "\n---\n".join(
                f"worker rc={rc}\nstdout:\n{out}\nstderr:\n{err[-2000:]}"
                for rc, out, err in outs)
            pytest.fail(("two-process run hung\n" if hung else
                         "two-process run failed\n") + detail)

    # every process wrote only its own shards; together: the full mesh
    shard_files = sorted(tmp_cwd.glob("soln0*.dat"))
    assert len(shard_files) == 4, shard_files

    # reassemble the 2x2 shard files into the global field
    ref = solve(HeatConfig(n=n, ntime=steps, dtype="float64",
                           backend="serial"))
    half = n // 2
    for idx, f in enumerate(shard_files):
        ci, cj = idx // 2, idx % 2
        _, blk = read_dat(f)
        np.testing.assert_allclose(
            blk, ref.T[ci * half:(ci + 1) * half, cj * half:(cj + 1) * half],
            rtol=0, atol=1e-12)

    # stdout contract: only process 0 speaks, and the json line parses
    out0 = outs[0][1]
    out1 = outs[1][1]
    assert "simulation completed!!!!" in out0
    assert "simulation completed!!!!" not in out1  # master-gated
    jline = [l for l in out0.splitlines() if l.startswith("{")][-1]
    rec = json.loads(jline)
    assert rec["backend"] == "sharded" and rec["gsum"] is not None


def test_cli_launch_subcommand(tmp_cwd):
    """`heat-tpu launch -n 2 run ...` — the mpirun-analog single-node
    launcher: spawns a real 2-process world through the CLI itself."""
    from heat_tpu.cli import main

    n, steps = 16, 3
    (tmp_cwd / "input.dat").write_text(f"{n} 0.25 0.05 2.0 {steps} 1\n")
    rc = main(["launch", "-n", "2", "--devices-per-process", "2",
               "run", "--backend", "sharded", "--dtype", "float64",
               "--mesh", "2x2"])
    assert rc == 0
    shard_files = sorted(tmp_cwd.glob("soln0*.dat"))
    assert len(shard_files) == 4
    ref = solve(HeatConfig(n=n, ntime=steps, dtype="float64",
                           backend="serial"))
    half = n // 2
    for idx, f in enumerate(shard_files):
        ci, cj = idx // 2, idx % 2
        _, blk = read_dat(f)
        np.testing.assert_allclose(
            blk, ref.T[ci * half:(ci + 1) * half,
                       cj * half:(cj + 1) * half], rtol=0, atol=1e-12)


def test_cli_launch_requires_worker_args(capsys):
    from heat_tpu.cli import main

    assert main(["launch", "-n", "2"]) == 2


def test_cli_launch_propagates_worker_failure(tmp_cwd):
    """Failure detection in the mpirun-analog launcher: when every worker
    exits nonzero fast (startup-class config error), the launcher must
    return the failure code promptly instead of hanging in collective
    rendezvous — the dead-peer cleanup of cmd_launch.run_world."""
    from heat_tpu.cli import main

    (tmp_cwd / "input.dat").write_text("16 0.25 0.05 2.0 3 0\n")
    # mesh rank 3 on a 2-D config: every rank rejects it at validation
    rc = main(["launch", "-n", "2", "run", "--backend", "sharded",
               "--mesh", "2x2x2"])
    assert rc != 0
