"""The two-axis-tiled Pallas kernels (3x3 halo-block scheme) and their
plans: correctness vs the XLA stencil in interpret mode, and plan behavior
(wide arrays switch to col-tiling; narrow arrays keep the thin band)."""

import jax.numpy as jnp
import numpy as np
import pytest

from heat_tpu.ops import pallas_stencil as ps
from heat_tpu.ops.stencil import ftcs_step_edges


def _ref(T, r, ksteps):
    T = jnp.asarray(T)
    for _ in range(ksteps):
        T = ftcs_step_edges(T, r)
    return np.asarray(T)


def _pad_to(T, mults):
    pads = [(0, ps._round_up(s, m) - s) for s, m in zip(T.shape, mults)]
    return jnp.pad(jnp.asarray(T), pads)


@pytest.mark.parametrize("ksteps", [1, 3, 8])
def test_2d_coltiled_matches_xla(ksteps):
    rng = np.random.default_rng(3)
    m, n = 100, 500
    T = rng.uniform(1, 2, (m, n)).astype(np.float32)
    R, C, kr, kc = 16, 256, 8, 128
    Tp = _pad_to(T, (R, C))
    out = ps._pallas_2d_coltiled(Tp, r=0.2, ksteps=ksteps, R=R, C=C, kr=kr,
                                 kc=kc, logical_shape=(m, n))[:m, :n]
    np.testing.assert_allclose(np.asarray(out), _ref(T, 0.2, ksteps),
                               rtol=0, atol=2e-6)


def test_2d_coltiled_bf16():
    rng = np.random.default_rng(4)
    m, n = 64, 300
    T = rng.uniform(1, 2, (m, n)).astype(jnp.bfloat16)
    R, C, kr, kc = 16, 128, 16, 128
    Tp = _pad_to(T, (R, C))
    out = ps._pallas_2d_coltiled(Tp, r=0.25, ksteps=5, R=R, C=C, kr=kr,
                                 kc=kc, logical_shape=(m, n))[:m, :n]
    ref = _ref(jnp.asarray(T), 0.25, 5)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), rtol=0, atol=3e-2)


@pytest.mark.parametrize("ksteps", [1, 4])
def test_3d_tiled_matches_xla(ksteps):
    rng = np.random.default_rng(5)
    shape = (40, 24, 260)
    T = rng.uniform(1, 2, shape).astype(np.float32)
    out = np.asarray(ps._multistep(jnp.asarray(T), 0.15, ksteps))
    np.testing.assert_allclose(out, _ref(T, 0.15, ksteps), rtol=0, atol=2e-6)


def test_3d_tiled_bounded_contract():
    """Bounded variant with a discard margin: interior matches the
    unbounded global run (the sharded backend's invariant)."""
    rng = np.random.default_rng(6)
    n = 32
    w = 3
    T = rng.uniform(1, 2, (n, n, n)).astype(np.float32)
    # global run, ghost-style: all cells update against a frozen pad ring
    Tpad = np.pad(T, w, constant_values=1.0)
    bounds = jnp.asarray([w - 1, n + w, w - 1, n + w, w - 1, n + w],
                         jnp.int32)
    out = ps.ftcs_multistep_bounded_pallas(jnp.asarray(Tpad), 0.15, w,
                                           bounds)
    # serial oracle: w ghost-BC steps
    from heat_tpu.backends.serial_np import step_ghost_np

    ref = T.copy()
    for _ in range(w):
        ref = step_ghost_np(ref, np.float32(0.15), np.float32(1.0))
    got = np.asarray(out)[w:-w, w:-w, w:-w]
    np.testing.assert_allclose(got, ref, rtol=0, atol=2e-6)


def test_plan_2d_wide_switches_to_coltiled():
    kind, *rest = ps._plan_2d((32768, 32768), "bfloat16", 16)
    assert kind == "coltiled"
    R, C, kr, kc, k = rest
    assert C < 32768 and C % kc == 0 and R % kr == 0 and k <= min(kr, kc)
    # f32 at the same width should also prefer col tiles
    assert ps._plan_2d((32768, 32768), "float32", 16)[0] == "coltiled"


def test_plan_2d_narrow_keeps_thin_band():
    assert ps._plan_2d((4096, 4096), "float32", 16)[0] == "thin"
    assert ps._plan_2d((1024, 1024), "float32", 16)[0] == "thin"


def test_plan_3d_geometry_valid():
    (m_pad, mid_pad, n_pad), R, M, k = ps._plan_3d((512, 512, 512),
                                                   "float32", 8)
    assert m_pad % R == 0 and mid_pad % M == 0 and n_pad % 128 == 0
    assert R % k == 0 and M % ps._round_up(k, 8) == 0
    # the band must be comfortably smaller than the old whole-plane scheme's
    # worst case: halo fraction under 2x
    band = (R + 2 * k) * (M + 2 * ps._round_up(k, 8))
    assert band / (R * M) < 2.0


def test_plan_3d_huge_lane_extent_falls_back_to_xla():
    """A lane extent too wide for any VMEM band: no plan, and
    pallas_available reports False so callers take the XLA step."""
    from heat_tpu.ops.pallas_stencil import pallas_available

    assert ps._plan_3d((256, 256, 32768), "float32", 8) is None
    assert not pallas_available((256, 256, 32768), jnp.float32)
    assert pallas_available((512, 512, 512), jnp.float32)


def test_plan_3d_small_shapes():
    (m_pad, mid_pad, n_pad), R, M, k = ps._plan_3d((16, 16, 16),
                                                   "float32", 2)
    assert m_pad % R == 0 and mid_pad % M == 0
    assert k <= 2


def test_plan_pins_match_measured_optima():
    """The plans these constants produce were measured on-chip (round 2);
    pin them so cost-model tweaks that would silently degrade a measured
    optimum fail here and force a re-measure:
    - bf16 32768^2 col-tiled 512x4096 fuse 16 -> 1.89e11 pts/s (92% of
      the one-pass roofline; 256 rows measured 82%, 1024 rows compiles
      >12 min)
    - 512^3 (64,64,k=8) -> 1.19e11 (117%; the max()-model pick (48,96,2)
      measured 68%)
    - 4096^2 stays thin-band (155-158% measured)
    """
    assert ps._plan_2d((32768, 32768), "bfloat16", 16) == (
        "coltiled", 512, 4096, 16, 128, 16)
    assert ps._plan_3d((512, 512, 512), "float32", 8) == (
        (512, 512, 512), 64, 64, 8)
    assert ps._plan_2d((4096, 4096), "float32", 16) == ("thin", 16)


def test_thin_deep_unroll_compile_cap():
    """Round-4 measured (AOT-topology bisect, Mosaic pinned): the 32-step
    unrolled thin kernel wedges Mosaic >36 min on ~10 MiB bands
    (8320-wide), while the 4224-wide headline shape compiles k=32 in
    ~1 min. Wide thin passes must chunk at 16; narrow ones keep 32."""
    assert ps._thin_chunk_cap(4224, "float32") == 32   # headline 4096^2
    assert ps._thin_chunk_cap(8320, "float32") == 16   # the wedge family
    assert ps._thin_chunk_cap(16512, "float32") == 16
    # the planner's thin choice reflects the cap (costs stay honest)
    plan = ps._plan_2d((8192, 8192), "float32", 32)
    assert plan[0] != "thin" or plan[1] <= 16


def test_effective_chunk_is_plan_aware():
    """effective_chunk_2d must report the chunk of the kernel _plan_2d
    SELECTS, not hardcode the thin cap: at the bf16-flagship ghosted
    shape the planner picks the coltiled body and the exchange depth
    must follow ITS kchunk (review r5)."""
    shape = (32832, 32832)  # 32768 + 2*32 ghosts
    plan = ps._plan_2d(shape, "bfloat16", 32)
    assert plan[0] == "coltiled"
    assert ps.effective_chunk_2d(shape, "bfloat16") == plan[-1] == 16
    # thin selections return the thin chunk (narrow: uncapped)
    assert ps.effective_chunk_2d((4160, 4160), "float32") == 32
    # anisotropic wide-band: 128-row shard of 16384^2 (consumed by the
    # fuse-depth chunk cap; the kernel still chunks at 16 at this width)
    assert ps.effective_chunk_2d((192, 16448), "float32") == 16
