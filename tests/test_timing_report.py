"""Direct coverage for runtime/timing report fields and the CLI --json
surface of the async-I/O accounting (``overlap_s``/``io_wait_s``) — added
in the async-pipeline PR but until now only exercised incidentally through
full CLI runs."""

import json

import numpy as np
import pytest

from heat_tpu.cli import main
from heat_tpu.runtime.timing import Timing


def test_report_lines_keep_reference_contract_lines():
    t = Timing(total_s=2.0, solve_s=1.0, steps=10, points=100)
    lines = t.report_lines()
    assert lines[0] == "simulation completed!!!!"     # serial/heat.f90:73
    assert any(l.startswith("total time:") for l in lines)
    assert any(l.startswith("Average time per timestep:") for l in lines)


def test_report_lines_async_overlap_only_when_pipeline_ran():
    quiet = Timing(total_s=1.0, solve_s=0.5, steps=4, points=16)
    assert not any("async I/O overlap" in l for l in quiet.report_lines())

    ran = Timing(total_s=1.0, solve_s=0.5, steps=4, points=16,
                 overlap_s=0.25, io_wait_s=0.125)
    (line,) = [l for l in ran.report_lines() if "async I/O overlap" in l]
    assert "0.250000 hidden" in line and "0.125000 blocked" in line


def test_report_lines_overlap_with_none_io_wait_renders_zero():
    # overlap_s set but io_wait_s None (a writer that never blocked the
    # driver): the line must not crash on the None format
    t = Timing(total_s=1.0, solve_s=0.5, steps=1, points=1,
               overlap_s=0.1, io_wait_s=None)
    (line,) = [l for l in t.report_lines() if "async I/O overlap" in l]
    assert "0.000000 blocked" in line


def test_report_lines_serve_dispatch_only_when_serving():
    solo = Timing(total_s=1.0, solve_s=0.5, steps=4, points=16)
    assert not any("serve dispatch" in l for l in solo.report_lines())

    served = Timing(total_s=1.0, solve_s=1.0, dispatch_depth=2,
                    boundary_wait_s=0.125)
    (line,) = [l for l in served.report_lines() if "serve dispatch" in l]
    assert "depth 2" in line and "boundary wait 0.125000" in line

    # the sync fallback (depth 0) still reports — 0 is a real depth, and
    # a None boundary wait must render as zero, not crash the format
    sync = Timing(total_s=1.0, solve_s=1.0, dispatch_depth=0,
                  boundary_wait_s=None)
    (line,) = [l for l in sync.report_lines() if "serve dispatch" in l]
    assert "depth 0" in line and "boundary wait 0.000000" in line


def test_report_lines_serve_policy_suffix():
    """The admission policy rides the dispatch line when set (two serve
    runs only compare when their ordering matched) and is absent on
    pre-policy Timing values so old consumers see identical lines."""
    with_policy = Timing(total_s=1.0, solve_s=1.0, dispatch_depth=2,
                         boundary_wait_s=0.0, serve_policy="edf")
    (line,) = [l for l in with_policy.report_lines()
               if "serve dispatch" in l]
    assert line.endswith(", policy edf")

    without = Timing(total_s=1.0, solve_s=1.0, dispatch_depth=2,
                     boundary_wait_s=0.0)
    (line,) = [l for l in without.report_lines() if "serve dispatch" in l]
    assert "policy" not in line


def test_report_lines_serve_faults_only_when_fault_domains_ran():
    solo = Timing(total_s=1.0, solve_s=0.5, steps=4, points=16)
    assert not any("serve faults" in l for l in solo.report_lines())

    served = Timing(total_s=1.0, solve_s=1.0, dispatch_depth=2,
                    lanes_quarantined=2, rollbacks=1, deadline_misses=3,
                    shed=4)
    (line,) = [l for l in served.report_lines() if "serve faults" in l]
    assert ("2 quarantined" in line and "1 rollback(s)" in line
            and "3 deadline miss(es)" in line and "4 shed" in line)

    # a clean serve run still reports the zero counters (0 is data; the
    # None defaults are what suppress the line), and None partners render
    # as zero rather than crash the format
    clean = Timing(total_s=1.0, solve_s=1.0, dispatch_depth=2,
                   lanes_quarantined=0, rollbacks=None)
    (line,) = [l for l in clean.report_lines() if "serve faults" in l]
    assert "0 quarantined" in line and "0 rollback(s)" in line


def test_report_lines_numerics_only_when_observatory_ran():
    """The numerics line rides the report only when the observatory
    ingested boundaries (None suppresses; 0 is data — a clean run with
    the observatory on still reports its zeros)."""
    solo = Timing(total_s=1.0, solve_s=0.5, steps=4, points=16)
    assert not any(l.startswith("numerics:") for l in solo.report_lines())

    served = Timing(total_s=1.0, solve_s=1.0, dispatch_depth=2,
                    steady_lanes=3, numerics_violations=1)
    (line,) = [l for l in served.report_lines()
               if l.startswith("numerics:")]
    assert "3 steady lane(s)" in line and "1 violation(s)" in line

    clean = Timing(total_s=1.0, solve_s=1.0, dispatch_depth=2,
                   steady_lanes=0, numerics_violations=None)
    (line,) = [l for l in clean.report_lines()
               if l.startswith("numerics:")]
    assert "0 steady lane(s)" in line and "0 violation(s)" in line


def test_compile_line_present_only_when_compiled():
    with_c = Timing(total_s=1.0, compile_s=0.3, solve_s=0.5, steps=1, points=1)
    without = Timing(total_s=1.0, compile_s=0.0, solve_s=0.5, steps=1, points=1)
    assert any(l.startswith("compile time:") for l in with_c.report_lines())
    assert not any(l.startswith("compile time:") for l in without.report_lines())


def test_rate_properties_and_zero_guards():
    t = Timing(total_s=4.0, solve_s=2.0, steps=8, points=100)
    assert t.per_step_s == pytest.approx(0.25)
    assert t.points_per_s == pytest.approx(100 * 8 / 2.0)
    empty = Timing()
    assert empty.per_step_s == 0.0 and empty.points_per_s == 0.0


def _json_record(out: str) -> dict:
    (line,) = [l for l in out.splitlines() if l.startswith("{")]
    return json.loads(line)


@pytest.fixture
def input_dat(tmp_cwd):
    (tmp_cwd / "input.dat").write_text("16 0.25 0.05 2.0 8 0\n")
    return tmp_cwd


def test_cli_json_reports_overlap_fields_when_async_ran(input_dat, capsys):
    rc = main(["run", "--backend", "xla", "--dtype", "float64",
               "--checkpoint-every", "2", "--json"])
    assert rc == 0
    rec = _json_record(capsys.readouterr().out)
    # the async writer really ran: both fields present, finite, >= 0
    assert rec["overlap_s"] >= 0.0
    assert rec["io_wait_s"] >= 0.0
    assert np.isfinite(rec["overlap_s"]) and np.isfinite(rec["io_wait_s"])


def test_cli_json_omits_overlap_fields_in_sync_mode(input_dat, capsys):
    rc = main(["run", "--backend", "xla", "--dtype", "float64",
               "--checkpoint-every", "2", "--async-io", "off", "--json"])
    assert rc == 0
    rec = _json_record(capsys.readouterr().out)
    assert "overlap_s" not in rec and "io_wait_s" not in rec


def test_cli_json_omits_overlap_fields_without_checkpointing(input_dat,
                                                            capsys):
    rc = main(["run", "--backend", "xla", "--dtype", "float64", "--json"])
    assert rc == 0
    rec = _json_record(capsys.readouterr().out)
    assert "overlap_s" not in rec and "io_wait_s" not in rec
