"""Socket-free fleet unit tests: placement policy math over fake
``GET /v1/status`` payloads, backend-spec parsing, the fleet fault
kinds, and the pure usage merge (heat_tpu/fleet — ISSUE 18).

Everything here is a pure function of Backend snapshots + dicts; the
live router (sockets, steals, chaos) is tests/test_fleet.py.
"""

import json

import pytest

from heat_tpu.fleet import placement
from heat_tpu.fleet.registry import (Backend, BackendRegistry,
                                     load_backends_file, parse_backends)
from heat_tpu.fleet.router import merge_usage
from heat_tpu.runtime import faults


@pytest.fixture(autouse=True)
def _fresh_faults():
    faults.reset()
    yield
    faults.reset()


def status(queued_steps=0, running_steps=0, s_per_lane_step=None,
           fast_burn=0.0, slow_burn=0.0, max_bucket=32, mega=False):
    """A fake /v1/status payload with just the fields placement reads."""
    cost = ([{"bucket": "2d/n32/l2", "ewma_s_per_lane_step":
              s_per_lane_step, "chunks": 100}]
            if s_per_lane_step is not None else [])
    return {"backlog": {"queued_steps": queued_steps,
                        "running_steps_bound": running_steps},
            "cost_model": cost,
            "slo_burn": {"standard": {"fast_burn": fast_burn,
                                      "slow_burn": slow_burn}},
            "mega": {"capable": mega, "max_bucket": max_bucket}}


def backend(name, st=None, pending_steps=0, healthy=True):
    b = Backend(name, f"127.0.0.1:{8000 + abs(hash(name)) % 1000}")
    b.status = st
    b.pending_steps = pending_steps
    b.healthy = healthy
    return b


# --- backend spec parsing ----------------------------------------------------


def test_parse_backends_names_and_defaults():
    got = parse_backends("10.0.0.1:8080, east=10.0.0.2:9090 ,10.0.0.3:70")
    assert got == [("b0", "10.0.0.1:8080"), ("east", "10.0.0.2:9090"),
                   ("b2", "10.0.0.3:70")]


@pytest.mark.parametrize("spec", ["nohost", "host:", ":123", "h:12x",
                                  "a=1.2.3.4:80,a=4.3.2.1:80",
                                  "x=1.1.1.1:1,y=1.1.1.1:1"])
def test_parse_backends_rejects_bad_and_duplicate(spec):
    with pytest.raises(ValueError):
        parse_backends(spec)


def test_backends_file_grammar_and_live_join(tmp_path):
    f = tmp_path / "backends.txt"
    f.write_text("# fleet members\none=127.0.0.1:7001\n\n127.0.0.1:7002  "
                 "# unnamed -> positional\n")
    assert load_backends_file(f) == [("one", "127.0.0.1:7001"),
                                     ("b1", "127.0.0.1:7002")]
    reg = BackendRegistry(backends_file=f)
    assert [b.name for b in reg.snapshot()] == ["one", "b1"]
    # same mtime -> no re-read; touched file with a new line -> live join
    assert reg.refresh_file() == []
    f.write_text(f.read_text() + "late=127.0.0.1:7003\n")
    import os
    os.utime(f, (0, 2**31 - 1))   # force an mtime move
    assert reg.refresh_file() == ["late"]
    # removing every line never evicts live members
    f.write_text("")
    os.utime(f, (0, 2**31 - 2))
    assert reg.refresh_file() == []
    assert len(reg.snapshot()) == 3


# --- least-loaded math -------------------------------------------------------


def test_least_loaded_picks_smallest_predicted_backlog():
    # same cost model, different queue work: 1000 steps vs 100 steps
    a = backend("a", status(queued_steps=1000, s_per_lane_step=1e-3))
    b = backend("b", status(queued_steps=100, s_per_lane_step=1e-3))
    chosen, decision = placement.choose("least-loaded", [a, b], 16, 0)
    assert chosen is b
    assert decision["backlog_s"]["a"] == pytest.approx(1.0)
    assert decision["backlog_s"]["b"] == pytest.approx(0.1)


def test_least_loaded_weighs_cost_model_not_just_steps():
    # fewer steps on a 10x slower backend is MORE predicted seconds
    slow = backend("slow", status(queued_steps=200, s_per_lane_step=1e-2))
    fast = backend("fast", status(queued_steps=1000, s_per_lane_step=1e-4))
    chosen, _ = placement.choose("least-loaded", [slow, fast], 16, 0)
    assert chosen is fast


def test_router_pending_counts_toward_backlog():
    # equal payloads; the router just routed 500 steps to `a` that the
    # backend's own status cannot know about yet
    a = backend("a", status(s_per_lane_step=1e-3), pending_steps=500)
    b = backend("b", status(s_per_lane_step=1e-3))
    chosen, _ = placement.choose("least-loaded", [a, b], 16, 1)
    assert chosen is b
    assert placement.predicted_backlog_s(a) == pytest.approx(0.5)


def test_cold_fleet_tiebreak_is_starvation_free():
    # no status payloads at all: every backend ties at the prior; the
    # round-robin tiebreak must rotate through ALL of them
    fleet = [backend(n) for n in ("a", "b", "c")]
    seen = {placement.choose("least-loaded", fleet, 16, rr)[0].name
            for rr in range(6)}
    assert seen == {"a", "b", "c"}


# --- burn-aware demotion -----------------------------------------------------


def test_burn_demotion_needs_both_windows():
    only_fast = status(fast_burn=5.0, slow_burn=0.2)
    only_slow = status(fast_burn=0.2, slow_burn=5.0)
    both = status(fast_burn=2.0, slow_burn=1.5)
    assert not placement.burn_demoted(only_fast)
    assert not placement.burn_demoted(only_slow)
    assert placement.burn_demoted(both)
    assert not placement.burn_demoted(None)


def test_burning_backend_demoted_unless_everyone_burns():
    burning = backend("burning", status(fast_burn=3.0, slow_burn=2.0,
                                        s_per_lane_step=1e-4))
    healthy = backend("healthy", status(queued_steps=10_000,
                                        s_per_lane_step=1e-3))
    # burning backend is empty and fast — but demoted, so the loaded
    # healthy one still wins
    chosen, decision = placement.choose("least-loaded",
                                        [burning, healthy], 16, 0)
    assert chosen is healthy
    assert decision["demoted"] == ["burning"]
    # when EVERY candidate burns, demotion is moot — work must land
    all_burn = [backend("x", status(fast_burn=2, slow_burn=2)),
                backend("y", status(fast_burn=2, slow_burn=2))]
    chosen, _ = placement.choose("least-loaded", all_burn, 16, 0)
    assert chosen is not None


# --- mega-capability routing -------------------------------------------------


def test_oversized_requests_only_go_to_mega_backends():
    small = backend("small", status(max_bucket=32, mega=False))
    mega = backend("mega", status(queued_steps=100_000, max_bucket=32,
                                  mega=True, s_per_lane_step=1e-3))
    # n=48 overflows max_bucket=32: only the (loaded!) mega backend
    chosen, _ = placement.choose("least-loaded", [small, mega], 48, 0)
    assert chosen is mega
    # n=32 fits: the empty non-mega backend wins on backlog
    chosen, _ = placement.choose("least-loaded", [small, mega], 32, 0)
    assert chosen is small
    # nothing mega-capable -> unroutable, reason says so
    chosen, decision = placement.choose("least-loaded", [small], 48, 0)
    assert chosen is None
    assert decision["reason"] == "no-eligible-backend"
    # a backend with NO status yet is assumed capable (cold fleet; the
    # engine itself rejects what it structurally cannot serve)
    cold = backend("cold")
    assert placement.choose("least-loaded", [cold], 48, 0)[0] is cold


def test_unhealthy_fault_down_lost_are_ineligible():
    down = backend("down", healthy=False)
    faulted = backend("faulted")
    faulted.fault_down = True
    lost = backend("lost")
    lost.lost = True
    ok = backend("ok")
    chosen, _ = placement.choose("least-loaded",
                                 [down, faulted, lost, ok], 16, 0)
    assert chosen is ok
    assert placement.choose("round-robin", [down, faulted, lost], 16,
                            0)[0] is None


# --- round-robin + policy plumbing ------------------------------------------


def test_round_robin_rotates_in_registration_order():
    fleet = [backend(n) for n in ("a", "b", "c")]
    picks = [placement.choose("round-robin", fleet, 16, rr)[0].name
             for rr in range(1, 7)]
    assert picks == ["b", "c", "a", "b", "c", "a"]


def test_unknown_policy_raises():
    with pytest.raises(ValueError, match="unknown placement policy"):
        placement.choose("best-effort", [backend("a")], 16, 0)


# --- fleet fault kinds (runtime/faults.py satellite) -------------------------


def test_backend_down_spec_parses_and_fires_once():
    plan = faults.plan_for_spec("backend-down@3:backend=b1")
    assert plan is not None
    assert plan.backend_down_target(1) is None
    assert plan.backend_down_target(2) is None
    assert plan.backend_down_target(3) == "b1"
    # fire-once: the Nth forward drops the target, later forwards don't
    assert plan.backend_down_target(4) is None


def test_backend_down_without_name_targets_the_routed_backend():
    plan = faults.plan_for_spec("backend-down@1")
    assert plan.backend_down_target(1) == ""   # "" = whichever was chosen


def test_backend_down_requires_step():
    with pytest.raises(ValueError, match="@N"):
        faults.parse_spec("backend-down")


def test_backend_slow_sleeps_per_forward(monkeypatch):
    plan = faults.plan_for_spec("backend-slow:ms=25")
    slept = []
    monkeypatch.setattr(faults.time, "sleep", slept.append)
    plan.backend_slow()
    plan.backend_slow()
    assert slept == [0.025, 0.025]


def test_empty_spec_stays_none_on_hot_path():
    assert faults.plan_for_spec("") is None
    assert faults.plan_for_spec(None) is None


# --- usage merge -------------------------------------------------------------


def test_merge_usage_reconciles_exactly():
    def ledger(lane_s, steps, requests):
        c = {"lane_s": lane_s, "steps": steps, "chunks": steps // 8,
             "bytes_written": steps * 10, "steps_saved": 0,
             "requests": requests}
        return {"tenants": {"acme": {"classes": {"standard": dict(c)}}},
                "totals": dict(c)}

    merged = merge_usage({"a": ledger(1.5, 800, 4),
                          "b": ledger(0.5, 200, 2)})
    assert merged["totals"]["lane_s"] == pytest.approx(2.0)
    assert merged["totals"]["steps"] == 1000
    assert merged["totals"]["requests"] == 6
    cls = merged["tenants"]["acme"]["classes"]["standard"]
    assert cls["steps"] == 1000 and cls["requests"] == 6
    # the raw per-backend ledgers ride along, so the reconciliation is
    # auditable: fleet totals == sum of per-engine totals, exactly
    assert sum(p["totals"]["steps"]
               for p in merged["per_backend"].values()) == 1000
    assert json.dumps(merged)   # JSON-serializable end to end
