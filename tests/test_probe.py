"""Black-box canary prober (serve/probe.py, ISSUE 15).

The prober is only trustworthy if it exercises the REAL serving path, so
these tests run it against a live Gateway on a localhost socket: POST
/v1/solve -> lanes -> writer -> GET /v1/requests/<id>?field=1, verified
against the closed-form sine-eigenmode decay. The failure story matters
as much as the pass story: a wrong-physics answer (not a transport
error) must fail the probe with a concrete error norm, and exactly one
``probe_failed`` record fires at the consecutive-miss threshold.
"""

import json

import numpy as np
import pytest

from heat_tpu.config import HeatConfig
from heat_tpu.grid import initial_condition, sine_decay_factor
from heat_tpu.runtime import faults
from heat_tpu.serve import Engine, ServeConfig
from heat_tpu.serve.gateway import Gateway, render_metrics, render_statusz
from heat_tpu.serve.probe import (DEFAULT_PROBE_REQUEST, PROBE_TENANT,
                                  PROBE_TOL, Prober, expected_probe_field,
                                  probe_urls)

TIMEOUT = 60

# A faster canary than the production default (same physics, fewer
# cells/steps): tier-1 runs dozens of probes.
SMALL_PROBE = {"n": 32, "ndim": 2, "ntime": 60, "dtype": "float32",
               "ic": "sine", "bc": "edges"}


@pytest.fixture(autouse=True)
def _fresh_faults():
    faults.reset()
    yield
    faults.reset()


def make_gateway(tmp_path=None, **scfg_kw):
    scfg_kw.setdefault("emit_records", False)
    scfg_kw.setdefault("lanes", 2)
    scfg_kw.setdefault("chunk", 8)
    scfg_kw.setdefault("buckets", (32,))
    if tmp_path is None:
        scfg_kw.setdefault("keep_fields", True)
    else:
        scfg_kw.setdefault("out_dir", str(tmp_path / "results"))
    eng = Engine(ServeConfig(**scfg_kw))
    gw = Gateway(eng, "127.0.0.1", 0, start_engine=True).start()
    return gw, eng


def records_of(capsys, event):
    out = capsys.readouterr().out
    return [json.loads(line) for line in out.splitlines()
            if line.startswith("{")
            and json.loads(line).get("event") == event]


def drain_close(gw):
    gw.request_drain()
    assert gw.wait_drained(TIMEOUT)
    gw.close()


# --- the pass story ----------------------------------------------------------


def test_probe_verifies_closed_form_through_real_gateway(capsys):
    """Acceptance e2e: one probe through the live HTTP path comes back
    with a max-norm error orders below tolerance, lands in the usage
    ledger under the reserved tenant, and emits a probe_result record
    carrying the verdict and the request's trace id."""
    gw, eng = make_gateway()
    try:
        prober = Prober(f"http://{gw.address}", interval_s=3600.0,
                        request=SMALL_PROBE)
        verdict = prober.run_once()
        assert verdict["ok"] is True and verdict["status"] == "ok"
        assert verdict["error_norm"] < PROBE_TOL["float32"] / 100
        assert verdict["trace_id"]
        # the probe is attributable: reserved tenant on the real record
        rec = eng.poll("_probe-0001")
        assert rec is not None and rec["tenant"] == PROBE_TENANT
        st = prober.stats()
        assert st["passes"] == 1 and st["fails"] == 0
        assert st["consecutive_failures"] == 0
        assert st["last_error_norm"] == verdict["error_norm"]
        # export surfaces: attach the prober the way cmd_serve does
        eng.prober = prober
        text = render_metrics(eng)
        assert 'heat_tpu_probe_runs_total{result="pass"} 1' in text
        assert 'heat_tpu_probe_runs_total{result="fail"} 0' in text
        assert "heat_tpu_probe_consecutive_failures 0" in text
        assert "heat_tpu_probe_last_error_norm" in text
        assert "prober: every 3600s, 1 pass / 0 fail" in \
            render_statusz(eng)
        (row,) = records_of(capsys, "probe_result")
        assert row["ok"] is True and row["trace_id"] == verdict["trace_id"]
        assert row["consecutive_failures"] == 0
    finally:
        drain_close(gw)


def test_field_endpoint_serves_solution_on_demand(tmp_path):
    """``?field=1`` returns the solved field (f64 nested lists) on BOTH
    retention paths — in-memory keep_fields and npz out_dir — while the
    plain record endpoint stays payload-free."""
    import urllib.request

    for with_dir in (False, True):
        gw, eng = make_gateway(tmp_path / "d" if with_dir else None)
        try:
            body = (json.dumps({"id": "x", "n": 16, "ntime": 8,
                                "dtype": "float64"}) + "\n").encode()
            req = urllib.request.Request(
                f"http://{gw.address}/v1/solve", data=body, method="POST")
            with urllib.request.urlopen(req, timeout=TIMEOUT) as resp:
                (rec,) = [json.loads(l) for l in
                          resp.read().decode().splitlines() if l.strip()]
            assert rec["status"] == "ok" and "T" not in rec
            with urllib.request.urlopen(
                    f"http://{gw.address}/v1/requests/x",
                    timeout=TIMEOUT) as resp:
                assert "T" not in json.loads(resp.read().decode())
            with urllib.request.urlopen(
                    f"http://{gw.address}/v1/requests/x?field=1",
                    timeout=TIMEOUT) as resp:
                got = np.asarray(json.loads(resp.read().decode())["T"])
            from heat_tpu.backends import solve
            expect = solve(HeatConfig(n=16, ntime=8, dtype="float64")).T
            np.testing.assert_array_equal(got, np.asarray(expect))
        finally:
            drain_close(gw)


def test_expected_probe_field_is_the_closed_form():
    cfg_req = dict(DEFAULT_PROBE_REQUEST)
    field = expected_probe_field(cfg_req)
    cfg = HeatConfig(n=64, ndim=2, ntime=200, dtype="float32", ic="sine",
                     bc="edges")
    lam = sine_decay_factor(cfg)
    np.testing.assert_array_equal(
        field, lam ** 200 * initial_condition(cfg).astype(np.float64))
    assert probe_urls("http://h:1/") == [
        "http://h:1/v1/solve", "http://h:1/v1/requests/<id>?field=1"]


# --- the failure story -------------------------------------------------------


def test_wrong_physics_fails_probe_and_probe_failed_fires_once(capsys):
    """A served answer that disagrees with the closed form (here: a hat
    IC solved correctly but verified against the sine eigenmode — the
    same signature a wrong-stencil regression leaves) fails probes;
    probe_failed fires exactly ONCE at the fail_after threshold and the
    run resets on the next pass."""
    gw, eng = make_gateway()
    try:
        prober = Prober(f"http://{gw.address}", interval_s=3600.0,
                        request=dict(SMALL_PROBE, ic="hat"), fail_after=2)
        for _ in range(3):
            verdict = prober.run_once()
            assert verdict["ok"] is False
            assert verdict["error_norm"] > PROBE_TOL["float32"]
            assert "exceeds tol" in verdict["error"]
        st = prober.stats()
        assert st["fails"] == 3 and st["consecutive_failures"] == 3
        rows = records_of(capsys, "probe_failed")
        assert len(rows) == 1     # fired at consecutive == 2, not again
        assert rows[0]["consecutive"] == 2 and rows[0]["threshold"] == 2
        # a pass resets the consecutive counter (a NEW run of failures
        # would page again)
        prober.request = dict(SMALL_PROBE)
        assert prober.run_once()["ok"] is True
        st = prober.stats()
        assert st["consecutive_failures"] == 0
        assert st["passes"] == 1 and st["fails"] == 3
    finally:
        drain_close(gw)


def test_transport_refusal_counts_as_probe_failure(capsys):
    """A request the engine cannot serve (periodic BC has no padded-lane
    form) is a failed probe carrying the rejection status — black-box
    probing covers 'cannot get through' as well as 'wrong answer'."""
    gw, eng = make_gateway()
    try:
        prober = Prober(f"http://{gw.address}", interval_s=3600.0,
                        request=dict(SMALL_PROBE, bc="periodic"),
                        fail_after=1)
        verdict = prober.run_once()
        assert verdict["ok"] is False and verdict["status"] == "rejected"
        assert verdict["error_norm"] is None
        assert "periodic" in verdict["error"]
        rows = records_of(capsys, "probe_failed")
        assert len(rows) == 1 and rows[0]["consecutive"] == 1
    finally:
        drain_close(gw)


def test_prober_thread_lifecycle():
    """start() spawns the named daemon thread (the conftest leak guard
    watches for it); stop() joins it promptly even mid-interval."""
    import threading

    gw, eng = make_gateway()
    try:
        prober = Prober(f"http://{gw.address}", interval_s=3600.0,
                        request=SMALL_PROBE).start()
        names = [t.name for t in threading.enumerate()]
        assert "heat-tpu-prober" in names
        prober.stop()
        assert not any(t.name == "heat-tpu-prober" and t.is_alive()
                       for t in threading.enumerate())
        # no probe ran (the first tick is one full interval out)
        assert prober.stats()["passes"] == prober.stats()["fails"] == 0
    finally:
        drain_close(gw)
