"""Performance & cost observatory (runtime/prof.py, ISSUE 8).

The load-bearing contracts:

- the online chunk-cost model's per-bucket estimate lands within a
  tested tolerance of the measured wall on a synthetic fixed-cost
  harness (acceptance), and its unit math is exact on synthetic
  observations;
- ``GET /v1/usage`` / the ledger totals reconcile EXACTLY with the sum
  of per-request terminal-record usage stamps for a drained run
  (acceptance) — including failed/preempted requests' partial work;
- the SLO burn monitor's window math, alert threshold, and cooldown are
  deterministic under synthetic timestamps, and a real deadline-missing
  wave emits a structured ``slo_alert`` record;
- the memory watermark leak sentinel fires exactly once per doubling on
  monotone growth and never on a plateau, and a leak-shaped device
  emits a structured ``mem_watermark`` record mid-drain;
- the compile observatory logs every aot_compile_chunks program with
  first-vs-warm attribution;
- ``--prof off`` disables aggregation while records keep their usage
  stamps (schema never flickers), and results stay bit-identical;
- the CLI surfaces (``heat-tpu usage``, ``heat-tpu perfcheck``) run
  against real artifacts.
"""

import json
import math
import time

import numpy as np
import pytest

from heat_tpu.config import (HeatConfig, parse_on_off, parse_slo_targets)
from heat_tpu.runtime import prof as prof_mod
from heat_tpu.serve import Engine, ServeConfig


def make_engine(**kw):
    kw.setdefault("lanes", 2)
    kw.setdefault("chunk", 8)
    kw.setdefault("buckets", (16,))
    kw.setdefault("emit_records", False)
    kw.setdefault("keep_fields", True)
    return Engine(ServeConfig(**kw))


# --- (a) online chunk-cost model ---------------------------------------------


def test_cost_model_unit_math_exact():
    cm = prof_mod.CostModel(alpha=0.5)
    # two observations for one key: 8 steps x 4 lanes, 0.032s then 0.064s
    cm.observe("2d/n32/float64/edges", 4, 2, 8, 0.032)
    cm.observe("2d/n32/float64/edges", 4, 2, 8, 0.064)
    per1, per2 = 0.032 / 32, 0.064 / 32
    ewma = 0.5 * per1 + 0.5 * per2
    assert cm.estimate_s_per_lane_step("2d/n32/float64/edges", 4, 2) == \
        pytest.approx(ewma)
    # request estimate: ntime * lanes * s_per_lane_step
    assert cm.estimate_request_s("2d/n32/float64/edges", 4, 2, 100) == \
        pytest.approx(ewma * 4 * 100)
    (snap,) = cm.snapshot()
    assert snap["chunks"] == 2
    assert snap["mean_s_per_lane_step"] == pytest.approx(
        (0.032 + 0.064) / (2 * 32))
    assert snap["wall_s"] == pytest.approx(0.096)
    # unknown key -> None, not a crash
    assert cm.estimate_s_per_lane_step("nope", 1, 0) is None
    assert cm.estimate_request_s("nope", 1, 0, 10) is None


def test_cost_model_estimate_within_tolerance_of_measured_wall(monkeypatch):
    """Acceptance: on a synthetic fixed-cost harness (every chunk
    dispatch costs a deterministic ~4 ms), the model's per-bucket
    request estimate lands within tolerance of the measured record
    wall."""
    from heat_tpu.serve import engine as engine_mod

    real = engine_mod.LaneEngine.dispatch_chunk

    def fixed_cost(self, k=None):
        handle = real(self, k)
        # the dominant, deterministic chunk cost: large enough that a
        # loaded CI box's per-chunk host jitter (~ms) cannot push the
        # measured wall outside the 50% band (4 ms flaked there)
        time.sleep(0.02)
        return handle

    monkeypatch.setattr(engine_mod.LaneEngine, "dispatch_chunk", fixed_cost)
    eng = make_engine(lanes=1, dispatch_depth=1)
    rid = eng.submit(HeatConfig(n=16, ntime=64, dtype="float64"))
    (rec,) = [r for r in eng.results() if r["id"] == rid]
    assert rec["status"] == "ok"
    est = eng.prof.cost.estimate_request_s("2d/n16/float64/edges", 1, 1, 64)
    assert est is not None
    # 8 chunks x ~4ms: estimate and measured wall agree within 50%
    assert est == pytest.approx(rec["solve_s"], rel=0.5)
    (snap,) = [e for e in eng.summary()["cost_model"]
               if e["lanes"] == 1]
    assert snap["chunks"] == 8


def test_cost_model_keys_sync_fallback_as_depth_zero():
    eng = make_engine(dispatch_depth=0)
    eng.submit(HeatConfig(n=16, ntime=16, dtype="float64"))
    eng.results()
    (snap,) = eng.summary()["cost_model"]
    assert snap["depth"] == 0 and snap["chunks"] == 2
    assert snap["ewma_s_per_lane_step"] > 0


# --- (d) per-tenant usage ledger ---------------------------------------------


def drain_mixed_wave(tmp_path=None, **kw):
    eng = make_engine(lanes=4,
                      **({"out_dir": str(tmp_path / "res")} if tmp_path
                         else {}), **kw)
    ids = []
    for i in range(8):
        ids.append(eng.submit(
            HeatConfig(n=16, ntime=16 + 8 * (i % 2), dtype="float64"),
            tenant=("acme", "zeta")[i % 2],
            slo_class=("interactive", "batch")[i % 2],
            deadline_ms=60_000.0))
    # one unservable request: rejected records carry zero usage stamps
    ids.append(eng.submit(HeatConfig(n=16, ntime=4, bc="periodic")))
    records = eng.results()
    return eng, [r for r in records if r["id"] in ids]


def test_usage_ledger_reconciles_exactly_with_record_stamps(tmp_path):
    """Acceptance: /v1/usage totals == the sum of the per-request
    terminal-record usage stamps for a drained run — ints exactly,
    lane-seconds to float-summation noise."""
    eng, records = drain_mixed_wave(tmp_path)
    assert all("usage" in r for r in records)
    totals = eng.prof.ledger.snapshot()["totals"]
    for field in ("steps", "chunks", "bytes_written"):
        assert totals[field] == sum(int(r["usage"][field])
                                    for r in records), field
    assert totals["lane_s"] == pytest.approx(
        sum(float(r["usage"]["lane_s"]) for r in records), abs=1e-6)
    assert totals["requests"] == len(records)
    # bytes_written is the real published file size
    ok = [r for r in records if r["status"] == "ok"]
    for r in ok:
        assert r["usage"]["bytes_written"] == \
            (tmp_path / "res" / f"{r['id']}.npz").stat().st_size
    # the gateway payload is the same snapshot (socket-free contract)
    from heat_tpu.serve.gateway import usage_payload

    payload = usage_payload(eng)
    assert payload["totals"] == totals
    assert set(payload["tenants"]) == {"acme", "zeta", "default"}
    assert payload["prof"] is True


def test_usage_stamps_on_failed_and_preempted_requests():
    """A quarantined lane's request bills the chunks it DID consume; a
    request shed while queued bills zero."""
    eng = make_engine(lanes=1, inject="lane-nan@8:req=bad")
    eng.submit(HeatConfig(n=16, ntime=32, dtype="float64"),
               request_id="bad")
    records = eng.results()
    (bad,) = [r for r in records if r["id"] == "bad"]
    assert bad["status"] == "nonfinite"
    assert bad["usage"]["chunks"] >= 1        # it ran before poisoning
    assert bad["usage"]["bytes_written"] == 0  # nothing published
    assert 0 < bad["usage"]["steps"] <= 32
    cell = eng.prof.ledger.snapshot()["totals"]
    assert cell["by_status"].get("nonfinite") == 1


def test_in_memory_results_bill_field_bytes():
    eng = make_engine()   # no out_dir: fields stay on the records
    eng.submit(HeatConfig(n=16, ntime=8, dtype="float64"))
    (rec,) = eng.results()
    assert rec["usage"]["bytes_written"] == rec["T"].nbytes


# --- (e) SLO burn-rate monitor -----------------------------------------------


def test_burn_monitor_window_math_threshold_and_cooldown():
    bm = prof_mod.BurnMonitor({"interactive": 0.9}, fast_window_s=10,
                              slow_window_s=100, threshold=1.5,
                              cooldown_s=50)
    t = 1000.0
    # 18 hits: burn 0, no alert
    for i in range(18):
        assert bm.note("interactive", True, t + i * 0.1) is None
    snap = bm.snapshot(t + 2)["interactive"]
    assert snap["fast_burn"] == 0.0 and snap["fast_hit_ratio"] == 1.0
    # 2 misses inside both windows: miss_frac 2/20 = budget -> burn 1.0,
    # still under threshold
    assert bm.note("interactive", False, t + 2.0) is None
    assert bm.note("interactive", False, t + 2.1) is None
    snap = bm.snapshot(t + 2.2)["interactive"]
    assert snap["fast_burn"] == pytest.approx(1.0)
    # 3 more misses -> 5/23 ~ 2.17x budget: alert fires once...
    alerts = [bm.note("interactive", False, t + 3 + i * 0.1)
              for i in range(3)]
    fired = [a for a in alerts if a is not None]
    assert len(fired) == 1
    a = fired[0]
    assert a["fast_burn"] >= 1.5 and a["slow_burn"] >= 1.5
    assert a["class"] == "interactive" and a["target"] == 0.9
    # ...and the cooldown suppresses an immediate repeat, but not one
    # after the cooldown elapses
    assert bm.note("interactive", False, t + 4) is None
    assert bm.note("interactive", False, t + 60) is not None
    # fast window slid away: only the slow window remembers old misses
    snap = bm.snapshot(t + 200)["interactive"]
    assert snap["fast_events"] == 0 and snap["slow_events"] == 0


def test_burn_monitor_ignores_undated_and_rejected():
    eng = make_engine()
    eng.submit(HeatConfig(n=16, ntime=8, dtype="float64"))   # undated
    eng.submit(HeatConfig(n=16, ntime=8, bc="periodic"),     # rejected
               deadline_ms=1000.0)
    eng.results()
    assert eng.summary()["slo_burn"] == {}


def test_deadline_missing_wave_emits_slo_alert_record(capsys):
    """A wave of dated requests all shed past deadline burns the class's
    budget in both windows -> one structured slo_alert JSON record."""
    eng = make_engine(slo_targets=(("standard", 0.5),),
                      slo_burn_threshold=1.5)
    for i in range(4):
        eng.submit(HeatConfig(n=16, ntime=400, dtype="float64"),
                   deadline_ms=0.01)   # missed before any lane starts
    records = eng.results()
    assert all(r["status"] == "deadline" for r in records)
    out = capsys.readouterr().out
    alert_lines = [json.loads(l) for l in out.splitlines()
                   if l.startswith("{") and '"slo_alert"' in l]
    assert alert_lines, out
    a = alert_lines[0]
    assert a["class"] == "standard" and a["fast_burn"] >= 1.5
    burn = eng.summary()["slo_burn"]["standard"]
    assert burn["alerts"] >= 1 and burn["fast_hit_ratio"] == 0.0


# --- (c) memory watermarks ---------------------------------------------------


def test_mem_watermark_leak_sentinel_unit():
    mw = prof_mod.MemWatermark(window=4, min_growth_bytes=100)
    # plateau: never fires
    for i in range(8):
        assert mw.note(1000, float(i)) is None
    # monotone growth past the floor: fires once...
    warn = None
    for i in range(4):
        warn = mw.note(2000 + 200 * i, 10.0 + i) or warn
    assert warn is not None
    assert warn["growth_bytes"] >= 100 and warn["source"] == "device"
    assert warn["slope_bytes_per_s"] > 0
    # ...and stays quiet until usage doubles again
    assert mw.note(2700, 15.0) is None
    warn2 = None
    for i in range(6):
        warn2 = mw.note(6000 + 300 * i, 20.0 + i) or warn2
    assert warn2 is not None
    assert mw.snapshot()["warnings"] == 2
    assert mw.snapshot()["peak_bytes"] == 6000 + 300 * 5


def test_device_memory_bytes_returns_int_on_cpu():
    nbytes, source = prof_mod.device_memory_bytes()
    assert isinstance(nbytes, int) and nbytes >= 0
    assert source in ("device", "live_arrays")


def test_leaky_device_emits_mem_watermark_record(capsys, monkeypatch):
    """A device whose memory grows monotonically across the sampling
    window produces one structured mem_watermark record mid-drain."""
    grow = {"n": 0}

    def leaky():
        grow["n"] += 1
        return (100 << 20) + grow["n"] * (8 << 20), "device"

    monkeypatch.setattr(prof_mod, "device_memory_bytes", leaky)
    eng = make_engine(lanes=1, mem_poll_every=1)
    eng.submit(HeatConfig(n=16, ntime=16 * prof_mod.MEM_WINDOW,
                          dtype="float64"))
    eng.results()
    out = capsys.readouterr().out
    warns = [json.loads(l) for l in out.splitlines()
             if l.startswith("{") and '"mem_watermark"' in l]
    assert warns and warns[0]["growth_bytes"] >= prof_mod.MEM_MIN_GROWTH_BYTES
    assert eng.prof.mem.snapshot()["warnings"] >= 1
    assert eng.timing.mem_peak_bytes == eng.prof.mem.snapshot()["peak_bytes"]
    assert any("observatory: mem peak" in l
               for l in eng.timing.report_lines())


# --- (b) compile observatory -------------------------------------------------


def test_compile_log_first_vs_warm_attribution():
    log = prof_mod.compile_log()
    before = log.summary()["programs"]
    eng = make_engine()
    eng.submit(HeatConfig(n=16, ntime=8, dtype="float64"))
    eng.results()
    mid = log.summary()
    assert mid["programs"] == before + 1
    ev = log.snapshot()[-1]
    assert ev["k"] == 8 and ev["seconds"] > 0
    # the label carries the lane-kernel tag (ISSUE 9): the Pallas and XLA
    # lane programs for one bucket/tier are distinct compile-log keys
    assert ev["label"] == "lanes 2d n16 float64 edges L1 [xla]"
    # a second engine compiles the same program again: warm re-compile
    eng2 = make_engine()
    eng2.submit(HeatConfig(n=16, ntime=8, dtype="float64"))
    eng2.results()
    after = log.summary()
    assert after["programs"] == before + 2
    assert log.snapshot()[-1]["first"] is False
    assert after["warm_s"] > 0


def test_compile_span_lands_on_trace_timeline():
    eng = make_engine()
    eng.submit(HeatConfig(n=16, ntime=8, dtype="float64"))
    eng.results()
    evs = eng.tracer.to_chrome()["traceEvents"]
    spans = [e for e in evs if e.get("cat") == "compile"
             and e.get("ph") == "X"]
    assert spans and spans[0]["name"] == "compile k=8"
    assert spans[0]["dur"] > 0


# --- --prof off (the A/B baseline) -------------------------------------------


def test_prof_off_disables_aggregation_but_keeps_usage_stamps(tmp_path):
    eng, records = drain_mixed_wave(tmp_path, prof=False)
    assert all("usage" in r for r in records)       # schema stable
    ok = [r for r in records if r["status"] == "ok"]
    assert ok and all(r["usage"]["steps"] > 0 for r in ok)
    s = eng.summary()
    assert s["prof"] is False
    assert s["cost_model"] == [] and s["slo_burn"] == {}
    assert s["mem"]["samples"] == 0
    assert eng.prof.ledger.snapshot()["totals"]["requests"] == 0
    assert eng.timing.mem_peak_bytes is None


def test_prof_on_off_bit_identical_results():
    fields = {}
    for prof in (True, False):
        eng = make_engine(prof=prof)
        rid = eng.submit(HeatConfig(n=16, ntime=24, dtype="float64"))
        (rec,) = [r for r in eng.results() if r["id"] == rid]
        fields[prof] = rec["T"]
    np.testing.assert_array_equal(fields[True], fields[False])


# --- flight-recorder record (satellite) --------------------------------------


def test_flight_dump_emits_structured_flightrec_record(tmp_path, capsys):
    eng = make_engine(flight_dir=str(tmp_path))
    eng.submit(HeatConfig(n=16, ntime=8, dtype="float64"))
    eng.results()
    eng._flight_dump("test trigger")
    out = capsys.readouterr().out
    recs = [json.loads(l) for l in out.splitlines()
            if l.startswith("{") and '"flightrec"' in l]
    assert recs, out
    r = recs[0]
    assert r["reason"] == "test trigger" and r["dump"] == 1
    assert r["path"].startswith(str(tmp_path))
    assert (tmp_path / r["path"].rsplit("/", 1)[1]).exists()
    assert eng.tracer.dump_paths == [r["path"]]
    # the /metrics counter reports it
    from heat_tpu.serve.gateway import render_metrics

    assert "heat_tpu_flightrec_dumps_total 1" in render_metrics(eng)


# --- /metrics + /statusz surfaces (socket-free) ------------------------------


def test_metrics_export_cost_usage_burn_mem_series(tmp_path):
    eng, _ = drain_mixed_wave(tmp_path, mem_poll_every=1)
    from heat_tpu.serve.gateway import render_metrics

    text = render_metrics(eng)
    assert ('heat_tpu_serve_cost_s_per_lane_step{bucket='
            '"2d/n16/float64/edges"') in text
    assert 'heat_tpu_usage_lane_seconds_total{tenant="acme"' in text
    assert ('heat_tpu_usage_steps_total{tenant="zeta",class="batch"}'
            in text)
    assert ('heat_tpu_slo_burn_rate{class="interactive",window="fast"}'
            in text)
    assert "heat_tpu_mem_peak_bytes" in text
    assert "heat_tpu_flightrec_dumps_total 0" in text
    # every sample line is parseable: name{labels} value
    for line in text.splitlines():
        if line.startswith("#") or not line:
            continue
        assert len(line.rsplit(" ", 1)) == 2, line
        float(line.rsplit(" ", 1)[1])


def test_statusz_renders_all_sections(tmp_path):
    eng, _ = drain_mixed_wave(tmp_path, mem_poll_every=1)
    from heat_tpu.serve.gateway import render_statusz

    text = render_statusz(eng)
    for needle in ("cost model", "compile observatory",
                   "memory watermarks", "slo burn", "usage ledger",
                   "2d/n16/float64/edges", "acme"):
        assert needle in text, needle


# --- config / ServeConfig grammar --------------------------------------------


def test_parse_slo_targets_grammar():
    assert parse_slo_targets("") == ()
    assert parse_slo_targets("interactive=0.999,batch=0.8") == \
        (("interactive", 0.999), ("batch", 0.8))
    for bad in ("nope=0.5", "interactive", "interactive=x",
                "interactive=1.0", "interactive=0"):
        with pytest.raises(ValueError):
            parse_slo_targets(bad)


def test_parse_on_off_grammar():
    assert parse_on_off("on", "--prof") is True
    assert parse_on_off("off", "--prof") is False
    with pytest.raises(ValueError):
        parse_on_off("maybe", "--prof")


def test_serve_config_validates_observatory_knobs():
    with pytest.raises(ValueError):
        ServeConfig(slo_targets=(("standard", 1.5),))
    with pytest.raises(ValueError):
        ServeConfig(slo_targets=(("bogus-class", 0.9),))
    with pytest.raises(ValueError):
        ServeConfig(slo_burn_threshold=0)
    with pytest.raises(ValueError):
        ServeConfig(mem_poll_every=-1)
    with pytest.raises(ValueError):
        ServeConfig(slo_fast_window_s=0)


# --- histogram re-export (policy.py moved to prof.py) ------------------------


def test_policy_histogram_reexport_is_prof_histogram():
    from heat_tpu.serve import policy as policy_mod

    assert policy_mod.Histogram is prof_mod.Histogram
    assert policy_mod.LATENCY_BUCKETS is prof_mod.LATENCY_BUCKETS
    h = policy_mod.Histogram(prof_mod.LANE_STEP_BUCKETS)
    h.observe(1e-6)
    assert h.quantile(0.5) == 1e-6
    over = prof_mod.Histogram((1.0,))
    over.observe(5.0)          # beyond the top bucket -> +Inf estimate
    assert math.isinf(over.quantile(0.5))


# --- CLI: heat-tpu usage / heat-tpu perfcheck --------------------------------


def test_cli_usage_renders_table_from_records_file(tmp_path, capsys):
    from heat_tpu.cli import main

    eng, records = drain_mixed_wave(tmp_path)
    log = tmp_path / "records.log"
    log.write_text("prologue line\n" + "\n".join(
        json.dumps({"event": "serve_request", **{k: v for k, v in r.items()
                                                 if k != "T"}})
        for r in records))
    assert main(["usage", str(log)]) == 0
    out = capsys.readouterr().out
    assert "acme" in out and "zeta" in out and "TOTAL" in out
    # --json round-trips the ledger snapshot and reconciles
    assert main(["usage", str(log), "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["totals"]["steps"] == sum(
        r["usage"]["steps"] for r in records)


def test_cli_usage_errors_on_missing_or_empty_source(tmp_path, capsys):
    from heat_tpu.cli import main

    assert main(["usage", str(tmp_path / "nope.log")]) == 2
    empty = tmp_path / "empty.log"
    empty.write_text("no records here\n")
    assert main(["usage", str(empty)]) == 2


def test_cli_perfcheck_no_fresh_validates_committed_artifacts(capsys):
    from heat_tpu.cli import main

    rc = main(["perfcheck", "--no-fresh"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "baseline overhead gate" in out
    assert "perfcheck: OK" in out
    assert "calibration cross-check" in out


def test_cli_perfcheck_fails_on_violated_baseline(tmp_path, capsys):
    from heat_tpu.cli import main

    bad = {"on_within_2pct_of_off": False, "on_overhead_frac": 0.5,
           "bit_identical_depth0": True, "bit_identical_depth2": True,
           "usage_reconciles": True, "platform": "cpu",
           "on": {"points_per_s": 1.0}, "cost_model": []}
    p = tmp_path / "bad.json"
    p.write_text(json.dumps(bad))
    rc = main(["perfcheck", "--no-fresh", "--baseline", str(p)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "FAIL baseline overhead gate" in out


def test_prof_overhead_lab_harness_smoke(tmp_path):
    """The committed lab's harness runs end-to-end on a tiny population
    (argv-injectable main, same pattern as serve_lab's smoke)."""
    import importlib.util
    import sys as _sys
    from pathlib import Path as _Path

    bdir = _Path(__file__).resolve().parent.parent / "benchmarks"
    for name, fname in (("_util", "_util.py"),
                        ("serve_lab", "serve_lab.py"),
                        ("prof_overhead_lab", "prof_overhead_lab.py")):
        if name not in _sys.modules:
            spec = importlib.util.spec_from_file_location(
                name, bdir / fname)
            mod = importlib.util.module_from_spec(spec)
            _sys.modules[name] = mod
            spec.loader.exec_module(mod)
    lab = _sys.modules["prof_overhead_lab"]
    out = tmp_path / "lab.json"
    rc = lab.main(["--requests", "6", "--bit-requests", "4",
                   "--lanes", "2", "--repeats", "1",
                   "--out", str(out)])
    rec = json.loads(out.read_text())
    assert rec["bit_identical_depth0"] and rec["bit_identical_depth2"]
    assert rec["usage_reconciles"] is True
    assert rec["cost_model"] and rec["mem"]["samples"] > 0
    assert rc in (0, 1)   # the 2% wall gate may jitter at this tiny size
